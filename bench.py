"""Benchmark: candidate fitness evaluations per second per chip.

The north-star metric (BASELINE.json / BASELINE.md): how many candidate
timetables the framework can evaluate per second on one chip — the
quantity that bounds the whole memetic GA, since >95% of the reference's
runtime is candidate evaluation inside local search (SURVEY section 3.2).

Prints ONE JSON line:
  {"metric": "fitness_evals_per_sec_per_chip", "value": N,
   "unit": "evals/s", "vs_baseline": R}

`vs_baseline` is the ratio against the same workload run with the same
XLA kernels on the host CPU (all cores, measured in a subprocess) — the
stand-in for the reference's CPU-node throughput until a same-box
MPI+OpenMP build exists (none is possible here: no mpicxx in the image;
BASELINE.md records the protocol).

Workload: comp05-scale synthetic instance (400 events, 10 rooms, 350
students, 45 slots), population 4096, full penalty evaluation (hcv + scv
+ penalty composition).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_EVENTS, N_ROOMS, N_FEATURES, N_STUDENTS = 400, 10, 10, 350
POP = 4096
# Enough scan iterations that the ~70ms tunnel dispatch latency is noise.
WARMUP, ITERS = 2, 100
CPU_ITERS = 3  # the CPU baseline is ~500x slower; 3 iterations suffice


def measure(label: str) -> float:
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness
    from timetabling_ga_tpu.problem import random_instance

    problem = random_instance(1234, n_events=N_EVENTS, n_rooms=N_ROOMS,
                              n_features=N_FEATURES,
                              n_students=N_STUDENTS, attend_prob=0.02)
    pa = problem.device_arrays()
    rng = np.random.default_rng(0)
    slots = rng.integers(0, problem.n_slots, size=(POP, N_EVENTS),
                         dtype=np.int32)
    rooms = rng.integers(0, N_ROOMS, size=(POP, N_EVENTS), dtype=np.int32)
    slots = jax.device_put(slots)
    rooms = jax.device_put(rooms)

    # Measure the production shape: a lax.scan whose every iteration's
    # input depends on the previous output. Iterations can neither
    # overlap nor be deduplicated, and per-dispatch host<->device latency
    # is amortized away exactly as it is in the real GA loop (ops/ga.py
    # runs whole generations under lax.scan).
    iters = ITERS

    @jax.jit
    def chain(s, r):
        def step(carry, _):
            s, r = carry
            pen, _, _ = fitness.batch_penalty(pa, s, r)
            s = (s + pen[:, None]) % (5 * 9)
            return (s, r), None
        (s, r), _ = jax.lax.scan(step, (s, r), None, length=iters)
        return s

    # Warm (compiles), then time with the WARMUP OUTPUT as input so the
    # timed dispatch is not bit-identical to the warmup (the tunnel
    # dedupes identical dispatches — see the methodology note in
    # BASELINE.md).
    warm = chain(slots, rooms)
    jax.block_until_ready(warm)
    t0 = time.perf_counter()
    out = chain(warm, rooms)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    evals_per_sec = POP * iters / dt
    print(f"# {label}: {evals_per_sec:,.0f} evals/s "
          f"({dt / ITERS * 1e3:.2f} ms/batch of {POP})", file=sys.stderr)
    return evals_per_sec


def main() -> None:
    if os.environ.get("_BENCH_CPU_CHILD") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
        global ITERS
        ITERS = CPU_ITERS
        print(json.dumps({"cpu_evals_per_sec": measure("cpu")}))
        return

    tpu = measure("tpu")

    env = dict(os.environ, _BENCH_CPU_CHILD="1")
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=1200, check=True)
        cpu = json.loads(out.stdout.strip().splitlines()[-1])[
            "cpu_evals_per_sec"]
        vs_baseline = tpu / cpu
    except Exception as e:  # pragma: no cover - defensive
        print(f"# cpu baseline failed: {e}", file=sys.stderr)
        vs_baseline = 0.0

    print(json.dumps({
        "metric": "fitness_evals_per_sec_per_chip",
        "value": round(tpu, 1),
        "unit": "evals/s",
        "vs_baseline": round(vs_baseline, 2),
    }))


if __name__ == "__main__":
    main()
