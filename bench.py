"""Benchmark: candidate fitness evaluations per second per chip, plus
full-pipeline (generation-level) throughput.

The north-star metric (BASELINE.json / BASELINE.md): how many candidate
timetables the framework can evaluate per second on one chip — the
quantity that bounds the whole memetic GA, since >95% of the reference's
runtime is candidate evaluation inside local search (SURVEY section 3.2).

Prints ONE JSON line:
  {"metric": "fitness_evals_per_sec_per_chip", "value": N,
   "unit": "evals/s", "vs_baseline": R, "extra": {...}}

`vs_baseline` is the ratio against the NATIVE C++ OpenMP evaluator
(native/timetabling_native.cpp tt_eval_batch) at full host cores — an
honest scalar-CPU denominator implementing identical semantics (the
reference binary itself cannot be built here: no mpicxx in the image;
BASELINE.md records the protocol). The round-1 denominator (same XLA
kernels on host CPU) flattered the ratio and is gone.

`extra` carries the secondary measurements the driver archives:
  - generation-level throughput of the FULL breeding pipeline
    (selection + crossover with room rematch + mutation + delta LS +
    replacement) — VERDICT round-1 item 5;
  - the 2000-event / pop-32768 scale config — VERDICT item 6;
  - the LS-mode shootout (systematic sweep vs K-random at equal wall
    clock) — VERDICT item 2.

Workload: comp05-scale synthetic instance (400 events, 10 rooms, 350
students, 45 slots), population 4096, full penalty evaluation.
"""

from __future__ import annotations

import json
import os
import sys
import time

N_EVENTS, N_ROOMS, N_FEATURES, N_STUDENTS = 400, 10, 10, 350
POP = 4096
# Enough scan iterations that the ~70ms tunnel dispatch latency is noise.
ITERS = 100



def _fence(out):
    """Completion fence that can be trusted on the tunneled device:
    fetch the smallest array leaf of the output pytree.
    jax.block_until_ready can acknowledge BEFORE the computation
    completes here (BASELINE.md round-5 fence audit: a 100k-step chain
    "finished" in 0.000 s by block_until_ready vs 51.2 s by an actual
    fetch, and two tuned-generation measures read 0 ms/gen in the same
    session); an XLA computation's output buffers only materialize when
    the whole dispatch has executed, so fetching any one of them is a
    real fence while transferring almost nothing."""
    import jax
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "size")]
    jax.device_get(min(leaves, key=lambda a: a.size))
    return out

def _instance():
    from timetabling_ga_tpu.problem import random_instance
    return random_instance(1234, n_events=N_EVENTS, n_rooms=N_ROOMS,
                           n_features=N_FEATURES, n_students=N_STUDENTS,
                           attend_prob=0.02)


def _small_instance():
    """The quality race's `small` spec (tools/quality_race.py SPECS) —
    the shape the small-scale tuned defaults are sized for."""
    from timetabling_ga_tpu.problem import random_instance
    return random_instance(101, n_events=100, n_rooms=5, n_features=5,
                           n_students=80, attend_prob=0.05)



def _make_eval_chain(pa, n_slots, pop, iters):
    """THE protocol-critical dependent-evaluation chain, shared by the
    headline and the scale row so a protocol fix cannot apply to one
    and silently miss the other (round-5 audit: the `+ 1` that forbids
    per-individual fixed points had to land in both). The final
    iteration's penalty is carried OUT of the scan so the fence can
    fetch a tiny leaf instead of the (pop, E) slots tensor; a post-scan
    batch_penalty would be cheaper still but recompiles the whole loop
    ~9x slower (BASELINE.md fence audit)."""
    import jax
    import jax.numpy as jnp
    from timetabling_ga_tpu.ops import fitness

    @jax.jit
    def chain(s, r):
        def step(carry, _):
            s, r, _ = carry
            pen, _, _ = fitness.batch_penalty(pa, s, r)
            s = (s + pen[:, None] + 1) % n_slots
            return (s, r, pen), None
        (s, r, pen), _ = jax.lax.scan(
            step, (s, r, jnp.zeros((pop,), jnp.int32)), None,
            length=iters)
        return s, pen
    return chain



def _slope_measure(pa, n_slots, pop, slots, rooms, short, long_):
    """Shared slope-timing protocol around _make_eval_chain: time a
    short and a long dependent chain (fresh warm per length, fence on
    the penalty leaf) and return (rate, times, compile_attempts).
    The warm call — where the multi-ten-second remote compile happens
    at scale — runs under retry.retry_transient: BENCH_r05 lost the
    whole scale_2000ev leg to one 'remote_compile: response body
    closed' blip that would have passed seconds later. attempts counts
    the total warm tries across both lengths (2 = clean run) so the
    leg JSON records what the measurement cost. The TIMED re-dispatch
    is never retried — a retry there would splice a sick-window stall
    into the slope. Degenerate levers (a tunnel stall on either leg
    making dt <= 0) return rate 0.0 — callers must handle it (the
    headline falls back to the long-chain single-point; the scale row
    reports the fallback the same way)."""
    from timetabling_ga_tpu.runtime import retry

    times = {}
    attempts = 0
    for iters in (short, long_):
        chain = _make_eval_chain(pa, n_slots, pop, iters)

        def _warm(chain=chain):
            w, pen = chain(slots, rooms)
            _fence(pen)
            return w

        warm, used = retry.retry_transient(_warm, attempts=3,
                                           wait_s=30.0)
        attempts += used
        t0 = time.perf_counter()
        _fence(chain(warm, rooms)[1])
        times[iters] = time.perf_counter() - t0
    dt = times[long_] - times[short]
    rate = pop * (long_ - short) / dt if dt > 0 else 0.0
    return rate, times, attempts


def measure_tpu_evals(problem) -> float:
    """Dependent-chain batched evaluation on the device, SLOPE-measured
    (see BASELINE.md methodology): identical dispatches get deduplicated
    by the tunnel, so every iteration feeds on the previous output; and
    a single-point timing over-counts the fixed dispatch + fetch-fence
    cost (~0.7 s — 4x inflation at 100 iterations), so the rate is the
    slope between a short and a long chain, which cancels every fixed
    term and is the steady-state throughput a long production dispatch
    actually sees. The +1 in the mix forbids per-individual fixed
    points (round-5 audit: the original `s + pen` mix absorbed into
    fixed points, letting the tunnel dedupe long chains)."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness

    pa = problem.device_arrays()
    rng = np.random.default_rng(0)
    slots = jax.device_put(rng.integers(0, problem.n_slots,
                                        size=(POP, N_EVENTS),
                                        dtype=np.int32))
    rooms = jax.device_put(rng.integers(0, N_ROOMS, size=(POP, N_EVENTS),
                                        dtype=np.int32))

    # Slope lever arm must dwarf the fetch-cost run variance (~+-0.3 s
    # on this tunnel — a 300-iteration lever measured 11M evals/s pure
    # noise in the round-5 audit), and the result must clear a physics
    # check: 27.6 MFLOP/eval means anything above ~5M evals/s would
    # exceed the chip's bf16 peak — report the conservative long-chain
    # single-point instead if the slope fails it.
    short, long_ = ITERS, 16 * ITERS
    rate, times, _attempts = _slope_measure(pa, problem.n_slots, POP,
                                            slots, rooms, short, long_)
    kind = "slope"
    if rate > 5e6 or rate <= 0:
        # physics check (27.6 MFLOP/eval: >5M evals/s would exceed the
        # bf16 peak) or a degenerate lever (tunnel stall on one leg):
        # fall back to the conservative long-chain single-point
        rate = POP * long_ / times[long_]
        kind = "single-point(long) — slope failed the sanity checks"
    print(f"# tpu evals: {rate:,.0f}/s "
          f"({POP / rate * 1e3:.2f} ms/batch of {POP}, {kind} over "
          f"{short}/{long_} iters = {times[short]:.2f}s/"
          f"{times[long_]:.2f}s)", file=sys.stderr)
    return rate


def measure_cpu_native(problem) -> float:
    """The honest CPU denominator: the C++ OpenMP evaluator at full
    cores on the same workload."""
    import numpy as np
    from timetabling_ga_tpu import native

    if not native.is_available():
        print(f"# native unavailable: {native.load_error()}",
              file=sys.stderr)
        return 0.0
    threads = os.cpu_count() or 1
    rng = np.random.default_rng(0)
    slots = rng.integers(0, problem.n_slots, size=(POP, N_EVENTS),
                         dtype=np.int32)
    rooms = rng.integers(0, N_ROOMS, size=(POP, N_EVENTS), dtype=np.int32)
    native.eval_batch(problem, slots[:64], rooms[:64], threads)  # warm
    reps = 3
    t0 = time.perf_counter()
    c0 = time.process_time()
    for _ in range(reps):
        native.eval_batch(problem, slots, rooms, threads)
    dt_wall = time.perf_counter() - t0
    dt_cpu = time.process_time() - c0
    # Contention-immune denominator: under background load the wall
    # clock overstates the native evaluator's cost (and so inflates
    # vs_baseline — dishonest in our favor). With ONE thread, process
    # CPU time is exact and contention-free, so use the smaller of the
    # two. With several threads, cpu/threads would assume perfect
    # OpenMP scaling (and trip over cgroup quotas below os.cpu_count),
    # OVERSTATING the baseline — keep the wall clock there; multi-core
    # boxes should run the bench idle.
    dt = min(dt_wall, dt_cpu) if threads == 1 else dt_wall
    rate = POP * reps / dt
    print(f"# cpu native ({threads} threads): {rate:,.0f} evals/s "
          f"(wall {dt_wall:.2f}s, cpu {dt_cpu:.2f}s)", file=sys.stderr)
    return rate


def measure_generation(problem, rooms_mode: str) -> dict:
    """Full breeding pipeline throughput: generations/sec at comp05
    scale with the production config (delta LS), one dispatch of a
    dependent generation chain."""
    import jax
    from timetabling_ga_tpu.ops import ga

    pa = problem.device_arrays()
    pop = 1024
    gens = 20
    cfg = ga.GAConfig(pop_size=pop, ls_steps=25, ls_candidates=8,
                      rooms_mode=rooms_mode)
    state = ga.init_population(pa, jax.random.key(0), pop)
    _fence(state)

    run = jax.jit(lambda k, s: ga.run(pa, k, s, cfg, gens)[0],
                  static_argnums=())
    warm = run(jax.random.key(1), state)
    _fence(warm)
    t0 = time.perf_counter()
    out = run(jax.random.key(2), warm)
    _fence(out)
    dt = time.perf_counter() - t0
    gps = gens / dt
    # candidate evaluations per generation: P children + P*K*rounds LS
    evals_per_gen = pop * (1 + cfg.ls_steps * cfg.ls_candidates)
    print(f"# generation pipeline ({rooms_mode} rooms): {gps:.2f} gen/s, "
          f"{gps * evals_per_gen:,.0f} LS-candidate evals/s, "
          f"{dt / gens * 1e3:.1f} ms/gen (pop {pop})", file=sys.stderr)
    return {"gen_per_sec": round(gps, 3),
            "ms_per_gen": round(dt / gens * 1e3, 2),
            "pop": pop,
            "candidate_evals_per_sec": round(gps * evals_per_gen, 1)}


def measure_generation_sweep(problem, pop: int) -> dict:
    """VERDICT round-2 item 2: the sweep-LS generation pipeline (the
    config the quality race actually ships) measured BEFORE racing it —
    ms/gen is the number the engine's budget-aware dispatch sizing
    consumes, candidate-evals/s the throughput comparison point.

    One generation with ls_sweeps=1 evaluates P * E * (T + swap_block)
    Move1+Move2 delta candidates (ops/sweep.py docstring)."""
    import jax
    from timetabling_ga_tpu.ops import ga

    pa = problem.device_arrays()
    gens = 4
    cfg = ga.GAConfig(pop_size=pop, ls_mode="sweep", ls_sweeps=1,
                      ls_swap_block=8)
    state = ga.init_population(pa, jax.random.key(0), pop)
    _fence(state)

    run = jax.jit(lambda k, s: ga.run(pa, k, s, cfg, gens)[0])
    warm = run(jax.random.key(1), state)
    _fence(warm)
    t0 = time.perf_counter()
    out = run(jax.random.key(2), warm)
    _fence(out)
    dt = time.perf_counter() - t0
    T = problem.n_slots
    evals_per_gen = pop * problem.n_events * (T + cfg.ls_swap_block)
    gps = gens / dt
    print(f"# sweep generation (pop {pop}): {dt / gens * 1e3:.0f} ms/gen, "
          f"{gps * evals_per_gen:,.0f} sweep-candidate evals/s",
          file=sys.stderr)
    return {"pop": pop, "ms_per_gen": round(dt / gens * 1e3, 1),
            "candidate_evals_per_sec": round(gps * evals_per_gen, 1)}


def measure_generation_sweep_tuned(problem, label: str) -> dict:
    """VERDICT round-3 next #7: bench the SHIPPED configuration. The
    plain `measure_generation_sweep` rows use ls_sweeps=1 without
    converge/sideways/hot-K, but `RunConfig.apply_tuned_defaults` ships
    something else entirely — this row derives the tuned config
    programmatically (so it cannot rot when the defaults move) and
    measures the ms/gen the engine's budget-aware dispatch sizing
    actually needs. When the tuned defaults define a post-feasibility
    phase, its config is measured too (`post_ms_per_gen`)."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.runtime import engine
    from timetabling_ga_tpu.runtime.config import RunConfig

    cfg = RunConfig(input="<bench>")
    cfg.apply_tuned_defaults(problem.n_events)
    gacfg = engine.build_ga_config(cfg)
    post = engine.build_post_config(cfg, gacfg)

    pa = problem.device_arrays()
    out = {"pop": gacfg.pop_size, "ls_sweeps": gacfg.ls_sweeps,
           "hot_k": gacfg.ls_hot_k, "converge": gacfg.ls_converge,
           "sideways": gacfg.ls_sideways}
    state = ga.init_population(pa, jax.random.key(0), gacfg.pop_size)
    _fence(state)
    # post-phase generations are deep (measured ~8 s/gen at comp05s
    # scale): keep the fused measurement dispatch under the device's
    # long-kernel watchdog (engine.DISPATCH_CAP_S rationale)
    for name, g, gens in (("ms_per_gen", gacfg, 4),) + (
            (("post_ms_per_gen", post, 2),) if post is not None else ()):
        # the post phase may run a SMALLER population (post_pop_size
        # elite shrink); measure it on the truncated elite rows exactly
        # as the engine runs it (state is penalty-sorted)
        st = (state if g.pop_size == gacfg.pop_size
              else jax.tree.map(lambda x: x[:g.pop_size], state))
        run = jax.jit(lambda k, s, g=g, gens=gens: ga.run(
            pa, k, s, g, gens)[0])
        warm = run(jax.random.key(1), st)
        _fence(warm)
        t0 = time.perf_counter()
        _fence(run(jax.random.key(2), warm))
        dt = time.perf_counter() - t0
        out[name] = round(dt / gens * 1e3, 1)
        print(f"# tuned sweep generation [{label}] {name} "
              f"(pop {g.pop_size}, sweeps {g.ls_sweeps}, hot_k "
              f"{g.ls_hot_k}): {dt / gens * 1e3:.0f} ms/gen",
              file=sys.stderr)
    return out


def measure_ls_shootout_feasible(problem) -> dict:
    """VERDICT round-3 next #8: the shootout regime the race is actually
    lost in. The random-start shootout ends with both sides infeasible —
    it measures hcv repair only. This one first polishes the population
    to feasibility OUTSIDE the timed section (converge sweeps with
    plateau walking, the production init-polish recipe), then compares
    one full-pivot sweep pass against an equal-wall-clock K-random
    budget on the scv-polish endgame. Lower mean penalty wins."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from timetabling_ga_tpu.ops import delta, fitness, sweep
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms

    pa = problem.device_arrays()
    P = 256
    slots = jax.random.randint(jax.random.key(6), (P, problem.n_events),
                               0, problem.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    # untimed prep: repair to (near-)feasibility, production recipe
    slots, rooms = sweep.jit_sweep_local_search(
        pa, jax.random.key(7), slots, rooms, 60, 8, converge=True,
        sideways=0.25, hot_k=48)
    _fence((slots, rooms))
    pen0, hcv0, _ = fitness.batch_penalty(pa, slots, rooms)
    feas_frac = float((np.asarray(hcv0) == 0).mean())

    def timed(fn, *args, **kw):
        out = fn(pa, jax.random.key(8), slots, rooms, *args, **kw)
        _fence(out)      # warm/compile
        t0 = time.perf_counter()
        out = fn(pa, jax.random.key(9), slots, rooms, *args, **kw)
        _fence(out)
        dt = time.perf_counter() - t0
        pen, _, _ = fitness.batch_penalty(pa, *out)
        return float(np.asarray(pen).mean()), dt

    sweep_pen, sweep_dt = timed(sweep.jit_sweep_local_search, 1, 16)
    probe_rounds = 50
    _, probe_dt = timed(delta.jit_batch_local_search_delta, probe_rounds, 8)
    rounds = max(1, int(probe_rounds * sweep_dt / probe_dt))
    rand_pen, rand_dt = timed(delta.jit_batch_local_search_delta, rounds, 8)
    if abs(rand_dt - sweep_dt) / sweep_dt > 0.05:
        rounds = max(1, int(rounds * sweep_dt / rand_dt))
        rand_pen, rand_dt = timed(delta.jit_batch_local_search_delta,
                                  rounds, 8)
    print(f"# LS shootout (feasible start, {feas_frac:.0%} feasible, "
          f"mean pen {float(np.asarray(pen0).mean()):,.1f}): sweep "
          f"{sweep_pen:,.1f} in {sweep_dt:.2f}s vs K-random "
          f"{rand_pen:,.1f} in {rand_dt:.2f}s ({rounds} rounds)",
          file=sys.stderr)
    return {"start_feasible_frac": round(feas_frac, 3),
            "start_mean_pen": round(float(np.asarray(pen0).mean()), 1),
            "sweep_mean_pen": round(sweep_pen, 1),
            "sweep_seconds": round(sweep_dt, 3),
            "krandom_mean_pen": round(rand_pen, 1),
            "krandom_seconds": round(rand_dt, 3),
            "krandom_rounds": rounds,
            "winner": "sweep" if sweep_pen <= rand_pen else "krandom"}


def measure_generation_nsga(problem) -> dict:
    """NSGA-II replacement-stage cost (BASELINE.json config 5, VERDICT
    round-4 next #4): the same generation pipeline with the scalar
    (penalty, scv) truncation vs the (hcv, scv) non-dominated-sort +
    crowding replacement, identical shapes — the delta is what the
    O(P^2) front machinery costs per generation. Quality evidence lives
    in the race (--nsga2 legs, BASELINE.md); this row is throughput."""
    import jax
    from timetabling_ga_tpu.ops import ga

    pa = problem.device_arrays()
    pop, gens = 64, 8
    out = {"pop": pop}
    for label, mo in (("scalar_ms_per_gen", False),
                      ("nsga2_ms_per_gen", True)):
        cfg = ga.GAConfig(pop_size=pop, ls_mode="sweep", ls_sweeps=1,
                          ls_swap_block=8, multi_objective=mo)
        state = ga.init_population(pa, jax.random.key(0), pop)
        run = jax.jit(lambda k, s, cfg=cfg: ga.run(pa, k, s, cfg, gens)[0])
        _fence(run(jax.random.key(1), state))
        t0 = time.perf_counter()
        _fence(run(jax.random.key(2), state))
        dt = time.perf_counter() - t0
        out[label] = round(dt / gens * 1e3, 1)
    out["nsga2_overhead_pct"] = round(
        100.0 * (out["nsga2_ms_per_gen"] / out["scalar_ms_per_gen"] - 1), 1)
    print(f"# nsga2 generation (pop {pop}): scalar "
          f"{out['scalar_ms_per_gen']} ms/gen vs nsga2 "
          f"{out['nsga2_ms_per_gen']} ms/gen "
          f"({out['nsga2_overhead_pct']:+.1f}%)", file=sys.stderr)
    return out


# v5e public peaks now live in the cost observatory (obs/cost.py) —
# the SAME constants the live roofline gauges use; kept as module
# aliases for external readers of older bench rounds' code
from timetabling_ga_tpu.obs.cost import (  # noqa: E402
    BF16_PEAK_TFLOPS, HBM_PEAK_GBPS)


def measure_lahc_chain(problem) -> dict:
    """LAHC endgame chain rate (ops/lahc.py, --post-lahc): ensemble
    steps/s and candidate evals/s for the shipped steepest-of-16 block
    at the comp-scale endgame walker count. The sequential acceptance
    chain is dispatch-latency-bound, which is WHY the LAHC endgame
    lost its comp01s probe to the sweep endgame (BASELINE.md round 5 —
    a measured negative result); this entry pins the rate that verdict
    rests on."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.ops.lahc import jit_init_lahc, jit_lahc_steps
    pa = problem.device_arrays()
    P, K, steps = 16, 16, 20000
    st = ga.init_population(pa, jax.random.key(0), P)
    ls0 = jit_init_lahc(pa, st.slots, st.rooms, hist_len=5000)
    args = dict(p1=1.0, p2=1.0, p3=0.0, k_cands=K)
    ls = jit_lahc_steps(pa, jax.random.key(1), ls0, 2000, **args)
    jax.device_get(ls.ls.pen)          # warm; REAL fence (see below)
    t0 = time.perf_counter()
    ls = jit_lahc_steps(pa, jax.random.key(2), ls0, steps, **args)
    # the fence MUST be a data fetch: on the tunneled device
    # block_until_ready acknowledges before the computation completes
    # (measured: a 100k-step dependent chain "finished" in 0.000 s by
    # block_until_ready, vs 51.2 s by device_get — the same artifact
    # class as the methodology note's deduped repeats)
    jax.device_get(ls.ls.pen)
    dt = time.perf_counter() - t0
    return {"walkers": P, "k_cands": K,
            "steps_per_sec": round(steps / dt, 1),
            "cand_evals_per_sec": round(steps * P * K / dt, 1)}


def measure_kernel_cost(problem, achieved_evals_per_sec: float) -> dict:
    """Arithmetic-intensity numbers behind the round-4 'bandwidth-bound'
    adjective (VERDICT round-4 weak #6), from XLA's own cost model for
    one fitness batch — sourced through the cost observatory
    (obs/cost.py) rather than this leg's own lower/compile plumbing
    (ISSUE 7 satellite: the SAME extraction now feeds the live
    `cost.*` gauges, so the bench and the dashboard cannot disagree),
    with the leg's compile accounted in the `compile.*` families
    (including transient-compile retries — the BENCH_r05 scale_2000ev
    'response body closed' class).

    Interpretation caveat that the numbers themselves expose: XLA's
    'bytes accessed' is LOGICAL (per-HLO buffer traffic, counted before
    fusion keeps intermediates in VMEM), so it upper-bounds HBM traffic.
    When logical bytes x measured evals/s exceeds the HBM peak — as it
    does here — that is POSITIVE evidence of fusion: the excess
    fraction provably never left the chip, and the kernel is
    compute-rich rather than HBM-starved."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.obs import cost as obs_cost
    from timetabling_ga_tpu.obs import metrics as obs_metrics
    from timetabling_ga_tpu.ops import fitness

    pa = problem.device_arrays()
    rng = np.random.default_rng(0)
    slots = rng.integers(0, problem.n_slots, size=(POP, N_EVENTS),
                         dtype=np.int32)
    rooms = rng.integers(0, N_ROOMS, size=(POP, N_EVENTS), dtype=np.int32)
    retries0 = obs_metrics.REGISTRY.counter("compile.retries").value
    prog = obs_cost.CostProgram(
        jax.jit(lambda s, r: fitness.batch_penalty(pa, s, r)),
        "bench_fitness")
    prog(slots, rooms)                 # compiles through the observatory
    cost = prog.last_cost or {}
    flops = cost.get("flops", 0.0)
    byts = cost.get("bytes_accessed", 0.0)
    entry = next((e for e in reversed(obs_cost.OBSERVATORY.entries)
                  if e["program"] == "bench_fitness"), {})
    out = {"pop": POP,
           **obs_cost.roofline(flops / POP, byts / POP,
                               achieved_evals_per_sec),
           "compile_seconds": round(entry.get("lower_s", 0.0)
                                    + entry.get("compile_s", 0.0), 3),
           "compile_retries": int(
               obs_metrics.REGISTRY.counter("compile.retries").value
               - retries0)}
    print(f"# kernel cost (XLA model): {out['flops_per_eval']:,.0f} "
          f"flop/eval, {out['logical_bytes_per_eval']:,.0f} logical "
          f"B/eval, AI={out['arithmetic_intensity_flops_per_byte']}; "
          f"achieved {out.get('achieved_tflops', '?')} TFLOP/s "
          f"({out.get('flop_utilization_vs_bf16_peak_pct', '?')}% of "
          f"bf16 peak), logical "
          f"{out.get('logical_gbps_at_measured_rate', '?')} GB/s vs "
          f"{HBM_PEAK_GBPS} HBM peak -> >= "
          f"{out.get('min_fused_fraction_pct', '?')}% provably fused",
          file=sys.stderr)
    return out


def measure_scale() -> dict:
    """VERDICT item 6: synthetic E=2000 / R=80, pop=32768, single chip —
    exercises the memory plan (SURVEY hard part 3)."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness
    from timetabling_ga_tpu.problem import random_instance

    E, R, S, P = 2000, 80, 1000, 32768
    problem = random_instance(7, n_events=E, n_rooms=R, n_features=10,
                              n_students=S, attend_prob=0.01)
    pa = problem.device_arrays()
    rng = np.random.default_rng(0)
    slots = jax.device_put(rng.integers(0, problem.n_slots, size=(P, E),
                                        dtype=np.int32))
    rooms = jax.device_put(rng.integers(0, R, size=(P, E), dtype=np.int32))
    # same slope protocol as the headline (shared chain + shared
    # timing loop, fixed costs cancel); shorter lever than the
    # headline's because each length is its own multi-ten-second
    # compile at this size. A degenerate lever (tunnel stall) falls
    # back to the long-chain single-point, like the headline.
    short, long_ = 4, 20
    rate, times, attempts = _slope_measure(pa, problem.n_slots, P,
                                           slots, rooms, short, long_)
    kind = "slope"
    if rate <= 0:
        rate = P * long_ / times[long_]
        kind = "single-point(long) — degenerate slope lever"
    print(f"# scale E={E} R={R} pop={P}: {rate:,.0f} evals/s "
          f"({P / rate * 1e3:.1f} ms/batch, {kind} {short}/{long_} "
          f"iters = {times[short]:.2f}s/{times[long_]:.2f}s, "
          f"{attempts} compile attempts), no OOM",
          file=sys.stderr)
    # compile_attempts: 2 = clean (one warm per chain length); more
    # means retry_transient absorbed remote-compile blips (BENCH_r05)
    return {"E": E, "R": R, "pop": P, "evals_per_sec": round(rate, 1),
            "ms_per_batch": round(P / rate * 1e3, 2),
            "compile_attempts": attempts}


def measure_pipeline(problem, pop: int = 1024, gens: int = 40) -> dict:
    """ISSUE 2 tentpole leg: the engine's depth-2 asynchronous dispatch
    pipeline, A/B against the strictly serial loop in the SAME session
    (shared compile caches via precompile, identical seeds/shapes/keys).

    Reported per mode: the generation loop's wall time (the engine's
    `gen-loop` trace record), host gap per dispatch / per generation,
    and device-busy fraction. Device time is taken from the SERIAL
    leg's enqueue-to-fence dispatch brackets — the trusted measurement
    path; the pipelined leg runs byte-identical device work (same
    programs, same key sequence; `records_identical_modulo_timing`
    asserts it from the JSONL protocol itself), so the serial bracket
    is the right denominator for both. The pipelined host gap must sit
    measurably below the serial one — that delta is the host I/O the
    pipeline hides behind device compute."""
    import dataclasses
    import io
    import json as _json
    import tempfile

    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    try:
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=5,
                         epochs_per_dispatch=1, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True)
        engine.precompile(base)
        # both legs start from the SAME sec/gen estimate: the serial
        # leg's EWMA updates land in the shared _SPG_CACHE and could
        # otherwise push the pipelined leg's dispatch sizing across a
        # pow2/watchdog threshold — a different shape sequence means
        # different key splits and records_identical=False for a
        # timing reason, not a pipelining one. (With this config n_ep
        # is pinned at 1 and the budget unbounded, so sizing thresholds
        # stay out of play; the identity field REPORTS the comparison
        # rather than assuming it.)
        spg_snapshot = dict(engine._SPG_CACHE)

        def leg(pipeline):
            engine._SPG_CACHE.clear()
            engine._SPG_CACHE.update(spg_snapshot)
            cfg = dataclasses.replace(base, pipeline=pipeline)
            buf = io.StringIO()
            best = engine.run(cfg, out=buf)
            lines = [_json.loads(x) for x in
                     buf.getvalue().splitlines()]
            disp = [x["phase"] for x in lines
                    if "phase" in x and x["phase"]["name"] == "dispatch"]
            loop = [x["phase"] for x in lines
                    if "phase" in x and x["phase"]["name"] == "gen-loop"]
            return {"best": best, "loop_s": loop[0]["seconds"],
                    "dispatches": loop[0]["dispatches"],
                    "active": loop[0]["pipelined"],
                    "disp_s": sum(d["seconds"] for d in disp),
                    "gens": sum(d["gens"] for d in disp),
                    "recs": jsonl.strip_timing(lines)}

        serial = leg(False)
        piped = leg(True)
    finally:
        os.unlink(tim)
    device_s = serial["disp_s"]
    nd, gens = serial["dispatches"], serial["gens"]
    gap_s = serial["loop_s"] - device_s
    # the serial bracket includes per-dispatch fetch overhead the
    # pipeline hides entirely, and device time varies a few percent
    # between the two runs — a fully-hidden host gap can therefore
    # compute slightly NEGATIVE; clamp to 0 (the magnitude lives in
    # loop_speedup / the loop_s pair)
    gap_p = max(0.0, piped["loop_s"] - device_s)
    out = {
        "pop": pop, "gens": gens, "dispatches": nd,
        "pipelined_active": bool(piped["active"]),
        "serial_loop_s": round(serial["loop_s"], 3),
        "pipelined_loop_s": round(piped["loop_s"], 3),
        "device_s_serial_bracket": round(device_s, 3),
        "host_gap_ms_per_dispatch_serial": round(gap_s / nd * 1e3, 3),
        "host_gap_ms_per_dispatch_pipelined": round(gap_p / nd * 1e3, 3),
        "host_gap_ms_per_gen_serial": round(gap_s / gens * 1e3, 3),
        "host_gap_ms_per_gen_pipelined": round(gap_p / gens * 1e3, 3),
        "device_busy_frac_serial":
            round(min(1.0, device_s / serial["loop_s"]), 4),
        "device_busy_frac_pipelined":
            round(min(1.0, device_s / piped["loop_s"]), 4),
        "loop_speedup": round(serial["loop_s"] / piped["loop_s"], 4),
        "records_identical_modulo_timing":
            serial["recs"] == piped["recs"],
    }
    print(f"# pipeline A/B (pop {pop}, {nd} dispatches, {gens} gens): "
          f"serial loop {serial['loop_s']:.3f}s vs pipelined "
          f"{piped['loop_s']:.3f}s (x{out['loop_speedup']}); host gap "
          f"{out['host_gap_ms_per_gen_serial']} -> "
          f"{out['host_gap_ms_per_gen_pipelined']} ms/gen; device busy "
          f"{out['device_busy_frac_serial']:.1%} -> "
          f"{out['device_busy_frac_pipelined']:.1%}; records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def measure_accord(problem, pop: int = 256, gens: int = 30) -> dict:
    """extra.accord leg (ISSUE 18, tt-accord): what the control side
    channel costs when nothing is wrong.

    Two measurements. (1) Single-process engine A/B, channel on (the
    inert solo loopback every default run now carries) vs off
    (--no-accord): wall-clock pair plus the records-identical
    assertion — the channel must be free AND invisible when there is
    no peer. (2) The protocol microbench: a 2-view LoopbackChannel
    group runs the real agreement code (`agree` process-0-wins fences
    and `guard_collective` rendezvous, second view on a thread), giving
    ms/fence for the agreement machinery itself — the per-fence
    overhead a multi-host run pays on the HOST path, off the device."""
    import dataclasses
    import io
    import json as _json
    import tempfile
    import threading

    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import control_channel as cc
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    try:
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=5,
                         epochs_per_dispatch=1, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True)
        engine.precompile(base)

        def leg(accord):
            cfg = dataclasses.replace(base, accord=accord)
            buf = io.StringIO()
            t0 = time.perf_counter()
            best = engine.run(cfg, out=buf)
            wall = time.perf_counter() - t0
            lines = [_json.loads(x) for x in
                     buf.getvalue().splitlines()]
            return {"best": best, "wall_s": wall,
                    "recs": jsonl.strip_timing(lines)}

        on = leg(True)
        off = leg(False)
    finally:
        os.unlink(tim)

    fences = 300
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        def follower():
            for _ in range(fences):
                ch1.agree("b", None)
            for _ in range(fences):
                ch1.guard_collective()
        t = threading.Thread(target=follower, daemon=True)
        t.start()
        t0 = time.perf_counter()
        for _ in range(fences):
            ch0.agree("b", [1, 2, 3])
        agree_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(fences):
            ch0.guard_collective()
        guard_s = time.perf_counter() - t0
        t.join(60)
    finally:
        ch0.close()
        ch1.close()

    out = {
        "pop": pop, "gens": gens,
        "wall_s_accord_on": round(on["wall_s"], 3),
        "wall_s_accord_off": round(off["wall_s"], 3),
        "best_on": on["best"], "best_off": off["best"],
        "records_identical": on["recs"] == off["recs"],
        "fences": fences,
        "agree_ms_per_fence": round(agree_s / fences * 1e3, 4),
        "guard_ms_per_fence": round(guard_s / fences * 1e3, 4),
    }
    print(f"# accord A/B (pop {pop}, {gens} gens): wall "
          f"{out['wall_s_accord_on']}s on vs "
          f"{out['wall_s_accord_off']}s off; records identical="
          f"{out['records_identical']}; loopback 2-view agreement "
          f"{out['agree_ms_per_fence']} ms/agree, "
          f"{out['guard_ms_per_fence']} ms/guard "
          f"({fences} fences)", file=sys.stderr)
    return out


def measure_ls_shootout(problem) -> dict:
    """VERDICT item 2: systematic sweep vs K-random local search, equal
    wall clock, same start population. Reports mean penalty reached —
    lower is better; the winner is the production default."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from timetabling_ga_tpu.ops import delta, fitness, sweep
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms

    pa = problem.device_arrays()
    P = 512
    slots = jax.random.randint(jax.random.key(3), (P, problem.n_events),
                               0, problem.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    _fence((slots, rooms))

    def timed(fn, *args, **kw):
        out = fn(pa, jax.random.key(4), slots, rooms, *args, **kw)
        _fence(out)      # warm/compile
        t0 = time.perf_counter()
        out = fn(pa, jax.random.key(5), slots, rooms, *args, **kw)
        _fence(out)
        dt = time.perf_counter() - t0
        pen, _, _ = fitness.batch_penalty(pa, *out)
        return float(np.asarray(pen).mean()), dt

    # one sweep pass vs a K-random budget tuned to EQUAL wall clock:
    # size rounds from a probe, then correct once from the measured run
    # so the two sides land within ~5% (VERDICT round-2 weak 4: the
    # round-2 shootout gave K-random 23% less time)
    sweep_pen, sweep_dt = timed(sweep.jit_sweep_local_search, 1, 8)
    probe_rounds = 50
    _, probe_dt = timed(delta.jit_batch_local_search_delta, probe_rounds, 8)
    rounds = max(1, int(probe_rounds * sweep_dt / probe_dt))
    rand_pen, rand_dt = timed(delta.jit_batch_local_search_delta, rounds, 8)
    if abs(rand_dt - sweep_dt) / sweep_dt > 0.05:
        rounds = max(1, int(rounds * sweep_dt / rand_dt))
        rand_pen, rand_dt = timed(delta.jit_batch_local_search_delta,
                                  rounds, 8)
    print(f"# LS shootout (equal wall clock): sweep {sweep_pen:,.1f} in "
          f"{sweep_dt:.2f}s vs K-random {rand_pen:,.1f} in {rand_dt:.2f}s "
          f"({rounds} rounds)", file=sys.stderr)
    return {"sweep_mean_pen": round(sweep_pen, 1),
            "sweep_seconds": round(sweep_dt, 3),
            "krandom_mean_pen": round(rand_pen, 1),
            "krandom_seconds": round(rand_dt, 3),
            "krandom_rounds": rounds,
            "winner": "sweep" if sweep_pen <= rand_pen else "krandom"}


def measure_serve() -> dict:
    """extra.serve leg (ISSUE 4): a mixed-size job stream through the
    tt-serve scheduler on one device vs the SAME jobs one-at-a-time.

    Reports jobs/minute for both, the bucket-compile count of the
    batched run (every job pads to a shared bucket shape, so the whole
    stream should trace each island program once per bucket), and
    p50/p95 per-job latency. The one-at-a-time baseline uses one lane
    with a quantum covering the whole budget — the sequential service
    a per-instance CLI loop would provide."""
    import io

    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.problem import random_instance
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    # mixed sizes: five different-shape jobs that all land in ONE
    # bucket (E<=128, R<=8, S<=64 with the default floors/ratio), plus
    # one job in a smaller bucket — realistic heterogeneous traffic
    shapes = [(100, 8, 60), (120, 7, 50), (90, 8, 55), (70, 6, 64),
              (110, 8, 60), (40, 4, 30)]
    problems = [random_instance(1000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.05)
                for i, (e, r, s) in enumerate(shapes)]
    gens = 60

    def run_stream(lanes, quantum):
        buf = io.StringIO()
        cfg = ServeConfig(lanes=lanes, quantum=quantum, pop_size=16,
                          max_steps=32)
        svc = SolveService(cfg, out=buf)
        t0 = time.perf_counter()
        ids = [svc.submit(p, generations=gens, seed=i)
               for i, p in enumerate(problems)]
        svc.drive()
        wall = time.perf_counter() - t0
        lat = sorted(svc.queue.get(j).finished_t
                     - svc.queue.get(j).submitted_t for j in ids)
        svc.close()
        return wall, lat

    c0 = dict(islands.TRACE_COUNTS)
    wall_b, lat_b = run_stream(lanes=4, quantum=20)
    c1 = dict(islands.TRACE_COUNTS)
    bucket_compiles = sum(c1.get(k, 0) - c0.get(k, 0) for k in c1)
    wall_s, lat_s = run_stream(lanes=1, quantum=gens)

    def pct(lat, q):
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    return {
        "jobs": len(problems),
        "generations_per_job": gens,
        "jobs_per_min_batched": round(len(problems) / wall_b * 60, 2),
        "jobs_per_min_serial": round(len(problems) / wall_s * 60, 2),
        "stream_speedup": round(wall_s / wall_b, 2) if wall_b else 0.0,
        "bucket_compiles": bucket_compiles,
        "p50_latency_s_batched": round(pct(lat_b, 0.5), 3),
        "p95_latency_s_batched": round(pct(lat_b, 0.95), 3),
        "p50_latency_s_serial": round(pct(lat_s, 0.5), 3),
        "p95_latency_s_serial": round(pct(lat_s, 0.95), 3),
        "note": "batched = 4 lanes x 20-gen quanta; serial = 1 lane, "
                "one job at a time (whole-budget quantum). On a serial "
                "CPU backend the vmapped lanes execute sequentially, "
                "so stream_speedup < 1 is expected there; on parallel "
                "accelerators lane width rides the vmap/batch "
                "dimension. bucket_compiles counts island-program "
                "traces across the whole batched stream (2 programs "
                "per bucket: init + runner).",
    }


def measure_serve_mesh() -> dict:
    """extra.serve_mesh leg (ISSUE 17): the multi-device serving A/B —
    the SAME six same-bucket jobs through the scheduler three ways:

      1dev_parked     --mesh-devices 1 --no-resident: the pre-ISSUE-17
                      baseline (single-device mesh, park/resume host
                      round trip every quantum)
      ndev_parked     full mesh, still parking every quantum — isolates
                      the lane-sharding win (jobs/min)
      ndev_resident   full mesh + device-resident groups — isolates the
                      residency win (host-gap ms/quantum, park/resume
                      bytes moved)

    Each mesh width gets a discarded warm pass first so every clocked
    leg rides warm bucket programs (compile keys include the mesh —
    the measure_usage discipline, per width). Asserts the per-job
    record streams of both N-device legs are strip_timing-identical to
    the 1-device baseline: lane RNG streams are pure functions of
    (seed, chunk, gen), so mesh width and residency must never show in
    a record. On a single-device host all three legs see devices=1 and
    the jobs/min comparison degenerates (reported, not asserted);
    under forced host devices (tests/conftest.py XLA flag) or a real
    multi-chip replica the spread is the tentpole's headline."""
    import io
    import json as _json

    from timetabling_ga_tpu.obs.metrics import MetricsRegistry
    from timetabling_ga_tpu.problem import random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    # six different-shape jobs that all land in ONE bucket (E<=128,
    # R<=8, S<=64 under the default floors/ratio): the whole stream
    # stacks into a single lane group — the shape sharding accelerates
    shapes = [(100, 8, 60), (120, 7, 50), (90, 8, 55), (70, 6, 64),
              (110, 8, 60), (95, 7, 58)]
    problems = [random_instance(1000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.05)
                for i, (e, r, s) in enumerate(shapes)]
    gens = 60

    def leg(mesh_devices, resident):
        buf = io.StringIO()
        cfg = ServeConfig(lanes=len(problems), quantum=15, pop_size=16,
                          max_steps=32, mesh_devices=mesh_devices,
                          resident=resident)
        # a PRIVATE registry per leg: park/resume byte counters and
        # quantum seconds must be this leg's own
        svc = SolveService(cfg, out=buf, registry=MetricsRegistry())
        t0 = time.perf_counter()
        ids = [svc.submit(p, job_id=f"m{i}", generations=gens, seed=i)
               for i, p in enumerate(problems)]
        svc.drive()
        wall = time.perf_counter() - t0
        reg = svc.registry

        def c(name):
            return reg.counter(name).value

        out = {"wall": wall, "devices": svc.scheduler.mesh.devices.size,
               "lanes": svc.scheduler.lanes,
               "quanta": int(c("serve.dispatches")),
               "device_s": c("serve.quantum_seconds"),
               "park_bytes": int(c("serve.park_bytes")),
               "resume_bytes": int(c("serve.resume_bytes")),
               "resident_hits": int(c("serve.resident_hits"))}
        svc.close()
        lines = [_json.loads(x) for x in buf.getvalue().splitlines()]
        out["per_job"] = {
            j: jsonl.strip_timing(
                [rec for rec in lines
                 if rec[next(iter(rec))].get("job") == j])
            for j in ids}
        return out

    leg(1, False)                   # warm pass, 1-device mesh
    leg(0, False)                   # warm pass, full mesh
    legs = {"1dev_parked": leg(1, False),
            "ndev_parked": leg(0, False),
            "ndev_resident": leg(0, True)}
    base = legs["1dev_parked"]
    for name, l in legs.items():
        assert l["per_job"] == base["per_job"], (
            f"serve_mesh: {name} per-job record streams diverged from "
            f"the 1-device parked baseline (strip_timing domain)")

    def row(l):
        q = max(1, l["quanta"])
        return {
            "devices": int(l["devices"]), "lanes": int(l["lanes"]),
            "quanta": l["quanta"],
            "jobs_per_min": round(len(problems) / l["wall"] * 60, 2),
            "host_gap_ms_per_quantum": round(
                (l["wall"] - l["device_s"]) / q * 1e3, 2),
            "park_resume_bytes_per_quantum": int(
                (l["park_bytes"] + l["resume_bytes"]) / q),
            "resident_hits": l["resident_hits"],
        }

    out = {"jobs": len(problems), "generations_per_job": gens,
           "records_identical_per_job": True,   # asserted above
           **{name: row(l) for name, l in legs.items()}}
    print(f"# serve_mesh A/B ({out['jobs']} jobs x {gens} gens): "
          f"1dev {out['1dev_parked']['jobs_per_min']} jobs/min -> "
          f"{legs['ndev_parked']['devices']}dev "
          f"{out['ndev_parked']['jobs_per_min']} jobs/min; resident "
          f"host gap {out['ndev_resident']['host_gap_ms_per_quantum']} "
          f"ms/quantum vs parked "
          f"{out['ndev_parked']['host_gap_ms_per_quantum']}, bytes/"
          f"quantum {out['ndev_resident']['park_resume_bytes_per_quantum']} "
          f"vs {out['ndev_parked']['park_resume_bytes_per_quantum']}; "
          f"records identical per job", file=sys.stderr)
    return out


def measure_usage() -> dict:
    """extra.usage leg (tt-meter, README "Usage metering"): same-seed
    serve stream with metering OFF vs ON — the meter's cost and its
    two pinned contracts on a live stream:

      overhead ms/dispatch    wall-time delta per dispatch (the drive
                              loop pays dict arithmetic + one bounded
                              deque append; the folds ride the ledger
                              thread)
      conservation            every emitted per-dispatch usageEntry's
                              lane shares sum EXACTLY to its dispatch
                              totals (obs/usage.split)
      records identical       strip_timing streams match with metering
                              on or off (usageEntry is TIMING)
    """
    import dataclasses
    import io
    import json as _json

    from timetabling_ga_tpu.obs import usage as obs_usage
    from timetabling_ga_tpu.problem import random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    shapes = [(100, 8, 60), (120, 7, 50), (90, 8, 55), (70, 6, 64),
              (110, 8, 60), (40, 4, 30)]
    problems = [random_instance(1000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.05)
                for i, (e, r, s) in enumerate(shapes)]
    gens = 60
    base = ServeConfig(lanes=4, quantum=15, pop_size=16, max_steps=32,
                       obs=True, metrics_every=0)

    from timetabling_ga_tpu.obs.metrics import MetricsRegistry

    def leg(usage):
        buf = io.StringIO()
        # a PRIVATE registry per leg: the dispatch count must be this
        # leg's own, not the process-cumulative one
        svc = SolveService(dataclasses.replace(base, usage=usage),
                           out=buf, registry=MetricsRegistry())
        t0 = time.perf_counter()
        for i, p in enumerate(problems):
            svc.submit(p, job_id=f"u{i}", seed=i, generations=gens,
                       tenant=f"tenant{i % 3}")
        svc.drive()
        wall = time.perf_counter() - t0
        dispatches = svc.registry.counter("serve.dispatches").value
        svc.close()
        lines = [_json.loads(x) for x in buf.getvalue().splitlines()]
        return {"wall": wall, "dispatches": int(dispatches),
                "entries": [x["usageEntry"] for x in lines
                            if "usageEntry" in x],
                "recs": jsonl.strip_timing(lines)}

    leg(False)      # warm-up: both clocked legs ride warm bucket
    #                 programs, so the delta prices the METER, not a
    #                 compile (the measure_fleet discipline)
    off = leg(False)
    on = leg(True)

    # conservation: every dispatch entry's lane shares sum EXACTLY to
    # its totals, for each conserved component
    disp_entries = [e for e in on["entries"] if "lanes" in e]
    conserved = bool(disp_entries) and all(
        sum(lane[f] for lane in e["lanes"]) == e[f]
        for e in disp_entries
        for f in ("gens", "device_seconds", "compile_seconds", "flops"))
    report = obs_usage.fold_entries(
        [{"usageEntry": e} for e in on["entries"]])
    out = {
        "jobs": len(problems), "gens_per_job": gens,
        "dispatches": on["dispatches"],
        "wall_s_usage_off": round(off["wall"], 3),
        "wall_s_usage_on": round(on["wall"], 3),
        "usage_overhead_ms_per_dispatch": round(
            (on["wall"] - off["wall"]) / max(1, on["dispatches"])
            * 1e3, 3),
        "usage_entries": len(on["entries"]),
        "tenants_metered": len(report["tenants"]),
        "conservation_holds": conserved,
        "records_identical_modulo_timing": off["recs"] == on["recs"],
    }
    print(f"# usage A/B ({out['dispatches']} dispatches): "
          f"{out['wall_s_usage_off']}s off vs "
          f"{out['wall_s_usage_on']}s on "
          f"({out['usage_overhead_ms_per_dispatch']} ms/dispatch, "
          f"{out['usage_entries']} usageEntry); conservation="
          f"{out['conservation_holds']}, records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def measure_soak() -> dict:
    """extra.soak leg (ISSUE 7): ROADMAP item 3's 'heavy traffic' as
    MEASURED numbers — a sustained mixed-stream of jobs arriving in
    waves against a deliberately small shed high-water mark, reporting
    the four quantities the fleet story is steered by:

      jobs/min           sustained completion rate under load
      p50/p99 latency    per-job submit-to-finish wall time
      compile-hit rate   warm-dispatch fraction from the cost
                         observatory's compile.{count,cache_hits}
                         families (obs/cost.py) — the number
                         bucket-affine routing amortizes
      shed rate          fraction of admitted work dropped by
                         registry-driven backpressure (--shed-queue-hwm)

    Arrival pattern: an initial burst over the HWM (so shedding
    actually engages), then waves of submissions interleaved with
    scheduler steps — jobs keep arriving while earlier ones execute,
    which is what makes the compile-hit rate meaningful (every wave
    after the first rides the first wave's bucket compiles)."""
    import io

    from timetabling_ga_tpu.obs import cost as obs_cost
    from timetabling_ga_tpu.obs import metrics as obs_metrics
    from timetabling_ga_tpu.problem import random_instance
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    # two buckets of mixed shapes (the big one dominates), 14 jobs
    shapes = ([(100, 8, 60), (120, 7, 50), (90, 8, 55), (110, 8, 60),
               (80, 6, 64), (95, 7, 58)] * 2 + [(40, 4, 30), (36, 4, 28)])
    problems = [random_instance(3000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.05)
                for i, (e, r, s) in enumerate(shapes)]
    gens = 40
    waves = [problems[:8], problems[8:11], problems[11:]]

    buf = io.StringIO()
    cfg = ServeConfig(lanes=2, quantum=10, pop_size=16, max_steps=32,
                      shed_queue_hwm=6)
    svc = SolveService(cfg, out=buf)
    reg = obs_metrics.REGISTRY

    def counters():
        return {k: reg.counter(k).value
                for k in ("compile.count", "compile.cache_hits",
                          "serve.jobs_admitted", "serve.jobs_shed",
                          "serve.jobs_done")}

    c0 = counters()
    ids: list = []
    t0 = time.perf_counter()
    for w, wave in enumerate(waves):
        for p in wave:
            ids.append(svc.submit(p, generations=gens,
                                  seed=len(ids), priority=0))
        # interleave arrival with service: a few dispatch cycles per
        # wave keeps the stream SUSTAINED rather than batch-then-drain
        for _ in range(3):
            if not svc.step():
                break
    svc.drive()
    wall = time.perf_counter() - t0
    c1 = counters()
    d = {k: c1[k] - c0[k] for k in c1}
    done_ids = [j for j in ids if svc.queue.get(j).state == "done"]
    lat = sorted(svc.queue.get(j).finished_t
                 - svc.queue.get(j).submitted_t for j in done_ids)
    svc.close()

    def pct(vals, q):
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    hits, compiles = d["compile.cache_hits"], d["compile.count"]
    out = {
        "jobs_submitted": len(ids),
        "generations_per_job": gens,
        "jobs_done": len(done_ids),
        "jobs_shed": int(d["serve.jobs_shed"]),
        "shed_rate": round(d["serve.jobs_shed"]
                           / max(1, d["serve.jobs_admitted"]), 3),
        "wall_s": round(wall, 3),
        "jobs_per_min": round(len(done_ids) / wall * 60, 2),
        "p50_latency_s": round(pct(lat, 0.5), 3) if lat else None,
        "p99_latency_s": round(pct(lat, 0.99), 3) if lat else None,
        "compiles": int(compiles),
        "compile_hits": int(hits),
        "compile_hit_rate": round(hits / max(1, hits + compiles), 3),
        "compile_hit_rate_process": round(obs_cost.compile_hit_rate(),
                                          3),
        "shed_queue_hwm": cfg.shed_queue_hwm,
        "note": "mixed 2-bucket stream in 3 waves against "
                "shed-queue-hwm 6; compile_hit_rate is the leg's "
                "delta, compile_hit_rate_process the whole-process "
                "ratio (warm from earlier legs)",
    }
    print(f"# soak ({len(ids)} jobs, {gens} gens each): "
          f"{out['jobs_per_min']} jobs/min, p50 {out['p50_latency_s']}s "
          f"p99 {out['p99_latency_s']}s, compile-hit rate "
          f"{out['compile_hit_rate']} ({hits}/{hits + compiles}), shed "
          f"rate {out['shed_rate']} ({out['jobs_shed']} shed)",
          file=sys.stderr)
    return out


def measure_fleet() -> dict:
    """extra.fleet leg (ISSUE 10 + the ISSUE 11 obs A/B): the
    same-seed mixed-bucket job stream through the fleet gateway
    against 1 routed replica vs 2, reporting the routing story's
    numbers:

      jobs/min (1 vs 2)    end-to-end completion rate at the gateway
      p50/p99 latency      submit-to-settled per job (includes the
                           gateway's poll cadence — the HONEST e2e
                           number a tenant sees)
      affinity hit rate    fraction of post-warm-up routings that
                           landed where the bucket was already warm
                           (fleet/router.py hit_rate)
      records identical    every routed job's record stream (modulo
                           timing fields) bit-equal to the SAME job
                           solved on a bare unrouted SolveService —
                           the failover/packing-neutrality contract
      obs A/B (tt-obs v5)  the 2-replica leg re-run with the
                           gateway's telemetry stream ON (`-o`):
                           gateway overhead ms/job, the span/route/
                           metrics record counts its log carried, the
                           fleet.route.* counters scraped off its
                           /metrics (via the shared obs/scrape
                           parser), and the SAME records-identical
                           assertion — the gateway observatory must
                           be a pure observer of the job streams

    In-process replicas with private registries (the CPU test double
    for N worker processes); the 1-replica run is the routed baseline,
    so the delta isolates what the second replica buys."""
    import io

    from timetabling_ga_tpu.fleet.gateway import Gateway
    from timetabling_ga_tpu.fleet.replicas import (
        http_json, http_text, in_process_replica)
    from timetabling_ga_tpu.obs import scrape as obs_scrape
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import FleetConfig, ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    # two shape buckets (E<=32 and E<=64 with the default floors),
    # interleaved — mixed traffic that exercises per-bucket pinning
    shapes = [(28, 3, 24), (52, 5, 40), (25, 3, 20), (60, 6, 44),
              (30, 3, 28), (56, 5, 42), (26, 3, 22), (62, 6, 46),
              (29, 3, 26), (58, 5, 44)]
    problems = [random_instance(5000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.08)
                for i, (e, r, s) in enumerate(shapes)]
    tims = [dump_tim(p) for p in problems]
    gens = 40

    def serve_cfg():
        return ServeConfig(backend="cpu", lanes=2, quantum=10,
                           pop_size=6, max_steps=16,
                           http="127.0.0.1:0")

    def run_fleet(n_replicas, obs=False):
        reps, handles = [], []
        for r in range(n_replicas):
            rep, handle = in_process_replica(serve_cfg(), f"b{r}")
            reps.append(rep)
            handles.append(handle)
        fcfg = FleetConfig(listen="127.0.0.1:0",
                           replicas=[h.url for h in handles],
                           probe_every=0.1, poll_every=0.05,
                           metrics_every=20)
        gwbuf = io.StringIO() if obs else None
        gw = Gateway(fcfg, handles, out=gwbuf).start()

        def settled():
            deadline = time.perf_counter() + 600
            while time.perf_counter() < deadline:
                with gw.jobs_lock:
                    if gw.jobs and all(
                            j.terminal() and j.records_final
                            for j in gw.jobs.values()):
                        return
                time.sleep(0.05)

        # warm-up: one tiny job per bucket pays each bucket's compile
        # on whichever replica the router pins it to, BEFORE the
        # clock starts — the timed stream then measures routed solve
        # throughput, not compile order (the affinity pins from the
        # warm-up are exactly what the timed jobs ride)
        for w, tim in enumerate(tims[:2]):
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": tim, "id": f"warm{w}", "seed": 900 + w,
                       "generations": 2})
        settled()
        t0 = time.perf_counter()
        for i, tim in enumerate(tims):
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": tim, "id": f"f{i}", "seed": i,
                       "generations": gens})
        settled()
        wall = time.perf_counter() - t0
        with gw.jobs_lock:
            timed = [j for j in gw.jobs.values()
                     if j.id.startswith("f")]     # warm-ups excluded
            lats = sorted(j.finished_t - j.submitted_t
                          for j in timed if j.finished_t is not None)
            records = {j.id: jsonl.strip_timing(j.records)
                       for j in timed}
            states = {j.id: j.state for j in timed}
        stats = gw.router.stats()
        route_counters = None
        if obs:
            # the routing counters as /metrics families, read back
            # through the one shared exposition parser (obs/scrape.py)
            fams = obs_scrape.parse_exposition(
                http_text(gw.url + "/metrics"))
            route_counters = {
                o: obs_scrape.scalar(fams,
                                     f"tt_fleet_route_{o}_total", 0.0)
                for o in ("hit", "warm", "miss")}
        gw.request_drain()
        gw.drained.wait(60)
        gw.close()
        for rep in reps:
            rep.stop()
        gw_records = ([json.loads(ln) for ln in
                       gwbuf.getvalue().splitlines()]
                      if obs else None)
        return (wall, lats, stats, records, states, gw_records,
                route_counters)

    wall2, lat2, stats2, recs2, states2, _, _ = run_fleet(2)
    wall2o, lat2o, stats2o, recs2o, states2o, gwrecs, route_ctr = \
        run_fleet(2, obs=True)
    wall1, lat1, stats1, recs1, states1, _, _ = run_fleet(1)

    # unrouted baseline: the same jobs (same ids, seeds, budgets,
    # serve shape) on a bare SolveService — per-job streams must match
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=10,
                                   pop_size=6, max_steps=16), out=buf)
    for i, p in enumerate(problems):
        svc.submit(p, job_id=f"f{i}", seed=i, generations=gens)
    svc.drive()
    svc.close()
    base: dict = {}
    for line in buf.getvalue().splitlines():
        rec = json.loads(line)
        kind = next(iter(rec))
        job = rec[kind].get("job") if isinstance(rec[kind], dict) \
            else None
        if job is not None:
            base.setdefault(job, []).append(rec)
    base = {j: jsonl.strip_timing(rs) for j, rs in base.items()}
    identical = all(recs2.get(j) == base.get(j)
                    and recs1.get(j) == base.get(j)
                    and recs2o.get(j) == base.get(j) for j in base)

    def pct(vals, q):
        if not vals:
            return None     # no finished job: report, don't crash
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 3)

    out = {
        "jobs": len(problems),
        "generations_per_job": gens,
        "jobs_done_2rep": sum(1 for s in states2.values()
                              if s == "done"),
        "jobs_done_1rep": sum(1 for s in states1.values()
                              if s == "done"),
        "jobs_per_min_2rep": round(len(problems) / wall2 * 60, 2),
        "jobs_per_min_1rep": round(len(problems) / wall1 * 60, 2),
        "fleet_speedup": round(wall1 / wall2, 2) if wall2 else 0.0,
        "p50_latency_s_2rep": pct(lat2, 0.5),
        "p99_latency_s_2rep": pct(lat2, 0.99),
        "p50_latency_s_1rep": pct(lat1, 0.5),
        "p99_latency_s_1rep": pct(lat1, 0.99),
        "affinity_hit_rate": stats2["affinity_hit_rate"],
        "affinity_hits": stats2["affinity_hits"],
        "warmups": stats2["warmups"],
        "records_identical": bool(identical),
        # --- gateway observatory A/B (tt-obs v5): same 2-replica
        # stream with the gateway log ON ---
        "jobs_per_min_2rep_obs": round(len(problems) / wall2o * 60, 2),
        "gateway_overhead_ms_per_job": round(
            (wall2o - wall2) / len(problems) * 1000, 2),
        "gateway_span_records": sum(1 for r in gwrecs
                                    if "spanEntry" in r),
        "gateway_route_records": sum(1 for r in gwrecs
                                     if "routeEntry" in r),
        "gateway_metrics_records": sum(1 for r in gwrecs
                                       if "metricsEntry" in r),
        "gateway_route_counters": route_ctr,
        "note": "2 in-process replicas (private registries) behind "
                "the gateway vs 1, same-seed 2-bucket 10-job stream; "
                "records_identical strips timing fields and compares "
                "every routed job's stream (obs-off AND obs-on legs) "
                "to a bare unrouted SolveService run of the same "
                "jobs. On a serial CPU box the replicas share cores, "
                "so fleet_speedup reflects scheduling overlap, not "
                "hardware scaling; gateway_overhead_ms_per_job is "
                "run-to-run noise-bounded, not a precise cost.",
    }
    if not identical:
        out["error"] = "routed record stream diverged from unrouted"
    print(f"# fleet: {out['jobs_per_min_2rep']} jobs/min @2rep vs "
          f"{out['jobs_per_min_1rep']} @1rep (speedup "
          f"{out['fleet_speedup']}), affinity "
          f"{out['affinity_hit_rate']}, p50/p99 "
          f"{out['p50_latency_s_2rep']}/{out['p99_latency_s_2rep']}s, "
          f"gateway obs {out['gateway_overhead_ms_per_job']} ms/job "
          f"({out['gateway_span_records']} spans, "
          f"{out['gateway_route_records']} routeEntries), "
          f"records identical: {identical}", file=sys.stderr)
    return out


def measure_autoscale() -> dict:
    """extra.scale leg (ISSUE 15, tt-scale): a bursty multi-bucket
    job stream against an AUTOSCALED fleet (1 replica + --scale-max 3,
    in-process spawn pool) vs the FIXED 1-replica baseline:

      jobs/min (auto vs fixed)  end-to-end completion rate at the
                                gateway for the identical stream
      p50/p99 latency           submit-to-settled per job
      scale actions             ups / downs / blocked_warmth /
                                blocked_cooldown counters + scaleEntry
                                record count off the gateway log — the
                                actuator's decision story
      zero lost jobs            every job of BOTH legs settles `done`
                                exactly once (scale-down is preempt
                                drain — lossless by construction)
      records identical         every job's stream (modulo timing)
                                bit-equal to a bare unrouted
                                SolveService AND across the two legs —
                                the scaler is a pure actuator over the
                                job streams

    In-process replicas/spawns (private registries — the CPU test
    double for worker processes); on a serial CPU box the spawned
    replicas share cores, so the jobs/min delta reflects scheduling
    overlap, not hardware scaling."""
    import io

    from timetabling_ga_tpu.fleet.gateway import Gateway
    from timetabling_ga_tpu.fleet.replicas import (
        http_json, in_process_replica)
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import FleetConfig, ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    # three shape buckets (default geometric floors): the burst keeps
    # landing fresh-bucket work that spawned capacity can absorb
    shapes = [(28, 3, 24), (52, 5, 40), (100, 8, 60)]
    problems = [random_instance(7000 + i, n_events=e, n_rooms=r,
                                n_features=4, n_students=s,
                                attend_prob=0.08)
                for i, (e, r, s) in enumerate(
                    shapes[i % 3] for i in range(12))]
    tims = [dump_tim(p) for p in problems]
    gens = 30

    def serve_cfg():
        return ServeConfig(backend="cpu", lanes=2, quantum=10,
                           pop_size=6, max_steps=16,
                           http="127.0.0.1:0")

    def leg(scaled: bool):
        rep0, h0 = in_process_replica(serve_cfg(), "a0")
        reps = [rep0]

        def spawn_fn(name):
            rep, handle = in_process_replica(serve_cfg(), name)
            reps.append(rep)
            return handle

        kw = {}
        if scaled:
            kw = dict(scale_min=1, scale_max=3,
                      scale_up_queue=3.0, scale_up_for=1.0,
                      scale_down_queue=1.0, scale_down_for=2.0,
                      scale_idle_window=2.0, scale_cooldown=1.5,
                      scale_every=0.2, scale_warm_recent=3.0)
        fcfg = FleetConfig(listen="127.0.0.1:0", replicas=[h0.url],
                           probe_every=0.1, poll_every=0.05,
                           history_every=0.2, metrics_every=0, **kw)
        gwbuf = io.StringIO()
        gw = Gateway(fcfg, [h0], out=gwbuf,
                     spawn_fn=spawn_fn if scaled else None).start()

        def settled():
            deadline = time.perf_counter() + 600
            while time.perf_counter() < deadline:
                with gw.jobs_lock:
                    timed_jobs = [j for j in gw.jobs.values()
                                  if j.id.startswith("sc")]
                    if timed_jobs and all(
                            j.terminal() and j.records_final
                            for j in timed_jobs):
                        return
                time.sleep(0.05)

        t0 = time.perf_counter()
        for i, tim in enumerate(tims):
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": tim, "id": f"sc{i}", "seed": i,
                       "generations": gens})
            time.sleep(0.05)          # a burst STREAM, not one batch
        settled()
        wall = time.perf_counter() - t0
        counters = {}
        if scaled:
            # let the idle phase retire the spawned capacity (the
            # lossless preempt-drain down) before reading the story
            deadline = time.perf_counter() + 30
            while (time.perf_counter() < deadline
                   and gw.registry.counter(
                       "fleet.scale.downs").value < 1):
                time.sleep(0.1)
            counters = {name: gw.registry.counter(
                f"fleet.scale.{name}").value
                for name in ("ups", "downs", "blocked_warmth",
                             "blocked_cooldown")}
        with gw.jobs_lock:
            timed_jobs = [j for j in gw.jobs.values()
                          if j.id.startswith("sc")]
            lats = sorted(j.finished_t - j.submitted_t
                          for j in timed_jobs
                          if j.finished_t is not None)
            records = {j.id: jsonl.strip_timing(j.records)
                       for j in timed_jobs}
            states = {j.id: j.state for j in timed_jobs}
        gw.request_drain()
        gw.drained.wait(60)
        gw.close()
        for rep in reps:
            rep.kill()
        scale_records = sum(1 for line in gwbuf.getvalue().splitlines()
                            if '"scaleEntry"' in line)
        return wall, lats, records, states, counters, scale_records

    # warm-up: compile each bucket's lane programs ONCE before either
    # leg — the islands program caches are process-global, so without
    # this the FIRST leg pays every multi-second XLA compile inside
    # its measurement and the A/B reads as compile order, not scaling
    wbuf = io.StringIO()
    warm = SolveService(ServeConfig(backend="cpu", lanes=2,
                                    quantum=10, pop_size=6,
                                    max_steps=16), out=wbuf)
    for w, p in enumerate(problems[:3]):
        warm.submit(p, job_id=f"warm{w}", seed=900 + w, generations=2)
    warm.drive()
    warm.close()

    wall_a, lat_a, recs_a, states_a, ctr, scale_recs = leg(True)
    wall_f, lat_f, recs_f, states_f, _, _ = leg(False)

    # unrouted identity baseline: the same jobs on a bare SolveService
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=10,
                                   pop_size=6, max_steps=16), out=buf)
    for i, p in enumerate(problems):
        svc.submit(p, job_id=f"sc{i}", seed=i, generations=gens)
    svc.drive()
    svc.close()
    base: dict = {}
    for line in buf.getvalue().splitlines():
        rec = json.loads(line)
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") is not None:
            base.setdefault(body["job"], []).append(rec)
    base = {j: jsonl.strip_timing(rs) for j, rs in base.items()}
    identical = all(recs_a.get(j) == base.get(j)
                    and recs_f.get(j) == base.get(j) for j in base)
    lost = sum(1 for s in list(states_a.values())
               + list(states_f.values()) if s != "done")

    def pct(vals, q):
        if not vals:
            return None
        return round(vals[min(len(vals) - 1, int(q * len(vals)))], 3)

    out = {
        "jobs": len(problems),
        "generations_per_job": gens,
        "jobs_per_min_scaled": round(len(problems) / wall_a * 60, 2),
        "jobs_per_min_fixed": round(len(problems) / wall_f * 60, 2),
        "scale_speedup": round(wall_f / wall_a, 2) if wall_a else 0.0,
        "p50_latency_s_scaled": pct(lat_a, 0.5),
        "p99_latency_s_scaled": pct(lat_a, 0.99),
        "p50_latency_s_fixed": pct(lat_f, 0.5),
        "p99_latency_s_fixed": pct(lat_f, 0.99),
        "scale_ups": ctr.get("ups"),
        "scale_downs": ctr.get("downs"),
        "scale_blocked_warmth": ctr.get("blocked_warmth"),
        "scale_blocked_cooldown": ctr.get("blocked_cooldown"),
        "scale_entries_logged": scale_recs,
        "jobs_lost": lost,
        "records_identical": bool(identical),
        "note": "12-job 3-bucket burst stream: gateway + in-process "
                "1-replica fleet with --scale-max 3 (in-proc spawn "
                "pool) vs the same fleet with the scaler off; "
                "records_identical strips timing fields and compares "
                "every job's stream in BOTH legs to a bare unrouted "
                "SolveService. Spawned replicas share this box's "
                "cores, so the jobs/min delta reflects scheduling "
                "overlap, not hardware scaling; zero lost jobs is "
                "the scale-down losslessness claim.",
    }
    errs = []
    if lost:
        errs.append(f"{lost} job(s) not done")
    if not identical:
        errs.append("scaled record stream diverged from unrouted")
    if errs:
        out["error"] = "; ".join(errs)
    print(f"# scale: {out['jobs_per_min_scaled']} jobs/min autoscaled "
          f"vs {out['jobs_per_min_fixed']} fixed "
          f"(x{out['scale_speedup']}), actions "
          f"up={out['scale_ups']} down={out['scale_downs']} "
          f"blocked_warmth={out['scale_blocked_warmth']}, "
          f"lost={lost}, records identical: {identical}",
          file=sys.stderr)
    return out


def measure_resume() -> dict:
    """extra.resume leg (ISSUE 12): kill-mid-stream failover A/B —
    replay (`--snapshot-hwm 0`, the pre-ISSUE-12 behavior) vs resume
    (snapshot shipping on, the default). Both legs run the same job
    stream through a gateway + 2 in-process replicas, kill one replica
    once a job on it has real progress, and run the stream to
    completion. Reported per leg:

      jobs/min             end-to-end completion rate at the gateway
      p50/p99 e2e          submit-to-settled per job
      wasted_gens_ratio    generations EXECUTED fleet-wide beyond the
                           submitted budgets, over the budgets — the
                           replay bill (a resumed job re-runs at most
                           one quantum; a replayed one re-runs
                           everything its dead replica had done)
      resume_hits/replays  the gateway's fleet.resume.* counters

    plus a records-identical assertion on the RESUME leg: every job's
    settled stream (prefix + continuation) must equal the same job on
    a bare unrouted SolveService, modulo timing/fault records."""
    import io

    from timetabling_ga_tpu.fleet.gateway import Gateway
    from timetabling_ga_tpu.fleet.replicas import (
        http_json, in_process_replica)
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import FleetConfig, ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    problems = [random_instance(7000 + i, n_events=28, n_rooms=3,
                                n_features=4, n_students=24,
                                attend_prob=0.08) for i in range(6)]
    tims = [dump_tim(p) for p in problems]
    gens = 80

    def serve_cfg():
        return ServeConfig(backend="cpu", lanes=2, quantum=5,
                           pop_size=6, max_steps=16,
                           http="127.0.0.1:0")

    def leg(resume: bool):
        reps, handles = [], []
        for r in range(2):
            rep, handle = in_process_replica(serve_cfg(), f"x{r}")
            reps.append(rep)
            handles.append(handle)
        fcfg = FleetConfig(
            listen="127.0.0.1:0", replicas=[h.url for h in handles],
            probe_every=0.1, poll_every=0.05, dead_after=2,
            snapshot_hwm=(FleetConfig().snapshot_hwm if resume
                          else 0))
        gw = Gateway(fcfg, handles).start()
        t0 = time.perf_counter()
        for i, tim in enumerate(tims):
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": tim, "id": f"k{i}", "seed": i,
                       "generations": gens})
        # kill a replica once one of its jobs has observable progress
        victim = None
        deadline = time.perf_counter() + 300
        while victim is None and time.perf_counter() < deadline:
            for rep in reps:
                for job in list(rep.svc.queue._jobs.values()):
                    if job.gens_done >= gens // 2:
                        victim = rep
                        break
                if victim:
                    break
            time.sleep(0.01)
        if victim is not None:
            victim.kill()
        deadline = time.perf_counter() + 600
        while time.perf_counter() < deadline:
            with gw.jobs_lock:
                if gw.jobs and all(j.terminal() and j.records_final
                                   for j in gw.jobs.values()):
                    break
            time.sleep(0.05)
        wall = time.perf_counter() - t0
        executed = sum(
            int(rep.svc.registry.counter("serve.gens").value)
            for rep in reps)
        budget = gens * len(tims)
        with gw.jobs_lock:
            jobs = list(gw.jobs.values())
            done = sum(1 for j in jobs if j.state == "done")
            lats = sorted(j.finished_t - j.submitted_t for j in jobs
                          if j.finished_t is not None)
            records = {j.id: jsonl.strip_timing(j.records)
                       for j in jobs}
        hits = int(gw.registry.counter("fleet.resume.hits").value)
        replays = int(gw.registry.counter("fleet.resume.replays")
                      .value)
        gw.close()
        for rep in reps:
            rep.kill()

        def pct(vals, q):
            return (round(vals[min(len(vals) - 1,
                                   int(q * len(vals)))], 3)
                    if vals else None)

        return {"jobs_done": done, "killed": victim is not None,
                "jobs_per_min": round(60.0 * done / wall, 1),
                "p50_s": pct(lats, 0.5), "p99_s": pct(lats, 0.99),
                "wasted_gens_ratio": round(
                    max(0, executed - budget) / budget, 4),
                "resume_hits": hits, "resume_replays": replays,
                }, records

    replay_leg, _ = leg(resume=False)
    resume_leg, resume_records = leg(resume=True)

    # records-identical assertion on the resumed streams
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=5,
                                   pop_size=6, max_steps=16), out=buf)
    for i, p in enumerate(problems):
        svc.submit(p, job_id=f"k{i}", seed=i, generations=gens)
    svc.drive()
    svc.close()
    base: dict = {}
    for line in buf.getvalue().splitlines():
        rec = json.loads(line)
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") is not None:
            base.setdefault(body["job"], []).append(rec)
    base = {j: jsonl.strip_timing(rs) for j, rs in base.items()}
    identical = all(resume_records.get(j) == base[j] for j in base)

    return {"replay": replay_leg, "resume": resume_leg,
            "records_identical": bool(identical),
            "wasted_gens_saved_ratio": round(
                replay_leg["wasted_gens_ratio"]
                - resume_leg["wasted_gens_ratio"], 4)}


def measure_edit() -> dict:
    """extra.edit leg (ISSUE 19, tt-edit): warm vs cold incremental
    re-solve A/B. One base job runs to completion (its freshest
    park-fence snapshot stands in for what the gateway caches), then
    the same small edit — one added event plus one attendance change —
    is solved twice with identical seed/budget:

      warm   snapshot present: population transplanted, anchored
             objective on (w_anchor=1)
      cold   no snapshot: demoted to a cold solve of the edited
             instance (the pre-tt-edit behavior)

    Reported per leg: wall time, time-to-feasible, generations to
    reach the BASE job's final quality, final best, edit_distance, and
    the demotion count (the warm same-bucket leg must show zero).
    Plus the w_anchor=0 identity assertion: a zero-weight cold edit's
    solver record stream must be identical to a plain solve of the
    edited instance."""
    import io

    from timetabling_ga_tpu.obs import metrics as obs_metrics
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve import JobState
    from timetabling_ga_tpu.serve import editsolve
    from timetabling_ga_tpu.serve.service import SolveService

    p = random_instance(9100, n_events=60, n_rooms=4, n_features=4,
                        n_students=40, attend_prob=0.06)
    base_gens = 200
    edit_gens = 200

    def serve_cfg():
        return ServeConfig(backend="cpu", lanes=2, quantum=5,
                           pop_size=8, max_steps=16)

    # base job to completion, keeping the freshest park-fence wire
    buf0 = io.StringIO()
    svc = SolveService(serve_cfg(), out=buf0)
    svc.submit(p, job_id="base", seed=1, generations=base_gens)
    wire = None
    while svc.state("base") not in (JobState.DONE, JobState.FAILED):
        if not svc.step():
            break
        svc.scheduler.flush_resident("ship")
        ship = svc.queue.get("base").ship
        if ship is not None:
            wire = ship.pack()
    svc.drive()
    base_best = int(svc.queue.get("base").best)
    svc.close()

    ops = [{"op": "add_event", "students": [2, 11], "features": [0]},
           {"op": "set_attendance", "event": 3, "student": 5,
            "value": 1}]
    edit_spec = {"base": {"tim": dump_tim(p)}, "base_id": "base",
                 "ops": ops}

    def leg(warm: bool, w_anchor: int = 1):
        reg = obs_metrics.REGISTRY
        dem0 = reg.counter("serve.jobs_edit_demoted").value
        buf = io.StringIO()
        svc = SolveService(serve_cfg(), out=buf)
        spec = dict(edit_spec, w_anchor=w_anchor)
        if warm:
            spec["snapshot"] = wire
        t0 = time.perf_counter()
        svc.submit(None, job_id="e", seed=2, generations=edit_gens,
                   edit=spec)
        gens_to_base = None
        t_feasible = None

        def observe():
            nonlocal gens_to_base, t_feasible
            job = svc.queue.get("e")
            if t_feasible is None and job.best < 10 ** 6:
                t_feasible = round(time.perf_counter() - t0, 3)
            if gens_to_base is None and job.best <= base_best:
                gens_to_base = int(job.gens_done)

        while svc.state("e") not in (JobState.DONE, JobState.FAILED):
            if not svc.step():
                break
            observe()
        svc.drive()
        observe()
        wall = time.perf_counter() - t0
        job = svc.queue.get("e")
        res = svc.result("e") or {}
        svc.close()
        return {"wall_s": round(wall, 3), "best": int(job.best),
                "gens": int(job.gens_done),
                "time_to_feasible_s": t_feasible,
                "gens_to_base_quality": gens_to_base,
                "edit_distance": res.get("edit_distance"),
                "demoted": int(reg.counter(
                    "serve.jobs_edit_demoted").value - dem0)}

    warm = leg(warm=True)
    cold = leg(warm=False)

    # w_anchor=0 cold leg: inert anchor machinery leaves the solver
    # stream byte-identical to a plain solve of the edited instance
    edited, _emap = editsolve.apply_ops(p, ops)

    def solver_stream(buf):
        keep = ("logEntry", "solution", "runEntry")
        out = []
        for line in buf.getvalue().splitlines():
            rec = json.loads(line)
            if next(iter(rec)) in keep:
                out.append(rec)
        return jsonl.strip_timing(out)

    buf_a = io.StringIO()
    svc_a = SolveService(serve_cfg(), out=buf_a)
    svc_a.submit(edited, job_id="z", seed=3, generations=30)
    svc_a.drive()
    svc_a.close()
    buf_b = io.StringIO()
    svc_b = SolveService(serve_cfg(), out=buf_b)
    svc_b.submit(None, job_id="z", seed=3, generations=30,
                 edit=dict(edit_spec, w_anchor=0))
    svc_b.drive()
    svc_b.close()
    identical = solver_stream(buf_a) == solver_stream(buf_b)

    gens_saved = None
    if (warm["gens_to_base_quality"] is not None
            and cold["gens_to_base_quality"] is not None):
        gens_saved = (cold["gens_to_base_quality"]
                      - warm["gens_to_base_quality"])
    return {"base_best": base_best, "base_gens": base_gens,
            "warm": warm, "cold": cold,
            "records_identical_w0": bool(identical),
            "gens_to_base_saved": gens_saved}


def measure_scrape() -> dict:
    """extra.scrape leg (ISSUE 6): the pull front's cost on a live
    serve stream.

    Same jobs, same seeds, three streams: an untimed warm-up (compiles
    the bucket programs so neither timed leg pays them), listener OFF,
    and listener ON with a 1 Hz scraper hammering /metrics + /readyz
    from a sidecar thread the whole time. Reports the overhead per
    dispatch and asserts the record streams are identical modulo
    timing — a scraper must be a pure observer (obs/http.py)."""
    import io
    import threading
    import urllib.request

    from timetabling_ga_tpu.obs import metrics as obs_metrics
    from timetabling_ga_tpu.problem import random_instance
    from timetabling_ga_tpu.runtime import jsonl
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService

    problems = [random_instance(2000 + i, n_events=80, n_rooms=8,
                                n_features=4, n_students=50,
                                attend_prob=0.06) for i in range(4)]
    gens = 60

    def run_stream(listen):
        buf = io.StringIO()
        cfg = ServeConfig(lanes=2, quantum=10, pop_size=16,
                          max_steps=32, obs=True, metrics_every=1,
                          obs_listen=listen)
        svc = SolveService(cfg, out=buf)
        stop = threading.Event()
        n_scrapes = [0]
        thr = None
        if svc.obs_server is not None:
            url = svc.obs_server.url

            def scraper():
                while not stop.is_set():
                    for ep in ("/metrics", "/readyz"):
                        try:
                            urllib.request.urlopen(
                                url + ep, timeout=2).read()
                        except Exception:
                            pass          # 503 /readyz is an answer
                    n_scrapes[0] += 1
                    stop.wait(1.0)

            thr = threading.Thread(target=scraper, daemon=True)
            thr.start()
        d0 = obs_metrics.REGISTRY.counter("serve.dispatches").value
        t0 = time.perf_counter()
        for i, p in enumerate(problems):
            svc.submit(p, generations=gens, seed=i)
        svc.drive()
        wall = time.perf_counter() - t0
        disp = (obs_metrics.REGISTRY.counter("serve.dispatches").value
                - d0)
        stop.set()
        if thr is not None:
            thr.join(timeout=5)
        svc.close()
        recs = [json.loads(x) for x in buf.getvalue().splitlines()]
        return wall, int(disp), jsonl.strip_timing(recs), n_scrapes[0]

    run_stream(None)                              # warm-up (compiles)
    off_wall, off_disp, off_recs, _ = run_stream(None)
    on_wall, on_disp, on_recs, scrapes = run_stream("127.0.0.1:0")
    out = {
        "jobs": len(problems), "generations_per_job": gens,
        "dispatches": on_disp,
        "wall_s_listener_off": round(off_wall, 3),
        "wall_s_listener_on": round(on_wall, 3),
        "scrapes": scrapes,
        "scrape_overhead_ms_per_dispatch": round(
            (on_wall - off_wall) / max(1, on_disp) * 1e3, 3),
        "records_identical_modulo_timing": off_recs == on_recs,
    }
    print(f"# scrape A/B ({len(problems)} jobs, {on_disp} dispatches): "
          f"wall {off_wall:.3f}s off vs {on_wall:.3f}s on with "
          f"{scrapes} 1 Hz scrape rounds "
          f"({out['scrape_overhead_ms_per_dispatch']} ms/dispatch); "
          f"records identical={out['records_identical_modulo_timing']}",
          file=sys.stderr)
    return out


def measure_obs(problem, pop: int = 256, gens: int = 600) -> dict:
    """extra.obs leg (ISSUE 5): span+metrics overhead and the
    telemetry-leaf reduction, same-session A/B.

    Three legs of the SAME run (same seed, same programs): obs off,
    obs on (spans + per-dispatch metricsEntry snapshots riding the
    writer), and obs on with --trace-mode deltas (the compressed
    telemetry leaf). `records_identical_modulo_timing` asserts all
    three emit the same protocol records — observability must never
    change what a run does. The leaf sizes are reported per island per
    dispatch: deltas wins once the fused generation count clears
    ~1.5x TRACE_DELTAS_CAP (below that the packed event block is the
    bigger buffer — the point of the mode is LONG fused dispatches)."""
    import dataclasses
    import io
    import json as _json
    import tempfile

    from timetabling_ga_tpu.parallel import islands as isl
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    try:
        # long fused dispatches (4 x 50 gens) so the leaf reduction is
        # in its design regime; 3 dispatches keep the leg cheap
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=50,
                         epochs_per_dispatch=4, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True, metrics_every=1)
        engine.precompile(base)
        engine.precompile(dataclasses.replace(base, trace_mode="deltas"))

        def leg(obs, trace_mode="full"):
            cfg = dataclasses.replace(base, obs=obs,
                                      trace_mode=trace_mode)
            buf = io.StringIO()
            t0 = time.perf_counter()
            best = engine.run(cfg, out=buf)
            wall = time.perf_counter() - t0
            lines = [_json.loads(x) for x in buf.getvalue().splitlines()]
            loop = [x["phase"] for x in lines if "phase" in x
                    and x["phase"]["name"] == "gen-loop"][0]
            n_spans = sum(1 for x in lines if "spanEntry" in x)
            return {"best": best, "wall": wall,
                    "loop_s": loop["seconds"],
                    "dispatches": loop["dispatches"],
                    "spans": n_spans,
                    "recs": jsonl.strip_timing(lines)}

        off = leg(False)
        on = leg(True)
        deltas = leg(True, trace_mode="deltas")
    finally:
        os.unlink(tim)
    gpd = 4 * 50
    leaf_full = gpd * 2
    leaf_deltas = isl.trace_leaf_width(gpd, "deltas")
    out = {
        "pop": pop, "gens": gens, "dispatches": off["dispatches"],
        "loop_s_obs_off": round(off["loop_s"], 3),
        "loop_s_obs_on": round(on["loop_s"], 3),
        "loop_s_obs_deltas": round(deltas["loop_s"], 3),
        "obs_overhead_ms_per_dispatch": round(
            (on["loop_s"] - off["loop_s"]) / max(1, on["dispatches"])
            * 1e3, 3),
        "span_records": on["spans"],
        "trace_leaf_ints_per_island_full": leaf_full,
        "trace_leaf_ints_per_island_deltas": leaf_deltas,
        "trace_leaf_shrink": round(leaf_full / leaf_deltas, 2),
        "records_identical_modulo_timing":
            off["recs"] == on["recs"] == deltas["recs"],
    }
    print(f"# obs A/B (pop {pop}, {off['dispatches']} dispatches): "
          f"loop {off['loop_s']:.3f}s off vs {on['loop_s']:.3f}s on "
          f"({out['obs_overhead_ms_per_dispatch']} ms/dispatch, "
          f"{on['spans']} spans) vs {deltas['loop_s']:.3f}s deltas; "
          f"leaf {leaf_full} -> {leaf_deltas} ints/island "
          f"(x{out['trace_leaf_shrink']}); records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def measure_prof(problem, pop: int = 256, gens: int = 600) -> dict:
    """extra.prof leg (ISSUE 20, tt-prof): phase-scope + capture cost
    and the attribution itself, same-seed A/B.

    Two legs of the SAME run (same seed, same programs, obs on both):
    profiler capture OFF vs ON (jax.profiler tracing the whole gen
    loop, scopes active on both legs — scopes are trace-time metadata,
    so they cost nothing at dispatch). `strip_timing` asserts the
    record streams bit-identical: profiling must never change what a
    run computes. The ON leg's capture then runs through the tt-prof
    attribution (obs/prof.py): reported are the attributed
    rooms/sweep/fitness fractions and the honest unattributed share —
    the measured answer to 'where do the device-seconds actually go'
    (ROADMAP item 4 wants the attack order, not a guess)."""
    import dataclasses
    import io
    import json as _json
    import shutil
    import tempfile

    import jax

    from timetabling_ga_tpu.obs import prof as obs_prof
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    capture_dir = tempfile.mkdtemp(prefix="tt-prof-bench-")
    try:
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=50,
                         epochs_per_dispatch=4, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True, obs=True, metrics_every=1)
        # compiles run note_executable (obs/cost.py) — the sidecar join
        # table is harvested HERE, before any capture exists
        engine.precompile(base)

        def leg(capture):
            buf = io.StringIO()
            if capture:
                jax.profiler.start_trace(capture_dir)
            try:
                best = engine.run(base, out=buf)
            finally:
                if capture:
                    jax.profiler.stop_trace()
            lines = [_json.loads(x)
                     for x in buf.getvalue().splitlines()]
            loop = [x["phase"] for x in lines if "phase" in x
                    and x["phase"]["name"] == "gen-loop"][0]
            return {"best": best, "loop_s": loop["seconds"],
                    "dispatches": loop["dispatches"],
                    "recs": jsonl.strip_timing(lines)}

        off = leg(False)
        on = leg(True)
        obs_prof.write_scope_map(capture_dir)
        attr = obs_prof.attribute(capture_dir)
    finally:
        os.unlink(tim)
        shutil.rmtree(capture_dir, ignore_errors=True)

    phases = attr["phases"]

    def frac(name):
        return round(phases.get(name, {}).get("frac", 0.0), 4)

    out = {
        "pop": pop, "gens": gens, "dispatches": off["dispatches"],
        "loop_s_capture_off": round(off["loop_s"], 3),
        "loop_s_capture_on": round(on["loop_s"], 3),
        "prof_overhead_ms_per_dispatch": round(
            (on["loop_s"] - off["loop_s"]) / max(1, on["dispatches"])
            * 1e3, 3),
        "device_s_attributed": round(
            attr["total_s"] - attr["unattributed_s"], 4),
        "frac_rooms": frac("rooms"),
        "frac_sweep": frac("sweep"),
        "frac_fitness": frac("fitness"),
        "unattributed_frac": round(attr["unattributed_frac"], 4),
        "records_identical_modulo_timing": off["recs"] == on["recs"],
    }
    print(f"# prof A/B (pop {pop}, {off['dispatches']} dispatches): "
          f"loop {off['loop_s']:.3f}s off vs {on['loop_s']:.3f}s "
          f"capture on ({out['prof_overhead_ms_per_dispatch']} "
          f"ms/dispatch); attributed rooms {out['frac_rooms']:.1%} "
          f"sweep {out['frac_sweep']:.1%} fitness "
          f"{out['frac_fitness']:.1%}, unattributed "
          f"{out['unattributed_frac']:.1%}; records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def measure_flight(problem, pop: int = 256, gens: int = 600) -> dict:
    """extra.flight leg (ISSUE 13): the flight recorder + history
    sampler's cost and its black-box output, same-seed A/B.

    Two legs of the SAME run (same seed, same programs, obs on both so
    the span/metrics machinery — priced by extra.obs — cancels): the
    tt-flight pair OFF vs ON (`--incident-dir` + a fast
    `--history-every`), with an identical injected transient on both
    legs so the ON leg's recorder has a real trigger to dump on.
    Reported: overhead ms/dispatch, the span ring's byte high-water,
    the bundle time-to-dump (trigger -> bundle on disk, the
    flight.dump_seconds histogram), bundle count — and the
    records-identical assertion: the recorder is a pure observer, the
    JSONL stream must not change with it on."""
    import dataclasses
    import io
    import json as _json
    import shutil
    import tempfile

    from timetabling_ga_tpu.obs.metrics import REGISTRY
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    incident_dir = tempfile.mkdtemp(prefix="tt-flight-bench-")
    try:
        # the same transient on BOTH legs: the recovery work is in
        # both measurements, so the delta isolates the recorder; the
        # faultEntry it emits is the ON leg's dump trigger (and
        # strip_timing drops it, so the identity assertion holds)
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=50,
                         epochs_per_dispatch=4, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True, obs=True, metrics_every=1,
                         faults="dispatch:2:unavailable")
        engine.precompile(base)

        def leg(flight):
            cfg = dataclasses.replace(
                base,
                incident_dir=incident_dir if flight else None,
                incident_min_interval=0.0,
                history_every=0.05 if flight else 0.0)
            buf = io.StringIO()
            best = engine.run(cfg, out=buf)
            lines = [_json.loads(x)
                     for x in buf.getvalue().splitlines()]
            loop = [x["phase"] for x in lines if "phase" in x
                    and x["phase"]["name"] == "gen-loop"][0]
            return {"best": best, "loop_s": loop["seconds"],
                    "dispatches": loop["dispatches"],
                    "recs": jsonl.strip_timing(lines)}

        off = leg(False)
        on = leg(True)
        bundles = sorted(p for p in os.listdir(incident_dir)
                         if p.startswith("incident-"))
        dump_h = REGISTRY.histogram("flight.dump_seconds").summary()
        ring_hw = REGISTRY.gauge("flight.span_ring_bytes_hw").value
    finally:
        os.unlink(tim)
        shutil.rmtree(incident_dir, ignore_errors=True)
    out = {
        "pop": pop, "gens": gens, "dispatches": off["dispatches"],
        "loop_s_flight_off": round(off["loop_s"], 3),
        "loop_s_flight_on": round(on["loop_s"], 3),
        "flight_overhead_ms_per_dispatch": round(
            (on["loop_s"] - off["loop_s"]) / max(1, on["dispatches"])
            * 1e3, 3),
        "bundles_written": len(bundles),
        "span_ring_bytes_hw": int(ring_hw if ring_hw == ring_hw
                                  else 0),
        "dump_p50_s": dump_h.get("p50"),
        "dump_max_s": dump_h.get("max"),
        "records_identical_modulo_timing": off["recs"] == on["recs"],
    }
    print(f"# flight A/B (pop {pop}, {off['dispatches']} dispatches): "
          f"loop {off['loop_s']:.3f}s off vs {on['loop_s']:.3f}s on "
          f"({out['flight_overhead_ms_per_dispatch']} ms/dispatch); "
          f"{out['bundles_written']} bundle(s), time-to-dump p50 "
          f"{out['dump_p50_s']}s, span ring hw "
          f"{out['span_ring_bytes_hw']}B; records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def measure_quality(problem, pop: int = 256, gens: int = 600) -> dict:
    """extra.quality leg (ISSUE 9): the search-quality observatory's
    overhead and its telemetry, same-session A/B.

    Two legs of the SAME run (same seed, same shapes): quality off vs
    quality on with --obs (operator counters in every generation, the
    migration-gain reduction on every exchange, end-of-dispatch
    diversity moments + Hamming sample, qualityEntry records).
    `records_identical_modulo_timing` asserts the observatory never
    changes what the run does; the reported hit rates / diversity are
    the numbers ROADMAP item 5's strategy races explain wins with."""
    import dataclasses
    import io
    import json as _json
    import tempfile

    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine, jsonl
    from timetabling_ga_tpu.runtime.config import RunConfig

    with tempfile.NamedTemporaryFile("w", suffix=".tim",
                                     delete=False) as f:
        f.write(dump_tim(problem))
        tim = f.name
    # pin the dispatch schedule: DISPATCH_CAP_S sizes dynamic chunks
    # from MEASURED sec/gen, and the off leg's measurements feed the on
    # leg's sizing (shared _SPG_CACHE) — on a loaded host the two legs
    # can then take different chunkings, hence different fold_in
    # schedules, and the records-identical assertion fails for timing
    # reasons, not observatory ones (observed on the CPU validation
    # box). An effectively-infinite cap makes both legs run the same
    # generation-budget-sized static dispatches + one dynamic tail.
    cap, engine.DISPATCH_CAP_S = engine.DISPATCH_CAP_S, 1e9
    try:
        base = RunConfig(input=tim, seed=1234, pop_size=pop, islands=1,
                         generations=gens, migration_period=50,
                         epochs_per_dispatch=4, ls_mode="sweep",
                         ls_sweeps=1, init_sweeps=0,
                         time_limit=100000.0, auto_tune=False,
                         trace=True, metrics_every=1)
        engine.precompile(base)
        engine.precompile(dataclasses.replace(base, quality=True))

        def leg(quality):
            # obs=True on BOTH legs: the A/B must isolate the QUALITY
            # block's cost, not re-measure the span/metrics machinery
            # measure_obs already prices (strip_timing drops the obs
            # records, so the identity assertion is unaffected)
            cfg = dataclasses.replace(base, quality=quality, obs=True)
            buf = io.StringIO()
            best = engine.run(cfg, out=buf)
            lines = [_json.loads(x) for x in buf.getvalue().splitlines()]
            loop = [x["phase"] for x in lines if "phase" in x
                    and x["phase"]["name"] == "gen-loop"][0]
            return {"best": best, "loop_s": loop["seconds"],
                    "dispatches": loop["dispatches"],
                    "quality": [x["qualityEntry"] for x in lines
                                if "qualityEntry" in x],
                    "recs": jsonl.strip_timing(lines)}

        off = leg(False)
        on = leg(True)
    finally:
        engine.DISPATCH_CAP_S = cap
        os.unlink(tim)
    from timetabling_ga_tpu.obs.quality import entry_win_rate
    qe = on["quality"][-1] if on["quality"] else {}

    def rate(w, a):
        # shared summer (obs/quality.py owns the key names): per-
        # dispatch deltas summed across the run; None = never attempted
        return entry_win_rate(on["quality"], w, a)

    out = {
        "pop": pop, "gens": gens, "dispatches": off["dispatches"],
        "loop_s_quality_off": round(off["loop_s"], 3),
        "loop_s_quality_on": round(on["loop_s"], 3),
        "quality_overhead_ms_per_dispatch": round(
            (on["loop_s"] - off["loop_s"]) / max(1, on["dispatches"])
            * 1e3, 3),
        "quality_entries": len(on["quality"]),
        "final_hamming": qe.get("quality.diversity.hamming"),
        "crossover_win_rate": rate("quality.ops.crossover_wins",
                                   "quality.ops.crossover_attempts"),
        "mutation_win_rate": rate("quality.ops.mutation_wins",
                                  "quality.ops.mutation_attempts"),
        "records_identical_modulo_timing": off["recs"] == on["recs"],
    }
    print(f"# quality A/B (pop {pop}, {off['dispatches']} dispatches): "
          f"loop {off['loop_s']:.3f}s off vs {on['loop_s']:.3f}s on "
          f"({out['quality_overhead_ms_per_dispatch']} ms/dispatch, "
          f"{out['quality_entries']} entries); final hamming "
          f"{out['final_hamming']}, xo win {out['crossover_win_rate']}, "
          f"mut win {out['mutation_win_rate']}; records identical="
          f"{out['records_identical_modulo_timing']}", file=sys.stderr)
    return out


def main(argv=None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    # fault injection for the bench harness itself (carried ROADMAP
    # item): `bench.py --faults site:nth:action,...` re-installs the
    # plan BEFORE EVERY LEG (install resets the per-site counters, so
    # each leg sees deterministic invocation indices regardless of
    # which legs ran before it); the per-leg recoveries /
    # faults_injected deltas below then show exactly which legs
    # absorbed an injected sick window inside their measurement
    faults_spec = None
    if "--faults" in args:
        i = args.index("--faults")
        if i + 1 >= len(args):
            raise SystemExit("bench.py --faults needs a plan "
                             "(runtime/faults.py site:nth:action)")
        faults_spec = args[i + 1]
        from timetabling_ga_tpu.runtime.faults import FaultPlan
        FaultPlan.parse(faults_spec)       # fail fast on a typo
    problem = _instance()
    # retry the headline through device sick windows (shared policy,
    # timetabling_ga_tpu/runtime/retry.py) instead of zeroing the round
    from timetabling_ga_tpu.runtime.retry import retry_transient
    tpu, tpu_attempts = retry_transient(measure_tpu_evals, problem)
    cpu = measure_cpu_native(problem)
    vs_baseline = tpu / cpu if cpu > 0 else 0.0

    extra = {"headline_attempts": tpu_attempts}
    for name, fn in (
            ("generation_scan", lambda: measure_generation(problem, "scan")),
            ("generation_parallel",
             lambda: measure_generation(problem, "parallel")),
            ("generation_sweep_128",
             lambda: measure_generation_sweep(problem, 128)),
            ("generation_sweep_1024",
             lambda: measure_generation_sweep(problem, 1024)),
            ("generation_sweep_tuned_comp",
             lambda: measure_generation_sweep_tuned(problem, "comp")),
            ("generation_sweep_tuned_small",
             lambda: measure_generation_sweep_tuned(
                 _small_instance(), "small")),
            ("generation_nsga2",
             lambda: measure_generation_nsga(problem)),
            ("lahc_chain", lambda: measure_lahc_chain(problem)),
            ("kernel_cost",
             lambda: measure_kernel_cost(problem, tpu)),
            ("pipeline", lambda: measure_pipeline(problem)),
            ("accord", lambda: measure_accord(problem)),
            ("obs", lambda: measure_obs(problem)),
            ("prof", lambda: measure_prof(problem)),
            ("quality", lambda: measure_quality(problem)),
            ("flight", lambda: measure_flight(problem)),
            ("serve", measure_serve),
            ("serve_mesh", measure_serve_mesh),
            ("usage", measure_usage),
            ("soak", measure_soak),
            ("fleet", measure_fleet),
            ("scale", measure_autoscale),
            ("resume", measure_resume),
            ("edit", measure_edit),
            ("scrape", measure_scrape),
            ("scale_2000ev", measure_scale),
            ("ls_shootout", lambda: measure_ls_shootout(problem)),
            ("ls_shootout_feasible",
             lambda: measure_ls_shootout_feasible(problem))):
        # every leg retries through transient tunnel windows (the
        # BENCH_r05 scale_2000ev 'response body closed' failure class)
        # instead of poisoning the round; attempts land in the leg JSON.
        # Engine-level recoveries and triggered fault injections are
        # recorded as per-leg DELTAS: a perf number that silently
        # absorbed a sick window (the supervisor replayed work inside
        # the measurement) must be visible in the trajectory.
        from timetabling_ga_tpu.runtime.engine import run_counters
        try:
            if faults_spec:
                from timetabling_ga_tpu.runtime import faults as _f
                _f.install(faults_spec)
            before = run_counters()
            result, attempts = retry_transient(fn, attempts=3,
                                               wait_s=60.0)
            after = run_counters()
            if isinstance(result, dict):
                result["attempts"] = attempts
                result["recoveries"] = (after["recoveries"]
                                        - before["recoveries"])
                result["faults_injected"] = (after["faults_injected"]
                                             - before["faults_injected"])
            extra[name] = result
        except Exception as e:  # pragma: no cover - defensive
            print(f"# {name} failed: {e}", file=sys.stderr)
            extra[name] = {"error": str(e)[:200],
                           "attempts": getattr(e, "tt_attempts", 1)}
    if faults_spec:
        from timetabling_ga_tpu.runtime import faults as _f
        _f.install(None)
        extra["faults_spec"] = faults_spec
    extra["cpu_native_evals_per_sec"] = round(cpu, 1)
    extra["cpu_threads"] = os.cpu_count() or 1
    # whole-round robustness totals (per-leg deltas above attribute them)
    from timetabling_ga_tpu.runtime.engine import run_counters
    totals = run_counters()
    extra["recoveries_total"] = totals["recoveries"]
    extra["faults_injected_total"] = totals["faults_injected"]
    # honesty note (VERDICT round-2 weak 5): the denominator runs on
    # THIS host's cores; the north star names a 32-core box. Scale
    # linearly for an estimate vs that target.
    extra["vs_baseline_note"] = (
        f"vs_baseline is measured against the native C++ evaluator at "
        f"{os.cpu_count() or 1} host core(s) — this box's hardware "
        f"limit; against the north star's 32-core reference it "
        f"extrapolates linearly to vs_baseline*{os.cpu_count() or 1}/32")

    print(json.dumps({
        "metric": "fitness_evals_per_sec_per_chip",
        "value": round(tpu, 1),
        "unit": "evals/s",
        "vs_baseline": round(vs_baseline, 2),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()
