// Native host runtime: .tim loader, fitness evaluator, and a complete
// single-process memetic GA for the CPU backend.
//
// This is the C++ half of the framework (SURVEY section 7: "C++ host
// retained... a pure-C++ single-process evaluation path so --backend=cpu
// works without Python"). It is a clean-room implementation of the
// *semantics* documented in SURVEY.md against the reference
// (Problem.cpp:3-96 loader; Solution.cpp:63-170 fitness;
// Solution.cpp:357-469 moves; Solution.cpp:772-833 room assignment with
// greedy fallback; ga.cpp:113-145 selection; Solution.cpp:893-910
// crossover; ga.cpp:580-585 replacement) — not a translation of the
// reference's code.
//
// Build (see native/Makefile):
//   libtimetabling_native.so  C ABI for ctypes (evaluation + GA)
//   tt_cpu                    standalone CLI emitting the JSONL protocol
//
// Parallelism: OpenMP over the population inside evaluation and breeding
// (the reference's intra-island axis, ga.cpp:488-588, without its shared
// RNG and unlocked-read races: each individual owns an RNG stream).

#include <algorithm>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <functional>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace tt {

// ---------------------------------------------------------------- RNG
// SplitMix64: tiny, seedable, per-individual streams. (The reference
// shares one Park-Miller LCG across all threads unsynchronized,
// Random.cc:27-37 + ga.cpp:47 — a race we must not reproduce.)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed ^ 0x9e3779b97f4a7c15ULL) {}
  uint64_t next_u64() {
    s += 0x9e3779b97f4a7c15ULL;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // unbiased-enough for GA purposes
  int next_int(int n) { return (int)(next_u64() % (uint64_t)n); }
  double next_double() { return (next_u64() >> 11) * (1.0 / 9007199254740992.0); }
};

// ------------------------------------------------------------- Problem
struct Problem {
  int E = 0, R = 0, F = 0, S = 0;
  int days = 5, spd = 9;              // timeslot grid (45 = 5 x 9)
  std::vector<int> room_size;         // (R)
  std::vector<int8_t> attends;        // (S, E)
  std::vector<int8_t> room_features;  // (R, F)
  std::vector<int8_t> event_features; // (E, F)
  // derived (Problem.cpp:34-95 semantics)
  std::vector<int> student_count;     // (E)
  std::vector<int8_t> conflict;       // (E, E)
  std::vector<int8_t> possible;       // (E, R)
  std::vector<std::vector<int>> suitable; // per event: suitable room list
  int n_slots() const { return days * spd; }

  void derive() {
    student_count.assign(E, 0);
    for (int s = 0; s < S; ++s)
      for (int e = 0; e < E; ++e)
        if (attends[(size_t)s * E + e]) student_count[e]++;

    conflict.assign((size_t)E * E, 0);
    for (int s = 0; s < S; ++s)
      for (int i = 0; i < E; ++i)
        if (attends[(size_t)s * E + i])
          for (int j = 0; j < E; ++j)
            if (attends[(size_t)s * E + j]) conflict[(size_t)i * E + j] = 1;

    possible.assign((size_t)E * R, 0);
    suitable.assign(E, {});
    for (int e = 0; e < E; ++e)
      for (int r = 0; r < R; ++r) {
        if (room_size[r] < student_count[e]) continue;
        bool ok = true;
        for (int f = 0; f < F && ok; ++f)
          if (event_features[(size_t)e * F + f] &&
              !room_features[(size_t)r * F + f]) ok = false;
        if (ok) {
          possible[(size_t)e * R + r] = 1;
          suitable[e].push_back(r);
        }
      }
  }
};

static bool load_tim(const char *path, Problem &p) {
  FILE *fh = std::fopen(path, "r");
  if (!fh) return false;
  auto rd = [&](int &out) { return std::fscanf(fh, "%d", &out) == 1; };
  if (!rd(p.E) || !rd(p.R) || !rd(p.F) || !rd(p.S)) { std::fclose(fh); return false; }
  p.room_size.resize(p.R);
  for (int r = 0; r < p.R; ++r) if (!rd(p.room_size[r])) { std::fclose(fh); return false; }
  auto rd8 = [&](std::vector<int8_t> &v, size_t n) {
    v.resize(n);
    for (size_t i = 0; i < n; ++i) {
      int x; if (std::fscanf(fh, "%d", &x) != 1) return false;
      v[i] = (int8_t)x;
    }
    return true;
  };
  bool ok = rd8(p.attends, (size_t)p.S * p.E) &&
            rd8(p.room_features, (size_t)p.R * p.F) &&
            rd8(p.event_features, (size_t)p.E * p.F);
  std::fclose(fh);
  if (ok) p.derive();
  return ok;
}

// ------------------------------------------------------------- fitness
// Exact count semantics of Solution::computeHcv / computeScv
// (Solution.cpp:86-160); see the Python oracle for the same spec.
static int compute_hcv(const Problem &p, const int *slots, const int *rooms) {
  int hcv = 0;
  for (int i = 0; i < p.E; ++i) {
    for (int j = i + 1; j < p.E; ++j) {
      if (slots[i] == slots[j]) {
        if (rooms[i] == rooms[j]) hcv++;
        if (p.conflict[(size_t)i * p.E + j]) hcv++;
      }
    }
    if (!p.possible[(size_t)i * p.R + rooms[i]]) hcv++;
  }
  return hcv;
}

static int compute_scv(const Problem &p, const int *slots,
                       std::vector<uint8_t> &att_scratch) {
  const int T = p.n_slots();
  int scv = 0;
  for (int e = 0; e < p.E; ++e)
    if (slots[e] % p.spd == p.spd - 1) scv += p.student_count[e];

  att_scratch.assign((size_t)p.S * T, 0);
  for (int e = 0; e < p.E; ++e) {
    const int t = slots[e];
    for (int s = 0; s < p.S; ++s)
      if (p.attends[(size_t)s * p.E + e]) att_scratch[(size_t)s * T + t] = 1;
  }
  for (int s = 0; s < p.S; ++s) {
    const uint8_t *row = &att_scratch[(size_t)s * T];
    for (int d = 0; d < p.days; ++d) {
      int consec = 0, cnt = 0;
      for (int k = 0; k < p.spd; ++k) {
        if (row[d * p.spd + k]) {
          cnt++; consec++;
          if (consec > 2) scv++;
        } else consec = 0;
      }
      if (cnt == 1) scv++;
    }
  }
  return scv;
}

static long long penalty_of(int hcv, int scv) {
  return hcv == 0 ? (long long)scv : 1000000LL + hcv;  // Solution.cpp:162-170
}

// ------------------------------------------------------ room assignment
// Greedy most-constrained-first matching; same policy as the JAX kernel
// (ops/rooms.py) and the reference's unmatched fallback
// (Solution.cpp:814-830): free suitable best-fit, else least-busy
// suitable, else least-busy any.
// Stateless w.r.t. assignment: `assign_all` keeps its occupancy grid on
// the stack so one Matcher is safely shared by all OpenMP threads.
struct Matcher {
  const Problem &p;
  std::vector<int> order;        // events by ascending #suitable
  std::vector<int> cap_rank;     // rooms by ascending capacity
  explicit Matcher(const Problem &pp) : p(pp) {
    order.resize(p.E);
    for (int e = 0; e < p.E; ++e) order[e] = e;
    std::stable_sort(order.begin(), order.end(), [&](int a, int b) {
      return p.suitable[a].size() < p.suitable[b].size();
    });
    std::vector<int> by_cap(p.R);
    for (int r = 0; r < p.R; ++r) by_cap[r] = r;
    std::stable_sort(by_cap.begin(), by_cap.end(), [&](int a, int b) {
      return p.room_size[a] < p.room_size[b];
    });
    cap_rank.assign(p.R, 0);
    for (int i = 0; i < p.R; ++i) cap_rank[by_cap[i]] = i;
  }

  // Marginal-hcv-cost key, in lockstep with ops/rooms.py::_room_key:
  // (occupancy + unsuitable) first, prefer suitable on ties, then
  // best-fit capacity. Bounds E,R < 4096 enforced at tt_problem_create.
  int choose(const int *occ_row, int e) const {
    long best_key = LONG_MAX;
    int best_r = 0;
    for (int r = 0; r < p.R; ++r) {
      const long unsuit = p.possible[(size_t)e * p.R + r] ? 0L : 1L;
      long key = ((long)occ_row[r] + unsuit) * (1L << 13) +
                 unsuit * (1L << 12) + cap_rank[r];
      if (key < best_key) { best_key = key; best_r = r; }
    }
    return best_r;
  }

  void assign_all(const int *slots, int *rooms) const {
    std::vector<int> occ((size_t)p.n_slots() * p.R, 0);
    for (int k = 0; k < p.E; ++k) {
      const int e = order[k], t = slots[e];
      int *row = &occ[(size_t)t * p.R];
      const int r = choose(row, e);
      rooms[e] = r;
      row[r]++;
    }
  }

  // re-room one moved event given current rooms of all others
  int insert(const int *slots, const int *rooms, int e, int new_t) const {
    std::vector<int> row(p.R, 0);
    for (int j = 0; j < p.E; ++j)
      if (j != e && slots[j] == new_t) row[rooms[j]]++;
    return choose(row.data(), e);
  }
};

// --------------------------------------------- exact per-slot matching
// The reference's PRIMARY room-assignment path is an exact per-timeslot
// maximum matching (Solution::maxMatching, Solution.cpp:836-849, via
// networkFlow's priority-first search). Clean-room equivalent: Kuhn's
// augmenting-path algorithm per slot (same optimum, simpler machinery),
// with the reference's fallback for unmatched events (least-busy
// suitable room, Solution.cpp:814-830). Used by the reference-faithful
// baseline GA below; the framework's own matcher is the cost-greedy
// Matcher above.
struct ExactMatcher {
  const Problem &p;
  explicit ExactMatcher(const Problem &pp) : p(pp) {}

  // match the events of one slot to distinct suitable rooms; unmatched
  // events fall back to the least-busy suitable (else least-busy) room
  void assign_slot(const std::vector<int> &evs, int *rooms) const {
    const int R = p.R;
    std::vector<int> match_r(R, -1);                 // room -> event idx
    std::vector<uint8_t> seen(R);
    std::function<bool(int)> aug = [&](int i) {
      for (int r : p.suitable[evs[i]]) {
        if (seen[r]) continue;
        seen[r] = 1;
        if (match_r[r] < 0 || aug(match_r[r])) { match_r[r] = i; return true; }
      }
      return false;
    };
    std::vector<int> assigned(evs.size(), -1);
    for (size_t i = 0; i < evs.size(); ++i) {
      std::fill(seen.begin(), seen.end(), 0);
      aug((int)i);
    }
    for (int r = 0; r < R; ++r)
      if (match_r[r] >= 0) assigned[match_r[r]] = r;
    // fallback: least-busy suitable room, else least-busy any
    std::vector<int> busy(R, 0);
    for (size_t i = 0; i < evs.size(); ++i)
      if (assigned[i] >= 0) busy[assigned[i]]++;
    for (size_t i = 0; i < evs.size(); ++i) {
      if (assigned[i] >= 0) { rooms[evs[i]] = assigned[i]; continue; }
      const auto &suit = p.suitable[evs[i]];
      int best = -1;
      for (int r : suit)
        if (best < 0 || busy[r] < busy[best]) best = r;
      if (best < 0)
        for (int r = 0; r < R; ++r)
          if (best < 0 || busy[r] < busy[best]) best = r;
      assigned[i] = best;
      busy[best]++;
      rooms[evs[i]] = best;
    }
  }

  void assign_all(const int *slots, int *rooms) const {
    const int T = p.n_slots();
    std::vector<std::vector<int>> by_slot(T);
    for (int e = 0; e < p.E; ++e) by_slot[slots[e]].push_back(e);
    for (int t = 0; t < T; ++t)
      if (!by_slot[t].empty()) assign_slot(by_slot[t], rooms);
  }
};

// ---------------------------------------------------------------- moves
// Move1/2/3 semantics (Solution.cpp:357-439) with greedy insert
// re-rooming, matching ops/moves.py.
struct MoveCtx {
  const Problem &p;
  const Matcher &m;
  Rng &rng;
  double p1, p2, p3;
};

static void random_move(const MoveCtx &c, std::vector<int> &slots,
                        std::vector<int> &rooms) {
  const int E = c.p.E, T = c.p.n_slots();
  double tot = c.p1 + c.p2 + c.p3;
  double u = c.rng.next_double() * (tot > 0 ? tot : 1.0);
  int e1 = c.rng.next_int(E), e2, e3;
  do { e2 = c.rng.next_int(E); } while (e2 == e1 && E > 1);
  do { e3 = c.rng.next_int(E); } while ((e3 == e1 || e3 == e2) && E > 2);

  if (u < c.p1 || tot <= 0) {                       // Move1
    const int t = c.rng.next_int(T);
    slots[e1] = t;
    rooms[e1] = c.m.insert(slots.data(), rooms.data(), e1, t);
  } else if (u < c.p1 + c.p2) {                     // Move2: swap slots
    std::swap(slots[e1], slots[e2]);
    rooms[e1] = c.m.insert(slots.data(), rooms.data(), e1, slots[e1]);
    rooms[e2] = c.m.insert(slots.data(), rooms.data(), e2, slots[e2]);
  } else {                                          // Move3: 3-cycle
    const int t1 = slots[e1];
    slots[e1] = slots[e2]; slots[e2] = slots[e3]; slots[e3] = t1;
    rooms[e1] = c.m.insert(slots.data(), rooms.data(), e1, slots[e1]);
    rooms[e2] = c.m.insert(slots.data(), rooms.data(), e2, slots[e2]);
    rooms[e3] = c.m.insert(slots.data(), rooms.data(), e3, slots[e3]);
  }
}

// ------------------------------------------------------------------- GA
struct Individual {
  std::vector<int> slots, rooms;
  int hcv = 0, scv = 0;
  long long pen = 0;
};

struct GaParams {
  int pop_size = 10;          // ga.cpp:64
  int generations = 2001;     // ga.cpp:510
  int tournament_k = 5;       // ga.cpp:129-145
  double p_crossover = 0.8;   // ga.cpp:562
  double p_mutation = 0.5;    // ga.cpp:569
  double p1 = 1.0, p2 = 1.0, p3 = 0.0;
  int ls_rounds = 25;         // maxSteps / ls_candidates
  int ls_candidates = 8;
  uint64_t seed = 1;
  double time_limit = 90.0;   // Control.cpp:62-68
  int threads = 1;
};

// --clock cpu switches every budget/timestamp read to process CPU time
// (CLOCK_PROCESS_CPUTIME_ID). Two uses: (a) budgets immune to machine
// contention when baselines run in the background; (b) an N-thread run
// at wall budget T burns ~N*T CPU-seconds, so "-t N*T --clock cpu" on
// one thread is the resource-equivalent stand-in for N OpenMP threads
// splitting the generation budget (ga.cpp:510) — the asymmetric-budget
// race protocol (BASELINE.md).
static bool g_clock_cpu = false;

static double now_sec() {
  if (g_clock_cpu) {
    struct timespec ts;
    clock_gettime(CLOCK_PROCESS_CPUTIME_ID, &ts);
    return (double)ts.tv_sec + ts.tv_nsec * 1e-9;
  }
#ifdef _OPENMP
  return omp_get_wtime();
#else
  return (double)clock() / CLOCKS_PER_SEC;
#endif
}

static void evaluate(const Problem &p, Individual &ind,
                     std::vector<uint8_t> &scratch) {
  ind.hcv = compute_hcv(p, ind.slots.data(), ind.rooms.data());
  ind.scv = compute_scv(p, ind.slots.data(), scratch);
  ind.pen = penalty_of(ind.hcv, ind.scv);
}

// K-candidate hill climb, same acceptance rule as ops/local_search.py
static void local_search(const Problem &p, const Matcher &m, Rng &rng,
                         Individual &ind, const GaParams &g,
                         std::vector<uint8_t> &scratch) {
  Individual cand = ind, best = ind;
  for (int round = 0; round < g.ls_rounds; ++round) {
    bool improved = false;
    best.pen = ind.pen;
    for (int k = 0; k < g.ls_candidates; ++k) {
      cand = ind;
      MoveCtx c{p, m, rng, g.p1, g.p2, g.p3};
      random_move(c, cand.slots, cand.rooms);
      evaluate(p, cand, scratch);
      if (cand.pen < best.pen) { best = cand; improved = true; }
    }
    if (improved) ind = best;
  }
}

// ------------------------------------- reference-faithful baseline GA
// A faithful re-statement of the reference ALGORITHM (not its code):
// steady-state pop-10 GA (ga.cpp:64, 580-585) whose local search is the
// exhaustive first-improvement sweep — every event (shuffled) x all 45
// Move1 targets (Solution.cpp:508-534) and all Move2 swap partners
// (535-561), counter reset on improvement so it runs to a local optimum,
// rooms re-matched EXACTLY per affected slot per candidate (the
// reference's primary matching path). This is the quality baseline the
// TPU path races at fixed wall clock (BASELINE.md), built because the
// reference binary itself cannot run here (no MPI in the image).
//
// hcv decomposes per slot (clash pairs + correlated pairs live inside a
// slot; unsuitable is per event), so a move's hcv delta touches only its
// two slots. scv decomposes per (student, day) windows + the last-slot
// term, maintained via an (S, T) attendance-count matrix.
struct RefLS {
  const Problem &p;
  const ExactMatcher &xm;
  std::vector<std::vector<int>> by_slot;   // slot -> events
  std::vector<int> att;                    // (S, T) attendance counts
  std::vector<std::vector<int>> attendees; // event -> students
  int hcv = 0, scv = 0;

  explicit RefLS(const Problem &pp, const ExactMatcher &x)
      : p(pp), xm(x), attendees(pp.E) {
    for (int s = 0; s < p.S; ++s)
      for (int e = 0; e < p.E; ++e)
        if (p.attends[(size_t)s * p.E + e]) attendees[e].push_back(s);
  }

  int slot_hcv(const std::vector<int> &evs, const int *slots,
               const int *rooms) const {
    (void)slots;
    int h = 0;
    for (size_t i = 0; i < evs.size(); ++i) {
      for (size_t j = i + 1; j < evs.size(); ++j) {
        if (rooms[evs[i]] == rooms[evs[j]]) h++;
        if (p.conflict[(size_t)evs[i] * p.E + evs[j]]) h++;
      }
      if (!p.possible[(size_t)evs[i] * p.R + rooms[evs[i]]]) h++;
    }
    return h;
  }

  // scv of one (student, day) window from the maintained att counts
  int day_scv(int s, int d) const {
    const int T = p.n_slots();
    const int *row = &att[(size_t)s * T + d * p.spd];
    int run = 0, cnt = 0, v = 0;
    for (int k = 0; k < p.spd; ++k) {
      if (row[k] > 0) { cnt++; if (++run > 2) v++; }
      else run = 0;
    }
    return v + (cnt == 1 ? 1 : 0);
  }

  void rebuild(Individual &ind) {
    const int T = p.n_slots();
    by_slot.assign(T, {});
    for (int e = 0; e < p.E; ++e) by_slot[ind.slots[e]].push_back(e);
    att.assign((size_t)p.S * T, 0);
    for (int e = 0; e < p.E; ++e)
      for (int s : attendees[e]) att[(size_t)s * T + ind.slots[e]]++;
    std::vector<uint8_t> scratch;
    evaluate(p, ind, scratch);
    hcv = ind.hcv;
    scv = ind.scv;
  }

  // scv delta of moving event e from slot t1 to t2 (t1 != t2)
  int scv_delta(int e, int t1, int t2) const {
    const int T = p.n_slots();
    const int d1 = t1 / p.spd, d2 = t2 / p.spd;
    int delta = 0;
    if (t1 % p.spd == p.spd - 1) delta -= p.student_count[e];
    if (t2 % p.spd == p.spd - 1) delta += p.student_count[e];
    for (int s : attendees[e]) {
      int *row = const_cast<int *>(&att[(size_t)s * T]);
      const int b1 = day_scv(s, d1), b2 = d2 == d1 ? 0 : day_scv(s, d2);
      row[t1]--; row[t2]++;
      delta += day_scv(s, d1) - b1;
      if (d2 != d1) delta += day_scv(s, d2) - b2;
      row[t1]++; row[t2]--;
    }
    return delta;
  }

  // hcv delta (and new rooms for both slots) of moving e from t1 to t2,
  // with EXACT re-matching of both affected slots per candidate — the
  // reference's per-candidate cost profile (SURVEY section 3.2)
  int hcv_delta_move1(Individual &ind, int e, int t2,
                      std::vector<int> &new_rooms) const {
    const int t1 = ind.slots[e];
    int before = slot_hcv(by_slot[t1], ind.slots.data(), ind.rooms.data())
               + slot_hcv(by_slot[t2], ind.slots.data(), ind.rooms.data());
    // tentative: move e, re-match both slots into new_rooms
    new_rooms = ind.rooms;
    std::vector<int> s1;
    for (int x : by_slot[t1]) if (x != e) s1.push_back(x);
    std::vector<int> s2 = by_slot[t2];
    s2.push_back(e);
    if (!s1.empty()) xm.assign_slot(s1, new_rooms.data());
    xm.assign_slot(s2, new_rooms.data());
    int after = 0;
    {
      // slot_hcv over the tentative rooms; e's slot membership changed
      int h = 0;
      for (size_t i = 0; i < s1.size(); ++i) {
        for (size_t j = i + 1; j < s1.size(); ++j) {
          if (new_rooms[s1[i]] == new_rooms[s1[j]]) h++;
          if (p.conflict[(size_t)s1[i] * p.E + s1[j]]) h++;
        }
        if (!p.possible[(size_t)s1[i] * p.R + new_rooms[s1[i]]]) h++;
      }
      for (size_t i = 0; i < s2.size(); ++i) {
        for (size_t j = i + 1; j < s2.size(); ++j) {
          if (new_rooms[s2[i]] == new_rooms[s2[j]]) h++;
          if (p.conflict[(size_t)s2[i] * p.E + s2[j]]) h++;
        }
        if (!p.possible[(size_t)s2[i] * p.R + new_rooms[s2[i]]]) h++;
      }
      after = h;
    }
    return after - before;
  }

  void apply_move1(Individual &ind, int e, int t2,
                   const std::vector<int> &new_rooms, int d_hcv,
                   int d_scv) {
    const int t1 = ind.slots[e];
    auto &v1 = by_slot[t1];
    v1.erase(std::find(v1.begin(), v1.end(), e));
    by_slot[t2].push_back(e);
    const int T = p.n_slots();
    for (int s : attendees[e]) {
      att[(size_t)s * T + t1]--;
      att[(size_t)s * T + t2]++;
    }
    ind.slots[e] = t2;
    ind.rooms = new_rooms;
    hcv += d_hcv;
    scv += d_scv;
    ind.hcv = hcv;
    ind.scv = scv;
    ind.pen = penalty_of(hcv, scv);
  }

  // The sweep itself: first-improvement over shuffled events; phase 1
  // (infeasible) accepts any hcv-reducing Move1/Move2; phase 2
  // (feasible) accepts hcv-neutral scv-reducing moves. Counter resets on
  // improvement; bounded by max_steps event visits and ls_limit seconds
  // (Solution.cpp:471-769 semantics; -l honored here, retired on TPU).
  void run(Individual &ind, Rng &rng, int max_steps, double ls_limit) {
    rebuild(ind);
    std::vector<int> order(p.E);
    for (int e = 0; e < p.E; ++e) order[e] = e;
    for (int e = p.E - 1; e > 0; --e)
      std::swap(order[e], order[rng.next_int(e + 1)]);

    const double t0 = now_sec();
    const int T = p.n_slots();
    int steps = 0, since_improve = 0;
    std::vector<int> new_rooms;
    for (int idx = 0; since_improve < p.E; idx = (idx + 1) % p.E) {
      if (++steps > max_steps || now_sec() - t0 > ls_limit) break;
      const int e = order[idx];
      bool improved = false;
      // Move1 sweep: all T target slots
      for (int t2 = 0; t2 < T && !improved; ++t2) {
        if (t2 == ind.slots[e]) continue;
        const int dh = hcv_delta_move1(ind, e, t2, new_rooms);
        if (hcv > 0 ? dh < 0 : dh == 0) {
          const int ds = scv_delta(e, ind.slots[e], t2);
          if (hcv > 0 ? true : ds < 0) {
            apply_move1(ind, e, t2, new_rooms, dh, ds);
            improved = true;
          }
        }
      }
      // Move2 sweep: swap with every other event (two chained Move1
      // deltas would not be exact; evaluate the swap directly)
      for (int j = 0; j < p.E && !improved; ++j) {
        const int f = order[j];
        if (f == e || ind.slots[f] == ind.slots[e]) continue;
        const int t1 = ind.slots[e], t2 = ind.slots[f];
        // swap = remove both, re-match both slots once
        int before =
            slot_hcv(by_slot[t1], ind.slots.data(), ind.rooms.data()) +
            slot_hcv(by_slot[t2], ind.slots.data(), ind.rooms.data());
        std::vector<int> s1, s2;
        for (int x : by_slot[t1]) s1.push_back(x == e ? f : x);
        for (int x : by_slot[t2]) s2.push_back(x == f ? e : x);
        new_rooms = ind.rooms;
        std::swap(ind.slots[e], ind.slots[f]);
        xm.assign_slot(s1, new_rooms.data());
        xm.assign_slot(s2, new_rooms.data());
        int after = 0;
        for (auto *sv : {&s1, &s2})
          for (size_t a = 0; a < sv->size(); ++a) {
            for (size_t b = a + 1; b < sv->size(); ++b) {
              if (new_rooms[(*sv)[a]] == new_rooms[(*sv)[b]]) after++;
              if (p.conflict[(size_t)(*sv)[a] * p.E + (*sv)[b]]) after++;
            }
            if (!p.possible[(size_t)(*sv)[a] * p.R + new_rooms[(*sv)[a]]])
              after++;
          }
        std::swap(ind.slots[e], ind.slots[f]);  // undo tentative
        const int dh = after - before;
        if (!(hcv > 0 ? dh < 0 : dh == 0)) continue;
        int ds = scv_delta(e, t1, t2);
        // apply e's att shift before computing f's delta (exactness)
        const int TT = p.n_slots();
        for (int s : attendees[e]) {
          att[(size_t)s * TT + t1]--; att[(size_t)s * TT + t2]++;
        }
        ds += scv_delta(f, t2, t1);
        for (int s : attendees[e]) {
          att[(size_t)s * TT + t1]++; att[(size_t)s * TT + t2]--;
        }
        if (hcv == 0 && ds >= 0) continue;
        // commit the swap
        auto &v1 = by_slot[t1];
        auto &v2 = by_slot[t2];
        *std::find(v1.begin(), v1.end(), e) = f;
        *std::find(v2.begin(), v2.end(), f) = e;
        for (int s : attendees[e]) {
          att[(size_t)s * TT + t1]--; att[(size_t)s * TT + t2]++;
        }
        for (int s : attendees[f]) {
          att[(size_t)s * TT + t2]--; att[(size_t)s * TT + t1]++;
        }
        std::swap(ind.slots[e], ind.slots[f]);
        ind.rooms = new_rooms;
        hcv += dh; scv += ds;
        ind.hcv = hcv; ind.scv = scv;
        ind.pen = penalty_of(hcv, scv);
        improved = true;
      }
      since_improve = improved ? 0 : since_improve + 1;
    }
  }
};

struct LogSink {
  FILE *os = stdout;
  void log_entry(int proc, int tid, long long best, double t) const {
    std::fprintf(os,
                 "{\"logEntry\":{\"procID\":%d,\"threadID\":%d,\"best\":%lld,"
                 "\"time\":%.6f}}\n", proc, tid, best, t < 0 ? 0.0 : t);
  }
};

static long long reported(const Individual &i) {  // ga.cpp:191
  return i.hcv == 0 ? (long long)i.scv
                    : (long long)i.hcv * 1000000LL + i.scv;
}

// Tournament-select two parents and breed one child: selection5 +
// uniform crossover + one-move mutation (ga.cpp:543-571). Shared by
// run_ga and both run_islands branches so breeding semantics cannot
// diverge; run_ga_reference keeps its own copy because its steady-state
// threads must snapshot parents inside a critical section. `xmatch`
// performs the crossover's full room rematch (greedy Matcher in the
// memetic path, ExactMatcher in the reference path); `greedy` serves
// the mutation's single-event re-room. NOT thread-safe against
// concurrent writers of `pop`.
template <class XMatcher>
static void breed_child(const Problem &p, const GaParams &g,
                        const std::vector<Individual> &pop, Rng &rng,
                        const XMatcher &xmatch, const Matcher &greedy,
                        Individual &child) {
  const int P = (int)pop.size();
  auto pick = [&]() {
    int best = rng.next_int(P);
    for (int k = 1; k < g.tournament_k; ++k) {
      int c = rng.next_int(P);
      if (pop[c].pen < pop[best].pen) best = c;
    }
    return best;
  };
  child = pop[pick()];
  const Individual &pb_ = pop[pick()];
  if (rng.next_double() < g.p_crossover) {   // uniform crossover (C11)
    for (int e = 0; e < p.E; ++e)
      if (rng.next_double() < 0.5) child.slots[e] = pb_.slots[e];
    xmatch.assign_all(child.slots.data(), child.rooms.data());
  }
  if (rng.next_double() < g.p_mutation) {    // one random move (C12)
    MoveCtx c{p, greedy, rng, g.p1, g.p2, g.p3};
    random_move(c, child.slots, child.rooms);
  }
}

// Generational mu+lambda GA, one island (the per-device program of the
// TPU path, ops/ga.py, in native form).
static Individual run_ga(const Problem &p, const GaParams &g,
                         const LogSink *sink, int proc_id) {
  Matcher m(p);
  const int P = g.pop_size;
  const double t0 = now_sec();

  std::vector<Individual> pop(P), children(P);
  std::vector<Rng> rngs;
  for (int i = 0; i < 2 * P; ++i)
    rngs.emplace_back(g.seed * 0x5851f42d4c957f2dULL + i);

  const int nthreads = g.threads > 0 ? g.threads : 1;

#pragma omp parallel num_threads(nthreads)
  {
    std::vector<uint8_t> scratch;
#pragma omp for
    for (int i = 0; i < P; ++i) {
      Individual &ind = pop[i];
      ind.slots.resize(p.E);
      ind.rooms.resize(p.E);
      for (int e = 0; e < p.E; ++e)
        ind.slots[e] = rngs[i].next_int(p.n_slots());
      m.assign_all(ind.slots.data(), ind.rooms.data());
      evaluate(p, ind, scratch);
      local_search(p, m, rngs[i], ind, g, scratch);
    }
  }
  auto by_pen = [](const Individual &a, const Individual &b) {
    return a.pen < b.pen;
  };
  std::sort(pop.begin(), pop.end(), by_pen);
  long long best_seen = LLONG_MAX;

  for (int gen = 0; gen < g.generations; ++gen) {
    if (now_sec() - t0 > g.time_limit) break;
#pragma omp parallel num_threads(nthreads)
    {
      std::vector<uint8_t> scratch;
#pragma omp for
      for (int i = 0; i < P; ++i) {
        Rng &rng = rngs[P + i];
        Individual &ch = children[i];
        breed_child(p, g, pop, rng, m, m, ch);
        evaluate(p, ch, scratch);
        local_search(p, m, rng, ch, g, scratch);
      }
    }
    // mu+lambda truncation (generational variant of ga.cpp:580-585)
    std::vector<Individual> all;
    all.reserve(2 * P);
    for (auto &x : pop) all.push_back(std::move(x));
    // children[i] is unconditionally reassigned next generation
    for (auto &x : children) all.push_back(std::move(x));
    std::sort(all.begin(), all.end(), by_pen);
    for (int i = 0; i < P; ++i) pop[i] = std::move(all[i]);

    const long long rep = reported(pop[0]);
    if (sink && rep < best_seen) {
      best_seen = rep;
      sink->log_entry(proc_id, 0, rep, now_sec() - t0);
    }
  }
  return pop[0];
}

// Steady-state reference-faithful GA: pop 10, tournament-5, uniform
// crossover (full EXACT rematch), one-move mutation, RefLS sweep to
// local optimum, child replaces the worst, re-sort (ga.cpp:543-585
// algorithm). Threads split the generation budget over a shared
// population like the reference's OpenMP loop (ga.cpp:510), minus its
// unlocked reads and shared-RNG races: selection-copy and replacement
// run inside criticals, each thread owns an RNG.
static Individual run_ga_reference(const Problem &p, const GaParams &g,
                                   const LogSink *sink, int proc_id,
                                   int max_steps, double ls_limit) {
  ExactMatcher xm(p);
  const int P = g.pop_size;
  const double t0 = now_sec();
  std::vector<Individual> pop(P);
  std::vector<uint8_t> scratch;
  {
    Rng rng(g.seed);
    RefLS ls(p, xm);
    for (int i = 0; i < P; ++i) {
      // every individual gets a VALID genotype (random + matching +
      // eval) even when over the time budget; only the expensive sweep
      // LS is skipped then — a default-constructed Individual (pen=0,
      // empty arrays) must never reach the sort below
      Individual &ind = pop[i];
      ind.slots.resize(p.E);
      ind.rooms.resize(p.E);
      for (int e = 0; e < p.E; ++e) ind.slots[e] = rng.next_int(p.n_slots());
      xm.assign_all(ind.slots.data(), ind.rooms.data());
      evaluate(p, ind, scratch);
      if (now_sec() - t0 <= g.time_limit)
        ls.run(ind, rng, max_steps, ls_limit);
    }
  }
  auto by_pen = [](const Individual &a, const Individual &b) {
    return a.pen < b.pen;
  };
  std::sort(pop.begin(), pop.end(), by_pen);
  long long best_seen = LLONG_MAX;

  const int nthreads = g.threads > 0 ? g.threads : 1;
#pragma omp parallel num_threads(nthreads)
  {
#ifdef _OPENMP
    const int tid = omp_get_thread_num();
#else
    const int tid = 0;
#endif
    Rng rng(g.seed * 0x9e3779b97f4a7c15ULL + 1000 + tid);
    RefLS ls(p, xm);
    Matcher greedy(p);  // mutation's single-event insert re-room
    std::vector<uint8_t> scr;
    Individual child, pa_, pb_;
    for (int gen = tid; gen < g.generations; gen += nthreads) {
      if (now_sec() - t0 > g.time_limit) break;
#pragma omp critical(ttpop)
      {
        auto pick = [&]() {
          int best = rng.next_int(P);
          for (int k = 1; k < g.tournament_k; ++k) {
            int c = rng.next_int(P);
            if (pop[c].pen < pop[best].pen) best = c;
          }
          return best;
        };
        pa_ = pop[pick()];
        pb_ = pop[pick()];
      }
      child = pa_;
      if (rng.next_double() < g.p_crossover) {
        for (int e = 0; e < p.E; ++e)
          if (rng.next_double() < 0.5) child.slots[e] = pb_.slots[e];
        xm.assign_all(child.slots.data(), child.rooms.data());
      }
      if (rng.next_double() < g.p_mutation) {
        MoveCtx c{p, greedy, rng, g.p1, g.p2, g.p3};
        random_move(c, child.slots, child.rooms);
      }
      evaluate(p, child, scr);
      ls.run(child, rng, max_steps, ls_limit);
#pragma omp critical(ttpop)
      {
        // child UNCONDITIONALLY overwrites the worst, then re-sort
        // (steady-state replacement, ga.cpp:580-585)
        pop[P - 1] = child;
        std::sort(pop.begin(), pop.end(), by_pen);
        const long long rep = reported(pop[0]);
        if (sink && rep < best_seen) {
          best_seen = rep;
          sink->log_entry(proc_id, tid, rep, now_sec() - t0);
        }
      }
    }
  }
  return pop[0];
}

// Multi-island mode: N islands in ONE process, threads parallelizing
// ACROSS islands, bidirectional ring migration every `migration_period`
// generations — the reference binary's flagship parallel axis
// (one island per MPI rank, ga.cpp:479-541) without MPI, with the same
// exchange semantics as the TPU path (parallel/islands.py _migrate):
// best solution forward, second-best backward, immigrants overwrite the
// two worst rows, then re-sort.
struct IslandCtx {
  std::vector<Individual> pop, children;
  std::vector<Rng> rngs;
  long long best_seen = LLONG_MAX;
};

static std::vector<Individual> run_islands(
    const Problem &p, const GaParams &g, const LogSink *sink,
    int n_islands, int migration_period, const std::string &algo,
    int max_steps, double ls_limit) {
  const int P = g.pop_size;
  const int N = n_islands;
  const double t0 = now_sec();
  const bool ref = (algo == "reference");
  std::vector<IslandCtx> isl(N);
  const int nthreads = g.threads > 0 ? g.threads : 1;

  // init: every island from its own seed stream (fold_in(key, island),
  // parallel/islands.py:59-82 — NOT the reference's broadcast-identical
  // populations, ga.cpp:429-444; documented divergence SURVEY C17)
#pragma omp parallel for num_threads(nthreads) schedule(dynamic)
  for (int is = 0; is < N; ++is) {
    IslandCtx &I = isl[is];
    I.pop.resize(P);
    I.children.resize(P);
    for (int i = 0; i < 2 * P; ++i)
      I.rngs.emplace_back(g.seed * 0x5851f42d4c957f2dULL + is * 77777 + i);
    Matcher m(p);
    ExactMatcher xm(p);
    RefLS ls(p, xm);
    std::vector<uint8_t> scratch;
    for (int i = 0; i < P; ++i) {
      Individual &ind = I.pop[i];
      ind.slots.resize(p.E);
      ind.rooms.resize(p.E);
      for (int e = 0; e < p.E; ++e)
        ind.slots[e] = I.rngs[i].next_int(p.n_slots());
      if (ref) xm.assign_all(ind.slots.data(), ind.rooms.data());
      else m.assign_all(ind.slots.data(), ind.rooms.data());
      evaluate(p, ind, scratch);
      if (now_sec() - t0 <= g.time_limit) {
        if (ref) ls.run(ind, I.rngs[i], max_steps, ls_limit);
        else local_search(p, m, I.rngs[i], ind, g, scratch);
      }
    }
    std::sort(I.pop.begin(), I.pop.end(),
              [](const Individual &a, const Individual &b) {
                return a.pen < b.pen;
              });
  }

  auto by_pen = [](const Individual &a, const Individual &b) {
    return a.pen < b.pen;
  };
  int gens_done = 0;
  while (gens_done < g.generations && now_sec() - t0 <= g.time_limit) {
    const int gens = std::min(migration_period, g.generations - gens_done);
#pragma omp parallel for num_threads(nthreads) schedule(dynamic)
    for (int is = 0; is < N; ++is) {
      IslandCtx &I = isl[is];
      Matcher m(p);
      ExactMatcher xm(p);
      RefLS ls(p, xm);
      std::vector<uint8_t> scratch;
      for (int gen = 0; gen < gens; ++gen) {
        if (now_sec() - t0 > g.time_limit) break;
        if (ref) {
          // steady-state: one child per generation (ga.cpp:543-585)
          Rng &rng = I.rngs[P];
          Individual child;
          breed_child(p, g, I.pop, rng, xm, m, child);
          evaluate(p, child, scratch);
          ls.run(child, rng, max_steps, ls_limit);
          I.pop[P - 1] = std::move(child);
          std::sort(I.pop.begin(), I.pop.end(),
                    [](const Individual &a, const Individual &b) {
                      return a.pen < b.pen;
                    });
        } else {
          // generational mu+lambda (run_ga's loop body, serial within
          // the island — threads are spent across islands here)
          for (int i = 0; i < P; ++i) {
            Rng &rng = I.rngs[P + i];
            Individual &ch = I.children[i];
            breed_child(p, g, I.pop, rng, m, m, ch);
            evaluate(p, ch, scratch);
            local_search(p, m, rng, ch, g, scratch);
          }
          std::vector<Individual> all;
          all.reserve(2 * P);
          for (auto &x : I.pop) all.push_back(std::move(x));
          for (auto &x : I.children) all.push_back(std::move(x));
          std::sort(all.begin(), all.end(),
                    [](const Individual &a, const Individual &b) {
                      return a.pen < b.pen;
                    });
          for (int i = 0; i < P; ++i) I.pop[i] = std::move(all[i]);
        }
        const long long rep = reported(I.pop[0]);
        if (sink && rep < I.best_seen) {
          I.best_seen = rep;
#pragma omp critical(ttlog)
          sink->log_entry(is, 0, rep, now_sec() - t0);
        }
      }
    }
    gens_done += gens;

    // ring migration (serial; the collectives' barrier semantics):
    // snapshot emigrants first so the exchange reads pre-migration
    // populations, like lax.ppermute of row 0 fwd / row 1 bwd.
    // P < 3 skips migration entirely — a victim row would alias the
    // BEST row (at P == 1 the island's only individual would be
    // destroyed, and pop[1] does not even exist; at P == 2 the
    // backward immigrant lands on pop[0]), matching the TPU path's
    // guard (parallel/islands.py _migrate)
    if (N > 1 && P >= 3) {
      std::vector<Individual> fwd(N), bwd(N);
      for (int is = 0; is < N; ++is) {
        fwd[is] = isl[is].pop[0];
        bwd[is] = isl[is].pop[1];
      }
      for (int is = 0; is < N; ++is) {
        isl[is].pop[P - 1] = fwd[(is - 1 + N) % N];
        isl[is].pop[P - 2] = bwd[(is + 1) % N];
        std::sort(isl[is].pop.begin(), isl[is].pop.end(), by_pen);
      }
    }
  }

  std::vector<Individual> bests(N);
  for (int is = 0; is < N; ++is) bests[is] = isl[is].pop[0];
  return bests;
}

}  // namespace tt

// =====================================================================
// C ABI (ctypes surface)

extern "C" {

// Opaque problem handle: parse + derive once, reuse across calls (the
// O(S*E^2) conflict derivation would otherwise dominate every batch).
void *tt_problem_create(int E, int R, int F, int S, int days, int spd,
                        const int *room_size, const int8_t *attends,
                        const int8_t *room_features,
                        const int8_t *event_features) {
  // Mirror ops/rooms.py's key-packing bounds: Matcher::choose packs
  // unsuitable/occupancy/cap_rank into one long key, so occupancy (<= E)
  // must stay below 1<<12 and cap_rank (< R) inside its field, or the
  // preference order silently inverts and desynchronizes from the JAX
  // kernel it cross-checks.
  if (E >= (1 << 12) || R >= (1 << 12)) return nullptr;
  auto *p = new tt::Problem();
  p->E = E; p->R = R; p->F = F; p->S = S; p->days = days; p->spd = spd;
  p->room_size.assign(room_size, room_size + R);
  p->attends.assign(attends, attends + (size_t)S * E);
  p->room_features.assign(room_features, room_features + (size_t)R * F);
  p->event_features.assign(event_features, event_features + (size_t)E * F);
  p->derive();
  return p;
}

void tt_problem_free(void *handle) {
  delete static_cast<tt::Problem *>(handle);
}

// Batch-evaluate P individuals; returns 0 on success. Arrays are dense
// int32 row-major; out arrays length P.
int tt_eval_batch(void *handle, const int *slots, const int *rooms, int P,
                  long long *out_pen, int *out_hcv, int *out_scv,
                  int threads) {
  const tt::Problem &p = *static_cast<tt::Problem *>(handle);
  const int nthreads = threads > 0 ? threads : 1;
  // num_threads clause, NOT omp_set_num_threads: this runs inside the
  // caller's (Python) process and must not mutate its global OpenMP state
#pragma omp parallel num_threads(nthreads)
  {
    std::vector<uint8_t> scratch;
#pragma omp for
    for (int i = 0; i < P; ++i) {
      const int *s = slots + (size_t)i * p.E;
      const int *r = rooms + (size_t)i * p.E;
      const int hcv = tt::compute_hcv(p, s, r);
      const int scv = tt::compute_scv(p, s, scratch);
      out_hcv[i] = hcv;
      out_scv[i] = scv;
      out_pen[i] = tt::penalty_of(hcv, scv);
    }
  }
  return 0;
}

// Greedy room matching for P individuals (same policy as ops/rooms.py).
int tt_assign_rooms(void *handle, const int *slots, int P, int *out_rooms) {
  const tt::Problem &p = *static_cast<tt::Problem *>(handle);
  tt::Matcher m(p);
  for (int i = 0; i < P; ++i)
    m.assign_all(slots + (size_t)i * p.E, out_rooms + (size_t)i * p.E);
  return 0;
}

}  // extern "C"

// =====================================================================
// Standalone CLI (tt_cpu): the reference binary's role on a CPU host.
#ifdef TT_MAIN

int main(int argc, char **argv) {
  const char *input = nullptr, *output = nullptr;
  tt::GaParams g;
  int problem_type = 1;
  bool max_steps_set = false;
  int max_steps = 200;
  double ls_limit = 99999.0;  // -l (Control.cpp:93-99); honored by --algo
                              // reference's sweep LS (Solution.cpp:499)
  std::string algo = "memetic";
  int n_islands = 1;          // --islands (the reference's MPI world
                              // size, ga.cpp:379) in one process
  int migration_period = 100; // generations between ring exchanges
                              // (ga.cpp:514 cadence, made explicit)

  for (int i = 1; i + 1 < argc + 1; ++i) {
    std::string a = argv[i] ? argv[i] : "";
    auto val = [&]() { return (i + 1 < argc) ? argv[++i] : nullptr; };
    if (a == "-i") input = val();
    else if (a == "-o") output = val();
    else if (a == "-s") { const char *v = val(); if (v) g.seed = std::strtoull(v, nullptr, 10); }
    else if (a == "-c") { const char *v = val(); if (v) g.threads = std::atoi(v); }
    else if (a == "-t") { const char *v = val(); if (v) g.time_limit = std::atof(v); }
    else if (a == "-p") { const char *v = val(); if (v) problem_type = std::atoi(v); }
    else if (a == "-m") { const char *v = val(); if (v) { max_steps = std::atoi(v); max_steps_set = true; } }
    else if (a == "-l") { const char *v = val(); if (v) ls_limit = std::atof(v); }
    else if (a == "--algo") { const char *v = val(); if (v) algo = v; }
    else if (a == "-p1") { const char *v = val(); if (v) g.p1 = std::atof(v); }
    else if (a == "-p2") { const char *v = val(); if (v) g.p2 = std::atof(v); }
    else if (a == "-p3") { const char *v = val(); if (v) g.p3 = std::atof(v); }
    else if (a == "--pop-size") { const char *v = val(); if (v) g.pop_size = std::atoi(v); }
    else if (a == "--generations") { const char *v = val(); if (v) g.generations = std::atoi(v); }
    else if (a == "--ls-candidates") { const char *v = val(); if (v) g.ls_candidates = std::atoi(v); }
    else if (a == "--islands") { const char *v = val(); if (v) n_islands = std::atoi(v); }
    else if (a == "--migration-period") { const char *v = val(); if (v) migration_period = std::atoi(v); }
    else if (a == "--clock") {
      const char *v = val();
      if (v && std::string(v) == "cpu") tt::g_clock_cpu = true;
      else if (v && std::string(v) == "wall") tt::g_clock_cpu = false;
      else { std::fprintf(stderr, "unknown --clock: %s (wall|cpu)\n", v ? v : ""); return 2; }
    }
    else if (!a.empty()) { std::fprintf(stderr, "unknown flag: %s\n", a.c_str()); return 2; }
  }
  if (!input) { std::fprintf(stderr, "No instance file specified, use -i <file>\n"); return 2; }
  if (n_islands < 1) n_islands = 1;
  // <1 (incl. atoi's 0 for junk) would make run_islands spin on
  // zero-generation epochs until the time limit
  if (migration_period < 1) migration_period = 1;
  if (!max_steps_set)
    max_steps = problem_type == 1 ? 200 : problem_type == 2 ? 1000 : 2000;
  g.ls_rounds = std::max(1, max_steps / g.ls_candidates);

  tt::Problem p;
  if (!tt::load_tim(input, p)) {
    std::fprintf(stderr, "cannot parse instance: %s\n", input);
    return 1;
  }

  tt::LogSink sink;
  if (output) {
    sink.os = std::fopen(output, "w");
    if (!sink.os) { std::fprintf(stderr, "cannot open %s\n", output); return 1; }
  }

  if (algo != "memetic" && algo != "reference") {
    std::fprintf(stderr, "unknown --algo: %s\n", algo.c_str());
    return 2;
  }
  const double t0 = tt::now_sec();
  std::vector<tt::Individual> bests;
  if (n_islands > 1) {
    bests = tt::run_islands(p, g, &sink, n_islands, migration_period,
                            algo, max_steps, ls_limit);
  } else {
    bests.push_back(algo == "reference"
                        ? tt::run_ga_reference(p, g, &sink, 0, max_steps,
                                               ls_limit)
                        : tt::run_ga(p, g, &sink, 0));
  }
  const double dt = tt::now_sec() - t0;

  // per-island solution records (endTry, ga.cpp:169-197)
  long long global = LLONG_MAX;
  bool global_feas = false;
  for (int is = 0; is < (int)bests.size(); ++is) {
    const tt::Individual &best = bests[is];
    const long long rep = tt::reported(best);
    const bool feas = best.hcv == 0;
    global = std::min(global, rep);
    global_feas = global_feas || feas;
    std::fprintf(sink.os,
                 "{\"solution\":{\"procID\":%d,\"threadID\":0,"
                 "\"totalTime\":%.6f,\"totalBest\":%lld,\"feasible\":%s",
                 is, dt, rep, feas ? "true" : "false");
    if (feas) {
      std::fprintf(sink.os, ",\"timeslots\":[");
      for (int e = 0; e < p.E; ++e)
        std::fprintf(sink.os, "%s%d", e ? "," : "", best.slots[e]);
      std::fprintf(sink.os, "],\"rooms\":[");
      for (int e = 0; e < p.E; ++e)
        std::fprintf(sink.os, "%s%d", e ? "," : "", best.rooms[e]);
      std::fprintf(sink.os, "]");
    }
    std::fprintf(sink.os, "}}\n");
  }
  // runEntry pair: global best = min over islands (the Allreduce MIN,
  // ga.cpp:234-257, 603-609)
  std::fprintf(sink.os, "{\"runEntry\":{\"totalBest\":%lld,\"feasible\":%s}}\n",
               global, global_feas ? "true" : "false");
  std::fprintf(sink.os,
               "{\"runEntry\":{\"totalBest\":%lld,\"feasible\":%s,"
               "\"procsNum\":%d,\"threadsNum\":%d,\"totalTime\":%.6f}}\n",
               global, global_feas ? "true" : "false", n_islands,
               g.threads, dt);
  if (output) std::fclose(sink.os);
  return 0;
}

#endif  // TT_MAIN
