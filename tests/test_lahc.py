"""Late-Acceptance Hill Climbing endgame (ops/lahc.py,
islands.make_lahc_runners, engine --post-lahc).

The reference has no LAHC (its phase-2 walk is first-improvement,
Solution.cpp:619-768); this is a TPU-side capability addition measured
against the scv-endgame regime the asymmetric race exposed
(BASELINE.md round 5). Tests pin the acceptance semantics and the
best-snapshot bookkeeping rather than any quality number.
"""

import io
import json

import jax
import numpy as np
import pytest

from timetabling_ga_tpu.ops import fitness, ga
from timetabling_ga_tpu.ops.lahc import (
    jit_init_lahc, jit_lahc_steps)


def _full_eval(pa, slots, rooms):
    pen, hcv, scv = fitness.batch_penalty(pa, slots, rooms)
    return np.asarray(pen), np.asarray(hcv), np.asarray(scv)


def _lex_le(pa_, sa, pb, sb):
    return (pa_ < pb) | ((pa_ == pb) & (sa <= sb))


def test_lahc_invariants(small_problem):
    """Maintained costs stay exact through hundreds of accepted moves,
    and best-so-far is lex-monotone and self-consistent."""
    pa = small_problem.device_arrays()
    P = 6
    key = jax.random.key(3)
    st0 = ga.init_population(pa, key, P)
    ls = jit_init_lahc(pa, st0.slots, st0.rooms, hist_len=16)
    ls = jit_lahc_steps(pa, jax.random.key(7), ls, 150,
                        p1=1.0, p2=1.0, p3=0.15)

    # current walker costs match a from-scratch evaluation (the delta
    # path composed over every accepted move introduced no drift)
    pen, hcv, scv = _full_eval(pa, ls.ls.slots, ls.ls.rooms)
    np.testing.assert_array_equal(pen, np.asarray(ls.ls.pen))
    np.testing.assert_array_equal(hcv, np.asarray(ls.ls.hcv))
    np.testing.assert_array_equal(scv, np.asarray(ls.ls.scv))

    # best snapshots evaluate to their recorded costs
    bpen, bhcv, bscv = _full_eval(pa, ls.best_slots, ls.best_rooms)
    np.testing.assert_array_equal(bpen, np.asarray(ls.best_pen))
    np.testing.assert_array_equal(bhcv, np.asarray(ls.best_hcv))
    np.testing.assert_array_equal(bscv, np.asarray(ls.best_scv))

    # best is lex <= both the initial cost and the current position
    p0, s0 = np.asarray(st0.penalty), np.asarray(st0.scv)
    assert _lex_le(bpen, bscv, p0, s0).all()
    assert _lex_le(bpen, bscv, pen, scv).all()

    # step counters advanced uniformly
    np.testing.assert_array_equal(np.asarray(ls.step), 150)


def test_lahc_block_candidates(small_problem):
    """steepest-of-K proposals (k_cands > 1) keep the exactness
    invariants: maintained costs match full re-evaluation and best
    snapshots are self-consistent."""
    pa = small_problem.device_arrays()
    st0 = ga.init_population(pa, jax.random.key(5), 4)
    ls = jit_init_lahc(pa, st0.slots, st0.rooms, hist_len=16)
    ls = jit_lahc_steps(pa, jax.random.key(9), ls, 60,
                        p1=1.0, p2=1.0, p3=0.15, k_cands=8)
    pen, hcv, scv = _full_eval(pa, ls.ls.slots, ls.ls.rooms)
    np.testing.assert_array_equal(pen, np.asarray(ls.ls.pen))
    np.testing.assert_array_equal(hcv, np.asarray(ls.ls.hcv))
    np.testing.assert_array_equal(scv, np.asarray(ls.ls.scv))
    bpen, bhcv, bscv = _full_eval(pa, ls.best_slots, ls.best_rooms)
    np.testing.assert_array_equal(bpen, np.asarray(ls.best_pen))
    np.testing.assert_array_equal(bscv, np.asarray(ls.best_scv))
    # K-block proposals descend at least as fast as the walk they
    # replace started from
    p0, s0 = np.asarray(st0.penalty), np.asarray(st0.scv)
    assert _lex_le(bpen, bscv, p0, s0).all()


def test_lahc_feasibility_one_way(small_problem):
    """A walker ensemble that starts feasible can never be accepted
    into infeasibility: an infeasible candidate's penalty lex-dominates
    every feasible history entry (the late-acceptance rule needs no
    explicit feasibility gate)."""
    pa = small_problem.device_arrays()
    st0 = ga.init_population(pa, jax.random.key(0), 16)
    # polish to feasibility first (small admits a perfect solution)
    from timetabling_ga_tpu.ops.sweep import jit_sweep_local_search
    slots, rooms = jit_sweep_local_search(
        pa, jax.random.key(1), st0.slots, st0.rooms, n_sweeps=30,
        swap_block=8, converge=True, sideways=0.25)
    pen, hcv, scv = _full_eval(pa, slots, rooms)
    feas0 = hcv == 0
    assert feas0.any(), "fixture should reach feasibility"

    ls = jit_init_lahc(pa, slots, rooms, hist_len=8)
    ls = jit_lahc_steps(pa, jax.random.key(2), ls, 200)
    hcv_after = np.asarray(ls.ls.hcv)
    assert (hcv_after[feas0] == 0).all()


def test_lahc_runners_mesh(small_problem):
    """Island-sharded LAHC programs on the 8-device mesh: runtime step
    counts, per-island stats, and the finalize PopState contract."""
    from timetabling_ga_tpu.parallel import islands
    pa = small_problem.device_arrays()
    n_islands, pop = 8, 4
    mesh = islands.make_mesh(n_islands)
    cfg = ga.GAConfig(pop_size=pop, p3=0.15)
    state = islands.init_island_population(
        pa, jax.random.key(0), mesh, pop, n_islands=n_islands)
    init_r, run_r, fin_r = islands.make_lahc_runners(
        mesh, cfg, hist_len=32, n_islands=n_islands)

    lstate = init_r(pa, state)
    # one compile serves different runtime chunk sizes
    lstate, stats1 = run_r(pa, jax.random.key(1), lstate, 10)
    lstate, stats2 = run_r(pa, jax.random.key(2), lstate, 25)
    assert stats1.shape == (3, n_islands)
    np.testing.assert_array_equal(np.asarray(lstate.step), 35)
    # island bests are monotone across chunks (lexicographic)
    s1, s2 = np.asarray(stats1), np.asarray(stats2)
    assert _lex_le(s2[0], s2[2], s1[0], s1[2]).all()

    final = fin_r(lstate)
    fpen = np.asarray(final.penalty).reshape(n_islands, pop)
    fscv = np.asarray(final.scv).reshape(n_islands, pop)
    fhcv = np.asarray(final.hcv).reshape(n_islands, pop)
    # row 0 of each island == that island's last stats entry
    np.testing.assert_array_equal(fpen[:, 0], s2[0])
    np.testing.assert_array_equal(fhcv[:, 0], s2[1])
    np.testing.assert_array_equal(fscv[:, 0], s2[2])
    # islands are lex-sorted best-first
    for i in range(n_islands):
        order = np.lexsort((fscv[i], fpen[i]))
        np.testing.assert_array_equal(order, np.arange(pop))
    # genotypes evaluate to the recorded costs
    pen, hcv, scv = _full_eval(pa, final.slots, final.rooms)
    np.testing.assert_array_equal(pen, np.asarray(final.penalty))
    np.testing.assert_array_equal(scv, np.asarray(final.scv))


@pytest.mark.slow
def test_engine_post_lahc(small_problem, tmp_path):
    """End-to-end --post-lahc run: the endgame enters the LAHC loop at
    the phase switch, logs monotone bests, and the endTry records come
    from the best snapshots."""
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime import engine
    from timetabling_ga_tpu.runtime.config import RunConfig
    tim_file = str(tmp_path / "small.tim")
    with open(tim_file, "w") as fh:
        fh.write(dump_tim(small_problem))
    cfg = RunConfig(input=tim_file, seed=1, islands=8, pop_size=4,
                    generations=50, migration_period=2,
                    ls_mode="sweep", ls_sweeps=2, ls_converge=True,
                    init_sweeps=2, post_lahc=64, post_pop_size=2,
                    time_limit=8.0, auto_tune=False, trace=True)
    engine.precompile(cfg)
    buf = io.StringIO()
    best = engine.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    phases = [x["phase"]["name"] for x in lines if "phase" in x]
    assert "lahc" in phases and "phase-switch" in phases, phases
    # logEntry bests are monotone decreasing PER ISLAND (ga.cpp:203-228
    # emits only on new local bests)
    per_isl = {}
    for x in lines:
        if "logEntry" in x:
            per_isl.setdefault(x["logEntry"]["procID"], []).append(
                x["logEntry"]["best"])
    assert per_isl
    for bests in per_isl.values():
        assert bests == sorted(bests, reverse=True)
    final = [x["runEntry"] for x in lines if "runEntry" in x][-1]
    assert final["totalBest"] == best
    assert best < 1_000_000   # tiny fixture reaches feasibility
