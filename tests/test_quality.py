"""Search-quality observatory tests (ISSUE 9; README "Search-quality
observatory").

The contract under test, layer by layer:

  - ON-DEVICE REDUCTIONS are pure telemetry: sweep/generation runners
    walk bit-identical trajectories with the quality flags on or off,
    and every population-derived reduction (diversity moments, the
    coprime-stride Hamming sample, migration gain) decodes to EXACTLY
    what a host recompute over the fetched population yields —
    bit-equal float32, not approximately.
  - STREAM IDENTITY: engine and serve JSONL record streams are
    bit-identical with the quality observatory on vs off (modulo
    qualityEntry/timing records), full and deltas trace modes alike —
    the tentpole acceptance criterion.
  - STALLS: the deterministic stall fixture fires the detector
    (faultEntry site=quality action=stall, engine.stalled gauge, the
    /readyz `stalled` reason) and, with --auto-kick-on-stall, the kick
    (faultEntry action=kick + engine.kicks).
  - CLI: `tt quality` summarizes a qualityEntry stream; `tt trace`
    renders the entries as counter tracks; `tt stats` appends the
    quality section.
"""

import functools
import io
import json
import os
import tempfile

import numpy as np
import pytest

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import quality as obs_quality
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig, ServeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM = os.path.join(REPO, "fixtures", "comp01s.tim")


# ------------------------------------------------------------ unit layer


def test_decode_rows_and_aggregate_layout():
    rows = np.zeros((2, obs_quality.QUALITY_WIDTH), np.int32)
    rows[0, :obs_quality.N_OPS] = [10, 3, 8, 2, 5, 4, 1]
    rows[1, obs_quality.OFF_MIG] = 7
    div = np.arange(obs_quality.N_DIV, dtype=np.float32) + 0.5
    rows[0, obs_quality.OFF_DIV:] = div.view(np.int32)
    rows[1, obs_quality.OFF_DIV:] = (div * 2).view(np.int32)
    d = obs_quality.decode_rows(rows)
    assert d["crossover_attempts"].tolist() == [10, 0]
    assert d["move3_accepts"].tolist() == [1, 0]
    assert d["migration_gain"].tolist() == [0, 7]
    assert d["penalty_mean"][0] == np.float32(0.5)
    agg = obs_quality.aggregate(d)
    assert agg["counters"]["quality.ops.crossover_attempts"] == 10
    assert agg["counters"]["quality.migration.gain"] == 7
    assert agg["gauges"]["quality.diversity.hamming_min"] == min(
        d["hamming"])
    # lane payload is flat and json-serializable
    payload = obs_quality.lane_payload(d, 0)
    json.dumps(payload)
    assert payload["crossover_wins"] == 3


def test_decode_rows_rejects_bad_shape():
    with pytest.raises(ValueError):
        obs_quality.decode_rows(np.zeros((2, 3), np.int32))


def test_stall_detector_window_and_collapse_threshold():
    det = obs_quality.StallDetector(window=2, hamming_floor=0.1)
    assert det.update(100, 0.05) is False      # first best: improvement
    assert det.update(100, 0.05) is False      # streak 1 < window
    assert det.update(100, 0.05) is True       # streak 2, collapsed
    assert det.update(100, 0.5) is False       # diverse plateau: no stall
    assert det.update(50, 0.05) is False       # new best resets streak
    assert det.update(50, 0.05) is False
    assert det.update(50, 0.05) is True
    det.reset()
    assert det.streak == 0 and det.stalled is False
    # window 0 disables entirely
    off = obs_quality.StallDetector(window=0, hamming_floor=1.0)
    assert all(not off.update(1, 0.0) for _ in range(5))


def test_readyz_stalled_reason():
    from timetabling_ga_tpu.obs import http as obs_http
    from timetabling_ga_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    ok, detail = obs_http.readiness(reg)
    assert ok
    reg.gauge("engine.stalled").set(1.0)
    ok, detail = obs_http.readiness(reg)
    assert not ok and "stalled" in detail["reasons"]
    reg.gauge("engine.stalled").set(0.0)
    ok, _ = obs_http.readiness(reg)
    assert ok


def test_hamming_stride_is_coprime():
    from timetabling_ga_tpu.parallel import islands
    import math
    assert islands._hamming_stride(1) == 0
    for pop in (2, 3, 4, 8, 10, 16, 30, 32):
        s = islands._hamming_stride(pop)
        assert 1 <= s <= pop // 2 or pop == 2
        assert math.gcd(s, pop) == 1


# ------------------------------------------- on-device reduction purity


def test_sweep_return_ops_is_trajectory_pure(small_problem):
    import jax
    from timetabling_ga_tpu.ops.sweep import jit_sweep_local_search
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(3)
    slots = rng.integers(0, small_problem.n_slots,
                         size=(6, small_problem.n_events)).astype(np.int32)
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    rooms = batch_assign_rooms(pa, slots)
    key = jax.random.key(9)
    s0, r0 = jit_sweep_local_search(pa, key, slots, rooms, 2,
                                    swap_block=4, converge=True, p3=0.2)
    s1, r1, ops = jit_sweep_local_search(pa, key, slots, rooms, 2,
                                         swap_block=4, converge=True,
                                         p3=0.2, return_ops=True)
    assert np.array_equal(np.asarray(s0), np.asarray(s1))
    assert np.array_equal(np.asarray(r0), np.asarray(r1))
    ops = np.asarray(ops)
    assert ops.shape == (3,) and (ops >= 0).all()
    assert ops.sum() > 0                       # random starts: something
    #                                            must have been accepted
    # p3=0 never produces a Move3 accept
    _, _, ops0 = jit_sweep_local_search(pa, key, slots, rooms, 1,
                                        swap_block=4, return_ops=True)
    assert int(np.asarray(ops0)[2]) == 0


def test_generation_with_quality_is_trajectory_pure(small_problem):
    import jax
    from timetabling_ga_tpu.ops import ga
    pa = small_problem.device_arrays()
    cfg = ga.GAConfig(pop_size=8)
    state = ga.init_population(pa, jax.random.key(1), 8, cfg)
    key = jax.random.key(2)
    plain = jax.jit(lambda s: ga.generation(pa, key, s, cfg))(state)
    with_q, q = jax.jit(
        lambda s: ga.generation(pa, key, s, cfg,
                                with_quality=True))(state)
    for a, b in zip(plain, with_q):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    q = np.asarray(q)
    assert q.shape == (obs_quality.N_OPS,)
    xo_a, xo_w, mu_a, mu_w = q[:4]
    assert 0 <= xo_a <= cfg.pop_size and 0 <= mu_a <= cfg.pop_size
    assert 0 <= xo_w <= xo_a and 0 <= mu_w <= mu_a
    assert (q[4:] == 0).all()                  # no sweep LS configured


def _host_div(mask, slots, pen, scv, pop):
    """Mirror of islands._div_stats in numpy float32 — the
    host-recompute reference the packed rows must bit-match."""
    from timetabling_ga_tpu.parallel import islands

    def mom(x):
        x = x.astype(np.float32)
        mn = np.float32(x.min())
        c = x - mn
        n = np.float32(len(c))
        mean_c = np.float32(c.sum() / n)
        var = np.float32(max(
            np.float32((c * c).sum() / n) - mean_c * mean_c,
            np.float32(0.0)))
        return [np.float32(mn + mean_c), var, mn, np.float32(x.max())]

    k = min(pop, obs_quality.HAMMING_PAIRS)
    s = islands._hamming_stride(pop)
    a, b = slots[:k], np.roll(slots, -s, axis=0)[:k]
    live = np.float32(max(mask.sum(), 1.0))
    diff = (a != b).astype(np.float32) * mask[None, :]
    ham = np.float32(diff.sum() / np.float32(k * live))
    return mom(pen) + mom(scv) + [ham]


def test_quality_rows_match_host_recomputation():
    """THE equivalence pin: run a quality dispatch, fetch the final
    population, recompute every population-derived reduction on host,
    and assert the decoded packed rows are bit-equal float32."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.problem import load_tim_file
    pa = load_tim_file(TIM).device_arrays()
    mesh = islands.make_mesh(2)
    pop = 8
    cfg = ga.GAConfig(pop_size=pop)
    state = islands.init_island_population(pa, jax.random.key(7), mesh,
                                           pop)
    run = islands.make_island_runner(mesh, cfg, n_epochs=2,
                                     gens_per_epoch=5, n_islands=2,
                                     trace_mode="deltas", quality=True)
    st, trace, _ = run(pa, jax.random.key(5), state)
    trace = np.asarray(trace)
    assert trace.shape == (2, islands.trace_leaf_width(10, "deltas",
                                                       quality=True))
    ev_leaf, qrows = islands.split_quality(trace, True)
    # the event half still decodes as a plain deltas leaf
    events, counts, _ = islands.trace_events(ev_leaf, "deltas")
    assert len(events) == 2 and counts is not None
    dec = obs_quality.decode_rows(qrows)
    host = jax.device_get(st)
    mask = np.asarray(pa.event_mask, np.float32)
    for i in range(2):
        rows = slice(i * pop, (i + 1) * pop)
        want = _host_div(mask, np.asarray(host.slots[rows]),
                         np.asarray(host.penalty[rows]),
                         np.asarray(host.scv[rows]), pop)
        got = [dec[n][i] for n in
               ("penalty_mean", "penalty_var", "penalty_min",
                "penalty_max", "scv_mean", "scv_var", "scv_min",
                "scv_max", "hamming")]
        assert got == want, (i, got, want)
        # operator counters: bounded by what the dispatch bred
        total_children = 10 * pop              # gens x pop per island
        assert 0 <= dec["crossover_attempts"][i] <= total_children
        assert dec["crossover_wins"][i] <= dec["crossover_attempts"][i]
        assert dec["mutation_wins"][i] <= dec["mutation_attempts"][i]
        assert dec["migration_gain"][i] >= 0


def test_quality_full_upgrade_is_uncapped(monkeypatch):
    """A --quality run in `full` trace mode must NEVER drop improvement
    events: the upgraded deltas packing is uncapped (K = the dispatch's
    generation count), so the quality-on stream matches the quality-off
    full stream even when a dispatch improves more than
    TRACE_DELTAS_CAP times. User-chosen deltas keeps its cap."""
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    monkeypatch.setattr(islands, "TRACE_DELTAS_CAP", 3)
    # strictly decreasing -> 8 improvements, cap 3
    tr = np.stack([np.arange(9, 1, -1), np.zeros(8)],
                  axis=1)[None].astype(np.int32)
    capped = np.asarray(islands._compress_trace(jnp.asarray(tr), None,
                                                "deltas"))
    ev, counts, _ = islands.trace_events(capped, "deltas")
    assert len(ev[0]) == 3 and counts[0] == 8      # capped: drops
    uncapped = np.asarray(islands._compress_trace(jnp.asarray(tr), None,
                                                  "deltas", cap=8))
    ev, counts, _ = islands.trace_events(uncapped, "deltas")
    assert len(ev[0]) == 8 == counts[0]            # uncapped: everything
    # width accounting follows: full+quality is uncapped, deltas capped
    q = obs_quality.QUALITY_WIDTH
    assert islands.trace_leaf_width(8, "full", quality=True) \
        == 3 * 8 + 1 + q
    assert islands.trace_leaf_width(8, "deltas", quality=True) \
        == 3 * 3 + 1 + q


def test_migration_gain_matches_host_recomputation(tiny_problem):
    """Crafted two-island exchange with a hand-computable outcome:
    island 0 (bests 100,110,...) receives island 1's best 5 forward and
    its second 6 backward -> new best 5, gain 95; island 1 (bests
    5,6,7,8) receives 100/110 into its worst rows -> best unchanged,
    gain 0."""
    import jax
    from jax.sharding import PartitionSpec as P
    from timetabling_ga_tpu.compat import shard_map
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.runtime import engine
    E = tiny_problem.n_events
    mesh = islands.make_mesh(2)
    scv = np.array([100, 110, 120, 130, 5, 6, 7, 8], np.int32)
    state = ga.PopState(
        slots=np.tile(np.arange(E, dtype=np.int32), (8, 1)),
        rooms=np.zeros((8, E), np.int32),
        penalty=scv.copy(), hcv=np.zeros((8,), np.int32),
        scv=scv.copy())
    dev_state = engine.reshard_state(state, mesh)
    specs = ga.PopState(slots=P(islands.AXIS), rooms=P(islands.AXIS),
                        penalty=P(islands.AXIS), hcv=P(islands.AXIS),
                        scv=P(islands.AXIS))

    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=(specs, P(islands.AXIS)),
                       check_vma=False)
    def mig(st):
        return islands._migrate(st, 2, 1, return_gain=True)

    out, gain = jax.jit(mig)(dev_state)
    assert np.asarray(gain).tolist() == [95, 0]
    out = jax.device_get(out)
    assert np.asarray(out.scv[:4]).tolist() == [5, 6, 100, 110]
    assert np.asarray(out.scv[4:]).tolist() == [5, 6, 100, 110]


# ------------------------------------------------------- engine A/B


def _engine_run(trace_mode="full", obs=False, **kw):
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                generations=30, migration_period=10, max_steps=8,
                time_limit=300, backend="cpu", auto_tune=False,
                trace=True, obs=obs, trace_mode=trace_mode,
                metrics_every=1)
    base.update(kw)
    best = eng.run(RunConfig(**base), out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def test_engine_stream_identical_with_quality(engine_stream_baseline):
    """Acceptance: engine record streams are bit-identical with the
    quality observatory on vs off (modulo qualityEntry/timing records),
    for both the full and deltas trace modes, with qualityEntry records
    and live quality.* metric families riding along."""
    b0, l0 = engine_stream_baseline
    for mode in ("full", "deltas"):
        b, l = _engine_run(trace_mode=mode, quality=True, obs=True)
        assert b == b0, mode
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), mode
        qes = [r["qualityEntry"] for r in l if "qualityEntry" in r]
        assert len(qes) >= 3                   # one per retired dispatch
        assert all("quality.diversity.hamming" in q for q in qes)
        snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
        assert "quality.diversity.hamming" in snaps[-1]["gauges"]
        assert ("quality.ops.crossover_attempts"
                in snaps[-1]["counters"])
    # /metrics exposition carries the families (live scrape view)
    text = obs_metrics.REGISTRY.to_openmetrics()
    assert "tt_quality_diversity_hamming" in text
    assert "tt_quality_ops_crossover_attempts_total" in text


def test_engine_quality_off_emits_no_quality_records(
        engine_stream_baseline):
    _, l0 = engine_stream_baseline
    assert not any("qualityEntry" in r for r in l0)


# -------------------------------------------------------- serve A/B


def _serve_run(quality=False, obs=False):
    from timetabling_ga_tpu.serve.service import serve_stream
    cfg = ServeConfig(backend="cpu", lanes=2, quantum=10, pop_size=8,
                      generations=20, obs=obs, quality=quality,
                      metrics_every=1)
    reqs = [{"submit": {"id": "a", "instance": TIM, "seed": 1}},
            {"submit": {"id": "b", "instance": TIM, "seed": 2}}]
    inp = io.StringIO("\n".join(json.dumps(r) for r in reqs) + "\n")
    out = io.StringIO()
    svc = serve_stream(cfg, inp, out)
    return svc, [json.loads(x) for x in out.getvalue().splitlines()]


def test_serve_stream_identical_with_quality():
    _, l0 = _serve_run()
    _, l1 = _serve_run(quality=True, obs=True)
    assert jsonl.strip_timing(l1) == jsonl.strip_timing(l0)
    qes = [r["qualityEntry"] for r in l1 if "qualityEntry" in r]
    assert qes and {q["job"] for q in qes} == {"a", "b"}
    # per-lane payloads are flat (lane_payload) and job-tagged
    assert all("hamming" in q and "crossover_attempts" in q
               for q in qes)
    # quality off emits nothing
    assert not any("qualityEntry" in r for r in l0)


# ------------------------------------------------- stall fixture + kick


@pytest.fixture(scope="module")
def small_tim(tmp_path_factory):
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    prob = random_instance(1, n_events=30, n_rooms=4, n_features=3,
                           n_students=20, attend_prob=0.15)
    path = tmp_path_factory.mktemp("quality") / "small.tim"
    path.write_text(dump_tim(prob))
    return str(path)


@pytest.fixture(scope="module")
def stall_run(small_tim):
    """One auto-kick stall run shared by the acceptance test and the
    CLI summarizer test (identical config, deterministic stream)."""
    kicks_before = obs_metrics.REGISTRY.counter("engine.kicks").value
    b, l = _engine_run(input=small_tim, seed=5, generations=80,
                       quality=True, obs=True, stall_window=2,
                       stall_hamming=1.0, auto_kick_on_stall=True)
    return b, l, kicks_before


def test_stall_fixture_fires_detector_and_auto_kick(stall_run):
    """The deterministic stall fixture (acceptance): a small instance
    whose population converges well inside the budget plateaus for
    stall_window dispatches; the detector fires (faultEntry
    site=quality action=stall + engine.stalled) and --auto-kick-on-
    stall dispatches the kick (faultEntry action=kick + engine.kicks),
    all visible on the stream and the registry."""
    b, l, kicks_before = stall_run
    fes = [r["faultEntry"] for r in l if "faultEntry" in r]
    stalls = [f for f in fes if (f["site"], f["action"]) == ("quality",
                                                             "stall")]
    kicks = [f for f in fes if (f["site"], f["action"]) == ("quality",
                                                            "kick")]
    assert stalls, fes
    assert stalls[0]["streak"] >= 2 and "hamming" in stalls[0]
    assert kicks and kicks[0]["moves"] >= 3
    assert (obs_metrics.REGISTRY.counter("engine.kicks").value
            - kicks_before) >= 1
    # the stall is visible in the qualityEntry stream too (the entries
    # bracket the stall; the gauge itself resets when the kick fires)
    assert any("qualityEntry" in r for r in l)


def test_stall_detector_without_autokick_keeps_stream(small_tim):
    """Detection alone is pure telemetry: same config minus the kick
    flag emits the stall faultEntry but the protocol stream matches the
    quality-off run exactly (strip_timing drops fault records)."""
    b0, l0 = _engine_run(input=small_tim, seed=5, generations=80)
    b1, l1 = _engine_run(input=small_tim, seed=5, generations=80,
                         quality=True, obs=True, stall_window=2,
                         stall_hamming=1.0)
    assert b1 == b0
    assert jsonl.strip_timing(l1) == jsonl.strip_timing(l0)
    assert any(r.get("faultEntry", {}).get("action") == "stall"
               for r in l1)
    assert not any(r.get("faultEntry", {}).get("action") == "kick"
                   for r in l1)


# ----------------------------------------------------------- CLI layer


def test_tt_quality_cli_summarizes(stall_run, tmp_path, capsys):
    _, lines, _ = stall_run
    log = tmp_path / "q.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in lines) + "\n")
    from timetabling_ga_tpu.obs.quality import main_quality
    assert main_quality([str(log)]) == 0
    out = capsys.readouterr().out
    assert "hamming" in out
    assert "crossover" in out
    assert "migration gain" in out
    assert "stall" in out and "kick" in out


def test_trace_export_renders_quality_counter_tracks():
    from timetabling_ga_tpu.obs.trace_export import export_chrome_trace
    recs = [{"qualityEntry": {"quality.diversity.hamming": 0.4,
                              "quality.ops.move1_accepts": 3,
                              "ts": 1.5, "dispatch": 0}},
            {"qualityEntry": {"hamming": 0.2, "job": "j1", "ts": 2.0,
                              "gens": 10}}]
    doc = export_chrome_trace(recs)
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    names = {e["name"] for e in counters}
    assert "quality.diversity.hamming" in names
    assert "quality.ops.move1_accepts" in names
    assert "hamming[j1]" in names              # job-tagged serve track
    # --job mode drops process-global counter tracks, like metricsEntry
    assert export_chrome_trace(recs, job="j1")["traceEvents"] == []


def test_tt_stats_includes_quality_section():
    from timetabling_ga_tpu.obs.logstats import summarize
    recs = [{"qualityEntry": {"quality.diversity.hamming": 0.4,
                              "quality.ops.crossover_wins": 2,
                              "quality.ops.crossover_attempts": 10,
                              "ts": 1.0}},
            {"faultEntry": {"site": "quality", "action": "stall",
                            "time": 3.0, "streak": 2, "hamming": 0.01,
                            "error": "x", "trial": 0, "recovery": 0,
                            "level": 0}}]
    text = summarize(recs)
    assert "quality entries: 1" in text
    assert "crossover: 2/10 wins" in text
    assert "stall @ 3.0s" in text
