"""tt-scale (ISSUE 15): the autoscaler — policy evaluation, warmth
guard, cooldown hysteresis, actuation seams, fault isolation.

The acceptance properties pinned here:

  1. TRIGGERS ARE SUSTAINED — a spike that visits the threshold once
     (or a ring that has not watched the signal long enough) never
     spawns; a window's worth of evidence does.
  2. WARMTH GUARD — a hot bucket's only warm replica is never the
     scale-down victim: the policy logs `blocked_warmth` and retires
     a cold replica instead (or holds entirely when nothing cold and
     idle remains).
  3. COOLDOWN — an oscillating queue-depth signal cannot flap the
     fleet: actions are bounded by elapsed/cooldown, blocks are
     counted, and the below-min floor heal bypasses the cooldown.
  4. ISOLATION — a dead or hung scaler thread (fault site `scaler`)
     freezes the fleet at its current size; routing, dispatch, job
     settlement, and writer drain never wait on it.
  5. E2E (slow) — a bursty multi-bucket stream against a 1-replica
     fleet with --scale-max 3 scales up under sustained backlog,
     scales back down via lossless preempt drain when idle, every
     job settles exactly once, and every stream is bit-identical to
     an unrouted baseline (strip-timing domain).
"""

import io
import json
import time

import pytest

from timetabling_ga_tpu.fleet.autoscaler import (
    AutoScaler, choose_victim, main_scale, summarize_entries)
from timetabling_ga_tpu.fleet.gateway import Gateway
from timetabling_ga_tpu.fleet.replicas import (
    ReplicaHandle, http_json, in_process_replica)
from timetabling_ga_tpu.obs.history import HistoryRing
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.obs.spans import NULL_TRACER
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args)


# ------------------------------------------------------------ stub fleet


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class _Handle:
    def __init__(self, name):
        self.name = name
        self.dead = False
        self.retired = False


class _Set:
    def __init__(self, handles):
        self._h = {h.name: h for h in handles}

    def all(self):
        return list(self._h.values())

    def live(self):
        return [h for h in self._h.values() if not h.dead]

    def get(self, name):
        return self._h.get(name)

    def add(self, handle):
        self._h[handle.name] = handle


class _StubGateway:
    """The narrow surface AutoScaler reads: a real registry + history
    ring on a fake clock, a settable scale snapshot, and actuation
    recorders in place of the spawn pool / preempt seam."""

    def __init__(self, handles, clock):
        self.registry = MetricsRegistry()
        self.now = clock
        self.history = HistoryRing(registry=self.registry,
                                   every_s=1.0, now=clock)
        self.replicas = _Set(handles)
        self.writer = io.StringIO()
        self.tracer = NULL_TRACER
        self.flight = None
        self.protected = {}
        self.preempted = []
        self.adopted = []

    def scale_snapshot(self):
        return {"replicas": {h.name: {"dead": h.dead,
                                      "retired": h.retired,
                                      "inflight": 0, "pins": 0}
                             for h in self.replicas.all()},
                "protected": dict(self.protected)}

    def preempt_replica(self, name):
        self.preempted.append(name)

    def adopt_replica(self, handle):
        self.adopted.append(handle)
        self.replicas.add(handle)

    def _rec(self, fn, *args, **kw):
        fn(*args, **kw)

    def records(self):
        return [json.loads(line) for line
                in self.writer.getvalue().splitlines()]

    def scale_records(self):
        return [r["scaleEntry"] for r in self.records()
                if "scaleEntry" in r]


def _cfg(**kw):
    kw.setdefault("spawn", 1)
    kw.setdefault("scale_min", 1)
    kw.setdefault("scale_max", 3)
    kw.setdefault("scale_up_queue", 5.0)
    kw.setdefault("scale_up_for", 10.0)
    kw.setdefault("scale_down_queue", 1.0)
    kw.setdefault("scale_down_for", 10.0)
    kw.setdefault("scale_idle_window", 10.0)
    kw.setdefault("scale_cooldown", 30.0)
    kw.setdefault("scale_every", 1.0)
    kw.setdefault("scale_warm_recent", 120.0)
    return FleetConfig(**kw)


def _scaler(gw, cfg):
    return AutoScaler(gw, cfg,
                      spawn_fn=lambda name: _Handle(name),
                      now=gw.now)


def _feed(gw, clock, seconds, depth, counters=None):
    """Advance the fake clock one second at a time, sampling the
    registry into the history ring — queue depth plus an idle backlog
    series for every current handle."""
    for _ in range(int(seconds)):
        clock.t += 1.0
        gw.registry.gauge("serve.queue_depth").set(float(depth))
        for h in gw.replicas.all():
            gw.registry.gauge(
                f"fleet.replica.{h.name}.backlog").set(0.0)
        for name, v in (counters or {}).items():
            gw.registry.counter(name).inc(v)
        gw.history.sample_once()


# --------------------------------------------------------------- parsing


def test_parse_scale_flags():
    cfg = parse_fleet_args(
        ["--spawn", "1", "--scale-max", "3", "--scale-min", "2",
         "--scale-up-queue", "16", "--scale-up-for", "45",
         "--scale-cooldown", "90", "--scale-dry-run"])
    assert (cfg.scale_max, cfg.scale_min) == (3, 2)
    assert cfg.scale_up_queue == 16.0
    assert cfg.scale_up_for == 45.0
    assert cfg.scale_cooldown == 90.0
    assert cfg.scale_dry_run is True

    # a static fleet has no pool to actuate — dry-run is the only form
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "http://x", "--scale-max", "2"])
    parse_fleet_args(["--replica", "http://x", "--scale-max", "2",
                      "--scale-dry-run"])
    with pytest.raises(SystemExit):
        parse_fleet_args(["--spawn", "1", "--scale-max", "2",
                          "--scale-min", "3"])
    with pytest.raises(SystemExit):
        # overlapping trigger bands guarantee flapping
        parse_fleet_args(["--spawn", "1", "--scale-max", "2",
                          "--scale-up-queue", "2",
                          "--scale-down-queue", "4"])
    with pytest.raises(SystemExit):
        # the policy evaluates history windows — no ring, no policy
        parse_fleet_args(["--spawn", "1", "--scale-max", "2",
                          "--history-every", "0"])


# ---------------------------------------------------------- victim choice


def test_choose_victim_warmth_and_order():
    reps = {"r0": {"inflight": 0, "idle": True},
            "r1": {"inflight": 0, "idle": True},
            "r2": {"inflight": 2, "idle": True}}
    # no protection: fewest in-flight, then name
    assert choose_victim(reps, {}) == ("r0", [])
    # r0 sole-warm for a hot bucket: skipped (counted), r1 retired
    victim, skipped = choose_victim(
        reps, {"r0": [[32, 4, 4, 32, 5, 9]]})
    assert victim == "r1" and skipped == ["r0"]
    # everything idle is protected: no victim, both skips counted
    victim, skipped = choose_victim(
        {"r0": {"inflight": 0, "idle": True},
         "r1": {"inflight": 0, "idle": True}},
        {"r0": [[1]], "r1": [[2]]})
    assert victim is None and skipped == ["r0", "r1"]
    # a non-idle replica is not a candidate at all (and not a "skip")
    victim, skipped = choose_victim(
        {"r0": {"inflight": 0, "idle": False}}, {})
    assert victim is None and skipped == []


def test_choose_victim_prefers_device_cold_replica():
    """Residency-aware retirement: a replica with zero resident
    groups retires for free, so it is preferred over one whose
    retire would flush parked device state — even when the warm
    one has fewer in-flight jobs."""
    reps = {"r0": {"inflight": 0, "idle": True,
                   "resident_groups": 3.0,
                   "resident_bytes": 4096.0},
            "r1": {"inflight": 1, "idle": True,
                   "resident_groups": 0.0,
                   "resident_bytes": 0.0}}
    assert choose_victim(reps, {}) == ("r1", [])
    # all warm: fewest resident bytes wins (smallest flush)
    reps = {"r0": {"inflight": 0, "idle": True,
                   "resident_groups": 2.0,
                   "resident_bytes": 8192.0},
            "r1": {"inflight": 0, "idle": True,
                   "resident_groups": 5.0,
                   "resident_bytes": 1024.0}}
    assert choose_victim(reps, {}) == ("r1", [])
    # never-scraped residency (None) is warm-unknown, never preferred
    # over a known-cold replica...
    reps = {"r0": {"inflight": 0, "idle": True},
            "r1": {"inflight": 2, "idle": True,
                   "resident_groups": 0.0, "resident_bytes": 0.0}}
    assert choose_victim(reps, {}) == ("r1", [])
    # ...and with no residency data anywhere the legacy order holds
    # exactly (fewest in-flight, then name)
    reps = {"r0": {"inflight": 2, "idle": True},
            "r1": {"inflight": 0, "idle": True}}
    assert choose_victim(reps, {}) == ("r1", [])
    # the warmth guard still outranks residency preference
    reps = {"r0": {"inflight": 0, "idle": True,
                   "resident_groups": 0.0, "resident_bytes": 0.0},
            "r1": {"inflight": 0, "idle": True,
                   "resident_groups": 7.0,
                   "resident_bytes": 2.0 ** 20}}
    victim, skipped = choose_victim(reps, {"r0": [[1]]})
    assert victim == "r1" and skipped == ["r0"]


# ------------------------------------------------------- policy evaluation


def test_spawn_needs_sustained_coverage():
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    scaler = _scaler(gw, _cfg())
    # 5 s of high backlog: the 10 s window is NOT covered — no action
    _feed(gw, clock, 5, depth=8.0)
    assert scaler.tick() is True
    assert gw.adopted == [] and gw.scale_records() == []
    # 12 s total: covered and sustained — one spawn, with evidence
    _feed(gw, clock, 7, depth=8.0)
    assert scaler.tick() is True
    assert [h.name for h in gw.adopted] == ["s0"]
    assert gw.registry.counter("fleet.scale.ups").value == 1
    recs = gw.scale_records()
    assert len(recs) == 1 and recs[0]["action"] == "up"
    assert recs[0]["reason"] == "queue_depth"
    ev = recs[0]["evidence"]["serve.queue_depth"]
    assert ev["op"] == ">=" and ev["for_s"] == 10.0
    assert gw.registry.gauge(
        "fleet.scale.replicas_live").value == 2.0


def test_cooldown_blocks_with_one_record_per_stretch():
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    scaler = _scaler(gw, _cfg(scale_cooldown=30.0))
    _feed(gw, clock, 12, depth=8.0)
    scaler.tick()
    assert len(gw.adopted) == 1
    # signal stays high inside the cooldown: every tick is blocked,
    # ONE record covers the whole stretch
    for _ in range(5):
        _feed(gw, clock, 1, depth=8.0)
        scaler.tick()
    assert len(gw.adopted) == 1
    assert gw.registry.counter(
        "fleet.scale.blocked_cooldown").value == 5
    blocked = [r for r in gw.scale_records()
               if r.get("blocked") == "cooldown"]
    assert len(blocked) == 1
    # past the cooldown the sustained signal acts again
    _feed(gw, clock, 30, depth=8.0)
    scaler.tick()
    assert [h.name for h in gw.adopted] == ["s0", "s1"]


def test_warmth_guard_retires_cold_replica_instead():
    """ISSUE 15 satellite: a hot bucket with ONE warm replica + a
    sustained scale-down signal must log blocked_warmth and retire a
    cold replica instead — the hard invariant, as a decision."""
    clock = _Clock()
    r0, r1 = _Handle("r0"), _Handle("r1")
    gw = _StubGateway([r0, r1], clock)
    gw.protected = {"r0": [[32, 4, 4, 32, 5, 9]]}
    scaler = _scaler(gw, _cfg(scale_min=1))
    _feed(gw, clock, 12, depth=0.0)
    scaler.tick()
    assert gw.preempted == ["r1"] and r1.retired and not r0.retired
    assert gw.registry.counter(
        "fleet.scale.blocked_warmth").value == 1
    assert gw.registry.counter("fleet.scale.downs").value == 1
    rec = gw.scale_records()[-1]
    assert rec["action"] == "down" and rec["replica"] == "r1"
    assert rec["evidence"]["warmth_skipped"] == {
        "r0": [[32, 4, 4, 32, 5, 9]]}


def test_warmth_guard_holds_when_everything_is_protected():
    clock = _Clock()
    r0, r1 = _Handle("r0"), _Handle("r1")
    gw = _StubGateway([r0, r1], clock)
    gw.protected = {"r0": [[1]], "r1": [[2]]}
    scaler = _scaler(gw, _cfg(scale_min=1))
    _feed(gw, clock, 12, depth=0.0)
    scaler.tick()
    assert gw.preempted == [] and not r0.retired and not r1.retired
    assert gw.registry.counter("fleet.scale.downs").value == 0
    assert gw.registry.counter(
        "fleet.scale.blocked_warmth").value == 2
    rec = gw.scale_records()[-1]
    assert rec["action"] == "down" and rec["blocked"] == "warmth"
    assert rec.get("replica") is None


def test_warmth_snapshot_ignores_retiring_owner():
    """Regression: the dispatcher's warmth snapshot computes
    sole-warm protection over SURVIVING capacity only. A retiring
    replica is still draining (and warm), but it is leaving —
    counting it as a second warm owner would leave a hot bucket's
    last remaining home unprotected, and a back-to-back scale-down
    could retire it (violating the hard invariant)."""
    r0 = ReplicaHandle("r0", "http://127.0.0.1:1")
    r1 = ReplicaHandle("r1", "http://127.0.0.1:2")
    cfg = FleetConfig(replicas=[r0.url, r1.url],
                      listen="127.0.0.1:0", scale_max=3,
                      scale_dry_run=True)
    gw = Gateway(cfg, [r0, r1])   # never started: no threads, no
    try:                          # probes — _refresh_view is driven
        bucket = (32, 4, 4, 32, 5, 9)          # by hand
        gw.router._warm = {"r0": {bucket}, "r1": {bucket}}
        gw._bucket_routed_t[bucket] = gw.now()   # recently routed: HOT
        r0.retired = True
        gw._refresh_view()
        snap = gw.scale_snapshot()
        assert snap["protected"] == {"r1": [list(bucket)]}
        assert snap["replicas"]["r0"]["retired"] is True
        # with r0 back in capacity the bucket has TWO warm homes and
        # needs no protection
        r0.retired = False
        gw._refresh_view()
        assert gw.scale_snapshot()["protected"] == {}
    finally:
        gw.close()


def test_flap_bounded_by_cooldown():
    """ISSUE 15 satellite: an oscillating queue-depth signal may not
    flap the fleet — actions are bounded by elapsed/cooldown and the
    blocks are visible."""
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    cooldown = 40.0
    scaler = _scaler(gw, _cfg(scale_cooldown=cooldown, scale_min=1,
                              scale_max=2))
    cycles = 4
    for _ in range(cycles):
        _feed(gw, clock, 12, depth=8.0)    # sustained high...
        scaler.tick()
        _feed(gw, clock, 12, depth=0.0)    # ...then sustained idle
        scaler.tick()
    reg = gw.registry
    actions = (reg.counter("fleet.scale.ups").value
               + reg.counter("fleet.scale.downs").value)
    # 96 simulated seconds: at most 1 + floor(96/40) = 3 actions
    assert actions <= 1 + int(clock.t // cooldown)
    assert actions >= 1
    assert reg.counter("fleet.scale.blocked_cooldown").value >= 1
    # and the scaler never actuated anything it didn't log
    recs = gw.scale_records()
    acted = [r for r in recs if not r.get("blocked")]
    assert len(acted) == actions


def test_min_floor_heals_through_cooldown():
    clock = _Clock()
    r0 = _Handle("r0")
    gw = _StubGateway([r0], clock)
    scaler = _scaler(gw, _cfg(scale_cooldown=1000.0))
    _feed(gw, clock, 12, depth=8.0)
    scaler.tick()                          # spawn s0; cooldown armed
    assert len(gw.adopted) == 1
    r0.dead = True
    gw.adopted[0].dead = True              # the whole fleet died
    _feed(gw, clock, 1, depth=8.0)
    scaler.tick()                          # min_floor bypasses cooldown
    assert len(gw.adopted) == 2
    assert gw.scale_records()[-1]["reason"] == "min_floor"


def test_tenant_starvation_trigger():
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    scaler = _scaler(gw, _cfg(scale_starve_rate=1.0))
    # queue calm, but acme accrues 2 queue-seconds per wall second —
    # jobs queue faster than they start (and the FLOP demand curve
    # rides the evidence)
    _feed(gw, clock, 12, depth=0.5,
          counters={"usage.tenant.acme.queue_seconds": 2.0,
                    "usage.tenant.acme.flops": 1e9})
    scaler.tick()
    assert len(gw.adopted) == 1
    rec = gw.scale_records()[-1]
    assert rec["reason"] == "tenant_starved:acme"
    assert "usage.tenant.acme.queue_seconds" in rec["evidence"]
    assert rec["evidence"]["demand_flops_per_s"]["acme"] > 0


def test_dry_run_decides_but_never_acts():
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    scaler = AutoScaler(gw, _cfg(scale_dry_run=True), spawn_fn=None,
                        now=clock)
    _feed(gw, clock, 12, depth=8.0)
    scaler.tick()
    assert gw.adopted == [] and gw.preempted == []
    rec = gw.scale_records()[-1]
    assert rec["action"] == "up" and rec["dry_run"] is True


# --------------------------------------------------------- fault isolation


def test_scaler_die_exits_tick_loop():
    clock = _Clock()
    gw = _StubGateway([_Handle("r0")], clock)
    scaler = _scaler(gw, _cfg())
    faults.install("scaler:1:die")
    try:
        assert scaler.tick() is False      # the thread would exit
    finally:
        faults.install(None)
    assert gw.adopted == [] and gw.scale_records() == []


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    kw.setdefault("http", "127.0.0.1:0")
    return ServeConfig(**kw)


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_dead_scaler_never_stalls_settlement():
    """Fault site `scaler` isolation: an injected scaler death leaves
    the fleet serving — a job submitted after the death still routes,
    solves, and settles, and the gateway closes cleanly."""
    rep, handle = in_process_replica(_serve_cfg(), "r0")
    cfg = FleetConfig(replicas=[handle.url], listen="127.0.0.1:0",
                      probe_every=0.1, poll_every=0.05,
                      history_every=0.2, scale_max=2,
                      scale_every=0.05, scale_dry_run=True,
                      faults="scaler:1:die")
    gw = Gateway(cfg, [handle]).start()
    try:
        _wait(lambda: not gw.scaler.alive(), 10, "scaler death")
        problem = random_instance(7, n_events=10, n_rooms=3,
                                  n_features=2, n_students=8,
                                  attend_prob=0.2)
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(problem), "id": "after-death",
                   "seed": 1, "generations": 6})
        _wait(lambda: http_json(
            "GET", gw.url + "/v1/jobs/after-death",
            ok=(200,))["state"] == "done", 120, "job settled")
    finally:
        faults.install(None)
        gw.request_drain()
        gw.drained.wait(30)
        gw.close()
        rep.kill()


# ------------------------------------------------------------- rendering


def test_tt_scale_cli(tmp_path, capsys):
    log = tmp_path / "gw.jsonl"
    recs = [
        {"scaleEntry": {"action": "up", "reason": "queue_depth",
                        "replica": "s0", "live": 1, "target": 2,
                        "dry_run": False, "ts": 10.0,
                        "evidence": {"serve.queue_depth": {
                            "op": ">=", "threshold": 8.0,
                            "for_s": 30.0, "mean": 11.5}}}},
        {"scaleEntry": {"action": "down", "reason": "idle",
                        "blocked": "cooldown", "live": 2,
                        "dry_run": False, "ts": 20.0}},
        {"logEntry": {"procID": 0, "threadID": 0, "best": 5,
                      "time": 1.0}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert main_scale([str(log)]) == 0
    out = capsys.readouterr().out
    assert "scale decisions (2 records)" in out
    assert "up (queue_depth)" in out and "+s0" in out
    assert "BLOCKED:cooldown" in out
    assert "serve.queue_depth >= 8 sustained 30s" in out
    # --json emits the raw entries
    assert main_scale([str(log), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert len(parsed) == 2 and parsed[0]["action"] == "up"
    # no entries is a report, not a crash
    assert "no scaleEntry records" in summarize_entries([])


# ----------------------------------------------------------- e2e (slow)


@pytest.mark.slow
def test_scale_acceptance_burst_up_idle_down_identical_streams():
    """ISSUE 15 acceptance: a bursty multi-bucket job stream against a
    1-replica fleet with scale-max 3 scales UP under sustained
    backlog (real spawns via the injected in-process pool), scales
    DOWN via lossless preempt drain once idle, every job settles
    exactly once, and every stream is bit-identical to the same job
    on a bare unrouted SolveService (strip-timing domain). No down
    decision ever names a warmth-protected victim."""
    from timetabling_ga_tpu.serve.service import SolveService

    rep0, h0 = in_process_replica(_serve_cfg(), "r0")
    reps = [rep0]

    def spawn_fn(name):
        rep, handle = in_process_replica(_serve_cfg(), name)
        reps.append(rep)
        return handle

    gwbuf = io.StringIO()
    cfg = FleetConfig(replicas=[h0.url], listen="127.0.0.1:0",
                      probe_every=0.1, poll_every=0.05, dead_after=2,
                      history_every=0.2, metrics_every=0,
                      scale_min=1, scale_max=3,
                      scale_up_queue=3.0, scale_up_for=1.0,
                      scale_down_queue=1.0, scale_down_for=2.0,
                      scale_idle_window=2.0, scale_cooldown=2.0,
                      scale_every=0.2, scale_warm_recent=3.0)
    gw = Gateway(cfg, [h0], out=gwbuf, spawn_fn=spawn_fn).start()
    shapes = [dict(n_events=12, n_rooms=3, n_features=2,
                   n_students=8, attend_prob=0.2),
              dict(n_events=40, n_rooms=4, n_features=2,
                   n_students=30, attend_prob=0.1),
              dict(n_events=70, n_rooms=6, n_features=3,
                   n_students=50, attend_prob=0.08)]
    jobs = []
    try:
        ids = []
        for i in range(12):
            p = random_instance(300 + i, **shapes[i % 3])
            jid = f"burst-{i}"
            jobs.append((jid, p, i, 30))
            ids.append(jid)
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": i,
                       "generations": 30})
            time.sleep(0.05)       # a stream, not one batch POST
        _wait(lambda: gw.registry.counter(
            "fleet.scale.ups").value >= 1, 60, "a scale-up")
        _wait(lambda: all(
            v["state"] == "done" for v in (
                http_json("GET", f"{gw.url}/v1/jobs/{j}",
                          ok=(200,)) for j in ids)), 420,
            "burst settled")
        ups = gw.registry.counter("fleet.scale.ups").value
        assert ups >= 1
        assert len(reps) >= 2      # real spawns happened

        # idle: sustained-low queue + per-replica idle backlogs →
        # lossless scale-down via preempt drain, back toward the floor
        _wait(lambda: gw.registry.counter(
            "fleet.scale.downs").value >= 1, 90, "a scale-down")
        retired = [h for h in gw.replicas.all()
                   if getattr(h, "retired", False)]
        assert retired, "a down decision must retire a real handle"

        # exactly-once settlement + stream identity vs unrouted
        views = {j: http_json("GET", f"{gw.url}/v1/jobs/{j}",
                              ok=(200,)) for j in ids}
        for jid, view in views.items():
            events = [r["jobEntry"]["event"] for r in view["records"]
                      if "jobEntry" in r]
            assert events.count("done") == 1, (jid, events)
        buf = io.StringIO()
        svc = SolveService(
            ServeConfig(backend="cpu", lanes=2, quantum=5,
                        pop_size=4, max_steps=8), out=buf)
        for jid, problem, seed, gens in jobs:
            svc.submit(problem, job_id=jid, seed=seed,
                       generations=gens)
        svc.drive()
        svc.close()
        base: dict = {}
        for line in buf.getvalue().splitlines():
            rec = json.loads(line)
            body = rec[next(iter(rec))]
            if isinstance(body, dict) and body.get("job") is not None:
                base.setdefault(body["job"], []).append(rec)
        base = {j: jsonl.strip_timing(rs) for j, rs in base.items()}
        for jid, view in views.items():
            assert jsonl.strip_timing(view["records"]) == base[jid], \
                f"stream diverged for {jid}"

        # the decision log: downs never name a protected victim, and
        # every down fired on a calm fleet (the sustained-low
        # evidence rides the record)
        gw.close()
        closed = True
        scale_recs = [json.loads(line)["scaleEntry"]
                      for line in gwbuf.getvalue().splitlines()
                      if "scaleEntry" in line
                      and "scaleEntry" in json.loads(line)]
        downs = [r for r in scale_recs
                 if r["action"] == "down" and not r.get("blocked")]
        assert downs
        for r in downs:
            skipped = (r.get("evidence") or {}).get(
                "warmth_skipped") or {}
            assert r["replica"] not in skipped
            ev = r["evidence"]["serve.queue_depth"]
            assert ev["op"] == "<=" and ev["mean"] <= ev["threshold"]
    finally:
        if not locals().get("closed"):
            gw.request_drain()
            gw.drained.wait(30)
            gw.close()
        for rep in reps:
            rep.kill()
