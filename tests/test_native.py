"""Native C++ component tests: the third independent implementation of
the fitness semantics (C++ vs JAX kernels vs Python oracle) must agree
exactly; the standalone CPU binary must emit the JSONL protocol.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from timetabling_ga_tpu import native
from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.problem import dump_tim, random_instance
from tests.conftest import random_assignment

pytestmark = pytest.mark.skipif(
    not native.is_available(),
    reason=f"native lib unavailable: {native.load_error()}")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TT_CPU = os.path.join(REPO, "native", "tt_cpu")


def test_native_eval_matches_jax(medium_problem):
    pa = medium_problem.device_arrays()
    rng = np.random.default_rng(0)
    slots, rooms = random_assignment(rng, medium_problem, 32)
    pen_j, hcv_j, scv_j = (np.asarray(x) for x in
                           fitness.batch_penalty(pa, slots, rooms))
    pen_n, hcv_n, scv_n = native.eval_batch(medium_problem, slots, rooms,
                                            threads=2)
    np.testing.assert_array_equal(hcv_n, hcv_j)
    np.testing.assert_array_equal(scv_n, scv_j)
    np.testing.assert_array_equal(pen_n, pen_j.astype(np.int64))


def test_native_matcher_suitability(small_problem):
    rng = np.random.default_rng(1)
    slots, _ = random_assignment(rng, small_problem, 8)
    rooms = native.assign_rooms_batch(small_problem, slots)
    for p in range(8):
        for e in range(small_problem.n_events):
            if small_problem.possible[e].any():
                assert small_problem.possible[e][rooms[p, e]]


def test_native_matcher_matches_jax_policy(small_problem):
    """C++ matcher implements the same greedy policy as ops/rooms.py —
    assignments must be identical."""
    from timetabling_ga_tpu.ops import rooms as rooms_ops
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(2)
    slots, _ = random_assignment(rng, small_problem, 8)
    native_rooms = native.assign_rooms_batch(small_problem, slots)
    jax_rooms = np.asarray(rooms_ops.batch_assign_rooms(pa, slots))
    np.testing.assert_array_equal(native_rooms, jax_rooms)


@pytest.mark.skipif(not os.path.exists(TT_CPU), reason="tt_cpu not built")
def test_tt_cpu_end_to_end(tmp_path):
    problem = random_instance(77, n_events=20, n_rooms=5, n_features=2,
                              n_students=12, attend_prob=0.1)
    inst = tmp_path / "inst.tim"
    inst.write_text(dump_tim(problem))
    out = subprocess.run(
        [TT_CPU, "-i", str(inst), "-s", "3", "-c", "2",
         "--pop-size", "16", "--generations", "40", "-t", "60"],
        capture_output=True, text=True, timeout=120, check=True)
    lines = [json.loads(x) for x in out.stdout.splitlines()]
    kinds = [next(iter(x)) for x in lines]
    assert kinds.count("solution") == 1
    assert kinds.count("runEntry") == 2
    sol = next(x["solution"] for x in lines if "solution" in x)
    if sol["feasible"]:
        # validate the timetable against the Python oracle
        from timetabling_ga_tpu.oracle import oracle_hcv, oracle_scv
        slots = sol["timeslots"]
        rooms = sol["rooms"]
        assert oracle_hcv(problem, slots, rooms) == 0
        assert oracle_scv(problem, slots) == sol["totalBest"]


@pytest.mark.skipif(not os.path.exists(TT_CPU), reason="tt_cpu not built")
def test_tt_cpu_reference_algo(tmp_path):
    """The reference-faithful baseline (--algo reference: steady-state
    pop-10, exhaustive first-improvement sweep LS, exact per-slot
    matching) runs, reaches feasibility on an easy instance, and its
    reported solution is exact under the Python oracle."""
    problem = random_instance(78, n_events=20, n_rooms=5, n_features=2,
                              n_students=12, attend_prob=0.1)
    inst = tmp_path / "inst.tim"
    inst.write_text(dump_tim(problem))
    out = subprocess.run(
        [TT_CPU, "-i", str(inst), "-s", "3", "-c", "2", "-t", "20",
         "--algo", "reference", "--generations", "200"],
        capture_output=True, text=True, timeout=120, check=True)
    lines = [json.loads(x) for x in out.stdout.splitlines()]
    run = [x["runEntry"] for x in lines if "runEntry" in x]
    assert len(run) == 2
    sol = next(x["solution"] for x in lines if "solution" in x)
    assert sol["feasible"]
    from timetabling_ga_tpu.oracle import oracle_hcv, oracle_scv
    assert oracle_hcv(problem, sol["timeslots"], sol["rooms"]) == 0
    assert oracle_scv(problem, sol["timeslots"]) == sol["totalBest"]
    # logEntry stream is monotone decreasing
    bests = [x["logEntry"]["best"] for x in lines if "logEntry" in x]
    assert bests == sorted(bests, reverse=True)


@pytest.mark.skipif(not os.path.exists(TT_CPU), reason="tt_cpu not built")
@pytest.mark.parametrize("algo", ["memetic", "reference"])
def test_tt_cpu_islands_protocol(tmp_path, algo):
    """tt_cpu --islands N (VERDICT round-2 item 7): N islands in one
    process with ring migration — per-island solution records with
    distinct procIDs, per-island monotone logEntry streams, and a
    correct global runEntry (min over islands), mirroring the reference
    MPI binary's multi-rank output (ga.cpp:169-197, 234-257)."""
    problem = random_instance(79, n_events=20, n_rooms=5, n_features=2,
                              n_students=12, attend_prob=0.1)
    inst = tmp_path / "inst.tim"
    inst.write_text(dump_tim(problem))
    out = subprocess.run(
        [TT_CPU, "-i", str(inst), "-s", "3", "-c", "2", "-t", "60",
         "--islands", "4", "--migration-period", "5",
         "--pop-size", "8", "--generations", "30", "--algo", algo],
        capture_output=True, text=True, timeout=180, check=True)
    lines = [json.loads(x) for x in out.stdout.splitlines()]
    sols = [x["solution"] for x in lines if "solution" in x]
    assert [s["procID"] for s in sols] == [0, 1, 2, 3]
    runs = [x["runEntry"] for x in lines if "runEntry" in x]
    assert len(runs) == 2
    assert runs[1]["procsNum"] == 4
    assert runs[0]["totalBest"] == min(s["totalBest"] for s in sols)
    # per-island logEntry streams are monotone decreasing
    per_island = {}
    for x in lines:
        if "logEntry" in x:
            e = x["logEntry"]
            per_island.setdefault(e["procID"], []).append(e["best"])
    assert set(per_island) <= {0, 1, 2, 3}
    for bests in per_island.values():
        assert bests == sorted(bests, reverse=True)
    # feasible solutions validate under the oracle
    from timetabling_ga_tpu.oracle import oracle_hcv
    for s in sols:
        if s["feasible"]:
            assert oracle_hcv(problem, s["timeslots"], s["rooms"]) == 0


@pytest.mark.slow
def test_sanitized_build_runs_clean_on_fixtures():
    """`make -C native asan` builds the ASan+UBSan-instrumented binary,
    and a short end-to-end solve on each committed fixtures/ instance
    produces ZERO sanitizer reports (leaks included) while still
    emitting the JSONL protocol. Memory bugs in the C++ backend
    (OpenMP races aside) surface here instead of as corrupt fitness
    values in the cross-implementation equality tests above."""
    build = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                            "asan"],
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stdout + build.stderr
    binary = os.path.join(REPO, "native", "tt_cpu_asan")
    assert os.path.exists(binary)

    env = dict(os.environ,
               ASAN_OPTIONS="halt_on_error=1:detect_leaks=1",
               UBSAN_OPTIONS="halt_on_error=1:print_stacktrace=1")
    for fixture in ("comp01s.tim", "comp05s.tim"):
        inst = os.path.join(REPO, "fixtures", fixture)
        out = subprocess.run(
            [binary, "-i", inst, "-s", "3", "-c", "2",
             "--pop-size", "8", "--generations", "5", "-t", "10"],
            capture_output=True, text=True, timeout=300, env=env)
        assert out.returncode == 0, (
            f"{fixture}: sanitized run failed\n{out.stderr[-4000:]}")
        for marker in ("AddressSanitizer", "LeakSanitizer",
                       "runtime error:"):
            assert marker not in out.stderr, (
                f"{fixture}: sanitizer report\n{out.stderr[-4000:]}")
        lines = [json.loads(x) for x in out.stdout.splitlines()]
        kinds = [next(iter(x)) for x in lines]
        assert kinds.count("runEntry") == 2


@pytest.mark.slow
def test_tsan_build_runs_clean_on_fixtures():
    """`make -C native tsan` builds the ThreadSanitizer-instrumented
    binary (carried ROADMAP item: the OpenMP breeding/evaluation loops
    are the one concurrency surface ASan cannot audit) and a short
    end-to-end solve emits the JSONL protocol with no ACTIONABLE race
    reports.

    Toolchain caveat, measured on this box: GCC's libgomp is not
    TSan-instrumented, so TSan cannot observe the happens-before edges
    of OpenMP barriers/joins — a multi-threaded run reports "races"
    between user frames whose synchronization lives entirely inside
    libgomp (e.g. the post-parallel-region sort the implicit barrier
    provably orders; both-stacks-restored variants occur too, so no
    report-shape heuristic separates them from real omp races).
    HONEST COVERAGE on this toolchain is therefore: the multi-threaded
    leg enforces run-completion + protocol and that no report is free
    of libgomp involvement (a race among threads we create directly
    would be); real race enforcement comes from the single-threaded
    control (any report fails) and from toolchains with an
    instrumented OpenMP runtime (clang + archer), where every omp
    report becomes trustworthy and this filter keeps enforcing
    zero."""
    build = subprocess.run(["make", "-C", os.path.join(REPO, "native"),
                            "tsan"],
                           capture_output=True, text=True, timeout=300)
    if build.returncode != 0 and "sanitize" in (build.stdout
                                                + build.stderr):
        pytest.skip("toolchain lacks -fsanitize=thread")
    assert build.returncode == 0, build.stdout + build.stderr
    binary = os.path.join(REPO, "native", "tt_cpu_tsan")
    assert os.path.exists(binary)
    inst = os.path.join(REPO, "fixtures", "comp01s.tim")

    def argv(threads):
        # -c is the binary's OpenMP thread count (num_threads), so
        # the control run must ask for 1 there, not via OMP_NUM_THREADS
        return [binary, "-i", inst, "-s", "3", "-c", str(threads),
                "--pop-size", "8", "--generations", "5", "-t", "10"]

    # control: single-threaded — NO report is environmental here
    env1 = dict(os.environ, TSAN_OPTIONS="exitcode=66")
    out1 = subprocess.run(argv(1), capture_output=True, text=True,
                          timeout=600, env=env1)
    assert out1.returncode == 0, (
        f"single-thread TSan run failed\n{out1.stderr[-4000:]}")
    assert "WARNING: ThreadSanitizer" not in out1.stderr, (
        f"single-thread race report\n{out1.stderr[-4000:]}")
    kinds = [next(iter(json.loads(x)))
             for x in out1.stdout.splitlines()]
    assert kinds.count("runEntry") == 2

    # multi-threaded: on an uninstrumented-libgomp toolchain every
    # report INVOLVING an omp thread is untrustworthy both ways
    # (docstring) — enforce only what remains enforceable: reports
    # with no libgomp involvement at all (races among threads the
    # binary creates directly) fail; everything else is environmental
    env4 = dict(os.environ, TSAN_OPTIONS="exitcode=0")
    out4 = subprocess.run(argv(4), capture_output=True, text=True,
                          timeout=600, env=env4)
    reports = [r for r in out4.stderr.split("==================")
               if "WARNING: ThreadSanitizer" in r]
    real = [r for r in reports if "libgomp" not in r]
    assert not real, (
        f"actionable TSan report(s)\n{real[0][-4000:]}")
    kinds = [next(iter(json.loads(x)))
             for x in out4.stdout.splitlines()]
    assert kinds.count("runEntry") == 2
