"""Island-model tests on the virtual 8-device CPU mesh (SURVEY section
4.4): migration topology, provenance of migrants, pmin global best, and a
multi-island evolution run.

conftest.py forces JAX_PLATFORMS=cpu with
xla_force_host_platform_device_count=8 before jax import, so `make_mesh`
sees 8 devices — the portable stand-in for a v5e-8 slice.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import random_instance


N_ISLANDS = 8
POP = 8


def migrate_sharded(mesh, state):
    """Run islands._migrate under shard_map with the canonical PopState
    sharding (shared by every migration test in this file)."""
    import functools
    from jax.sharding import PartitionSpec as P
    from timetabling_ga_tpu.compat import shard_map

    spec = ga.PopState(slots=P(islands.AXIS), rooms=P(islands.AXIS),
                       penalty=P(islands.AXIS), hcv=P(islands.AXIS),
                       scv=P(islands.AXIS))

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                       out_specs=spec)
    def do_migrate(st):
        return islands._migrate(st, N_ISLANDS)

    return do_migrate(state)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= N_ISLANDS
    return islands.make_mesh(N_ISLANDS)


@pytest.fixture(scope="module")
def island_setup(mesh):
    problem = random_instance(31, n_events=20, n_rooms=5, n_features=2,
                              n_students=12, attend_prob=0.1)
    pa = problem.device_arrays()
    state = islands.init_island_population(
        pa, jax.random.key(0), mesh, POP)
    return problem, pa, state


def test_init_shapes_and_island_independence(island_setup):
    problem, pa, state = island_setup
    assert state.slots.shape == (N_ISLANDS * POP, problem.n_events)
    # islands drew from fold_in(key, i): populations must differ
    blocks = np.asarray(state.slots).reshape(N_ISLANDS, POP, -1)
    assert not np.array_equal(blocks[0], blocks[1])
    # each island block is sorted by penalty (best first)
    pen = np.asarray(state.penalty).reshape(N_ISLANDS, POP)
    assert (np.diff(pen, axis=1) >= 0).all()


def test_migration_topology(island_setup, mesh):
    """Tag each island's best row with a recognizable penalty, run one
    migration, and assert ring provenance: island i's worst slot receives
    island (i-1)'s best, its 2nd-worst receives island (i+1)'s 2nd best
    (ga.cpp:522-535 bidirectional ring)."""
    problem, pa, state = island_setup
    # Give island i best-penalty 1000+i and 2nd-best 2000+i so migrants
    # are identifiable after the exchange. (Penalties are only labels
    # here; _migrate moves rows by penalty order.)
    pen = np.asarray(state.penalty).reshape(N_ISLANDS, POP).copy()
    pen.sort(axis=1)
    for i in range(N_ISLANDS):
        pen[i, 0] = 1000 + i
        pen[i, 1] = 2000 + i
        pen[i, 2:] = 3_000_000 + np.arange(POP - 2)
    state = state._replace(penalty=jnp.asarray(pen.reshape(-1)))

    out = migrate_sharded(mesh, state)
    pen_out = np.asarray(out.penalty).reshape(N_ISLANDS, POP)
    for i in range(N_ISLANDS):
        got = set(pen_out[i].tolist())
        # own best two stay (they were rows 0,1; immigrants replaced the
        # two worst rows before re-sorting)
        assert 1000 + i in got
        assert 2000 + i in got
        # immigrant from previous island's best (forward ring)
        assert 1000 + (i - 1) % N_ISLANDS in got
        # immigrant from next island's second best (backward ring)
        assert 2000 + (i + 1) % N_ISLANDS in got


def test_island_run_and_global_best(island_setup, mesh):
    problem, pa, state = island_setup
    cfg = ga.GAConfig(pop_size=POP)
    runner = islands.make_island_runner(mesh, cfg, n_epochs=3,
                                        gens_per_epoch=5)
    out, trace, global_best = runner(pa, jax.random.key(1), state)
    # per-generation (hcv, scv) best trace: (islands, epochs, gens, 2)
    trace = np.asarray(trace)
    assert trace.shape == (N_ISLANDS, 3, 5, 2)
    # the final trace entry must equal the final population's best row
    hcv = np.asarray(out.hcv).reshape(N_ISLANDS, POP)
    # (migration after the last generation may have imported a better row,
    # so the final best is <= the last pre-migration trace entry)
    assert (hcv[:, 0] <= trace[:, -1, -1, 0]).all()
    # global best == min over islands of local best
    pen = np.asarray(out.penalty).reshape(N_ISLANDS, POP)
    assert int(global_best) == int(pen[:, 0].min())
    # evolution improved or held the best penalty on every island
    pen0 = np.asarray(state.penalty).reshape(N_ISLANDS, POP)
    assert (pen[:, 0] <= pen0[:, 0]).all()


def test_dynamic_runner_gen_count_and_sentinels(island_setup, mesh):
    """The dynamic tail runner (islands.make_island_runner_dynamic) must
    honor its runtime n_gens: trace rows < n_gens are real (hcv, scv)
    pairs, rows >= n_gens stay INT_MAX sentinels, and one compiled
    program serves different n_gens values (no recompilation)."""
    problem, pa, state = island_setup
    cfg = ga.GAConfig(pop_size=POP)
    runner = islands.make_island_runner_dynamic(mesh, cfg, max_gens=10)
    INT_MAX = 2 ** 31 - 1

    st3, tr3, gb3 = runner(pa, jax.random.key(5), state, 3)
    tr3 = np.asarray(tr3).reshape(N_ISLANDS, 10, 2)
    assert (tr3[:, :3] < INT_MAX).all()
    assert (tr3[:, 3:] == INT_MAX).all()

    st10, tr10, gb10 = runner(pa, jax.random.key(5), state, 10)
    tr10 = np.asarray(tr10).reshape(N_ISLANDS, 10, 2)
    assert (tr10 < INT_MAX).all()
    # same key, shared prefix: the first 3 generations of the n_gens=10
    # call follow the identical trajectory as the n_gens=3 call
    assert (tr10[:, :3] == tr3[:, :3]).all()
    # global best is a pmin over islands of final best penalty
    assert int(gb10) <= int(gb3)


def test_dynamic_runner_migrates(island_setup, mesh):
    """The tail dispatch still closes its epoch with ring migration:
    after running it, each island's population contains a row matching
    its neighbor's emigrant (same provenance semantics as the static
    runner's epoch)."""
    problem, pa, state = island_setup
    cfg = ga.GAConfig(pop_size=POP)
    runner = islands.make_island_runner_dynamic(mesh, cfg, max_gens=4)
    st, _, _ = runner(pa, jax.random.key(9), state, 0)
    # n_gens=0: no generations, only migration — state rows must be a
    # permutation of the input rows plus immigrant copies (every row of
    # the output exists somewhere in the input global population)
    inp = np.asarray(state.slots).reshape(-1, problem.n_events)
    outp = np.asarray(st.slots).reshape(-1, problem.n_events)
    inp_set = {r.tobytes() for r in inp}
    for r in outp:
        assert r.tobytes() in inp_set


def test_migration_skipped_for_tiny_population(island_setup, mesh):
    """P < 3 skips migration: a victim row would alias the island's
    BEST row (at P == 1 both writes would destroy its only individual —
    ADVICE round 3). The populations must come through unchanged."""
    problem, pa, _ = island_setup
    for tiny_pop in (1, 2):
        state = islands.init_island_population(
            pa, jax.random.key(5), mesh, tiny_pop)
        out = migrate_sharded(mesh, state)
        assert np.array_equal(np.asarray(out.slots),
                              np.asarray(state.slots))
        assert np.array_equal(np.asarray(out.penalty),
                              np.asarray(state.penalty))


def test_kick_runner_reseeds_worst_half(island_setup, mesh):
    """Stall kick (VERDICT round-4 next #5): the worst half of every
    island becomes mutated copies of its best; the elite half (and in
    particular the island best) is preserved, and the state comes back
    evaluated + sorted."""
    problem, pa, state = island_setup
    cfg = ga.GAConfig(pop_size=POP)
    kick = islands.make_kick_runner(mesh, cfg)
    out = kick(pa, jax.random.key(11), state, 3)
    E = problem.n_events
    in_slots = np.asarray(state.slots).reshape(N_ISLANDS, POP, E)
    in_pen = np.asarray(state.penalty).reshape(N_ISLANDS, POP)
    out_pen = np.asarray(out.penalty).reshape(N_ISLANDS, POP)
    out_scv = np.asarray(out.scv).reshape(N_ISLANDS, POP)
    out_slots = np.asarray(out.slots).reshape(N_ISLANDS, POP, E)
    for i in range(N_ISLANDS):
        # the island best never regresses (elite half untouched)
        assert out_pen[i, 0] <= in_pen[i, 0]
        # sorted by (penalty, scv)
        keys = list(zip(out_pen[i].tolist(), out_scv[i].tolist()))
        assert keys == sorted(keys)
        # elite rows survive: every pre-kick elite row is still present
        out_set = {r.tobytes() for r in out_slots[i]}
        for j in range(POP // 2):
            assert in_slots[i, j].tobytes() in out_set


def test_kick_runner_tiny_population_noop(mesh):
    """P < 2 has no 'worst half'; the kick must be an identity."""
    problem = random_instance(33, n_events=12, n_rooms=4, n_features=2,
                              n_students=8, attend_prob=0.15)
    pa = problem.device_arrays()
    state = islands.init_island_population(pa, jax.random.key(2), mesh, 1)
    cfg = ga.GAConfig(pop_size=1)
    kick = islands.make_kick_runner(mesh, cfg)
    out = kick(pa, jax.random.key(3), state, 3)
    assert np.array_equal(np.asarray(out.slots), np.asarray(state.slots))


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): local-island layout + migration
# ring stay tier-1-covered by test_local_islands_runner_trace_order and
# test_migration_topology
def test_local_islands_init_and_migration(mesh):
    """Local islands (n_islands > device count — the multiple-MPI-ranks-
    per-node analogue): 16 islands on the 8-device mesh (L=2). Init gives
    16 independent sorted populations; one migration preserves the exact
    bidirectional ring provenance over ALL 16 islands, crossing shard
    boundaries via ppermute and local-island boundaries via rolls."""
    import functools
    from jax.sharding import PartitionSpec as P
    from timetabling_ga_tpu.compat import shard_map

    NI = 2 * N_ISLANDS
    problem = random_instance(31, n_events=20, n_rooms=5, n_features=2,
                              n_students=12, attend_prob=0.1)
    pa = problem.device_arrays()
    state = islands.init_island_population(
        pa, jax.random.key(0), mesh, POP, n_islands=NI)
    assert state.slots.shape == (NI * POP, problem.n_events)
    blocks = np.asarray(state.slots).reshape(NI, POP, -1)
    for i in range(NI - 1):
        assert not np.array_equal(blocks[i], blocks[i + 1])
    pen = np.asarray(state.penalty).reshape(NI, POP)
    assert (np.diff(pen, axis=1) >= 0).all()   # per-island sorted

    pen = pen.copy()
    for i in range(NI):
        pen[i, 0] = 1000 + i
        pen[i, 1] = 2000 + i
        pen[i, 2:] = 3_000_000 + np.arange(POP - 2)
    state = state._replace(penalty=jnp.asarray(pen.reshape(-1)))

    spec = ga.PopState(slots=P(islands.AXIS), rooms=P(islands.AXIS),
                       penalty=P(islands.AXIS), hcv=P(islands.AXIS),
                       scv=P(islands.AXIS))

    @functools.partial(shard_map, mesh=mesh, in_specs=(spec,),
                       out_specs=spec)
    def do_migrate(st):
        return islands._migrate(st, NI, L=2)

    out = do_migrate(state)
    pen_out = np.asarray(out.penalty).reshape(NI, POP)
    for i in range(NI):
        got = set(pen_out[i].tolist())
        assert 1000 + i in got and 2000 + i in got
        assert 1000 + (i - 1) % NI in got     # forward ring
        assert 2000 + (i + 1) % NI in got     # backward ring


def test_local_islands_runner_trace_order(mesh):
    """The island-major trace layout holds for L>1: runner trace rows
    [d*L, (d+1)*L) belong to device d's local islands, and each equals
    that island's best (hcv, scv) after the last generation (modulo the
    final migration, which can only improve a best row)."""
    NI = 2 * N_ISLANDS
    problem = random_instance(37, n_events=16, n_rooms=4, n_features=2,
                              n_students=10, attend_prob=0.15)
    pa = problem.device_arrays()
    state = islands.init_island_population(
        pa, jax.random.key(1), mesh, POP, n_islands=NI)
    cfg = ga.GAConfig(pop_size=POP)
    runner = islands.make_island_runner(mesh, cfg, n_epochs=2,
                                        gens_per_epoch=3, n_islands=NI)
    out, trace, global_best = runner(pa, jax.random.key(2), state)
    trace = np.asarray(trace)
    assert trace.shape == (NI, 2, 3, 2)
    hcv = np.asarray(out.hcv).reshape(NI, POP)
    pen = np.asarray(out.penalty).reshape(NI, POP)
    assert (hcv[:, 0] <= trace[:, -1, -1, 0]).all()
    assert int(global_best) == int(pen[:, 0].min())
