"""tt-accord (ISSUE 18): the multi-host control side channel.

The LoopbackChannel fault matrix runs the FULL agreement protocol —
process-0-wins fences, pre-collective guards, fault-recovery consensus,
heartbeat expiry, disagreeing-verdict merges — as N channel views over
one in-process store on single-process CPU, so every recovery-agreement
path is tier-1. The slow 2-process subprocess e2e then kills a real
peer mid-run (`dispatch@1:2:die`) and pins the acceptance: the survivor
classifies PeerLost within --peer-timeout instead of hanging at the
dead peer's collective, aborts with a final durable checkpoint, and a
resumed rerun's stream matches an uninjected run's modulo timing/fault
records. Single-process, the channel is inert: record streams are
identical with accord on or off (modulo timing, like every A/B).
"""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import control_channel as cc
from timetabling_ga_tpu.runtime import faults, jsonl, retry
from timetabling_ga_tpu.runtime.config import RunConfig

# ----------------------------------------------------------- verdict merge


def test_merge_verdicts_lowest_pid_real_site_wins():
    agreed = cc.merge_verdicts([
        {"proc": 1, "site": "dispatch", "action": "recover", "gens": 10},
        {"proc": 0, "site": "accord", "action": "recover", "gens": 10},
    ])
    # the flag-observer (site 'accord') defers to the process that saw
    # the real error, regardless of pid order
    assert agreed["site"] == "dispatch" and agreed["decider"] == 1
    assert agreed["agreed"] is True and agreed["procs"] == [0, 1]


def test_merge_verdicts_abort_wins():
    agreed = cc.merge_verdicts([
        {"proc": 0, "site": "dispatch", "action": "recover", "gens": 5},
        {"proc": 1, "site": "fetch", "action": "abort", "gens": 5},
    ])
    # a budget-exhausted (or lost) process must never be outvoted into
    # a retry its state cannot survive
    assert agreed["action"] == "abort" and agreed["decider"] == 1
    # two real recover sites, no abort: lowest pid decides
    agreed = cc.merge_verdicts([
        {"proc": 1, "site": "fetch", "action": "recover", "gens": 5},
        {"proc": 0, "site": "dispatch", "action": "recover", "gens": 5},
    ])
    assert agreed["site"] == "dispatch" and agreed["decider"] == 0
    with pytest.raises(ValueError):
        cc.merge_verdicts([])


# --------------------------------------------------------- solo / registry


def test_solo_channel_is_inert():
    ch = cc.LoopbackChannel.solo()
    try:
        assert ch._hb_thread is None          # no heartbeat thread
        assert ch.agree("s", [3, 7]) == [3, 7]
        ch.guard_collective()                 # no-op, returns
        agreed = ch.agree_on_fault(
            {"site": "dispatch", "action": "recover", "gens": 10})
        assert agreed["site"] == "dispatch" and agreed["decider"] == 0
    finally:
        ch.close()


def test_open_channel_gates():
    # --no-accord: no channel at all
    assert cc.open_channel(accord=False) is None
    # single-process: the inert solo loopback
    ch = cc.open_channel(accord=True)
    try:
        assert isinstance(ch, cc.LoopbackChannel) and ch.nproc == 1
    finally:
        ch.close()
    # the registry round-trip dispatch_core.fetch guards through
    assert cc.active() is None
    try:
        assert cc.install(ch) is ch and cc.active() is ch
    finally:
        cc.install(None)
    assert cc.active() is None


# ------------------------------------------------- the loopback fault matrix


def _join(threads, results, timeout=30.0):
    """Join worker threads and re-raise the first captured failure."""
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "accord protocol thread hung"
    for r in results:
        if isinstance(r, BaseException):
            raise r


def _spawn(fn, *args):
    """Run fn(*args) on a thread, capturing result or exception."""
    box = [None]

    def run():
        try:
            box[0] = fn(*args)
        except BaseException as e:        # noqa: BLE001 — re-raised
            box[0] = e
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, box


def test_agree_is_process0_wins():
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        t, box = _spawn(ch1.agree, "s", [0, 0])     # p1's local values
        assert ch0.agree("s", [5, 9]) == [5, 9]     # p0 never blocks
        _join([t], [])
        assert box[0] == [5, 9]                     # p1 adopted p0's
        # the per-tag fence counter advances: a second fence is fresh
        t, box = _spawn(ch1.agree, "s", None)
        assert ch0.agree("s", [1]) == [1]
        _join([t], [])
        assert box[0] == [1]
    finally:
        ch0.close(), ch1.close()


def test_guard_collective_rendezvous():
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        t, box = _spawn(ch1.guard_collective)
        ch0.guard_collective()
        _join([t], box)
    finally:
        ch0.close(), ch1.close()


def test_one_sees_fault_peer_joins_agreement():
    """The asymmetric case: p1 is healthy and waiting at a collective
    guard when p0 faults. The fault flag converts p1's wait into
    AccordPeerFault (transient), p1 joins the agreement as a deferring
    observer (site 'accord'), and both adopt p0's verdict — then the
    bumped epoch lets post-recovery fences run fresh."""
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        guard_box = [None]

        def p1_side():
            try:
                ch1.guard_collective()
            except cc.AccordPeerFault as e:
                guard_box[0] = e
                return ch1.agree_on_fault(
                    {"site": "accord", "action": "recover", "gens": 10})
            raise AssertionError("guard passed with a faulted peer")

        t1, box1 = _spawn(p1_side)
        time.sleep(0.1)                 # let p1 reach the guard wait
        agreed0 = ch0.agree_on_fault(
            {"site": "dispatch", "action": "recover", "gens": 10})
        _join([t1], box1)
        assert isinstance(guard_box[0], cc.AccordPeerFault)
        assert retry.is_transient(guard_box[0])
        # identical agreement on both processes: p0's real site won
        assert agreed0 == box1[0]
        assert agreed0["site"] == "dispatch"
        assert agreed0["action"] == "recover" and agreed0["decider"] == 0
        # epoch bumped in lockstep — replayed fences use fresh keys
        assert ch0.epoch == 1 and ch1.epoch == 1
        t1, box1 = _spawn(ch1.guard_collective)
        ch0.guard_collective()
        _join([t1], box1)
    finally:
        ch0.close(), ch1.close()


def test_both_see_fault_disagreeing_verdicts_merge_to_one():
    """Both processes fault in the same window with DIFFERENT local
    verdicts (different sites): both enter agreement concurrently, the
    flag double-write is benign, and the merge is identical on both —
    lowest-pid real site."""
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        t1, box1 = _spawn(
            ch1.agree_on_fault,
            {"site": "fetch", "action": "recover", "gens": 10})
        agreed0 = ch0.agree_on_fault(
            {"site": "dispatch", "action": "recover", "gens": 10})
        _join([t1], box1)
        assert agreed0 == box1[0]
        assert agreed0["site"] == "dispatch" and agreed0["decider"] == 0
    finally:
        ch0.close(), ch1.close()


def test_abort_verdict_wins_agreement():
    """A budget-exhausted process's abort outvotes the peer's recover:
    both adopt the clean abort (the engine then writes the final
    durable checkpoint and re-raises — never a hang)."""
    ch0, ch1 = cc.LoopbackChannel.group(2)
    try:
        t1, box1 = _spawn(
            ch1.agree_on_fault,
            {"site": "dispatch", "action": "abort", "gens": 10})
        agreed0 = ch0.agree_on_fault(
            {"site": "dispatch", "action": "recover", "gens": 10})
        _join([t1], box1)
        assert agreed0 == box1[0]
        assert agreed0["action"] == "abort" and agreed0["decider"] == 1
    finally:
        ch0.close(), ch1.close()


def test_dead_peer_heartbeat_expiry_at_guard():
    """The liveness conversion: a peer whose heartbeat went silent past
    --peer-timeout raises PeerLost at the guard (NOT transient — the
    process is gone) instead of waiting forever at the collective the
    peer will never join."""
    ch0, ch1 = cc.LoopbackChannel.group(2, peer_timeout=0.5)
    try:
        ch1.kill()                      # p1's process "dies"
        t0 = time.monotonic()
        with pytest.raises(cc.PeerLost) as ei:
            ch0.guard_collective()
        wall = time.monotonic() - t0
        assert ei.value.proc == 1 and ei.value.silence_s > 0.5
        assert wall < 10.0              # bounded, not a hang
        assert not retry.is_transient(ei.value)
    finally:
        ch0.close(), ch1.close()


def test_peer_lost_mid_agreement_is_an_abort_vote():
    """A peer that dies DURING fault agreement contributes a
    synthesized abort verdict instead of raising — its death IS a
    vote, and abort wins the merge."""
    ch0, ch1 = cc.LoopbackChannel.group(2, peer_timeout=0.5)
    try:
        ch1.kill()
        agreed = ch0.agree_on_fault(
            {"site": "dispatch", "action": "recover", "gens": 10})
        assert agreed["action"] == "abort" and agreed["decider"] == 1
        assert agreed.get("lost") is True and agreed["site"] == "accord"
    finally:
        ch0.close(), ch1.close()


def test_peer_timeout_zero_waits_forever():
    """--peer-timeout 0 disables liveness classification: the guard
    keeps waiting (here until the peer actually arrives)."""
    ch0, ch1 = cc.LoopbackChannel.group(2, peer_timeout=0.0)
    try:
        ch1.kill()                      # silence alone must not expire
        t0, box0 = _spawn(ch0.guard_collective)
        time.sleep(0.8)
        assert t0.is_alive()            # still waiting, not PeerLost
        ch1.guard_collective()          # late arrival completes it
        _join([t0], box0)
    finally:
        ch0.close(), ch1.close()


# ---------------------------------------------------- fault-plan @proc scope


def test_fault_plan_process_scoping():
    """`site@proc` entries parse away on every other process, and
    UNSCOPED entries apply to process 0 only under a multi-process
    launch — one shared TT_FAULTS value, per-process stable indices."""
    spec = "dispatch@1:2:die,dispatch@0:1:hang,fetch:1:error"
    try:
        faults.set_process(1, 2)
        plan = faults.FaultPlan.parse(spec)
        assert plan.pop_action("dispatch") is None      # @0: not ours
        assert plan.pop_action("dispatch") == "die"     # @1 entry
        assert plan.pop_action("fetch") is None         # unscoped -> p0
        faults.set_process(0, 2)
        plan = faults.FaultPlan.parse(spec)
        assert plan.pop_action("dispatch") == "hang"    # @0 entry
        assert plan.pop_action("dispatch") is None      # @1: not ours
        assert plan.pop_action("fetch") == "error"      # unscoped = p0
        # single-process (the default): @0 is equivalent to unscoped
        faults.set_process(0, 1)
        plan = faults.FaultPlan.parse("dispatch@0:1:die,fetch:1:hang")
        assert plan.pop_action("dispatch") == "die"
        assert plan.pop_action("fetch") == "hang"
        with pytest.raises(faults.FaultPlanError):
            faults.FaultPlan.parse("dispatch@x:1:die")
        with pytest.raises(faults.FaultPlanError):
            faults.FaultPlan.parse("dispatch@-1:1:die")
    finally:
        faults.set_process(0, 1)


# ------------------------------------------- single-process A/B (channel off)


@pytest.fixture(scope="module")
def tim_file(tmp_path_factory):
    problem = random_instance(55, n_events=15, n_rooms=5, n_features=2,
                              n_students=10, attend_prob=0.1)
    path = tmp_path_factory.mktemp("accord") / "tiny.tim"
    path.write_text(dump_tim(problem))
    return str(path)


def _go(tim_file, **kw):
    from timetabling_ga_tpu.runtime import engine
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    trace=True, **kw)
    best = engine.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def test_single_process_stream_identical_accord_on_off(tim_file):
    """ISSUE 18 acceptance: single-process record streams are identical
    with the channel on (the inert solo loopback) or off (--no-accord)
    — the channel adds fields only under a real multi-host agreement."""
    best_on, on = _go(tim_file)                    # accord defaults True
    best_off, off = _go(tim_file, accord=False)
    assert best_on == best_off
    assert jsonl.strip_timing(on) == jsonl.strip_timing(off)
    # and recovery through the solo channel stays free of accord fields
    best_f, lines = _go(tim_file, faults="dispatch:2:unavailable")
    fe = [x["faultEntry"] for x in lines if "faultEntry" in x]
    assert [e["action"] for e in fe] == ["recover"]
    assert "agreed" not in fe[0] and "proc" not in fe[0]
    assert best_f == best_on
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(on)


# ------------------------------------------------------ 2-process kill e2e


@pytest.mark.slow
def test_two_process_peer_death_agreed_abort_and_resume(tim_file,
                                                        tmp_path):
    """The acceptance e2e: a REAL 2-process jax.distributed run where
    `dispatch@1:2:die` kills process 1 mid-run. The survivor must NOT
    hang at the dead peer's next collective: its channel guard
    classifies PeerLost within --peer-timeout, emits the abort
    faultEntry (lostProc=1), leaves a final durable checkpoint from
    the last agreed fence, and exits. A fresh 2-process rerun resuming
    that checkpoint then matches an uninjected run's stream modulo
    timing/fault records."""
    import socket
    import subprocess
    import sys as _sys

    def run_pair(outfile, ckfile, tt_faults=None, resume=False):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]

        def proc(pid):
            env = dict(
                os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4")
            env.pop("TT_FAULTS", None)
            if tt_faults:
                env["TT_FAULTS"] = tt_faults   # ONE shared value: the
                #                                @proc scope picks who
            args = [_sys.executable, "-m", "timetabling_ga_tpu.cli",
                    "-i", tim_file, "-s", "9", "--backend", "cpu",
                    "--coordinator", f"localhost:{port}",
                    "--num-processes", "2", "--process-id", str(pid),
                    "--pop-size", "4", "--generations", "20",
                    "--migration-period", "5", "--no-auto-tune",
                    "--ls-mode", "sweep", "--ls-sweeps", "1",
                    "-m", "8", "-t", "600", "--no-precompile",
                    "--peer-timeout", "8",
                    "--checkpoint", ckfile, "--checkpoint-every", "1"]
            if resume:
                args += ["--resume"]
            if pid == 0:
                args += ["-o", outfile]
            return subprocess.Popen(args, env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE, text=True)

        p0, p1 = proc(0), proc(1)
        out0, err0 = p0.communicate(timeout=600)   # bounded = no hang
        out1, err1 = p1.communicate(timeout=120)
        return (p0.returncode, err0), (p1.returncode, err1)

    clean_out = str(tmp_path / "clean.jsonl")
    fault_out = str(tmp_path / "fault.jsonl")
    resume_out = str(tmp_path / "resume.jsonl")
    clean_ck = str(tmp_path / "clean.npz")
    fault_ck = str(tmp_path / "fault.npz")

    # 1) uninjected baseline
    (rc0, err0), (rc1, err1) = run_pair(clean_out, clean_ck)
    assert rc0 == 0, err0[-3000:]
    assert rc1 == 0, err1[-3000:]

    # 2) kill process 1's second dispatch: the survivor classifies
    #    PeerLost at its next channel guard and aborts — both
    #    processes EXIT (communicate() returning at all is the no-hang
    #    assertion), neither cleanly
    (rc0, err0), (rc1, err1) = run_pair(fault_out, fault_ck,
                                        tt_faults="dispatch@1:2:die")
    assert rc1 != 0                      # the injected SystemExit
    assert rc0 != 0 and "lost contact with process 1" in err0, \
        err0[-3000:]
    lines = [json.loads(x) for x in open(fault_out)]
    fe = [x["faultEntry"] for x in lines if "faultEntry" in x]
    assert fe and fe[-1]["site"] == "accord"
    assert fe[-1]["action"] == "abort" and fe[-1]["lostProc"] == 1
    assert fe[-1]["agreed"] is False and fe[-1]["proc"] == 0
    # the final durable checkpoint from the last agreed fence (gen 5:
    # process 1 died entering chunk 2 of 5-generation chunks)
    with np.load(fault_ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 5
        assert z["slots"].shape[0] == 8 * 4     # GLOBAL population

    # 3) rerun resuming the abort checkpoint: completes, and the
    #    stream's protocol core (solutions + runEntries) matches the
    #    uninjected run's modulo timing — the determinism contract
    #    across the death. logEntry improvement floors reset per
    #    incarnation by design (a resumed run re-announces its current
    #    best), so the cross-incarnation comparison is over the
    #    solution/runEntry records.
    (rc0, err0), (rc1, err1) = run_pair(resume_out, fault_ck,
                                        resume=True)
    assert rc0 == 0, err0[-3000:]
    assert rc1 == 0, err1[-3000:]

    def core(path):
        recs = [json.loads(x) for x in open(path)]
        return jsonl.strip_timing(
            [r for r in recs if "solution" in r or "runEntry" in r])

    assert core(resume_out) == core(clean_out)
