"""ITC-2002-style fixture instances (VERDICT round-2 item 3).

The repo vendors two characterized stand-ins for the competition set
(`fixtures/comp01s.tim`, `fixtures/comp05s.tim`) built by
`problem.itc_like_instance`, which plants a perfect solution the way the
competition generator did (every real comp instance admits a feasible,
scv=0 timetable). These tests pin (a) the loader parses the committed
files (Problem.cpp:7-31 format), (b) the generator's planted witness is
exactly zero-penalty, (c) the fixture stats stay in the published
competition band (events 350-440, rooms 10-11, features 5-10, students
200-350, 45 slots).
"""

import os

import numpy as np
import pytest

from timetabling_ga_tpu.oracle.reference_oracle import (
    oracle_hcv, oracle_scv)
from timetabling_ga_tpu.problem import (
    ITC_PRESETS, itc_like_instance, load_tim_file)

FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "fixtures")


@pytest.mark.parametrize("name", sorted(ITC_PRESETS))
def test_fixture_parses_and_matches_preset(name):
    p = load_tim_file(os.path.join(FIXTURES, f"{name}s.tim"))
    want = ITC_PRESETS[name]
    assert p.n_events == want["n_events"]
    assert p.n_rooms == want["n_rooms"]
    assert p.n_features == want["n_features"]
    assert p.n_students == want["n_students"]
    assert p.n_slots == 45
    # competition character: every event placeable, suitability scarce
    suit = p.possible.sum(axis=1)
    assert suit.min() >= 1
    assert np.median(suit) <= 6


@pytest.mark.parametrize("name", sorted(ITC_PRESETS))
def test_planted_solution_is_perfect(name):
    p, slots, rooms = itc_like_instance(
        2002 + int(name[-2:]), **ITC_PRESETS[name], return_planted=True)
    assert oracle_hcv(p, slots, rooms) == 0
    assert oracle_scv(p, slots, rooms) == 0


@pytest.mark.parametrize("name", sorted(ITC_PRESETS))
def test_planted_witness_in_committed_fixture(name):
    """The committed fixture BYTES admit a perfect solution: the planted
    witness is committed alongside each .tim (fixtures/*.witness.json)
    and must evaluate to exactly zero under the reference-semantics
    oracle on the loaded file. (Deliberately not a byte-identity check
    against the generator: NumPy Generator streams may change across
    feature releases, NEP 19 — the committed witness keeps the guarantee
    pinned to the committed bytes.)"""
    import json
    p = load_tim_file(os.path.join(FIXTURES, f"{name}s.tim"))
    with open(os.path.join(FIXTURES, f"{name}s.witness.json")) as fh:
        w = json.load(fh)
    assert oracle_hcv(p, w["slots"], w["rooms"]) == 0
    assert oracle_scv(p, w["slots"], w["rooms"]) == 0


def test_planted_witness_survives_sparse_cells():
    """With far fewer events than (slot, room) cells, many usable slots
    host no event; student patterns must still avoid single-class days
    (the empty-slot silent-skip bug found in round-3 review)."""
    p, slots, rooms = itc_like_instance(
        9, n_events=100, n_rooms=10, n_features=5, n_students=50,
        return_planted=True)
    assert oracle_hcv(p, slots, rooms) == 0
    assert oracle_scv(p, slots, rooms) == 0
