"""Room-matching kernel properties (ops/rooms.py vs reference semantics).

The reference's assignRooms (Solution.cpp:772-833) guarantees: matched
events get a suitable room each; unmatched events fall back to the
least-busy suitable room. Our greedy kernel must (a) always pick suitable
rooms when any exist, (b) produce clash-free assignments whenever rooms
are plentiful, (c) never do worse than random assignment on room-hcv.
"""

import numpy as np

from timetabling_ga_tpu.ops import rooms
from timetabling_ga_tpu.problem import derive, random_instance
from tests.conftest import random_assignment


def _room_hcv_parts(problem, slots, rooms_arr):
    """(pair clashes, unsuitable count) for one solution, scalar oracle."""
    clash = 0
    e = problem.n_events
    for i in range(e):
        for j in range(i + 1, e):
            if slots[i] == slots[j] and rooms_arr[i] == rooms_arr[j]:
                clash += 1
    unsuit = sum(1 for i in range(e) if not problem.possible[i][rooms_arr[i]])
    return clash, unsuit


def test_always_suitable_when_possible():
    problem = random_instance(5, n_events=40, n_rooms=6, n_features=3,
                              n_students=25, attend_prob=0.1)
    rng = np.random.default_rng(0)
    slots, _ = random_assignment(rng, problem, 8)
    pa = problem.device_arrays()
    assigned = np.asarray(rooms.batch_assign_rooms(pa, slots))
    for p in range(8):
        for e in range(problem.n_events):
            if problem.possible[e].any():
                assert problem.possible[e][assigned[p, e]], (p, e)


def test_clash_free_when_rooms_plentiful():
    """Every event fits every room and there are more rooms than events
    per slot -> greedy matching must produce zero room clashes."""
    n_events, n_rooms = 12, 12
    attends = np.zeros((3, n_events), dtype=np.int8)
    problem = derive(n_events, n_rooms, 1, 3,
                     room_size=np.full(n_rooms, 100, np.int32),
                     attends=attends,
                     room_features=np.ones((n_rooms, 1), np.int8),
                     event_features=np.zeros((n_events, 1), np.int8))
    pa = problem.device_arrays()
    rng = np.random.default_rng(1)
    slots = rng.integers(0, problem.n_slots,
                         size=(16, n_events)).astype(np.int32)
    assigned = np.asarray(rooms.batch_assign_rooms(pa, slots))
    for p in range(16):
        clash, unsuit = _room_hcv_parts(problem, slots[p], assigned[p])
        assert clash == 0, p
        assert unsuit == 0, p


def test_matching_near_exact_lower_bound():
    """The matcher-attributable hcv (pair clashes + unsuitable rooms) of
    the cost-greedy matcher must stay within 2% of the EXACT lower bound
    (per-slot Hopcroft-Karp matching deficiency) on room-TIGHT instances
    — the regime where the round-1 greedy lost 60%+ of slots. This is
    the quality evidence VERDICT item 3 demanded: we beat the
    reference's own unmatched fallback (which stacks surplus events into
    the least-busy suitable room, Solution.cpp:814-830) by parking at
    marginal hcv cost instead."""
    from timetabling_ga_tpu.oracle import matching as M
    from timetabling_ga_tpu.problem import room_tight_instance

    total_got, total_lb = 0, 0
    for seed in (11, 23):
        problem = room_tight_instance(seed, n_events=200, n_rooms=10,
                                      n_features=5, n_students=180,
                                      attend_prob=0.05)
        pa = problem.device_arrays()
        rng = np.random.default_rng(seed)
        slots = rng.integers(0, problem.n_slots,
                             size=(8, 200)).astype(np.int32)
        import jax.numpy as jnp
        matched = np.asarray(rooms.batch_assign_rooms(pa,
                                                      jnp.asarray(slots)))
        for i in range(8):
            total_lb += M.room_hcv_lower_bound(problem, slots[i])
            total_got += M.assignment_room_hcv(problem, slots[i],
                                               matched[i])
    assert total_got <= total_lb * 1.02, (total_got, total_lb)


def test_parallel_assign_rooms_quality():
    """The O(1)-depth parallel matcher (best-fit init + bounded
    augmentation + cost parking) must stay within 15% of the exact lower
    bound on room-tight instances, and be exactly clash-free where rooms
    are plentiful."""
    from timetabling_ga_tpu.oracle import matching as M
    from timetabling_ga_tpu.problem import room_tight_instance
    import jax.numpy as jnp

    problem = room_tight_instance(11, n_events=200, n_rooms=10,
                                  n_features=5, n_students=180,
                                  attend_prob=0.05)
    pa = problem.device_arrays()
    rng = np.random.default_rng(2)
    slots = rng.integers(0, problem.n_slots, size=(8, 200)).astype(np.int32)
    par = np.asarray(rooms.batch_parallel_assign_rooms(
        pa, jnp.asarray(slots), n_rounds=4))
    got = sum(M.assignment_room_hcv(problem, slots[i], par[i])
              for i in range(8))
    lb = sum(M.room_hcv_lower_bound(problem, slots[i]) for i in range(8))
    assert got <= lb * 1.15, (got, lb)


def test_hopcroft_karp_matches_bruteforce():
    """The exact-matching oracle itself, checked against exhaustive
    search on small random bipartite graphs."""
    import itertools
    from timetabling_ga_tpu.oracle.matching import hopcroft_karp

    rng = np.random.default_rng(4)
    for _ in range(30):
        n_l, n_r = int(rng.integers(1, 7)), int(rng.integers(1, 6))
        adj_m = rng.random((n_l, n_r)) < 0.4
        adj = [np.nonzero(adj_m[i])[0].tolist() for i in range(n_l)]
        got = sum(1 for m in hopcroft_karp(adj, n_r) if m >= 0)
        # brute force: every injective partial assignment
        best = 0
        for choice in itertools.product(*[a + [-1] for a in adj]):
            used = [c for c in choice if c >= 0]
            if len(used) == len(set(used)):
                best = max(best, len(used))
        assert got == best


def test_occupancy_counts():
    problem = random_instance(7, n_events=20, n_rooms=4, n_features=2,
                              n_students=10)
    pa = problem.device_arrays()
    rng = np.random.default_rng(3)
    slots, rms = random_assignment(rng, problem, 1)
    occ = np.asarray(rooms.occupancy(pa, slots[0], rms[0]))
    assert occ.sum() == problem.n_events
    for e in range(problem.n_events):
        assert occ[slots[0][e], rms[0][e]] >= 1


def test_choose_room_prefers_free_suitable():
    """Single-event insert: picks a free suitable room (best capacity fit)
    over a busy one, and the fallback is least-busy suitable."""
    # 1 event, 3 rooms: room0 too small, room1 fits (cap 10), room2 fits
    # (cap 50). Best-fit => room1 when free.
    attends = np.ones((5, 1), dtype=np.int8)  # event has 5 students
    problem = derive(1, 3, 1, 5, room_size=np.array([2, 10, 50]),
                     attends=attends,
                     room_features=np.ones((3, 1), np.int8),
                     event_features=np.zeros((1, 1), np.int8))
    pa = problem.device_arrays()
    free = np.zeros(3, np.int32)
    assert int(rooms.choose_room(pa, free, np.int32(0))) == 1
    # room1 busy -> still prefer free suitable room2 over busy room1
    busy1 = np.array([0, 1, 0], np.int32)
    assert int(rooms.choose_room(pa, busy1, np.int32(0))) == 2
    # both suitable rooms busy -> least busy of them
    busy = np.array([0, 2, 1], np.int32)
    assert int(rooms.choose_room(pa, busy, np.int32(0))) == 2
