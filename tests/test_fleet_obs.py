"""tt-obs v5 (ISSUE 11): the fleet observatory — cross-process flow
tracing, gateway /metrics parity, SLO-burn readiness.

The acceptance properties pinned here:

  1. CROSS-PROCESS FLOWS — a routed job's gateway spans (route /
     submit / routed / settle) and its replica-side spans (admit /
     pack / quantum / ...) share ONE flow id from the XFLOW_BASE range
     (shipped as X-TT-Flow), and `export_stitched` over gateway +
     replica logs renders one timeline whose flow chain crosses the
     process boundary;
  2. /METRICS PARITY — everything /v1/fleet shows is a real registry
     family on the gateway's port (per-replica gauges, routing
     counters, tick timing, job_seconds exemplars), parsed by the one
     shared OpenMetrics parser (obs/scrape.py);
  3. READINESS — the gateway answers the pinned /readyz JSON contract
     with the new `slo_burn` and `dispatcher_stalled` reasons;
  4. ISOLATION — a dead gateway log writer (`gw_writer`) or a hung
     replica scrape (`gw_scrape`) never stalls the dispatcher thread
     or job settlement;
  5. IDENTITY — with the gateway's telemetry stream ON, every routed
     job's record stream stays bit-identical (modulo timing records)
     to the same job solved on a bare unrouted SolveService.
"""

import io
import json
import time
import urllib.error
import urllib.request

import pytest

from timetabling_ga_tpu.fleet.gateway import Gateway
from timetabling_ga_tpu.fleet.replicas import (
    ReplicaHandle, http_json, http_text, in_process_replica)
from timetabling_ga_tpu.fleet.router import Router
from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import scrape as obs_scrape
from timetabling_ga_tpu.obs.logstats import summarize
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.obs.spans import XFLOW_BASE
from timetabling_ga_tpu.obs.trace_export import export_stitched
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args)
from timetabling_ga_tpu.serve.service import SolveService

_SHAPE = dict(n_events=12, n_rooms=3, n_features=2, n_students=8,
              attend_prob=0.2)


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    kw.setdefault("http", "127.0.0.1:0")
    return ServeConfig(**kw)


def _fleet_cfg(urls, **kw):
    kw.setdefault("listen", "127.0.0.1:0")
    kw.setdefault("probe_every", 0.1)
    kw.setdefault("poll_every", 0.05)
    kw.setdefault("dead_after", 2)
    return FleetConfig(replicas=list(urls), **kw)


def _wait_done(gw, ids, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with gw.jobs_lock:
            if all(j in gw.jobs and gw.jobs[j].terminal()
                   and gw.jobs[j].records_final for j in ids):
                return {j: gw.jobs[j] for j in ids}
        time.sleep(0.05)
    with gw.jobs_lock:
        states = {j: getattr(gw.jobs.get(j), "state", "?")
                  for j in ids}
    raise AssertionError(f"jobs not settled after {timeout}s: {states}")


def _records(buf) -> list:
    return [json.loads(ln) for ln in buf.getvalue().splitlines()]


def _spans(recs, **match):
    out = []
    for r in recs:
        s = r.get("spanEntry")
        if s is None:
            continue
        if all(s.get(k) == v for k, v in match.items()):
            out.append(s)
    return out


# ----------------------------------------------------- scrape parser


def test_scrape_parser_families_and_exemplars():
    text = (
        "# TYPE tt_serve_queue_depth gauge\n"
        "tt_serve_queue_depth 3\n"
        "# TYPE tt_compile_count counter\n"
        "tt_compile_count_total 4\n"
        "tt_compile_cache_hits_total 12\n"
        "# TYPE tt_fleet_job_seconds histogram\n"
        'tt_fleet_job_seconds_bucket{le="0.5"} 0\n'
        'tt_fleet_job_seconds_bucket{le="+Inf"} 2 '
        '# {job="j 1"} 0.93\n'
        "tt_fleet_job_seconds_sum 1.5\n"
        "tt_fleet_job_seconds_count 2\n"
        'weird{label="a\\"b\\\\c"} 7\n'
        "not a sample line at all\n"
        "# EOF\n")
    fams = obs_scrape.parse_exposition(text)
    assert obs_scrape.scalar(fams, obs_scrape.QUEUE_DEPTH) == 3.0
    assert obs_scrape.scalar(fams, obs_scrape.COMPILE_COUNT) == 4.0
    assert obs_scrape.scalar(fams, "missing", 9.0) == 9.0
    assert obs_scrape.hit_rate(fams) == pytest.approx(12 / 16)
    # labeled lookup + exemplar-bearing line parses to its VALUE
    assert obs_scrape.labeled(fams, "tt_fleet_job_seconds_bucket",
                              le="+Inf") == 2.0
    # escaped label values round-trip
    assert fams["weird"][0][0]["label"] == 'a"b\\c'
    assert fams["weird"][0][1] == 7.0
    # empty/garbage degrade to empty dict, never raise
    assert obs_scrape.parse_exposition("") == {}
    assert obs_scrape.hit_rate({}) == 0.0
    # exemplars come out of the SAME parser (one copy of the format
    # knowledge — tools/bench_report.py --metrics consumes this)
    ex = obs_scrape.parse_exemplars(text)
    assert ex == [("tt_fleet_job_seconds_bucket", {"job": "j 1"},
                   0.93)]
    assert obs_scrape.parse_exemplars("") == []


def test_scrape_parses_real_registry_exposition():
    reg = MetricsRegistry()
    reg.counter("compile.count").inc(2)
    reg.gauge("serve.queue_depth").set(5)
    reg.histogram("fleet.job_seconds").observe(
        0.3, exemplar={"job": "j1"})
    for text in (reg.to_prometheus(), reg.to_openmetrics()):
        fams = obs_scrape.parse_exposition(text)
        assert obs_scrape.scalar(fams, "tt_compile_count_total") == 2.0
        assert obs_scrape.scalar(fams, obs_scrape.QUEUE_DEPTH) == 5.0
        assert obs_scrape.labeled(
            fams, "tt_fleet_job_seconds_bucket", le="+Inf") == 1.0


# ----------------------------------------------- router /metrics unit


class _FakeHandle:
    def __init__(self, name, depth=0.0):
        self.name = name
        self.ready = True
        self.dead = False
        self.queue_depth = depth
        self.compile_count = 0.0
        self.compile_cache_hits = 0.0

    def compile_hit_rate(self):
        return 0.0


class _FakeSet:
    def __init__(self, handles):
        self.handles = handles

    def live(self):
        return [h for h in self.handles if not h.dead]


def test_router_route_counters_and_last_decision():
    reg = MetricsRegistry()
    r0, r1 = _FakeHandle("r0"), _FakeHandle("r1")
    router = Router(_FakeSet([r0, r1]), registry=reg)
    first = router.route(("A",))
    assert router.last_decision["outcome"] == "warm"
    assert router.last_decision["replica"] == first.name
    assert router.last_decision["pins"] == 1
    router.route(("A",))
    assert router.last_decision["outcome"] == "hit"
    # detour: the pinned home goes not-ready -> miss on the other
    first.ready = False
    router.route(("A",))
    assert router.last_decision["outcome"] == "miss"
    first.ready = True
    c = reg.snapshot()["counters"]
    assert c["fleet.route.warm"] == 1
    assert c["fleet.route.hit"] == 1
    assert c["fleet.route.miss"] == 1
    # pin_counts follows pin moves and deaths
    assert router.pin_counts[first.name] == 1
    router.on_replica_dead(first.name)
    assert router.pin_counts[first.name] == 0


# ------------------------------------------------- readiness contract


def test_readyz_dispatcher_stalled_reason():
    reg = MetricsRegistry()
    reg.gauge("fleet.tick_age_s").set(0.1)
    reg.gauge("fleet.tick_stall_after").set(1.0)
    ok, detail = obs_http.readiness(reg)
    assert ok and detail["reasons"] == []
    reg.gauge("fleet.tick_age_s").set(5.0)
    ok, detail = obs_http.readiness(reg)
    assert not ok and "dispatcher_stalled" in detail["reasons"]
    # threshold 0 = watchdog off
    reg.gauge("fleet.tick_stall_after").set(0.0)
    ok, _ = obs_http.readiness(reg)
    assert ok


def test_readyz_slo_burn_reason():
    reg = MetricsRegistry()
    ok, detail = obs_http.readiness(reg)
    assert ok
    reg.gauge("fleet.slo_burn").set(1.0)
    ok, detail = obs_http.readiness(reg)
    assert not ok and "slo_burn" in detail["reasons"]
    reg.gauge("fleet.slo_burn").set(0.0)   # burn cleared: reason live
    ok, _ = obs_http.readiness(reg)
    assert ok


def test_dispatcher_death_flips_readyz_dispatcher_stalled():
    """route:1:die ends the dispatcher on the first routing decision;
    the tick-age watchdog then flips /readyz to `dispatcher_stalled`
    under the pinned JSON contract — HA stacks route around a gateway
    that accepts jobs it will never place."""
    handle = ReplicaHandle("rx", "http://127.0.0.1:9")  # nothing there
    gw = Gateway(_fleet_cfg([handle.url], faults="route:1:die",
                            stall_after=0.4),
                 [handle]).start()
    try:
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": "4 2 2 5\n", "id": "s1"})
        deadline = time.monotonic() + 20
        reasons = []
        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(gw.url + "/readyz", timeout=5)
            except urllib.error.HTTPError as e:
                body = json.loads(e.read())
                reasons = body["reasons"]
                assert e.headers["Content-Type"] == "application/json"
                if "dispatcher_stalled" in reasons:
                    break
            time.sleep(0.1)
        assert "dispatcher_stalled" in reasons, reasons
    finally:
        gw.close()
        faults.install(None)


# ------------------------------------------------ stitched trace unit


def _gw_rec(name, ts, dur, flow, **extra):
    return {"spanEntry": dict(name=name, cat="fleet", ts=ts, dur=dur,
                              depth=0, tid=0, flow=flow, **extra)}


def test_export_stitched_cross_process_chain_and_remap():
    xid = XFLOW_BASE + 7
    gw_log = [
        _gw_rec("routed", 0.0, 0.5, xid, job="a"),
        _gw_rec("settle", 4.0, 0.0, xid, job="a"),
        # a gateway-local chain that must NOT merge with the replica's
        _gw_rec("poll", 1.0, 0.1, 3),
        _gw_rec("poll2", 1.2, 0.1, 3),
    ]
    rep_log = [
        _gw_rec("admit", 0.6, 0.1, xid, job="a"),
        _gw_rec("quantum", 1.0, 2.0, xid, job="a"),
        # replica-local chunk chain with the SAME local id 3
        _gw_rec("dispatch", 0.5, 0.2, 3),
        _gw_rec("process", 0.9, 0.2, 3),
    ]
    doc = export_stitched([("gw.jsonl", gw_log),
                           ("rep.jsonl", rep_log)])
    evs = doc["traceEvents"]
    # process lanes are labeled
    meta = [e for e in evs if e.get("ph") == "M"]
    assert {(e["pid"], e["args"]["name"]) for e in meta} == {
        (0, "gw.jsonl"), (1, "rep.jsonl")}
    # the cross-process chain keeps its id and SPANS BOTH pids
    xflow = [e for e in evs if e.get("ph") in ("s", "t", "f")
             and e["id"] == xid]
    assert {e["pid"] for e in xflow} == {0, 1}
    assert [e["ph"] for e in sorted(xflow, key=lambda e: e["ts"])] \
        == ["s", "t", "t", "f"]
    # the two LOCAL id-3 chains stay separate (remapped per input)
    local_ids = {e["id"] for e in evs if e.get("ph") in ("s", "t", "f")
                 and e["id"] != xid}
    assert len(local_ids) == 2
    # --job filters across inputs and keeps the cross-process chain
    jdoc = export_stitched([("gw.jsonl", gw_log),
                            ("rep.jsonl", rep_log)], job="a")
    jevs = jdoc["traceEvents"]
    assert sorted(e["name"] for e in jevs if e.get("ph") == "X") == \
        ["admit", "quantum", "routed", "settle"]
    assert {e["id"] for e in jevs
            if e.get("ph") in ("s", "t", "f")} == {xid}


def test_single_log_export_unchanged_no_remap():
    log = [_gw_rec("a", 0.0, 1.0, 3), _gw_rec("b", 1.0, 1.0, 3)]
    from timetabling_ga_tpu.obs.trace_export import export_chrome_trace
    doc = export_chrome_trace(log)
    evs = doc["traceEvents"]
    assert not any(e.get("ph") == "M" for e in evs)
    assert {e["id"] for e in evs
            if e.get("ph") in ("s", "t", "f")} == {3}
    assert all(e["pid"] == 0 for e in evs)


# ------------------------------------------- acceptance: fleet e2e


def test_gateway_obs_end_to_end_flow_metrics_slo_identity():
    """ISSUE 11 acceptance: a routed job traced end to end. The
    gateway's log and the replica's log share the job's XFLOW flow id;
    the stitched export draws the chain across the process boundary;
    the gateway serves /metrics parity families and the contract
    /readyz (slo_burn, with the burn faultEntry on the log); tt stats
    over both logs shows the `routed` component and the placement
    summary; and the job record streams stay identical to an unrouted
    solve (modulo timing records)."""
    rep, handle = in_process_replica(_serve_cfg(obs=True), "r0")
    gwbuf = io.StringIO()
    gw = Gateway(_fleet_cfg([handle.url], slo_p99=0.001,
                            metrics_every=10),
                 [handle], out=gwbuf).start()
    jobs = [(f"fo-{i}", random_instance(700 + i, **_SHAPE), 40 + i, 8)
            for i in range(2)]
    try:
        for jid, p, seed, gens in jobs:
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": seed,
                       "generations": gens})
        settled = _wait_done(gw, [j[0] for j in jobs])
        assert all(j.state == "done" for j in settled.values())

        # --- /metrics parity, via the shared parser -----------------
        fams = obs_scrape.parse_exposition(
            http_text(gw.url + "/metrics"))
        assert obs_scrape.scalar(
            fams, "tt_fleet_jobs_done_total") == 2.0
        assert (obs_scrape.scalar(fams, "tt_fleet_route_warm_total",
                                  0.0)
                + obs_scrape.scalar(fams, "tt_fleet_route_hit_total",
                                    0.0)) >= 2.0
        assert obs_scrape.scalar(
            fams, "tt_fleet_replica_r0_ready") == 1.0
        assert obs_scrape.scalar(
            fams, "tt_fleet_replica_r0_pins") >= 1.0
        assert obs_scrape.scalar(
            fams, "tt_fleet_replica_r0_probe_seconds") is not None
        assert obs_scrape.scalar(
            fams, "tt_fleet_tick_seconds_count") > 0
        assert obs_scrape.labeled(
            fams, "tt_fleet_job_seconds_bucket", le="+Inf") == 2.0
        # job-id exemplar on the e2e histogram (OpenMetrics form)
        assert '# {job="fo-' in http_text(gw.url + "/metrics")

        # --- /readyz: the SLO (0.001s) is burning -------------------
        try:
            urllib.request.urlopen(gw.url + "/readyz", timeout=5)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            body = json.loads(e.read())
        assert body["ready"] is False
        assert "slo_burn" in body["reasons"]
    finally:
        gw.request_drain()
        gw.drained.wait(30)
        gw.close()
        rep.stop(timeout=60)

    gwrecs = _records(gwbuf)
    reprecs = _records(rep.tail._stream)

    # --- cross-process flow identity (THE acceptance pin) -----------
    routed = _spans(gwrecs, name="routed", job="fo-0")
    assert routed, "gateway emitted no routed span"
    flow = routed[0]["flow"]
    assert flow >= XFLOW_BASE
    rep_admit = _spans(reprecs, name="admit", job="fo-0")
    assert rep_admit and rep_admit[0]["flow"] == flow, \
        "replica admit span does not continue the gateway's chain"
    # every gateway phase span of the job rides the same chain
    for name in ("route", "submit", "settle"):
        ss = _spans(gwrecs, name=name, job="fo-0")
        assert ss and all(s["flow"] == flow for s in ss)

    # --- routeEntry placement records -------------------------------
    routes = [r["routeEntry"] for r in gwrecs if "routeEntry" in r]
    assert {r["job"] for r in routes} == {"fo-0", "fo-1"}
    assert all(r["replica"] == "r0" for r in routes)
    assert all(r["outcome"] in ("hit", "warm", "miss")
               for r in routes)
    assert all("compile_hit_rate" in r and "pins" in r
               for r in routes)

    # --- the SLO burn left a faultEntry on the gateway log ----------
    burns = [r["faultEntry"] for r in gwrecs if "faultEntry" in r]
    assert any(f["site"] == "slo_burn" and f["action"] == "burn"
               for f in burns)
    # --- periodic metricsEntry snapshots rode the log ---------------
    assert any("metricsEntry" in r for r in gwrecs)

    # --- stitched export crosses the process boundary ---------------
    doc = export_stitched([("gateway.jsonl", gwrecs),
                           ("replica.jsonl", reprecs)], job="fo-0")
    evs = doc["traceEvents"]
    chain = [e for e in evs if e.get("ph") in ("s", "t", "f")
             and e["id"] == flow]
    assert {e["pid"] for e in chain} == {0, 1}, \
        "flow chain does not cross the process boundary"
    names = {e["name"] for e in evs if e.get("ph") == "X"}
    assert "routed" in names and "quantum" in names

    # --- tt stats learns the gateway records ------------------------
    text = summarize(gwrecs + reprecs)
    assert "placements" in text and "r0: 2 placements" in text
    line = next(x for x in text.splitlines()
                if x.startswith("  fo-0: total "))
    assert "routed" in line
    assert "routed: p50" in text

    # --- record identity: routed (gateway obs ON) == unrouted -------
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=5,
                                   pop_size=4, max_steps=8), out=buf)
    for jid, p, seed, gens in jobs:
        svc.submit(p, job_id=jid, seed=seed, generations=gens)
    svc.drive()
    svc.close()
    base: dict = {}
    for rec in _records(buf):
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") is not None:
            base.setdefault(body["job"], []).append(rec)
    for jid, j in settled.items():
        assert jsonl.strip_timing(j.records) == \
            jsonl.strip_timing(base[jid]), f"stream diverged for {jid}"


# ------------------------------------------- fault-site isolation


def test_dead_gateway_writer_never_stalls_settlement():
    """gw_writer:1:die kills the gateway's telemetry writer on its
    first record: obs emission latches OFF and every job still routes,
    solves, and settles — the dispatcher never waits on the log."""
    rep, handle = in_process_replica(_serve_cfg(), "rw")
    gwbuf = io.StringIO()
    gw = Gateway(_fleet_cfg([handle.url], faults="gw_writer:1:die"),
                 [handle], out=gwbuf).start()
    try:
        p = random_instance(711, **_SHAPE)
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "w1", "seed": 5,
                   "generations": 8})
        settled = _wait_done(gw, ["w1"], timeout=90)
        assert settled["w1"].state == "done"
        assert not gw.writer.alive()           # the worker is dead
        assert gw._obs_dead                    # emission latched off
    finally:
        gw.close()
        faults.install(None)
        rep.kill()


def test_hung_replica_scrape_never_stalls_settlement(monkeypatch):
    """gw_scrape:2:hang parks the PROBER thread mid-scrape (the first
    scrape is the synchronous pre-start probe): routing runs on the
    last-probed gauges and the job settles — nothing on the dispatch
    or settlement path ever waits for the scrape."""
    monkeypatch.setattr(faults, "HANG_S", 8.0)
    rep, handle = in_process_replica(_serve_cfg(), "rh")
    gw = Gateway(_fleet_cfg([handle.url], faults="gw_scrape:2:hang"),
                 [handle]).start()
    try:
        p = random_instance(712, **_SHAPE)
        t0 = time.monotonic()
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "h1", "seed": 6,
                   "generations": 8})
        settled = _wait_done(gw, ["h1"], timeout=90)
        assert settled["h1"].state == "done"
        # settlement did not serialize behind the 8 s hang window in
        # any blocking way — it completed while/despite the prober
        # being parked (generous bound: solve time, not hang time)
        assert time.monotonic() - t0 < 60
    finally:
        gw.close()
        faults.install(None)
        rep.kill()


def test_gateway_ctor_failure_closes_writer():
    """A taken listen port fails Gateway.__init__ AFTER the telemetry
    writer started its worker thread — close() is unreachable, so the
    constructor itself must drain and stop the writer (the
    SolveService constructor-failure discipline)."""
    import socket
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    handle = ReplicaHandle("rz", "http://127.0.0.1:9")
    buf = io.StringIO()
    import threading
    before = threading.active_count()
    try:
        with pytest.raises(OSError):
            Gateway(_fleet_cfg([handle.url],
                               listen=f"127.0.0.1:{port}"),
                    [handle], out=buf)
        deadline = time.monotonic() + 5
        while threading.active_count() > before \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert threading.active_count() <= before, \
            "writer worker thread leaked past the failed constructor"
    finally:
        blocker.close()


def test_gw_writer_site_is_separate_from_writer_site():
    """A `writer` plan must not fire on a gw_writer-sited AsyncWriter
    and vice versa — separate sites keep a gateway-log fault from
    shifting a replica writer plan's invocation indices."""
    faults.install("writer:1:die")
    try:
        buf = io.StringIO()
        w = jsonl.AsyncWriter(buf, site="gw_writer")
        w.write('{"a":1}\n')
        w.drain()
        assert w.alive()                       # plan did not fire
        w.close()
        assert buf.getvalue() == '{"a":1}\n'
    finally:
        faults.install(None)
    faults.install("gw_writer:1:die")
    try:
        w = jsonl.AsyncWriter(io.StringIO(), site="gw_writer")
        deadline = time.monotonic() + 5
        w.write('{"a":1}\n')                   # worker dies dequeuing
        while w.alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not w.alive()
        with pytest.raises(RuntimeError):
            w.write('{"b":2}\n')
            w.drain()
    finally:
        faults.install(None)


# ------------------------------------------------ stats breakdown fix


def _serve_span(name, ts, dur, job):
    return {"spanEntry": dict(name=name, cat="serve", ts=ts, dur=dur,
                              depth=0, tid=0, job=job, flow=1)}


def test_breakdown_routed_identity_across_clock_domains():
    """The gateway leg enters the breakdown as a clock-safe duration
    SUM: gateway timestamps (own epoch, here skewed +100s) are never
    differenced against replica timestamps, `parked` does not absorb
    the routed time, and the printed identity total = queued + routed
    + packed + executing + parked holds (modulo finalize)."""
    xid = XFLOW_BASE + 1
    recs = [
        # gateway log: epoch skewed far from the replica's
        _gw_rec("routed", 100.0, 2.0, xid, job="a"),
        _gw_rec("settle", 104.0, 0.0, xid, job="a"),
        # replica log: its own epoch
        _serve_span("admit", 0.0, 0.0, "a"),
        _serve_span("pack", 0.5, 0.5, "a"),
        _serve_span("quantum", 1.0, 1.0, "a"),
        _serve_span("finalize", 2.0, 0.0, "a"),
    ]
    from timetabling_ga_tpu.obs.logstats import _job_breakdown
    b = _job_breakdown([r["spanEntry"] for r in recs])["a"]
    assert b["routed"] == pytest.approx(2.0)
    # window = replica spans only (2.0s), NOT the 100s epoch skew
    assert b["total"] == pytest.approx(4.0)      # 2.0 window + routed
    assert b["queued"] == pytest.approx(0.5)
    assert b["packed"] == pytest.approx(0.5)
    assert b["executing"] == pytest.approx(1.0)
    assert b["parked"] == pytest.approx(0.0)     # no double-count
    assert b["total"] == pytest.approx(
        b["queued"] + b["routed"] + b["packed"] + b["executing"]
        + b["parked"])

    # gateway-ONLY view: the routed span IS the window's work — still
    # no double-count, identity still holds
    g = _job_breakdown([r["spanEntry"] for r in recs
                        if r["spanEntry"]["cat"] == "fleet"])["a"]
    assert g["total"] == pytest.approx(4.0)
    assert g["routed"] == pytest.approx(2.0)
    assert g["parked"] == pytest.approx(2.0)     # placed→settled
    assert g["total"] == pytest.approx(
        g["queued"] + g["routed"] + g["packed"] + g["executing"]
        + g["parked"])


def test_breakdown_failover_windows_one_replica_log():
    """A failed-over job has replica spans in TWO logs with unrelated
    epochs: the window (and the replica-side tallies) come from the
    leg that FINALIZED (`_src` provenance, stamped per input file by
    main_stats); the dead replica's partial leg never mixes its
    timestamps in. The gateway's routed spans — one per placement
    round, non-overlapping — sum across rounds."""
    from timetabling_ga_tpu.obs.logstats import _job_breakdown
    xid = XFLOW_BASE + 2

    def src(rec, i):
        rec["spanEntry"]["_src"] = i
        return rec["spanEntry"]

    spans = [
        # gateway log (src 0): first placement + failover re-placement
        src(_gw_rec("routed", 0.0, 0.5, xid, job="a"), 0),
        src(_gw_rec("routed", 10.0, 1.5, xid, job="a"), 0),
        # dead replica r0 (src 1): partial leg, big epoch offset
        src(_serve_span("admit", 900.0, 0.0, "a"), 1),
        src(_serve_span("quantum", 900.5, 3.0, "a"), 1),
        # surviving replica r1 (src 2): full replay, small epoch
        src(_serve_span("admit", 1.0, 0.0, "a"), 2),
        src(_serve_span("pack", 1.5, 0.5, "a"), 2),
        src(_serve_span("quantum", 2.0, 2.0, "a"), 2),
        src(_serve_span("finalize", 4.0, 0.0, "a"), 2),
    ]
    b = _job_breakdown(spans)["a"]
    assert b["routed"] == pytest.approx(2.0)     # 0.5 + 1.5, summed
    # window = the finalizing leg only (3.0s), never the 900s epoch
    assert b["total"] == pytest.approx(3.0 + 2.0)
    assert b["executing"] == pytest.approx(2.0)  # r1's quantum only
    assert b["total"] == pytest.approx(
        b["queued"] + b["routed"] + b["packed"] + b["executing"]
        + b["parked"])


# --------------------------------------------------------- CLI flags


def test_parse_fleet_args_obs_flags():
    cfg = parse_fleet_args(
        ["--replica", "http://a:1", "-o", "gw.jsonl",
         "--slo-p99", "2.5", "--slo-window", "50",
         "--stall-after", "10", "--metrics-every", "20"])
    assert cfg.output == "gw.jsonl"
    assert cfg.slo_p99 == 2.5
    assert cfg.slo_window == 50
    assert cfg.stall_after == 10.0
    assert cfg.metrics_every == 20
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "u", "--slo-p99", "-1"])
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "u", "--slo-window", "0"])
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "u", "--stall-after", "-1"])
