"""Delta-evaluation tests (ops/delta.py vs full re-evaluation).

The delta local search must be bit-for-bit equivalent to the
full-re-evaluation search under the same keys: same candidates, same
greedy room choices, same acceptance decisions, same final populations.
Plus direct checks that the maintained att/occ tensors stay consistent.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from timetabling_ga_tpu.ops import delta, fitness, ga, local_search
from timetabling_ga_tpu.problem import random_instance
from tests.conftest import random_assignment


@pytest.mark.parametrize("p1,p2,p3", [
    (1.0, 0.0, 0.0),      # Move1 only
    (0.0, 1.0, 0.0),      # Move2 only
    (0.0, 0.0, 1.0),      # Move3 only
    (1.0, 1.0, 0.0),      # reference default mix
    (1.0, 1.0, 0.5),      # all three
])
def test_delta_ls_equals_full_ls(small_problem, p1, p2, p3):
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 16)
    key = jax.random.key(42)
    s_full, r_full = local_search.batch_local_search(
        pa, key, st.slots, st.rooms, n_rounds=15, n_candidates=4,
        p1=p1, p2=p2, p3=p3)
    s_dlt, r_dlt = delta.batch_local_search_delta(
        pa, key, st.slots, st.rooms, n_rounds=15, n_candidates=4,
        p1=p1, p2=p2, p3=p3)
    np.testing.assert_array_equal(np.asarray(s_full), np.asarray(s_dlt))
    np.testing.assert_array_equal(np.asarray(r_full), np.asarray(r_dlt))


def test_delta_ls_equivalence_medium(medium_problem):
    """Same equivalence on a bigger instance with the default mix."""
    pa = medium_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(3), 8)
    key = jax.random.key(7)
    s_full, r_full = local_search.batch_local_search(
        pa, key, st.slots, st.rooms, n_rounds=10, n_candidates=8)
    s_dlt, r_dlt = delta.batch_local_search_delta(
        pa, key, st.slots, st.rooms, n_rounds=10, n_candidates=8)
    np.testing.assert_array_equal(np.asarray(s_full), np.asarray(s_dlt))
    np.testing.assert_array_equal(np.asarray(r_full), np.asarray(r_dlt))


def test_maintained_state_consistent_after_search(small_problem):
    """After a delta search, penalties recomputed from scratch must match
    what the maintained counters accumulated to (guards against drift in
    att/occ bookkeeping)."""
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(5), 16)
    key = jax.random.key(9)
    s, r = delta.batch_local_search_delta(
        pa, key, st.slots, st.rooms, n_rounds=30, n_candidates=4)
    # fresh full evaluation
    pen_fresh, hcv_fresh, _ = fitness.batch_penalty(pa, s, r)
    # penalty can only have improved
    assert (np.asarray(pen_fresh) <= np.asarray(st.penalty)).all()
    # and delta LS respects the feasibility gate exactly like full LS
    _, hcv0, _ = fitness.batch_penalty(pa, st.slots, st.rooms)
    was_feasible = np.asarray(hcv0) == 0
    assert (np.asarray(hcv_fresh)[was_feasible] == 0).all()


def test_init_state_counters(small_problem):
    """att/occ built by init_state match direct recomputation."""
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(0)
    slots, rooms = random_assignment(rng, small_problem, 4)
    from timetabling_ga_tpu.ops.rooms import occupancy
    st = delta.init_state(pa, jnp.asarray(slots), jnp.asarray(rooms))
    for p in range(4):
        att = np.asarray(fitness.attendance_matrix(pa, slots[p]))
        np.testing.assert_array_equal(np.asarray(st.att[p]), att)
        occ = np.asarray(occupancy(pa, slots[p], rooms[p]))
        np.testing.assert_array_equal(np.asarray(st.occ[p]), occ)
