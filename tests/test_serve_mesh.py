"""Multi-device serving (ISSUE 17): mesh sizing, lane padding, and
device-resident job groups.

The properties pinned here:

  1. lane PADDING is shape-only: `islands.pad_lanes` rounds the
     configured lane count up to a device multiple, the padded lanes
     are zero-generation filler, and the job-packing CAPACITY stays
     the configured `--lanes`;
  2. mesh width is INVISIBLE in the record protocol: per-job streams
     are strip_timing-identical between a 1-device mesh and the full
     forced-8-device mesh (lane RNG streams are pure functions of
     (seed, chunk, gen) — tests/conftest.py forces 8 host devices for
     the whole suite);
  3. RESIDENCY is a pure transport optimization: it cuts park/resume
     bytes and scores hits, never changes a stream, and always falls
     back to a host park on repack, fault, flush request, and preempt
     drain — so every ship unit a handler serves is a real park-fence
     unit.
"""

import io
import json
import time

import pytest

from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import ServeConfig, parse_serve_args
from timetabling_ga_tpu.serve.service import SolveService

_SHAPE_A = dict(n_events=12, n_rooms=3, n_features=2, n_students=8,
                attend_prob=0.2)
_PA = random_instance(71, **_SHAPE_A)
_PA2 = random_instance(73, **_SHAPE_A)


@pytest.fixture(autouse=True)
def _no_fault_plan():
    faults.install(None)
    yield
    faults.install(None)


def _cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _job_records(text, job_id):
    out = []
    for line in text.splitlines():
        rec = json.loads(line)
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") == str(job_id):
            out.append(rec)
    return out


def _run(jobs, **cfg_kw):
    """Run `jobs` to completion; return (svc, {id: strip_timing})."""
    buf = io.StringIO()
    svc = SolveService(_cfg(**cfg_kw), out=buf,
                       registry=MetricsRegistry())
    for jid, problem, seed, gens in jobs:
        svc.submit(problem, job_id=jid, seed=seed, generations=gens)
    svc.drive()
    svc.close()
    return svc, {jid: jsonl.strip_timing(_job_records(buf.getvalue(),
                                                      jid))
                 for jid, *_ in jobs}


# ------------------------------------------------------- lane padding


def test_pad_lanes_rounds_up_to_device_multiple():
    mesh = islands.make_mesh(None)
    n = mesh.devices.size
    assert islands.pad_lanes(mesh, 1) == n
    assert islands.pad_lanes(mesh, n) == n
    assert islands.pad_lanes(mesh, n + 1) == 2 * n
    # zero-lane degenerate input still yields a dispatchable width
    assert islands.pad_lanes(mesh, 0) == n


def test_scheduler_pads_width_but_not_capacity():
    """lanes % devices != 0: the dispatch width pads up to a device
    multiple, while job-packing capacity stays the configured count
    (padding lanes are filler, not admission slots)."""
    import jax

    n_dev = jax.device_count()
    svc = SolveService(_cfg(lanes=3, mesh_devices=0), out=io.StringIO(),
                       registry=MetricsRegistry())
    sch = svc.scheduler
    assert sch.mesh.devices.size == n_dev
    assert sch.lanes % n_dev == 0 and sch.lanes >= 3
    assert sch.cfg.lanes == 3           # capacity unchanged
    assert svc.registry.gauge("serve.mesh_devices").value == n_dev
    assert svc.registry.gauge("serve.lanes").value == sch.lanes
    svc.close()


def test_mesh_wider_than_runnable_lanes():
    """One job on the full mesh: every lane past the first is filler,
    the job completes, and its stream matches the 1-device run."""
    jobs = [("solo", _PA, 3, 15)]
    svc1, base = _run(jobs, mesh_devices=1, resident=False)
    svcN, wide = _run(jobs, mesh_devices=0, resident=False)
    assert svcN.queue.get("solo").state == "done"
    assert svcN.scheduler.lanes >= svcN.scheduler.mesh.devices.size
    assert wide["solo"] == base["solo"]


# --------------------------------------- stream identity across meshes


def test_stream_identity_across_mesh_sizes():
    """Per-job record streams are strip_timing-identical between the
    1-device mesh and the full mesh, parked or resident — mesh width
    and residency must never show in a record."""
    jobs = [("ia", _PA, 3, 15), ("ib", _PA2, 4, 15)]
    _, base = _run(jobs, mesh_devices=1, resident=False)
    for kw in (dict(mesh_devices=0, resident=False),
               dict(mesh_devices=0, resident=True),
               dict(mesh_devices=1, resident=True)):
        _, got = _run(jobs, **kw)
        for jid, *_ in jobs:
            assert got[jid] == base[jid], (jid, kw)


# ------------------------------------------------------------ residency


def test_residency_scores_hits_and_cuts_bytes():
    """Same stream, resident on vs off (private registries): the
    resident run scores hits and moves strictly fewer park/resume
    bytes; the parked run never hits."""
    jobs = [("ra", _PA, 3, 30), ("rb", _PA2, 4, 30)]
    svc_off, base = _run(jobs, resident=False)
    svc_on, got = _run(jobs, resident=True)

    def bytes_moved(svc):
        return (svc.registry.counter("serve.park_bytes").value
                + svc.registry.counter("serve.resume_bytes").value)

    assert svc_off.registry.counter("serve.resident_hits").value == 0
    assert svc_on.registry.counter("serve.resident_hits").value > 0
    assert bytes_moved(svc_on) < bytes_moved(svc_off)
    for jid, *_ in jobs:
        assert got[jid] == base[jid], jid


def test_residency_invalidated_by_repack():
    """A second job admitted into a resident group's bucket changes
    the lane assignment: the group flushes (a full host park) before
    the repacked quantum, and both streams stay identical to a
    never-resident run."""
    jobs = [("pa", _PA, 3, 30), ("pb", _PA2, 4, 30)]
    _, base = _run(jobs, resident=False)

    buf = io.StringIO()
    svc = SolveService(_cfg(resident=True), out=buf,
                       registry=MetricsRegistry())
    svc.submit(_PA, job_id="pa", seed=3, generations=30)
    svc.step()                           # q1: first park, ship built
    svc.step()                           # q2: goes resident
    assert len(svc.scheduler._resident) == 1
    svc.submit(_PA2, job_id="pb", seed=4, generations=30)
    svc.step()                           # repack: [pb, pa] != [pa]
    assert svc.registry.counter("serve.resident_flushes").value >= 1
    svc.drive()
    svc.close()
    for jid, *_ in jobs:
        got = jsonl.strip_timing(_job_records(buf.getvalue(), jid))
        assert got == base[jid], jid


def test_residency_invalidated_by_fault():
    """A transient fault on a RESIDENT quantum drops the device state
    and rolls the cursors back to the last host fence: the job
    recovers from its park snapshot and the stream is bit-identical
    to an uninjected run."""
    jobs = [("fa", _PA, 3, 30)]
    _, base = _run(jobs, resident=False)

    buf = io.StringIO()
    svc = SolveService(_cfg(resident=True), out=buf,
                       registry=MetricsRegistry())
    # q1 parks (first ship), q2 goes resident, q3 faults mid-residency
    faults.install("quantum:3:unavailable")
    svc.submit(_PA, job_id="fa", seed=3, generations=30)
    svc.drive()
    faults.install(None)
    svc.close()
    assert svc.registry.counter("serve.job_recoveries").value >= 1
    assert svc.queue.get("fa").state == "done"
    assert len(svc.scheduler._resident) == 0
    got = jsonl.strip_timing(_job_records(buf.getvalue(), "fa"))
    assert got == base["fa"]


def test_flush_request_and_preempt_flush_refresh_ship():
    """request_flush (the ?snapshot=1 handler hook) parks the group at
    the next fence with a fence-fresh ship unit; flush_resident (the
    preempt-drain hook) does it immediately between quanta."""
    svc = SolveService(_cfg(resident=True), out=io.StringIO(),
                       registry=MetricsRegistry())
    svc.submit(_PA, job_id="s", seed=3, generations=40)
    svc.step()                           # q1: park, ship @ 5 gens
    svc.step()                           # q2: resident, ship frozen
    job = svc.queue.get("s")
    assert len(svc.scheduler._resident) == 1
    assert job.ship.gens_done == 5       # frozen at the host fence
    # handler-style request: flag only, honored at the NEXT fence —
    # the flush lands before q3 dispatches, so the ship re-syncs to
    # the pre-q3 cursor (10) and the group may re-enter residency
    svc.scheduler.request_flush()
    svc.step()                           # fence flush (ship @ 10), q3
    assert job.ship.gens_done == 10
    assert job.gens_done == 15
    assert len(svc.scheduler._resident) == 1   # resident again
    # preempt-drain style: immediate flush between quanta
    flushed = svc.scheduler.flush_resident("preempt")
    assert flushed == 1
    assert len(svc.scheduler._resident) == 0
    assert job.ship.gens_done == job.gens_done == 15
    svc.drive()
    svc.close()
    assert svc.queue.get("s").state == "done"


def test_ship_hot_job_parks_every_fence():
    """A job someone polls ?snapshot=1 on (ship_hot) keeps its group
    parking at every fence — snapshot freshness beats residency."""
    svc = SolveService(_cfg(resident=True), out=io.StringIO(),
                       registry=MetricsRegistry())
    svc.submit(_PA, job_id="h", seed=3, generations=40)
    svc.step()
    job = svc.queue.get("h")
    job.ship_hot = True                  # what job_view sets
    svc.step()
    svc.step()
    assert len(svc.scheduler._resident) == 0
    assert job.ship.gens_done == job.gens_done == 15
    svc.drive()
    svc.close()


# ---------------------------------------------------------------- flags


def test_mesh_flags_parse_and_validate():
    cfg = parse_serve_args(["--mesh-devices", "2", "--no-resident",
                            "--backend", "cpu"])
    assert cfg.mesh_devices == 2 and cfg.resident is False
    cfg = parse_serve_args(["--backend", "cpu"])
    assert cfg.mesh_devices == 0 and cfg.resident is True
    with pytest.raises(SystemExit):
        parse_serve_args(["--mesh-devices", "-1"])
