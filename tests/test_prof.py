"""tt-prof phase profiler tests (timetabling_ga_tpu/obs/prof.py).

Layers:

  unit        scope registry validation + null-scope decorator duty,
              HLO sidecar harvest (metadata ops AND the call-graph
              majority-vote fallback for optimizer-synthesized whiles),
              sidecar write/load roundtrip, self-time stack pass,
              innermost-wins phase extraction
  parser      synthetic jax.profiler captures (plain + gzip, plugin
              dir layout): exact per-phase seconds/fracs, container-op
              double-count correction, token fallback, and the HONEST
              `unattributed` bucket — unknown ops are reported, never
              folded into a phase
  publish     prof.phase_seconds.* gauges + the profEntry record;
              profEntry is a TIMING record so strip_timing drops it
              (the stream identity contract by construction)
  identity    THE acceptance criterion: a full engine run with
              TT_PROF_SCOPES=0 vs =1 in subprocesses — protocol
              records modulo timing AND islands.TRACE_COUNTS are
              bit-identical (scopes are metadata-only, the TT202
              discipline)
  CLI         `tt hotspots` on capture dirs and profEntry logs,
              --json, --diff, missing-input exit code; the `tt stats`
              "== phases" section
  gate        tools/perf_gate.py: regression detection, direction
              handling, skipped metrics, the no-vacuous-pass rule
  e2e (slow)  real capture on a live engine: >= 90% of device op time
              attributed to tt.* phases

The parser/CLI/gate layers are jax-free by design (`tt hotspots` must
run on a host with no accelerator stack).
"""

import gzip
import io
import json
import os
import subprocess
import sys

import pytest

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.runtime import jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM = os.path.join(REPO, "fixtures", "comp01s.tim")
TOOLS = os.path.join(REPO, "tools")


# ------------------------------------------------------------------ unit


def test_scope_rejects_unregistered_names():
    with pytest.raises(ValueError, match="tt.breeding"):
        obs_prof.scope("tt.breeding")
    with pytest.raises(ValueError):
        obs_prof.scope("sweep")          # must be the dotted form


def test_scope_registry_is_the_single_source():
    # every phase is dotted, unique, and round-trips through short()
    assert len(set(obs_prof.PHASES)) == len(obs_prof.PHASES)
    for p in obs_prof.PHASES:
        assert p.startswith("tt.")
        assert obs_prof.short(p) == p[3:]
    assert obs_prof.short("unattributed") == "unattributed"


def test_null_scope_serves_both_positions(monkeypatch):
    """With scopes disabled, scope() must still work as a context
    manager AND a decorator — it swaps in for jax.named_scope in both
    positions across the ops modules."""
    monkeypatch.setattr(obs_prof, "SCOPES_ENABLED", False)

    @obs_prof.scope("tt.sweep")
    def f(x):
        return x + 1

    assert f(1) == 2
    with obs_prof.scope("tt.fitness"):
        y = f(2)
    assert y == 3
    # validation still applies when disabled — a typo'd scope must not
    # survive until someone re-enables profiling
    with pytest.raises(ValueError):
        obs_prof.scope("tt.nope")


def test_phase_of_op_name_innermost_wins():
    f = obs_prof.phase_of_op_name
    assert f("jit(g)/jit(main)/tt.sweep/mul") == "tt.sweep"
    assert f("jit(g)/tt.ga/while/body/tt.sweep/dot") == "tt.sweep"
    assert f("jit(g)/jit(main)/mul") is None
    # phase names are matched as whole path components, not substrings
    assert f("jit(g)/tt.sweeper/mul") is None


_SYNTH_HLO = """\
HloModule jit_gen, entry_computation_layout={()->f32[]}

%body.1 (p: f32[]) -> f32[] {
  %p = f32[] parameter(0)
  %mul.1 = f32[] multiply(%p, %p), metadata={op_name="jit(gen)/jit(main)/tt.sweep/mul" source_file="x.py"}
  ROOT %add.1 = f32[] add(%mul.1, %p), metadata={op_name="jit(gen)/jit(main)/tt.sweep/add"}
}

%cond.1 (p: f32[]) -> pred[] {
  %p = f32[] parameter(0)
  ROOT %lt = pred[] compare(%p, %p), direction=LT, metadata={op_name="jit(gen)/jit(main)/tt.sweep/lt"}
}

ENTRY %main.9 () -> f32[] {
  %c = f32[] constant(0)
  %dot.7 = f32[] multiply(%c, %c), metadata={op_name="jit(gen)/jit(main)/tt.fitness/dot_general"}
  %while.42 = f32[] while(%c), condition=%cond.1, body=%body.1
  ROOT %out = f32[] add(%while.42, %dot.7), metadata={op_name="jit(gen)/jit(main)/tt.ga/add"}
}
"""


class _FakeExe:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        return self._text


def test_note_executable_harvests_metadata_and_call_graph():
    """Ops with op_name metadata join directly; the optimizer-
    synthesized `while.42` (NO metadata) resolves through the
    majority vote over its condition/body computations."""
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(_FakeExe(_SYNTH_HLO))
        ops = obs_prof._SCOPE_MAPS["jit_gen"]
        assert ops["dot.7"] == "tt.fitness"
        assert ops["out"] == "tt.ga"
        assert ops["mul.1"] == "tt.sweep"
        assert ops["while.42"] == "tt.sweep"     # the callee vote
        # ENTRY-computation glue with no resolvable phase must stay
        # OUT of the map — the parser's unattributed bucket owns it
        assert "c" not in ops
    finally:
        obs_prof._reset_scope_maps()


def test_note_executable_merges_same_named_modules():
    """Two executables can share one HLO module name (XLA names the
    module after the jitted callable — different runner variants built
    from same-named inner functions collide), and the trace only
    records the NAME. The op tables must merge; an op name the
    variants put in DIFFERENT phases is ambiguous and must drop to
    unattributed — not silently take the last variant's phase."""
    other = _SYNTH_HLO.replace(
        # variant B reuses the name dot.7 for a tt.rooms op and brings
        # a new op gather.9 the first variant doesn't have
        'op_name="jit(gen)/jit(main)/tt.fitness/dot_general"',
        'op_name="jit(gen)/jit(main)/tt.rooms/dot_general"').replace(
        "ROOT %out = f32[] add(%while.42, %dot.7), "
        'metadata={op_name="jit(gen)/jit(main)/tt.ga/add"}',
        "%gather.9 = f32[] add(%while.42, %dot.7), "
        'metadata={op_name="jit(gen)/jit(main)/tt.lahc/add"}\n'
        "  ROOT %out = f32[] add(%while.42, %dot.7), "
        'metadata={op_name="jit(gen)/jit(main)/tt.ga/add"}')
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(_FakeExe(_SYNTH_HLO))
        obs_prof.note_executable(_FakeExe(other))
        ops = obs_prof._SCOPE_MAPS["jit_gen"]
        assert "dot.7" not in ops                # conflict -> dropped
        assert ops["gather.9"] == "tt.lahc"      # new op merged in
        assert ops["out"] == "tt.ga"             # agreement kept
        # the conflict is pinned: a THIRD compile agreeing with either
        # side must not resurrect the dropped name
        obs_prof.note_executable(_FakeExe(_SYNTH_HLO))
        assert "dot.7" not in obs_prof._SCOPE_MAPS["jit_gen"]
    finally:
        obs_prof._reset_scope_maps()


def test_runner_variants_get_distinct_module_names():
    """The islands jit builders name each compiled variant after its
    static build parameters — without this, every engine runner lowers
    to a module literally named jit__run and the sidecar join table
    can only hold ONE of them (the 4-variant engine run measured 86%
    unattributed before the rename, 0.1% after)."""
    jnp = pytest.importorskip("jax.numpy")
    from timetabling_ga_tpu.parallel import islands

    jf = islands._named_jit(lambda x: x + 1, name="variant_e4x50_full")
    text = jf.lower(jnp.ones((2,))).as_text()
    assert "variant_e4x50_full" in text


def test_note_executable_degrades_without_as_text():
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(object())       # no as_text(): no-op
        obs_prof.note_executable(_FakeExe(""))   # empty text: no-op
        assert obs_prof._SCOPE_MAPS == {}
    finally:
        obs_prof._reset_scope_maps()


def test_write_scope_map_roundtrip(tmp_path):
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(_FakeExe(_SYNTH_HLO))
        path = obs_prof.write_scope_map(str(tmp_path))
        assert path and os.path.basename(path) == obs_prof.SIDECAR
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["modules"]["jit_gen"]["dot.7"] == "tt.fitness"
    finally:
        obs_prof._reset_scope_maps()
    # nothing harvested -> no sidecar, parser falls back honestly
    assert obs_prof.write_scope_map(str(tmp_path / "empty")) is None


def test_self_times_subtracts_container_spans():
    """A while op spanning its body ops on the same thread must not
    double-count: the container keeps only its SELF time."""
    evs = [
        {"ts": 0.0, "dur": 100.0, "name": "while.1"},
        {"ts": 10.0, "dur": 30.0, "name": "fusion.1"},
        {"ts": 50.0, "dur": 40.0, "name": "fusion.2"},
        {"ts": 200.0, "dur": 10.0, "name": "dot.3"},
    ]
    got = {ev["name"]: s for ev, s in obs_prof._self_times(evs)}
    assert got == {"while.1": 30.0, "fusion.1": 30.0,
                   "fusion.2": 40.0, "dot.3": 10.0}


# ---------------------------------------------------------------- parser


def _trace_doc():
    """A synthetic Chrome trace: one device thread with a container
    while + body ops (sidecar-joined), one token-fallback op, and one
    op NOBODY can place (the honest-unattributed probe). Durations in
    microseconds."""
    return {"traceEvents": [
        # sidecar-joined body ops under a while container
        {"ph": "X", "pid": 1, "tid": 7, "ts": 0, "dur": 100,
         "name": "while.42",
         "args": {"hlo_module": "jit_gen", "hlo_op": "while.42"}},
        {"ph": "X", "pid": 1, "tid": 7, "ts": 10, "dur": 60,
         "name": "mul.1",
         "args": {"hlo_module": "jit_gen", "hlo_op": "mul.1"}},
        # token fallback: no sidecar entry, scope path inlined in name
        {"ph": "X", "pid": 1, "tid": 7, "ts": 200, "dur": 40,
         "name": "jit(gen)/tt.rooms/gather",
         "args": {"hlo_op": "gather.5"}},
        # unattributable: unknown module, opaque name
        {"ph": "X", "pid": 1, "tid": 7, "ts": 300, "dur": 50,
         "name": "custom-call.9",
         "args": {"hlo_module": "jit_other", "hlo_op": "custom-call.9"}},
        # not a device op (no hlo args): ignored
        {"ph": "X", "pid": 1, "tid": 9, "ts": 0, "dur": 999,
         "name": "TraceMe host frame", "args": {}},
        # metadata event: ignored
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "device"}},
    ]}


def _write_capture(root, gz=True):
    """Lay out a capture dir the way the profiler plugin does:
    <root>/plugins/profile/<run>/<host>.trace.json(.gz) plus the
    tt-prof sidecar at the capture root."""
    run = os.path.join(root, "plugins", "profile", "run1")
    os.makedirs(run, exist_ok=True)
    doc = json.dumps(_trace_doc())
    if gz:
        with gzip.open(os.path.join(run, "host.trace.json.gz"),
                       "wt", encoding="utf-8") as f:
            f.write(doc)
    else:
        with open(os.path.join(run, "host.trace.json"),
                  "w", encoding="utf-8") as f:
            f.write(doc)
    with open(os.path.join(root, obs_prof.SIDECAR), "w",
              encoding="utf-8") as f:
        json.dump({"modules": {"jit_gen": {"while.42": "tt.sweep",
                                           "mul.1": "tt.sweep"}}}, f)
    return root


@pytest.mark.parametrize("gz", [True, False])
def test_attribute_synthetic_capture(tmp_path, gz):
    """Exact numbers: while.42 self time is 100-60=40us, mul.1 60us
    (tt.sweep 100us total), gather 40us via token fallback (tt.rooms),
    custom-call 50us unattributed. Total 190us, counted once."""
    attr = obs_prof.attribute(_write_capture(str(tmp_path), gz=gz))
    assert attr["n_events"] == 4
    assert attr["total_s"] == pytest.approx(190e-6)
    assert attr["phases"]["sweep"]["seconds"] == pytest.approx(100e-6)
    assert attr["phases"]["rooms"]["seconds"] == pytest.approx(40e-6)
    assert attr["unattributed_s"] == pytest.approx(50e-6)
    assert attr["unattributed_frac"] == pytest.approx(50 / 190,
                                                      abs=1e-3)
    fr = sum(d["frac"] for d in attr["phases"].values())
    assert fr + attr["unattributed_frac"] == pytest.approx(1.0,
                                                           abs=1e-2)
    # the unattributed bucket names its ops — honest, not folded
    assert attr["unattributed_top_ops"][0][0] == "custom-call.9"
    # phase tables rank their ops
    assert attr["phases"]["sweep"]["top_ops"][0][0] == "mul.1"


def test_attribute_without_sidecar_is_honest(tmp_path):
    """No sidecar: the join misses, only the token fallback places
    ops, and everything else lands in `unattributed` — the parser
    never guesses."""
    root = _write_capture(str(tmp_path))
    os.remove(os.path.join(root, obs_prof.SIDECAR))
    attr = obs_prof.attribute(root)
    assert "sweep" not in attr["phases"]
    assert attr["phases"]["rooms"]["seconds"] == pytest.approx(40e-6)
    assert attr["unattributed_s"] == pytest.approx(150e-6)


def test_attribute_missing_capture_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        obs_prof.attribute(str(tmp_path / "nope"))


def test_attribute_newest_run_wins(tmp_path):
    """Two plugin runs under one dir: the NEWEST (lexicographically
    last) run is attributed, not a merge of both."""
    root = _write_capture(str(tmp_path))
    stale = os.path.join(root, "plugins", "profile", "run0")
    os.makedirs(stale)
    with open(os.path.join(stale, "host.trace.json"), "w",
              encoding="utf-8") as f:
        json.dump({"traceEvents": [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 10_000_000,
             "name": "stale", "args": {"hlo_op": "stale.1"}}]}, f)
    attr = obs_prof.attribute(root)
    assert attr["total_s"] == pytest.approx(190e-6)


# --------------------------------------------------------------- publish


def test_publish_gauges_and_prof_entry(tmp_path):
    attr = obs_prof.attribute(_write_capture(str(tmp_path)))
    reg = MetricsRegistry()
    buf = io.StringIO()
    obs_prof.publish(attr, registry=reg, out=buf, now=lambda: 12.5)
    g = reg.snapshot()["gauges"]
    assert g["prof.phase_seconds.sweep"] == pytest.approx(100e-6)
    assert g["prof.phase_seconds.rooms"] == pytest.approx(40e-6)
    assert g["prof.total_seconds"] == pytest.approx(190e-6)
    assert g["prof.unattributed_seconds"] == pytest.approx(50e-6)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(recs) == 1 and "profEntry" in recs[0]
    body = recs[0]["profEntry"]
    assert body["ts"] == 12.5
    assert body["phases"]["sweep"]["s"] == pytest.approx(100e-6)
    assert body["unattributedFrac"] == pytest.approx(50 / 190,
                                                     abs=1e-3)
    # profEntry is a TIMING record: the identity contract holds by
    # construction because strip_timing drops it
    assert "profEntry" in jsonl.TIMING_RECORDS
    assert jsonl.strip_timing(recs) == []


def test_publish_without_emitter_only_sets_gauges(tmp_path):
    attr = obs_prof.attribute(_write_capture(str(tmp_path)))
    reg = MetricsRegistry()
    obs_prof.publish(attr, registry=reg, out=None)
    assert "prof.total_seconds" in reg.snapshot()["gauges"]


def test_capture_hook_runs_sidecar_attribute_publish(tmp_path):
    """The ProfileCapture on-complete path: hook(dir) writes the
    sidecar into the finished capture, attributes it, publishes, and
    returns the attribution for /profile?last=1."""
    root = str(tmp_path)
    _write_capture(root)
    os.remove(os.path.join(root, obs_prof.SIDECAR))
    obs_prof._reset_scope_maps()
    try:
        # harvested at "compile time"; the hook must land it on disk
        obs_prof.note_executable(_FakeExe(_SYNTH_HLO))
        reg = MetricsRegistry()
        buf = io.StringIO()
        hook = obs_prof.capture_hook(out=buf, registry=reg,
                                     now=lambda: 1.0)
        attr = hook(root)
    finally:
        obs_prof._reset_scope_maps()
    assert os.path.isfile(os.path.join(root, obs_prof.SIDECAR))
    assert attr["phases"]["sweep"]["seconds"] == pytest.approx(100e-6)
    assert "profEntry" in buf.getvalue()
    assert "prof.phase_seconds.sweep" in reg.snapshot()["gauges"]


# ------------------------------------------------------- scope identity


def _identity_leg(scopes: str):
    """One engine run in a SUBPROCESS (TT_PROF_SCOPES is read at
    import, so the off leg needs its own interpreter): prints the
    protocol records modulo timing plus the retrace/compile
    counters."""
    code = """
import io, json, sys
from timetabling_ga_tpu.runtime import engine, jsonl
from timetabling_ga_tpu.runtime.config import RunConfig
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.obs import metrics as obs_metrics
buf = io.StringIO()
best = engine.run(RunConfig(
    input=%r, seed=3, pop_size=8, islands=2, generations=20,
    migration_period=10, max_steps=8, time_limit=300.0,
    backend="cpu", auto_tune=False, trace=True, metrics_every=1),
    out=buf)
recs = [json.loads(x) for x in buf.getvalue().splitlines()]
c = obs_metrics.REGISTRY.snapshot()["counters"]
json.dump({"best": best,
           "records": jsonl.strip_timing(recs),
           "traces": dict(islands.TRACE_COUNTS),
           "compiles": {k: v for k, v in sorted(c.items())
                        if k.startswith("compile.count")}},
          sys.stdout)
""" % TIM
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TT_PROF_SCOPES=scopes)
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, cwd=REPO,
                       env=env, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout)


def test_scope_identity_records_and_trace_counts():
    """THE acceptance criterion: phase scopes are metadata-only.
    TT_PROF_SCOPES=0 vs =1 on the same seeded run — identical best
    quality, identical protocol records modulo timing, identical
    retrace counts (a scope that forced an extra trace or reshaped a
    record would show here)."""
    on = _identity_leg("1")
    off = _identity_leg("0")
    assert on["best"] == off["best"]
    assert on["records"] == off["records"]
    assert on["traces"] == off["traces"]
    # the compile counters are the engine path's trace counts (the
    # lane TRACE_COUNTS only tick on the serve path): a scope that
    # perturbed a compile-cache key would compile a different program
    # population here
    assert on["compiles"] == off["compiles"]
    assert on["compiles"], "nothing compiled — the A/B proved nothing"


def test_scoped_ops_match_plain_math():
    """In-process half of the identity story: a scoped jitted function
    is bit-identical to the plain computation (named_scope annotates
    metadata, never ops)."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    @jax.jit
    @obs_prof.scope("tt.sweep")
    def scoped(x):
        return (x * x + 3.0).sum()

    @jax.jit
    def plain(x):
        return (x * x + 3.0).sum()

    x = jnp.arange(64, dtype=jnp.float32) / 7.0
    assert scoped(x) == plain(x)


def test_scopes_reach_compiled_metadata():
    """The threading satellite, proven end-to-end in miniature: lower
    a computation that enters registered scopes and find the phases in
    the compiled HLO metadata — then note_executable harvests them."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    @obs_prof.scope("tt.rooms")
    def rooms(x):
        return x * 2.0

    @obs_prof.scope("tt.fitness")
    def fitness(x):
        return x.sum()

    def gen(x):
        return fitness(rooms(x))

    exe = (jax.jit(gen)
           .lower(jnp.zeros((8, 8), jnp.float32)).compile())
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(exe)
        assert obs_prof._SCOPE_MAPS, "no module harvested"
        phases = set()
        for ops in obs_prof._SCOPE_MAPS.values():
            phases.update(ops.values())
        assert "tt.rooms" in phases
        assert "tt.fitness" in phases
    finally:
        obs_prof._reset_scope_maps()


# -------------------------------------------------------------------- CLI


def test_render_lists_every_phase_and_unattributed(tmp_path):
    attr = obs_prof.attribute(_write_capture(str(tmp_path)))
    text = obs_prof.render(attr)
    assert "tt.sweep" in text and "tt.rooms" in text
    assert "unattributed" in text
    assert "custom-call.9" in text       # top op named in the table


def test_diff_and_render_diff(tmp_path):
    a = obs_prof.attribute(_write_capture(str(tmp_path / "a")))
    b = json.loads(json.dumps(a))
    b["phases"]["sweep"]["seconds"] = 2 * a["phases"]["sweep"]["seconds"]
    d = obs_prof.diff(a, b)
    assert d["rows"]["sweep"]["delta_s"] == pytest.approx(
        a["phases"]["sweep"]["seconds"])
    assert d["rows"]["rooms"]["delta_s"] == 0.0
    assert "unattributed" in d["rows"]
    text = obs_prof.render_diff(d)
    assert "tt.sweep" in text and "->" in text


def test_main_hotspots_capture_dir_and_json(tmp_path, capsys):
    root = _write_capture(str(tmp_path))
    assert obs_prof.main_hotspots([root]) == 0
    out = capsys.readouterr().out
    assert "tt.sweep" in out and "unattributed" in out
    assert obs_prof.main_hotspots([root, "--json", "--top", "1"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["phases"]["sweep"]["seconds"] == pytest.approx(100e-6)


def test_main_hotspots_log_input_and_diff(tmp_path, capsys):
    """A records log is a first-class input: the newest profEntry
    renders; --diff takes one side from a log and one from a capture
    dir."""
    root = _write_capture(str(tmp_path))
    attr = obs_prof.attribute(root)
    log = tmp_path / "records.jsonl"
    with open(log, "w", encoding="utf-8") as f:
        obs_prof.publish(attr, registry=MetricsRegistry(), out=f)
    assert obs_prof.main_hotspots([str(log)]) == 0
    assert "tt.sweep" in capsys.readouterr().out
    assert obs_prof.main_hotspots(["--diff", str(log), root]) == 0
    out = capsys.readouterr().out
    assert "phase diff" in out
    assert obs_prof.main_hotspots(["--diff", str(log), root,
                                   "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rows"]["sweep"]["delta_s"] == pytest.approx(0.0,
                                                            abs=1e-9)


def test_main_hotspots_missing_input_is_exit_1(tmp_path, capsys):
    assert obs_prof.main_hotspots([str(tmp_path / "gone")]) == 1
    assert "tt hotspots:" in capsys.readouterr().err


def test_main_hotspots_help_and_usage(capsys):
    assert obs_prof.main_hotspots(["--help"]) == 0
    assert "usage" in capsys.readouterr().out
    with pytest.raises(SystemExit):
        obs_prof.main_hotspots([])           # no input
    with pytest.raises(SystemExit):
        obs_prof.main_hotspots(["--diff", "only-one"])


def test_tt_stats_phases_section(tmp_path):
    """`tt stats` grows a "== phases" section from profEntry records:
    per-phase p50/p95 share across captures, unattributed included."""
    from timetabling_ga_tpu.obs import logstats
    root = _write_capture(str(tmp_path))
    attr = obs_prof.attribute(root)
    buf = io.StringIO()
    obs_prof.publish(attr, registry=MetricsRegistry(), out=buf)
    obs_prof.publish(attr, registry=MetricsRegistry(), out=buf)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    text = logstats.summarize(recs)
    assert "== phases (2 profEntry records)" in text
    assert "sweep: share p50" in text
    assert "unattributed: share p50" in text


# ------------------------------------------------------------------- gate


def _gate():
    sys.path.insert(0, TOOLS)
    try:
        import perf_gate
    finally:
        sys.path.remove(TOOLS)
    return perf_gate


def test_perf_gate_detects_synthetic_regression(tmp_path, capsys):
    """A 20% gens/s drop must trip the gate (tolerance 0.15) and a
    matched fresh run must pass — the ISSUE's calibration case."""
    pg = _gate()
    base = {"gens/s parallel": 1.25, "gens/s scan": 4.0,
            "ms/gen sweep128": 900.0, "soak jobs/min": 30.0}
    fresh_ok = dict(base)
    fresh_bad = dict(base, **{"gens/s parallel": 1.0})   # -20%
    rows = pg.check(fresh_bad, base, tolerance=0.15)
    by = {r["metric"]: r for r in rows}
    assert by["gens/s parallel"]["status"] == "regression"
    assert by["gens/s parallel"]["change"] == pytest.approx(-0.2)
    assert by["gens/s scan"]["status"] == "ok"
    assert all(r["status"] == "ok"
               for r in pg.check(fresh_ok, base, tolerance=0.15))


def test_perf_gate_directions():
    """ms/gen is lower-is-better: latency DOUBLING is a regression,
    halving is an improvement; throughput is the mirror image."""
    pg = _gate()
    base = {"ms/gen sweep128": 100.0, "gens/s scan": 2.0}
    worse = pg.check({"ms/gen sweep128": 200.0, "gens/s scan": 4.0},
                     base)
    by = {r["metric"]: r for r in worse}
    assert by["ms/gen sweep128"]["status"] == "regression"
    assert by["ms/gen sweep128"]["change"] == pytest.approx(-1.0)
    assert by["gens/s scan"]["status"] == "ok"
    assert by["gens/s scan"]["change"] == pytest.approx(1.0)


def test_perf_gate_skips_missing_metrics_and_refuses_vacuous_pass():
    pg = _gate()
    rows = pg.check({"gens/s scan": 2.0}, {"gens/s scan": 2.0})
    by = {r["metric"]: r for r in rows}
    assert by["gens/s scan"]["status"] == "ok"
    assert by["soak jobs/min"]["status"] == "skipped"
    # nothing comparable at all -> the verdict is REGRESSION, never a
    # silent pass on two empty files
    empty = pg.check({}, {})
    assert all(r["status"] == "skipped" for r in empty)
    assert "REGRESSION" in pg.render(empty, 0.25)


def test_perf_gate_main_exit_codes(tmp_path):
    """End to end through main(): a self-comparison passes (exit 0), a
    doctored regression fails (exit 1), a missing file is a usage
    error (exit 2). Baselines exercise BOTH accepted shapes: the raw
    bench JSON and the driver {tail: ...} wrapper."""
    pg = _gate()
    doc = {"generation_parallel": {"gen_per_sec": 1.25},
           "generation_scan": {"gen_per_sec": 4.0},
           "generation_sweep_128": {"ms_per_gen": 900.0},
           "soak": {"jobs_per_min": 30.0}}
    base = tmp_path / "base.json"
    base.write_text(json.dumps(doc), encoding="utf-8")
    wrapper = tmp_path / "wrapped.json"
    wrapper.write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 0,
         "tail": json.dumps(doc), "parsed": None}), encoding="utf-8")
    assert pg.extract_metrics(str(base)) == pg.extract_metrics(
        str(wrapper))

    fresh = tmp_path / "fresh.json"
    fresh.write_text(json.dumps(doc), encoding="utf-8")
    assert pg.main([str(fresh), "--baseline", str(base)]) == 0
    assert pg.main([str(fresh), "--baseline", str(wrapper),
                    "--json"]) == 0

    bad_doc = json.loads(json.dumps(doc))
    bad_doc["generation_parallel"]["gen_per_sec"] = 0.5  # -60%
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_doc), encoding="utf-8")
    assert pg.main([str(bad), "--baseline", str(base)]) == 1
    # inside tolerance: a 60% drop passes a 90% band
    assert pg.main([str(bad), "--baseline", str(base),
                    "--tolerance", "0.9"]) == 0

    assert pg.main([str(tmp_path / "gone.json"),
                    "--baseline", str(base)]) == 2
    assert pg.main([]) == 2


def test_ci_check_perf_mode_wiring():
    """`ci_check.sh --perf FILE` exists and routes to perf_gate.py."""
    with open(os.path.join(TOOLS, "ci_check.sh"),
              encoding="utf-8") as f:
        sh = f.read()
    assert "--perf" in sh and "perf_gate.py" in sh


# ------------------------------------------------------------- e2e (slow)


@pytest.mark.slow
def test_real_capture_attribution_floor(tmp_path):
    """The acceptance floor on a REAL capture: profile a live jitted
    generation+sweep loop and attribute >= 90% of device op time to
    tt.* phases (unattributed < 10%)."""
    jax = pytest.importorskip("jax")
    from timetabling_ga_tpu.ops import ga as ga_ops
    from timetabling_ga_tpu.problem import random_instance

    prob = random_instance(2, n_events=80, n_rooms=8, n_features=5,
                           n_students=60, attend_prob=0.08)
    pa = prob.device_arrays()
    cfg = ga_ops.GAConfig(pop_size=64)
    key = jax.random.PRNGKey(0)
    state = ga_ops.init_population(pa, key, cfg.pop_size)

    def step(state, key):
        return ga_ops.generation(pa, key, state, cfg)

    run = jax.jit(step)
    exe = run.lower(state, key).compile()
    obs_prof._reset_scope_maps()
    try:
        obs_prof.note_executable(exe)
        # keys presplit OUTSIDE the trace window: a per-iteration
        # fold_in would dispatch its own (un-noted) threefry module
        # inside the capture and pollute `unattributed`
        keys = list(jax.random.split(key, 20))
        state = run(state, keys[0])                  # warm
        jax.block_until_ready(state)
        cap = str(tmp_path / "cap")
        jax.profiler.start_trace(cap)
        for k in keys:
            state = run(state, k)
        jax.block_until_ready(state)
        jax.profiler.stop_trace()
        obs_prof.write_scope_map(cap)
        attr = obs_prof.attribute(cap)
    finally:
        obs_prof._reset_scope_maps()
    assert attr["n_events"] > 0
    assert attr["phases"], obs_prof.render(attr)
    assert attr["unattributed_frac"] < 0.10, obs_prof.render(attr)
