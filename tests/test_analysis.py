"""tt-analyze (timetabling_ga_tpu/analysis) tests.

Every rule family must fire on its seeded-violation fixture at the
expected file:line (the fixtures carry `# EXPECT TTxxx` markers that
these tests read, so fixture and assertion cannot drift), the clean
fixture must produce zero findings, and — the regression that matters
most — the shipped package itself must be strict-clean.

The analyzer is stdlib-only; no jax/device needed here.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from timetabling_ga_tpu.analysis import run_analysis
from timetabling_ga_tpu.analysis.config import (
    ALL_RULES, AnalyzerConfig, load_compat_table, load_config)
from timetabling_ga_tpu.analysis.core import suppressions

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analyzer_fixtures")

_EXPECT_RE = re.compile(r"#\s*EXPECT\s+(TT\d{3})")


def expected_findings(fixture: str) -> set[tuple[str, int]]:
    """(rule, line) pairs the fixture's `# EXPECT TTxxx` markers declare."""
    out = set()
    with open(os.path.join(FIXTURES, fixture), encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            for rule in _EXPECT_RE.findall(line):
                out.add((rule, lineno))
    return out


def fixture_config() -> AnalyzerConfig:
    cfg = load_config(REPO)
    cfg.root = REPO
    # the sync/collective rules only audit configured modules; opt the
    # fixtures in
    cfg.dispatch_modules = list(cfg.dispatch_modules) + ["viol_sync.py",
                                                         "viol_cost.py",
                                                         "viol_quality.py",
                                                         "viol_flight.py",
                                                         "viol_edit.py",
                                                         "interproc/loop.py"]
    cfg.sharded_modules = (list(cfg.sharded_modules)
                           + ["viol_collective.py", "viol_quality.py"])
    cfg.fleet_modules = list(cfg.fleet_modules) + ["viol_fleet.py",
                                                   "viol_gw_api.py",
                                                   "viol_scale.py"]
    cfg.accord_modules = list(cfg.accord_modules) + ["viol_accord.py"]
    return cfg


def analyze_fixture(fixture: str):
    path = os.path.join(FIXTURES, fixture)
    return run_analysis([path], fixture_config())


@pytest.mark.parametrize("fixture", [
    "viol_trace.py",       # TT101 tracer-unsafe control flow
    "viol_boolop.py",      # TT102 and/or short-circuit on traced values
    "viol_recompile.py",   # TT201/TT202 recompile hazards
    "viol_donate.py",      # TT203 donated-buffer reuse
    "viol_sync.py",        # TT301 hidden host syncs
    "viol_collective.py",  # TT302 collective-bearing random ops
    "viol_rng.py",         # TT401 RNG key reuse
    "viol_loopkey.py",     # TT402 loop-carried key reuse
    "viol_api.py",         # TT501 pinned API surface
    "viol_attr_api.py",    # TT502 attribute-access API pinning
    "viol_obs_clock.py",   # TT601 wall clocks / spans in trace targets
    "viol_obs_http.py",    # TT602 blocking I/O / registry writes in
    #                        HTTP handler paths
    "viol_cost.py",        # TT603 cost/memory introspection in trace
    #                        targets and dispatch loops
    "viol_quality.py",     # TT604 host-side quality recompute in
    #                        dispatch loops + collectives in quality
    #                        reduction paths
    "viol_fleet.py",       # TT605 device work / unbounded socket
    #                        reads on fleet handler paths
    "viol_gw_api.py",      # TT602/TT605 on *Api handler-path roots
    #                        (the fleet fronts' enqueue-or-read-only
    #                        api surfaces — tt-obs v5)
    "viol_flight.py",      # TT606 bundle serialization in dispatch
    #                        loops / trace targets + flight-recorder
    #                        dump triggers on handler paths (tt-flight)
    "viol_usage.py",       # TT607 usage-ledger mutation in trace
    #                        targets / handler paths + handler-side
    #                        metering clocks (tt-meter)
    "viol_scale.py",       # TT608 fleet actuator calls on handler
    #                        paths / dispatcher-tick bodies (tt-scale)
    "viol_edit.py",        # TT309 edit-solve (diff/transplant) calls
    #                        in dispatch loops / trace targets (tt-edit)
    "viol_accord.py",      # TT307 collectives / multihost_utils in
    #                        accord modules (tt-accord side channel)
    "viol_supervisor.py",  # TT307 collectives inside *Supervisor
    #                        recovery-policy bodies (with the healthy-
    #                        path collective as a negative)
    "viol_prof.py",        # TT310 phase scopes outside the tt-prof
    #                        registry + scopes on handler paths
    #                        (tt-prof), with registered-scope negatives
])
def test_rule_fires_at_expected_lines(fixture):
    """Each rule family fires exactly at the marked (rule, line) pairs —
    no misses, no extras."""
    expected = expected_findings(fixture)
    assert expected, f"fixture {fixture} declares no EXPECT markers"
    got = {(f.rule, f.line) for f in analyze_fixture(fixture)}
    assert got == expected


def test_clean_fixture_has_zero_findings():
    assert analyze_fixture("clean.py") == []


def test_interproc_rules_fire_across_module_boundary():
    """The whole-program rules (TT303/TT304/TT305/TT306) must localize
    each seeded CROSS-MODULE violation — factory, donation and
    sanctioned fetch all declared in interproc/core.py, broken in
    interproc/loop.py — to the exact file:line, and the clean core
    module (plus loop.py's clean resident-dispatch idiom) must stay
    silent."""
    pkg = os.path.join(FIXTURES, "interproc")
    expected = set()
    for name in sorted(os.listdir(pkg)):
        if name.endswith(".py"):
            for rule, line in expected_findings(
                    os.path.join("interproc", name)):
                expected.add((rule, name, line))
    assert expected, "interproc fixtures declare no EXPECT markers"
    got = {(f.rule, os.path.basename(f.path), f.line)
           for f in run_analysis([pkg], fixture_config())}
    assert got == expected
    # all four whole-program rules exercised, nothing in core.py
    assert {r for r, _, _ in got} == {"TT303", "TT304", "TT305",
                                      "TT306"}
    assert all(name == "loop.py" for _, name, _ in got)


def test_warn_unused_ignores(tmp_path):
    """--warn-unused-ignores: a marker that suppresses nothing is
    TT901; the USED marker in viol_api.py stays silent."""
    cfg = fixture_config()
    cfg.warn_unused_ignores = True
    findings = run_analysis(
        [os.path.join(FIXTURES, "viol_api.py")], cfg)
    assert not any(f.rule == "TT901" for f in findings)

    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # tt-analyze: ignore[TT301]\n"
                     '"""prose mentioning # tt-analyze: ignore is not '
                     'a marker"""\n', encoding="utf-8")
    findings = run_analysis([str(stale)], cfg)
    assert [(f.rule, f.line) for f in findings] == [("TT901", 1)]


def test_sarif_export_matches_golden():
    """`--sarif` output is pinned by a golden file: schema/version,
    the rules table, and 1-based columns must not drift."""
    from timetabling_ga_tpu.analysis import _rule_docs
    from timetabling_ga_tpu.analysis.sarif import to_sarif
    findings = analyze_fixture("viol_api.py")
    assert findings, "golden needs a non-empty findings list"
    got = json.dumps(to_sarif(findings, _rule_docs()),
                     indent=2, sort_keys=True) + "\n"
    with open(os.path.join(FIXTURES, "sarif_golden.json"),
              encoding="utf-8") as f:
        assert got == f.read()


def test_shipped_package_is_strict_clean():
    """`--strict` over the real package must stay at zero findings; a
    new violation in ops/runtime/parallel fails here before it fails in
    CI."""
    cfg = load_config(REPO)
    cfg.root = REPO
    findings = run_analysis(["timetabling_ga_tpu"], cfg)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_compat_table_loads_without_jax():
    cfg = load_config(REPO)
    cfg.root = REPO
    table = load_compat_table(cfg)
    assert "jax" in table
    assert "jax.numpy" in table
    # the seed-breaking symbol must NOT be blessed at the top level
    assert "shard_map" not in table["jax"]


def test_suppression_parsing():
    src = (
        "x = 1  # tt-analyze: ignore[TT301]\n"
        "# tt-analyze: ignore\n"
        "y = 2\n"
        "z = 3\n"
    )
    supp = suppressions(src)
    assert supp[1] == {"TT301"}
    assert supp[2] is None          # bare ignore: all rules
    assert supp[3] is None          # comment line covers the line below
    assert 4 not in supp


def test_inline_suppression_filters_finding():
    # viol_api.py line with `pure_callback` carries an inline ignore;
    # without suppression handling it would be a TT501 finding
    findings = analyze_fixture("viol_api.py")
    assert not any("pure_callback" in f.message for f in findings)


def test_cli_json_and_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base = [sys.executable, "-m", "timetabling_ga_tpu.analysis",
            "--root", REPO]

    # strict over the shipped tree: exit 0
    r = subprocess.run(base + ["--strict", "timetabling_ga_tpu"],
                       capture_output=True, text=True, cwd=REPO, env=env,
                       timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr

    # strict over a violation fixture: exit nonzero, JSON report carries
    # the findings
    r = subprocess.run(
        base + ["--strict", "--json",
                os.path.join(FIXTURES, "viol_api.py")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 1
    report = json.loads(r.stdout)
    assert report["count"] == len(report["findings"]) > 0
    assert all(f["rule"] == "TT501" for f in report["findings"])
    # single-parse driver reports analyzer cost like a bench leg
    assert report["timing"]["total_s"] > 0
    assert report["timing"]["per_rule_s"]

    # non-strict is advisory: findings reported, exit 0
    r = subprocess.run(
        base + [os.path.join(FIXTURES, "viol_api.py")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0
    assert "TT501" in r.stdout


def test_rules_filter():
    cfg = fixture_config()
    cfg.rules = ["TT401"]
    path = os.path.join(FIXTURES, "viol_api.py")
    assert run_analysis([path], cfg) == []  # TT501 disabled


def test_all_rules_registered():
    from timetabling_ga_tpu.analysis import _rule_modules
    assert set(_rule_modules()) == set(ALL_RULES)


def test_minimal_toml_parser_on_repo_pyproject():
    """The no-tomllib/no-tomli fallback must produce the same usable
    config as the real parsers — in particular regex values must come
    through with escapes DECODED (a literal '\\\\w' pattern would
    silently disable TT301's device-producer matching)."""
    from timetabling_ga_tpu.analysis.config import _parse_toml_minimal
    with open(os.path.join(REPO, "pyproject.toml"), encoding="utf-8") as f:
        data = _parse_toml_minimal(f.read())
    section = data["tool"]["tt-analyze"]
    assert section["paths"] == ["timetabling_ga_tpu"]
    assert "TT302" in section["rules"]
    pats = section["device-producers"]
    assert any(re.match(p, "cached_runner") for p in pats), pats
    assert any(re.match(p, "jax.jit") for p in pats), pats
