"""Dispatch-core parity + fault matrix (runtime/dispatch_core.py).

The extraction contract (ROADMAP item 1): porting all three dispatch
loops — engine run loop, serve scheduler, fleet replica drive — onto
the shared core must not move a single record. The parity tests replay
the committed PRE-refactor captures (tests/parity_fixtures/, written by
`python -m tests.parity_recipes` on the pre-core tree) and assert
bit-identity in the strip_timing domain. The fault matrix re-runs the
dispatch/fetch x hang/die cells through the shared core's injection
points (runtime/faults.py), pinning that extraction moved the fetch
watchdog and the fault sites, not just the happy path.
"""

import io
import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import parity_recipes  # noqa: E402  (tests/ is not a package)
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import dispatch_core as dcore
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import RunConfig

FIXDIR = parity_recipes.FIXDIR


def _golden(name):
    with open(os.path.join(FIXDIR, f"{name}_stream.json"),
              encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------- parity

def test_engine_stream_parity(engine_stream_baseline):
    """The engine loop on the shared core emits the pre-refactor
    record stream bit-identically (strip_timing domain). Reuses the
    session baseline run — same config as the committed capture."""
    _, records = engine_stream_baseline
    assert jsonl.strip_timing(records) == _golden("engine")


def test_serve_stream_parity():
    """Packing scheduler on the shared core: two same-bucket jobs
    through packing / time-slicing / park-resume / telemetry decode
    reproduce the pre-refactor stream exactly."""
    assert parity_recipes.serve_stream() == _golden("serve")


def test_fleet_stream_parity():
    """Replica drive loop on the shared core (CommandFence inbox,
    submit -> drive -> drain): stream identical to pre-refactor."""
    assert parity_recipes.fleet_stream() == _golden("fleet")


# ------------------------------------------------------- core unit tests

def test_pipeline_depth2_discipline():
    """DispatchPipeline: at most one in-flight chunk; pipelined submit
    retires the predecessor WITH the successor dispatched (passed as
    inflight), drain is the loop-exit barrier, abandon forgets without
    retiring — the recovery teardown."""
    calls = []
    pipe = dcore.DispatchPipeline(
        lambda chunk, inflight=None: calls.append((chunk, inflight)),
        enabled=True)
    pipe.submit("a")
    pipe.submit("b")
    pipe.submit("c")
    assert calls == [("a", "b"), ("b", "c")]
    pipe.drain()
    assert calls[-1] == ("c", None) and pipe.pending is None

    calls.clear()
    pipe.enabled = False
    pipe.submit("d")                 # serial: retire immediately
    assert calls == [("d", None)]

    pipe.enabled = True
    pipe.submit("e")
    assert pipe.abandon() == "e"     # recovery: forget, never process
    assert pipe.pending is None and len(calls) == 1


def test_command_fence_poll_and_wait():
    """CommandFence: poll is the non-blocking busy-fence drain, wait
    the bounded idle tick — both return None on an empty inbox."""
    fence = dcore.CommandFence()
    assert fence.poll() is None
    fence.put(("submit", "j1"))
    assert fence.poll() == ("submit", "j1")
    t0 = time.monotonic()
    assert fence.wait(timeout=0.05) is None
    assert time.monotonic() - t0 < 5.0
    fence.put(("drain",))
    assert fence.wait(timeout=0.05) == ("drain",)


# ------------------------------------------------ fault matrix: fetch x

@pytest.fixture
def _fault_cleanup():
    yield
    faults.install(None)
    dcore.set_fetch_timeout(None)


def test_fetch_hang_times_out_through_core(_fault_cleanup):
    """fetch x hang: the shared core's watchdog abandons a hung
    control-fence read at the deadline and raises the classified
    FetchTimeout — the hang is never slept through."""
    faults.install("fetch:1:hang")
    dcore.set_fetch_timeout(0.3)
    t0 = time.monotonic()
    with pytest.raises(dcore.FetchTimeout) as ei:
        dcore.fetch(np.arange(8))
    assert time.monotonic() - t0 < faults.HANG_S
    assert ei.value.tt_site == "fetch"
    from timetabling_ga_tpu.runtime import retry
    assert retry.is_transient(ei.value)


def test_fetch_die_surfaces_on_main_thread(_fault_cleanup):
    """fetch x die: a SystemExit on the watchdog thread must not
    vanish with the thread — the core re-raises it on the main loop,
    classified with the fetch site."""
    faults.install("fetch:1:die")
    dcore.set_fetch_timeout(5.0)
    with pytest.raises(SystemExit) as ei:
        dcore.fetch(np.arange(8))
    assert ei.value.tt_site == "fetch"


# --------------------------------------------- fault matrix: dispatch x

@pytest.fixture(scope="module")
def tim_file(tmp_path_factory):
    problem = random_instance(77, n_events=15, n_rooms=5, n_features=2,
                              n_students=10, attend_prob=0.1)
    path = tmp_path_factory.mktemp("dcore") / "tiny.tim"
    path.write_text(dump_tim(problem))
    return str(path)


def _go(tim_file, **kw):
    from timetabling_ga_tpu.runtime import engine
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    trace=True, **kw)
    best = engine.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def test_dispatch_hang_is_timing_only(tim_file, monkeypatch):
    """dispatch x hang: a dispatch-site stall (shortened hang) delays
    the run but changes nothing it emits — hang is a timing fault, and
    strip_timing is exactly the domain that proves it."""
    monkeypatch.setattr(faults, "HANG_S", 0.2)
    clean_best, clean = _go(tim_file, pipeline=False)
    best, lines = _go(tim_file, pipeline=False, faults="dispatch:2:hang")
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)
    assert not any("faultEntry" in x for x in lines)


def test_dispatch_die_aborts_run(tim_file):
    """dispatch x die: SystemExit at a dispatch site is NOT transient —
    the supervisor must re-raise, not recover; the run aborts."""
    with pytest.raises(SystemExit):
        _go(tim_file, pipeline=False, faults="dispatch:2:die")
    faults.install(None)
