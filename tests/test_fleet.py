"""tt-fleet (ISSUE 10): HTTP solve front, bucket-affine routing,
failover, drain.

The acceptance properties pinned here:

  1. AFFINITY — a mixed-bucket stream against 2 routed replicas keeps
     each bucket pinned to one replica (hit rate >= 0.9 after
     warm-up) and spreads distinct buckets across the fleet;
  2. FAILOVER — killing a replica mid-stream still completes every
     submitted job exactly once;
  3. RECORD IDENTITY — every routed job's record stream (modulo
     timing fields) is bit-identical to the same job solved on a bare
     unrouted SolveService;
  4. ISOLATION — a wedged gateway accept loop or routing decision
     (fault sites `gateway` / `route`) never stalls replica dispatch
     or writer drain;
  5. the /readyz wire contract the router parses: structured JSON
     (`{"ready": bool, "reasons": [...]}`), content-type
     application/json, with the `draining` / `no_ready_replica`
     reasons.
"""

import io
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from timetabling_ga_tpu.fleet.client import main_submit
from timetabling_ga_tpu.fleet.gateway import (
    Gateway, parse_solve_body, payload_counts)
from timetabling_ga_tpu.fleet.replicas import (
    FleetHTTPError, JobTail, ReplicaHandle, ReplicaSet, http_json,
    in_process_replica)
from timetabling_ga_tpu.fleet.router import NoReplicaError, Router
from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args)
from timetabling_ga_tpu.serve.service import SolveService

# bucket A: E<=32; bucket B: 32<E<=64 (default geometric floors)
_SHAPE_A = dict(n_events=12, n_rooms=3, n_features=2, n_students=8,
                attend_prob=0.2)
_SHAPE_B = dict(n_events=40, n_rooms=4, n_features=2, n_students=30,
                attend_prob=0.1)


def _problem(seed, shape):
    return random_instance(seed, **shape)


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    kw.setdefault("http", "127.0.0.1:0")
    return ServeConfig(**kw)


def _fleet_cfg(urls, **kw):
    kw.setdefault("listen", "127.0.0.1:0")
    kw.setdefault("probe_every", 0.1)
    kw.setdefault("poll_every", 0.05)
    kw.setdefault("dead_after", 2)
    return FleetConfig(replicas=list(urls), **kw)


def _wait_jobs(url, ids, timeout=120.0):
    """Poll the front until every id is terminal with settled records;
    returns {id: view}."""
    from urllib.parse import quote
    deadline = time.monotonic() + timeout
    views = {}
    while time.monotonic() < deadline:
        views = {j: http_json("GET", f"{url}/v1/jobs/{quote(j)}",
                              ok=(200,))
                 for j in ids}
        if all(v["state"] in ("done", "failed", "cancelled", "shed",
                              "rejected") for v in views.values()):
            return views
        time.sleep(0.1)
    raise AssertionError(
        f"jobs not terminal after {timeout}s: "
        f"{ {j: v['state'] for j, v in views.items()} }")


def _unrouted_streams(jobs):
    """{id: strip_timing(records)} for the SAME jobs on a bare
    SolveService — the record-identity baseline."""
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=5,
                                   pop_size=4, max_steps=8), out=buf)
    for job_id, problem, seed, gens in jobs:
        svc.submit(problem, job_id=job_id, seed=seed,
                   generations=gens)
    svc.drive()
    svc.close()
    per_job: dict = {}
    for line in buf.getvalue().splitlines():
        rec = json.loads(line)
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") is not None:
            per_job.setdefault(body["job"], []).append(rec)
    return {j: jsonl.strip_timing(rs) for j, rs in per_job.items()}


# ------------------------------------------------------------- protocol


def test_parse_solve_body_forms():
    assert parse_solve_body(b'{"tim": "1 2 3 4", "seed": 7}') == {
        "tim": "1 2 3 4", "seed": 7}
    # raw .tim text
    assert parse_solve_body(b"4 2 2 5\n10\n") == {"tim": "4 2 2 5\n10\n"}
    # unknown JSON keys are dropped, not errors
    assert "x" not in parse_solve_body(b'{"tim": "1 1 1 1", "x": 2}')
    with pytest.raises(ValueError):
        parse_solve_body(b"")
    with pytest.raises(ValueError):
        parse_solve_body(b'{"seed": 1}')       # neither tim nor problem
    with pytest.raises(ValueError):
        parse_solve_body(b'{"tim": ')          # bad JSON


def test_payload_counts_header_only():
    assert payload_counts({"tim": "12 3 2 8\nrest ignored"}) == (
        12, 3, 2, 8, 5, 9)
    assert payload_counts({"tim": "1 1 1 1", "n_days": 3,
                           "slots_per_day": 4}) == (1, 1, 1, 1, 3, 4)
    assert payload_counts({"problem": {
        "n_events": 9, "n_rooms": 2, "n_features": 1,
        "n_students": 5}}) == (9, 2, 1, 5, 5, 9)
    with pytest.raises(ValueError):
        payload_counts({"tim": "12 3"})        # short header
    with pytest.raises(ValueError):
        payload_counts({"tim": "a b c d"})


def test_parse_fleet_args():
    cfg = parse_fleet_args(["--listen", "127.0.0.1:0", "--replica",
                            "http://a:1", "--replica", "http://b:2",
                            "--probe-every", "0.2", "--",
                            "--backend", "cpu", "--lanes", "4"])
    assert cfg.replicas == ["http://a:1", "http://b:2"]
    assert cfg.probe_every == 0.2
    assert cfg.serve_args == ["--backend", "cpu", "--lanes", "4"]
    with pytest.raises(SystemExit):
        parse_fleet_args([])                   # no replicas
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "http://a:1", "--spawn", "2"])
    with pytest.raises(SystemExit):            # bad worker flags
        parse_fleet_args(["--spawn", "1", "--", "--bogus", "x"])
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "u", "--dead-after", "0"])


# ------------------------------------------------------------ record tail


def test_job_tail_tee_and_filter():
    base = io.StringIO()
    tail = JobTail(base, cap=3)
    # chunked writes must reassemble into lines
    tail.write('{"jobEntry": {"job": "a", "ev')
    tail.write('ent": "admitted"}}\n{"logEntry": {"best": 1}}\n')
    tail.write('{"logEntry": {"best": 2, "job": "a"}}\n')
    for i in range(5):
        tail.write(json.dumps(
            {"logEntry": {"best": i, "job": "b"}}) + "\n")
    assert base.getvalue().count("\n") == 8      # byte passthrough
    assert [r[next(iter(r))].get("event", r[next(iter(r))].get("best"))
            for r in tail.tail("a")] == ["admitted", 2]
    assert len(tail.tail("b")) == 3              # capped
    assert tail.tail("zzz") == []                # unknown job
    # the untagged record reached the stream but no tail
    assert '"best": 1' in base.getvalue()


# ----------------------------------------------------------- router unit


class _FakeHandle:
    def __init__(self, name, depth=0.0, hits=0.0, count=0.0):
        self.name = name
        self.ready = True
        self.dead = False
        self.queue_depth = depth
        self.compile_count = count
        self.compile_cache_hits = hits

    def compile_hit_rate(self):
        total = self.compile_count + self.compile_cache_hits
        return self.compile_cache_hits / total if total else 0.0


class _FakeSet:
    def __init__(self, handles):
        self.handles = handles

    def live(self):
        return [h for h in self.handles if not h.dead]


def test_router_affinity_and_scoring():
    r0, r1 = _FakeHandle("r0"), _FakeHandle("r1")
    router = Router(_FakeSet([r0, r1]))
    ba, bb = ("A",), ("B",)
    # first landing pins deterministically; repeats stay pinned
    first = router.route(ba)
    for _ in range(4):
        assert router.route(ba) is first
    # a second bucket spreads to the other replica (pin-count term)
    second = router.route(bb)
    assert second is not first
    assert router.hit_rate() == 1.0
    assert router.stats()["warmups"] == 2

    # not-ready home: the job DETOURS (a miss) but the pin stays —
    # the moment the home probes ready again the bucket returns to
    # its warm programs as a hit
    first.ready = False
    moved = router.route(ba)
    assert moved is second
    assert router.stats()["misses"] == 1
    assert router.stats()["repins"] == 0       # detour, not a repin
    first.ready = True
    back = router.route(ba)
    assert back is first
    assert router.stats()["misses"] == 1       # a warm hit, no churn

    # backlog dominates placement of a FRESH bucket
    second.queue_depth, first.queue_depth = 9.0, 0.0
    assert router.route(("C",)) is first

    # death: pins + warmth forgotten; survivors take over
    second.dead = True
    router.on_replica_dead(second.name)
    assert router.route(bb) is first
    # nothing live -> NoReplicaError
    first.dead = True
    with pytest.raises(NoReplicaError):
        router.route(ba)


def test_replica_set_boot_grace_and_restart():
    """A replica that has NEVER probed OK stays alive through the
    boot grace (a spawned worker pays a long jax import before it
    binds its port); once the grace expires it dies — or respawns,
    with its grace and probe state reset, until restarts run out."""
    deaths = []

    class _Proc:
        def poll(self):
            return None

        def terminate(self):
            pass

        def wait(self, timeout=None):
            return 0

    # nothing listens on this port: every probe fails fast
    h = ReplicaHandle("boot", "http://127.0.0.1:9")
    rs = ReplicaSet([h], dead_after=1, boot_grace=60.0,
                    probe_timeout=0.2,
                    on_death=lambda hh, r: deaths.append((hh.name, r)))
    rs.probe_all()
    assert not h.dead and deaths == []         # booting, not dead
    h.born -= 120.0                            # grace expired
    rs.probe_all()
    assert h.dead and deaths == [("boot", False)]

    # a spawned handle respawns (probe state reset) then dies for good
    deaths.clear()
    h2 = ReplicaHandle("w", "http://127.0.0.1:9", proc=_Proc(),
                       respawn=_Proc)
    h2.ok_once = True                          # it HAD come up once
    rs2 = ReplicaSet([h2], dead_after=1, boot_grace=60.0,
                     probe_timeout=0.2, max_restarts=1,
                     on_death=lambda hh, r: deaths.append(r))
    rs2.probe_all()
    assert deaths == [True] and not h2.dead    # respawned
    assert h2.restarts == 1 and not h2.ok_once
    h2.born -= 120.0                           # the respawn never
    rs2.probe_all()                            # comes up either
    assert h2.dead and deaths == [True, False]


def test_router_compile_hit_rate_tie_break():
    cold = _FakeHandle("cold", count=10.0, hits=0.0)
    warm = _FakeHandle("warm", count=10.0, hits=90.0)
    router = Router(_FakeSet([cold, warm]))
    # equal depth, equal pins: the measured compile-hit rate decides
    assert router.route(("N",)) is warm


# ----------------------------------------------------- /readyz contract


def test_readyz_structured_json_contract():
    """Satellite: the router PARSES /readyz — body shape, content
    type, and the status-code contract are wire-pinned here."""
    reg = MetricsRegistry()
    srv = obs_http.ObsServer("127.0.0.1:0", registry=reg).start()
    try:
        with urllib.request.urlopen(srv.url + "/readyz",
                                    timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"] == "application/json"
            body = json.loads(resp.read())
        assert body["ready"] is True and body["reasons"] == []

        # draining flips 503 with a parseable reason
        reg.gauge("serve.draining").set(1.0)
        try:
            urllib.request.urlopen(srv.url + "/readyz", timeout=5)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers["Content-Type"] == "application/json"
            body = json.loads(e.read())
        assert body["ready"] is False
        assert "draining" in body["reasons"]
    finally:
        srv.close()


def test_readyz_no_ready_replica_reason():
    reg = MetricsRegistry()
    reg.gauge("fleet.replicas_ready").set(0.0)
    ok, detail = obs_http.readiness(reg)
    assert not ok and "no_ready_replica" in detail["reasons"]
    reg.gauge("fleet.replicas_ready").set(2.0)
    ok, detail = obs_http.readiness(reg)
    assert ok and detail["reasons"] == []


# ------------------------------------------------------- replica front


def test_replica_http_lifecycle():
    """One in-process replica: solve, status, rejection, duplicate,
    cancel, drain — all over the /v1 protocol, with /readyz flipping
    to `draining` and the record stream drained on exit."""
    rep, _ = in_process_replica(_serve_cfg(), "rx")
    url = rep.url
    try:
        tim = dump_tim(_problem(0, _SHAPE_A))
        acc = http_json("POST", url + "/v1/solve",
                        {"tim": tim, "id": "ok1", "seed": 1,
                         "generations": 10})
        assert acc == {"id": "ok1", "state": "accepted"}
        # duplicate id refused while the first lives
        dup = http_json("POST", url + "/v1/solve",
                        {"tim": tim, "id": "ok1"}, ok=(409,))
        assert dup["error"] == "duplicate job id"
        # a garbage instance is REJECTED by the drive loop, recorded,
        # and the replica keeps serving
        http_json("POST", url + "/v1/solve",
                  {"tim": "9 9 9 9\nnot numbers at all"},
                  ok=(202,))
        # unknown job
        with pytest.raises(FleetHTTPError):
            http_json("GET", url + "/v1/jobs/nope", ok=(200,))
        # a long job we cancel mid-flight
        http_json("POST", url + "/v1/solve",
                  {"tim": tim, "id": "long1", "seed": 2,
                   "generations": 500})
        http_json("DELETE", url + "/v1/jobs/long1", ok=(202,))
        # an id with a quotable character round-trips (clients QUOTE
        # the URL segment; the handler must unquote it back)
        http_json("POST", url + "/v1/solve",
                  {"tim": tim, "id": "sp 1", "seed": 6,
                   "generations": 5})

        views = _wait_jobs(url, ["ok1", "long1", "sp 1"])
        assert views["sp 1"]["state"] == "done"
        assert views["ok1"]["state"] == "done"
        assert views["ok1"]["result"]["gens"] == 10
        kinds = [next(iter(r)) for r in views["ok1"]["records"]]
        assert "jobEntry" in kinds and "solution" in kinds
        assert views["long1"]["state"] == "cancelled"

        # drain: no new work, /readyz says so, loop exits, writer
        # drained
        http_json("POST", url + "/v1/drain", {}, ok=(200,))
        assert rep.drained.wait(30)
        rz = http_json("GET", url + "/readyz", ok=(503,))
        assert "draining" in rz["reasons"]
        refused = http_json("POST", url + "/v1/solve", {"tim": tim},
                            ok=(503,))
        assert refused["error"] == "draining"
        assert not rep.svc.writer.alive()       # closed + drained
        stream_events = [
            json.loads(ln)["jobEntry"]["event"]
            for ln in rep.tail._stream.getvalue().splitlines()
            if "jobEntry" in json.loads(ln)]
        assert "done" in stream_events
        assert "rejected" in stream_events
        assert "cancelled" in stream_events
    finally:
        rep.kill()


# --------------------------------------------- acceptance: fleet e2e


def test_fleet_acceptance_affinity_failover_record_identity():
    """ISSUE 10 acceptance: gateway + 2 in-process replicas solve a
    mixed-bucket stream with affinity >= 0.9 after warm-up; killing
    one replica mid-stream still completes every job exactly once;
    and every job's record stream is bit-identical to the same job
    solved unrouted (modulo timing fields)."""
    rep0, h0 = in_process_replica(_serve_cfg(), "r0")
    rep1, h1 = in_process_replica(_serve_cfg(), "r1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    jobs = []      # (id, problem, seed, gens) — the baseline replays
    try:
        # phase 1: interleaved 2-bucket stream
        ids1 = []
        for i in range(8):
            shape = _SHAPE_A if i % 2 == 0 else _SHAPE_B
            p = _problem(100 + i, shape)
            jid = f"p1-{i}"
            jobs.append((jid, p, i, 10))
            ids1.append(jid)
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": i,
                       "generations": 10})
        views1 = _wait_jobs(gw.url, ids1)
        assert all(v["state"] == "done" for v in views1.values())
        stats = gw.router.stats()
        assert stats["affinity_hit_rate"] >= 0.9
        # two buckets spread over two replicas, each pinned to one
        assert len(stats["pins"]) == 2
        assert sorted(stats["pins"].values()) == ["r0", "r1"]

        # phase 2: longer jobs, then kill a replica MID-STREAM — the
        # kill waits until a phase-2 job is observably in flight on
        # r0, so failover is guaranteed to have real work to move
        ids2 = []
        gens2 = 200                         # 40 quanta: can't finish
        #                                     inside the kill latency
        for i in range(6):
            shape = _SHAPE_A if i % 2 == 0 else _SHAPE_B
            p = _problem(200 + i, shape)
            jid = f"p2-{i}"
            jobs.append((jid, p, 50 + i, gens2))
            ids2.append(jid)
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": 50 + i,
                       "generations": gens2})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                inflight = [j for j in gw.jobs.values()
                            if j.id.startswith("p2-")
                            and j.replica == "r0"
                            and not j.terminal()]
            if inflight:
                break
            time.sleep(0.02)
        assert inflight, "no phase-2 job ever in flight on r0"
        rep0.kill()
        views2 = _wait_jobs(gw.url, ids2, timeout=180)
        assert all(v["state"] == "done" for v in views2.values())
        # every job of BOTH phases completed exactly once: exactly
        # one terminal jobEntry and one solution record per stream
        all_views = {**views1, **_wait_jobs(gw.url, ids1 + ids2)}
        for jid, view in all_views.items():
            events = [r["jobEntry"]["event"] for r in view["records"]
                      if "jobEntry" in r]
            assert events.count("done") == 1, (jid, events)
            assert sum(1 for r in view["records"]
                       if "solution" in r) == 1
        # record identity vs the bare unrouted service — including
        # the jobs that failed over mid-flight
        baseline = _unrouted_streams(jobs)
        for jid, view in all_views.items():
            assert jsonl.strip_timing(view["records"]) \
                == baseline[jid], f"stream diverged for {jid}"
        # the death was observed and failover engaged
        assert gw.replicas.get("r0").dead
        assert gw.registry.counter("fleet.jobs_failed_over").value \
            >= 1
    finally:
        gw.request_drain()
        gw.drained.wait(30)
        gw.close()
        rep0.kill()
        rep1.kill()


def test_cancel_survives_failover():
    """A job cancelled while its replica is dying must NOT be
    resurrected by failover onto the surviving replica: the 202 the
    client got for its DELETE stays the truth."""
    rep0, h0 = in_process_replica(_serve_cfg(), "c0")
    rep1, h1 = in_process_replica(_serve_cfg(), "c1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    try:
        p = _problem(600, _SHAPE_A)
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "cx", "seed": 1,
                   "generations": 5000})   # cannot finish in time
        deadline = time.monotonic() + 30
        view = {}
        while time.monotonic() < deadline:
            view = http_json("GET", gw.url + "/v1/jobs/cx",
                             ok=(200,))
            if view.get("replica"):
                break
            time.sleep(0.05)
        assert view.get("replica"), "job never routed"
        victim = rep0 if view["replica"] == "c0" else rep1
        victim.kill()                       # remote cancel will fail
        http_json("DELETE", gw.url + "/v1/jobs/cx", ok=(202,))
        views = _wait_jobs(gw.url, ["cx"], timeout=60)
        assert views["cx"]["state"] == "cancelled", views["cx"]
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


def test_gateway_drain_finishes_parked_jobs():
    """A drain requested while jobs are parked mid-budget lets them
    FINISH (full generation budget, state done — not cancelled), then
    drains the owned replicas, which exit their drive loops."""
    rep, handle = in_process_replica(_serve_cfg(), "rd")
    gw = Gateway(_fleet_cfg([handle.url]), [handle],
                 owned=True).start()
    try:
        ids = []
        for i in range(3):
            p = _problem(300 + i, _SHAPE_A)
            ids.append(f"d{i}")
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": f"d{i}", "seed": i,
                       "generations": 15})    # 3 quanta -> parks
        http_json("POST", gw.url + "/v1/drain", {}, ok=(200,))
        # new work refused the moment the drain is requested
        refused = http_json("POST", gw.url + "/v1/solve",
                            {"tim": dump_tim(_problem(9, _SHAPE_A))},
                            ok=(503,))
        assert "draining" in refused.get("reasons", [])
        assert gw.drained.wait(120), "gateway drain never completed"
        views = {j: http_json("GET", f"{gw.url}/v1/jobs/{j}",
                              ok=(200,)) for j in ids}
        for j, v in views.items():
            assert v["state"] == "done", (j, v["state"], v["error"])
            assert v["result"]["gens"] == 15
        # owned replica was drained too: drive loop exited cleanly
        assert rep.drained.wait(30)
    finally:
        gw.close()
        rep.kill()


# ------------------------------------------------- fault-site isolation


def test_wedged_gateway_never_stalls_replica():
    """`gateway:1:hang` parks the gateway's accept loop at startup:
    the front is unreachable, but a replica served directly keeps
    dispatching and its writer drains on close — the isolation
    contract of the new fault sites."""
    rep, handle = in_process_replica(_serve_cfg(), "ri")
    try:
        gw = Gateway(_fleet_cfg([handle.url],
                                faults="gateway:1:hang"),
                     [handle]).start()
        try:
            # the accept loop is parked; the replica solves anyway
            tim = dump_tim(_problem(7, _SHAPE_A))
            http_json("POST", rep.url + "/v1/solve",
                      {"tim": tim, "id": "iso1", "seed": 3,
                       "generations": 10})
            views = _wait_jobs(rep.url, ["iso1"], timeout=60)
            assert views["iso1"]["state"] == "done"
        finally:
            gw.close()
            faults.install(None)
    finally:
        rep.stop(timeout=60)
        assert rep.drained.is_set()             # writer drained
        assert not rep.svc.writer.alive()


def test_route_die_kills_dispatcher_not_replicas():
    """`route:1:die` ends the dispatcher thread on the first routing
    decision: the gateway's /healthz dispatcher probe goes false and
    the routed job stays `accepted` — while the replica keeps solving
    direct submissions untouched."""
    rep, handle = in_process_replica(_serve_cfg(), "rj")
    gw = Gateway(_fleet_cfg([handle.url], faults="route:1:die"),
                 [handle]).start()
    try:
        tim = dump_tim(_problem(8, _SHAPE_A))
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": tim, "id": "dead1", "seed": 4,
                   "generations": 10})
        deadline = time.monotonic() + 20
        down = False
        while time.monotonic() < deadline and not down:
            hz = http_json("GET", gw.url + "/healthz",
                           ok=(200, 503))
            down = hz["probes"].get("dispatcher") is False
            time.sleep(0.1)
        assert down, "dispatcher death never surfaced on /healthz"
        view = http_json("GET", gw.url + "/v1/jobs/dead1", ok=(200,))
        assert view["state"] == "accepted"      # never placed
        # the replica is untouched by the dead dispatcher
        http_json("POST", rep.url + "/v1/solve",
                  {"tim": tim, "id": "alive1", "seed": 5,
                   "generations": 10})
        views = _wait_jobs(rep.url, ["alive1"], timeout=60)
        assert views["alive1"]["state"] == "done"
    finally:
        gw.close()
        faults.install(None)
        rep.kill()


# --------------------------------------------------------- tt submit


def test_tt_submit_round_trip(tmp_path, capsys):
    """`tt submit` round-trips a `.tim` fixture end-to-end on CPU:
    file -> gateway -> routed replica -> polled result on stdout."""
    p = _problem(4, _SHAPE_A)
    tim_path = os.path.join(tmp_path, "instance.tim")
    with open(tim_path, "w") as fh:
        fh.write(dump_tim(p))
    rep, handle = in_process_replica(_serve_cfg(), "rs")
    gw = Gateway(_fleet_cfg([handle.url]), [handle]).start()
    try:
        tail_path = os.path.join(tmp_path, "cli1.jsonl")
        rc = main_submit([gw.url, tim_path, "--id", "cli1", "-s", "9",
                          "--generations", "10", "--poll", "0.1",
                          "--records", "--records-out", tail_path])
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert out["state"] == "done" and out["id"] == "cli1"
        assert out["replica"] == "rs"
        assert isinstance(out["result"]["best"], int)
        assert any("solution" in r for r in out["records"])
        # and the stream matches the unrouted baseline
        baseline = _unrouted_streams([("cli1", p, 9, 10)])
        assert jsonl.strip_timing(out["records"]) == baseline["cli1"]
        # --records-out wrote the SAME stream as JSONL lines (a
        # tt stats / tt trace input)
        with open(tail_path) as fh:
            lines = [json.loads(ln) for ln in fh if ln.strip()]
        assert lines == out["records"]
    finally:
        gw.request_drain()
        gw.drained.wait(30)
        gw.close()
        rep.stop(timeout=60)
