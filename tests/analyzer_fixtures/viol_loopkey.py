"""TT402 fixture: loop-carried PRNG key reuse.

Not imported or executed — parsed by tests/test_analysis.py. Each
violation is ONE call site (so TT401's per-site model stays silent)
that consumes the same key on every `for` iteration.
"""
import jax


def restart_loop(pa, key, n):
    outs = []
    for _ in range(n):
        outs.append(jax.random.normal(key, (4,)))   # EXPECT TT402
    return outs


def fold_constant_loop(key, n):
    outs = []
    for _ in range(n):
        k = jax.random.fold_in(key, 7)              # EXPECT TT402
        outs.append(jax.random.normal(k, (2,)))
    return outs


def unchained_split_loop(key, items):
    outs = []
    for it in items:
        ks = jax.random.split(key, 4)               # EXPECT TT402
        outs.append(ks[0])
        _ = it
    return outs


def fold_on_index_ok(key, n):
    outs = []
    for i in range(n):
        k = jax.random.fold_in(key, i)        # OK: loop-indexed stream
        outs.append(jax.random.normal(k, (2,)))
    return outs


def fold_on_derived_ok(key, n):
    outs = []
    for i in range(n):
        step = i * 2 + 1              # derived from the loop variable
        k = jax.random.fold_in(key, step)   # OK: varies per iteration
        outs.append(jax.random.normal(k, (2,)))
    return outs


def chained_rebind_ok(key, n):
    outs = []
    for _ in range(n):
        key, k = jax.random.split(key)        # OK: the chain advances
        outs.append(jax.random.normal(k, (2,)))
    return outs


def loop_target_is_fresh_ok(key, n):
    outs = []
    for key in jax.random.split(key, n):      # OK: target varies per
        outs.append(jax.random.normal(key, (2,)))   # iteration
    return outs
