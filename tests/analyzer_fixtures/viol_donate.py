"""TT203 fixture: donated-buffer reuse.

Not imported or executed — parsed by tests/test_analysis.py. Donation
deletes the input buffer at dispatch; every read below the donating
call is a runtime `Array has been deleted` waiting for the backend
that enforces it.
"""
import functools

import jax


def _step(pa, key, state):
    return state


runner = jax.jit(_step, donate_argnums=(2,))


@functools.partial(jax.jit, donate_argnums=(1,))
def polish(pa, state):
    return state


@functools.partial(jax.jit, donate_argnames=("state",))
def kick(pa, key, state):
    return state


def read_after_donate(pa, key, state):
    out = runner(pa, key, state)
    best = state.penalty            # EXPECT TT203 (donated, then read)
    return out, best


def read_in_later_call(pa, state):
    new = polish(pa, state)
    return new, polish(pa, state)   # EXPECT TT203 (donated, reused)


def argnames_resolve_positionally(pa, key, state):
    out = kick(pa, key, state)
    return out + state              # EXPECT TT203 (donate_argnames)


def _step2(pa, state):
    return state


sweeper = jax.jit(_step2, donate_argnames=("state",))


def argnames_assignment_form(pa, state):
    out = sweeper(pa, state)
    return out, state.rooms         # EXPECT TT203 (argnames via assign)


def rebind_is_clean(pa, key_a, key_b, state):
    state = runner(pa, key_a, state)  # OK: donate + rebind, one statement
    state = runner(pa, key_b, state)  # OK: consumes the previous output
    return state.penalty              # OK: reads the live output


def clone_before_donate(pa, key, state):
    import jax.numpy as jnp
    probe = runner(pa, key, jax.tree.map(jnp.copy, state))
    return probe, state.penalty     # OK: the clone was donated, not state
