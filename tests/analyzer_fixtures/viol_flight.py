"""TT606 fixture: bundle serialization off the recorder thread.

Not imported or executed — parsed by tests/test_analysis.py (the test
config adds this file to `dispatch-modules` so the loop half fires).
The flight recorder's contract (obs/flight.py): bundle serialization
and file I/O run on the RECORDER thread only — never in trace targets,
never in dispatch loops, and never from an HTTP handler, which may
only read the in-memory `latest()` / history `window()` state.
"""
import http.server
import json

import jax


def dispatch_loop(chunks, runner, state):
    for chunk in chunks:
        state = runner(state, chunk)
        blob = json.dumps({"state": 1})              # EXPECT TT606
        with open("bundle.json", "w") as fh:         # EXPECT TT606
            fh.write(blob)
    return state


@jax.jit
def traced_dump(x):
    json.dumps({"x": 1})                             # EXPECT TT606
    return x * 2


class FlightHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        core = self.server.flight.dump()             # EXPECT TT606
        self.server.flight.trigger("manual")         # EXPECT TT606
        self._write_bundle(core)

    def _write_bundle(self, core):
        # reachable via self._write_bundle() from do_GET — still the
        # handler path; bundle writes belong on the recorder thread
        json.dump(core, self.wfile)                  # EXPECT TT606

    def do_HEAD(self):
        # OK: serving the in-memory copy is exactly what the handler
        # is for (FlightRecorder.latest / HistoryRing.window)
        core = self.server.flight.latest()
        window = self.server.history.window(30.0)
        self.wfile.write(str((core, window)).encode())


def recorder_thread_is_fine(recorder, core):
    # OK: not a trace target, not a loop in a dispatch module, not a
    # handler path — the recorder thread's dump body lives here
    with open("incident.json", "w") as fh:
        json.dump(core, fh)
    recorder.trigger("manual")
