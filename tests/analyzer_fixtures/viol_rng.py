"""TT401 fixture: PRNG key reuse.

Not imported or executed — parsed by tests/test_analysis.py.
"""
import jax


def double_consume(key, state):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))     # EXPECT TT401 (second consumer)
    return a + b + state


def fold_collision(key):
    a = jax.random.normal(jax.random.fold_in(key, 7), (2,))
    b = jax.random.normal(jax.random.fold_in(key, 7), (2,))  # EXPECT TT401
    return a + b


def disciplined(key):
    k_a, k_b = jax.random.split(key)
    a = jax.random.normal(k_a, (4,))
    b = jax.random.uniform(k_b, (4,))     # OK: fresh subkeys
    return a + b


def branches_are_exclusive(key, flag):
    if flag:
        out = jax.random.normal(key, (2,))
    else:
        out = jax.random.uniform(key, (2,))  # OK: exclusive branch
    return out


def subkey_array_reuse(key):
    ks = jax.random.split(key, 4)
    a = jax.random.normal(ks[0], (2,))
    b = jax.random.uniform(ks[0], (2,))   # EXPECT TT401 (same element)
    c = jax.random.uniform(ks[1], (2,))   # OK: distinct element
    return a + b + c
