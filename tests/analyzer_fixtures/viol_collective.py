"""TT302 fixture: collective-bearing random ops in shard_map-executed
code.

Not imported or executed — parsed by tests/test_analysis.py (which
registers this directory as a sharded module). These are the exact
calls whose shuffle-by-sort lowering the SPMD partitioner replicates
with cross-device all-reduces — the round-1 CPU deadlock and merged
island RNG streams.
"""
import jax
import jax.numpy as jnp
from jax import lax


def shuffled_pivots(key, E):
    return jax.random.permutation(key, E)          # EXPECT TT302


def sample_events(key, E):
    return jax.random.choice(key, E, shape=(3,),   # EXPECT TT302
                             replace=False)


def safe_equivalents(key, E):
    k_a, k_b = jax.random.split(key)
    # affine permutation: elementwise, partitions locally
    b = jax.random.randint(k_a, (), 0, E)
    perm = (jnp.arange(E) + b) % E
    # ordered distinct triple via top_k of iid uniforms
    evs = lax.top_k(jax.random.uniform(k_b, (E,)), 3)[1]
    return perm, evs
