"""TT301 fixture: hidden host-device syncs in dispatch loops.

Not imported or executed — parsed by tests/test_analysis.py. The test
registers this file as a dispatch module so the rule applies.
"""
import jax
import numpy as np


def _fetch(x):
    return np.asarray(x)   # sanctioned helper: exempt


def cached_step(n):
    return jax.jit(lambda s: s + n)


def epoch_loop(state, n_epochs):
    step = cached_step(3)
    total = 0.0
    for _ in range(n_epochs):
        state = step(state)
        total += float(state)          # EXPECT TT301 (float() per epoch)
        best = state.min().item()      # EXPECT TT301 (.item() per epoch)
        host = np.asarray(state)       # EXPECT TT301 (asarray per epoch)
        _ = best, host
    return state, total


def fetched_loop(state, n_epochs):
    step = cached_step(3)
    bests = []
    for _ in range(n_epochs):
        state = step(state)
        host = _fetch(state)           # OK: the sanctioned fetch helper
        bests.append(int(host.min()))  # OK: host memory already
    return state, bests
