"""TT102 fixture: `and`/`or` short-circuit on traced values.

Not imported or executed — parsed by tests/test_analysis.py. Short-
circuit operators call bool() on their left operand, the same tracer
hazard TT101 catches in `if`, hidden in expression position.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def assign_and(x, y):
    ok = (x > 0) and (y > 0)                 # EXPECT TT102
    return jnp.where(ok, x, y)


@jax.jit
def return_or(x, y):
    return x or y                            # EXPECT TT102


def scan_body_guard(carry, x):
    flag = carry and x                       # EXPECT TT102
    return carry + x, flag


def run_scan(xs):
    c, _ = lax.scan(scan_body_guard, jnp.zeros(()), xs)
    return c


@jax.jit
def if_test_chain(x, y):
    if (x > 0) and (y > 0):   # EXPECT TT101 # EXPECT TT102
        return x
    return y


@functools.partial(jax.jit, static_argnames=("mode",))
def statics_are_fine(x, mode):
    fast = mode == "fast" or mode == "quick"   # OK: mode declared static
    big = x.shape[0] > 4 and x.ndim > 1        # OK: shapes are static
    cond = jnp.logical_and(x > 0, x < 9)       # OK: the element-wise form
    both = (x > 0) & (x < 9)                   # OK: bitwise, no bool()
    last = fast or (x > 0)    # OK: bool() never runs on the LAST operand
    if fast and big:                           # OK: both operands static
        return jnp.where(cond, x, -x), jnp.where(last, both, x)
    return x, both
