"""TT601 fixture: wall-clock reads / span enters inside trace targets.

Not imported or executed — parsed by tests/test_analysis.py. A clock
read (or a span emission) inside a jitted function executes at TRACE
time: the compiled program carries the compile's wall clock as a
constant, so the "timing" it reports never moves again.
"""
import functools
import time
from time import perf_counter

import jax
import jax.numpy as jnp
from jax import lax

from timetabling_ga_tpu.obs.spans import NULL_TRACER, SpanTracer

tracer = SpanTracer(out=None, enabled=False)


@jax.jit
def stamped_step(x):
    t0 = time.monotonic()                    # EXPECT TT601
    y = x * 2
    return y, t0


@jax.jit
def perf_counter_step(x):
    start = perf_counter()                   # EXPECT TT601
    return x + 1, start


def scan_body_clock(carry, x):
    now = time.time()                        # EXPECT TT601
    return carry + x, now


def run_scan(xs):
    c, _ = lax.scan(scan_body_clock, jnp.zeros(()), xs)
    return c


@functools.partial(jax.jit, static_argnames=("n",))
def span_inside_jit(x, n):
    tracer.record("step", 0.0, 0.1)          # EXPECT TT601
    with tracer.span("block"):               # EXPECT TT601
        y = x * n
    return y


def vmapped_with_null_tracer(x):
    NULL_TRACER.record("lane", 0.0, 0.0)     # EXPECT TT601
    return x + 1


def run_vmap(xs):
    return jax.vmap(vmapped_with_null_tracer)(xs)


def host_side_is_fine(x):
    # OK: not a trace target — host code times itself freely
    t0 = time.monotonic()
    with tracer.span("host"):
        y = jnp.sum(x)
    return y, time.monotonic() - t0


@jax.jit
def data_not_clocks(x):
    # OK: shipping DATA the host will timestamp is the designed pattern
    best = jnp.min(x)
    return best
