"""TT501 fixture: JAX imports outside the pinned compatibility table.

Not imported or executed — parsed by tests/test_analysis.py. This is
the exact breakage class that killed the seed suite: `from jax import
shard_map` does not exist on JAX 0.4.37.
"""
from jax import shard_map            # EXPECT TT501 (not in compat table)
import jax.interpreters.xla          # EXPECT TT501 (undeclared module)
import jax                           # OK: declared
import jax.numpy as jnp              # OK: declared
from jax import lax                  # OK: declared

try:
    from jax import tree_util_flatten_with_keys_v2   # OK: guarded
except ImportError:
    tree_util_flatten_with_keys_v2 = None

from jax import pure_callback  # tt-analyze: ignore[TT501] (suppressed)

_ = shard_map, jax, jnp, lax, pure_callback
