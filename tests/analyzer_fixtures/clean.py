"""Negative fixture: idiomatic JAX code that must produce ZERO findings
under every rule — the analyzer's false-positive regression guard.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax

_RUNNER_CACHE: dict = {}


@jax.jit
def traced_ok(x, y):
    # data-dependent selection the traced way
    return jnp.where(x > 0, y, -y)


@functools.partial(jax.jit, static_argnames=("depth",))
def static_branch_ok(x, depth):
    if depth > 2:            # static param: trace-time branch is fine
        x = x * 2
    n = x.shape[0]
    if n > 16:               # shape-derived: static under tracing
        x = x[:16]
    return x


def make_step(scale):
    return jax.jit(lambda s: s * scale)


def cached_step(scale):
    k = (scale,)
    r = _RUNNER_CACHE.get(k)
    if r is None:
        r = make_step(scale)
        _RUNNER_CACHE[k] = r
    return r


def evolve(key, state, n):
    def body(carry, k):
        noise = jax.random.normal(k, carry.shape)
        return carry + noise, noise.sum()

    keys = jax.random.split(key, n)
    state, trace = lax.scan(body, state, keys)
    return state, trace


def per_island(key, n_islands, state):
    keys = jax.vmap(
        lambda i: jax.random.fold_in(key, i))(jnp.arange(n_islands))
    return jax.vmap(lambda k, s: s + jax.random.normal(k, s.shape))(
        keys, state)
