"""TT602/TT605 fixture: gateway telemetry discipline on *Api surfaces.

Not imported or executed — parsed by tests/test_analysis.py (the test
config adds this file to `fleet-modules`; `handler-api-suffixes`
defaults to ["Api"]). The fleet fronts route every HTTP request into
an `api` object (fleet/gateway.py GatewayApi, fleet/replicas.py
ReplicaApi) whose methods run ON the handler thread but live in a
class with no `do_*` methods — before tt-obs v5 the reachability walk
could not see them, so a registry bump or an outbound scrape inside
`accept_solve` passed the gate the handler discipline exists to
enforce. The `*Api` suffix now marks these classes handler-path roots
for both TT602 (registry mutation / blocking I/O) and TT605 (device
work) — this fixture pins the new surface.
"""
import urllib.request

from timetabling_ga_tpu.obs import metrics as obs_metrics


class WrongGatewayApi:
    """An api surface doing everything the dispatcher owns — each one
    a regression the handler-thread discipline must catch."""

    def __init__(self, gw, registry):
        self._gw = gw
        self._registry = registry

    def accept_solve(self, payload, flow=0):
        # counting admissions is DISPATCHER work: handlers only enqueue
        self._registry.counter("fleet.jobs_accepted").inc()  # EXPECT TT602
        self._gw.inbox.put(("submit", payload))
        return 202, {"ok": True}

    def fleet_view(self):
        # outbound I/O on a handler thread: a slow replica now stalls
        # every client reading /v1/fleet
        body = urllib.request.urlopen("http://r0:1/metrics")  # EXPECT TT602
        return 200, {"metrics": body.read().decode()}

    def accept_drain(self):
        self._drain_inline()
        return 200, {"draining": True}

    def _drain_inline(self):
        # reachable via self._drain_inline() from accept_drain — still
        # the handler path; driving the scheduler is DEVICE work
        self._gw.svc.drive()                                 # EXPECT TT605


class ReadOnlyViewApi:
    """OK: the sanctioned shape — enqueue commands, read cached
    state, mutate nothing shared."""

    def __init__(self, gw):
        self._gw = gw

    def accept_solve(self, payload, flow=0):
        self._gw.inbox.put(("submit", payload))
        return 202, {"ok": True}

    def fleet_view(self):
        return 200, self._gw.fleet_snapshot()


def dispatcher_side_is_fine(gw, registry):
    # OK: not reachable from any handler or api class — the dispatcher
    # thread is exactly where routing I/O and registry writes belong
    registry.counter("fleet.jobs_routed").inc()
    urllib.request.urlopen(gw.url + "/metrics")
    gw.svc.drive()
