"""TT307 fixture: collectives inside a *Supervisor recovery policy.

Not imported or executed — parsed by tests/test_analysis.py. This
file is NOT in accord-modules: only the `*Supervisor` class-body
scope may fire here, so the free function's collective below is a
deliberate negative (the healthy program path is allowed to be
collective — it is the program).
"""


class DriveSupervisor:
    def classify(self, exc):
        return "dispatch"

    def agree_on_fault(self, states):
        from jax.experimental import multihost_utils
        # recovery consensus over the poisoned program: hangs
        return multihost_utils.process_allgather(states)  # EXPECT TT307

    def snapshot(self, state):
        from jax import lax
        penalty = lax.pmin(state.penalty, "i")            # EXPECT TT307
        self.snap = (state, penalty)


def healthy_migration(pop):
    from jax import lax
    # OK: a collective on the healthy program path, outside any
    # Supervisor body and outside accord-modules
    return lax.ppermute(pop, "i", [(0, 1), (1, 0)])
