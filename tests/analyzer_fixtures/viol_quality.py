"""TT604 fixture: quality accounting off device.

Not imported or executed — parsed by tests/test_analysis.py. The
search-quality observatory ships diversity/operator/migration numbers
as packed int32 rows on the telemetry leaf the dispatch loop already
fetches; recomputing them on host per dispatch re-introduces the
O(pop x E) host bill (and hidden sync) the on-device reduction removed,
and a quality-reduction helper that adds a collective (or a
collective-bearing random op — TT302's shuffle-sort hazard) turns
telemetry into a deadlock surface.
"""
import jax
from jax import lax
from jax.lax import psum


def drive_loop(runner, pa, state, batch_penalty):
    for _step in range(8):
        state, trace = runner(pa, state)
        pen = batch_penalty(pa, state.slots, state.rooms)  # EXPECT TT604
    return state, pen


def poll_until_drained(queue, pa, state, event_heat):
    while queue:
        queue.pop()
        heat = event_heat(pa, state.slots, state.rooms)    # EXPECT TT604
    return heat


def _quality_gain_rows(best, perm):
    # a quality reduction must ride the EXISTING migration exchange,
    # never add its own collective
    return lax.ppermute(best, "island", perm)          # EXPECT TT604


def quality_mean_rows(rep):
    # bare imported form of the same hazard — flagged identically
    return psum(rep, "island")                         # EXPECT TT604


def hamming_sample_rows(key, slots):
    # the coprime-stride sample exists precisely to avoid this shuffle
    # (TT302 flags the same call: it is the same hazard class)
    order = jax.random.permutation(key, 8)  # EXPECT TT604 # EXPECT TT302
    return slots[order]


def fine_outside_loops(pa, state, batch_penalty):
    # OK: a one-off evaluation outside any dispatch loop (tests,
    # endTry verification) is not per-generation recompute
    return batch_penalty(pa, state.slots, state.rooms)
