"""TT310 fixture: phase scopes outside the tt-prof registry / on
handler paths.

Not imported or executed — parsed by tests/test_analysis.py. tt-prof's
contract (obs/prof.py): every phase scope string comes from the ONE
registry (PHASES), statically checkable, and HTTP handler paths never
enter scopes at all (named_scope is jax machinery on a scrape thread).
"""
import jax

from timetabling_ga_tpu.obs import prof as obs_prof
from timetabling_ga_tpu.obs.prof import scope


@obs_prof.scope("tt.breeding")                             # EXPECT TT310
def decorated_unregistered(x):
    return x * 2


@obs_prof.scope("tt.fitness")
def decorated_registered_ok(x):
    return x * 2


def freehand_named_scope(x):
    with jax.named_scope("my_phase"):                      # EXPECT TT310
        return x + 1


def bare_import_unregistered(x):
    with scope("tt.nope"):                                 # EXPECT TT310
        return x + 1


def dynamic_phase_name(x, which):
    with obs_prof.scope("tt." + which):                    # EXPECT TT310
        return x + 1


def registered_with_ok(x):
    with obs_prof.scope("tt.sweep"):
        return x + 1


class StatsHandler:
    """Duck-typed http.server handler (do_* routing convention)."""

    def do_GET(self):
        self._render()

    def _render(self):
        with obs_prof.scope("tt.quality"):                 # EXPECT TT310
            self.wfile.write(b"ok")
