"""TT101 fixture: tracer-unsafe control flow inside jit targets.

Not imported or executed — parsed by tests/test_analysis.py. Expected
findings are marked with `# EXPECT TTxxx` comments the test reads.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def branch_on_traced(x, y):
    if x > 0:            # EXPECT TT101
        return y
    return -y


@functools.partial(jax.jit, static_argnames=("mode",))
def static_param_is_fine(x, mode):
    if mode == "fast":   # OK: mode is declared static
        return x * 2
    while x.sum() > 0:   # EXPECT TT101
        x = x - 1
    return x


def scan_body_branch(carry, x):
    assert carry >= 0    # EXPECT TT101
    return carry + x, x


def run_scan(xs):
    # shape-derived bounds are static: no finding
    def body(c, x):
        n = xs.shape[0]
        if n > 4:        # OK: shape access is trace-time static
            return c + x, x
        return c, x
    c0 = jnp.zeros(())
    c1, _ = lax.scan(scan_body_branch, c0, xs)
    c2, _ = lax.scan(body, c1, xs)
    return c2


def vmapped_loop(v):
    for item in v:       # EXPECT TT101
        v = v + item
    return v


batched = jax.vmap(vmapped_loop)
