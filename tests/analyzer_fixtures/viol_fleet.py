"""TT605 fixture: device work / unbounded reads on fleet handler paths.

Not imported or executed — parsed by tests/test_analysis.py (the test
config adds this file to `fleet-modules`). The fleet front's design
rule (fleet/gateway.py): HTTP handlers ENQUEUE and READ ONLY — the
drive loop owns every device call, the dispatcher thread every piece
of outbound I/O, and body reads are bounded by Content-Length.
"""
import http.server

import jax


class SolveFrontHandler(http.server.BaseHTTPRequestHandler):
    def do_POST(self):
        body = self.rfile.read()                     # EXPECT TT605
        job = self.server.api.svc.submit(body)       # EXPECT TT605
        self._solve_inline(job)

    def _solve_inline(self, job):
        # reachable via self._solve_inline() from do_POST — still the
        # handler path
        state = self.server.api.scheduler.step()     # EXPECT TT605
        jax.block_until_ready(state)                 # EXPECT TT605
        push_result(state)

    def do_GET(self):
        n = int(self.headers.get("Content-Length", 0))
        chunk = self.rfile.read(n)                   # OK: bounded read
        self._reply(chunk)

    def _reply(self, body):
        self.wfile.write(body)                       # OK: own socket


def push_result(state):
    # bare-name reachable from _solve_inline — still the handler path
    arrs = state.problem.device_arrays()             # EXPECT TT605
    return arrs


def drive_loop_is_fine(svc):
    # OK: not reachable from any handler — the DRIVE LOOP is exactly
    # where dispatch entries and device materialization belong
    while svc.queue.ready():
        svc.step()
        svc.scheduler.drive()
