"""TT502 fixture: jax.* attribute access outside the pinned table.

Not imported or executed — parsed by tests/test_analysis.py. This is
the gap TT501 cannot see: `jax.profiler.start_trace` never appears in
an import statement, but an attribute a supported JAX version does not
export fails exactly like an undeclared import — at the first call.
"""
import functools

import jax
import jax as j
import jax.numpy as jnp


def uses_declared_surface(x):
    jax.profiler.start_trace("/tmp/t")          # OK: declared
    jax.profiler.stop_trace()                   # OK: declared
    jax.distributed.initialize()                # OK: declared
    jax.config.update("jax_platforms", "cpu")   # OK: declared
    y = jax.jit(lambda a: a + 1)(x)             # OK: declared
    return jax.block_until_ready(y)


def undeclared_attributes(x):
    jax.profiler.annotate_function(x)    # EXPECT TT502 (not in table)
    jax.distributed.shutdown()           # EXPECT TT502 (not in table)
    jax.live_arrays()                    # EXPECT TT502 (not under jax)
    j.experimental.pallas.when(x)        # EXPECT TT502 (via alias too)
    return jnp.asarray(x)                # OK: jax.numpy is "*"


def wildcard_and_deep_ok(key):
    a = jax.random.normal(key, (2,))     # OK: jax.random is "*"
    b = jax.tree.map(lambda v: v, a)     # OK: jax.tree is "*"
    jax.tree_util.register_pytree_node(int, None, None)  # OK: declared
    return functools.reduce(lambda u, v: u + v, [a, b])


def guarded_probe_is_exempt():
    try:
        return jax.extend.backend.get_backend()   # OK: guarded
    except AttributeError:
        return None


jax.numpy.asarray(0)                     # OK: jax.numpy is "*"
_bad = jax.sharding.AbstractMesh         # EXPECT TT502 (not declared)
_ok = getattr(jax, "live_arrays", None)  # OK: getattr probing
