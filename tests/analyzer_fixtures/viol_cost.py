"""TT603 fixture: cost/memory introspection on hot paths.

Not imported or executed — parsed by tests/test_analysis.py.
`cost_analysis()` / `memory_analysis()` exist only on a compiled
executable (anywhere else they force a recompile) and
`memory_stats()` is a device-allocator RPC: inside a trace target the
call runs against a tracer at trace time, inside a dispatch loop it
serializes the pipeline. The sanctioned home is the cost observatory
(obs/cost.py): extract at compile time, poll from its own thread.
"""
import jax
from jax import lax

DEVICE = None      # stands in for jax.local_devices()[0]


@jax.jit
def traced_introspection(x, compiled):
    analysis = compiled.cost_analysis()          # EXPECT TT603
    return x + len(analysis)


def scan_body_memory(carry, x):
    stats = DEVICE.memory_stats()                # EXPECT TT603
    return carry + x, stats


def run_scan(xs):
    return lax.scan(scan_body_memory, 0.0, xs)


def dispatch_loop(runner, pa, state):
    for _step in range(8):
        state = runner(pa, state)
        stats = DEVICE.memory_stats()            # EXPECT TT603
    return state, stats


def drain_loop(queue, compiled):
    while queue:
        queue.pop()
        mem = compiled.memory_analysis()         # EXPECT TT603
    return mem


def compile_time_is_fine(fn, args):
    # OK: one-off extraction right after an explicit compile — the
    # observatory's own pattern (obs/cost.py), outside any loop
    compiled = fn.lower(*args).compile()
    return compiled.cost_analysis(), compiled.memory_analysis()
