"""The clean side of the interproc fixtures: a miniature dispatch
core. Factories matching the `taint-sources` patterns return compiled
programs (one donating its state slot), `fetch` is the sanctioned
readback helper, and `advance` is summarized device-returning — all
facts the whole-program layer must carry into loop.py."""

import jax
import numpy as np


def step(state, seed):
    return state


def cached_runner(mesh):
    """Factory: a compiled dispatch program donating its state arg."""
    runner = jax.jit(step, donate_argnums=(0,))
    return runner


def make_lane_runner(mesh, lanes):
    """Caching factory returning the `(runner, cache_hit)` tuple."""
    runner = jax.jit(step, donate_argnums=(0,))
    return runner, False


def fetch(x):
    """Sanctioned packed device->host readback."""
    return np.asarray(x)


def advance(state, seed):
    """Device-returning helper: its result is a dispatch program's
    output, so callers in other modules inherit the taint."""
    runner = cached_runner(None)
    return runner(state, seed)
