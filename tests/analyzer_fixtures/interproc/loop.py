"""Dispatch loops breaking the whole-program discipline ACROSS the
module boundary into interproc/core.py: every violating fact here —
the program's factory, its donate_argnums, the sanctioned fetch — is
declared in the other module, which is exactly what the single-module
rules (TT301/TT203) cannot see."""

import jax
import numpy as np

from interproc import core


def taint_sink_loop(pa, steps):
    """TT303: host-forcing sinks on values a cross-module dispatch
    program produced, inside the dispatch loop."""
    runner = core.cached_runner(None)
    state = pa
    for i in range(steps):
        state = runner(state, i)
        cur = float(state)                          # EXPECT TT303
        hist = state.tolist()                       # EXPECT TT303
        if state > cur:                             # EXPECT TT303
            break
    return state, hist


def summary_taint(pa, steps):
    """TT303 through a device-returning SUMMARY: core.advance wraps
    the program call, the taint still arrives here."""
    state = pa
    for i in range(steps):
        state = core.advance(state, i)
        done = bool(state)                          # EXPECT TT303
        if done:
            break
    return state


def donated_read_loop(pa, steps):
    """TT304: the donating jit lives in core.make_lane_runner; reading
    the donated buffer after the dispatch is a cross-module kill."""
    runner, hit = core.make_lane_runner(None, 2)
    state = pa
    out = None
    prev = None
    for i in range(steps):
        out = runner(state, i)
        prev = core.fetch(state)                    # EXPECT TT304
        state = out
    return state, prev


def telemetry_fence_loop(pa, steps):
    """TT305(a): a telemetry-only fetch BEFORE the dispatch fences it —
    only control reads may precede a dispatch."""
    runner = core.cached_runner(None)
    state = pa
    rows = []
    for i in range(steps):
        trace = core.fetch(state)                   # EXPECT TT305
        rows.append(trace)
        state = runner(state, i)
    return state, rows


def blocking_control_loop(pa, steps):
    """TT305(b): control flow steered through block_until_ready instead
    of the sanctioned packed fetch."""
    runner = core.cached_runner(None)
    state = pa
    for i in range(steps):
        state = runner(state, i)
        done = jax.block_until_ready(state)         # EXPECT TT305
        if not done:
            break
    return state


def resident_fetch_loop(sched, steps):
    """TT306: host fetches of resident-group state outside a park
    fence — the direct store read, a name rooted in it, and a
    conversion sink all flag; the configured fence_helpers bodies are
    the only legal site for these bytes to move."""
    rows = []
    snap = None
    for bkey in list(sched._resident):
        entry = sched._resident[bkey]
        snap = core.fetch(entry["state"])           # EXPECT TT306
        rows.append(
            np.asarray(sched._resident[bkey]["state"]))  # EXPECT TT306
    return snap, rows


def resident_dispatch_clean(sched, steps):
    """CLEAN under TT306: the resident state feeds the dispatch, and
    the park fetch reads the runner's OUTPUT — a rebind from a plain
    call clears store-rootedness (the scheduler's _cycle idiom)."""
    runner = core.cached_runner(None)
    state = sched._resident["b"]["state"]
    for i in range(steps):
        state = runner(state, i)
    host = core.fetch(state)
    return host
