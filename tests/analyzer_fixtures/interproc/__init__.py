"""Fixture package for the whole-program rules (TT303/TT304/TT305):
`core.py` plays the dispatch core (factories, donation, sanctioned
fetch), `loop.py` plays a dispatch loop in another module that breaks
the taint/donation/fence discipline across the package boundary."""
