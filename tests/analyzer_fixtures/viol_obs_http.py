"""TT602 fixture: blocking I/O / registry mutation in handler paths.

Not imported or executed — parsed by tests/test_analysis.py. The pull
front's design rule (obs/http.py): an HTTP handler is a PURE OBSERVER —
it reads registry snapshots/expositions and writes its own response
socket, nothing else. Mutation (including the get-or-create accessors)
changes the numbers every other consumer reads; foreign blocking I/O
on a handler thread is how a listener learns to stall the run.
"""
import http.server
import time

from timetabling_ga_tpu.obs import metrics as obs_metrics

REGISTRY = obs_metrics.REGISTRY


class ScrapeHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        REGISTRY.counter("scrapes").inc()            # EXPECT TT602
        time.sleep(0.5)                              # EXPECT TT602
        body = self.server.registry.to_prometheus()  # OK: read-only
        self._audit(body)
        self._reply(200, body.encode())

    def _audit(self, body):
        # reachable via self._audit() from do_GET — still handler path
        with open("/tmp/scrapes.log", "a") as fh:    # EXPECT TT602
            fh.write(str(len(body)))
        touch_gauge()

    def _reply(self, status, body):
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)                       # OK: own socket


def touch_gauge():
    # reachable from _audit by bare-name call — still handler path;
    # gauge() is get-or-create, a registry WRITE when the name is new
    obs_metrics.REGISTRY.gauge("scrape.last").set(1.0)   # EXPECT TT602


class DuckHandler:
    """No http.server base — the `do_*` method convention alone marks
    it a handler (duck-typed routing)."""

    def __init__(self, registry):
        self.registry = registry

    def do_POST(self):
        self.registry.histogram("scrape.lat")        # EXPECT TT602


def host_side_is_fine():
    # OK: not reachable from any handler — services and engines mutate
    # their registry (and sleep, and open files) freely
    obs_metrics.REGISTRY.counter("serve.jobs_done").inc()
    time.sleep(0.001)
    with open("/tmp/ok", "w") as fh:
        fh.write("x")
