"""TT607 fixture: usage-ledger mutation / wall-clock metering off its
home threads.

Not imported or executed — parsed by tests/test_analysis.py. The
tt-meter contract (obs/usage.py): the ledger is fed from the
scheduler's park fence and folded on its own thread; HTTP handlers
(and the fleet fronts' *Api surfaces) only READ the meter, and never
read wall clocks to meter where requests land.
"""
import http.server
import time

import jax


@jax.jit
def traced_meter(x, ledger):
    ledger.dispatch({"gens": 1})                     # EXPECT TT607
    return x * 2


def traced_lambda_site(xs, usage):
    return jax.vmap(lambda x: usage.final("j", "t", {}) or x)(xs)  # EXPECT TT607


class UsageHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        t0 = time.monotonic()                        # EXPECT TT607
        self.server.usage.job("j1", "acme")          # EXPECT TT607
        self._meter(t0)

    def _meter(self, t0):
        # reachable via self._meter() from do_GET — still the handler
        # path; metering clocks belong to the drive loop's fences
        dt = time.perf_counter() - t0                # EXPECT TT607
        self.server.ledger.final("j1", "acme", {"s": dt})  # EXPECT TT607

    def do_HEAD(self):
        # OK: reading the meter is exactly what a handler is for
        totals = self.server.usage.totals()
        self.wfile.write(str(totals).encode())


class MeterApi:
    # a fleet-front api surface (handler-api-suffixes root): its
    # methods run ON handler threads even without do_* names
    def usage_view(self):
        return 200, {"tenants": self._ledger.totals()}   # OK: read

    def accept_solve(self, payload):
        self._ledger.job(payload["id"], payload["tenant"])  # EXPECT TT607
        return 202, {"id": payload["id"]}


def drive_loop_fence_is_fine(ledger, jobs, now):
    # OK: not a trace target, not a handler path — the scheduler's
    # park fence is the sanctioned feed point, and its clock reads
    # are the fence brackets themselves
    t0 = now()
    ledger.dispatch({"gens": 5, "lanes": []})
    ledger.final("j", "t", {"queue_seconds": now() - t0})
