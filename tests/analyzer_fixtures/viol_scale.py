"""TT608 fixture: fleet actuation off the scaler thread.

Not imported or executed — parsed by tests/test_analysis.py. The
tt-scale contract (fleet/autoscaler.py): spawning, preempting, and
adopting replicas (and the process/port mutation underneath) happen
ONLY on the autoscaler's control-loop thread, where the decision
carries sustained-window evidence, cooldown hysteresis, and the
warmth guard. Handlers enqueue; the dispatcher executes enqueued
commands.
"""
import http.server
import subprocess


class ScaleHandler(http.server.BaseHTTPRequestHandler):
    def do_POST(self):
        # a client POST that resizes the fleet: no policy, no guard
        self.server.gw.preempt_replica("r0")             # EXPECT TT608
        subprocess.Popen(["tt"])  # EXPECT TT608 # EXPECT TT602

    def _grow(self):
        # reachable via self._grow() from a do_* method — still the
        # handler path
        handle = spawn_one(self.server.cfg, "s9")        # EXPECT TT608
        self.server.gw.adopt_replica(handle)             # EXPECT TT608

    def do_PUT(self):
        self._grow()


class ScalerApi:
    # a fleet-front api surface (handler-api-suffixes root): its
    # methods run ON handler threads even without do_* names
    def accept_scale(self, payload):
        self._gw.retire_replica(payload["replica"])      # EXPECT TT608
        return 202, {}

    def scale_view(self):
        # OK: reading the decision snapshot is exactly what a
        # handler is for
        return 200, self._gw.scale_snapshot()


class FakeGateway:
    def _dispatch_loop(self):
        while True:
            self._poll_jobs()
            # originating actuation on the dispatcher tick: stalls
            # routing/polling/failover and skips the policy's guards
            self.preempt_replica("r1")                   # EXPECT TT608

    def _handle(self, cmd):
        port = free_port()                               # EXPECT TT608
        return port

    def _drain_tick(self):
        for handle in self.replicas.live():
            # executing a graceful drain COMMAND is fine — drain is
            # not an actuator verb
            handle.drain(timeout=2.0)


def scaler_thread_is_fine(gw, cfg, victim):
    # OK: not a handler path, not a tick body — the autoscaler's
    # control loop is the sanctioned actuation site (and
    # fleet/autoscaler.py itself is exempt wholesale)
    handle = gw.replicas.get(victim)
    handle.retired = True
    gw.preempt_replica(victim)
