"""TT307 fixture: device collectives on the recovery/agreement path.

Not imported or executed — parsed by tests/test_analysis.py, which
opts this file into `accord-modules`. The tt-accord contract
(runtime/control_channel.py): after a fault the collective program is
poisoned on at least one process, so agreement/recovery code must be
pure host-side — a collective here hangs at the rendezvous the
faulted peer never reaches.
"""
import json

from jax.experimental import multihost_utils          # EXPECT TT307


def agree_fallback(vals):
    # 'just reuse the broadcast' — THE bug class: the broadcast IS
    # the collective program that died
    return multihost_utils.broadcast_one_to_all(vals)  # EXPECT TT307


def collect_verdicts(local):
    import jax.numpy as jnp
    from jax import lax
    # a collective reduction to merge verdicts: same hang
    votes = lax.psum(jnp.asarray(local), "i")          # EXPECT TT307
    return votes


def gather_states(state):
    return multihost_utils.process_allgather(state)    # EXPECT TT307


def merge_locally(verdicts):
    # OK: host-side deterministic merge — what the channel does
    ordered = sorted(verdicts, key=lambda v: v["proc"])
    return json.loads(json.dumps(ordered[0]))
