"""TT309 fixture: edit-solve work on the dispatch path / in traces.

Not imported or executed — parsed by tests/test_analysis.py (the test
config adds this file to `dispatch-modules` so the loop half fires).
tt-edit's contract (serve/editsolve.py): diff/apply, anchor
attachment, and the population transplant are ADMISSION-TIME host
work — they run once at the submit/prepare seam
(Scheduler.prepare_edit), never per dispatch quantum and never inside
a compiled region.
"""
import functools

import jax

from timetabling_ga_tpu.serve import editsolve
from timetabling_ga_tpu.serve.editsolve import transplant as warm_start


def dispatch_loop(jobs, base, wire, runner, state):
    for job in jobs:
        edited, emap = editsolve.apply_ops(base, job.ops)  # EXPECT TT309
        job.resume_wire = warm_start(                      # EXPECT TT309
            edited, emap, wire, bucket=job.bucket,
            pop_size=16, seed=job.seed)
        state = runner(state, job)
    return state


def drain_until_idle(queue, base, edited):
    while queue.busy():
        ops, emap = editsolve.diff_problems(base, edited)  # EXPECT TT309
        queue.tick(ops, emap)


@jax.jit
def traced_edit(x, base, edited):
    editsolve.diff_problems(base, edited)                  # EXPECT TT309
    return x * 2


@functools.partial(jax.jit, static_argnums=(1,))
def traced_anchor(x, spec):
    editsolve.parse_edit_spec(spec)                        # EXPECT TT309
    return x + 1


def prepare_edit_is_fine(job, base_wire, cfg):
    # OK: the admission seam — once per submitted edit, outside any
    # loop and outside any trace (the scheduler's sanctioned lazy
    # import looks exactly like this)
    from timetabling_ga_tpu.serve import editsolve as es
    base, edited, emap, _ops = es.resolve_edit(job.edit)
    return es.transplant(edited, emap, base_wire,
                         bucket=job.bucket, pop_size=cfg.pop_size,
                         seed=job.seed)


def distance_at_finalize_is_fine(snap, padded, emap):
    # OK: one call at record finalization, not per quantum
    return editsolve.edit_distance(snap.slots[0],
                                   padded.anchor_slots, emap)
