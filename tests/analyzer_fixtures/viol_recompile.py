"""TT201/TT202 fixture: recompile hazards.

Not imported or executed — parsed by tests/test_analysis.py.
"""
import jax
import numpy as np

_PROGRAM_CACHE: dict = {}


def heavy(x, cfg):
    return x * cfg[0]


jitted = jax.jit(heavy, static_argnums=(1,))


def call_sites(x):
    out = jitted(x, [2, 3])            # EXPECT TT201 (unhashable list)
    out = out + jitted(x, np.array([1]))   # EXPECT TT201 (np array)
    for step in range(10):
        out = out + jitted(x, step)    # EXPECT TT201 (loop variable)
    return out


def make_runner(mesh, cfg, n_epochs, migration):
    def run(x):
        return x * n_epochs * migration
    return run


def cached_runner(mesh, cfg, n_epochs, migration):
    # the key omits `migration`, which the factory bakes into the
    # compiled program: two migration cadences collide on one entry
    k = (mesh, cfg, n_epochs)
    r = _PROGRAM_CACHE.get(k)
    if r is None:
        r = make_runner(mesh, cfg, n_epochs, migration)  # EXPECT TT202
        _PROGRAM_CACHE[k] = r
    return r


def cached_complete(mesh, cfg, n_epochs, migration):
    # complete key: no finding
    k = (mesh, cfg, n_epochs, migration)
    r = _PROGRAM_CACHE.get(k)
    if r is None:
        r = make_runner(mesh, cfg, n_epochs, migration)
        _PROGRAM_CACHE[k] = r
    return r
