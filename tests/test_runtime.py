"""Host-runtime tests: CLI flag parsing, JSONL schema, engine end-to-end,
checkpoint/resume (SURVEY C17-C19, section 5).
"""

import io
import json
import os

import numpy as np
import jax
import pytest

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.problem import dump_tim, load_tim, random_instance
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig, parse_args
from timetabling_ga_tpu.runtime.engine import build_ga_config, run


# --------------------------------------------------------------------- config

def test_parse_reference_flags():
    cfg = parse_args(["-i", "x.tim", "-s", "42", "-c", "4", "-p", "2",
                      "-t", "30", "-p1", "0.7", "-p3", "0.1"])
    assert cfg.input == "x.tim"
    assert cfg.seed == 42
    assert cfg.threads == 4
    assert cfg.problem_type == 2
    assert cfg.time_limit == 30
    assert cfg.p1 == 0.7 and cfg.p3 == 0.1
    # LS budget by problem type (ga.cpp:389-397)
    assert cfg.resolved_max_steps() == 1000


def test_parse_extensions():
    cfg = parse_args(["-i", "x.tim", "--islands", "4", "--pop-size", "64",
                      "--backend", "cpu", "--resume",
                      "--checkpoint", "/tmp/c.npz"])
    assert cfg.islands == 4 and cfg.pop_size == 64
    assert cfg.backend == "cpu" and cfg.resume
    assert cfg.checkpoint == "/tmp/c.npz"


def test_missing_input_exits():
    with pytest.raises(SystemExit):
        parse_args(["-s", "1"])


def test_unknown_flag_exits():
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--bogus", "1"])


def test_ls_budget_mapping():
    cfg = parse_args(["-i", "x.tim", "-p", "1", "--ls-candidates", "8"])
    g = build_ga_config(cfg)
    assert g.ls_steps == 200 // 8
    cfg2 = parse_args(["-i", "x.tim", "-m", "80", "--ls-candidates", "8"])
    assert build_ga_config(cfg2).ls_steps == 10


# ---------------------------------------------------------------------- jsonl

def test_jsonl_schema():
    buf = io.StringIO()
    jsonl.log_entry(buf, 0, 1, 117, 2.5)
    jsonl.solution_record(buf, 0, 1, 10.0, 5, True,
                          timeslots=[1, 2], rooms=[0, 1])
    jsonl.solution_record(buf, 1, 0, 10.0, 3000007, False)
    jsonl.run_entry(buf, 5, True)
    jsonl.run_entry(buf, 5, True, procs_num=8, threads_num=4,
                    total_time=10.0)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert lines[0] == {"logEntry": {"procID": 0, "threadID": 1,
                                     "best": 117, "time": 2.5}}
    sol = lines[1]["solution"]
    assert sol["feasible"] is True
    assert sol["timeslots"] == [1, 2] and sol["rooms"] == [0, 1]
    # infeasible solution records omit the timetable arrays
    # (ga.cpp:189-196 feasible branch only appends arrays)
    assert "timeslots" not in lines[2]["solution"]
    assert lines[3] == {"runEntry": {"totalBest": 5, "feasible": True}}
    assert set(lines[4]["runEntry"]) == {
        "totalBest", "feasible", "procsNum", "threadsNum", "totalTime"}


def test_reported_best_formula():
    assert jsonl.reported_best(0, 42) == 42
    assert jsonl.reported_best(3, 7) == 3_000_007


# --------------------------------------------------------------------- engine

@pytest.fixture(scope="module")
def tim_file(tmp_path_factory):
    problem = random_instance(55, n_events=15, n_rooms=5, n_features=2,
                              n_students=10, attend_prob=0.1)
    path = tmp_path_factory.mktemp("inst") / "tiny.tim"
    path.write_text(dump_tim(problem))
    return str(path)


def test_tim_round_trip(tim_file):
    with open(tim_file) as fh:
        problem = load_tim(fh)
    assert problem.n_events == 15
    text2 = dump_tim(problem)
    assert dump_tim(load_tim(text2)) == text2


@pytest.mark.slow
def test_engine_end_to_end(tim_file):
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=2,
                    generations=40, migration_period=10,
                    problem_type=1, max_steps=16, time_limit=300,
                    backend="cpu")
    best = run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    kinds = [next(iter(x)) for x in lines]
    # protocol shape: logEntries, then one solution per island, then the
    # two runEntry lines (ga.cpp:603-609)
    assert kinds.count("solution") == 2
    assert kinds.count("runEntry") == 2
    assert kinds[-1] == "runEntry" and kinds[-2] == "runEntry"
    assert "procsNum" in lines[-1]["runEntry"]
    run_best = lines[-1]["runEntry"]["totalBest"]
    assert run_best == best
    # solution totalBest must be consistent with runEntry (min over islands)
    sol_bests = [x["solution"]["totalBest"] for x in lines
                 if "solution" in x]
    assert min(sol_bests) == run_best
    # logEntry bests per island are strictly decreasing
    per_island = {}
    for x in lines:
        if "logEntry" in x:
            e = x["logEntry"]
            per_island.setdefault(e["procID"], []).append(e["best"])
    for bests in per_island.values():
        assert bests == sorted(bests, reverse=True)
        assert len(set(bests)) == len(bests)


def test_checkpoint_roundtrip(tmp_path, small_problem):
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 8)
    gacfg = ga.GAConfig(pop_size=8)
    fp = ckpt.config_fingerprint(small_problem, gacfg, n_islands=2)
    path = str(tmp_path / "ck.npz")
    key = jax.random.key(7)
    ckpt.save(path, st, key, 120, fp, best_seen=[42, 99], seed=7)
    st2, key2, gen2, best2, seed2 = ckpt.load(path, fp)
    assert gen2 == 120
    assert best2 == [42, 99]
    assert seed2 == 7
    np.testing.assert_array_equal(np.asarray(st.slots),
                                  np.asarray(st2.slots))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(key)),
        np.asarray(jax.random.key_data(key2)))
    # fingerprint mismatch refuses to load
    with pytest.raises(ValueError):
        ckpt.load(path, fp + "X")
    # a different island count is a different fingerprint, so a
    # mismatched --islands resume is refused cleanly (not a reshape error)
    fp4 = ckpt.config_fingerprint(small_problem, gacfg, n_islands=4)
    with pytest.raises(ValueError):
        ckpt.load(path, fp4)


@pytest.mark.slow
def test_engine_resume_seed_conflict(tim_file, tmp_path):
    """Resuming with an EXPLICIT conflicting -s is refused; resuming
    without -s adopts the checkpoint's seed (default time() seeds must
    not break resume)."""
    ck = str(tmp_path / "seedck.npz")
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=2,
                    generations=10, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    checkpoint=ck, checkpoint_every=1)
    run(cfg, out=io.StringIO())
    bad = RunConfig(input=tim_file, seed=6, pop_size=8, islands=2,
                    generations=20, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    checkpoint=ck, checkpoint_every=1, resume=True)
    with pytest.raises(ValueError):
        run(bad, out=io.StringIO())
    noseed = RunConfig(input=tim_file, seed=None, pop_size=8, islands=2,
                       generations=20, migration_period=10,
                       max_steps=8, time_limit=300, backend="cpu",
                       checkpoint=ck, checkpoint_every=1, resume=True)
    run(noseed, out=io.StringIO())
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["seed"]) == 5
        assert int(z["generation"]) == 20


@pytest.mark.slow
def test_engine_exact_generation_budget(tim_file):
    """A budget not divisible by migration_period must be honored exactly
    (clamped final dispatch), not overshot."""
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=9, pop_size=8, islands=2,
                    generations=25, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    trace=True)
    run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    gens = sum(x["phase"].get("gens", 0) for x in lines if "phase" in x)
    assert gens == 25


@pytest.mark.slow
def test_engine_trace_phases(tim_file):
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=2, pop_size=8, islands=2,
                    generations=20, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    trace=True)
    run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    names = [x["phase"]["name"] for x in lines if "phase" in x]
    for expect in ("load", "init", "dispatch", "fetch"):
        assert expect in names
    for x in lines:
        if "phase" in x:
            assert x["phase"]["seconds"] >= 0


@pytest.mark.slow
def test_engine_multi_epoch_dispatch(tim_file):
    """epochs_per_dispatch > 1 fuses epochs into one device call but
    must produce the identical generation count and protocol shape."""
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=4, pop_size=8, islands=2,
                    generations=40, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    epochs_per_dispatch=4, trace=True)
    run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    dispatches = [x["phase"] for x in lines
                  if "phase" in x and x["phase"]["name"] == "dispatch"]
    assert len(dispatches) == 1 and dispatches[0]["gens"] == 40
    kinds = [next(iter(x)) for x in lines]
    assert kinds.count("solution") == 2 and kinds.count("runEntry") == 2


@pytest.mark.slow
def test_engine_trace_profile(tim_file, tmp_path):
    """--trace-profile captures ONE jax.profiler trace of a warm mid-run
    dispatch (SURVEY section 5 tracing; the reference's only trace hook
    is the disabled MPE flag, Makefile:3)."""
    from timetabling_ga_tpu.runtime import engine as eng
    prof_dir = str(tmp_path / "prof")
    cfg = RunConfig(input=tim_file, seed=3, pop_size=4, islands=2,
                    generations=20, migration_period=5,
                    time_limit=30.0, auto_tune=False,
                    trace_profile=prof_dir)
    eng.precompile(cfg)               # warm: the capture needs a warm
    buf = io.StringIO()               # dispatch to profile the program,
    eng.run(cfg, out=buf)             # not its compile
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    profs = [x["phase"] for x in lines
             if "phase" in x and x["phase"]["name"] == "profile"]
    assert len(profs) == 1 and profs[0]["dir"] == prof_dir
    # the capture actually wrote a trace artifact
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof_dir)
             for f in fs]
    assert found, "no profiler artifacts written"


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): the checkpoint/resume round trip
# stays tier-1-covered by test_obs's checkpointed deltas run (loadable
# checkpoint) and test_faults' snapshot-rehydrate paths; the full
# two-run resume equivalence runs in the slow tier
def test_engine_resume(tim_file, tmp_path):
    ck = str(tmp_path / "resume.npz")
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=2,
                    generations=20, migration_period=10,
                    max_steps=8, time_limit=300, backend="cpu",
                    checkpoint=ck, checkpoint_every=1)
    run(cfg, out=io.StringIO())
    # resume continues from the checkpoint (generation counter there)
    import numpy as np
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 20
    cfg2 = RunConfig(input=tim_file, seed=5, pop_size=8, islands=2,
                     generations=40, migration_period=10,
                     max_steps=8, time_limit=300, backend="cpu",
                     checkpoint=ck, checkpoint_every=1, resume=True)
    buf = io.StringIO()
    run(cfg2, out=buf)
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 40
        best_saved = np.array(z["best_seen"]).tolist()
    # the resumed stream stays monotone: every post-resume logEntry beats
    # the best already reported before the interruption (persisted
    # best_seen), so no pre-crash bests are re-emitted
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    per_island = {}
    for x in lines:
        if "logEntry" in x:
            e = x["logEntry"]
            per_island.setdefault(e["procID"], []).append(e["best"])
    for i, bests in per_island.items():
        assert bests == sorted(bests, reverse=True)
        assert len(set(bests)) == len(bests)
        assert bests[-1] <= best_saved[i]


@pytest.mark.slow
def test_engine_dynamic_tail_serves_clamped_final_dispatch(tim_file):
    """The clamped final dispatch (generation budget not a multiple of
    migration_period) must run through the dynamic-gens runner — exact
    generation count, no fresh static compile shape — and a time-limited
    run must stop within one dispatch of its budget (VERDICT round-2
    weak 3). The generation-budget half is deterministic: 123 = 50 + 50
    + a 23-generation dynamic tail."""
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=1,
                    generations=123, migration_period=50,
                    max_steps=8, time_limit=3600, backend="cpu",
                    trace=True)
    eng.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    gens = [x["phase"]["gens"] for x in lines
            if "phase" in x and x["phase"]["name"] == "dispatch"]
    assert gens == [50, 50, 23], gens


@pytest.mark.slow
def test_engine_time_budget_holds(tim_file):
    """With programs compiled and the sec/gen estimate seeded outside
    the budget (the race protocol, tools/quality_race.py warm_tpu), the
    wall clock of a timed run must not overshoot the -t budget by more
    than one dispatch's granularity."""
    import time as _time
    from timetabling_ga_tpu.runtime import engine as eng
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=1,
                    generations=10 ** 9, migration_period=50,
                    max_steps=8, time_limit=6.0, backend="cpu")
    eng.precompile(cfg)
    # 1.05x + the measured endTry fetch reserve (VERDICT round-3 next
    # #4: the budget must hold to ~5%, with the fetch inside it)
    fetch = max(eng._FETCH_CACHE.values()) if eng._FETCH_CACHE else 1.0
    t0 = _time.monotonic()
    eng.run(cfg, out=io.StringIO())
    wall = _time.monotonic() - t0
    assert wall < 6.0 * 1.05 + fetch + 0.5, \
        f"budget 6s (+{fetch:.2f}s fetch reserve), ran {wall:.1f}s"


@pytest.mark.slow
def test_budget_tail_polish(tim_file):
    """When the generation loop stops because not even one more
    generation is predicted to fit, the stranded budget slice must run
    sweep-granular tail polish instead of idling (engine tail-polish
    phase; the reference's per-candidate clock check means ITS last
    slice is pure local search too, Solution.cpp:499)."""
    import time as _time
    from timetabling_ga_tpu.runtime import engine as eng
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=1,
                    generations=10 ** 9, migration_period=5,
                    ls_mode="sweep", ls_sweeps=1, init_sweeps=0,
                    time_limit=4.0, backend="cpu", trace=True,
                    auto_tune=False)
    eng.precompile(cfg)
    saved = dict(eng._SPG_CACHE)
    try:
        # force the generation loop to stop immediately (every
        # generation predicted not to fit) so the WHOLE budget is tail
        for k in list(eng._SPG_CACHE):
            eng._SPG_CACHE[k] = 1e9
        buf = io.StringIO()
        t0 = _time.monotonic()
        eng.run(cfg, out=buf)
        wall = _time.monotonic() - t0
    finally:
        eng._SPG_CACHE.clear()
        eng._SPG_CACHE.update(saved)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    phases = [x["phase"]["name"] for x in lines if "phase" in x]
    assert "tail-polish" in phases, phases
    assert phases.count("dispatch") == 0   # no generation ever fit
    fetch = max(eng._FETCH_CACHE.values()) if eng._FETCH_CACHE else 1.0
    assert wall < 4.0 * 1.05 + fetch + 0.5, \
        f"tail polish overshot: {wall:.1f}s on a 4s budget"
    bests = [x["logEntry"]["best"] for x in lines if "logEntry" in x]
    assert bests == sorted(bests, reverse=True)
    assert any("runEntry" in x for x in lines)


@pytest.mark.slow
def test_time_to_feasible_guard(tim_file):
    """Regression guard (VERDICT round-2 item 9): the engine must reach
    feasibility on an easy instance and report it through logEntry
    records with a finite time — so the capability cannot silently rot.
    Budget is generous: this guards the capability, not the speed."""
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=11, pop_size=16, islands=1,
                    generations=200, migration_period=20,
                    ls_mode="sweep", ls_sweeps=2, init_sweeps=10,
                    ls_converge=True, time_limit=120, backend="cpu")
    run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    feas_times = [x["logEntry"]["time"] for x in lines
                  if "logEntry" in x and x["logEntry"]["best"] < 10 ** 6]
    assert feas_times, "never reached feasibility on the easy instance"
    assert feas_times[0] < 120.0


def test_distributed_flag_validation():
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--coordinator", "h:1"])  # no n/id
    # multi-host + checkpoint is SUPPORTED since round 5 (process 0
    # saves the allgathered global population; resume re-shards)
    cfg = parse_args(["-i", "x.tim", "--distributed",
                      "--checkpoint", "c.npz"])
    assert cfg.distributed and cfg.checkpoint == "c.npz"
    cfg = parse_args(["-i", "x.tim", "--coordinator", "h:1",
                      "--num-processes", "2", "--process-id", "1"])
    assert cfg.coordinator == "h:1"
    assert cfg.num_processes == 2 and cfg.process_id == 1


@pytest.mark.slow
def test_distributed_single_process_smoke(tim_file):
    """The multi-host entry point (VERDICT round-2 item 6, the
    reference's MPI_Init role, ga.cpp:373-380) wires end-to-end with
    num_processes=1: jax.distributed.initialize runs before the mesh is
    built and a full engine.run completes. A subprocess is required
    because initialize() must precede any backend use in the process."""
    import subprocess
    import sys as _sys
    code = (
        "import io, sys\n"
        "from timetabling_ga_tpu.runtime.config import parse_args\n"
        "from timetabling_ga_tpu.runtime import engine\n"
        "cfg = parse_args(['-i', sys.argv[1],\n"
        "    '--coordinator', 'localhost:38217',\n"
        "    '--num-processes', '1', '--process-id', '0',\n"
        "    '--backend', 'cpu', '--pop-size', '4', '-s', '1',\n"
        "    '--generations', '5', '--migration-period', '5'])\n"
        "best = engine.run(cfg, out=io.StringIO())\n"
        "import jax\n"
        "assert jax.process_count() == 1\n"
        "assert engine._DISTRIBUTED_DONE\n"
        "print('DIST_OK', best)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([_sys.executable, "-c", code, tim_file],
                         capture_output=True, text=True, timeout=300,
                         env=env)
    assert "DIST_OK" in out.stdout, out.stderr[-2000:]


def test_apply_tuned_defaults_size_rule_and_overrides():
    """Size-tuned production defaults (VERDICT round-2 item 8): small
    populations with deep per-child sweeps at both scales, comp scale
    adding violation-guided repair + a full-pivot post-feasibility
    endgame; explicit user settings always win."""
    small = RunConfig(input="x.tim").apply_tuned_defaults(100)
    assert (small.pop_size, small.ls_sweeps, small.init_sweeps) == \
        (32, 6, 30)
    assert small.ls_mode == "sweep" and small.ls_converge
    assert small.ls_sideways > 0
    assert small.post_ls_sweeps and small.post_hot_k == 0
    assert small.p3 > 0   # Move3 sweep block: the small-plateau lever
    big = RunConfig(input="x.tim").apply_tuned_defaults(400)
    assert (big.pop_size, big.ls_sweeps, big.init_sweeps) == (16, 2, 200)
    assert big.ls_hot_k > 0 and big.post_hot_k == 0
    assert big.post_ls_sweeps > big.ls_sweeps
    # explicit values survive
    mine = RunConfig(input="x.tim", pop_size=64,
                     ls_sweeps=3).apply_tuned_defaults(400)
    assert mine.pop_size == 64 and mine.ls_sweeps == 3
    assert mine.init_sweeps == 200  # untouched field still tuned


def test_explicit_flags_survive_auto_tune():
    """A flag the user EXPLICITLY set to a value that happens to equal
    the dataclass default must survive apply_tuned_defaults (ADVICE
    round 3: value-vs-default comparison alone cannot distinguish
    'unset' from 'explicitly default')."""
    from timetabling_ga_tpu.runtime.config import parse_args
    cfg = parse_args(["-i", "x.tim", "--ls-mode", "random",
                      "--ls-sweeps", "1", "--ls-sideways", "0"])
    cfg.apply_tuned_defaults(400)
    assert cfg.ls_mode == "random"      # not overridden to "sweep"
    assert cfg.ls_sweeps == 1           # not overridden to 2
    assert cfg.ls_sideways == 0.0       # not overridden to 0.25
    assert cfg.pop_size == 16           # untouched field still tuned


@pytest.mark.slow
def test_tpu_path_thread_id_is_zero(tim_file):
    """threadID := 0 on the TPU path, by definition (runtime/jsonl.py
    module docstring): island breeding is one fused vmap with no thread
    identity. The protocol field stays (schema parity) pinned at 0."""
    import io
    from timetabling_ga_tpu.runtime import engine
    from timetabling_ga_tpu.runtime.config import RunConfig
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=7, generations=12, islands=2,
                    pop_size=8, auto_tune=False, ls_mode="sweep",
                    ls_sweeps=1, init_sweeps=2)
    engine.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    entries = [x["logEntry"] for x in lines if "logEntry" in x]
    assert entries, "expected at least one logEntry"
    assert all(e["threadID"] == 0 for e in entries)
    sols = [x["solution"] for x in lines if "solution" in x]
    assert all(s["threadID"] == 0 for s in sols)


@pytest.mark.slow
def test_post_feasibility_phase_switch(tim_file):
    """With post_* flags set, the engine must switch breeding configs at
    the first dispatch boundary after the global best reaches
    feasibility (the reference's phase-2 scv polish, Solution.cpp:
    619-768): a --trace run shows the phase-switch record, and the run
    still completes with a monotone logEntry stream."""
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=16, islands=1,
                    generations=120, migration_period=10,
                    ls_mode="sweep", ls_sweeps=1, init_sweeps=0,
                    ls_hot_k=4, post_ls_sweeps=2, post_hot_k=0,
                    time_limit=120, backend="cpu", trace=True)
    eng.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    phases = [x["phase"]["name"] for x in lines if "phase" in x]
    feas = [x for x in lines
            if "logEntry" in x and x["logEntry"]["best"] < 10 ** 6]
    if feas:   # easy instance: expected to go feasible -> must switch
        assert "phase-switch" in phases
    bests = [x["logEntry"]["best"] for x in lines if "logEntry" in x]
    assert bests == sorted(bests, reverse=True)
    assert any("runEntry" in x for x in lines)


def test_build_post_config_mapping():
    """build_post_config: None when no post field is set or when the
    post config would equal the base config; otherwise only the named
    fields change."""
    from timetabling_ga_tpu.runtime.engine import (build_ga_config,
                                                   build_post_config)
    base_cfg = RunConfig(input="x.tim", ls_mode="sweep", ls_sweeps=2,
                         ls_hot_k=48)
    g = build_ga_config(base_cfg)
    assert build_post_config(base_cfg, g) is None
    cfg2 = RunConfig(input="x.tim", ls_mode="sweep", ls_sweeps=2,
                     ls_hot_k=48, post_hot_k=48)   # equal -> no switch
    assert build_post_config(cfg2, build_ga_config(cfg2)) is None
    cfg3 = RunConfig(input="x.tim", ls_mode="sweep", ls_sweeps=2,
                     ls_hot_k=48, post_hot_k=0, post_ls_sweeps=4,
                     post_swap_block=16, post_sideways=0.5)
    p = build_post_config(cfg3, build_ga_config(cfg3))
    assert p is not None
    assert (p.ls_hot_k, p.ls_sweeps, p.ls_swap_block) == (0, 4, 16)
    assert p.ls_sideways == 0.5
    # untouched fields inherit
    assert p.ls_mode == "sweep" and p.pop_size == cfg3.pop_size
    # post_sideways alone is enough to define a post phase
    cfg4 = RunConfig(input="x.tim", ls_mode="sweep", ls_sideways=0.25,
                     post_sideways=0.0)
    p4 = build_post_config(cfg4, build_ga_config(cfg4))
    assert p4 is not None and p4.ls_sideways == 0.0


@pytest.mark.slow
def test_distributed_two_process_run(tim_file, tmp_path):
    """A REAL 2-process jax.distributed run (VERDICT round-3 next #5 —
    the reference's mpirun actually exercised >1 rank, ga.cpp:373-380):
    two CPU processes x 4 virtual devices each form one 8-island mesh.
    Asserts both processes exit cleanly, process 1 emits NOTHING
    (single-controller reporting), and process 0's protocol covers all
    8 islands with procsNum=8 in the runEntry."""
    import socket
    import subprocess
    import sys as _sys
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    outfile = str(tmp_path / "dist0.jsonl")

    def proc(pid):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4")
        args = [_sys.executable, "-m", "timetabling_ga_tpu.cli",
                "-i", tim_file, "-s", "9", "--backend", "cpu",
                "--coordinator", f"localhost:{port}",
                "--num-processes", "2", "--process-id", str(pid),
                "--pop-size", "4", "--generations", "10",
                "--migration-period", "5", "--no-auto-tune",
                "--ls-mode", "sweep", "--ls-sweeps", "1",
                "-m", "8", "-t", "600"]
        if pid == 0:
            args += ["-o", outfile]
        return subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)

    p0, p1 = proc(0), proc(1)
    out0, err0 = p0.communicate(timeout=600)
    out1, err1 = p1.communicate(timeout=120)
    assert p0.returncode == 0, err0[-3000:]
    assert p1.returncode == 0, err1[-3000:]
    # single-controller reporting: only process 0 writes the protocol
    # (process 1's stdout may carry collective-backend chatter like
    # "[Gloo] Rank ..." — what matters is zero JSONL records)
    assert not [ln for ln in out1.splitlines()
                if ln.strip().startswith("{")], out1[:500]
    lines = [json.loads(x) for x in open(outfile)]
    kinds = [next(iter(x)) for x in lines]
    assert kinds.count("solution") == 8     # one per island, global view
    assert kinds.count("runEntry") == 2
    final = lines[-1]["runEntry"]
    assert final["procsNum"] == 8
    # global best consistency across the allgathered view
    sol_bests = [x["solution"]["totalBest"] for x in lines
                 if "solution" in x]
    assert min(sol_bests) == final["totalBest"]


@pytest.mark.slow
def test_distributed_checkpoint_resume(tim_file, tmp_path):
    """Multi-host checkpoint/resume (VERDICT round-4 next #7): a
    2-process 8-island run checkpoints (process 0 writes the allgathered
    GLOBAL population), is torn down, and a second 2-process run resumes
    from the file and re-shards — the npz serves all ranks the way the
    reference's wire format did (ga.cpp:264-368)."""
    import socket
    import subprocess
    import sys as _sys
    from timetabling_ga_tpu.runtime import checkpoint as ck_mod
    ckfile = str(tmp_path / "dist.ck.npz")
    outfile = str(tmp_path / "dist_resume.jsonl")

    def run_pair(gens, resume):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]

        def proc(pid):
            env = dict(
                os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=4")
            args = [_sys.executable, "-m", "timetabling_ga_tpu.cli",
                    "-i", tim_file, "-s", "9", "--backend", "cpu",
                    "--coordinator", f"localhost:{port}",
                    "--num-processes", "2", "--process-id", str(pid),
                    "--pop-size", "4", "--generations", str(gens),
                    "--migration-period", "5", "--no-auto-tune",
                    "--ls-mode", "sweep", "--ls-sweeps", "1",
                    "-m", "8", "-t", "600", "--no-precompile",
                    "--checkpoint", ckfile, "--checkpoint-every", "1"]
            if resume:
                args += ["--resume"]
            if pid == 0:
                args += ["-o", outfile]
            return subprocess.Popen(args, env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.PIPE, text=True)

        p0, p1 = proc(0), proc(1)
        out0, err0 = p0.communicate(timeout=600)
        out1, err1 = p1.communicate(timeout=120)
        assert p0.returncode == 0, err0[-3000:]
        assert p1.returncode == 0, err1[-3000:]

    run_pair(gens=10, resume=False)   # writes the gen-10 checkpoint
    assert os.path.exists(ckfile)
    with np.load(ckfile, allow_pickle=False) as z:
        assert int(z["generation"]) == 10
        assert z["slots"].shape[0] == 8 * 4   # GLOBAL population saved
    run_pair(gens=20, resume=True)    # second "incarnation" continues
    with np.load(ckfile, allow_pickle=False) as z:
        assert int(z["generation"]) == 20
    lines = [json.loads(x) for x in open(outfile)]
    assert [x for x in lines if "runEntry" in x]


@pytest.mark.slow
def test_post_pop_size_elite_shrink(tim_file):
    """post_pop_size: at the post-feasibility switch every island
    truncates to its elite rows (islands.make_shrink_runner); the run
    completes with per-island solution records, the phase switch is
    visible, and the kick operates on the shrunk population without
    shape errors."""
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=5, pop_size=8, islands=2,
                    generations=60, migration_period=5,
                    ls_mode="sweep", ls_sweeps=1, init_sweeps=2,
                    post_ls_sweeps=2, post_pop_size=3, kick_stall=1,
                    time_limit=300, backend="cpu", trace=True,
                    auto_tune=False)
    best = eng.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    phases = [x["phase"]["name"] for x in lines if "phase" in x]
    assert "phase-switch" in phases
    sols = [x["solution"] for x in lines if "solution" in x]
    assert len(sols) == 2             # one per island, post-shrink
    # the logEntry stream is monotone PER ISLAND (islands interleave)
    for i in range(2):
        bests = [x["logEntry"]["best"] for x in lines
                 if "logEntry" in x and x["logEntry"]["procID"] == i]
        assert bests == sorted(bests, reverse=True)
    assert best == min(s["totalBest"] for s in sols)


# ---------------------------------------------------- dispatch pipeline

def test_pipeline_depth2_matches_serial(tim_file, tmp_path):
    """Tier-1 pipeline determinism (fast, single device, 3 chunks): the
    depth-2 pipelined engine must emit protocol records byte-identical
    to the serial engine's modulo timing fields — pipelining reorders
    WHEN telemetry is processed, never WHAT is dispatched — and the
    checkpoint written mid-pipeline (a control fence on the in-flight
    chunk + writer-thread serialization) must land on disk."""
    from timetabling_ga_tpu.runtime import engine as eng
    ck = str(tmp_path / "pipe.ck.npz")

    def go(pipeline, checkpoint=None):
        buf = io.StringIO()
        cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                        generations=30, migration_period=10,
                        max_steps=8, time_limit=300, backend="cpu",
                        auto_tune=False, trace=True, pipeline=pipeline,
                        checkpoint=checkpoint)
        best = eng.run(cfg, out=buf)
        return best, [json.loads(x) for x in buf.getvalue().splitlines()]

    b_serial, l_serial = go(False)
    b_piped, l_piped = go(True, checkpoint=ck)
    assert b_serial == b_piped
    assert jsonl.strip_timing(l_serial) == jsonl.strip_timing(l_piped)
    # the pipelined leg really ran pipelined, depth 2 over 3 chunks
    loops = [x["phase"] for x in l_piped
             if "phase" in x and x["phase"]["name"] == "gen-loop"]
    assert loops and loops[0]["pipelined"] is True
    assert loops[0]["dispatches"] == 3
    loops0 = [x["phase"] for x in l_serial
              if "phase" in x and x["phase"]["name"] == "gen-loop"]
    assert loops0 and loops0[0]["pipelined"] is False
    # mid-pipeline checkpoint is durable and loadable
    assert os.path.exists(ck)
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 30


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): pipeline-vs-serial record identity
# stays tier-1-pinned by test_pipeline_depth2_matches_serial; this one
# only checks the auto-disable predicate across config combinations
def test_pipeline_auto_disables_on_control_paths(tim_file):
    """A post config makes the phase switch a between-dispatch CONTROL
    read, so the engine must fall back to the serial loop even with
    pipeline=True (module docstring's control-vs-telemetry rule)."""
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=20, migration_period=10,
                    ls_mode="sweep", ls_sweeps=1, init_sweeps=0,
                    post_ls_sweeps=2, max_steps=8, time_limit=300,
                    backend="cpu", auto_tune=False, trace=True,
                    pipeline=True)
    eng.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    loops = [x["phase"] for x in lines
             if "phase" in x and x["phase"]["name"] == "gen-loop"]
    assert loops and loops[0]["pipelined"] is False


def test_async_writer_order_jobs_and_error_propagation():
    """jsonl.AsyncWriter: record order is preserved, submitted jobs run
    in queue order, close() drains, and a worker-side write error
    surfaces on the main thread instead of vanishing."""
    buf = io.StringIO()
    w = jsonl.AsyncWriter(buf)
    for i in range(200):
        jsonl.log_entry(w, 0, 0, 10_000 - i, 0.5)
    ran = []
    w.submit(lambda: ran.append(len(buf.getvalue().splitlines())))
    jsonl.run_entry(w, 1, True)
    w.close()
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert len(lines) == 201
    bests = [x["logEntry"]["best"] for x in lines if "logEntry" in x]
    assert bests == sorted(bests, reverse=True)   # FIFO order held
    assert ran == [200]          # job saw every earlier record flushed
    assert "runEntry" in lines[-1]

    class _Boom(io.StringIO):
        def write(self, s):
            raise IOError("disk full")

    w2 = jsonl.AsyncWriter(_Boom())
    w2.write("{}\n")
    with pytest.raises(IOError):
        w2.close()
    w2.close()           # idempotent: no deadlock on a second close
    with pytest.raises(RuntimeError):
        w2.write("{}\n")   # records must never be silently dropped
    # close(raise_error=False): the exception-path form swallows the
    # worker error instead of masking the run's own failure
    w3 = jsonl.AsyncWriter(_Boom())
    w3.write("{}\n")
    w3.close(raise_error=False)


@pytest.mark.slow
def test_checkpoint_survives_sigkill_and_jsonl_stays_line_atomic(
        tim_file, tmp_path):
    """ISSUE 2 satellite: kill the run mid-stream (SIGKILL — no atexit,
    no drain) and assert (a) the last fsynced checkpoint round-trips
    through _reshard_state bit-exact, and (b) the JSONL output holds
    only whole records — the writer thread hands each record to the OS
    in one write, so a kill can truncate at most the final line."""
    import signal
    import subprocess
    import sys as _sys
    import time as _time
    from timetabling_ga_tpu.parallel import islands as isl
    from timetabling_ga_tpu.runtime import engine as eng
    ck = str(tmp_path / "kill.ck.npz")
    outfile = str(tmp_path / "kill.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    args = [_sys.executable, "-m", "timetabling_ga_tpu.cli",
            "-i", tim_file, "-s", "5", "--backend", "cpu",
            "--pop-size", "8", "--islands", "2",
            "--generations", "1000000", "--migration-period", "5",
            "--no-auto-tune", "--no-precompile", "-m", "8",
            "-t", "100000", "--checkpoint", ck,
            "--checkpoint-every", "1", "-o", outfile]
    proc = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    try:
        deadline = _time.monotonic() + 240
        # let it checkpoint at least twice so the kill lands mid-stream,
        # beyond the first save
        saves = 0
        last_mtime = None
        while _time.monotonic() < deadline and saves < 2:
            if os.path.exists(ck):
                m = os.path.getmtime(ck)
                if m != last_mtime:
                    saves += 1
                    last_mtime = m
            if proc.poll() is not None:
                raise AssertionError(
                    "run exited early: "
                    + proc.stderr.read().decode()[-2000:])
            _time.sleep(0.05)
        assert saves >= 2, "never reached a second checkpoint"
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)

    # (a) checkpoint integrity: load -> reshard onto a live mesh ->
    # fetch back, bit-exact against the file's own arrays
    with np.load(ck, allow_pickle=False) as z:
        fp = str(z["fingerprint"])
        saved = {k: np.array(z[k]) for k in
                 ("slots", "rooms", "penalty", "hcv", "scv")}
    state, key, gen, best_seen, seed = ckpt.load(ck, fp)
    assert gen >= 1 and seed == 5 and best_seen is not None
    mesh = isl.make_mesh(2)
    resharded = eng._reshard_state(state, mesh)
    for name, arr in saved.items():
        np.testing.assert_array_equal(
            np.asarray(getattr(resharded, name)), arr,
            err_msg=f"{name} not bit-exact through _reshard_state")

    # (b) line atomicity: every line but (at most) the torn final one
    # parses as exactly one record
    with open(outfile) as fh:
        raw = fh.read()
    lines = raw.splitlines()
    if lines and not raw.endswith("\n"):
        lines = lines[:-1]          # a SIGKILL may tear the final line
    assert lines, "no JSONL output before the kill"
    for ln in lines:
        rec = json.loads(ln)        # no spliced/interleaved records
        assert len(rec) == 1


def test_post_pop_size_flag_validation():
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--post-pop-size", "4",
                    "--checkpoint", "c.npz"])
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--post-pop-size", "32",
                    "--pop-size", "16"])
    cfg = parse_args(["-i", "x.tim", "--post-pop-size", "4"])
    assert cfg.post_pop_size == 4
    # tuned defaults drop the shrink when a checkpoint is configured
    ck = RunConfig(input="x.tim", checkpoint="c.npz")
    ck.apply_tuned_defaults(400)
    assert ck.post_pop_size is None
    nock = RunConfig(input="x.tim").apply_tuned_defaults(400)
    assert nock.post_pop_size == 4
