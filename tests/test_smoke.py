"""API-drift smoke tests: the one clear failure you get instead of a
wall of collection errors.

The seed spent its whole life broken by a single import
(`from jax import shard_map` on JAX 0.4.37) that surfaced as an
ImportError in every test module's collection. These tests pin the two
entry points that must ALWAYS work — package import and CLI --help —
with no device access, so the next API drift fails here with a readable
message.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_package_imports():
    import timetabling_ga_tpu
    assert timetabling_ga_tpu.__version__


def test_compat_shard_map_resolves():
    """The version-tolerant resolver must hand back a callable on the
    installed JAX, whichever home shard_map lives in."""
    from timetabling_ga_tpu.compat import shard_map
    assert callable(shard_map)


def test_cli_help_runs_without_device():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu", "--help"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    assert "-i" in r.stdout


def test_analysis_cli_runs_without_device():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu.analysis",
         "--list-rules"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    assert "TT501" in r.stdout
