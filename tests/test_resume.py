"""Resume, don't replay (ISSUE 12): snapshot-shipping failover +
per-job serve-path fault recovery.

The acceptance properties pinned here:

  1. WIRE FORMAT — the per-job snapshot serialization round-trips;
     fingerprint mismatches and truncated/corrupted bytes are both
     REJECTED with named fields/fingerprints (serve/snapshot.py);
  2. RESUME IDENTITY — a job resumed from a shipped snapshot emits,
     prefix + continuation, a record stream identical to an
     uninterrupted solve modulo timing/fault records, duplicate-free
     by the restored `emitted` floor;
  3. SERVE-PATH FAULT RECOVERY — a transient fault during a stacked
     quantum requeues ONLY the dispatch's jobs from their park
     snapshots (streams still identical to an uninjected run); a
     non-transient/budget-exhausted job fails ALONE with a terminal
     jobEntry, co-tenants bit-identical;
  4. ISOLATION — a hung snapshot export parks one handler thread
     only; a die during resume admission demotes to replay; neither
     stalls the drive loop, other jobs, or writer drain (fault sites
     quantum / snapshot_ship / resume);
  5. FLEET ACCEPTANCE — gateway + 2 replicas, kill one observed
     mid-job: the job completes on the survivor having re-run at most
     one quantum (never from gen 0), `fleet.resume.hits` >= 1 on
     /metrics, and every stream equals the unrouted baseline;
  6. PREEMPT DRAIN — /v1/drain?mode=preempt parks + ships within the
     deadline; a gateway-driven preempt is lossless scale-down.
"""

import io
import json
import time

import pytest

from timetabling_ga_tpu.fleet.gateway import Gateway
from timetabling_ga_tpu.fleet.replicas import (
    http_json, http_text, in_process_replica)
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_fleet_args, parse_serve_args)
from timetabling_ga_tpu.serve import snapshot as snapshot_mod
from timetabling_ga_tpu.serve.service import SolveService

_SHAPE_A = dict(n_events=12, n_rooms=3, n_features=2, n_students=8,
                attend_prob=0.2)
_SHAPE_B = dict(n_events=40, n_rooms=4, n_features=2, n_students=30,
                attend_prob=0.1)

_PA = random_instance(71, **_SHAPE_A)
_PB = random_instance(72, **_SHAPE_B)


@pytest.fixture(autouse=True)
def _no_fault_plan():
    """Every test leaves the process without an installed plan."""
    faults.install(None)
    yield
    faults.install(None)


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _fleet_cfg(urls, **kw):
    kw.setdefault("listen", "127.0.0.1:0")
    kw.setdefault("probe_every", 0.1)
    kw.setdefault("poll_every", 0.05)
    kw.setdefault("dead_after", 2)
    return FleetConfig(replicas=list(urls), **kw)


def _job_records(text, job_id):
    out = []
    for line in text.splitlines():
        rec = json.loads(line)
        body = rec[next(iter(rec))]
        if isinstance(body, dict) and body.get("job") == str(job_id):
            out.append(rec)
    return out


def _baseline(jobs, **cfg_kw):
    """{id: strip_timing(records)} for `jobs` on a bare service."""
    buf = io.StringIO()
    svc = SolveService(_serve_cfg(**cfg_kw), out=buf)
    for jid, problem, seed, gens in jobs:
        svc.submit(problem, job_id=jid, seed=seed, generations=gens)
    svc.drive()
    svc.close()
    return {jid: jsonl.strip_timing(_job_records(buf.getvalue(), jid))
            for jid, *_ in jobs}


# ---------------------------------------------------------- wire format


def test_wire_roundtrip_and_rejections():
    buf = io.StringIO()
    svc = SolveService(_serve_cfg(), out=buf)
    svc.submit(_PA, job_id="w", seed=5, generations=20)
    svc.step()
    job = svc.queue.get("w")
    ship = job.ship
    assert ship is not None and ship.gens_done > 0
    wire = ship.pack()
    svc.close()

    # JSON-safe: the wire object must survive the /v1 protocol
    wire = json.loads(json.dumps(wire))
    expect = snapshot_mod.wire_fingerprint(job.bucket, 4, 5)
    assert wire["fingerprint"] == expect
    state, meta = snapshot_mod.unpack_state(
        wire, expect_fingerprint=expect)
    assert (state.slots == ship.state.slots).all()
    assert (state.penalty == ship.state.penalty).all()
    assert meta == {"gens_done": ship.gens_done,
                    "chunks": ship.chunks, "emitted": ship.emitted,
                    "best": ship.best}

    # fingerprint mismatch: NAMED fingerprints, SnapshotMismatch
    other = snapshot_mod.wire_fingerprint(job.bucket, 8, 5)
    with pytest.raises(snapshot_mod.SnapshotMismatch) as ei:
        snapshot_mod.verify_wire(wire, expect_fingerprint=other)
    assert expect in str(ei.value) and other in str(ei.value)

    # truncated bytes: named field, SnapshotCorrupt
    cut = dict(wire, npz=wire["npz"][: len(wire["npz"]) // 2])
    with pytest.raises(snapshot_mod.SnapshotCorrupt) as ei:
        snapshot_mod.verify_wire(cut)
    assert "npz" in str(ei.value)

    # CRC mismatch (bit rot at the right length): named field
    with pytest.raises(snapshot_mod.SnapshotCorrupt) as ei:
        snapshot_mod.verify_wire(dict(wire, crc=wire["crc"] ^ 1))
    assert "CRC" in str(ei.value)

    # missing field + foreign version
    with pytest.raises(snapshot_mod.SnapshotCorrupt) as ei:
        snapshot_mod.verify_wire({k: v for k, v in wire.items()
                                  if k != "gens_done"})
    assert "gens_done" in str(ei.value)
    with pytest.raises(snapshot_mod.SnapshotMismatch):
        snapshot_mod.verify_wire(dict(wire, v=99))


# -------------------------------------------------------- resume (serve)


def test_resumed_stream_identity():
    """Prefix (shipped records) + continuation (resumed service) ==
    uninterrupted stream, modulo timing/fault records — ISSUE 12's
    duplicate-free seam, at the serve level."""
    jobs = [("r", _PA, 3, 20)]
    base = _baseline(jobs)

    buf1 = io.StringIO()
    svc1 = SolveService(_serve_cfg(), out=buf1)
    svc1.submit(_PA, job_id="r", seed=3, generations=20)
    svc1.step()
    svc1.step()
    # the group went device-resident after its first park; exporting
    # the CURRENT progress is a snapshot-shipping request, and the
    # park fence for those is flush_resident (scheduler RESIDENCY)
    svc1.scheduler.flush_resident("ship")
    ship = svc1.queue.get("r").ship
    wire = json.loads(json.dumps(ship.pack()))
    prefix = list(ship.records)
    assert ship.gens_done == 10
    svc1.close()

    buf2 = io.StringIO()
    svc2 = SolveService(_serve_cfg(), out=buf2)
    svc2.submit(_PA, job_id="r", seed=3, generations=20,
                snapshot=wire)
    job = svc2.queue.get("r")
    assert job.state == "parked" and job.gens_done == 10
    svc2.drive()
    svc2.close()
    cont = _job_records(buf2.getvalue(), "r")
    # the only seam is the faultEntry (site=fleet action=resume),
    # which strip_timing drops
    seams = [r for r in cont if "faultEntry" in r]
    assert any(r["faultEntry"]["site"] == "fleet"
               and r["faultEntry"]["action"] == "resume"
               for r in seams)
    assert jsonl.strip_timing(prefix + cont) == base["r"]
    assert svc2.queue.get("r").result["resumed_at"] == 10


def test_bad_snapshot_demotes_to_replay():
    """A corrupt / mismatched / die-injected resume falls back to a
    fresh solve — never an error, never a stalled drive loop — and
    the fresh stream matches the plain baseline."""
    base = _baseline([("d", _PA, 3, 10)])

    buf1 = io.StringIO()
    svc1 = SolveService(_serve_cfg(), out=buf1)
    svc1.submit(_PA, job_id="seed", seed=3, generations=10)
    svc1.step()
    wire = svc1.queue.get("seed").ship.pack()
    svc1.close()

    for case, bad in (
            ("corrupt", dict(wire, npz=wire["npz"][:40])),
            ("foreign", dict(wire, fingerprint="j1|b9|p9|s9")),
            ("die", dict(wire))):
        buf = io.StringIO()
        svc = SolveService(_serve_cfg(), out=buf)
        if case == "die":
            faults.install("resume:1:die")
        svc.submit(_PA, job_id="d", seed=3, generations=10,
                   snapshot=bad)
        faults.install(None)
        job = svc.queue.get("d")
        assert job.state == "pending", case     # demoted, not parked
        svc.drive()
        svc.close()
        recs = _job_records(buf.getvalue(), "d")
        assert jsonl.strip_timing(recs) == base["d"], case
        assert any(r["faultEntry"]["site"] == "resume"
                   and r["faultEntry"]["action"] == "replay"
                   for r in recs if "faultEntry" in r), case
        assert svc.registry.counter(
            "serve.jobs_resume_rejected").value >= 1, case


# ------------------------------------------- serve-path fault recovery


def test_quantum_fault_requeues_from_snapshots():
    """A transient fault during a stacked quantum requeues only the
    affected jobs from their park snapshots: every job still
    completes, and every stream — affected and co-tenant — is
    bit-identical to an uninjected run (strip_timing domain)."""
    jobs = [("qa", _PA, 3, 15), ("qb", _PB, 4, 15)]
    base = _baseline(jobs)

    buf = io.StringIO()
    svc = SolveService(_serve_cfg(), out=buf)
    faults.install("quantum:2:unavailable")
    for jid, p, seed, gens in jobs:
        svc.submit(p, job_id=jid, seed=seed, generations=gens)
    svc.drive()
    faults.install(None)
    svc.close()
    assert svc.registry.counter("serve.job_recoveries").value >= 1
    for jid, *_ in jobs:
        assert svc.queue.get(jid).state == "done"
        assert jsonl.strip_timing(
            _job_records(buf.getvalue(), jid)) == base[jid], jid


def test_quantum_fault_budget_exhausted_fails_alone():
    """A non-transient quantum fault (or an exhausted per-job
    recovery budget) fails THAT dispatch's jobs with a terminal
    jobEntry; jobs of the other bucket run on bit-identically."""
    jobs = [("fa", _PA, 3, 15), ("fb", _PB, 4, 15)]
    base = _baseline(jobs)

    buf = io.StringIO()
    svc = SolveService(_serve_cfg(), out=buf)
    faults.install("quantum:1:error")
    for jid, p, seed, gens in jobs:
        svc.submit(p, job_id=jid, seed=seed, generations=gens)
    svc.drive()
    faults.install(None)
    svc.close()
    states = {jid: svc.queue.get(jid).state for jid, *_ in jobs}
    failed = [j for j, s in states.items() if s == "failed"]
    assert len(failed) == 1, states         # one bucket's dispatch
    survivor = next(j for j, s in states.items() if s == "done")
    assert jsonl.strip_timing(
        _job_records(buf.getvalue(), survivor)) == base[survivor]
    fail_recs = _job_records(buf.getvalue(), failed[0])
    assert any(r["jobEntry"]["event"] == "failed"
               and "quantum fault" in r["jobEntry"].get("reason", "")
               for r in fail_recs if "jobEntry" in r)
    # exhausted budget path: repeated transients past the per-job cap
    buf2 = io.StringIO()
    svc2 = SolveService(_serve_cfg(max_job_recoveries=1), out=buf2)
    faults.install("quantum:1:unavailable,quantum:2:unavailable")
    svc2.submit(_PA, job_id="fx", seed=3, generations=15)
    svc2.drive()
    faults.install(None)
    svc2.close()
    assert svc2.queue.get("fx").state == "failed"
    assert svc2.queue.get("fx").recoveries == 2


# -------------------------------------------------------- fault isolation


def test_snapshot_ship_hang_parks_handler_only(monkeypatch):
    """A hung snapshot export (`snapshot_ship:1:hang`) parks ONE
    replica handler thread: the fetch times out client-side, the
    drive loop keeps solving, a later export works, and the writer
    drains on stop."""
    monkeypatch.setattr(faults, "HANG_S", 30.0)
    rep, handle = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "hang0")
    try:
        http_json("POST", rep.url + "/v1/solve",
                  {"tim": dump_tim(_PA), "id": "h", "seed": 3,
                   "generations": 400})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if rep.svc.queue.get("h").ship is not None:
                    break
            except KeyError:
                pass
            time.sleep(0.02)
        faults.install("snapshot_ship:1:hang")
        with pytest.raises(Exception):
            handle.get_job("h", timeout=0.5, with_records=False,
                           snapshot=True)
        # the drive loop never stalled: progress continues
        g0 = rep.svc.queue.get("h").gens_done
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if rep.svc.queue.get("h").gens_done > g0:
                break
            time.sleep(0.02)
        assert rep.svc.queue.get("h").gens_done > g0
        # the next export (invocation 2) works
        view = handle.get_job("h", timeout=10.0, with_records=False,
                              snapshot=True)
        assert view.get("snapshot") is not None
        faults.install(None)
        # writer drains: graceful stop completes the stream
        rep.svc.cancel("h")
        rep.stop(timeout=60)
        assert rep.drained.wait(5)
    finally:
        faults.install(None)
        rep.kill()


# ------------------------------------------------------- preempt drain


def test_preempt_drain_ships_and_honors_deadline():
    """/v1/drain?mode=preempt parks every active job as `preempted`
    with its snapshot published; the replica exits once every ship
    unit is fetched — or at --preempt-grace when nobody fetches."""
    # nobody fetches: the deadline bounds the wait
    rep, handle = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", preempt_grace=1.5), "pd0")
    http_json("POST", rep.url + "/v1/solve",
              {"tim": dump_tim(_PA), "id": "p1", "seed": 3,
               "generations": 5000})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            if rep.svc.queue.get("p1").ship is not None:
                break
        except KeyError:
            pass
        time.sleep(0.02)
    t0 = time.monotonic()
    http_json("POST", rep.url + "/v1/drain?mode=preempt", {},
              ok=(200,))
    assert rep.drained.wait(30)
    assert time.monotonic() - t0 < 15       # grace 1.5s + slack
    rep.kill()

    # fetched: exit is prompt, the view shows `preempted` + snapshot
    rep2, handle2 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", preempt_grace=60.0), "pd1")
    http_json("POST", rep2.url + "/v1/solve",
              {"tim": dump_tim(_PA), "id": "p2", "seed": 3,
               "generations": 5000})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            if rep2.svc.queue.get("p2").ship is not None:
                break
        except KeyError:
            pass
        time.sleep(0.02)
    http_json("POST", rep2.url + "/v1/drain?mode=preempt", {},
              ok=(200,))
    deadline = time.monotonic() + 30
    view = {}
    while time.monotonic() < deadline:
        view = handle2.get_job("p2", timeout=5.0,
                               with_records=False, snapshot=True)
        if view.get("state") == "preempted":
            break
        time.sleep(0.05)
    assert view.get("state") == "preempted"
    assert view.get("snapshot") is not None
    assert any("jobEntry" in r for r in view.get("snapshot_records",
                                                 []))
    # the fetch above marked the unit served: prompt exit, way before
    # the 60s grace
    assert rep2.drained.wait(30)
    rep2.kill()
    # a bad mode is a 400
    rep3, _ = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "pd2")
    from timetabling_ga_tpu.fleet.replicas import FleetHTTPError
    with pytest.raises(FleetHTTPError):
        http_json("POST", rep3.url + "/v1/drain?mode=bogus", {},
                  ok=(200,))
    rep3.kill()


# ----------------------------------------------------- gateway caching


def test_gateway_snapshot_cache_eviction_and_replay_fallback():
    """Under a tiny --snapshot-hwm every cached snapshot evicts
    (oldest-progress-first, counted) and a subsequent kill falls back
    to the REPLAY failover — still completing with an identical
    stream, just from gen 0 (`fleet.resume.replays`)."""
    jobs = [("e0", _PA, 3, 60)]
    rep0, h0 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "e0r")
    rep1, h1 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "e1r")
    gw = Gateway(_fleet_cfg([h0.url, h1.url], snapshot_hwm=1),
                 [h0, h1]).start()
    try:
        for jid, p, seed, gens in jobs:
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": seed,
                       "generations": gens})
        deadline = time.monotonic() + 90
        killed = None
        reps = {"e0r": rep0, "e1r": rep1}
        while time.monotonic() < deadline:
            if gw.registry.counter("fleet.resume.evictions").value \
                    >= 1:
                with gw.jobs_lock:
                    j = gw.jobs.get("e0")
                    owner, snap = j.replica, j.snap
                assert snap is None       # evicted, nothing cached
                if owner in reps:
                    killed = owner
                    reps[owner].kill()
                    break
            time.sleep(0.02)
        assert killed, "no eviction observed"
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            v = http_json("GET", gw.url + "/v1/jobs/e0", ok=(200,))
            if v["state"] in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        assert gw.registry.counter("fleet.resume.replays").value >= 1
        assert gw.registry.counter("fleet.resume.hits").value == 0
        assert jsonl.strip_timing(v["records"]) \
            == _baseline(jobs)["e0"]
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


def test_remote_rejection_demotes_without_duplicates():
    """A survivor whose serve config cannot validate the attached
    snapshot (different pop size → foreign fingerprint) replays from
    gen 0 — the gateway detects the fresh stream by its `admitted`
    jobEntry, DROPS the now-redundant prefix (fleet.resume.demoted),
    and the settled stream stays duplicate-free."""
    rep0, h0 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "m0")
    rep1, h1 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", pop_size=8), "m1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    try:
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(_PA), "id": "mx", "seed": 3,
                   "generations": 1200})
        # wait until the job runs on m0 with a cached snapshot
        deadline = time.monotonic() + 90
        ok = False
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                j = gw.jobs.get("mx")
                ok = j.replica == "m0" and j.snap_gens >= 10
            if ok:
                break
            time.sleep(0.01)
        if not ok:
            pytest.skip("job landed on the mismatched replica first")
        rep0.kill()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            v = http_json("GET", gw.url + "/v1/jobs/mx", ok=(200,))
            if v["state"] in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        assert v["result"]["resumed_at"] == 0       # replayed
        assert gw.registry.counter("fleet.resume.demoted").value >= 1
        events = [r["jobEntry"]["event"] for r in v["records"]
                  if "jobEntry" in r]
        assert events.count("admitted") == 1, events
        assert events.count("started") == 1, events
        assert events.count("done") == 1, events
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


# ------------------------------------------------- fleet acceptance e2e


def test_fleet_acceptance_kill_resumes_not_replays():
    """ISSUE 12 acceptance: gateway + 2 replicas, kill one observed
    mid-job. The job completes on the survivor having re-run AT MOST
    one quantum's generations (never from gen 0), its stream is
    duplicate-free and identical to an uninterrupted solve modulo
    timing/fault records, and fleet.resume.hits >= 1 on /metrics."""
    jobs = [("ra", _PA, 3, 2000), ("rb", _PB, 4, 40)]
    rep0, h0 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "a0")
    rep1, h1 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "a1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    reps = {"a0": rep0, "a1": rep1}
    try:
        for jid, p, seed, gens in jobs:
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": seed,
                       "generations": gens})
        # kill ra's owner at a moment the gateway's cached snapshot is
        # in sync with the replica's progress (within one quantum), so
        # the re-run bound is deterministic
        deadline = time.monotonic() + 120
        killed = None
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                j = gw.jobs.get("ra")
                owner, snap_gens = j.replica, j.snap_gens
            if owner in reps and snap_gens >= 10:
                try:
                    gens_now = reps[owner].svc.queue.get(
                        "ra").gens_done
                except KeyError:
                    gens_now = None
                if gens_now is not None and snap_gens \
                        >= gens_now - 5:
                    killed = owner
                    reps[owner].kill()
                    break
            time.sleep(0.005)
        assert killed, "never reached a synced kill point"

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            views = {jid: http_json(
                "GET", f"{gw.url}/v1/jobs/{jid}", ok=(200,))
                for jid, *_ in jobs}
            if all(v["state"] in ("done", "failed")
                   for v in views.values()):
                break
            time.sleep(0.1)
        assert all(v["state"] == "done" for v in views.values()), \
            {j: v["state"] for j, v in views.items()}

        # resumed, not replayed: the survivor restarted from the
        # shipped snapshot, re-running at most the one quantum that
        # was in flight at the kill
        res = views["ra"]["result"]
        assert res["resumed_at"] > 0
        dead_gens = reps[killed].svc.queue.get("ra").gens_done
        assert dead_gens - res["resumed_at"] <= 5       # one quantum
        assert gw.registry.counter("fleet.resume.hits").value >= 1
        metrics = http_text(gw.url + "/metrics")
        assert "tt_fleet_resume_hits_total 1" in metrics

        # duplicate-free + identical to the unrouted baseline
        base = _baseline(jobs)
        for jid, v in views.items():
            events = [r["jobEntry"]["event"] for r in v["records"]
                      if "jobEntry" in r]
            assert events.count("done") == 1, (jid, events)
            assert sum(1 for r in v["records"] if "solution" in r) \
                == 1, jid
            assert jsonl.strip_timing(v["records"]) == base[jid], jid
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


def test_gateway_preempt_scale_down_lossless():
    """Targeted POST /v1/drain?mode=preempt&replica=NAME: the
    preempted replica ships + drains, its job resumes on the survivor
    from the preempt fence (zero re-run), and the settled stream is
    identical to an unrouted solve."""
    jobs = [("px", _PA, 3, 1500)]
    rep0, h0 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", preempt_grace=30.0), "s0")
    rep1, h1 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", preempt_grace=30.0), "s1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    reps = {"s0": rep0, "s1": rep1}
    try:
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(_PA), "id": "px", "seed": 3,
                   "generations": 1500})
        deadline = time.monotonic() + 90
        owner = None
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                j = gw.jobs.get("px")
                owner, snap_gens = j.replica, j.snap_gens
            if owner in reps and snap_gens >= 10:
                break
            time.sleep(0.01)
        assert owner in reps
        ack = http_json(
            "POST",
            f"{gw.url}/v1/drain?mode=preempt&replica={owner}", {},
            ok=(202,))
        assert ack == {"preempting": owner}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            v = http_json("GET", gw.url + "/v1/jobs/px", ok=(200,))
            if v["state"] in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        assert v["replica"] != owner            # moved, not restarted
        assert reps[owner].drained.wait(30)     # replica exited clean
        assert gw.registry.counter("fleet.resume.hits").value >= 1
        # LOSSLESS: resumed exactly at the preempt fence — the dead
        # incarnation's committed progress equals the resume point
        assert v["result"]["resumed_at"] \
            == reps[owner].svc.queue.get("px").gens_done
        assert jsonl.strip_timing(v["records"]) \
            == _baseline(jobs)["px"]
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


# ----------------------------------------------------- flags & plumbing


def test_new_flags_parse_and_validate():
    cfg = parse_fleet_args(["--replica", "http://a:1",
                            "--snapshot-hwm", "1024"])
    assert cfg.snapshot_hwm == 1024
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "u", "--snapshot-hwm", "-1"])
    scfg = parse_serve_args(["--max-job-recoveries", "3",
                             "--preempt-grace", "2.5",
                             "--preempt-on-term"])
    assert scfg.max_job_recoveries == 3
    assert scfg.preempt_grace == 2.5
    assert scfg.preempt_on_term is True
    with pytest.raises(SystemExit):
        parse_serve_args(["--max-job-recoveries", "-1"])
    with pytest.raises(SystemExit):
        parse_serve_args(["--preempt-grace", "-1"])
    # the new fault sites are part of the closed, validated set
    plan = faults.FaultPlan.parse(
        "quantum:1:unavailable,snapshot_ship:2:hang,resume:1:die")
    assert plan is not None
    with pytest.raises(faults.FaultPlanError):
        faults.FaultPlan.parse("quantums:1:die")


def test_tt_stats_recovered_component(tmp_path, capsys):
    """A resumed job's serve log (obs on) yields a `recovered`
    latency component in the tt stats breakdown."""
    from timetabling_ga_tpu.obs.logstats import main_stats

    buf1 = io.StringIO()
    svc1 = SolveService(_serve_cfg(obs=True), out=buf1)
    svc1.submit(_PA, job_id="t", seed=3, generations=20)
    svc1.step()
    wire = svc1.queue.get("t").ship.pack()
    svc1.close()

    log = tmp_path / "resumed.jsonl"
    with open(log, "w") as fh:
        svc2 = SolveService(_serve_cfg(obs=True), out=fh)
        svc2.submit(_PA, job_id="t", seed=3, generations=20,
                    snapshot=wire)
        svc2.drive()
        svc2.close()
    assert main_stats([str(log)]) == 0
    out = capsys.readouterr().out
    assert "recovered" in out
    assert "job latency breakdown" in out
