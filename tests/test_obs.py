"""tt-obs tests (timetabling_ga_tpu/obs + --trace-mode).

Four layers:

  unit        metrics registry (counter/gauge/histogram/Prometheus),
              SpanTracer, the spanEntry/metricsEntry record emitters,
              strip_timing over the new record types, and the on-device
              trace compression vs a host recomputation
  engine A/B  --trace-mode full|deltas|stats x pipeline x --obs must
              emit IDENTICAL protocol records modulo timing (the
              acceptance criterion: telemetry reduction changes WHAT is
              fetched, never what is emitted) — including through a
              checkpointed pipelined run and a fault recovery
  serve A/B   the same contract for the lane scheduler, plus the
              `stats` line-JSON command and Prometheus exposition
  CLI         `tt trace` emits well-formed Chrome trace-event JSON;
              `tt stats` summarizes a log without jq
"""

import io
import json
import math
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs.logstats import summarize
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.obs.spans import NULL_TRACER, SpanTracer
from timetabling_ga_tpu.obs.trace_export import (
    export_chrome_trace, read_jsonl)
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig, ServeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM = os.path.join(REPO, "fixtures", "comp01s.tim")


# ---------------------------------------------------------------- metrics


def test_counter_monotone_and_negative_inc_raises():
    reg = MetricsRegistry()
    c = reg.counter("x.count")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_pull_and_degrade():
    reg = MetricsRegistry()
    g = reg.gauge("x.level")
    g.set(4)
    assert g.value == 4.0
    pull = reg.gauge_fn("x.depth", lambda: 7)
    assert pull.value == 7.0
    # a dead pull source degrades to nan (JSON null), never raises
    reg.gauge_fn("x.depth", lambda: 1 / 0)
    assert math.isnan(reg.gauge("x.depth").value)
    snap = reg.snapshot()
    assert snap["gauges"]["x.depth"] is None
    assert snap["gauges"]["x.level"] == 4.0


def test_histogram_percentiles_and_summary():
    reg = MetricsRegistry()
    h = reg.histogram("x.lat")
    for v in [0.002, 0.004, 0.02, 0.02, 0.3, 2.0]:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 6
    assert s["min"] == 0.002 and s["max"] == 2.0
    assert 0.002 <= s["p50"] <= 0.3
    assert s["p95"] <= 2.0
    assert reg.histogram("x.lat") is h          # get-or-create


def test_registry_kind_collision_raises():
    reg = MetricsRegistry()
    reg.counter("a.b")
    with pytest.raises(TypeError):
        reg.gauge("a.b")


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("engine.gens").inc(5)
    reg.gauge("serve.queue_depth").set(3)
    reg.histogram("serve.job_seconds").observe(0.3)
    text = reg.to_prometheus()
    assert "# TYPE tt_engine_gens_total counter" in text
    assert "tt_engine_gens_total 5" in text
    assert "tt_serve_queue_depth 3" in text
    assert 'tt_serve_job_seconds_bucket{le="+Inf"} 1' in text
    assert "tt_serve_job_seconds_count 1" in text
    assert text.endswith("\n")


def test_registry_is_thread_safe():
    reg = MetricsRegistry()
    c = reg.counter("t.n")

    def hammer():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert c.value == 4000


# ------------------------------------------------------------------ spans


def test_span_tracer_nesting_and_record():
    buf = io.StringIO()
    tracer = SpanTracer(buf)
    with tracer.span("outer", cat="t"):
        with tracer.span("inner", cat="t", k=1):
            pass
    tracer.record("measured", tracer._clock() - 0.5, 0.25, cat="d")
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    spans = {r["spanEntry"]["name"]: r["spanEntry"] for r in recs}
    assert spans["inner"]["depth"] == 1 and spans["outer"]["depth"] == 0
    assert spans["inner"]["k"] == 1
    assert spans["measured"]["dur"] == 0.25
    # inner closes before outer -> emitted first
    assert [r["spanEntry"]["name"] for r in recs] == [
        "inner", "outer", "measured"]
    assert spans["outer"]["dur"] >= spans["inner"]["dur"]


def test_span_tracer_disabled_is_noop():
    with NULL_TRACER.span("x"):
        pass
    NULL_TRACER.record("y", 0.0, 1.0)   # no output target, no error


def test_span_error_is_marked_and_propagates():
    buf = io.StringIO()
    tracer = SpanTracer(buf)
    with pytest.raises(RuntimeError):
        with tracer.span("failing"):
            raise RuntimeError("boom")
    rec = json.loads(buf.getvalue())
    assert rec["spanEntry"]["error"] is True


# ------------------------------------------------- records + strip_timing


def test_strip_timing_drops_obs_records():
    buf = io.StringIO()
    jsonl.log_entry(buf, 0, 0, 42, 1.5)
    jsonl.span_entry(buf, "dispatch", "device", 1.0, 0.5, gens=10)
    jsonl.metrics_entry(buf, {"counters": {"engine.gens": 10}}, ts=2.0)
    jsonl.phase_record(buf, "init", 0, 0.1)
    jsonl.fault_entry(buf, "dispatch", "recover", ValueError("x"), 0, 1,
                      0, 1.0)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert {next(iter(r)) for r in recs} == {
        "logEntry", "spanEntry", "metricsEntry", "phase", "faultEntry"}
    stripped = jsonl.strip_timing(recs)
    assert len(stripped) == 1
    assert "logEntry" in stripped[0]
    assert "time" not in stripped[0]["logEntry"]


def test_span_and_metrics_records_are_well_formed():
    buf = io.StringIO()
    jsonl.span_entry(buf, "quantum", "serve", 1.23456789, 0.001, depth=2,
                     tid=1, job="j1")
    jsonl.metrics_entry(buf, {"gauges": {"g": 1.0}})
    span, metrics = [json.loads(x) for x in buf.getvalue().splitlines()]
    s = span["spanEntry"]
    assert (s["name"], s["cat"], s["depth"], s["tid"], s["job"]) == (
        "quantum", "serve", 2, 1, "j1")
    assert s["ts"] == 1.234568            # 6-digit rounding
    assert "ts" not in metrics["metricsEntry"]   # optional


# ------------------------------------------------- trace compression unit


def _host_improvements(tr, n):
    SENT = 2 ** 31 - 1
    best, out = (SENT, SENT), []
    for g in range(n):
        h, s = int(tr[g, 0]), int(tr[g, 1])
        if (h, s) < best:
            best = (h, s)
            out.append((g, h, s))
    return out


def test_compress_trace_matches_host_recomputation():
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    rng = np.random.default_rng(7)
    tr = rng.integers(0, 6, size=(4, 12, 2)).astype(np.int32)
    for mode in ("deltas", "stats"):
        packed = np.asarray(islands._compress_trace(
            jnp.asarray(tr), None, mode))
        assert packed.shape == (4, islands.trace_leaf_width(12, mode))
        events, counts, moments = islands.trace_events(packed, mode)
        for i in range(4):
            want = _host_improvements(tr[i], 12)
            assert events[i] == want
            assert counts[i] == len(want)
        assert (moments is not None) == (mode == "stats")


def test_compress_trace_per_lane_valid_counts():
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    rng = np.random.default_rng(8)
    tr = rng.integers(0, 6, size=(3, 10, 2)).astype(np.int32)
    nv = np.array([4, 10, 0], np.int32)
    packed = np.asarray(islands._compress_trace(
        jnp.asarray(tr), jnp.asarray(nv), "deltas"))
    events, counts, _ = islands.trace_events(packed, "deltas")
    for i in range(3):
        assert events[i] == _host_improvements(tr[i], int(nv[i]))
    assert events[2] == [] and counts[2] == 0


def test_compress_trace_overflow_is_counted(monkeypatch):
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    monkeypatch.setattr(islands, "TRACE_DELTAS_CAP", 3)
    # strictly decreasing -> every generation improves (8 events, cap 3)
    tr = np.stack([np.arange(9, 1, -1), np.zeros(8)],
                  axis=1)[None].astype(np.int32)
    packed = np.asarray(islands._compress_trace(
        jnp.asarray(tr), None, "deltas"))
    events, counts, _ = islands.trace_events(packed, "deltas")
    assert len(events[0]) == 3           # last K kept, earliest dropped
    assert counts[0] == 8                # the count exposes the drop
    # the LAST improvements survive: the dispatch's final best (what
    # best_seen and the post-feasibility switch read) is never lost
    assert events[0] == _host_improvements(tr[0], 8)[-3:]


def test_full_trace_decode_matches_layouts():
    from timetabling_ga_tpu.parallel import islands
    tr = np.arange(2 * 1 * 3 * 2).reshape(2, 1, 3, 2).astype(np.int32)
    events, counts, moments = islands.trace_events(tr, "full")
    assert counts is None and moments is None
    assert events[0] == [(0, 0, 1), (1, 2, 3), (2, 4, 5)]


def test_polish_runner_with_passes_is_trajectory_pure():
    """The with_passes polish program (--trace-mode stats) must return
    a bit-identical population and (penalty, hcv, scv) block — the
    pass-count row and the bitcast moment rows (the tail-polish
    endgame's streamed-moment telemetry) are the ONLY difference. Pins
    the invariant the engine-level stats A/B relies on, without the
    engine's timing-sensitive dispatch scheduling in the loop."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.problem import load_tim_file
    pa = load_tim_file(TIM).device_arrays()
    mesh = islands.make_mesh(2)
    cfg = ga.GAConfig(pop_size=8, ls_mode="sweep", ls_sweeps=1,
                      ls_hot_k=4, ls_swap_block=4, init_sweeps=2)
    state = islands.init_island_population(pa, jax.random.key(7), mesh, 8)
    outs = {}
    for wp in (False, True):
        pol = islands.make_polish_runner(mesh, cfg, n_islands=2,
                                         with_passes=wp)
        st, stats = pol(pa, jax.random.key(5), state, 2)
        outs[wp] = (jax.device_get(st), np.asarray(stats))
    st0, s0 = outs[False]
    st1, s1 = outs[True]
    assert s0.shape[0] == 3
    assert s1.shape[0] == 4 + islands.TRACE_N_MOMENTS
    assert np.array_equal(s0, s1[:3])
    assert (s1[3] >= 1).all()            # executed >= 1 converge pass
    # rows 4..: bitcast float32 mean/var/min/max of reported values
    mom = np.ascontiguousarray(
        s1[4:4 + islands.TRACE_N_MOMENTS]).view(np.float32)
    mean, var, mn, mx = (mom[i, 0] for i in range(4))
    assert mn <= mean <= mx and var >= 0.0
    for a, b in zip(st0, st1):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_lahc_runner_with_moments_is_trajectory_pure():
    """The with_moments LAHC run program (--trace-mode stats on the
    endgame) must walk the IDENTICAL trajectory: lahc_state and the
    (penalty, hcv, scv) stats block are bit-equal with and without the
    moment rows — which decode to sane walker-ensemble float32
    mean/var/min/max per island. This is what makes the engine's
    across-mode stream identity hold through the LAHC endgame."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.problem import load_tim_file
    pa = load_tim_file(TIM).device_arrays()
    mesh = islands.make_mesh(2)
    cfg = ga.GAConfig(pop_size=4, ls_mode="sweep", ls_sweeps=1,
                      ls_hot_k=4, ls_swap_block=4)
    state = islands.init_island_population(pa, jax.random.key(3), mesh, 4)
    outs = {}
    for wm in (False, True):
        init_r, run_r, fin_r = islands.make_lahc_runners(
            mesh, cfg, hist_len=8, k_cands=2, n_islands=2,
            with_moments=wm)
        lstate = init_r(pa, state)
        lstate, stats = run_r(pa, jax.random.key(9), lstate, 5)
        outs[wm] = (jax.device_get(lstate), np.asarray(stats))
    ls0, s0 = outs[False]
    ls1, s1 = outs[True]
    assert s0.shape[0] == 3
    assert s1.shape[0] == 3 + islands.TRACE_N_MOMENTS
    assert np.array_equal(s0, s1[:3])
    mom = np.ascontiguousarray(
        s1[3:3 + islands.TRACE_N_MOMENTS]).view(np.float32)
    for isl in range(mom.shape[1]):
        mean, var, mn, mx = mom[:, isl]
        assert mn <= mean <= mx and var >= 0.0
    for a, b in zip(jax.tree_util.tree_leaves(ls0),
                    jax.tree_util.tree_leaves(ls1)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------- engine A/Bs


def _engine_run(trace_mode="full", obs=False, pipeline=True,
                checkpoint=None, faults=None, **kw):
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                generations=30, migration_period=10, max_steps=8,
                time_limit=300, backend="cpu", auto_tune=False,
                trace=True, pipeline=pipeline, obs=obs,
                trace_mode=trace_mode, metrics_every=1,
                checkpoint=checkpoint, faults=faults)
    base.update(kw)
    cfg = RunConfig(**base)
    best = eng.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


@pytest.fixture(scope="module")
def engine_baseline(engine_stream_baseline):
    """full-trace, obs-off, pipelined reference stream — the session-
    shared baseline (conftest.engine_stream_baseline), identical to
    what `_engine_run()` would produce here; sharing it across the
    obs/cost/quality modules keeps tier-1 inside its budget."""
    return engine_stream_baseline


def test_trace_mode_stream_identical_under_pipeline(engine_baseline):
    """THE acceptance criterion: deltas and stats ship a reduced
    telemetry leaf but the emitted record stream is identical to full
    modulo timing, with obs enabled end-to-end under the pipelined
    engine."""
    b0, l0 = engine_baseline
    for mode in ("deltas", "stats"):
        b, l = _engine_run(trace_mode=mode, obs=True)
        assert b == b0, mode
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), mode
        assert any("spanEntry" in r for r in l)
        assert any("metricsEntry" in r for r in l)


def test_trace_mode_stream_identical_serial(engine_baseline):
    b0, l0 = engine_baseline
    b, l = _engine_run(trace_mode="deltas", obs=True, pipeline=False)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)


def test_obs_off_emits_no_obs_records(engine_baseline):
    _, l0 = engine_baseline
    assert not any("spanEntry" in r or "metricsEntry" in r for r in l0)


def test_obs_span_taxonomy_and_metrics_content(engine_baseline):
    b0, l0 = engine_baseline
    before = dict(obs_metrics.REGISTRY.snapshot().get("counters", {}))
    b, l = _engine_run(trace_mode="stats", obs=True)
    assert b == b0
    names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
    assert {"init", "dispatch", "fetch", "process"} <= names, names
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert snaps
    last = snaps[-1]
    c = last["counters"]
    assert (c["engine.dispatches"]
            - before.get("engine.dispatches", 0)) >= 3
    assert "engine.gens" in c
    assert "writer.queue_depth" in last["gauges"]
    # stats mode streams on-device moments into gauges
    assert "engine.trace_best_min" in last["gauges"]
    assert "engine.dispatch_seconds" in last["histograms"]


def test_trace_mode_with_checkpoint_and_resume(tmp_path, engine_baseline):
    """The checkpoint's in-flight trace fold decodes the compressed
    leaf: a pipelined checkpointed deltas run emits the same stream and
    lands a loadable checkpoint."""
    b0, l0 = engine_baseline
    ck = str(tmp_path / "obs.ck.npz")
    b, l = _engine_run(trace_mode="deltas", obs=True, checkpoint=ck)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    assert os.path.exists(ck)
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 30


def test_trace_mode_fault_recovery_stream_identical(engine_baseline):
    """A recovered deltas-mode run matches the uninjected full-mode
    stream modulo timing+fault records — the recovery paths (poisoned
    buffer teardown, snapshot rehydrate, emitted-floor replay) all
    decode the compressed leaf."""
    b0, l0 = engine_baseline
    b, l = _engine_run(trace_mode="deltas", obs=True,
                       faults="dispatch:2:unavailable")
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    assert any("faultEntry" in r for r in l)
    names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
    assert "recover" in names


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): the heaviest test of the suite
# (~27 s on the dev box, ~2x that on the 2-core box) whose load-bearing
# half — with_passes trajectory purity — is already pinned by the
# direct runner A/B above; the engine-level double-precompile A/B is
# belt-and-suspenders the full tier still runs
def test_polish_pass_counts_ride_stats_mode(monkeypatch):
    """--trace-mode stats adds the sweep-pass-count row to the polish
    stats fetch (islands.make_polish_runner with_passes); the stream
    stays identical to full mode and the gauge is populated.

    The A/B needs BOTH runs to see the same dispatch/polish schedule
    (the schedule feeds fold_in offsets, so it IS the trajectory):
    precompile both configs first — engine.run alone does not, so the
    first run would enter the init polish with a cold _SPS_CACHE and
    chunk it 1+1 while the warm second run chunks it 2 (exactly how
    bench.measure_obs pre-warms its A/B) — pin DISPATCH_CAP_S out of
    range (sweep generations cost ~seconds on CPU, close enough to
    the watchdog boundary for timing noise to flip static dispatches
    into timing-SIZED dynamic ones), and keep the sweep cheap via
    ls_hot_k (the trajectory-purity of with_passes itself is pinned
    by the direct runner A/B above)."""
    from timetabling_ga_tpu.runtime import engine as eng
    monkeypatch.setattr(eng, "DISPATCH_CAP_S", 1e9)
    kw = dict(ls_mode="sweep", ls_sweeps=1, init_sweeps=2,
              ls_hot_k=4, ls_swap_block=4, generations=20)
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                migration_period=10, max_steps=8, time_limit=300,
                backend="cpu", auto_tune=False, trace=True,
                metrics_every=1, **kw)
    eng.precompile(RunConfig(**base))
    eng.precompile(RunConfig(trace_mode="stats", **base))
    b0, l0 = _engine_run(**kw)
    b, l = _engine_run(trace_mode="stats", obs=True, **kw)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert snaps and snaps[-1]["gauges"].get("engine.polish_passes", 0) >= 1


def test_run_counters_backcompat_dict():
    from timetabling_ga_tpu.runtime import engine as eng
    c = eng.run_counters()
    assert set(c) == {"recoveries", "faults_injected"}
    assert isinstance(c["recoveries"], int)
    assert c["recoveries"] == int(
        obs_metrics.REGISTRY.counter("engine.recoveries").value)


# ------------------------------------------------------------ serve A/Bs


def _serve_run(trace_mode="full", obs=False, requests=None):
    from timetabling_ga_tpu.serve.service import serve_stream
    cfg = ServeConfig(backend="cpu", lanes=2, quantum=10, pop_size=8,
                      generations=20, obs=obs, trace_mode=trace_mode,
                      metrics_every=1)
    reqs = requests or [
        {"submit": {"id": "a", "instance": TIM, "seed": 1}},
        {"submit": {"id": "b", "instance": TIM, "seed": 2}},
    ]
    inp = io.StringIO("\n".join(json.dumps(r) for r in reqs) + "\n")
    out = io.StringIO()
    svc = serve_stream(cfg, inp, out)
    return svc, [json.loads(x) for x in out.getvalue().splitlines()]


@pytest.fixture(scope="module")
def serve_baseline():
    return _serve_run()


def test_serve_trace_modes_stream_identical(serve_baseline):
    _, l0 = serve_baseline
    for mode in ("deltas", "stats"):
        svc, l = _serve_run(trace_mode=mode, obs=True)
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), mode
        names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
        assert {"admit", "pack", "quantum", "park", "resume",
                "init"} <= names, names


def test_serve_stats_command_and_prometheus(serve_baseline):
    _, l0 = serve_baseline
    reqs = [
        {"submit": {"id": "a", "instance": TIM, "seed": 1}},
        {"submit": {"id": "b", "instance": TIM, "seed": 2}},
        {"drain": True},
        {"stats": True},
        {"stats": "prometheus"},
    ]
    svc, l = _serve_run(obs=True, requests=reqs)
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert len(snaps) >= 2
    plain, prom = snaps[-2], snaps[-1]
    assert "prometheus" not in plain
    assert "tt_serve_dispatches_total" in prom["prometheus"]
    assert "tt_serve_job_seconds_bucket" in prom["prometheus"]
    assert prom["counters"]["serve.jobs_done"] >= 2
    # the protocol records are unaffected by the stats traffic
    assert jsonl.strip_timing(l) == jsonl.strip_timing(
        serve_baseline[1])
    # live Python API mirrors the stream
    assert "serve.job_seconds" in svc.stats().get("histograms", {})


# -------------------------------------------------------------------- CLI


@pytest.fixture(scope="module")
def obs_log(tmp_path_factory):
    """One obs-enabled engine run's JSONL log on disk."""
    _, recs = _engine_run(trace_mode="stats", obs=True)
    p = tmp_path_factory.mktemp("obs") / "run.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_tt_trace_emits_wellformed_chrome_trace(obs_log, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu", "trace", obs_log,
         "-o", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    with open(out) as fh:
        doc = json.load(fh)           # well-formed JSON or this raises
    events = doc["traceEvents"]
    assert events, "no trace events exported"
    for ev in events:
        assert ev["ph"] in ("X", "C", "s", "t", "f")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert "name" in ev and "pid" in ev and "tid" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        if ev["ph"] in ("s", "t", "f"):
            assert ev["id"] > 0       # flow chains carry their id
    phs = {ev["ph"] for ev in events}
    # spans+phases, counter tracks, AND flow arrows (each dispatch
    # chunk's dispatch->fetch->process chain carries a flow id)
    assert phs == {"X", "C", "s", "t", "f"}, phs
    names = {ev["name"] for ev in events if ev["ph"] == "X"}
    assert "dispatch" in names
    # every flow chain is well-formed: one s, one f, >= 0 t's
    chains = {}
    for ev in events:
        if ev["ph"] in ("s", "t", "f"):
            chains.setdefault(ev["id"], []).append(ev["ph"])
    assert chains
    for fid, phs_ in chains.items():
        assert phs_.count("s") == 1 and phs_.count("f") == 1, (fid, phs_)


def test_export_tolerates_torn_tail_line(tmp_path):
    p = tmp_path / "torn.jsonl"
    p.write_text('{"spanEntry": {"name": "a", "cat": "t", "ts": 0.0, '
                 '"dur": 1.0, "depth": 0, "tid": 0}}\n{"spanEnt')
    recs = read_jsonl(str(p))
    assert len(recs) == 1
    assert export_chrome_trace(recs)["traceEvents"]


def test_tt_stats_summarizes_log(obs_log):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu", "stats", obs_log],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    assert "best-so-far" in r.stdout
    assert "last metrics snapshot" in r.stdout
    assert "faults: none" in r.stdout


def test_stats_summarize_jobs_and_faults():
    recs = [
        {"logEntry": {"procID": 0, "best": 9, "time": 0.5, "job": "j1"}},
        {"logEntry": {"procID": 0, "best": 2, "time": 1.0, "job": "j1"}},
        {"solution": {"procID": 0, "totalBest": 2, "feasible": True,
                      "totalTime": 1.5, "job": "j1"}},
        {"jobEntry": {"job": "j1", "event": "admitted"}},
        {"jobEntry": {"job": "j1", "event": "done", "best": 2,
                      "gens": 20}},
        {"faultEntry": {"site": "dispatch", "action": "recover",
                        "level": 1, "error": "UNAVAILABLE"}},
    ]
    text = summarize(recs)
    assert "job j1" in text
    assert "dispatch/recover" in text
    assert "latency p50" in text


def test_tt_trace_and_stats_work_without_jax(obs_log, tmp_path):
    """The offline obs surfaces must run on a machine with no
    accelerator stack: the package __init__ is PEP 562-lazy and cli.py
    defers every runtime import past the trace/stats dispatch, so a
    blocked `import jax` never fires."""
    out = str(tmp_path / "trace.json")
    blocker = (
        "import sys\n"
        "class _NoJax:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('BLOCKED import of jax')\n"
        "sys.meta_path.insert(0, _NoJax())\n"
        "from timetabling_ga_tpu.cli import main\n"
        "assert main(['trace', %r, '-o', %r]) == 0\n"
        "assert main(['stats', %r]) == 0\n" % (obs_log, out, obs_log))
    r = subprocess.run([sys.executable, "-c", blocker],
                       capture_output=True, text=True, cwd=REPO,
                       timeout=240)
    assert r.returncode == 0, r.stderr
    with open(out) as fh:
        assert json.load(fh)["traceEvents"]


def test_gauge_bind_none_freezes_and_releases():
    reg = MetricsRegistry()
    g = reg.gauge_fn("w.depth", lambda: 5)
    assert g.value == 5.0
    g.set(2.0)
    g.bind(None)                      # unbind: engine.run's finally
    assert g.value == 2.0             # frozen at the set() value


def test_engine_run_unbinds_writer_gauges(engine_baseline):
    """engine.run must not leave the process-global registry holding a
    closure over the finished run's writer (and its output stream)."""
    for name in ("writer.records", "writer.queue_depth"):
        assert obs_metrics.REGISTRY.gauge(name)._fn is None, name


# ------------------------------------------------ exemplars + OpenMetrics


def test_openmetrics_exemplars_and_eof():
    """`observe(v, exemplar=...)` remembers the last exemplar per
    bucket; to_openmetrics renders it OpenMetrics-style and ends with
    `# EOF`; the 0.0.4 exposition ignores exemplars entirely."""
    reg = MetricsRegistry()
    h = reg.histogram("serve.job_seconds")
    h.observe(0.3, exemplar={"job": "j1"})
    h.observe(0.4, exemplar={"job": "j2"})      # same bucket: last wins
    h.observe(40.0, exemplar={"job": 'sl"ow'})  # quote needs escaping
    h.observe(0.02)                             # no exemplar: bucket bare
    reg.counter("serve.jobs_done").inc(3)
    reg.gauge("serve.queue_depth").set(1)
    text = reg.to_openmetrics()
    assert text.endswith("# EOF\n")
    assert "# TYPE tt_serve_jobs_done counter" in text
    assert "tt_serve_jobs_done_total 3" in text
    assert 'le="0.5"} 3 # {job="j2"} 0.4' in text
    assert '# {job="sl\\"ow"} 40' in text
    assert '{job="j1"}' not in text             # overwritten in-bucket
    prom = reg.to_prometheus()
    assert "# {" not in prom and "# EOF" not in prom


def test_histogram_exemplar_ignores_empty():
    reg = MetricsRegistry()
    h = reg.histogram("x")
    h.observe(0.1, exemplar=None)
    h.observe(0.1, exemplar={})
    assert all(e is None for e in h._exemplars)


# ----------------------------------------------------- pull front (http)


def _http_get(url, timeout=5.0):
    """(status, body, content_type) — 4xx/5xx are answers, not errors."""
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode(), r.headers.get(
                "Content-Type", "")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), e.headers.get(
            "Content-Type", "")


def test_parse_listen_specs():
    from timetabling_ga_tpu.obs.http import parse_listen
    assert parse_listen("127.0.0.1:9090") == ("127.0.0.1", 9090)
    assert parse_listen("localhost:0") == ("localhost", 0)
    for bad in ("nohost", ":9090", "h:not_a_port", "h:70000"):
        with pytest.raises(ValueError):
            parse_listen(bad)


def test_run_config_rejects_bad_obs_listen():
    from timetabling_ga_tpu.runtime.config import (
        parse_args, parse_serve_args)
    with pytest.raises(SystemExit):
        parse_args(["-i", TIM, "--obs-listen", "nope"])
    with pytest.raises(SystemExit):
        parse_serve_args(["--obs-listen", "host:port"])


def test_obs_server_endpoints():
    """/metrics serves OpenMetrics (with exemplars) from the given
    registry; /healthz reflects the probes; /readyz derives from
    registry state alone; unknown routes 404. The handlers never write
    a record anywhere — there is no stream to write to."""
    from timetabling_ga_tpu.obs.http import ObsServer
    reg = MetricsRegistry()
    reg.histogram("serve.job_seconds").observe(
        0.3, exemplar={"job": "jX"})
    probe_ok = [True]
    srv = ObsServer("127.0.0.1:0", registry=reg,
                    probes={"writer": lambda: probe_ok[0]}).start()
    try:
        st, body, ctype = _http_get(srv.url + "/metrics")
        assert st == 200
        assert ctype.startswith("application/openmetrics-text")
        assert "tt_serve_job_seconds_bucket" in body
        assert '# {job="jX"} 0.3' in body
        assert body.endswith("# EOF\n")

        st, body, _ = _http_get(srv.url + "/healthz")
        assert st == 200 and json.loads(body)["ok"] is True
        probe_ok[0] = False
        st, body, _ = _http_get(srv.url + "/healthz")
        assert st == 503
        assert json.loads(body)["probes"]["writer"] is False

        # ready: no gauges set at all -> no NOT-READY condition
        st, body, _ = _http_get(srv.url + "/readyz")
        assert st == 200 and json.loads(body)["ready"] is True
        # backlog full flips it
        reg.gauge("serve.backlog").set(4)
        reg.gauge("serve.queue_depth").set(4)
        st, body, _ = _http_get(srv.url + "/readyz")
        assert st == 503
        assert "backlog_full" in json.loads(body)["reasons"]
        reg.gauge("serve.queue_depth").set(1)
        # degradation ladder level >= 2 flips it
        reg.gauge("engine.degrade_level").set(2)
        st, body, _ = _http_get(srv.url + "/readyz")
        assert st == 503
        assert "degraded" in json.loads(body)["reasons"]
        reg.gauge("engine.degrade_level").set(0)
        # exhausted recovery budget flips it (only when configured)
        reg.gauge("engine.recovery_budget_configured").set(3)
        reg.gauge("engine.recovery_budget_remaining").set(0)
        st, body, _ = _http_get(srv.url + "/readyz")
        assert st == 503
        assert "recovery_exhausted" in json.loads(body)["reasons"]
        reg.gauge("engine.recovery_budget_remaining").set(3)
        st, _, _ = _http_get(srv.url + "/readyz")
        assert st == 200

        st, _, _ = _http_get(srv.url + "/nope")
        assert st == 404
    finally:
        srv.close()
    assert not srv.alive()


def test_scrape_faults_stay_on_their_request():
    """The `scrape` fault site (runtime/faults.py): an injected error
    or death aborts ITS request only — the next scrape (a fresh
    connection, a fresh daemon handler thread) succeeds, and close()
    returns promptly either way."""
    from timetabling_ga_tpu.obs.http import ObsServer
    from timetabling_ga_tpu.runtime import faults
    reg = MetricsRegistry()
    reg.counter("x").inc()
    for action in ("error", "die"):
        faults.install(f"scrape:1:{action}")
        srv = ObsServer("127.0.0.1:0", registry=reg).start()
        try:
            with pytest.raises(Exception):
                # the injected failure kills the request mid-flight
                _http_get(srv.url + "/metrics", timeout=5.0)
            st, body, _ = _http_get(srv.url + "/metrics")
            assert st == 200 and "tt_x_total 1" in body
        finally:
            srv.close()
            faults.install(None)


def test_scrape_hang_parks_only_its_thread():
    """A hung handler (scrape hang sleeps for TT_FAULT_HANG_S) parks
    ONE daemon thread: concurrent scrapes on new connections still
    answer, and close() does not wait for the sleeper."""
    import time as _time
    from timetabling_ga_tpu.obs.http import ObsServer
    from timetabling_ga_tpu.runtime import faults
    faults.install("scrape:1:hang")
    srv = ObsServer("127.0.0.1:0", registry=MetricsRegistry()).start()
    try:
        with pytest.raises(Exception):
            _http_get(srv.url + "/healthz", timeout=0.5)   # times out
        st, _, _ = _http_get(srv.url + "/healthz")
        assert st == 200
    finally:
        t0 = _time.monotonic()
        srv.close()
        assert _time.monotonic() - t0 < 5.0
        faults.install(None)


def test_obs_listen_die_kills_only_the_listener():
    """The `obs_listen` fault site: a death on the server thread at
    startup takes down the accept loop and NOTHING else — the owner
    (engine/serve) runs on; close() is safe."""
    from timetabling_ga_tpu.obs.http import ObsServer
    from timetabling_ga_tpu.runtime import faults
    faults.install("obs_listen:1:die")
    try:
        srv = ObsServer("127.0.0.1:0", registry=MetricsRegistry())
        srv.start()
        srv._thread.join(timeout=5.0)
        assert not srv.alive()
        srv.close()                     # no deadlock on the dead loop
    finally:
        faults.install(None)


# --------------------------------------------- serve + pull front, shed


def _serve_api_run(jobs=3, scrape=False, **cfg_kw):
    """Drive SolveService directly (step loop) so a scraper can hit the
    pull front BETWEEN dispatches — a live run, deterministically."""
    from timetabling_ga_tpu.problem import load_tim_file
    from timetabling_ga_tpu.serve.service import SolveService
    kw = dict(backend="cpu", lanes=2, quantum=10, pop_size=8,
              generations=20, obs=True, metrics_every=1)
    kw.update(cfg_kw)
    cfg = ServeConfig(**kw)
    out = io.StringIO()
    svc = SolveService(cfg, out=out)
    scrapes = []
    try:
        prob = load_tim_file(TIM)
        for i in range(jobs):
            svc.submit(prob, job_id=f"sj{i}", seed=i + 1,
                       priority=jobs - i)
        def _scrape(ep):
            try:
                scrapes.append(_http_get(svc.obs_server.url + ep,
                                         timeout=2.0))
            except Exception as e:       # injected hang/die: the
                scrapes.append(("failed", str(e), ""))   # run goes on
        while svc.step():
            if scrape and svc.obs_server is not None:
                _scrape("/metrics")
        if scrape and svc.obs_server is not None:
            _scrape("/metrics")
            _scrape("/readyz")
    finally:
        svc.close()
    return ([json.loads(x) for x in out.getvalue().splitlines()],
            scrapes, svc)


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): listener-on/off stream identity is
# still tier-1-covered on the engine path
# (test_engine_run_with_obs_listen_stream_identical) and the serve
# listener's endpoints/faults by the tests around this one
def test_serve_obs_listen_stream_identical_with_exemplars():
    """THE tentpole contract: a live serve run with the pull front on
    and a scraper hitting /metrics between every dispatch emits a
    record stream identical (modulo timing records) to a listener-off
    run — and the scrape text carries serve_job_seconds exemplars
    joining back to real job ids."""
    l_off, _, _ = _serve_api_run(scrape=False)
    l_on, scrapes, _ = _serve_api_run(scrape=True,
                                      obs_listen="127.0.0.1:0")
    assert jsonl.strip_timing(l_on) == jsonl.strip_timing(l_off)
    assert scrapes
    st, last, ctype = scrapes[-2]
    assert st == 200 and ctype.startswith("application/openmetrics")
    assert "tt_serve_job_seconds_bucket" in last
    assert '# {job="sj' in last          # exemplar -> jobEntry join
    assert last.endswith("# EOF\n")
    st, ready, _ = scrapes[-1]
    assert st in (200, 503)              # derived, never an error
    done = [r["jobEntry"]["job"] for r in l_on
            if "jobEntry" in r and r["jobEntry"]["event"] == "done"]
    assert len(done) == 3


def test_serve_shed_backpressure():
    """shed_queue_hwm: while queue depth sits at/over the mark the
    scheduler sheds the LOWEST-priority runnable work — jobEntry
    `shed` records, serve.jobs_shed counter, SHED terminal state —
    and the surviving job still completes."""
    from timetabling_ga_tpu.serve.queue import JobState
    before = obs_metrics.REGISTRY.counter("serve.jobs_shed").value
    recs, _, svc = _serve_api_run(jobs=3, shed_queue_hwm=2)
    shed = [r["jobEntry"] for r in recs
            if "jobEntry" in r and r["jobEntry"]["event"] == "shed"]
    done = [r["jobEntry"] for r in recs
            if "jobEntry" in r and r["jobEntry"]["event"] == "done"]
    # depth 3 >= 2 sheds sj2 (lowest priority), depth 2 >= 2 sheds
    # sj1, depth 1 < 2 -> sj0 (highest priority) runs to completion
    assert [s["job"] for s in shed] == ["sj2", "sj1"]
    assert all(s["reason"] == "queue_hwm" for s in shed)
    assert [d["job"] for d in done] == ["sj0"]
    assert svc.state("sj2") == JobState.SHED
    assert svc.result("sj2") is None
    after = obs_metrics.REGISTRY.counter("serve.jobs_shed").value
    assert after - before == 2


def test_serve_shed_disabled_by_default():
    recs, _, _ = _serve_api_run(jobs=2)
    assert not any(r["jobEntry"]["event"] == "shed"
                   for r in recs if "jobEntry" in r)


def test_serve_run_under_scrape_faults_never_stalls():
    """THE fault-site contract (runtime/faults.py obs_listen/scrape):
    a live serve run scraped between dispatches while the scrape site
    hangs one request and kills another still drives every job to
    completion and drains its writer — the listener can fail, the
    service cannot notice."""
    from timetabling_ga_tpu.runtime import faults
    faults.install("scrape:1:hang,scrape:2:die")
    try:
        # quantum=5 -> 4 quanta for the 20-generation jobs, so a LIVE
        # /metrics scrape lands after the two faulted ones. (At the
        # default quantum the only post-fault scrape was /readyz,
        # whose status is derived from process-global gauges — earlier
        # modules in a full-suite run leave engine.degrade_level /
        # fleet readiness set and it answers 503, which is correct
        # readiness reporting but not this test's recovery signal.)
        recs, scrapes, svc = _serve_api_run(
            jobs=2, scrape=True, obs_listen="127.0.0.1:0", quantum=5)
    finally:
        faults.install(None)
    done = [r["jobEntry"]["job"] for r in recs
            if "jobEntry" in r and r["jobEntry"]["event"] == "done"]
    assert sorted(done) == ["sj0", "sj1"]
    assert any(s[0] == "failed" for s in scrapes)    # faults did fire
    assert any(s[0] == 200 for s in scrapes)         # ...and later
    #                                                  scrapes recover


# ------------------------------------------------------- flow events


def _span(name, ts, dur, tid=0, **extra):
    return {"spanEntry": dict(name=name, cat="serve", ts=ts, dur=dur,
                              depth=0, tid=tid, **extra)}


_FLOW_RECORDS = [
    _span("admit", 0.00, 0.01, job="a", flow=1),
    _span("admit", 0.05, 0.01, job="b", flow=2),
    _span("pack", 0.10, 0.02, job=["a", "b"], flow=[1, 2]),
    _span("quantum", 0.20, 0.30, tid=0, job=["a", "b"], flow=[1, 2]),
    _span("fetch-read", 0.25, 0.01, tid=1, flow=9),   # singleton: no
    #                                                   arrow drawn
    _span("finalize", 0.60, 0.02, job="a", flow=1),
    {"metricsEntry": {"ts": 0.7, "counters": {"c": 1}}},
    {"phase": {"name": "gen-loop", "seconds": 0.5}},
]


def test_flow_events_connect_chains_across_spans():
    doc = export_chrome_trace(_FLOW_RECORDS)
    evs = doc["traceEvents"]
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], []).append(e)
    assert set(by_id) == {1, 2}          # singleton chain 9 draws none
    # chain 1: admit -> pack -> quantum -> finalize = s t t f, in ts
    # order, each event INSIDE its span (midpoint binding)
    phs1 = [e["ph"] for e in sorted(by_id[1], key=lambda e: e["ts"])]
    assert phs1 == ["s", "t", "t", "f"]
    assert [e["ph"] for e in sorted(by_id[2], key=lambda e: e["ts"])] \
        == ["s", "t", "f"]
    assert all(e.get("bp") == "e" for e in flows if e["ph"] == "f")
    spans = {(e["name"], e["ts"]): e for e in evs if e["ph"] == "X"}
    for e in flows:
        inside = [s for s in spans.values()
                  if s["tid"] == e["tid"]
                  and s["ts"] <= e["ts"] <= s["ts"] + s["dur"]]
        assert inside, f"flow event at {e['ts']} binds to no span"


def test_flow_export_job_filter():
    """--job a: only a's spans survive (scalar-tagged and packed), the
    arrows are a's own chain (flow 1) — not co-tenant b's chain that
    the shared pack/quantum spans also advanced — and the
    process-global counter/phase lanes are dropped."""
    doc = export_chrome_trace(_FLOW_RECORDS, job="a")
    evs = doc["traceEvents"]
    assert doc["otherData"]["job"] == "a"
    xs = [e["name"] for e in evs if e["ph"] == "X"]
    assert sorted(xs) == ["admit", "finalize", "pack", "quantum"]
    assert not any(e["ph"] == "C" for e in evs)
    flow_ids = {e["id"] for e in evs if e["ph"] in ("s", "t", "f")}
    assert flow_ids == {1}
    assert [e["ph"] for e in sorted(
        (e for e in evs if e["ph"] in ("s", "t", "f")),
        key=lambda e: e["ts"])] == ["s", "t", "t", "f"]


def test_tt_trace_job_flag_cli(tmp_path):
    p = tmp_path / "serve.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in _FLOW_RECORDS))
    out = str(tmp_path / "a.json")
    from timetabling_ga_tpu.obs.trace_export import main_trace
    assert main_trace([str(p), "-o", out, "--job", "a"]) == 0
    with open(out) as fh:
        doc = json.load(fh)
    assert {e["ph"] for e in doc["traceEvents"]} == {"X", "s", "t", "f"}


def test_serve_log_job_flows_end_to_end():
    """A real serve log renders one connected chain per job: every
    lifecycle span of job sjN carries its flow id, and `tt trace
    --job` yields exactly one s...f chain through admit -> pack ->
    quantum -> park -> finalize."""
    recs, _, _ = _serve_api_run(jobs=2)
    doc = export_chrome_trace(recs, job="sj0")
    evs = doc["traceEvents"]
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"admit", "pack", "quantum", "park"} <= names, names
    flows = [e for e in evs if e["ph"] in ("s", "t", "f")]
    ids = {e["id"] for e in flows}
    assert len(ids) == 1                 # the job's own chain only
    phs = [e["ph"] for e in sorted(flows, key=lambda e: e["ts"])]
    assert phs[0] == "s" and phs[-1] == "f"
    assert all(p == "t" for p in phs[1:-1])


# ----------------------------------------------- tt stats job breakdown


def test_stats_job_latency_breakdown():
    recs = [
        {"jobEntry": {"job": "a", "event": "admitted"}},
        _span("admit", 0.0, 0.0, job="a", flow=1),
        _span("pack", 1.0, 0.2, job=["a"], flow=[1]),
        _span("quantum", 1.2, 2.0, job=["a"], flow=[1]),
        _span("park", 3.2, 0.1, job=["a"], flow=[1]),
        # 1.5s parked gap while a co-tenant holds the lanes
        _span("resume", 4.8, 0.1, job=["a"], flow=[1]),
        _span("quantum", 4.9, 1.0, job=["a"], flow=[1]),
        _span("finalize", 5.9, 0.1, job="a", flow=1),
        {"jobEntry": {"job": "a", "event": "done", "best": 3,
                      "gens": 20}},
    ]
    text = summarize(recs)
    assert "job latency breakdown" in text
    line = next(x for x in text.splitlines()
                if x.startswith("  a: total "))
    assert "total 6.00s" in line
    assert "queued 1.00" in line         # admit 0.0 -> pack 1.0
    assert "executing 3.00" in line      # 2.0 + 1.0 quantum
    assert "packed 0.40" in line         # pack + park + resume
    assert "parked 1.50" in line         # the gap, minus finalize
    assert "total: p50 6.00s p99 6.00s" in text


def test_stats_breakdown_absent_without_spans():
    text = summarize([{"jobEntry": {"job": "a", "event": "done"}}])
    assert "job latency breakdown" not in text


# --------------------------------------------- engine + pull front


def test_engine_run_with_obs_listen_stream_identical(engine_baseline):
    """An engine run with the pull front on emits the identical record
    stream — the listener writes no records and shares nothing with
    the dispatch loop but the registry lock."""
    b0, l0 = engine_baseline
    b, l = _engine_run(trace_mode="full", obs=True,
                       obs_listen="127.0.0.1:0")
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    # the run set the /readyz source gauges on its way through
    g = obs_metrics.REGISTRY.snapshot()["gauges"]
    assert g.get("engine.degrade_level") == 0
    assert g.get("engine.recovery_budget_remaining") is not None


def test_engine_dispatch_seconds_carries_dispatch_exemplars(
        engine_baseline):
    """engine.dispatch_seconds observations carry the dispatch ordinal
    as their exemplar, so a latency spike on the scrape joins back to
    the record stream position. Instruments update with or without
    --obs, so the baseline run already fed the process registry."""
    text = obs_metrics.REGISTRY.to_openmetrics()
    assert "tt_engine_dispatch_seconds_bucket" in text
    assert '# {dispatch="' in text
