"""tt-obs tests (timetabling_ga_tpu/obs + --trace-mode).

Four layers:

  unit        metrics registry (counter/gauge/histogram/Prometheus),
              SpanTracer, the spanEntry/metricsEntry record emitters,
              strip_timing over the new record types, and the on-device
              trace compression vs a host recomputation
  engine A/B  --trace-mode full|deltas|stats x pipeline x --obs must
              emit IDENTICAL protocol records modulo timing (the
              acceptance criterion: telemetry reduction changes WHAT is
              fetched, never what is emitted) — including through a
              checkpointed pipelined run and a fault recovery
  serve A/B   the same contract for the lane scheduler, plus the
              `stats` line-JSON command and Prometheus exposition
  CLI         `tt trace` emits well-formed Chrome trace-event JSON;
              `tt stats` summarizes a log without jq
"""

import io
import json
import math
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs.logstats import summarize
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.obs.spans import NULL_TRACER, SpanTracer
from timetabling_ga_tpu.obs.trace_export import (
    export_chrome_trace, read_jsonl)
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig, ServeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM = os.path.join(REPO, "fixtures", "comp01s.tim")


# ---------------------------------------------------------------- metrics


def test_counter_monotone_and_negative_inc_raises():
    reg = MetricsRegistry()
    c = reg.counter("x.count")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_pull_and_degrade():
    reg = MetricsRegistry()
    g = reg.gauge("x.level")
    g.set(4)
    assert g.value == 4.0
    pull = reg.gauge_fn("x.depth", lambda: 7)
    assert pull.value == 7.0
    # a dead pull source degrades to nan (JSON null), never raises
    reg.gauge_fn("x.depth", lambda: 1 / 0)
    assert math.isnan(reg.gauge("x.depth").value)
    snap = reg.snapshot()
    assert snap["gauges"]["x.depth"] is None
    assert snap["gauges"]["x.level"] == 4.0


def test_histogram_percentiles_and_summary():
    reg = MetricsRegistry()
    h = reg.histogram("x.lat")
    for v in [0.002, 0.004, 0.02, 0.02, 0.3, 2.0]:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 6
    assert s["min"] == 0.002 and s["max"] == 2.0
    assert 0.002 <= s["p50"] <= 0.3
    assert s["p95"] <= 2.0
    assert reg.histogram("x.lat") is h          # get-or-create


def test_registry_kind_collision_raises():
    reg = MetricsRegistry()
    reg.counter("a.b")
    with pytest.raises(TypeError):
        reg.gauge("a.b")


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("engine.gens").inc(5)
    reg.gauge("serve.queue_depth").set(3)
    reg.histogram("serve.job_seconds").observe(0.3)
    text = reg.to_prometheus()
    assert "# TYPE tt_engine_gens_total counter" in text
    assert "tt_engine_gens_total 5" in text
    assert "tt_serve_queue_depth 3" in text
    assert 'tt_serve_job_seconds_bucket{le="+Inf"} 1' in text
    assert "tt_serve_job_seconds_count 1" in text
    assert text.endswith("\n")


def test_registry_is_thread_safe():
    reg = MetricsRegistry()
    c = reg.counter("t.n")

    def hammer():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert c.value == 4000


# ------------------------------------------------------------------ spans


def test_span_tracer_nesting_and_record():
    buf = io.StringIO()
    tracer = SpanTracer(buf)
    with tracer.span("outer", cat="t"):
        with tracer.span("inner", cat="t", k=1):
            pass
    tracer.record("measured", tracer._clock() - 0.5, 0.25, cat="d")
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    spans = {r["spanEntry"]["name"]: r["spanEntry"] for r in recs}
    assert spans["inner"]["depth"] == 1 and spans["outer"]["depth"] == 0
    assert spans["inner"]["k"] == 1
    assert spans["measured"]["dur"] == 0.25
    # inner closes before outer -> emitted first
    assert [r["spanEntry"]["name"] for r in recs] == [
        "inner", "outer", "measured"]
    assert spans["outer"]["dur"] >= spans["inner"]["dur"]


def test_span_tracer_disabled_is_noop():
    with NULL_TRACER.span("x"):
        pass
    NULL_TRACER.record("y", 0.0, 1.0)   # no output target, no error


def test_span_error_is_marked_and_propagates():
    buf = io.StringIO()
    tracer = SpanTracer(buf)
    with pytest.raises(RuntimeError):
        with tracer.span("failing"):
            raise RuntimeError("boom")
    rec = json.loads(buf.getvalue())
    assert rec["spanEntry"]["error"] is True


# ------------------------------------------------- records + strip_timing


def test_strip_timing_drops_obs_records():
    buf = io.StringIO()
    jsonl.log_entry(buf, 0, 0, 42, 1.5)
    jsonl.span_entry(buf, "dispatch", "device", 1.0, 0.5, gens=10)
    jsonl.metrics_entry(buf, {"counters": {"engine.gens": 10}}, ts=2.0)
    jsonl.phase_record(buf, "init", 0, 0.1)
    jsonl.fault_entry(buf, "dispatch", "recover", ValueError("x"), 0, 1,
                      0, 1.0)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert {next(iter(r)) for r in recs} == {
        "logEntry", "spanEntry", "metricsEntry", "phase", "faultEntry"}
    stripped = jsonl.strip_timing(recs)
    assert len(stripped) == 1
    assert "logEntry" in stripped[0]
    assert "time" not in stripped[0]["logEntry"]


def test_span_and_metrics_records_are_well_formed():
    buf = io.StringIO()
    jsonl.span_entry(buf, "quantum", "serve", 1.23456789, 0.001, depth=2,
                     tid=1, job="j1")
    jsonl.metrics_entry(buf, {"gauges": {"g": 1.0}})
    span, metrics = [json.loads(x) for x in buf.getvalue().splitlines()]
    s = span["spanEntry"]
    assert (s["name"], s["cat"], s["depth"], s["tid"], s["job"]) == (
        "quantum", "serve", 2, 1, "j1")
    assert s["ts"] == 1.234568            # 6-digit rounding
    assert "ts" not in metrics["metricsEntry"]   # optional


# ------------------------------------------------- trace compression unit


def _host_improvements(tr, n):
    SENT = 2 ** 31 - 1
    best, out = (SENT, SENT), []
    for g in range(n):
        h, s = int(tr[g, 0]), int(tr[g, 1])
        if (h, s) < best:
            best = (h, s)
            out.append((g, h, s))
    return out


def test_compress_trace_matches_host_recomputation():
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    rng = np.random.default_rng(7)
    tr = rng.integers(0, 6, size=(4, 12, 2)).astype(np.int32)
    for mode in ("deltas", "stats"):
        packed = np.asarray(islands._compress_trace(
            jnp.asarray(tr), None, mode))
        assert packed.shape == (4, islands.trace_leaf_width(12, mode))
        events, counts, moments = islands.trace_events(packed, mode)
        for i in range(4):
            want = _host_improvements(tr[i], 12)
            assert events[i] == want
            assert counts[i] == len(want)
        assert (moments is not None) == (mode == "stats")


def test_compress_trace_per_lane_valid_counts():
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    rng = np.random.default_rng(8)
    tr = rng.integers(0, 6, size=(3, 10, 2)).astype(np.int32)
    nv = np.array([4, 10, 0], np.int32)
    packed = np.asarray(islands._compress_trace(
        jnp.asarray(tr), jnp.asarray(nv), "deltas"))
    events, counts, _ = islands.trace_events(packed, "deltas")
    for i in range(3):
        assert events[i] == _host_improvements(tr[i], int(nv[i]))
    assert events[2] == [] and counts[2] == 0


def test_compress_trace_overflow_is_counted(monkeypatch):
    import jax.numpy as jnp
    from timetabling_ga_tpu.parallel import islands
    monkeypatch.setattr(islands, "TRACE_DELTAS_CAP", 3)
    # strictly decreasing -> every generation improves (8 events, cap 3)
    tr = np.stack([np.arange(9, 1, -1), np.zeros(8)],
                  axis=1)[None].astype(np.int32)
    packed = np.asarray(islands._compress_trace(
        jnp.asarray(tr), None, "deltas"))
    events, counts, _ = islands.trace_events(packed, "deltas")
    assert len(events[0]) == 3           # last K kept, earliest dropped
    assert counts[0] == 8                # the count exposes the drop
    # the LAST improvements survive: the dispatch's final best (what
    # best_seen and the post-feasibility switch read) is never lost
    assert events[0] == _host_improvements(tr[0], 8)[-3:]


def test_full_trace_decode_matches_layouts():
    from timetabling_ga_tpu.parallel import islands
    tr = np.arange(2 * 1 * 3 * 2).reshape(2, 1, 3, 2).astype(np.int32)
    events, counts, moments = islands.trace_events(tr, "full")
    assert counts is None and moments is None
    assert events[0] == [(0, 0, 1), (1, 2, 3), (2, 4, 5)]


def test_polish_runner_with_passes_is_trajectory_pure():
    """The with_passes polish program (--trace-mode stats) must return
    a bit-identical population and (penalty, hcv, scv) block — the
    pass-count row is the ONLY difference. Pins the invariant the
    engine-level stats A/B relies on, without the engine's
    timing-sensitive dispatch scheduling in the loop."""
    import jax
    from timetabling_ga_tpu.ops import ga
    from timetabling_ga_tpu.parallel import islands
    from timetabling_ga_tpu.problem import load_tim_file
    pa = load_tim_file(TIM).device_arrays()
    mesh = islands.make_mesh(2)
    cfg = ga.GAConfig(pop_size=8, ls_mode="sweep", ls_sweeps=1,
                      ls_hot_k=4, ls_swap_block=4, init_sweeps=2)
    state = islands.init_island_population(pa, jax.random.key(7), mesh, 8)
    outs = {}
    for wp in (False, True):
        pol = islands.make_polish_runner(mesh, cfg, n_islands=2,
                                         with_passes=wp)
        st, stats = pol(pa, jax.random.key(5), state, 2)
        outs[wp] = (jax.device_get(st), np.asarray(stats))
    st0, s0 = outs[False]
    st1, s1 = outs[True]
    assert s0.shape[0] == 3 and s1.shape[0] == 4
    assert np.array_equal(s0, s1[:3])
    assert (s1[3] >= 1).all()            # executed >= 1 converge pass
    for a, b in zip(st0, st1):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------- engine A/Bs


def _engine_run(trace_mode="full", obs=False, pipeline=True,
                checkpoint=None, faults=None, **kw):
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                generations=30, migration_period=10, max_steps=8,
                time_limit=300, backend="cpu", auto_tune=False,
                trace=True, pipeline=pipeline, obs=obs,
                trace_mode=trace_mode, metrics_every=1,
                checkpoint=checkpoint, faults=faults)
    base.update(kw)
    cfg = RunConfig(**base)
    best = eng.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


@pytest.fixture(scope="module")
def engine_baseline():
    """full-trace, obs-off, pipelined reference stream."""
    return _engine_run()


def test_trace_mode_stream_identical_under_pipeline(engine_baseline):
    """THE acceptance criterion: deltas and stats ship a reduced
    telemetry leaf but the emitted record stream is identical to full
    modulo timing, with obs enabled end-to-end under the pipelined
    engine."""
    b0, l0 = engine_baseline
    for mode in ("deltas", "stats"):
        b, l = _engine_run(trace_mode=mode, obs=True)
        assert b == b0, mode
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), mode
        assert any("spanEntry" in r for r in l)
        assert any("metricsEntry" in r for r in l)


def test_trace_mode_stream_identical_serial(engine_baseline):
    b0, l0 = engine_baseline
    b, l = _engine_run(trace_mode="deltas", obs=True, pipeline=False)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)


def test_obs_off_emits_no_obs_records(engine_baseline):
    _, l0 = engine_baseline
    assert not any("spanEntry" in r or "metricsEntry" in r for r in l0)


def test_obs_span_taxonomy_and_metrics_content(engine_baseline):
    b0, l0 = engine_baseline
    before = dict(obs_metrics.REGISTRY.snapshot().get("counters", {}))
    b, l = _engine_run(trace_mode="stats", obs=True)
    assert b == b0
    names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
    assert {"init", "dispatch", "fetch", "process"} <= names, names
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert snaps
    last = snaps[-1]
    c = last["counters"]
    assert (c["engine.dispatches"]
            - before.get("engine.dispatches", 0)) >= 3
    assert "engine.gens" in c
    assert "writer.queue_depth" in last["gauges"]
    # stats mode streams on-device moments into gauges
    assert "engine.trace_best_min" in last["gauges"]
    assert "engine.dispatch_seconds" in last["histograms"]


def test_trace_mode_with_checkpoint_and_resume(tmp_path, engine_baseline):
    """The checkpoint's in-flight trace fold decodes the compressed
    leaf: a pipelined checkpointed deltas run emits the same stream and
    lands a loadable checkpoint."""
    b0, l0 = engine_baseline
    ck = str(tmp_path / "obs.ck.npz")
    b, l = _engine_run(trace_mode="deltas", obs=True, checkpoint=ck)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    assert os.path.exists(ck)
    with np.load(ck, allow_pickle=False) as z:
        assert int(z["generation"]) == 30


def test_trace_mode_fault_recovery_stream_identical(engine_baseline):
    """A recovered deltas-mode run matches the uninjected full-mode
    stream modulo timing+fault records — the recovery paths (poisoned
    buffer teardown, snapshot rehydrate, emitted-floor replay) all
    decode the compressed leaf."""
    b0, l0 = engine_baseline
    b, l = _engine_run(trace_mode="deltas", obs=True,
                       faults="dispatch:2:unavailable")
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    assert any("faultEntry" in r for r in l)
    names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
    assert "recover" in names


def test_polish_pass_counts_ride_stats_mode(monkeypatch):
    """--trace-mode stats adds the sweep-pass-count row to the polish
    stats fetch (islands.make_polish_runner with_passes); the stream
    stays identical to full mode and the gauge is populated.

    The A/B needs BOTH runs to see the same dispatch/polish schedule
    (the schedule feeds fold_in offsets, so it IS the trajectory):
    precompile both configs first — engine.run alone does not, so the
    first run would enter the init polish with a cold _SPS_CACHE and
    chunk it 1+1 while the warm second run chunks it 2 (exactly how
    bench.measure_obs pre-warms its A/B) — pin DISPATCH_CAP_S out of
    range (sweep generations cost ~seconds on CPU, close enough to
    the watchdog boundary for timing noise to flip static dispatches
    into timing-SIZED dynamic ones), and keep the sweep cheap via
    ls_hot_k (the trajectory-purity of with_passes itself is pinned
    by the direct runner A/B above)."""
    from timetabling_ga_tpu.runtime import engine as eng
    monkeypatch.setattr(eng, "DISPATCH_CAP_S", 1e9)
    kw = dict(ls_mode="sweep", ls_sweeps=1, init_sweeps=2,
              ls_hot_k=4, ls_swap_block=4, generations=20)
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                migration_period=10, max_steps=8, time_limit=300,
                backend="cpu", auto_tune=False, trace=True,
                metrics_every=1, **kw)
    eng.precompile(RunConfig(**base))
    eng.precompile(RunConfig(trace_mode="stats", **base))
    b0, l0 = _engine_run(**kw)
    b, l = _engine_run(trace_mode="stats", obs=True, **kw)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert snaps and snaps[-1]["gauges"].get("engine.polish_passes", 0) >= 1


def test_run_counters_backcompat_dict():
    from timetabling_ga_tpu.runtime import engine as eng
    c = eng.run_counters()
    assert set(c) == {"recoveries", "faults_injected"}
    assert isinstance(c["recoveries"], int)
    assert c["recoveries"] == int(
        obs_metrics.REGISTRY.counter("engine.recoveries").value)


# ------------------------------------------------------------ serve A/Bs


def _serve_run(trace_mode="full", obs=False, requests=None):
    from timetabling_ga_tpu.serve.service import serve_stream
    cfg = ServeConfig(backend="cpu", lanes=2, quantum=10, pop_size=8,
                      generations=20, obs=obs, trace_mode=trace_mode,
                      metrics_every=1)
    reqs = requests or [
        {"submit": {"id": "a", "instance": TIM, "seed": 1}},
        {"submit": {"id": "b", "instance": TIM, "seed": 2}},
    ]
    inp = io.StringIO("\n".join(json.dumps(r) for r in reqs) + "\n")
    out = io.StringIO()
    svc = serve_stream(cfg, inp, out)
    return svc, [json.loads(x) for x in out.getvalue().splitlines()]


@pytest.fixture(scope="module")
def serve_baseline():
    return _serve_run()


def test_serve_trace_modes_stream_identical(serve_baseline):
    _, l0 = serve_baseline
    for mode in ("deltas", "stats"):
        svc, l = _serve_run(trace_mode=mode, obs=True)
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), mode
        names = {r["spanEntry"]["name"] for r in l if "spanEntry" in r}
        assert {"admit", "pack", "quantum", "park", "resume",
                "init"} <= names, names


def test_serve_stats_command_and_prometheus(serve_baseline):
    _, l0 = serve_baseline
    reqs = [
        {"submit": {"id": "a", "instance": TIM, "seed": 1}},
        {"submit": {"id": "b", "instance": TIM, "seed": 2}},
        {"drain": True},
        {"stats": True},
        {"stats": "prometheus"},
    ]
    svc, l = _serve_run(obs=True, requests=reqs)
    snaps = [r["metricsEntry"] for r in l if "metricsEntry" in r]
    assert len(snaps) >= 2
    plain, prom = snaps[-2], snaps[-1]
    assert "prometheus" not in plain
    assert "tt_serve_dispatches_total" in prom["prometheus"]
    assert "tt_serve_job_seconds_bucket" in prom["prometheus"]
    assert prom["counters"]["serve.jobs_done"] >= 2
    # the protocol records are unaffected by the stats traffic
    assert jsonl.strip_timing(l) == jsonl.strip_timing(
        serve_baseline[1])
    # live Python API mirrors the stream
    assert "serve.job_seconds" in svc.stats().get("histograms", {})


# -------------------------------------------------------------------- CLI


@pytest.fixture(scope="module")
def obs_log(tmp_path_factory):
    """One obs-enabled engine run's JSONL log on disk."""
    _, recs = _engine_run(trace_mode="stats", obs=True)
    p = tmp_path_factory.mktemp("obs") / "run.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_tt_trace_emits_wellformed_chrome_trace(obs_log, tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu", "trace", obs_log,
         "-o", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    with open(out) as fh:
        doc = json.load(fh)           # well-formed JSON or this raises
    events = doc["traceEvents"]
    assert events, "no trace events exported"
    for ev in events:
        assert ev["ph"] in ("X", "C")
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert "name" in ev and "pid" in ev and "tid" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
    phs = {ev["ph"] for ev in events}
    assert phs == {"X", "C"}          # spans+phases AND counter tracks
    names = {ev["name"] for ev in events if ev["ph"] == "X"}
    assert "dispatch" in names


def test_export_tolerates_torn_tail_line(tmp_path):
    p = tmp_path / "torn.jsonl"
    p.write_text('{"spanEntry": {"name": "a", "cat": "t", "ts": 0.0, '
                 '"dur": 1.0, "depth": 0, "tid": 0}}\n{"spanEnt')
    recs = read_jsonl(str(p))
    assert len(recs) == 1
    assert export_chrome_trace(recs)["traceEvents"]


def test_tt_stats_summarizes_log(obs_log):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "timetabling_ga_tpu", "stats", obs_log],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=240)
    assert r.returncode == 0, r.stderr
    assert "best-so-far" in r.stdout
    assert "last metrics snapshot" in r.stdout
    assert "faults: none" in r.stdout


def test_stats_summarize_jobs_and_faults():
    recs = [
        {"logEntry": {"procID": 0, "best": 9, "time": 0.5, "job": "j1"}},
        {"logEntry": {"procID": 0, "best": 2, "time": 1.0, "job": "j1"}},
        {"solution": {"procID": 0, "totalBest": 2, "feasible": True,
                      "totalTime": 1.5, "job": "j1"}},
        {"jobEntry": {"job": "j1", "event": "admitted"}},
        {"jobEntry": {"job": "j1", "event": "done", "best": 2,
                      "gens": 20}},
        {"faultEntry": {"site": "dispatch", "action": "recover",
                        "level": 1, "error": "UNAVAILABLE"}},
    ]
    text = summarize(recs)
    assert "job j1" in text
    assert "dispatch/recover" in text
    assert "latency p50" in text


def test_tt_trace_and_stats_work_without_jax(obs_log, tmp_path):
    """The offline obs surfaces must run on a machine with no
    accelerator stack: the package __init__ is PEP 562-lazy and cli.py
    defers every runtime import past the trace/stats dispatch, so a
    blocked `import jax` never fires."""
    out = str(tmp_path / "trace.json")
    blocker = (
        "import sys\n"
        "class _NoJax:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith('jax.'):\n"
        "            raise ImportError('BLOCKED import of jax')\n"
        "sys.meta_path.insert(0, _NoJax())\n"
        "from timetabling_ga_tpu.cli import main\n"
        "assert main(['trace', %r, '-o', %r]) == 0\n"
        "assert main(['stats', %r]) == 0\n" % (obs_log, out, obs_log))
    r = subprocess.run([sys.executable, "-c", blocker],
                       capture_output=True, text=True, cwd=REPO,
                       timeout=240)
    assert r.returncode == 0, r.stderr
    with open(out) as fh:
        assert json.load(fh)["traceEvents"]


def test_gauge_bind_none_freezes_and_releases():
    reg = MetricsRegistry()
    g = reg.gauge_fn("w.depth", lambda: 5)
    assert g.value == 5.0
    g.set(2.0)
    g.bind(None)                      # unbind: engine.run's finally
    assert g.value == 2.0             # frozen at the set() value


def test_engine_run_unbinds_writer_gauges(engine_baseline):
    """engine.run must not leave the process-global registry holding a
    closure over the finished run's writer (and its output stream)."""
    for name in ("writer.records", "writer.queue_depth"):
        assert obs_metrics.REGISTRY.gauge(name)._fn is None, name
