"""tt-edit: incremental re-solve (ISSUE 19).

The acceptance properties pinned here:

  1. the anchored objective is NEUTRAL at w_anchor == 0 — anchored
     columns of zero weight evaluate bit-identically to the unanchored
     objective, per individual, and a w_anchor=0 edit job's solver
     record stream is identical to a plain solve of the edited
     instance;
  2. anchored evaluation is bucket-padding-exact: padded and unpadded
     instances agree on (penalty, hcv, scv) bit-for-bit on the
     committed ITC fixtures, anchor term included;
  3. delta/sweep acceptance agrees with full (host-recomputable)
     evaluation under an anchored problem;
  4. the transplant carries base genes exactly on every warm path and
     DEMOTES (never errors) on every cold obstacle;
  5. the service surface: an edit job's result/records carry
     mode/edit_distance/edit_of, and `tt stats` / `tt usage` split
     edit traffic out without changing non-edit rendering.
"""

import dataclasses
import io
import json
import os

import numpy as np
import jax
import pytest

from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs import logstats
from timetabling_ga_tpu.obs import usage as obs_usage
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.ops import delta, fitness, ga, local_search, sweep
from timetabling_ga_tpu.problem import (
    dump_tim, load_tim, load_tim_file, random_instance)
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import ServeConfig
from timetabling_ga_tpu.serve import BucketSpec, JobState, bucket_key, \
    pad_problem
from timetabling_ga_tpu.serve import editsolve
from timetabling_ga_tpu.serve import snapshot as snapshot_mod
from timetabling_ga_tpu.serve.bucket import embed_population
from timetabling_ga_tpu.serve.editsolve import EditDemoted, EditError
from timetabling_ga_tpu.serve.service import SolveService

FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "fixtures")
SPEC = BucketSpec()


def _cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 10)
    kw.setdefault("pop_size", 6)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _records(buf):
    return [json.loads(x) for x in buf.getvalue().splitlines()]


def _base_problem(seed=11, n_events=10):
    return random_instance(seed, n_events=n_events, n_rooms=3,
                           n_features=2, n_students=8,
                           attend_prob=0.2)


def _anchored(p, w, seed=0):
    """p with a random anchor attached to every event at weight w
    (identity event map — the pure-objective tests don't need an
    actual edit)."""
    rng = np.random.default_rng(seed)
    anchor = rng.integers(0, p.n_slots, size=p.n_events).astype(
        np.int32)
    return editsolve.attach_anchor(
        p, np.arange(p.n_events, dtype=np.int32), anchor, w), anchor


# ------------------------------------------------------------- .tim codec

@pytest.mark.parametrize("name", ["comp01s", "comp05s"])
def test_to_tim_roundtrip_fixture(name):
    p = load_tim_file(os.path.join(FIXTURES, f"{name}.tim"))
    q = load_tim(p.to_tim())
    np.testing.assert_array_equal(p.attends, q.attends)
    np.testing.assert_array_equal(p.room_size, q.room_size)
    np.testing.assert_array_equal(p.room_features, q.room_features)
    np.testing.assert_array_equal(p.event_features, q.event_features)
    assert (p.n_days, p.slots_per_day) == (q.n_days, q.slots_per_day)
    # canonical: serializing the round-tripped problem is a fixpoint
    assert q.to_tim() == p.to_tim()


def test_to_tim_roundtrip_random():
    p = _base_problem()
    q = load_tim(p.to_tim(), n_days=p.n_days,
                 slots_per_day=p.slots_per_day)
    np.testing.assert_array_equal(p.attends, q.attends)
    np.testing.assert_array_equal(p.possible, q.possible)


# ---------------------------------------------------------- spec + differ

def test_parse_edit_spec_rejections():
    ok = {"base": {"tim": "x"}, "ops": []}
    assert editsolve.parse_edit_spec(ok) is ok
    for bad in (
            "nope",                                     # not an object
            {"ops": []},                                # no base
            {"base": {}, "ops": [], "edited": {}},      # both forms
            {"base": {}},                               # neither form
            {"base": {}, "ops": [{"op": "explode"}]},   # unknown op
            {"base": {}, "ops": "add"},                 # ops not a list
            {"base": {}, "ops": [], "w_anchor": -1},    # negative w
            {"base": {}, "ops": [], "w_anchor": "z"},   # non-int w
    ):
        with pytest.raises(EditError):
            editsolve.parse_edit_spec(bad)


def test_apply_ops_event_map_and_arrays():
    p = _base_problem()
    E = p.n_events
    edited, emap = editsolve.apply_ops(p, [
        {"op": "add_event", "students": [0, 3], "features": [1]},
        {"op": "remove_event", "event": 2},
        {"op": "set_attendance", "event": 0, "student": 5, "value": 1},
        {"op": "set_room_size", "room": 1, "size": 1},
        {"op": "set_room_features", "room": 0, "features": [0, 1]},
        {"op": "set_event_features", "event": 1, "features": []},
    ])
    # map: original events minus #2, then the added event as -1
    assert emap.tolist() == [0, 1] + list(range(3, E)) + [-1]
    assert edited.n_events == E       # +1 added, -1 removed
    assert edited.attends[5, 0] == 1
    assert int(edited.room_size[1]) == 1
    assert edited.room_features[0].tolist() == [1, 1]
    assert edited.event_features[1].sum() == 0
    new_col = edited.attends[:, -1]
    assert np.flatnonzero(new_col).tolist() == [0, 3]
    # applicability errors are EditError, not crashes
    with pytest.raises(EditError):
        editsolve.apply_ops(p, [{"op": "remove_event", "event": E}])
    with pytest.raises(EditError):
        editsolve.apply_ops(p, [{"op": "set_attendance", "event": 0,
                                 "student": 99, "value": 1}])
    with pytest.raises(EditError):
        editsolve.apply_ops(
            p, [{"op": "remove_event", "event": 0}] * E)  # empties


def test_diff_problems_recovers_apply_ops():
    """diff(base, apply_ops(base, ops)) yields ops that rebuild the
    same edited instance (positional convention: trailing adds)."""
    p = _base_problem(seed=21)
    edited, emap = editsolve.apply_ops(p, [
        {"op": "set_attendance", "event": 1, "student": 2, "value": 1},
        {"op": "set_room_size", "room": 0, "size": 2},
        {"op": "add_event", "students": [4], "features": [0]},
    ])
    ops2, emap2 = editsolve.diff_problems(p, edited)
    assert emap2.tolist() == emap.tolist()
    rebuilt, _ = editsolve.apply_ops(p, ops2)
    np.testing.assert_array_equal(rebuilt.attends, edited.attends)
    np.testing.assert_array_equal(rebuilt.room_size, edited.room_size)
    np.testing.assert_array_equal(rebuilt.event_features,
                                  edited.event_features)
    np.testing.assert_array_equal(rebuilt.room_features,
                                  edited.room_features)

    # shrinking edit: trailing removes, reported in the map as absent
    shrunk, smap = editsolve.apply_ops(
        p, [{"op": "remove_event", "event": p.n_events - 1}])
    ops3, smap3 = editsolve.diff_problems(p, shrunk)
    assert smap3.tolist() == smap.tolist()
    assert {"op": "remove_event", "event": p.n_events - 1} in ops3

    # axis mismatches refuse to diff rather than guess
    other = random_instance(5, n_events=p.n_events, n_rooms=4,
                            n_features=2, n_students=8,
                            attend_prob=0.2)
    with pytest.raises(EditError):
        editsolve.diff_problems(p, other)


# ----------------------------------------------------- anchored objective

@pytest.mark.parametrize("name", ["comp01s", "comp05s"])
def test_anchored_penalty_padded_bit_exact(name):
    """ISSUE 19 acceptance: the anchored penalty is bit-exact padded
    vs unpadded (the anchor term rides the padding neutrality contract
    through zero weights on padded events)."""
    p = load_tim_file(os.path.join(FIXTURES, f"{name}.tim"))
    ap, anchor = _anchored(p, w=3, seed=1)
    pp = pad_problem(ap, SPEC)
    rng = np.random.default_rng(7)
    P = 4
    slots = rng.integers(0, p.n_slots, size=(P, p.n_events)).astype(
        np.int32)
    rooms = rng.integers(0, p.n_rooms, size=(P, p.n_events)).astype(
        np.int32)
    s_pad, r_pad = embed_population(slots, rooms, pp)

    pen, hcv, scv = fitness.batch_penalty(ap.device_arrays(), slots,
                                          rooms)
    pen2, hcv2, scv2 = fitness.batch_penalty(pp.device_arrays(),
                                             s_pad, r_pad)
    np.testing.assert_array_equal(np.asarray(pen), np.asarray(pen2))
    np.testing.assert_array_equal(np.asarray(hcv), np.asarray(hcv2))
    np.testing.assert_array_equal(np.asarray(scv), np.asarray(scv2))

    # host recompute: penalty == base + w * Hamming(slots, anchor),
    # and hcv/scv are pure constraint counts (anchor never leaks)
    pen0, hcv0, scv0 = fitness.batch_penalty(p.device_arrays(), slots,
                                             rooms)
    np.testing.assert_array_equal(np.asarray(hcv), np.asarray(hcv0))
    np.testing.assert_array_equal(np.asarray(scv), np.asarray(scv0))
    ham = (slots != anchor[None, :]).sum(axis=1)
    np.testing.assert_array_equal(
        np.asarray(pen), np.asarray(pen0) + 3 * ham)


def test_anchor_w_zero_is_bit_identical():
    p = _base_problem(seed=31)
    ap, _anchor = _anchored(p, w=0, seed=2)
    rng = np.random.default_rng(3)
    slots = rng.integers(0, p.n_slots, size=(8, p.n_events)).astype(
        np.int32)
    rooms = rng.integers(0, p.n_rooms, size=(8, p.n_events)).astype(
        np.int32)
    for a, b in zip(
            fitness.batch_penalty(p.device_arrays(), slots, rooms),
            fitness.batch_penalty(ap.device_arrays(), slots, rooms)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_anchor_cost_and_delta_consistent():
    p = _base_problem(seed=41)
    ap, anchor = _anchored(p, w=2, seed=4)
    pa = ap.device_arrays()
    rng = np.random.default_rng(5)
    slots = rng.integers(0, p.n_slots, size=p.n_events).astype(
        np.int32)
    cost = int(fitness.anchor_cost(pa, slots))
    assert cost == 2 * int(np.sum(slots != anchor))
    # sparse move delta == recompute difference, with inactive lanes
    # (new == old) contributing exactly 0
    evs = np.array([0, 3, 3], np.int32)       # repeated event: the
    #                                           padding convention
    new = np.array([anchor[0], (slots[3] + 1) % p.n_slots, slots[3]],
                   np.int32)
    moved = slots.copy()
    moved[0] = anchor[0]
    moved[3] = (slots[3] + 1) % p.n_slots
    d = int(fitness.anchor_delta(pa, slots, evs, new))
    # the repeated lane passes new == old for the FINAL value; delta
    # is defined per-lane against the pre-move slots, so compare
    # against the two real moves only
    real = (int(fitness.anchor_cost(pa, moved))
            - int(fitness.anchor_cost(pa, slots)))
    assert d == real


@pytest.mark.parametrize("w", [0, 2])
def test_delta_ls_agrees_with_full_ls_anchored(w):
    """Acceptance: delta-path acceptance (residual arithmetic) and the
    full-reevaluation path make identical decisions under an anchored
    problem — same final populations, bit for bit."""
    p = _base_problem(seed=51, n_events=12)
    ap, _ = _anchored(p, w=w, seed=6)
    pa = ap.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 8)
    key = jax.random.key(42)
    s_full, r_full = local_search.batch_local_search(
        pa, key, st.slots, st.rooms, n_rounds=10, n_candidates=4)
    s_dlt, r_dlt = delta.batch_local_search_delta(
        pa, key, st.slots, st.rooms, n_rounds=10, n_candidates=4)
    np.testing.assert_array_equal(np.asarray(s_full),
                                  np.asarray(s_dlt))
    np.testing.assert_array_equal(np.asarray(r_full),
                                  np.asarray(r_dlt))


def test_sweep_anchored_neutral_at_zero_and_consistent():
    p = _base_problem(seed=61, n_events=12)
    ap0, _ = _anchored(p, w=0, seed=7)
    ap2, _ = _anchored(p, w=2, seed=7)
    st = ga.init_population(p.device_arrays(), jax.random.key(1), 8)
    key = jax.random.key(9)
    s_plain, r_plain = sweep.sweep_local_search(
        p.device_arrays(), key, st.slots, st.rooms, n_sweeps=3)
    s_zero, r_zero = sweep.sweep_local_search(
        ap0.device_arrays(), key, st.slots, st.rooms, n_sweeps=3)
    np.testing.assert_array_equal(np.asarray(s_plain),
                                  np.asarray(s_zero))
    np.testing.assert_array_equal(np.asarray(r_plain),
                                  np.asarray(r_zero))
    # w > 0: the sweep's maintained acceptance never drifts from the
    # host-recomputable anchored objective (monotone non-increase)
    pa2 = ap2.device_arrays()
    pen0, _, _ = fitness.batch_penalty(pa2, st.slots, st.rooms)
    s2, r2 = sweep.sweep_local_search(pa2, key, st.slots, st.rooms,
                                      n_sweeps=3)
    pen1, _, _ = fitness.batch_penalty(pa2, s2, r2)
    assert (np.asarray(pen1) <= np.asarray(pen0)).all()


# ------------------------------------------------------------- transplant

def _wire_for(padded, pop_size=6, seed=5, bucket=None):
    pa = padded.device_arrays()
    st = ga.init_population(pa, jax.random.key(seed), pop_size)
    st = ga.PopState(slots=np.asarray(st.slots),
                     rooms=np.asarray(st.rooms),
                     penalty=np.asarray(st.penalty),
                     hcv=np.asarray(st.hcv), scv=np.asarray(st.scv))
    return st, snapshot_mod.pack_state(
        st, bucket=bucket, pop_size=pop_size, seed=seed, gens_done=9,
        chunks=3, emitted=123, best=123)


def test_transplant_warm_carries_base_genes():
    p = _base_problem(seed=71)
    bucket = bucket_key(p, SPEC)
    base_padded = pad_problem(p, SPEC)
    base_st, wire = _wire_for(base_padded, bucket=bucket)
    wire = json.loads(json.dumps(wire))        # the /v1 wire form

    edited, emap = editsolve.apply_ops(p, [
        {"op": "add_event", "students": [1], "features": []},
        {"op": "remove_event", "event": 2},
        {"op": "set_attendance", "event": 0, "student": 4,
         "value": 1},
    ])
    assert bucket_key(edited, SPEC) == bucket  # same-bucket edit
    ep = pad_problem(edited, SPEC)
    out = editsolve.transplant(ep, emap, wire, bucket=bucket,
                               pop_size=6, seed=77)
    state, meta = snapshot_mod.unpack_state(wire=out)
    # cursors reset: the edit job's record stream starts clean
    assert meta["gens_done"] == 0 and meta["chunks"] == 0
    assert meta["emitted"] == meta["best"] == 2**31 - 1
    slots = np.asarray(state.slots)
    rooms = np.asarray(state.rooms)
    live = ep.n_live_events
    carried = np.flatnonzero(emap >= 0)
    # the transplant lex-sorts the population under the edited
    # problem, so rows come back PERMUTED: compare as row sets
    got = sorted(map(tuple, np.concatenate(
        [slots[:, carried], rooms[:, carried]], axis=1)))
    want = sorted(map(tuple, np.concatenate(
        [base_st.slots[:, emap[carried]],
         base_st.rooms[:, emap[carried]]], axis=1)))
    assert got == want
    fresh = np.flatnonzero(emap < 0)
    assert ((slots[:, fresh] >= 0)
            & (slots[:, fresh] < p.n_slots)).all()
    assert (rooms[:, fresh] == 0).all()
    # re-evaluated under the EDITED instance and lex-sorted
    pen, hcv, scv = fitness.batch_penalty(ep.device_arrays(),
                                          slots[:, :],
                                          rooms[:, :])
    np.testing.assert_array_equal(np.asarray(pen), state.penalty)
    order = np.asarray(fitness.lex_order(pen, scv))
    assert order.tolist() == list(range(6))    # already sorted
    assert live == edited.n_events


def test_transplant_demotions():
    p = _base_problem(seed=81)
    bucket = bucket_key(p, SPEC)
    _st, wire = _wire_for(pad_problem(p, SPEC), bucket=bucket)
    edited, emap = editsolve.apply_ops(
        p, [{"op": "set_room_size", "room": 0, "size": 1}])
    ep = pad_problem(edited, SPEC)

    with pytest.raises(EditDemoted):           # no base snapshot
        editsolve.transplant(ep, emap, None, bucket=bucket,
                             pop_size=6, seed=1)
    other = tuple(list(bucket[:-1]) + [bucket[-1] + 1])
    with pytest.raises(EditDemoted):           # cross-bucket
        editsolve.transplant(ep, emap, wire, bucket=other,
                             pop_size=6, seed=1)
    with pytest.raises(EditDemoted):           # population mismatch
        editsolve.transplant(ep, emap, wire, bucket=bucket,
                             pop_size=12, seed=1)
    cut = dict(wire, npz=wire["npz"][: len(wire["npz"]) // 2])
    with pytest.raises(EditDemoted):           # undecodable wire
        editsolve.transplant(ep, emap, cut, bucket=bucket,
                             pop_size=6, seed=1)
    # classify mirrors the same warm/cold rule
    assert editsolve.classify(bucket, wire)
    assert not editsolve.classify(other, wire)
    assert not editsolve.classify(bucket, None)


def test_edit_distance_counts_carried_moves_only():
    anchor = np.array([1, 2, 3, 4], np.int32)
    emap = np.array([0, -1, 2, 3], np.int32)   # event 1 is new
    final = np.array([1, 9, 9, 4], np.int32)
    # event 0 kept, event 1 NEW (ignored), event 2 moved, event 3 kept
    assert editsolve.edit_distance(final, anchor, emap) == 1
    assert editsolve.edit_distance(final, None, emap) is None
    assert editsolve.edit_distance(final, anchor, None) is None


# ------------------------------------------------------------ service e2e

def test_service_edit_end_to_end_warm():
    reg = obs_metrics.REGISTRY
    before_edit = reg.counter("serve.jobs_edit").value
    before_dem = reg.counter("serve.jobs_edit_demoted").value

    p = _base_problem(seed=91)
    buf = io.StringIO()
    svc = SolveService(_cfg(), out=buf)
    svc.submit(p, job_id="base", seed=5, generations=40)
    svc.step()
    svc.scheduler.flush_resident("ship")
    wire = json.loads(json.dumps(svc.queue.get("base").ship.pack()))
    svc.drive()

    ops = [{"op": "add_event", "students": [2], "features": []},
           {"op": "set_attendance", "event": 1, "student": 3,
            "value": 1}]
    svc.submit(None, job_id="ed", seed=6, generations=10,
               edit={"base": {"tim": dump_tim(p)}, "base_id": "base",
                     "ops": ops, "snapshot": wire, "w_anchor": 1})
    svc.drive()
    svc.close()
    assert svc.state("ed") == JobState.DONE

    res = svc.result("ed")
    assert res["mode"] == "edit"
    assert res["edit_of"] == "base"
    assert res["edit_demoted"] is False
    assert isinstance(res["edit_distance"], int)
    # ISSUE 19 acceptance: the same-bucket path never demotes
    assert reg.counter("serve.jobs_edit").value == before_edit + 1
    assert reg.counter("serve.jobs_edit_demoted").value == before_dem

    recs = _records(buf)
    evs = {r["jobEntry"]["event"]: r["jobEntry"] for r in recs
           if "jobEntry" in r and r["jobEntry"]["job"] == "ed"}
    assert evs["admitted"]["mode"] == "edit"
    assert evs["admitted"]["edit_of"] == "base"
    assert evs["done"]["mode"] == "edit"
    assert evs["done"]["edit_distance"] == res["edit_distance"]
    assert "demoted" not in evs["admitted"]

    # tt stats reads the same stream: edit jobs get their own row
    text = logstats.summarize(recs)
    assert "[edit]" in text
    assert "edit: 1 jobs" in text
    assert "edit_distance" in text


def test_edit_w_zero_cold_stream_identical_to_plain_solve():
    """ISSUE 19 acceptance: a w_anchor=0 edit with no base snapshot
    (the demoted/cold leg) produces a solver record stream identical
    to a plain solve of the edited instance — the anchored machinery
    is invisible when inert."""
    p = _base_problem(seed=101)
    ops = [{"op": "set_attendance", "event": 0, "student": 1,
            "value": 1},
           {"op": "set_room_size", "room": 2, "size": 3}]
    edited, _ = editsolve.apply_ops(p, ops)

    def solver_stream(buf):
        keep = ("logEntry", "solution", "runEntry")
        return jsonl.strip_timing(
            [r for r in _records(buf) if next(iter(r)) in keep])

    buf_a = io.StringIO()
    svc_a = SolveService(_cfg(), out=buf_a)
    svc_a.submit(edited, job_id="j", seed=9, generations=12)
    svc_a.drive()
    svc_a.close()

    buf_b = io.StringIO()
    svc_b = SolveService(_cfg(), out=buf_b)
    svc_b.submit(None, job_id="j", seed=9, generations=12,
                 edit={"base": {"tim": dump_tim(p)}, "ops": ops,
                       "w_anchor": 0})
    svc_b.drive()
    svc_b.close()
    assert svc_b.result("j")["edit_demoted"] is True

    assert solver_stream(buf_a) == solver_stream(buf_b)


# ----------------------------------------------------------- obs surface

def test_usage_entry_mode_tag_is_additive():
    buf = io.StringIO()
    ledger = obs_usage.UsageLedger(registry=MetricsRegistry(),
                                   out=buf)
    ledger.final("e1", "acme", {"gens": 5.0}, mode="edit")
    ledger.final("p1", "acme", {"gens": 7.0}, mode="solve")
    ledger.final("p2", "acme", {"gens": 2.0})
    ledger.drain()
    totals = {}
    for rec in _records(buf):
        body = rec.get("usageEntry", {})
        if body.get("event") == "total":
            totals[body["job"]] = body
    assert totals["e1"]["mode"] == "edit"
    # default-mode records keep the pre-edit shape byte-for-byte
    assert "mode" not in totals["p1"]
    assert "mode" not in totals["p2"]
    # and the fold treats the tag as additive metadata
    text = obs_usage.summarize_entries(_records(buf))
    assert "acme" in text


def test_stats_edit_row_rendering():
    recs = [
        {"jobEntry": {"job": "e1", "event": "admitted",
                      "mode": "edit", "edit_of": "b1"}},
        {"jobEntry": {"job": "e1", "event": "done", "mode": "edit",
                      "edit_distance": 3, "best": 5, "gens": 10}},
        {"solution": {"job": "e1", "totalBest": 5, "feasible": True,
                      "totalTime": 1.25}},
        {"jobEntry": {"job": "e2", "event": "admitted",
                      "mode": "edit", "demoted": True}},
        {"jobEntry": {"job": "e2", "event": "done", "mode": "edit",
                      "best": 9, "gens": 10}},
        {"solution": {"job": "e2", "totalBest": 9, "feasible": True,
                      "totalTime": 2.5}},
        {"jobEntry": {"job": "s1", "event": "admitted"}},
        {"jobEntry": {"job": "s1", "event": "done", "best": 7,
                      "gens": 4}},
    ]
    text = logstats.summarize(recs)
    assert "e1 [edit]:" in text
    assert "e2 [edit, demoted]:" in text
    assert "edit: 2 jobs (1 demoted)" in text
    assert "edit_distance p50 3 max 3" in text
    # the plain job's line keeps the legacy shape
    assert "s1: admitted->done" in text


# ------------------------------------------------- fleet: settled base


def test_gateway_edit_of_settled_base_warm_starts():
    """`--edit-of` a base job that already SETTLED at the gateway
    (payload released, snapshot cache dropped) still warm-starts:
    the gateway resolves the instance from the retained edit basis
    and grabs the base's FINAL ship unit live from its owner — the
    replica keeps a terminal job's ship unit exactly for this."""
    import time

    from timetabling_ga_tpu.fleet.gateway import Gateway
    from timetabling_ga_tpu.fleet.replicas import (
        http_json, in_process_replica)
    from timetabling_ga_tpu.runtime.config import FleetConfig

    p = _base_problem(seed=23, n_events=12)
    rep, handle = in_process_replica(
        _cfg(http="127.0.0.1:0", quantum=5), "ed0")
    gw = Gateway(FleetConfig(replicas=[handle.url],
                             listen="127.0.0.1:0", probe_every=0.1,
                             poll_every=0.05, dead_after=2),
                 [handle]).start()
    try:
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "gb", "seed": 3,
                   "generations": 10})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            base = gw.jobs.get("gb")
            if (base is not None and base.state == "done"
                    and base.payload is None):     # settled: released
                break
            time.sleep(0.05)
        base = gw.jobs["gb"]
        assert base.state == "done" and base.payload is None
        assert base.edit_basis is not None and "tim" in base.edit_basis
        assert base.snap is None                   # cache share dropped

        ops = [{"op": "set_attendance", "event": 1, "student": 0,
                "value": 1}]
        http_json("POST", gw.url + "/v1/solve",
                  {"id": "ge", "seed": 4, "generations": 10,
                   "edit": {"base": "gb", "ops": ops, "w_anchor": 1}})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            view = http_json("GET", gw.url + "/v1/jobs/ge",
                             ok=(200,))
            if view["state"] in ("done", "failed"):
                break
            time.sleep(0.05)
        assert view["state"] == "done", view.get("error")
        res = view["result"]
        assert res["mode"] == "edit"
        assert res["edit_of"] == "gb"
        # the warm path: the live final-wire grab made the transplant
        # possible — no demotion
        assert res["edit_demoted"] is False
        assert isinstance(res["edit_distance"], int)
    finally:
        gw.close()
        rep.kill()
