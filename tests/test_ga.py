"""GA operator and generation-loop tests (ops/moves.py, ops/ga.py).

Property tests per SURVEY section 4.2: move invariants (every event keeps
exactly one slot/room; swaps preserve the slot multiset), selection and
crossover semantics, and an end-to-end evolution run that must reach
feasibility on an easy instance.
"""

import numpy as np
import jax
import jax.numpy as jnp

from timetabling_ga_tpu.ops import fitness, ga, moves
from timetabling_ga_tpu.problem import derive, random_instance
from tests.conftest import random_assignment


def _one_solution(problem, seed=0):
    rng = np.random.default_rng(seed)
    slots, rooms = random_assignment(rng, problem, 1)
    return jnp.asarray(slots[0]), jnp.asarray(rooms[0])


def test_move1_semantics(small_problem):
    pa = small_problem.device_arrays()
    slots, rooms = _one_solution(small_problem)
    e, t = jnp.int32(3), jnp.int32(17)
    s2, r2 = moves.move1(pa, slots, rooms, e, t)
    assert int(s2[3]) == 17
    # all other events untouched
    keep = np.ones(small_problem.n_events, bool)
    keep[3] = False
    np.testing.assert_array_equal(np.asarray(s2)[keep],
                                  np.asarray(slots)[keep])
    np.testing.assert_array_equal(np.asarray(r2)[keep],
                                  np.asarray(rooms)[keep])
    # moved event got a suitable room (instance guarantees one exists)
    assert small_problem.possible[3][int(r2[3])]


def test_move2_swaps_slots(small_problem):
    pa = small_problem.device_arrays()
    slots, rooms = _one_solution(small_problem, 1)
    e1, e2 = jnp.int32(2), jnp.int32(9)
    s2, _ = moves.move2(pa, slots, rooms, e1, e2)
    assert int(s2[2]) == int(slots[9])
    assert int(s2[9]) == int(slots[2])
    # slot multiset preserved
    assert sorted(np.asarray(s2).tolist()) == sorted(
        np.asarray(slots).tolist())


def test_move3_cycles_slots(small_problem):
    pa = small_problem.device_arrays()
    slots, rooms = _one_solution(small_problem, 2)
    s2, _ = moves.move3(pa, slots, rooms, jnp.int32(0), jnp.int32(4),
                        jnp.int32(7))
    assert int(s2[0]) == int(slots[4])
    assert int(s2[4]) == int(slots[7])
    assert int(s2[7]) == int(slots[0])
    assert sorted(np.asarray(s2).tolist()) == sorted(
        np.asarray(slots).tolist())


def test_random_move_only_move1(small_problem):
    """With p1=1, p2=p3=0 every move is a Move1: at most one slot entry
    changes (Solution.cpp:441-469 type sampling)."""
    pa = small_problem.device_arrays()
    slots, rooms = _one_solution(small_problem, 3)
    for i in range(10):
        key = jax.random.key(i)
        s2, _ = moves.random_move(pa, key, slots, rooms, 1.0, 0.0, 0.0)
        assert int(jnp.sum(s2 != slots)) <= 1


def test_random_move_never_move1(small_problem):
    """With p1=0 the slot multiset is always preserved (Move2/Move3 are
    permutations)."""
    pa = small_problem.device_arrays()
    slots, rooms = _one_solution(small_problem, 4)
    for i in range(10):
        key = jax.random.key(100 + i)
        s2, _ = moves.random_move(pa, key, slots, rooms, 0.0, 1.0, 1.0)
        assert sorted(np.asarray(s2).tolist()) == sorted(
            np.asarray(slots).tolist())


def test_tournament_picks_best_of_draws(small_problem):
    penalty = jnp.asarray(np.arange(100, 0, -1, dtype=np.int32))  # best=99
    scv = jnp.zeros(100, jnp.int32)
    for i in range(20):
        key = jax.random.key(i)
        w = int(ga.tournament(key, penalty, scv, 5))
        draws = np.asarray(jax.random.randint(key, (5,), 0, 100))
        assert w == draws[np.argmin(np.asarray(penalty)[draws])]


def test_tournament_breaks_penalty_ties_by_scv():
    """At equal penalty the tournament must prefer lower scv — the
    reported-metric (hcv*1e6+scv) tie-break (fitness.lex_order): when
    hcv sits at an infeasibility floor the race is decided by scv."""
    penalty = jnp.full((50,), 1_000_005, jnp.int32)
    scv = jnp.asarray(np.arange(50, 0, -1, dtype=np.int32))
    for i in range(20):
        key = jax.random.key(200 + i)
        w = int(ga.tournament(key, penalty, scv, 5))
        draws = np.asarray(jax.random.randint(key, (5,), 0, 50))
        assert int(scv[w]) == int(np.asarray(scv)[draws].min())


def test_lex_order_sorts_reported_metric():
    """fitness.lex_order == ascending sort of hcv*1e6+scv whenever the
    internal penalty majorizes (it always does: feasible penalty IS scv
    and any hcv difference dominates the infeasible offset)."""
    rng = np.random.default_rng(3)
    hcv = rng.integers(0, 4, 64).astype(np.int32)
    scv = rng.integers(0, 300, 64).astype(np.int32)
    pen = np.where(hcv == 0, scv, 1_000_000 + hcv).astype(np.int32)
    order = np.asarray(fitness.lex_order(jnp.asarray(pen),
                                         jnp.asarray(scv)))
    reported = hcv.astype(np.int64) * 1_000_000 + scv
    assert (np.diff(reported[order]) >= 0).all()


def test_init_population_sorted_and_valid(small_problem):
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 32)
    pen = np.asarray(st.penalty)
    assert (np.diff(pen) >= 0).all()
    # penalties consistent with a fresh evaluation
    pen2, hcv2, scv2 = fitness.batch_penalty(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(pen, np.asarray(pen2))
    # all rooms suitable (greedy matcher; instance has suitable rooms)
    possible = small_problem.possible
    sl = np.asarray(st.slots)
    rm = np.asarray(st.rooms)
    for p in range(32):
        for e in range(small_problem.n_events):
            if possible[e].any():
                assert possible[e][rm[p, e]]


def test_generation_monotone_best(small_problem):
    """mu+lambda truncation can never worsen the best penalty."""
    pa = small_problem.device_arrays()
    cfg = ga.GAConfig(pop_size=16)
    st = ga.init_population(pa, jax.random.key(1), 16)
    best = int(st.penalty[0])
    for i in range(5):
        st = ga.generation(pa, jax.random.key(10 + i), st, cfg)
        nb = int(st.penalty[0])
        assert nb <= best
        best = nb


def test_run_reaches_feasibility_easy_instance():
    """End-to-end: an easy instance (few conflicts, plentiful rooms) must
    reach hcv==0 within a small generation budget (SURVEY section 4.5)."""
    problem = random_instance(11, n_events=20, n_rooms=6, n_features=2,
                              n_students=15, attend_prob=0.08)
    pa = problem.device_arrays()
    cfg = ga.GAConfig(pop_size=32)
    st = ga.init_population(pa, jax.random.key(2), 32)
    st, trace = ga.run(pa, jax.random.key(3), st, cfg, 60)
    assert int(st.hcv[0]) == 0, int(st.penalty[0])
    # trace is the per-generation best and is monotone non-increasing
    tr = np.asarray(trace)
    assert (np.diff(tr) <= 0).all()
