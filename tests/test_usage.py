"""tt-meter (ISSUE 14): per-job / per-tenant usage metering and
capacity attribution, fleet-wide.

The acceptance properties pinned here:

  1. CONSERVATION — `obs/usage.split` shares sum BIT-EXACTLY to the
     quantized total (in float and through JSON), and every emitted
     per-dispatch usageEntry's lane shares sum to its dispatch totals
     for each conserved component;
  2. IDENTITY — the record stream is identical with metering on or
     off (usageEntry is a TIMING record);
  3. CONTINUITY — a job resumed from a shipped snapshot CONTINUES its
     meter (the wire usage cursor): its settle total equals an
     uninterrupted solve's deterministic components, while the
     survivor's ledger counts only its own deltas;
  4. ISOLATION — a dead or hung ledger (fault site `usage`) never
     stalls dispatch, settlement, or writer drain;
  5. FLEET — replicas serve GET /v1/usage, the gateway aggregates
     fleet-wide (a dead replica's last-scraped ledger included), and
     a killed-and-resumed job's tenant totals on the gateway match an
     uninterrupted solve's modulo the re-run quantum;
  6. RENDERING — `tt usage` (logs + --json) and `tt stats`'s
     `== usage` section.
"""

import io
import json
import random
import time

import pytest

from timetabling_ga_tpu.fleet.gateway import _PAYLOAD_KEYS, Gateway
from timetabling_ga_tpu.fleet.replicas import (
    http_json, in_process_replica)
from timetabling_ga_tpu.obs import usage as obs_usage
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, ServeConfig, parse_serve_args)
from timetabling_ga_tpu.serve.service import SolveService

_PA = random_instance(71, n_events=12, n_rooms=3, n_features=2,
                      n_students=8, attend_prob=0.2)
_PB = random_instance(72, n_events=40, n_rooms=4, n_features=2,
                      n_students=30, attend_prob=0.1)


@pytest.fixture(autouse=True)
def _no_fault_plan():
    faults.install(None)
    yield
    faults.install(None)


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _fleet_cfg(urls, **kw):
    kw.setdefault("listen", "127.0.0.1:0")
    kw.setdefault("probe_every", 0.1)
    kw.setdefault("poll_every", 0.05)
    kw.setdefault("dead_after", 2)
    return FleetConfig(replicas=list(urls), **kw)


def _records(buf):
    return [json.loads(x) for x in buf.getvalue().splitlines()]


def _dispatch_entries(recs):
    return [r["usageEntry"] for r in recs
            if "usageEntry" in r and "lanes" in r["usageEntry"]]


# ------------------------------------------------------------- unit tier


def test_split_conservation_and_proportionality():
    rng = random.Random(7)
    for _ in range(2000):
        n = rng.randint(1, 8)
        total = rng.choice([rng.uniform(0, 1), rng.uniform(0, 1e9),
                            rng.uniform(0, 1e16),
                            float(rng.randint(0, 10 ** 12))])
        ws = [rng.choice([0, rng.randint(0, 100)]) for _ in range(n)]
        qt, shares = obs_usage.split(total, ws)
        # bit-exact, in float AND through a JSON round trip
        assert sum(shares) == qt
        assert sum(json.loads(json.dumps(shares))) \
            == json.loads(json.dumps(qt))
        # quantization error lands on the total once, sub-quantum
        assert abs(qt - total) <= max(obs_usage.QUANTUM,
                                      abs(total) / 2 ** 50)
        # zero-weight lanes get zero (unless every weight is zero)
        if any(ws):
            for w, s in zip(ws, shares):
                if w == 0:
                    assert s == 0.0
    # integer grid (FLOPs): totals preserved exactly
    qt, shares = obs_usage.split(7.0, [1, 1, 1], quantum=1.0)
    assert qt == 7.0 and sum(shares) == 7.0
    assert sorted(shares) == [2.0, 2.0, 3.0]
    # proportionality on the integer grid
    qt, shares = obs_usage.split(800.0, [3, 5], quantum=1.0)
    assert shares == [300.0, 500.0]
    # degenerate shapes
    assert obs_usage.split(5.0, []) == (0.0, [])
    qt, shares = obs_usage.split(10.0, [0, 0])
    assert qt == 10.0 and sum(shares) == 10.0   # even split fallback


def test_tenant_label():
    assert obs_usage.tenant_label(None) == "default"
    assert obs_usage.tenant_label("") == "default"
    assert obs_usage.tenant_label("  ") == "default"
    assert obs_usage.tenant_label("acme") == "acme"
    assert obs_usage.tenant_label("bob corp!") == "bob_corp_"
    assert len(obs_usage.tenant_label("x" * 200)) == 64


def _lane(job, tenant, **kw):
    d = obs_usage.new_usage()
    d.update(kw)
    return {"job": job, "tenant": tenant, **d}


def test_ledger_units():
    reg = MetricsRegistry()
    buf = io.StringIO()
    ledger = obs_usage.UsageLedger(registry=reg, out=buf,
                                   now=lambda: 1.5)
    ledger.job("j1", "acme")
    ledger.job("j2", "acme")
    ledger.job("j3", "zeta")
    ledger.dispatch({
        "dispatch": 0, "gens": 8, "device_seconds": 1.0,
        "compile_seconds": 0.5, "flops": 100.0,
        "lanes": [_lane("j1", "acme", gens=5, dispatches=1,
                        device_seconds=0.625, compile_seconds=0.3125,
                        flops=62.5, queue_seconds=0.25),
                  _lane("j3", "zeta", gens=3, dispatches=1,
                        device_seconds=0.375, compile_seconds=0.1875,
                        flops=37.5, park_seconds=0.5)]})
    ledger.final("j1", "acme", {"gens": 5, "dispatches": 1,
                                "device_seconds": 0.625, "flops": 62.5})
    assert ledger.drain()
    totals = ledger.totals()
    assert totals["acme"]["jobs"] == 2
    assert totals["acme"]["gens"] == 5
    assert totals["acme"]["device_seconds"] == 0.625
    assert totals["zeta"]["jobs"] == 1
    assert totals["zeta"]["park_seconds"] == 0.5
    # live counters (what obs/history.py samples for demand curves)
    assert reg.counter("usage.tenant.acme.gens").value == 5
    assert reg.counter("usage.tenant.acme.jobs").value == 2
    assert reg.counter("usage.tenant.zeta.flops").value == 37.5
    assert reg.counter("usage.dispatches").value == 1
    ledger.close()
    recs = _records(buf)
    assert len(_dispatch_entries(recs)) == 1
    tot = [r["usageEntry"] for r in recs
           if r.get("usageEntry", {}).get("event") == "total"]
    assert tot and tot[0]["job"] == "j1" and tot[0]["gens"] == 5
    assert tot[0]["ts"] == 1.5


def test_fold_entries_render_and_aggregate():
    buf = io.StringIO()
    ledger = obs_usage.UsageLedger(registry=MetricsRegistry(), out=buf)
    ledger.dispatch({
        "dispatch": 0, "gens": 8, "device_seconds": 1.0,
        "compile_seconds": 0.0, "flops": 100.0,
        "lanes": [_lane("j1", "acme", gens=5, device_seconds=0.625,
                        flops=62.5, dispatches=1),
                  _lane("j2", "zeta", gens=3, device_seconds=0.375,
                        flops=37.5, dispatches=1)]})
    ledger.final("j1", "acme", {"gens": 10, "flops": 125.0,
                                "dispatches": 2})
    ledger.drain()
    ledger.close()
    report = obs_usage.fold_entries(_records(buf))
    # the settle total overrides the job's delta sum (authoritative,
    # cumulative across incarnations)
    assert report["jobs"]["j1"]["usage"]["gens"] == 10
    assert report["jobs"]["j2"]["usage"]["gens"] == 3
    # tenant totals come from the deltas (each metered exactly once)
    assert report["tenants"]["acme"]["gens"] == 5
    assert report["tenants"]["acme"]["jobs"] == 1
    text = obs_usage.render(report)
    assert "== usage by tenant" in text and "acme" in text
    assert "j2 (zeta)" in text
    # tenant filter
    only = obs_usage.render(report, tenant="zeta")
    assert "acme" not in only and "zeta" in only

    # fleet aggregation: tenants SUM, jobs take the highest-progress
    # view, a dead replica's cached payload still contributes
    p0 = {"tenants": {"acme": dict(obs_usage.new_usage(), jobs=1,
                                   gens=10, flops=50.0)},
          "jobs": {"r": {"tenant": "acme", "state": "preempted",
                         "gens": 10,
                         "usage": dict(obs_usage.new_usage(),
                                       gens=10)}}}
    p1 = {"tenants": {"acme": dict(obs_usage.new_usage(), jobs=0,
                                   gens=30, flops=150.0)},
          "jobs": {"r": {"tenant": "acme", "state": "done",
                         "gens": 40,
                         "usage": dict(obs_usage.new_usage(),
                                       gens=40)}}}
    agg = obs_usage.aggregate([("r0", True, p0), ("r1", False, p1),
                               ("r2", False, None)])
    assert agg["tenants"]["acme"]["gens"] == 40
    assert agg["tenants"]["acme"]["flops"] == 200.0
    assert agg["tenants"]["acme"]["jobs"] == 1
    assert agg["jobs"]["r"]["usage"]["gens"] == 40
    assert agg["jobs"]["r"]["replica"] == "r1"
    assert agg["replicas"]["r0"]["dead"] is True
    assert agg["replicas"]["r2"]["scraped"] is False


def test_ledger_tenant_cardinality_cap():
    """The tenant tag is client-controlled: past TENANTS_CAP distinct
    labels, NEW tenants fold into the shared overflow bucket — still
    metered and conserved, honestly counted, never unbounded."""
    reg = MetricsRegistry()
    ledger = obs_usage.UsageLedger(registry=reg, tenants_cap=2)
    for i, tenant in enumerate(("t0", "t1", "t2", "t3")):
        ledger.job(f"j{i}", tenant)
        ledger.dispatch({"dispatch": i, "gens": 1,
                         "device_seconds": 0.0, "compile_seconds": 0.0,
                         "flops": 0.0,
                         "lanes": [_lane(f"j{i}", tenant, gens=1,
                                         dispatches=1)]})
    ledger.drain()
    ledger.close()
    totals = ledger.totals()
    assert set(totals) == {"t0", "t1", obs_usage.OVERFLOW_TENANT}
    assert totals[obs_usage.OVERFLOW_TENANT]["jobs"] == 2
    assert totals[obs_usage.OVERFLOW_TENANT]["gens"] == 2
    # nothing lost: the fold conserves the fleet-wide sums
    assert sum(t["gens"] for t in totals.values()) == 4
    assert reg.counter("usage.tenant_overflow").value > 0
    assert reg.counter(
        f"usage.tenant.{obs_usage.OVERFLOW_TENANT}.gens").value == 2


def test_respawned_replica_keeps_dead_incarnations_ledger():
    """A respawned worker answers /v1/usage with a fresh, near-empty
    ledger; the handle folds the dead incarnation's last scrape into
    `usage_base` so the gateway's bill never loses metered work."""
    from timetabling_ga_tpu.fleet.replicas import (ReplicaHandle,
                                                   ReplicaSet)
    h = ReplicaHandle("r0", "http://127.0.0.1:1",
                      respawn=lambda: None)
    h.last_usage = {
        "tenants": {"acme": dict(obs_usage.new_usage(), jobs=1,
                                 gens=150, flops=50.0)},
        "jobs": {"j": {"tenant": "acme", "state": "running",
                       "gens": 150,
                       "usage": dict(obs_usage.new_usage(),
                                     gens=150)}}}
    rs = ReplicaSet([h], max_restarts=1)
    rs._declare_dead(h)
    assert not h.dead and h.restarts == 1      # respawned, not dead
    assert h.last_usage is None                # fresh incarnation
    assert h.usage_payload()["tenants"]["acme"]["gens"] == 150
    # the new incarnation's scrape ADDS to the retired history
    h.last_usage = {
        "tenants": {"acme": dict(obs_usage.new_usage(), jobs=0,
                                 gens=450, flops=150.0)},
        "jobs": {"j": {"tenant": "acme", "state": "done", "gens": 600,
                       "usage": dict(obs_usage.new_usage(),
                                     gens=600)}}}
    merged = h.usage_payload()
    assert merged["tenants"]["acme"]["gens"] == 600
    assert merged["tenants"]["acme"]["jobs"] == 1
    # per-job: highest-progress view wins, never the sum
    assert merged["jobs"]["j"]["usage"]["gens"] == 600


def test_static_restart_detected_by_backward_counters():
    """The documented PR-14 gap, closed (ISSUE 15 satellite): a STATIC
    (non-spawned) replica restarted behind our back has no respawn
    event to fold its ledger on — the prober now detects the restart
    by the BACKWARD-moving usage counters of the fresh scrape
    (obs/usage.progress, the flight-recorder dump-counter discipline)
    and folds the dead incarnation's cached payload into usage_base,
    so the bill survives external restarts too."""
    from timetabling_ga_tpu.fleet.replicas import ReplicaHandle
    h = ReplicaHandle("r0", "http://127.0.0.1:1")   # static: no proc,
    #                                                 no respawn
    old = {"tenants": {"acme": dict(obs_usage.new_usage(), jobs=2,
                                    gens=300, flops=90.0)},
           "jobs": {}}
    h.note_usage(old)
    # forward motion: a normal scrape replaces, never folds
    grown = {"tenants": {"acme": dict(obs_usage.new_usage(), jobs=2,
                                      gens=400, flops=120.0)},
             "jobs": {}}
    h.note_usage(grown)
    assert h.usage_base is None
    assert h.usage_payload()["tenants"]["acme"]["gens"] == 400
    # the restart: counters moved BACKWARD — the fresh incarnation's
    # near-empty ledger must ADD to the cached one, not replace it
    fresh = {"tenants": {"acme": dict(obs_usage.new_usage(), jobs=1,
                                      gens=50, flops=10.0)},
             "jobs": {}}
    h.note_usage(fresh)
    assert h.usage_base is not None
    merged = h.usage_payload()
    assert merged["tenants"]["acme"]["gens"] == 450
    assert merged["tenants"]["acme"]["jobs"] == 3
    assert merged["tenants"]["acme"]["flops"] == 130.0
    # progress() is the monotone restart detector itself
    assert obs_usage.progress(fresh) < obs_usage.progress(grown)
    assert obs_usage.progress({}) == 0.0


def test_resubmit_header_does_not_rebill_job():
    """A gateway RESEND (X-TT-Resubmit — failover replay/resume)
    admits and METERS the job but never re-counts it in the tenant's
    `jobs` ledger: the first admission already billed it."""
    rep, h = in_process_replica(_serve_cfg(http="127.0.0.1:0"), "rs0")
    try:
        http_json("POST", h.url + "/v1/solve",
                  {"tim": dump_tim(_PA), "id": "rj", "seed": 3,
                   "generations": 10, "tenant": "acme"},
                  headers={"X-TT-Resubmit": "1"})
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            v = http_json("GET", h.url + "/v1/jobs/rj?records=0",
                          ok=(200,))
            if v.get("state") in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        payload = http_json("GET", h.url + "/v1/usage", ok=(200,))
        acme = payload["tenants"]["acme"]
        assert acme["gens"] == 10          # work metered as usual
        assert acme["jobs"] == 0           # but never re-billed
        assert payload["jobs"]["rj"]["usage"]["gens"] == 10
    finally:
        rep.kill()


# ------------------------------------------------------------ serve tier


def test_serve_ab_identity_and_conservation():
    """Metering on vs off: strip_timing streams identical; the on
    leg's usageEntry dispatch records conserve every component; the
    unequal-gens pack splits proportionally."""
    jobs = [("a", _PA, 3, 3, "acme"), ("b", _PA, 4, 10, "acme"),
            ("c", _PB, 5, 10, "zeta")]

    def leg(usage):
        buf = io.StringIO()
        svc = SolveService(_serve_cfg(obs=True, usage=usage), out=buf,
                           registry=MetricsRegistry())
        for jid, p, seed, gens, tenant in jobs:
            svc.submit(p, job_id=jid, seed=seed, generations=gens,
                       tenant=tenant)
        svc.drive()
        svc.close()
        return svc, _records(buf)

    svc_off, recs_off = leg(False)
    svc_on, recs_on = leg(True)
    assert jsonl.strip_timing(recs_off) == jsonl.strip_timing(recs_on)
    assert not _dispatch_entries(recs_off)
    disp = _dispatch_entries(recs_on)
    assert disp
    for u in disp:
        for f in ("gens", "device_seconds", "compile_seconds",
                  "flops"):
            assert sum(lane[f] for lane in u["lanes"]) == u[f], (f, u)
    # the packed a+b dispatch (gens 3 vs 5) splits flops 3:5 on the
    # integer grid
    packed = next(u for u in disp if len(u["lanes"]) == 2
                  and {x["job"] for x in u["lanes"]} == {"a", "b"})
    by_job = {x["job"]: x for x in packed["lanes"]}
    assert by_job["a"]["gens"] == 3 and by_job["b"]["gens"] == 5
    if packed["flops"]:
        assert by_job["a"]["flops"] \
            == obs_usage.split(packed["flops"], [3, 5],
                               quantum=1.0)[1][0]
    # results: the meter travels with the result only when metering on
    assert "usage" not in svc_off.queue.get("a").result
    res = svc_on.queue.get("b").result
    assert res["tenant"] == "acme" and res["usage"]["gens"] == 10
    # tenant ledgers: gens are deterministic and exact
    totals = svc_on.usage.totals()
    assert totals["acme"]["gens"] == 13 and totals["acme"]["jobs"] == 2
    assert totals["zeta"]["gens"] == 10 and totals["zeta"]["jobs"] == 1
    # the per-tenant counters live in the registry (what the history
    # ring samples into autoscaler demand curves)
    snap = svc_on.registry.snapshot()
    assert snap["counters"]["usage.tenant.acme.gens"] == 13
    assert snap["counters"]["usage.tenant.zeta.jobs"] == 1


def test_resume_meter_continuity():
    """The snapshot wire's usage cursor: a resumed job CONTINUES its
    meter — settle totals match an uninterrupted solve's deterministic
    components — while the survivor's ledger counts only its own
    deltas (fleet sums never double count)."""
    base_svc = SolveService(_serve_cfg(), out=io.StringIO(),
                            registry=MetricsRegistry())
    base_svc.submit(_PA, job_id="r", seed=3, generations=20,
                    tenant="acme")
    base_svc.drive()
    base_svc.close()
    base_usage = base_svc.queue.get("r").result["usage"]
    assert base_usage["gens"] == 20

    svc1 = SolveService(_serve_cfg(), out=io.StringIO(),
                        registry=MetricsRegistry())
    svc1.submit(_PA, job_id="r", seed=3, generations=20,
                tenant="acme")
    svc1.step()
    svc1.step()
    # park the device-resident group so the exported wire carries the
    # CURRENT usage cursor (snapshot-shipping requests flush first)
    svc1.scheduler.flush_resident("ship")
    ship = svc1.queue.get("r").ship
    wire = json.loads(json.dumps(ship.pack()))
    svc1.close()
    assert wire["usage"]["gens"] == 10     # the cursor rides the wire

    svc2 = SolveService(_serve_cfg(), out=io.StringIO(),
                        registry=MetricsRegistry())
    svc2.submit(_PA, job_id="r", seed=3, generations=20,
                snapshot=wire, tenant="acme")
    job = svc2.queue.get("r")
    assert job.usage["gens"] == 10         # seeded, not reset
    svc2.drive()
    svc2.close()
    res = svc2.queue.get("r").result
    assert res["usage"]["gens"] == base_usage["gens"]
    assert res["usage"]["flops"] == base_usage["flops"]
    assert res["usage"]["dispatches"] == base_usage["dispatches"]
    # the survivor's LEDGER has only the post-resume half, and did NOT
    # re-count the job (resumed admissions skip the jobs counter)
    totals = svc2.usage.totals()
    assert totals["acme"]["gens"] == 10
    assert totals["acme"]["jobs"] == 0


@pytest.mark.parametrize("action", ["die", "hang"])
def test_ledger_fault_isolation(action):
    """Fault site `usage`: a dead or hung ledger never stalls
    dispatch, settlement, or writer drain — jobs finish, the stream
    completes, and the INLINE per-job meter (the drive loop's own
    arithmetic) still reaches the result."""
    buf = io.StringIO()
    svc = SolveService(_serve_cfg(obs=True), out=buf,
                       registry=MetricsRegistry())
    faults.install(f"usage:1:{action}")
    t0 = time.monotonic()
    svc.submit(_PA, job_id="f", seed=3, generations=10,
               tenant="acme")
    svc.drive()
    faults.install(None)
    svc.close()
    assert time.monotonic() - t0 < 60      # nothing waited on the hang
    assert svc.queue.get("f").state == "done"
    res = svc.queue.get("f").result
    assert res["usage"]["gens"] == 10      # inline meter unaffected
    recs = _records(buf)
    assert any("solution" in r for r in recs)   # writer drained
    if action == "die":
        assert not svc.usage.alive()


# ------------------------------------------------------------ fleet tier


def test_v1_usage_endpoint_and_gateway_aggregation():
    """Replicas serve GET /v1/usage; the gateway aggregates
    fleet-wide off the prober's cache — and a DEAD replica's
    last-scraped ledger keeps contributing."""
    rep0, h0 = in_process_replica(_serve_cfg(http="127.0.0.1:0"), "u0")
    rep1, h1 = in_process_replica(_serve_cfg(http="127.0.0.1:0"), "u1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    jobs = [("ja", _PA, 3, "acme"), ("jb", _PA, 4, "acme"),
            ("jc", _PB, 5, "zeta")]
    try:
        for jid, p, seed, tenant in jobs:
            http_json("POST", gw.url + "/v1/solve",
                      {"tim": dump_tim(p), "id": jid, "seed": seed,
                       "generations": 10, "tenant": tenant})
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            views = {jid: http_json(
                "GET", f"{gw.url}/v1/jobs/{jid}?records=0", ok=(200,))
                for jid, *_ in jobs}
            if all(v["state"] == "done" for v in views.values()):
                break
            time.sleep(0.1)
        assert all(v["state"] == "done" for v in views.values())
        # the result carries tenant + meter through the fleet view
        full = http_json("GET", gw.url + "/v1/jobs/ja", ok=(200,))
        assert full["result"]["tenant"] == "acme"
        assert full["result"]["usage"]["gens"] == 10

        # each replica's own /v1/usage
        per_rep = []
        for h in (h0, h1):
            payload = http_json("GET", h.url + "/v1/usage", ok=(200,))
            per_rep.append(payload)
        rep_gens = sum(t.get("gens", 0)
                       for p in per_rep
                       for t in p["tenants"].values())
        assert rep_gens == 30              # deterministic, exact

        # gateway aggregation reaches the same totals once the prober
        # cache catches up
        deadline = time.monotonic() + 30
        agg = None
        while time.monotonic() < deadline:
            agg = http_json("GET", gw.url + "/v1/usage", ok=(200,))
            got = sum(t.get("gens", 0)
                      for t in agg["tenants"].values())
            if got == 30:
                break
            time.sleep(0.2)
        assert sum(t.get("gens", 0)
                   for t in agg["tenants"].values()) == 30
        assert agg["tenants"]["acme"]["jobs"] == 2
        assert agg["tenants"]["zeta"]["jobs"] == 1
        for jid, *_ in jobs:
            assert agg["jobs"][jid]["usage"]["gens"] == 10

        # kill one replica: its last-scraped ledger keeps feeding the
        # fleet totals (metered work never vanishes with its replica)
        rep0.kill()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if h0.dead:
                break
            time.sleep(0.1)
        assert h0.dead
        agg2 = http_json("GET", gw.url + "/v1/usage", ok=(200,))
        assert sum(t.get("gens", 0)
                   for t in agg2["tenants"].values()) == 30
        assert agg2["replicas"]["u0"]["dead"] is True
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


@pytest.mark.slow
def test_fleet_acceptance_killed_job_tenant_totals():
    """ISSUE 14 acceptance: kill a replica mid-job; after the
    failover RESUME the tenant's fleet-wide gens on the gateway match
    an uninterrupted solve's modulo the re-run quantum and the scrape
    cadence (the dead replica's LAST-scraped ledger + the survivor's
    continuation — on a fast CPU backend hundreds of generations fit
    inside one probe interval, so the tolerance is derived from the
    measured generation rate, not guessed), the tenant's jobs count
    stays 1 — a resumed job is never re-billed as a new job — and the
    job's own cumulative meter is exact."""
    gens_budget = 2000
    rep0, h0 = in_process_replica(_serve_cfg(http="127.0.0.1:0"), "k0")
    rep1, h1 = in_process_replica(_serve_cfg(http="127.0.0.1:0"), "k1")
    gw = Gateway(_fleet_cfg([h0.url, h1.url]), [h0, h1]).start()
    reps = {"k0": rep0, "k1": rep1}
    try:
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(_PA), "id": "ka", "seed": 3,
                   "generations": gens_budget, "tenant": "acme"})
        deadline = time.monotonic() + 120
        killed = None
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                j = gw.jobs.get("ka")
                owner, snap_gens = j.replica, j.snap_gens
            if owner in reps and snap_gens >= gens_budget // 2:
                # measure the generation rate: the honest tolerance is
                # what one scrape/poll interval of lag costs in gens
                g1 = reps[owner].svc.queue.get("ka").gens_done
                time.sleep(0.25)
                g2 = reps[owner].svc.queue.get("ka").gens_done
                rate = max(0.0, (g2 - g1) / 0.25)
                killed = owner
                reps[owner].kill()
                break
            time.sleep(0.005)
        assert killed, "never reached a kill point"
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            v = http_json("GET", gw.url + "/v1/jobs/ka?records=0",
                          ok=(200,))
            if v["state"] in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        full = http_json("GET", gw.url + "/v1/jobs/ka", ok=(200,))
        assert full["result"]["resumed_at"] > 0    # resumed, not replayed

        agg = http_json("GET", gw.url + "/v1/usage", ok=(200,))
        acme = agg["tenants"]["acme"]
        # dead ledger (scraped a bounded-but-loaded-box-dependent
        # moment before the kill: a probe round is several HTTP calls
        # across two replicas on two cores) + survivor deltas: within
        # the re-run quantum plus a TWO-second lag window of the
        # uninterrupted budget — and far from the two failure modes
        # this pins (double-billed history ~ 1.5x budget; dropped
        # dead ledger ~ 0.5x budget). If the box dispatches so fast
        # that the lag window swamps the signal, skip rather than
        # assert vacuously.
        slack = int(rate * 2.0) + 4 * 5
        if slack >= gens_budget * 0.45:
            pytest.skip(f"dispatch rate {rate:.0f} gens/s too high "
                        f"to bound scrape lag on this box")
        assert abs(acme["gens"] - gens_budget) <= slack, (acme, slack)
        assert acme["jobs"] == 1                   # never re-billed
        # and the job's own cumulative meter is exact (cursor + tail)
        assert full["result"]["usage"]["gens"] == gens_budget
    finally:
        gw.close()
        rep0.kill()
        rep1.kill()


# -------------------------------------------------------- rendering tier


def test_tt_usage_and_tt_stats_rendering(tmp_path, capsys):
    log = tmp_path / "serve.jsonl"
    with open(log, "w") as fh:
        svc = SolveService(_serve_cfg(obs=True), out=fh,
                           registry=MetricsRegistry())
        svc.submit(_PA, job_id="ra", seed=3, generations=10,
                   tenant="acme")
        svc.submit(_PA, job_id="rb", seed=4, generations=5,
                   tenant="zeta")
        svc.drive()
        svc.close()

    assert obs_usage.main_usage([str(log)]) == 0
    out = capsys.readouterr().out
    assert "== usage by tenant" in out
    assert "acme" in out and "zeta" in out
    assert "ra (acme)" in out

    assert obs_usage.main_usage([str(log), "--json",
                                 "--tenant", "acme"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert list(doc["tenants"]) == ["acme"]
    assert doc["jobs"]["ra"]["usage"]["gens"] == 10
    assert "rb" not in doc["jobs"]

    from timetabling_ga_tpu.obs.logstats import main_stats
    assert main_stats([str(log)]) == 0
    out = capsys.readouterr().out
    assert "== usage by tenant" in out
    assert "rb (zeta)" in out

    with pytest.raises(SystemExit):
        obs_usage.main_usage([])
    assert obs_usage.main_usage(["-h"]) == 0
    capsys.readouterr()


# ---------------------------------------------------- flags & plumbing


def test_flags_and_wire_plumbing():
    assert parse_serve_args([]).usage is True
    assert parse_serve_args(["--no-usage"]).usage is False
    # the fault site is part of the closed, validated set
    assert faults.FaultPlan.parse("usage:1:die") is not None
    with pytest.raises(faults.FaultPlanError):
        faults.FaultPlan.parse("usages:1:die")
    # the tenant tag survives the gateway payload filter (routing and
    # failover resends keep it)
    assert "tenant" in _PAYLOAD_KEYS
    # tt submit grew --tenant
    from timetabling_ga_tpu.fleet import client
    import inspect
    assert "--tenant" in inspect.getsource(client.main_submit)
    # usageEntry is a TIMING record: strip_timing drops it
    assert jsonl.strip_timing([{"usageEntry": {"gens": 1}},
                               {"runEntry": {"totalBest": 1,
                                             "feasible": True}}]) \
        == [{"runEntry": {"totalBest": 1, "feasible": True}}]
