"""tt-flight (ISSUE 13): metrics history rings + the incident flight
recorder, fleet-wide.

The acceptance properties pinned here:

  1. HISTORY — the ring's window queries (`rate`, `mean_over`,
     `sustained` — the documented autoscaler trigger primitive) answer
     correctly, `sustained` refuses uncovered windows, and
     `GET /metrics/history?window=S` serves the ring read-only on the
     existing pull front;
  2. RECORDER — triggers (manual, faultEntry on the stream, a /readyz
     reason flipping on) produce rate-limited, retained, self-contained
     bundles; the span ring honors its byte budget; the record tee
     changes nothing about the stream;
  3. IDENTITY — an engine run with recorder+sampler ON emits a JSONL
     stream bit-identical (strip_timing domain) to recorder OFF;
  4. ISOLATION — a hung or dead sampler/dump thread (`history` /
     `flight_dump` sites) never stalls dispatch, settlement, or writer
     drain;
  5. FLEET (slow) — an injected replica fault during a routed solve
     produces a replica bundle AND a stitched gateway bundle sharing
     the job's XFLOW id; `tt incident` renders a Perfetto-loadable
     timeline from the stitched bundle; streams stay identical to the
     unrouted recorder-off baseline.
"""

import io
import json
import os
import time
import urllib.request

import pytest

from timetabling_ga_tpu.obs import flight as obs_flight
from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs.history import HistoryRing
from timetabling_ga_tpu.obs.logstats import summarize
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.obs.spans import XFLOW_BASE
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import (
    FleetConfig, RunConfig, ServeConfig, parse_args, parse_fleet_args,
    parse_serve_args)

from tests.conftest import TIM_FIXTURE


@pytest.fixture(autouse=True)
def _no_fault_plan():
    faults.install(None)
    yield
    faults.install(None)


def _ring(every=1.0):
    t = [0.0]
    reg = MetricsRegistry()
    ring = HistoryRing(registry=reg, every_s=every,
                       now=lambda: t[0])
    return t, reg, ring


# ------------------------------------------------------------- history


def test_history_rate_mean_sustained():
    t, reg, ring = _ring()
    reg.gauge("g").set(4.0)
    reg.histogram("lat").observe(0.5)
    for _ in range(6):
        ring.sample_once()
        reg.counter("c").inc(3)
        t[0] += 1.0
    # counter rate: 3/tick over 1s ticks
    assert ring.rate("c", 10.0) == pytest.approx(3.0)
    assert ring.mean_over("g", 10.0) == pytest.approx(4.0)
    # histogram series materialize as .count/.sum
    assert ring.series("lat.count")[-1][1] == 1.0
    assert ring.series("lat.sum")[-1][1] == pytest.approx(0.5)
    # sustained: every sample in a covered window satisfies the op
    assert ring.sustained("g", ">=", 4.0, 3.0)
    assert not ring.sustained("g", ">=", 5.0, 3.0)
    assert ring.sustained("g", "<=", 4.0, 3.0)
    # window payload shape (the /metrics/history body)
    w = ring.window(2.5)
    assert w["every_s"] == 1.0
    assert all(len(pts) <= 3 for pts in w["series"].values())
    with pytest.raises(ValueError):
        ring.sustained("g", "~", 1.0, 3.0)


def test_history_sustained_requires_coverage():
    t, reg, ring = _ring()
    reg.gauge("g").set(9.0)
    ring.sample_once()          # a single young sample
    t[0] += 0.5
    # the signal satisfies the op but the ring has not WATCHED it for
    # 30s — a fresh process must not claim a sustained condition
    assert not ring.sustained("g", ">=", 1.0, 30.0)
    # absent series: False, never a KeyError
    assert not ring.sustained("nope", ">=", 1.0, 1.0)
    assert ring.rate("g", 30.0) is None         # < 2 samples
    assert ring.mean_over("nope", 1.0) is None


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read().decode())


def test_history_endpoint_on_pull_front():
    t, reg, ring = _ring()
    reg.gauge("serve.queue_depth").set(7.0)
    for _ in range(3):
        ring.sample_once()
        t[0] += 1.0
    srv = obs_http.ObsServer("127.0.0.1:0", registry=reg,
                             history=ring).start()
    try:
        status, body = _get(srv.url + "/metrics/history")
        assert status == 200
        assert body["series"]["serve.queue_depth"][-1][1] == 7.0
        assert body["samples"] == 3
        status, body = _get(srv.url + "/metrics/history?window=1.5")
        assert body["window"] == 1.5
        # bad window: 400, not a traceback
        try:
            _get(srv.url + "/metrics/history?window=soon")
            raise AssertionError("expected 400")
        except urllib.request.HTTPError as e:
            assert e.code == 400
    finally:
        srv.close()
    # a front with NO ring answers 404 (engine run without the flag)
    srv2 = obs_http.ObsServer("127.0.0.1:0", registry=reg).start()
    try:
        _get(srv2.url + "/metrics/history")
        raise AssertionError("expected 404")
    except urllib.request.HTTPError as e:
        assert e.code == 404
    finally:
        srv2.close()


# ------------------------------------------------------------ recorder


def _recorder(tmp_path, **kw):
    t = [100.0]
    reg = MetricsRegistry()
    kw.setdefault("min_interval_s", 0.0)
    kw.setdefault("process", "test")
    rec = obs_flight.FlightRecorder(str(tmp_path), registry=reg,
                                    now=lambda: t[0], **kw)
    return t, reg, rec


def test_manual_trigger_rate_limit_and_retention(tmp_path):
    t, reg, rec = _recorder(tmp_path, keep=2, min_interval_s=5.0)
    rec.trigger("manual:one")
    assert rec.poll_once()
    assert rec.latest()["trigger"] == "manual:one"
    # inside the min interval: DEFERRED (counted once), no new bundle
    t[0] += 1.0
    rec.trigger("manual:two")
    assert rec.poll_once()
    assert rec.poll_once()                       # re-check, same count
    assert reg.counter("flight.rate_limited").value == 1
    assert rec.latest()["trigger"] == "manual:one"
    # interval elapses with NO new trigger: the deferred incident
    # still gets its bundle — the limit means one bundle per storm,
    # never zero for a distinct incident
    t[0] += 6.0
    assert rec.poll_once()
    assert rec.latest()["trigger"] == "manual:two"
    assert reg.counter("flight.dumps").value == 2
    # later triggers past the interval dump; retention ages out the
    # oldest bundles
    for name in ("manual:three", "manual:four"):
        t[0] += 6.0
        rec.trigger(name)
        assert rec.poll_once()
    files = obs_flight.list_bundles(str(tmp_path))
    assert len(files) == 2                       # keep=2, oldest gone
    assert rec.latest()["trigger"] == "manual:four"
    assert reg.counter("flight.dumps").value == 4
    # the in-memory copy and the newest file agree
    assert obs_flight.load_bundle(files[-1])["trigger"] == "manual:four"
    # a PEER-carrying trigger (a failover's correlation dump) bypasses
    # the rate limit: losing the one stitched bundle a failover asked
    # for because a reason flapped seconds earlier would defeat the
    # recorder's purpose
    t[0] += 1.0                                  # inside min_interval
    rec.trigger("failover:r0", peers=("r0",))
    assert rec.poll_once()
    assert rec.latest()["trigger"] == "failover:r0"
    assert reg.counter("flight.dumps").value == 5


def test_record_tee_rings_budget_and_fault_trigger(tmp_path):
    t, reg, rec = _recorder(tmp_path, span_bytes=400, records_cap=3)
    buf = io.StringIO()
    tee = rec.tee(buf)
    lines = [
        '{"logEntry":{"procID":0,"threadID":0,"best":9,"time":1.0}}',
        '{"spanEntry":{"name":"dispatch","cat":"device","ts":0.1,'
        '"dur":0.2,"depth":0,"tid":0,"flow":7}}',
        '{"spanEntry":{"name":"fetch","cat":"engine","ts":0.3,'
        '"dur":0.1,"depth":0,"tid":0,"flow":7}}',
        '{"spanEntry":{"name":"process","cat":"engine","ts":0.4,'
        '"dur":0.1,"depth":0,"tid":0,"flow":7}}',
        '{"logEntry":{"procID":0,"threadID":0,"best":8,"time":2.0}}',
        '{"logEntry":{"procID":0,"threadID":0,"best":7,"time":3.0}}',
        '{"logEntry":{"procID":0,"threadID":0,"best":6,"time":4.0}}',
        '{"faultEntry":{"site":"dispatch","action":"recover",'
        '"error":"x","trial":0,"recovery":1,"level":0,"time":4.5}}',
    ]
    for ln in lines:
        tee.write(ln + "\n")
    # the tee is a pure pass-through
    assert buf.getvalue() == "".join(ln + "\n" for ln in lines)
    assert rec.poll_once()
    core = rec.latest()
    assert core["trigger"] == "fault:dispatch/recover"
    # record ring: count-capped, newest kept (the faultEntry survives)
    assert len(core["records"]) == 3
    assert "faultEntry" in core["records"][-1]
    assert core["records_dropped"] == 2          # 5 non-span records
    # span ring: byte-budgeted — 3 small spans fit 400B or evict
    # oldest-first; whatever remains, accounting is honest
    assert len(core["spans"]) + core["spans_dropped"] == 3
    assert core["spans"][-1]["name"] == "process"
    assert rec.span_bytes_hw > 0


def test_readiness_flip_triggers_dump(tmp_path):
    t, reg, rec = _recorder(tmp_path)
    # first poll: all clear, nothing pending
    assert rec.poll_once()
    assert rec.latest() is None
    # a /readyz reason flips ON (backlog_full: queue >= backlog)
    reg.gauge("serve.backlog").set(4.0)
    reg.gauge("serve.queue_depth").set(4.0)
    assert rec.poll_once()
    assert rec.latest()["trigger"] == "reason:backlog_full"
    assert rec.latest()["reasons"] == ["backlog_full"]
    # still on: no re-trigger (flip detection, not level detection)
    t[0] += 1.0
    assert rec.poll_once()
    assert reg.counter("flight.dumps").value == 1
    # clears, then flips on again: a NEW incident
    reg.gauge("serve.queue_depth").set(0.0)
    assert rec.poll_once()
    reg.gauge("serve.queue_depth").set(9.0)
    t[0] += 1.0
    assert rec.poll_once()
    assert reg.counter("flight.dumps").value == 2


def test_flight_dump_die_ends_recorder_thread(tmp_path):
    t, reg, rec = _recorder(tmp_path)
    faults.install("flight_dump:1:die")
    rec.trigger("manual:x")
    assert rec.poll_once() is False              # thread would exit
    assert rec.latest() is None
    faults.install(None)


def test_history_die_ends_sampler():
    t, reg, ring = _ring()
    faults.install("history:1:die")
    assert ring.sample_once() is False
    faults.install(None)
    assert ring.sample_once() is True


# ---------------------------------------------------- bundles -> tools


def _mk_bundle(tmp_path, spans=(), records=(), **core_kw):
    core = {"version": 1, "process": "engine", "pid": 1,
            "trigger": "manual:t", "reasons": [], "ts": 1.0,
            "unix_time": 0.0, "config": None, "metrics": {},
            "history": None, "mem": {}, "spans": list(spans),
            "records": list(records), "spans_dropped": 0,
            "records_dropped": 0}
    core.update(core_kw)
    path = os.path.join(str(tmp_path), "incident-1-0001-manual-t.json")
    with open(path, "w") as fh:
        json.dump({"incident": core}, fh)
    return path, core


def test_tt_incident_renders_and_lists(tmp_path, capsys):
    span = {"name": "dispatch", "cat": "device", "ts": 0.1,
            "dur": 0.2, "depth": 0, "tid": 0, "flow": 3}
    path, _ = _mk_bundle(
        tmp_path, spans=[span],
        records=[{"faultEntry": {"site": "dispatch",
                                 "action": "recover", "error": "x",
                                 "trial": 0, "recovery": 1,
                                 "level": 0, "time": 1.0}}])
    out = os.path.join(str(tmp_path), "t.json")
    assert obs_flight.main_incident([str(tmp_path), "-o", out]) == 0
    text = capsys.readouterr().out
    assert "== incident: manual:t" in text
    assert "last fault: dispatch/recover" in text
    with open(out) as fh:
        doc = json.load(fh)
    assert any(e.get("name") == "dispatch" and e.get("ph") == "X"
               for e in doc["traceEvents"])
    # --list mode names the bundles without rendering
    assert obs_flight.main_incident([str(tmp_path), "--list"]) == 0
    assert "manual:t" in capsys.readouterr().out


def test_tt_trace_accepts_bundle_next_to_jsonl(tmp_path):
    from timetabling_ga_tpu.obs.trace_export import main_trace
    span = {"name": "quantum", "cat": "device", "ts": 0.5,
            "dur": 0.2, "depth": 0, "tid": 0,
            "flow": XFLOW_BASE + 1}
    bundle_path, _ = _mk_bundle(tmp_path, spans=[span])
    log_path = os.path.join(str(tmp_path), "gw.jsonl")
    with open(log_path, "w") as fh:
        fh.write(json.dumps({"spanEntry": {
            "name": "routed", "cat": "fleet", "ts": 0.1, "dur": 0.6,
            "depth": 0, "tid": 0, "flow": XFLOW_BASE + 1}}) + "\n")
    out = os.path.join(str(tmp_path), "stitched.json")
    assert main_trace([log_path, bundle_path, "-o", out]) == 0
    with open(out) as fh:
        doc = json.load(fh)
    names = {e.get("name") for e in doc["traceEvents"]}
    assert {"routed", "quantum"} <= names
    # the XFLOW chain crosses the two inputs: one s + one f flow event
    flows = [e for e in doc["traceEvents"]
             if e.get("ph") in ("s", "t", "f")
             and e.get("id") == XFLOW_BASE + 1]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert {e["pid"] for e in flows} == {0, 1}


def test_stats_incidents_section():
    recs = [{"spanEntry": {"name": "flight_dump", "cat": "flight",
                           "ts": 5.0, "dur": 0.4, "depth": 0,
                           "tid": 1, "trigger": "fault:dispatch"}},
            {"spanEntry": {"name": "flight_dump", "cat": "flight",
                           "ts": 9.0, "dur": 0.2, "depth": 0,
                           "tid": 1, "trigger": "reason:slo_burn"}}]
    text = summarize(recs)
    assert "== incidents (2 dumps)" in text
    assert "fault:dispatch: 1x" in text
    assert "reason:slo_burn: 1x" in text
    assert "time-to-dump p50 0.400s" in text


# --------------------------------------------------------------- flags


def test_flight_flags_parse_and_validate():
    cfg = parse_args(["-i", "x.tim", "--history-every", "0.5",
                      "--incident-dir", "/tmp/inc",
                      "--incident-min-interval", "10"])
    assert (cfg.history_every, cfg.incident_dir,
            cfg.incident_min_interval) == (0.5, "/tmp/inc", 10.0)
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--history-every", "-1"])
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--incident-min-interval", "-1"])
    scfg = parse_serve_args(["--incident-dir", "/tmp/i",
                             "--history-every", "2"])
    assert scfg.incident_dir == "/tmp/i"
    assert scfg.history_every == 2.0
    with pytest.raises(SystemExit):
        parse_serve_args(["--history-every", "-3"])
    fcfg = parse_fleet_args(["--replica", "http://x:1",
                             "--incident-dir", "/tmp/g",
                             "--incident-min-interval", "0"])
    assert fcfg.incident_dir == "/tmp/g"
    with pytest.raises(SystemExit):
        parse_fleet_args(["--replica", "http://x:1",
                          "--incident-min-interval", "-2"])
    # new fault sites parse
    faults.FaultPlan.parse("history:1:hang,flight_dump:2:die")


# ------------------------------------------------- engine e2e + identity


def _engine_run(tmp_path=None, **kw):
    from timetabling_ga_tpu.runtime import engine
    base = dict(input=TIM_FIXTURE, seed=3, pop_size=8, islands=2,
                generations=30, migration_period=10, max_steps=8,
                time_limit=300, backend="cpu", auto_tune=False,
                trace=True)
    base.update(kw)
    buf = io.StringIO()
    best = engine.run(RunConfig(**base), out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def test_engine_bundle_and_stream_identity(tmp_path, engine_stream_baseline):
    """Recorder+sampler ON: an injected transient produces a bundle
    carrying trigger/metrics/history/rings, and the JSONL stream is
    bit-identical to the session baseline (recorder OFF, fault-free —
    strip_timing drops the fault/obs records)."""
    d = str(tmp_path / "inc")
    best_off, base_recs = engine_stream_baseline
    best_on, on_recs = _engine_run(
        obs=True, faults="dispatch:2:unavailable",
        incident_dir=d, incident_min_interval=0.0, history_every=0.05)
    assert best_on == best_off
    assert jsonl.strip_timing(on_recs) == jsonl.strip_timing(base_recs)
    bundles = [obs_flight.load_bundle(p)
               for p in obs_flight.list_bundles(d)]
    fault_bundles = [b for b in bundles
                     if b["trigger"].startswith("fault:dispatch")]
    assert fault_bundles, [b["trigger"] for b in bundles]
    core = fault_bundles[0]
    assert core["process"] == "engine"
    assert core["config"]["kind"] == "RunConfig"
    assert core["metrics"].get("counters", {}).get("flight.triggers")
    assert len((core["history"] or {}).get("series", {})) > 0
    assert core["records"]                      # the tee fed the ring
    # the dump span landed on the stream (the tt stats source)
    assert any(r.get("spanEntry", {}).get("name") == "flight_dump"
               for r in on_recs)


def test_hung_sampler_and_dumper_never_stall_the_run(tmp_path,
                                                     monkeypatch):
    """Isolation (the mem_poll discipline): a sampler that dies on its
    first sample AND a dump attempt that hangs leave the run
    untouched — it completes, the writer drains, the stream is whole."""
    monkeypatch.setattr(faults, "HANG_S", 30.0)
    d = str(tmp_path / "inc")
    best, recs = _engine_run(
        obs=True,
        faults="history:1:die,dispatch:2:unavailable,"
               "flight_dump:1:hang",
        incident_dir=d, incident_min_interval=0.0, history_every=0.05)
    # the run completed and the stream is complete (solution + final
    # runEntry drained through the writer)
    assert any("solution" in r for r in recs)
    assert any("runEntry" in r for r in recs)
    # the hung dump produced nothing — and stalled nothing
    assert obs_flight.list_bundles(d) == []


# ------------------------------------------------ replica front (fast)


def _serve_cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 5)
    kw.setdefault("pop_size", 4)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _fleet_cfg(urls, **kw):
    kw.setdefault("listen", "127.0.0.1:0")
    kw.setdefault("probe_every", 0.1)
    kw.setdefault("poll_every", 0.05)
    kw.setdefault("dead_after", 2)
    return FleetConfig(replicas=list(urls), **kw)


def test_replica_incident_endpoint(tmp_path):
    """GET /v1/incident serves the replica's newest bundle from
    memory; GET /metrics/history serves its ring; both 404 cleanly
    when unwired."""
    from timetabling_ga_tpu.fleet.replicas import (
        FleetHTTPError, http_json, in_process_replica)
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    p = random_instance(71, n_events=12, n_rooms=3, n_features=2,
                        n_students=8, attend_prob=0.2)
    rep, handle = in_process_replica(
        _serve_cfg(http="127.0.0.1:0", obs=True,
                   incident_dir=str(tmp_path / "r"),
                   incident_min_interval=0.0, history_every=0.1),
        "fl0")
    try:
        # before any incident: a clean 404 (and the handle's client
        # decodes it to None)
        assert handle.get_incident(timeout=5.0) is None
        faults.install("quantum:1:unavailable")
        http_json("POST", rep.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "fj", "seed": 3,
                   "generations": 10})
        deadline = time.monotonic() + 90
        core = None
        while time.monotonic() < deadline:
            core = handle.get_incident(timeout=5.0)
            if core is not None:
                break
            time.sleep(0.05)
        assert core is not None, "no bundle served"
        assert core["trigger"].startswith("fault:quantum")
        assert core["process"] == "serve"
        hist = handle.get_history(window=30.0, timeout=5.0)
        assert hist["series"], "empty history ring"
    finally:
        faults.install(None)
        rep.kill()
    # a replica WITHOUT the flags answers 404 on /v1/incident
    rep2, handle2 = in_process_replica(
        _serve_cfg(http="127.0.0.1:0"), "fl1")
    try:
        assert handle2.get_incident(timeout=5.0) is None
        with pytest.raises(FleetHTTPError):
            http_json("GET", rep2.url + "/metrics/history", ok=(200,))
    finally:
        rep2.kill()


# ----------------------------------------------- fleet acceptance (slow)


@pytest.mark.slow
def test_fleet_kill_mid_stream_incident_acceptance(tmp_path):
    """ISSUE 13 acceptance: with --incident-dir set fleet-wide, an
    injected replica fault during a routed solve produces a REPLICA
    bundle and — after the replica is killed mid-stream — a STITCHED
    gateway bundle; the two share the job's XFLOW id, `tt incident`
    renders a Perfetto-loadable cross-process timeline from the
    stitched bundle, and the settled record stream (recorder+sampler
    ON everywhere) is bit-identical to the recorder-OFF unrouted
    baseline."""
    from timetabling_ga_tpu.fleet.gateway import Gateway
    from timetabling_ga_tpu.fleet.replicas import (
        http_json, in_process_replica)
    from timetabling_ga_tpu.problem import dump_tim, random_instance
    from timetabling_ga_tpu.serve.service import SolveService
    p = random_instance(71, n_events=12, n_rooms=3, n_features=2,
                        n_students=8, attend_prob=0.2)

    def rep_cfg(tag):
        return _serve_cfg(http="127.0.0.1:0", obs=True,
                          incident_dir=str(tmp_path / tag),
                          incident_min_interval=0.0,
                          history_every=0.1)

    rep0, h0 = in_process_replica(rep_cfg("r0"), "fk0")
    rep1, h1 = in_process_replica(rep_cfg("r1"), "fk1")
    gw_dir = str(tmp_path / "gw")
    gwbuf = io.StringIO()
    gw = Gateway(_fleet_cfg([h0.url, h1.url], incident_dir=gw_dir,
                            incident_min_interval=0.0,
                            history_every=0.1),
                 [h0, h1], out=gwbuf).start()
    reps = {"fk0": rep0, "fk1": rep1}
    handles = {"fk0": h0, "fk1": h1}
    try:
        # the injected replica fault: the FIRST quantum anywhere in
        # the process dies transiently — i.e. on the job's owner
        faults.install("quantum:1:unavailable")
        http_json("POST", gw.url + "/v1/solve",
                  {"tim": dump_tim(p), "id": "kx", "seed": 3,
                   "generations": 2000})
        # wait for the owner's recorder to dump on the fault AND for
        # the gateway prober to cache that bundle off the dump-counter
        # scrape (the dead replica's bundle must survive its death)
        deadline = time.monotonic() + 120
        owner = None
        while time.monotonic() < deadline:
            with gw.jobs_lock:
                j = gw.jobs.get("kx")
                owner, flow = j.replica, j.flow
            if (owner in reps
                    and handles[owner].last_incident is not None
                    and j.snap_gens >= 5):
                break
            time.sleep(0.02)
        assert owner in reps, "job never placed"
        rep_core = handles[owner].last_incident
        assert rep_core is not None, "prober never cached the bundle"
        assert rep_core["trigger"].startswith("fault:quantum")
        assert flow >= XFLOW_BASE

        # kill mid-stream: failover stitches gateway + cached replica
        reps[owner].kill()
        deadline = time.monotonic() + 120
        stitched = None
        while time.monotonic() < deadline:
            for path in obs_flight.list_bundles(gw_dir):
                core = obs_flight.load_bundle(path)
                if core.get("stitched") and core["trigger"] \
                        == f"failover:{owner}":
                    stitched = (path, core)
            if stitched:
                break
            time.sleep(0.05)
        assert stitched, "no stitched failover bundle"
        st_path, st_core = stitched

        # the job completes on the survivor, stream identical to the
        # recorder-OFF unrouted baseline
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            v = http_json("GET", gw.url + "/v1/jobs/kx", ok=(200,))
            if v["state"] in ("done", "failed"):
                break
            time.sleep(0.1)
        assert v["state"] == "done"
        base_buf = io.StringIO()
        svc = SolveService(_serve_cfg(), out=base_buf)
        svc.submit(p, job_id="kx", seed=3, generations=2000)
        svc.drive()
        svc.close()
        base = [json.loads(x)
                for x in base_buf.getvalue().splitlines()]
        assert jsonl.strip_timing(v["records"]) \
            == jsonl.strip_timing(base)

        # the shared XFLOW id: gateway spans and the replica bundle's
        # spans both carry the job's cross-process flow
        def flows(core):
            out = set()
            for s in core.get("spans", ()):
                f = s.get("flow")
                for x in (f if isinstance(f, list) else [f]):
                    if isinstance(x, (int, float)):
                        out.add(int(x))
            return out

        assert flow in flows(st_core), "gateway bundle lost the flow"
        peer = next(pr["incident"] for pr in st_core["peers"]
                    if pr["label"] == owner)
        assert peer is not None
        assert flow in flows(peer), "replica bundle lost the flow"
        # the embedded stitched trace reused export_stitched's rules:
        # per-process lanes + the verbatim XFLOW chain
        assert any(e.get("ph") == "M" for e in
                   st_core["trace"]["traceEvents"])

        # tt incident renders the stitched bundle as Perfetto JSON
        out = str(tmp_path / "incident.trace.json")
        assert obs_flight.main_incident([st_path, "-o", out]) == 0
        with open(out) as fh:
            doc = json.load(fh)
        assert doc["traceEvents"]
        xfl = [e for e in doc["traceEvents"]
               if e.get("ph") in ("s", "t", "f")
               and e.get("id") == flow]
        assert xfl, "no cross-process flow arrows in the timeline"
        assert len({e["pid"] for e in xfl}) >= 2
    finally:
        faults.install(None)
        gw.close()
        rep0.kill()
        rep1.kill()
