"""In-run fault recovery (ISSUE 3): the engine run supervisor, the
fetch watchdog, the degradation ladder, and the deterministic fault
harness (runtime/faults.py) that drives them all on the CPU backend —
plus the satellite hardening of retry/jsonl/checkpoint.

The determinism contract under test: a run that absorbs an injected
transient failure must emit protocol records IDENTICAL to an uninjected
run with the same seed, modulo timing fields and fault/phase records
(jsonl.strip_timing is the shared definition of that domain).
"""

import io
import json
import os
import time

import numpy as np
import pytest

from timetabling_ga_tpu.problem import dump_tim, random_instance
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import faults, jsonl, retry
from timetabling_ga_tpu.runtime.config import RunConfig, parse_args


# ------------------------------------------------------------- satellites

def test_is_transient_walks_cause_and_context():
    """jit dispatch wraps the XLA UNAVAILABLE error in a RuntimeError;
    the classifier must walk __cause__ AND __context__ or exactly the
    failures the retry policy exists for re-raise as permanent."""
    assert not retry.is_transient(RuntimeError("boom"))
    assert retry.is_transient(RuntimeError("UNAVAILABLE: device"))
    # explicit cause chain (raise ... from ...)
    try:
        try:
            raise ValueError("UNAVAILABLE: TPU device error")
        except ValueError as inner:
            raise RuntimeError("dispatch failed") from inner
    except RuntimeError as e:
        assert retry.is_transient(e)
    # implicit context chain (raise during except)
    try:
        try:
            raise OSError("remote_compile: response body closed")
        except OSError:
            raise KeyError("wrapped")
    except KeyError as e:
        assert retry.is_transient(e)
    # a cycle must terminate, not spin
    a, b = RuntimeError("a"), RuntimeError("b")
    a.__cause__, b.__cause__ = b, a
    assert not retry.is_transient(a)


def test_retry_backoff_schedule_and_cap(monkeypatch):
    """Exponential backoff from wait_s by `backoff`, capped at
    max_wait_s — a fixed 120 s wait either burns budget on blips or
    re-enters a long sick window still sick."""
    assert retry.backoff_schedule(4, 10.0, 2.0, 35.0) == [10.0, 20.0, 35.0]
    slept = []
    monkeypatch.setattr(time, "sleep", slept.append)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: window")
        return "ok"

    result, attempts = retry.retry_transient(
        flaky, attempts=4, wait_s=5.0, backoff=3.0, max_wait_s=10.0)
    assert result == "ok" and attempts == 3
    assert slept == [5.0, 10.0]          # 5, then 15 capped to 10
    # non-transient: no retry, no sleep
    slept.clear()
    with pytest.raises(ValueError):
        retry.retry_transient(lambda: (_ for _ in ()).throw(
            ValueError("real bug")), attempts=3, wait_s=1.0)
    assert slept == []


def test_fault_plan_grammar():
    plan = faults.FaultPlan.parse(
        "dispatch:3:unavailable, fetch:5:hang,writer:1:die,ckpt:2:truncate")
    assert plan.pop_action("dispatch") is None          # invocation 1
    assert plan.pop_action("dispatch") is None          # 2
    assert plan.pop_action("dispatch") == "unavailable"  # 3
    assert plan.pop_action("dispatch") is None          # 4: one-shot
    assert plan.injected == 1
    for bad in ("dispatch:x:unavailable", "dispatch:0:unavailable",
                "dispatch:1:explode", "dispatch:1",
                "dispath:1:unavailable"):   # typo'd site: loud, not no-op
        with pytest.raises(faults.FaultPlanError):
            faults.FaultPlan.parse(bad)
    # unavailable raises a WRAPPED transient (the cause-chain shape)
    faults.install("dispatch:1:unavailable")
    try:
        with pytest.raises(RuntimeError) as ei:
            faults.maybe_fail("dispatch")
        assert "UNAVAILABLE" not in str(ei.value)   # top exception clean
        assert retry.is_transient(ei.value)         # chain classifies
    finally:
        faults.install(None)


def test_async_writer_death_aware_enqueue_and_close():
    """If the worker thread dies with the bounded queue full, write()/
    submit()/drain()/close() must raise, not block forever on
    queue.put/join (the pre-fix deadlock)."""
    faults.install("writer:1:die")
    try:
        buf = io.StringIO()
        w = jsonl.AsyncWriter(buf, maxsize=2)
        w.write('{"a":1}\n')          # consumed by the worker, which dies
        deadline = time.monotonic() + 30
        with pytest.raises(RuntimeError, match="worker thread died"):
            while time.monotonic() < deadline:
                w.write('{"b":2}\n')   # fills the queue, then must raise
        with pytest.raises(RuntimeError, match="worker thread died"):
            w.drain()
        with pytest.raises(RuntimeError, match="worker thread died"):
            w.close()
        w.close(raise_error=False)     # exception-path close: no raise,
        #                                no deadlock
    finally:
        faults.install(None)


def test_checkpoint_rotation_and_corrupt_fallback(tmp_path, small_problem):
    """save rotates path -> path.prev; a truncated newest file (via the
    ckpt fault site) falls back to the previous good one; both bad is a
    CheckpointCorrupt naming both paths."""
    import jax
    from timetabling_ga_tpu.ops import ga
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 8)
    gacfg = ga.GAConfig(pop_size=8)
    fp = ckpt.config_fingerprint(small_problem, gacfg, n_islands=2)
    path = str(tmp_path / "ck.npz")
    ckpt.save(path, st, jax.random.key(1), 10, fp, best_seen=[5, 6], seed=1)
    assert not os.path.exists(ckpt.prev_path(path))   # nothing to rotate
    ckpt.save(path, st, jax.random.key(2), 20, fp, best_seen=[4, 5], seed=1)
    assert os.path.exists(ckpt.prev_path(path))
    # generation 30 save is torn on disk by the fault harness
    faults.install("ckpt:1:truncate")
    try:
        ckpt.save(path, st, jax.random.key(3), 30, fp,
                  best_seen=[3, 4], seed=1)
    finally:
        faults.install(None)
    _st, _key, gen, best, seed = ckpt.load(path, fp)
    assert gen == 20 and best == [4, 5]    # the rotated previous-good one
    # a missing main with a good .prev also falls back (the crash window
    # between save's two renames)
    os.unlink(path)
    assert ckpt.load(path, fp)[2] == 20
    # both unreadable: CheckpointCorrupt naming both paths
    with open(path, "wb") as f:
        f.write(b"not-a-zip")
    with open(ckpt.prev_path(path), "wb") as f:
        f.write(b"also-bad")
    with pytest.raises(ckpt.CheckpointCorrupt) as ei:
        ckpt.load(path, fp)
    assert path in str(ei.value) and ckpt.prev_path(path) in str(ei.value)
    # no file at all stays FileNotFoundError (the engine's fresh-init
    # resume path depends on it)
    os.unlink(path)
    os.unlink(ckpt.prev_path(path))
    with pytest.raises(FileNotFoundError):
        ckpt.load(path, fp)


def test_fault_flags_parse():
    cfg = parse_args(["-i", "x.tim", "--max-recoveries", "5",
                      "--fetch-timeout", "30",
                      "--faults", "dispatch:1:unavailable"])
    assert cfg.max_recoveries == 5
    assert cfg.fetch_timeout == 30.0
    assert cfg.faults == "dispatch:1:unavailable"
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--max-recoveries", "-1"])
    with pytest.raises(SystemExit):
        parse_args(["-i", "x.tim", "--fetch-timeout", "-2"])
    # config beats env; env is the fallback
    assert faults.active_spec("a:1:hang") == "a:1:hang"
    os.environ["TT_FAULTS"] = "b:2:die"
    try:
        assert faults.active_spec(None) == "b:2:die"
        assert faults.active_spec("a:1:hang") == "a:1:hang"
    finally:
        del os.environ["TT_FAULTS"]
    assert faults.active_spec(None) is None


# -------------------------------------------------------- recovery matrix

@pytest.fixture(scope="module")
def tim_file(tmp_path_factory):
    problem = random_instance(55, n_events=15, n_rooms=5, n_features=2,
                              n_students=10, attend_prob=0.1)
    path = tmp_path_factory.mktemp("faults") / "tiny.tim"
    path.write_text(dump_tim(problem))
    return str(path)


def _go(tim_file, **kw):
    from timetabling_ga_tpu.runtime import engine
    buf = io.StringIO()
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    trace=True, **kw)
    best = engine.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def _fault_entries(lines):
    return [x["faultEntry"] for x in lines if "faultEntry" in x]


def test_dispatch_kill_recovers_with_identical_jsonl(tim_file):
    """ISSUE 3 acceptance: an injected mid-run dispatch kill (serial
    loop, snapshot = init state) recovers via snapshot rehydration and
    the stream is identical to an uninjected run's modulo timing and
    fault records — including the absence of duplicate logEntries for
    the replayed span."""
    clean_best, clean = _go(tim_file, pipeline=False)
    best, lines = _go(tim_file, pipeline=False,
                      faults="dispatch:2:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover"]
    assert fe[0]["site"] == "dispatch" and fe[0]["recovery"] == 1
    assert fe[0]["lostGens"] == 10          # chunk 1 replayed
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)


def test_dispatch_kill_pipelined_checkpoint_snapshot(tim_file, tmp_path):
    """Pipelined run with per-epoch checkpoints: the rolling snapshot
    advances at every checkpoint fence (covering the in-flight chunk),
    so a later kill replays only from the last fence — and the
    in-flight chunk's logEntries, folded into the snapshot, are still
    emitted exactly once."""
    clean_best, clean = _go(tim_file, pipeline=True,
                            checkpoint=str(tmp_path / "a.npz"),
                            checkpoint_every=1)
    best, lines = _go(tim_file, pipeline=True,
                      checkpoint=str(tmp_path / "b.npz"),
                      checkpoint_every=1,
                      faults="dispatch:3:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover"]
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)
    # the recovered run's final checkpoint is durable and loadable
    with np.load(str(tmp_path / "b.npz"), allow_pickle=False) as z:
        assert int(z["generation"]) == 30


def test_fetch_hang_watchdog_recovery(tim_file):
    """A hung control-fence fetch (the BENCH_r05 worst case) becomes a
    FetchTimeout via the watchdog thread, classifies transient, and
    recovers — fetch site invocation 3 is the first chunk's trace fetch
    (1 = init fence, 2 = the supervisor's initial snapshot)."""
    clean_best, clean = _go(tim_file, pipeline=False)
    t0 = time.monotonic()
    best, lines = _go(tim_file, pipeline=False, fetch_timeout=1.0,
                      faults="fetch:3:hang")
    wall = time.monotonic() - t0
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover"]
    assert fe[0]["site"] == "fetch"
    assert "fetch watchdog" in fe[0]["error"]
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)
    # the hang was abandoned at the deadline, not slept through
    assert wall < faults.HANG_S


def test_two_failures_in_window_degrade_to_serial(tim_file):
    """The degradation ladder: a second failure inside the window steps
    level 0 -> 1 (strictly serial loop), emitted as a degrade record;
    the run still completes with identical records (serial vs pipelined
    changes WHEN telemetry is processed, never WHAT is dispatched)."""
    clean_best, clean = _go(tim_file, pipeline=False)
    best, lines = _go(tim_file, pipeline=True, max_recoveries=5,
                      faults="dispatch:1:unavailable,"
                             "dispatch:2:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover", "recover", "degrade"]
    assert fe[-1]["level"] == 1 and fe[-1]["mode"] == "serial"
    loops = [x["phase"] for x in lines
             if "phase" in x and x["phase"]["name"] == "gen-loop"]
    assert loops and loops[0]["pipelined"] is False   # ladder took hold
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)


def test_ladder_level2_halves_dispatch_chunks(tim_file):
    """Three failures in the window reach level 2: dispatch chunks are
    halved (migration_period 10 -> 5-generation dynamic dispatches), so
    less work is lost per kill. Chunk sizes change the key-split
    sequence, so only completion and the generation budget are asserted
    — not record identity."""
    best, lines = _go(tim_file, pipeline=False, max_recoveries=6,
                      faults="dispatch:1:unavailable,"
                             "dispatch:2:unavailable,"
                             "dispatch:3:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == [
        "recover", "recover", "degrade", "recover", "degrade"]
    assert fe[-1]["level"] == 2 and fe[-1]["mode"] == "chunk-1/2"
    gens = [x["phase"]["gens"] for x in lines
            if "phase" in x and x["phase"]["name"] == "dispatch"]
    assert sum(gens) == 30                  # budget still exact
    assert any(g == 5 for g in gens)        # halved chunks actually ran
    assert any("runEntry" in x for x in lines)


def test_recovery_exhaustion_aborts_cleanly(tim_file, tmp_path):
    """--max-recoveries exhausted: the run raises the transient error
    (so outer harnesses can still classify it), after emitting an abort
    faultEntry through the DRAINED writer and leaving a final durable
    checkpoint from the snapshot."""
    ck = str(tmp_path / "abort.npz")
    buf = io.StringIO()
    from timetabling_ga_tpu.runtime import engine
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    pipeline=False, checkpoint=ck, checkpoint_every=1,
                    max_recoveries=1,
                    faults="dispatch:1:unavailable,dispatch:2:unavailable")
    with pytest.raises(RuntimeError) as ei:
        engine.run(cfg, out=buf)
    assert retry.is_transient(ei.value)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover", "abort"]
    # the abort record reached the stream: the writer was drained on the
    # error path, not abandoned
    assert fe[-1]["site"] == "dispatch"
    # final durable checkpoint from the snapshot round-trips
    fp = ckpt.config_fingerprint  # noqa: F841  (doc pointer)
    with np.load(ck, allow_pickle=False) as z:
        assert "generation" in z and "slots" in z


def test_init_site_kill_retries_with_identical_jsonl(tim_file):
    """ISSUE 4 satellite (ROADMAP PR-3 follow-up): a transient failure
    at the INIT dispatch — before the first supervisor snapshot exists
    — is retried by the supervised-init wrapper instead of propagating,
    and the stream stays identical to an uninjected run's modulo timing
    and fault records."""
    clean_best, clean = _go(tim_file, pipeline=False)
    best, lines = _go(tim_file, pipeline=False,
                      faults="init:1:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover"]
    assert fe[0]["site"] == "init" and fe[0].get("init") is True
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)


@pytest.mark.slow
# re-tiered (ISSUE 9 tier-1 budget): the init-site retry determinism
# contract stays tier-1-pinned by test_init_site_kill_retries_with_
# identical_jsonl; this variant only moves the injection point into the
# polish window
def test_init_retry_covers_init_polish_window(tim_file):
    """The retry wraps the whole pre-snapshot window: a dispatch kill
    INSIDE the init polish (dispatch site invocation 1, with
    init_sweeps > 0) re-runs init+polish from the same keys; the
    emitted floor keeps replayed polish bests from duplicating."""
    clean_best, clean = _go(tim_file, pipeline=False, init_sweeps=3)
    best, lines = _go(tim_file, pipeline=False, init_sweeps=3,
                      faults="dispatch:1:unavailable")
    fe = _fault_entries(lines)
    assert [e["action"] for e in fe] == ["recover"]
    assert fe[0].get("init") is True
    assert best == clean_best
    assert jsonl.strip_timing(lines) == jsonl.strip_timing(clean)


def test_init_retry_bounded_and_disabled_by_zero_recoveries(tim_file):
    """Three consecutive init kills exhaust the bounded retry (2) and
    the last error propagates; with --max-recoveries 0 the FIRST init
    failure propagates untouched — no hidden retry behind the
    recovery-off switch."""
    buf = io.StringIO()
    from timetabling_ga_tpu.runtime import engine
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    pipeline=False,
                    faults="init:1:unavailable,init:2:unavailable,"
                           "init:3:unavailable")
    with pytest.raises(RuntimeError) as ei:
        engine.run(cfg, out=buf)
    assert retry.is_transient(ei.value)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert [e["action"] for e in _fault_entries(lines)] == [
        "recover", "recover"]
    # recovery disabled: the init window is NOT silently retried
    buf2 = io.StringIO()
    cfg2 = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                     generations=30, migration_period=10, max_steps=8,
                     time_limit=300, backend="cpu", auto_tune=False,
                     pipeline=False, max_recoveries=0,
                     faults="init:1:unavailable")
    with pytest.raises(RuntimeError):
        engine.run(cfg2, out=buf2)
    lines2 = [json.loads(x) for x in buf2.getvalue().splitlines()]
    assert _fault_entries(lines2) == []


def test_non_transient_injected_error_is_not_recovered(tim_file):
    """The supervisor must never retry a real bug into flakiness: the
    `error` action raises a NON-transient failure, which propagates
    with no recover record."""
    buf = io.StringIO()
    from timetabling_ga_tpu.runtime import engine
    cfg = RunConfig(input=tim_file, seed=3, pop_size=8, islands=1,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    pipeline=False, faults="dispatch:1:error")
    with pytest.raises(faults.FaultInjected):
        engine.run(cfg, out=buf)
    lines = [json.loads(x) for x in buf.getvalue().splitlines()]
    assert _fault_entries(lines) == []
