"""Golden tests: batched fitness kernels vs the scalar reference oracle.

Exact integer equality on random populations over random instances — the
oracle transcribes Solution.cpp:63-170 semantics (see
timetabling_ga_tpu/oracle/reference_oracle.py).
"""

import numpy as np
import pytest

from timetabling_ga_tpu.ops import fitness
from timetabling_ga_tpu.oracle import (
    oracle_hcv, oracle_scv, oracle_penalty, oracle_feasible)
from tests.conftest import random_assignment


@pytest.mark.parametrize("prob_fixture,pop", [
    ("tiny_problem", 16), ("small_problem", 8), ("medium_problem", 4)])
def test_kernels_match_oracle(prob_fixture, pop, request):
    problem = request.getfixturevalue(prob_fixture)
    pa = problem.device_arrays()
    rng = np.random.default_rng(42)
    slots, rooms = random_assignment(rng, problem, pop)

    pen, hcv, scv = fitness.batch_penalty(pa, slots, rooms)
    pen, hcv, scv = np.asarray(pen), np.asarray(hcv), np.asarray(scv)

    for i in range(pop):
        assert hcv[i] == oracle_hcv(problem, slots[i], rooms[i]), i
        assert scv[i] == oracle_scv(problem, slots[i]), i
        assert pen[i] == oracle_penalty(problem, slots[i], rooms[i]), i


def test_batch_matches_per_individual_calls(medium_problem):
    """The batched kernel must agree exactly with individually-traced
    per-solution evaluations (a genuinely separate compilation path —
    no vmap batching rules involved)."""
    pa = medium_problem.device_arrays()
    rng = np.random.default_rng(17)
    slots, rooms = random_assignment(rng, medium_problem, 8)
    pen_b, hcv_b, scv_b = (np.asarray(x) for x in
                           fitness.batch_penalty(pa, slots, rooms))
    for i in range(8):
        pen, hcv, scv = fitness.compute_penalty(
            pa, np.asarray(slots[i]), np.asarray(rooms[i]))
        assert int(pen) == pen_b[i]
        assert int(hcv) == hcv_b[i]
        assert int(scv) == scv_b[i]


def test_feasible_iff_hcv_zero(small_problem):
    problem = small_problem
    pa = problem.device_arrays()
    rng = np.random.default_rng(7)
    slots, rooms = random_assignment(rng, problem, 16)
    _, hcv, _ = fitness.batch_penalty(pa, slots, rooms)
    feas = np.asarray(fitness.batch_feasible(pa, slots, rooms))
    for i in range(16):
        assert feas[i] == oracle_feasible(problem, slots[i], rooms[i])
    assert (np.asarray(hcv) == 0).tolist() == feas.tolist()


def test_reported_evaluation_no_overflow(small_problem):
    """hcv*1e6+scv must not wrap int32 (ga.cpp:191 reporting formula)."""
    from timetabling_ga_tpu.oracle import oracle_reported_evaluation
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(9)
    slots, rooms = random_assignment(rng, small_problem, 4)
    _, hcv, scv = fitness.batch_penalty(pa, slots, rooms)
    for i in range(4):
        got = fitness.reported_evaluation(hcv[i], scv[i])
        assert got == oracle_reported_evaluation(
            small_problem, slots[i], rooms[i])
        assert got >= 0
    # synthetic large hcv: would wrap int32 if not host-int
    assert fitness.reported_evaluation(np.int32(3000), np.int32(7)) \
        == 3_000_000_007


def test_penalty_formula(small_problem):
    """penalty = scv if hcv==0 else 1e6 + hcv (Solution.cpp:162-170)."""
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(3)
    slots, rooms = random_assignment(rng, small_problem, 32)
    pen, hcv, scv = (np.asarray(x)
                     for x in fitness.batch_penalty(pa, slots, rooms))
    expected = np.where(hcv == 0, scv, fitness.INFEASIBLE_OFFSET + hcv)
    np.testing.assert_array_equal(pen, expected)


def test_handcrafted_scv_last_slot():
    """One event in the last slot of a day costs its student count."""
    from timetabling_ga_tpu.problem import derive
    attends = np.array([[1], [1], [0]], dtype=np.int8)  # 2 of 3 students
    problem = derive(1, 1, 1, 3, room_size=np.array([5]),
                     attends=attends,
                     room_features=np.ones((1, 1), np.int8),
                     event_features=np.zeros((1, 1), np.int8))
    pa = problem.device_arrays()
    slots = np.array([[8]], dtype=np.int32)   # last slot of day 0
    rooms = np.array([[0]], dtype=np.int32)
    pen, hcv, scv = fitness.batch_penalty(pa, slots, rooms)
    # last-slot costs 2; each of the two students has a single class
    # that day (+1 each) => scv = 4
    assert int(hcv[0]) == 0
    assert int(scv[0]) == 4
    assert int(pen[0]) == 4


def test_handcrafted_consecutive():
    """A student with 3 consecutive classes incurs exactly +1."""
    from timetabling_ga_tpu.problem import derive
    # 3 events, 1 student attending all, 3 rooms so no clashes
    attends = np.ones((1, 3), dtype=np.int8)
    problem = derive(3, 3, 1, 1, room_size=np.array([5, 5, 5]),
                     attends=attends,
                     room_features=np.ones((3, 1), np.int8),
                     event_features=np.zeros((3, 1), np.int8))
    pa = problem.device_arrays()
    slots = np.array([[0, 1, 2]], dtype=np.int32)
    rooms = np.array([[0, 1, 2]], dtype=np.int32)
    _, hcv, scv = fitness.batch_penalty(pa, slots, rooms)
    # events share the student => all three in same slot would be hcv;
    # here they are consecutive: all 3 correlated pairwise but in
    # different slots -> hcv = 0. scv: one run of 3 => +1; no single-class
    # day; no last slot. => scv == 1
    assert int(hcv[0]) == 0
    assert int(scv[0]) == 1


def test_handcrafted_hcv_clashes():
    from timetabling_ga_tpu.problem import derive
    # 2 events, disjoint students, same room same slot => 1 hcv pair
    attends = np.array([[1, 0], [0, 1]], dtype=np.int8)
    problem = derive(2, 2, 1, 2, room_size=np.array([5, 5]),
                     attends=attends,
                     room_features=np.ones((2, 1), np.int8),
                     event_features=np.zeros((2, 1), np.int8))
    pa = problem.device_arrays()
    slots = np.array([[3, 3]], dtype=np.int32)
    rooms = np.array([[1, 1]], dtype=np.int32)
    _, hcv, _ = fitness.batch_penalty(pa, slots, rooms)
    assert int(hcv[0]) == 1  # room clash only; no shared students

    # correlated events in same slot, different rooms => 1 hcv
    attends2 = np.array([[1, 1]], dtype=np.int8)
    problem2 = derive(2, 2, 1, 1, room_size=np.array([5, 5]),
                      attends=attends2,
                      room_features=np.ones((2, 1), np.int8),
                      event_features=np.zeros((2, 1), np.int8))
    pa2 = problem2.device_arrays()
    rooms2 = np.array([[0, 1]], dtype=np.int32)
    _, hcv2, _ = fitness.batch_penalty(pa2, slots, rooms2)
    assert int(hcv2[0]) == 1
