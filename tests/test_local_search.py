"""Batched local-search tests (ops/local_search.py).

Key properties: penalty never worsens (hill-climb acceptance), feasibility
is never broken once reached (the penalty encoding's phase-2 gate,
Solution.cpp:619-768 semantics), and search makes real progress from
random starts.
"""

import numpy as np
import jax
import pytest

from timetabling_ga_tpu.ops import fitness, ga, local_search
from timetabling_ga_tpu.problem import random_instance


def test_never_worsens(small_problem):
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 16)
    pen0 = np.asarray(st.penalty)
    s2, r2 = local_search.batch_local_search(
        pa, jax.random.key(1), st.slots, st.rooms, n_rounds=20)
    pen1, _, _ = fitness.batch_penalty(pa, s2, r2)
    assert (np.asarray(pen1) <= pen0).all()


def test_feasible_stays_feasible(small_problem):
    """Once hcv==0, accepted moves can never re-break feasibility: an
    infeasible candidate has penalty >= 1e6 > any scv."""
    pa = small_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(2), 32)
    s2, r2 = local_search.batch_local_search(
        pa, jax.random.key(3), st.slots, st.rooms, n_rounds=60)
    _, hcv1, _ = fitness.batch_penalty(pa, s2, r2)
    s3, r3 = local_search.batch_local_search(
        pa, jax.random.key(4), s2, r2, n_rounds=30)
    _, hcv2, _ = fitness.batch_penalty(pa, s3, r3)
    was_feasible = np.asarray(hcv1) == 0
    assert (np.asarray(hcv2)[was_feasible] == 0).all()


def test_makes_progress(medium_problem):
    """From random starts, mean penalty must drop substantially."""
    pa = medium_problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(5), 16)
    pen0 = np.asarray(st.penalty).mean()
    s2, r2 = local_search.jit_batch_local_search(
        pa, jax.random.key(6), st.slots, st.rooms, n_rounds=50,
        n_candidates=8)
    pen1, _, _ = fitness.batch_penalty(pa, s2, r2)
    assert np.asarray(pen1).mean() < pen0


@pytest.mark.slow
def test_memetic_generation_beats_plain(request):
    """A memetic generation (GA + LS) must reach feasibility faster than
    plain GA on a small instance — the whole point of the memetic design
    (ga.cpp:574 runs localSearch on every child)."""
    problem = random_instance(21, n_events=25, n_rooms=5, n_features=2,
                              n_students=15, attend_prob=0.12)
    pa = problem.device_arrays()
    cfg = ga.GAConfig(pop_size=16, ls_steps=10, ls_candidates=8)
    st = ga.init_population(pa, jax.random.key(7), 16)
    st, _ = ga.run(pa, jax.random.key(8), st, cfg, 10)
    assert int(st.hcv[0]) == 0
