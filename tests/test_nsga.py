"""NSGA-II selection tests (ops/nsga.py): front ranking vs a brute-force
oracle, crowding-distance boundary behavior, survivor properties, and a
multi-objective evolution run.
"""

import numpy as np
import jax
import jax.numpy as jnp

from timetabling_ga_tpu.ops import ga, nsga
from timetabling_ga_tpu.problem import random_instance


def _oracle_ranks(hcv, scv):
    """Brute-force front peeling."""
    n = len(hcv)
    pts = list(zip(hcv, scv))

    def dominates(a, b):
        return a[0] <= b[0] and a[1] <= b[1] and a != b \
            and (a[0] < b[0] or a[1] < b[1])

    ranks = [-1] * n
    assigned = 0
    f = 0
    while assigned < n:
        front = [i for i in range(n) if ranks[i] < 0 and not any(
            ranks[j] < 0 and dominates(pts[j], pts[i]) for j in range(n))]
        for i in front:
            ranks[i] = f
        assigned += len(front)
        f += 1
    return ranks


def test_ranks_match_oracle():
    rng = np.random.default_rng(0)
    hcv = rng.integers(0, 6, 60).astype(np.int32)
    scv = rng.integers(0, 40, 60).astype(np.int32)
    got = np.asarray(nsga.nondominated_ranks(jnp.asarray(hcv),
                                             jnp.asarray(scv)))
    want = _oracle_ranks(hcv.tolist(), scv.tolist())
    np.testing.assert_array_equal(got, want)


def test_ranks_with_duplicates():
    """Duplicate points do not dominate each other — all in one front."""
    hcv = jnp.asarray(np.array([2, 2, 2], np.int32))
    scv = jnp.asarray(np.array([5, 5, 5], np.int32))
    got = np.asarray(nsga.nondominated_ranks(hcv, scv))
    np.testing.assert_array_equal(got, [0, 0, 0])


def test_crowding_boundaries_infinite():
    hcv = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    scv = jnp.asarray(np.array([30, 20, 10, 0], np.int32))  # one front
    ranks = nsga.nondominated_ranks(hcv, scv)
    assert (np.asarray(ranks) == 0).all()
    crowd = np.asarray(nsga.crowding_distance(hcv, scv, ranks))
    assert np.isinf(crowd[0]) and np.isinf(crowd[3])
    assert np.isfinite(crowd[1]) and np.isfinite(crowd[2])


def _oracle_crowding(hcv, scv, ranks):
    """Scalar re-statement of crowding_distance's documented formula:
    within each front, per objective, boundary members get +inf and
    interior members get (next - prev) / global_range, summed over both
    objectives. Tie order follows the stable (rank, obj, index) sort."""
    n = len(hcv)
    dist = [0.0] * n
    for obj in (hcv, scv):
        rng = max(max(obj) - min(obj), 1.0)
        order = sorted(range(n), key=lambda i: (ranks[i], obj[i], i))
        for pos, i in enumerate(order):
            interior = (pos > 0 and ranks[order[pos - 1]] == ranks[i]
                        and pos < n - 1
                        and ranks[order[pos + 1]] == ranks[i])
            if interior:
                dist[i] += (obj[order[pos + 1]] - obj[order[pos - 1]]) / rng
            else:
                dist[i] = float("inf")
    return dist


def test_crowding_multi_front_matches_oracle():
    """Regression for the round-1 int32-truncation bug: with >1 front the
    shifted-int64 key collapsed to the bare objective and every interior
    individual got +inf. Exact within-front ordering is now required."""
    # front 0: (0,30) (1,20) (2,10) (3,0); front 1: (2,30) (3,25) (4,20)
    hcv = np.array([0, 1, 2, 3, 2, 3, 4], np.int32)
    scv = np.array([30, 20, 10, 0, 30, 25, 20], np.int32)
    ranks = nsga.nondominated_ranks(jnp.asarray(hcv), jnp.asarray(scv))
    np.testing.assert_array_equal(np.asarray(ranks), [0, 0, 0, 0, 1, 1, 1])
    crowd = np.asarray(nsga.crowding_distance(jnp.asarray(hcv),
                                              jnp.asarray(scv), ranks))
    want = _oracle_crowding(hcv.tolist(), scv.tolist(),
                            np.asarray(ranks).tolist())
    # interior members of BOTH fronts must be finite (indices 1, 2, 5)
    assert np.isfinite(crowd[[1, 2, 5]]).all()
    assert np.isinf(crowd[[0, 3, 4, 6]]).all()
    np.testing.assert_allclose(crowd, want, rtol=1e-6)


def test_crowding_multi_front_random_matches_oracle():
    rng = np.random.default_rng(7)
    hcv = rng.integers(0, 5, 40).astype(np.int32)
    scv = rng.integers(0, 30, 40).astype(np.int32)
    ranks = nsga.nondominated_ranks(jnp.asarray(hcv), jnp.asarray(scv))
    crowd = np.asarray(nsga.crowding_distance(jnp.asarray(hcv),
                                              jnp.asarray(scv), ranks))
    want = _oracle_crowding(hcv.tolist(), scv.tolist(),
                            np.asarray(ranks).tolist())
    np.testing.assert_allclose(crowd, want, rtol=1e-6)


def test_survivor_order_rank_then_crowding():
    """Survivors come out rank-ascending, and within a rank
    crowding-descending — the exact crowded-comparison order."""
    rng = np.random.default_rng(3)
    hcv = rng.integers(0, 5, 48).astype(np.int32)
    scv = rng.integers(0, 40, 48).astype(np.int32)
    ranks = np.asarray(nsga.nondominated_ranks(jnp.asarray(hcv),
                                               jnp.asarray(scv)))
    crowd = np.asarray(nsga.crowding_distance(
        jnp.asarray(hcv), jnp.asarray(scv), jnp.asarray(ranks)))
    keep = np.asarray(nsga.nsga_survivor_indices(
        jnp.asarray(hcv), jnp.asarray(scv), 48))
    kr, kc = ranks[keep], crowd[keep]
    assert (np.diff(kr) >= 0).all()
    same = kr[1:] == kr[:-1]
    # within a front, crowding must be non-increasing
    assert (kc[1:][same] <= kc[:-1][same] + 1e-6).all()


def test_crowded_tournament_prefers_lower_rank_then_crowding():
    ranks = jnp.asarray(np.array([1, 0, 0, 2], np.int32))
    crowd = jnp.asarray(np.array([np.inf, 0.5, 2.0, np.inf], np.float32))
    for s in range(20):
        key = jax.random.key(s)
        win = int(nsga.crowded_tournament(key, ranks, crowd, 4))
        draws = np.asarray(jax.random.randint(key, (4,), 0, 4))
        # the winner must be lexicographically minimal in
        # (rank asc, crowding desc) among the drawn contestants
        best = min(draws.tolist(),
                   key=lambda i: (int(ranks[i]), -float(crowd[i])))
        assert (int(ranks[win]), -float(crowd[win])) == \
            (int(ranks[best]), -float(crowd[best]))


def test_survivors_keep_pareto_front():
    rng = np.random.default_rng(1)
    hcv = rng.integers(0, 5, 64).astype(np.int32)
    scv = rng.integers(0, 50, 64).astype(np.int32)
    keep = np.asarray(nsga.nsga_survivor_indices(
        jnp.asarray(hcv), jnp.asarray(scv), 32))
    assert len(set(keep.tolist())) == 32
    ranks = _oracle_ranks(hcv.tolist(), scv.tolist())
    front0 = {i for i in range(64) if ranks[i] == 0}
    if len(front0) <= 32:
        assert front0.issubset(set(keep.tolist()))


def test_multi_objective_run_reaches_feasibility():
    problem = random_instance(41, n_events=20, n_rooms=6, n_features=2,
                              n_students=12, attend_prob=0.08)
    pa = problem.device_arrays()
    cfg = ga.GAConfig(pop_size=32, multi_objective=True)
    st = ga.init_population(pa, jax.random.key(0), 32)
    st, _ = ga.run(pa, jax.random.key(1), st, cfg, 60)
    assert int(st.hcv[0]) == 0


def test_generation_uses_crowded_parent_selection(monkeypatch):
    """--nsga2 must wire BOTH halves of NSGA-II: front-based replacement
    AND crowded-comparison parent selection (VERDICT round-2 item 5 —
    crowded_tournament was dead code in round 2). Sentinel-patch the
    parent selector: the multi-objective generation must reach it, the
    scalar generation must not."""
    problem = random_instance(7, n_events=12, n_rooms=4, n_features=2,
                              n_students=8, attend_prob=0.1)
    pa = problem.device_arrays()
    st = ga.init_population(pa, jax.random.key(0), 8)

    calls = []
    real = nsga.crowded_tournament

    def spy(key, ranks, crowd, k):
        calls.append(1)
        return real(key, ranks, crowd, k)

    monkeypatch.setattr(nsga, "crowded_tournament", spy)
    ga.generation(pa, jax.random.key(1), st,
                  ga.GAConfig(pop_size=8, multi_objective=True))
    assert calls, "multi-objective generation skipped crowded_tournament"
    n = len(calls)
    ga.generation(pa, jax.random.key(1), st, ga.GAConfig(pop_size=8))
    assert len(calls) == n, "scalar generation used crowded_tournament"
