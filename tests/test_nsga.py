"""NSGA-II selection tests (ops/nsga.py): front ranking vs a brute-force
oracle, crowding-distance boundary behavior, survivor properties, and a
multi-objective evolution run.
"""

import numpy as np
import jax
import jax.numpy as jnp

from timetabling_ga_tpu.ops import ga, nsga
from timetabling_ga_tpu.problem import random_instance


def _oracle_ranks(hcv, scv):
    """Brute-force front peeling."""
    n = len(hcv)
    pts = list(zip(hcv, scv))

    def dominates(a, b):
        return a[0] <= b[0] and a[1] <= b[1] and a != b \
            and (a[0] < b[0] or a[1] < b[1])

    ranks = [-1] * n
    assigned = 0
    f = 0
    while assigned < n:
        front = [i for i in range(n) if ranks[i] < 0 and not any(
            ranks[j] < 0 and dominates(pts[j], pts[i]) for j in range(n))]
        for i in front:
            ranks[i] = f
        assigned += len(front)
        f += 1
    return ranks


def test_ranks_match_oracle():
    rng = np.random.default_rng(0)
    hcv = rng.integers(0, 6, 60).astype(np.int32)
    scv = rng.integers(0, 40, 60).astype(np.int32)
    got = np.asarray(nsga.nondominated_ranks(jnp.asarray(hcv),
                                             jnp.asarray(scv)))
    want = _oracle_ranks(hcv.tolist(), scv.tolist())
    np.testing.assert_array_equal(got, want)


def test_ranks_with_duplicates():
    """Duplicate points do not dominate each other — all in one front."""
    hcv = jnp.asarray(np.array([2, 2, 2], np.int32))
    scv = jnp.asarray(np.array([5, 5, 5], np.int32))
    got = np.asarray(nsga.nondominated_ranks(hcv, scv))
    np.testing.assert_array_equal(got, [0, 0, 0])


def test_crowding_boundaries_infinite():
    hcv = jnp.asarray(np.array([0, 1, 2, 3], np.int32))
    scv = jnp.asarray(np.array([30, 20, 10, 0], np.int32))  # one front
    ranks = nsga.nondominated_ranks(hcv, scv)
    assert (np.asarray(ranks) == 0).all()
    crowd = np.asarray(nsga.crowding_distance(hcv, scv, ranks))
    assert np.isinf(crowd[0]) and np.isinf(crowd[3])
    assert np.isfinite(crowd[1]) and np.isfinite(crowd[2])


def test_survivors_keep_pareto_front():
    rng = np.random.default_rng(1)
    hcv = rng.integers(0, 5, 64).astype(np.int32)
    scv = rng.integers(0, 50, 64).astype(np.int32)
    keep = np.asarray(nsga.nsga_survivor_indices(
        jnp.asarray(hcv), jnp.asarray(scv), 32))
    assert len(set(keep.tolist())) == 32
    ranks = _oracle_ranks(hcv.tolist(), scv.tolist())
    front0 = {i for i in range(64) if ranks[i] == 0}
    if len(front0) <= 32:
        assert front0.issubset(set(keep.tolist()))


def test_multi_objective_run_reaches_feasibility():
    problem = random_instance(41, n_events=20, n_rooms=6, n_features=2,
                              n_students=12, attend_prob=0.08)
    pa = problem.device_arrays()
    cfg = ga.GAConfig(pop_size=32, multi_objective=True)
    st = ga.init_population(pa, jax.random.key(0), 32)
    st, _ = ga.run(pa, jax.random.key(1), st, cfg, 60)
    assert int(st.hcv[0]) == 0
