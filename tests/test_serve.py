"""tt-serve (ISSUE 4): shape bucketing, the job queue, the packing/
time-slicing scheduler, and the line-JSON service frontend.

The two acceptance properties pinned here:

  1. padding is NEUTRAL: a bucket-padded instance evaluates (penalty,
     hcv, scv) bit-exactly equal to the unpadded instance for any
     genotype, on the committed ITC fixtures — and the greedy matcher
     assigns live events the same rooms;
  2. the bucket is the compile key: two instances of DIFFERENT sizes
     in the same bucket trigger exactly one trace of each island
     program (islands.TRACE_COUNTS), and a third job into the warm
     bucket compiles nothing.
"""

import io
import json
import os

import numpy as np
import pytest

from timetabling_ga_tpu.ops import fitness, ga
from timetabling_ga_tpu.ops.rooms import (
    batch_assign_rooms, batch_parallel_assign_rooms)
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import (
    dump_tim, load_tim_file, random_instance)
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import ServeConfig, parse_serve_args
from timetabling_ga_tpu.serve import (
    AdmissionError, BucketSpec, Job, JobQueue, JobState, bucket_dims,
    bucket_key, pad_problem)
from timetabling_ga_tpu.serve.bucket import embed_population
from timetabling_ga_tpu.serve.service import SolveService, serve_stream

FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "fixtures")

SPEC = BucketSpec()


def _cfg(**kw):
    kw.setdefault("backend", "cpu")
    kw.setdefault("lanes", 2)
    kw.setdefault("quantum", 10)
    kw.setdefault("pop_size", 6)
    kw.setdefault("max_steps", 8)
    return ServeConfig(**kw)


def _records(buf):
    return [json.loads(x) for x in buf.getvalue().splitlines()]


def _job_records(lines, job_id):
    out = []
    for rec in lines:
        kind = next(iter(rec))
        if rec[kind].get("job") == job_id:
            out.append(rec)
    return out


# ---------------------------------------------------------------- bucketing

def test_bucket_dims_geometric():
    p = random_instance(0, n_events=20, n_rooms=3, n_features=2,
                        n_students=12, attend_prob=0.1)
    assert bucket_dims(p, SPEC) == (32, 4, 4, 32)
    q = random_instance(0, n_events=33, n_rooms=9, n_features=5,
                        n_students=70, attend_prob=0.05)
    assert bucket_dims(q, SPEC) == (64, 16, 8, 128)
    # the slot grid is part of the key, never padded
    assert bucket_key(p, SPEC) == (32, 4, 4, 32, 5, 9)
    # idempotent: an exactly-bucket-shaped instance keeps its dims
    pp = pad_problem(p, SPEC)
    assert bucket_dims(pp, SPEC) == (32, 4, 4, 32)
    assert (pp.n_events, pp.n_rooms, pp.n_features,
            pp.n_students) == (32, 4, 4, 32)
    assert pp.n_live_events == 20 and pp.n_live_rooms == 3


def test_padding_contract_possible_and_masks():
    p = random_instance(3, n_events=10, n_rooms=3, n_features=2,
                        n_students=8, attend_prob=0.2)
    pp = pad_problem(p, SPEC)
    assert not pp.possible[p.n_events:, :].any()    # padded events: none
    assert not pp.possible[:, p.n_rooms:].any()     # padded rooms: none
    np.testing.assert_array_equal(pp.possible[:10, :3], p.possible)
    pa = pp.device_arrays()
    np.testing.assert_array_equal(
        np.asarray(pa.event_mask), (np.arange(32) < 10).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(pa.room_mask), np.arange(4) < 3)
    # padded events carry nothing: zero attendance, zero conflict
    assert pp.attends[:, 10:].sum() == 0
    assert pp.student_count[10:].sum() == 0
    assert not pp.conflict[10:, :].any()


@pytest.mark.parametrize("name", ["comp01s", "comp05s"])
def test_padded_penalty_bit_exact_on_itc_fixture(name):
    """ISSUE 4 acceptance: padded bucket evaluation is bit-exact with
    unpadded on the ITC fixtures — penalty, hcv AND scv, per
    individual, for arbitrary genotypes."""
    p = load_tim_file(os.path.join(FIXTURES, f"{name}.tim"))
    pp = pad_problem(p, SPEC)
    rng = np.random.default_rng(7)
    P = 4
    slots = rng.integers(0, p.n_slots, size=(P, p.n_events)).astype(
        np.int32)
    rooms = rng.integers(0, p.n_rooms, size=(P, p.n_events)).astype(
        np.int32)
    s_pad, r_pad = embed_population(slots, rooms, pp)

    pen, hcv, scv = fitness.batch_penalty(p.device_arrays(), slots, rooms)
    pen2, hcv2, scv2 = fitness.batch_penalty(pp.device_arrays(),
                                             s_pad, r_pad)
    np.testing.assert_array_equal(np.asarray(pen), np.asarray(pen2))
    np.testing.assert_array_equal(np.asarray(hcv), np.asarray(hcv2))
    np.testing.assert_array_equal(np.asarray(scv), np.asarray(scv2))


@pytest.mark.parametrize("name", ["comp01s"])
def test_padded_matching_bit_exact_on_itc_fixture(name):
    """The greedy matcher gives LIVE events identical rooms on the
    padded instance (padded rooms carry the _W_DEAD key penalty and
    padded events occupy nothing, so every live argmin is preserved)."""
    p = load_tim_file(os.path.join(FIXTURES, f"{name}.tim"))
    pp = pad_problem(p, SPEC)
    rng = np.random.default_rng(11)
    P = 3
    slots = rng.integers(0, p.n_slots, size=(P, p.n_events)).astype(
        np.int32)
    s_pad, _ = embed_population(slots, np.zeros_like(slots), pp)

    rooms = np.asarray(batch_assign_rooms(p.device_arrays(), slots))
    rooms_pad = np.asarray(batch_assign_rooms(pp.device_arrays(), s_pad))
    np.testing.assert_array_equal(rooms, rooms_pad[:, :p.n_events])
    # live events never land in a padded (dead) room
    assert (rooms_pad[:, :p.n_events] < p.n_rooms).all()

    par = np.asarray(batch_parallel_assign_rooms(p.device_arrays(), slots))
    par_pad = np.asarray(
        batch_parallel_assign_rooms(pp.device_arrays(), s_pad))
    np.testing.assert_array_equal(par, par_pad[:, :p.n_events])


def test_padded_event_deltas_are_zero():
    """A padded event's relocation has EXACTLY zero delta on every
    delta-evaluation path (sweep Move1 and the shared 3-relocation
    kernel) — a padded move may be taken, but can never change a
    penalty or corrupt a live event's maintained occupancy."""
    import jax.numpy as jnp
    from timetabling_ga_tpu.ops.delta import _delta_one, init_state
    from timetabling_ga_tpu.ops.rooms import capacity_rank
    from timetabling_ga_tpu.ops.sweep import _move1_sweep

    p = random_instance(5, n_events=12, n_rooms=3, n_features=2,
                        n_students=10, attend_prob=0.2)
    pp = pad_problem(p, SPEC)
    pa = pp.device_arrays()
    rng = np.random.default_rng(0)
    slots = rng.integers(0, p.n_slots, size=(1, pp.n_events)).astype(
        np.int32)
    rooms = np.asarray(batch_assign_rooms(pa, jnp.asarray(slots)))
    st = init_state(pa, jnp.asarray(slots), jnp.asarray(rooms))
    cap = capacity_rank(pa)

    padded_e = jnp.int32(p.n_events + 1)        # a padding event
    d_hcv, d_scv, _ = _move1_sweep(
        pa, st.slots[0], st.rooms[0], st.att[0], st.occ[0], padded_e, cap)
    assert not np.asarray(d_hcv).any()
    assert not np.asarray(d_scv).any()

    evs = jnp.asarray([p.n_events + 1, p.n_events + 2, 0], jnp.int32)
    ns = jnp.asarray([3, 4, int(slots[0, 0])], jnp.int32)
    active = jnp.asarray([True, True, False])
    dh, ds, _ = _delta_one(pa, st.slots[0], st.rooms[0], st.att[0],
                           st.occ[0], evs, ns, active, cap)
    assert int(dh) == 0 and int(ds) == 0


def test_oversize_bucket_rejected_cleanly():
    """Geometric rounding must not manufacture an instance that trips
    the room-key packing bound (`assert E < 4096`, ops/rooms.py) at
    trace time: pad_problem rejects it at admission, and a failing
    submit leaves the service fully usable with the queue untouched."""
    big = random_instance(99, n_events=2500, n_rooms=3, n_features=2,
                          n_students=5, attend_prob=0.01)
    with pytest.raises(ValueError, match="packing bound"):
        pad_problem(big, SPEC)

    buf = io.StringIO()
    svc = SolveService(_cfg(), out=buf)
    with pytest.raises(ValueError, match="packing bound"):
        svc.submit(big, job_id="huge", generations=5)
    assert len(svc.queue) == 0        # no half-admitted job left behind
    ok = svc.submit(random_instance(98, n_events=10, n_rooms=3,
                                    n_features=2, n_students=6,
                                    attend_prob=0.2), generations=5)
    svc.drive()
    svc.close()
    assert svc.state(ok) == JobState.DONE


def test_unpadded_instances_have_all_live_masks(small_problem):
    """Every pre-serve construction path yields all-ones masks — the
    masked kernels then reduce to the unmasked math exactly (the whole
    existing suite is the regression net for that)."""
    pa = small_problem.device_arrays()
    assert np.asarray(pa.event_mask).all()
    assert np.asarray(pa.room_mask).all()


# ---------------------------------------------------------------- queue

def test_queue_admission_priority_cancel():
    q = JobQueue(backlog=2)
    p = random_instance(0, n_events=8, n_rooms=2, n_features=2,
                        n_students=5, attend_prob=0.2)
    a = Job(id="a", problem=p, priority=0)
    b = Job(id="b", problem=p, priority=5)
    q.submit(a)
    q.submit(b)
    with pytest.raises(AdmissionError, match="backlog full"):
        q.submit(Job(id="c", problem=p))
    # cancel frees a backlog slot; duplicate ids stay rejected
    assert q.cancel("a")
    with pytest.raises(AdmissionError, match="duplicate"):
        q.submit(Job(id="b", problem=p))
    q.submit(Job(id="d", problem=p))
    # priority first, FIFO within
    assert [j.id for j in q.ready()] == ["b", "d"]
    assert q.get("a").state == JobState.CANCELLED
    # least-served overtakes within a priority class
    q.get("b").priority = 0
    q.get("b").gens_done = 50
    assert [j.id for j in q.ready()] == ["d", "b"]
    assert not q.cancel("a")          # terminal: cancel is a no-op


# ------------------------------------------------------- compile-once

def test_bucket_compile_reuse_exactly_one_trace():
    """ISSUE 4 acceptance: two .tim instances of DIFFERENT sizes in the
    same bucket trigger exactly one trace/compile of each island
    program; a third job into the warm bucket adds zero."""
    p1 = random_instance(21, n_events=18, n_rooms=3, n_features=2,
                         n_students=14, attend_prob=0.1)
    p2 = random_instance(22, n_events=27, n_rooms=4, n_features=2,
                         n_students=20, attend_prob=0.1)
    assert bucket_key(p1, SPEC) == bucket_key(p2, SPEC)
    assert (p1.n_events, p1.n_rooms) != (p2.n_events, p2.n_rooms)

    # fresh programs: drop any cached lane programs from earlier tests
    from timetabling_ga_tpu.runtime import engine
    for cache in (engine._RUNNER_CACHE, engine._INIT_CACHE):
        for k in [k for k in cache
                  if isinstance(k[0], str) and k[0].startswith("lane")]:
            del cache[k]
    before = dict(islands.TRACE_COUNTS)

    buf = io.StringIO()
    svc = SolveService(_cfg(), out=buf)
    a = svc.submit(p1, generations=15, seed=1)
    b = svc.submit(p2, generations=15, seed=2)
    svc.drive()
    assert svc.state(a) == svc.state(b) == JobState.DONE
    mid = dict(islands.TRACE_COUNTS)
    assert mid.get("lane_init", 0) - before.get("lane_init", 0) == 1
    assert mid.get("lane_runner", 0) - before.get("lane_runner", 0) == 1

    # a third, different-size job into the WARM bucket: zero compiles
    p3 = random_instance(23, n_events=24, n_rooms=2, n_features=3,
                         n_students=9, attend_prob=0.1)
    assert bucket_key(p3, SPEC) == bucket_key(p1, SPEC)
    c = svc.submit(p3, generations=5, seed=3)
    svc.drive()
    svc.close()
    assert svc.state(c) == JobState.DONE
    assert dict(islands.TRACE_COUNTS) == mid


# ------------------------------------------------------- scheduling

def test_small_late_job_completes_while_long_job_runs():
    """ISSUE 4 satellite: with ONE lane, a small job submitted AFTER a
    long job still completes while the long job is mid-flight — the
    least-served ordering hands it the lane at the next control fence
    instead of letting the long job monopolize the hardware."""
    long_p = random_instance(31, n_events=16, n_rooms=3, n_features=2,
                             n_students=10, attend_prob=0.1)
    small_p = random_instance(32, n_events=12, n_rooms=3, n_features=2,
                              n_students=8, attend_prob=0.1)
    buf = io.StringIO()
    svc = SolveService(_cfg(lanes=1, quantum=5), out=buf)
    long_id = svc.submit(long_p, generations=100, seed=1)
    assert svc.step()                 # long job takes the first quantum
    small_id = svc.submit(small_p, generations=5, seed=2)
    assert svc.step()                 # fence: small job gets the lane
    assert svc.state(small_id) == JobState.DONE
    assert svc.state(long_id) in (JobState.PARKED, JobState.RUNNING)
    assert svc.queue.get(long_id).gens_done < 100
    svc.drive()
    assert svc.state(long_id) == JobState.DONE
    svc.close()
    # the small job's records all precede the long job's terminal ones
    lines = _records(buf)
    kinds = [(next(iter(r)), r[next(iter(r))].get("job"))
             for r in lines]
    assert kinds.index(("runEntry", small_id)) < kinds.index(
        ("runEntry", long_id))


def test_job_stream_independent_of_co_tenants():
    """RNG isolation: a job's records are bit-identical (modulo timing)
    whether it runs alone or packed with another tenant — lane RNG
    derives from (job seed, job progress), never from lane position or
    dispatch mix."""
    p = random_instance(41, n_events=14, n_rooms=3, n_features=2,
                        n_students=10, attend_prob=0.15)
    other = random_instance(42, n_events=22, n_rooms=4, n_features=2,
                            n_students=12, attend_prob=0.1)

    buf_solo = io.StringIO()
    svc = SolveService(_cfg(), out=buf_solo)
    a = svc.submit(p, job_id="target", generations=25, seed=5)
    svc.drive()
    svc.close()
    assert svc.state(a) == JobState.DONE

    buf_packed = io.StringIO()
    svc2 = SolveService(_cfg(), out=buf_packed)
    svc2.submit(other, job_id="noise", generations=40, seed=6)
    svc2.submit(p, job_id="target", generations=25, seed=5)
    svc2.drive()
    svc2.close()

    solo = jsonl.strip_timing(_job_records(_records(buf_solo), "target"))
    packed = jsonl.strip_timing(
        _job_records(_records(buf_packed), "target"))
    assert solo == packed


def test_deadline_cuts_budget_and_prestart_deadline_fails():
    clock = {"t": 0.0}

    def now():
        return clock["t"]

    p = random_instance(51, n_events=10, n_rooms=3, n_features=2,
                        n_students=8, attend_prob=0.15)
    buf = io.StringIO()
    svc = SolveService(_cfg(lanes=1, quantum=5), out=buf, now=now)
    a = svc.submit(p, generations=10_000, seed=1, deadline_s=5.0)
    b = svc.submit(p, generations=10, seed=2, priority=-1,
                   deadline_s=1.0)
    svc.step()                        # a runs one quantum at t=0
    clock["t"] = 10.0                 # both deadlines pass
    svc.drive()
    svc.close()
    assert svc.state(a) == JobState.DONE       # budget CUT, best kept
    assert svc.result(a)["deadline_hit"] is True
    assert svc.result(a)["gens"] < 10_000
    assert svc.state(b) == JobState.FAILED     # never got a slice
    events = [r["jobEntry"]["event"] for r in _records(buf)
              if "jobEntry" in r and r["jobEntry"]["job"] == b]
    assert events == ["admitted", "failed"]


# ------------------------------------------------------- protocol

def test_line_json_protocol_end_to_end(tmp_path):
    p1 = random_instance(61, n_events=10, n_rooms=3, n_features=2,
                         n_students=8, attend_prob=0.15)
    p2 = random_instance(62, n_events=13, n_rooms=3, n_features=2,
                         n_students=9, attend_prob=0.15)
    tim_path = tmp_path / "p1.tim"
    tim_path.write_text(dump_tim(p1))
    requests = "\n".join([
        json.dumps({"submit": {"id": "f", "instance": str(tim_path),
                               "generations": 10, "seed": 3}}),
        json.dumps({"submit": {"id": "i", "tim": dump_tim(p2),
                               "generations": 10, "seed": 4,
                               "priority": 2}}),
        json.dumps({"submit": {"id": "bad", "instance": "/no/such"}}),
        "not json at all",
        json.dumps({"cancel": "f"}),
        json.dumps({"drain": True}),
    ]) + "\n"
    buf = io.StringIO()
    svc = serve_stream(_cfg(backlog=8), io.StringIO(requests),
                       out_stream=buf)
    lines = _records(buf)
    events = [(r["jobEntry"]["job"], r["jobEntry"]["event"])
              for r in lines if "jobEntry" in r]
    assert ("f", "admitted") in events
    assert ("i", "admitted") in events
    assert ("bad", "rejected") in events
    assert ("?", "rejected") in events          # the non-JSON line
    assert ("f", "cancelled") in events
    assert ("i", "done") in events
    # the cancelled job produced no solve records; the served one did
    assert _job_records(lines, "f") == [
        r for r in lines if "jobEntry" in r
        and r["jobEntry"]["job"] == "f"]
    i_recs = _job_records(lines, "i")
    assert any("solution" in r for r in i_recs)
    assert any("runEntry" in r for r in i_recs)
    assert any("logEntry" in r for r in i_recs)
    assert svc.state("i") == JobState.DONE
    assert svc.result("i")["gens"] == 10


def test_backlog_admission_control():
    p = random_instance(71, n_events=8, n_rooms=2, n_features=2,
                        n_students=6, attend_prob=0.2)
    buf = io.StringIO()
    svc = SolveService(_cfg(backlog=1), out=buf)
    svc.submit(p, job_id="one", generations=5)
    with pytest.raises(AdmissionError):
        svc.submit(p, job_id="two", generations=5)
    svc.drive()
    svc.submit(p, job_id="three", generations=5)   # slot freed
    svc.drive()
    svc.close()
    assert svc.state("three") == JobState.DONE


def test_parse_serve_args_and_validation():
    cfg = parse_serve_args(["--lanes", "8", "--quantum", "50",
                            "--backlog", "16", "--backend", "cpu",
                            "--bucket-events", "64"])
    assert (cfg.lanes, cfg.quantum, cfg.backlog) == (8, 50, 16)
    assert cfg.bucket_events == 64 and cfg.backend == "cpu"
    for bad in (["--lanes", "0"], ["--quantum", "0"],
                ["--bucket-ratio", "1.0"], ["--frobnicate", "1"],
                ["--backend", "gpu"]):
        with pytest.raises(SystemExit):
            parse_serve_args(bad)
    with pytest.raises(SystemExit):
        parse_serve_args(["-h"])


def test_solution_record_verifies_against_oracle():
    """The timetable a DONE job reports must evaluate to the reported
    (hcv, scv) under the reference-semantics oracle on the UNPADDED
    instance — the end-to-end proof that serving through a padded
    bucket returns answers about the real problem."""
    from timetabling_ga_tpu.oracle.reference_oracle import (
        oracle_hcv, oracle_scv)
    p = random_instance(81, n_events=12, n_rooms=3, n_features=2,
                        n_students=8, attend_prob=0.1)
    buf = io.StringIO()
    svc = SolveService(_cfg(quantum=20), out=buf)
    a = svc.submit(p, generations=60, seed=1)
    svc.drive()
    svc.close()
    res = svc.result(a)
    slots = np.asarray(res["timeslots"], np.int32)
    rooms = np.asarray(res["rooms"], np.int32)
    assert slots.shape == (p.n_events,)
    assert oracle_hcv(p, slots, rooms) == res["hcv"]
    assert oracle_scv(p, slots) == res["scv"]
