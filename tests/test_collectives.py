"""Compiled-program collective audit.

The island programs may contain EXACTLY the collectives their design
calls for: `lax.ppermute` migration (collective-permute) and the
`lax.pmin` global best (one all-reduce at the epoch boundary). Anything
else is XLA's SPMD partitioner "resolving" an op it cannot keep
shard-local — the failure mode found in round 1: a traced-index gather
on the sweep's shuffled pivot array made the partitioner replicate the
shuffle via masked all-reduces INSIDE the converge while_loop, whose
trip count is legitimately per-island varying. Consequences: every
island silently shared one shuffle stream, and when islands' pass
counts diverged one device exited the loop while the other waited at
the collective rendezvous forever — the CPU-backend deadlock that hung
the whole engine test tier.

These tests compile each runner and count collectives in the optimized
HLO, so a reintroduced hazard fails here with the op's source line
instead of as a wall-clock hang. Static analysis (tt-analyze TT302)
catches the known-bad *sources*; this audit catches the *lowering*,
whatever the source.
"""

import re

import jax
import pytest

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import random_instance

pytestmark = pytest.mark.slow  # compiles ~6 programs (minutes on CPU)


def _collectives(compiled_text: str) -> dict[str, list[str]]:
    """op kind -> [source annotations] for every collective DEFINITION
    in the HLO (a `kind(`-call on the line; operand references to a
    collective's result don't count)."""
    kinds = ("all-reduce", "all-gather", "collective-permute",
             "all-to-all", "reduce-scatter", "all-reduce-start",
             "all-gather-start", "collective-permute-start")
    out: dict[str, list[str]] = {}
    for line in compiled_text.splitlines():
        for kind in kinds:
            if f" {kind}(" in line or f"{kind}-done(" in line:
                src = re.search(r'op_name="([^"]*)"', line)
                out.setdefault(kind, []).append(
                    src.group(1) if src else line.strip()[:120])
    return out


@pytest.fixture(scope="module")
def setup():
    p = random_instance(1, n_events=30, n_rooms=4, n_features=3,
                        n_students=20, attend_prob=0.15)
    pa = p.device_arrays()
    mesh = islands.make_mesh(2)
    cfg = ga.GAConfig(pop_size=8, ls_mode="sweep", ls_sweeps=2,
                      init_sweeps=4, ls_converge=True)
    key = jax.random.key(0)
    state = islands.init_island_population(pa, key, mesh, 8,
                                           ga.GAConfig(pop_size=8),
                                           n_islands=2)
    return p, pa, mesh, cfg, key, state


def test_polish_runner_has_no_collectives(setup):
    """The polish program is island-local by design AND contains the
    per-island-varying converge while_loop: ANY collective inside it is
    both a correctness bug and a deadlock (round-1 hang)."""
    _, pa, mesh, cfg, key, state = setup
    polish = islands.make_polish_runner(mesh, cfg, n_islands=2)
    txt = polish.lower(pa, key, state, 4).compile().as_text()
    assert _collectives(txt) == {}, _collectives(txt)


def test_init_runner_has_no_collectives(setup):
    _, pa, mesh, cfg, key, _ = setup
    init = jax.jit(lambda pa_, k_: islands.init_island_population(
        pa_, k_, mesh, 8, cfg, n_islands=2))
    txt = init.lower(pa, key).compile().as_text()
    assert _collectives(txt) == {}, _collectives(txt)


def test_kick_runner_has_no_collectives(setup):
    _, pa, mesh, cfg, key, state = setup
    kick = islands.make_kick_runner(mesh, cfg, n_islands=2)
    txt = kick.lower(pa, key, state, 3).compile().as_text()
    assert _collectives(txt) == {}, _collectives(txt)


def test_lahc_runners_have_no_collectives(setup):
    _, pa, mesh, cfg, key, state = setup
    init_r, run_r, fin_r = islands.make_lahc_runners(mesh, cfg, 16,
                                                     n_islands=2)
    lstate = init_r(pa, state)
    for prog, args in ((init_r, (pa, state)),
                       (run_r, (pa, key, lstate, 8)),
                       (fin_r, (lstate,))):
        txt = prog.lower(*args).compile().as_text()
        assert _collectives(txt) == {}, _collectives(txt)


def test_island_runner_has_only_designed_collectives(setup):
    """Migration (ppermute) and the global best (pmin) are the design's
    collectives; anything else — especially an all-reduce whose op_name
    is NOT the pmin — is partitioner fallout."""
    _, pa, mesh, cfg, key, state = setup
    runner = islands.make_island_runner(mesh, cfg, n_epochs=1,
                                        gens_per_epoch=2, n_islands=2)
    txt = runner.lower(pa, key, state).compile().as_text()
    col = _collectives(txt)
    assert set(col) <= {"all-reduce", "collective-permute"}, col
    for src in col.get("all-reduce", []):
        assert "pmin" in src or "min" in src, col


def test_dynamic_runner_has_only_designed_collectives(setup):
    _, pa, mesh, cfg, key, state = setup
    runner = islands.make_island_runner_dynamic(mesh, cfg, max_gens=4,
                                                n_islands=2)
    txt = runner.lower(pa, key, state, 2).compile().as_text()
    col = _collectives(txt)
    assert set(col) <= {"all-reduce", "collective-permute"}, col
    for src in col.get("all-reduce", []):
        assert "pmin" in src or "min" in src, col
