"""Shared run recipes for the dispatch-core parity suite.

Each recipe is a pure function of its config and seeds: it runs one of
the three dispatch loops (engine, serve scheduler, fleet replica) and
returns the record stream projected into the strip_timing domain — the
bit-identity domain of every pipeline/obs/fault A/B in the suite
(runtime/jsonl.py TIMING_RECORDS).

The module doubles as the capture tool: `python -m tests.parity_recipes`
writes the streams as JSON under tests/parity_fixtures/.  The committed
fixtures were captured from the PRE-refactor tree (before
runtime/dispatch_core.py existed); tests/test_dispatch_core.py re-runs
the same recipes on the current tree and asserts byte-identity, so any
behavioural drift introduced by the shared-core port shows up as a
record diff, not a vague failure.
"""

import io
import json
import os

from timetabling_ga_tpu.runtime import jsonl

_HERE = os.path.dirname(os.path.abspath(__file__))
FIXDIR = os.path.join(_HERE, "parity_fixtures")
TIM_FIXTURE = os.path.join(os.path.dirname(_HERE), "fixtures",
                           "comp01s.tim")


def _records(buf):
    return [json.loads(x) for x in buf.getvalue().splitlines()]


def engine_stream():
    """The conftest engine_stream_baseline config, stripped: comp01s,
    seed 3, pop 8, islands 2, 30 gens at migration period 10, full
    trace."""
    from timetabling_ga_tpu.runtime import engine as eng
    from timetabling_ga_tpu.runtime.config import RunConfig
    buf = io.StringIO()
    cfg = RunConfig(input=TIM_FIXTURE, seed=3, pop_size=8, islands=2,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    trace=True)
    eng.run(cfg, out=buf)
    return jsonl.strip_timing(_records(buf))


def _serve_problems():
    from timetabling_ga_tpu.problem import random_instance
    p1 = random_instance(11, n_events=14, n_rooms=3, n_features=2,
                         n_students=10, attend_prob=0.2)
    p2 = random_instance(12, n_events=12, n_rooms=3, n_features=2,
                         n_students=9, attend_prob=0.2)
    return p1, p2


def serve_stream():
    """Two same-bucket jobs through the packing scheduler: packing,
    time-slicing, park/resume and the telemetry decode all exercise the
    lane dispatch path."""
    from timetabling_ga_tpu.runtime.config import ServeConfig
    from timetabling_ga_tpu.serve.service import SolveService
    p1, p2 = _serve_problems()
    buf = io.StringIO()
    svc = SolveService(ServeConfig(backend="cpu", lanes=2, quantum=5,
                                   pop_size=4, max_steps=8), out=buf)
    svc.submit(p1, job_id="pa", seed=1, generations=15)
    svc.submit(p2, job_id="pb", seed=2, generations=15)
    svc.drive()
    svc.close()
    return jsonl.strip_timing(_records(buf))


def fleet_stream():
    """The same two jobs through a foreground in-process Replica drive
    loop (no HTTP front): inbox submit -> drive -> drain covers the
    fleet fence protocol end to end."""
    from timetabling_ga_tpu.fleet.replicas import Replica
    from timetabling_ga_tpu.problem import dump_tim
    from timetabling_ga_tpu.runtime.config import ServeConfig
    p1, p2 = _serve_problems()
    buf = io.StringIO()
    rep = Replica(ServeConfig(backend="cpu", lanes=2, quantum=5,
                              pop_size=4, max_steps=8),
                  name="parity", out=buf)
    rep.inbox.put(("submit", "fa",
                   {"tim": dump_tim(p1), "seed": 1, "generations": 15}))
    rep.inbox.put(("submit", "fb",
                   {"tim": dump_tim(p2), "seed": 2, "generations": 15}))
    rep.inbox.put(("drain",))
    rep.run()
    return jsonl.strip_timing(_records(buf))


RECIPES = {
    "engine": engine_stream,
    "serve": serve_stream,
    "fleet": fleet_stream,
}


def main():
    os.makedirs(FIXDIR, exist_ok=True)
    for name, recipe in RECIPES.items():
        path = os.path.join(FIXDIR, f"{name}_stream.json")
        records = recipe()
        with open(path, "w") as f:
            json.dump(records, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {path} ({len(records)} records)")


if __name__ == "__main__":
    main()
