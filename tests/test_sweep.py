"""Sweep local-search tests (ops/sweep.py): Move1 sweep delta exactness
against full re-evaluation, maintained-state invariants after passes, and
search-power comparison against the K-random-candidate search.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from timetabling_ga_tpu.ops import fitness, sweep
from timetabling_ga_tpu.ops.delta import init_state
from timetabling_ga_tpu.ops.local_search import batch_local_search
from timetabling_ga_tpu.ops.rooms import batch_assign_rooms, capacity_rank
from timetabling_ga_tpu.problem import random_instance


@pytest.fixture(scope="module")
def inst():
    problem = random_instance(77, n_events=24, n_rooms=6, n_features=3,
                              n_students=15, attend_prob=0.12)
    return problem, problem.device_arrays()


def _rand_pop(pa, key, P):
    slots = jax.random.randint(key, (P, pa.n_events), 0, pa.n_slots,
                               dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    return slots, rooms


def test_move1_sweep_deltas_exact(inst):
    """Every (event, target-slot) delta must equal full re-evaluation of
    the moved-and-re-roomed solution."""
    problem, pa = inst
    E, T = pa.n_events, pa.n_slots
    slots, rooms = _rand_pop(pa, jax.random.key(0), 1)
    s, r = slots[0], rooms[0]
    st = init_state(pa, slots, rooms)
    att, occ = st.att[0], st.occ[0]
    hcv0, scv0 = int(st.hcv[0]), int(st.scv[0])
    cap_rank = capacity_rank(pa)

    for e in [0, 3, 11, E - 1]:
        d_hcv, d_scv, new_rooms = sweep._move1_sweep(
            pa, s, r, att, occ, jnp.int32(e), cap_rank)
        d_hcv, d_scv = np.asarray(d_hcv), np.asarray(d_scv)
        new_rooms = np.asarray(new_rooms)
        for t in range(T):
            s2 = s.at[e].set(t)
            r2 = r.at[e].set(int(new_rooms[t]))
            _, hcv2, scv2 = fitness.compute_penalty(pa, s2, r2)
            assert int(hcv2) - hcv0 == d_hcv[t], (e, t)
            assert int(scv2) - scv0 == d_scv[t], (e, t)


def test_sweep_pass_state_consistent(inst):
    """After a pass, the maintained (pen, hcv, scv, att, occ) must match
    recomputation from the genotypes."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(1), 8)
    st = init_state(pa, slots, rooms)
    st, improved = sweep.sweep_pass(pa, jax.random.key(2), st,
                                    swap_block=4)
    assert bool(improved)   # a random population always has a move
    pen, hcv, scv = fitness.batch_penalty(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.hcv), np.asarray(hcv))
    np.testing.assert_array_equal(np.asarray(st.scv), np.asarray(scv))
    np.testing.assert_array_equal(np.asarray(st.pen), np.asarray(pen))
    st2 = init_state(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.att), np.asarray(st2.att))
    np.testing.assert_array_equal(np.asarray(st.occ), np.asarray(st2.occ))


def test_sweep_monotone_improvement(inst):
    """Penalties never worsen, and a pass strictly improves a random
    population (it examines every event x 45 targets)."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(3), 8)
    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms)
    s1, r1 = sweep.sweep_local_search(pa, jax.random.key(4), slots, rooms,
                                      n_sweeps=1, swap_block=4)
    pen1, _, _ = fitness.batch_penalty(pa, s1, r1)
    assert (np.asarray(pen1) <= np.asarray(pen0)).all()
    assert (np.asarray(pen1) < np.asarray(pen0)).any()
    # invariant: each event still has exactly one slot/room assignment
    assert s1.shape == slots.shape and r1.shape == rooms.shape
    assert (np.asarray(s1) >= 0).all() and (np.asarray(s1) < pa.n_slots).all()


def test_sweep_acceptance_is_lexicographic(inst):
    """Acceptance uses the (penalty, scv) lexicographic order — the
    reported evaluation's (hcv*1e6+scv) total order: per individual a
    pass may never worsen the pair, and among infeasible individuals
    whose penalty holds, scv may only drop (penalty-only acceptance let
    scv drift while hcv sat at an infeasibility floor — the round-4
    `medium` race regime)."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(21), 8)
    st0 = init_state(pa, slots, rooms)
    st1, _ = sweep.sweep_pass(pa, jax.random.key(22), st0, swap_block=4)
    p0, s0 = np.asarray(st0.pen), np.asarray(st0.scv)
    p1, s1 = np.asarray(st1.pen), np.asarray(st1.scv)
    assert ((p1 < p0) | ((p1 == p0) & (s1 <= s0))).all()


def test_sweep_converge_reaches_local_optimum(inst):
    """converge=True must run passes until the WHOLE population is at a
    Move1+Move2-block local optimum (the reference's stopping rule): one
    more pass on the result accepts nothing."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(7), 6)
    s_c, r_c = sweep.sweep_local_search(pa, jax.random.key(8), slots,
                                        rooms, n_sweeps=50, swap_block=4,
                                        converge=True)
    st = init_state(pa, s_c, r_c)
    # the post-convergence pass must find nothing, under ANY shuffle key
    _, improved = sweep.sweep_pass(pa, jax.random.key(9), st, swap_block=0)
    assert not bool(improved)
    # and it must be at least as good as a fixed 3-pass budget
    pen_c, _, _ = fitness.batch_penalty(pa, s_c, r_c)
    s_f, r_f = sweep.sweep_local_search(pa, jax.random.key(8), slots,
                                        rooms, n_sweeps=3, swap_block=4)
    pen_f, _, _ = fitness.batch_penalty(pa, s_f, r_f)
    assert np.asarray(pen_c).mean() <= np.asarray(pen_f).mean()


@pytest.mark.slow
def test_sweep_beats_random_candidates_at_equal_depth(inst):
    """At equal SERIAL DEPTH — the TPU-relevant cost model: a sweep step
    evaluates P*(T+B) candidates in one wide fused step, while a K-random
    round evaluates P*K; both are one dependent step in the scan chain —
    the systematic sweep must reach better-or-equal mean penalty (VERDICT
    round-1 item 2). Wall-clock superiority on real hardware is measured
    separately by bench.py's LS-mode shootout."""
    problem, pa = inst
    P = 16
    slots, rooms = _rand_pop(pa, jax.random.key(5), P)
    E = pa.n_events
    # sweep: 1 pass = E dependent steps; K-random: E rounds = E steps
    s_r, r_r = batch_local_search(pa, jax.random.key(6), slots, rooms,
                                  n_rounds=E, n_candidates=8)
    pen_r, _, _ = fitness.batch_penalty(pa, s_r, r_r)
    s_s, r_s = sweep.sweep_local_search(pa, jax.random.key(6), slots,
                                        rooms, n_sweeps=1, swap_block=4)
    pen_s, _, _ = fitness.batch_penalty(pa, s_s, r_s)
    assert np.asarray(pen_s).mean() <= np.asarray(pen_r).mean()


def test_block_sweep_monotone_and_improves(small_problem):
    """block_events > 1 (the latency-optimized sweep): penalties stay
    monotone non-increasing per pass, the pass improves a random
    population, and the B = E edge (whole pass in one scan step) works."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    from timetabling_ga_tpu.ops.sweep import sweep_local_search

    pa = small_problem.device_arrays()
    P = 8
    slots = jax.random.randint(jax.random.key(0), (P, pa.n_events), 0,
                               pa.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms)
    for B in (4, pa.n_events):
        s2, r2 = sweep_local_search(pa, jax.random.key(1), slots, rooms,
                                    n_sweeps=3, swap_block=4,
                                    block_events=B)
        pen2, _, _ = fitness.batch_penalty(pa, s2, r2)
        assert (np.asarray(pen2) <= np.asarray(pen0)).all()
        assert np.asarray(pen2).mean() < np.asarray(pen0).mean()


def test_block_sweep_one_is_serial_sweep(small_problem):
    """block_events=1 must stay bit-identical to the serial sweep (the
    refactor shares one code path; existing exactness tests rely on it)."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    from timetabling_ga_tpu.ops.sweep import sweep_local_search

    pa = small_problem.device_arrays()
    slots = jax.random.randint(jax.random.key(2), (4, pa.n_events), 0,
                               pa.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    a = sweep_local_search(pa, jax.random.key(3), slots, rooms,
                           n_sweeps=2, swap_block=4, block_events=1)
    b = sweep_local_search(pa, jax.random.key(3), slots, rooms,
                           n_sweeps=2, swap_block=4)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


def test_sideways_never_increases_and_stays_deterministic(small_problem):
    """Sideways acceptance (plateau walk) may accept EQUAL-penalty moves
    but never worse ones, and the pass stays a pure function of its key."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    from timetabling_ga_tpu.ops.sweep import sweep_local_search

    pa = small_problem.device_arrays()
    slots = jax.random.randint(jax.random.key(5), (8, pa.n_events), 0,
                               pa.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms)
    a = sweep_local_search(pa, jax.random.key(6), slots, rooms,
                           n_sweeps=3, swap_block=4, sideways=0.5)
    b = sweep_local_search(pa, jax.random.key(6), slots, rooms,
                           n_sweeps=3, swap_block=4, sideways=0.5)
    pen_a, _, _ = fitness.batch_penalty(pa, *a)
    assert (np.asarray(pen_a) <= np.asarray(pen0)).all()
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))


@pytest.mark.slow
def test_sideways_escapes_plateau_that_strict_cannot():
    """A 3-event instance engineered so the strict sweep is stuck on an
    hcv plateau: correlated events in one slot whose every single-event
    relocation keeps global penalty equal — only an equal-penalty drift
    (or luck of ordering) untangles them. The sideways sweep must reach
    a strictly better state than the strict sweep from the same start at
    least for some individuals."""
    import jax
    import numpy as np
    from timetabling_ga_tpu.ops import fitness
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    from timetabling_ga_tpu.ops.sweep import sweep_local_search
    from timetabling_ga_tpu.problem import random_instance

    # dense-conflict instance: plenty of hcv plateaus
    p = random_instance(13, n_events=30, n_rooms=3, n_features=2,
                        n_students=25, attend_prob=0.3)
    pa = p.device_arrays()
    P = 32
    slots = jax.random.randint(jax.random.key(7), (P, pa.n_events), 0,
                               pa.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    strict, _ = (sweep_local_search(pa, jax.random.key(8), slots, rooms,
                                    n_sweeps=12, swap_block=4,
                                    converge=True),
                 None)
    side, _ = (sweep_local_search(pa, jax.random.key(8), slots, rooms,
                                  n_sweeps=12, swap_block=4,
                                  converge=True, sideways=0.3),
               None)
    pen_strict, _, _ = fitness.batch_penalty(pa, *strict)
    pen_side, _, _ = fitness.batch_penalty(pa, *side)
    assert float(np.asarray(pen_side).mean()) \
        < float(np.asarray(pen_strict).mean())


def test_event_heat_matches_skip_rule(inst):
    """Heat semantics (the reference's sweep skip rule in tensor form):
    hcv involvement must be positive for SOME event iff hcv > 0, zero
    everywhere iff hcv == 0 — and an event not implicated in any clash
    must score 0 while the individual is infeasible."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(11), 16)
    st = init_state(pa, slots, rooms)
    heat = jax.vmap(lambda s, r, a, o, h: sweep.event_heat(
        pa, s, r, a, o, h))(st.slots, st.rooms, st.att, st.occ, st.hcv)
    heat = np.asarray(heat)
    hcv = np.asarray(st.hcv)
    scv = np.asarray(st.scv)
    for i in range(16):
        if hcv[i] > 0:
            # every hcv violation implicates at least one event
            assert heat[i].max() > 0
            # heat must upper-bound involvement: every pairwise clash
            # touches exactly the events the reference's eventHcv sees;
            # summing involvement over events >= hcv (each clash counted
            # from both sides)
            assert heat[i].sum() >= hcv[i]
        elif scv[i] > 0:
            assert heat[i].max() > 0
        else:
            assert (heat[i] == 0).all()


def test_event_heat_zero_for_clean_events(inst):
    """Construct one individual with a known isolated clash: two
    UNCORRELATED events forced into the same (slot, room). Only those
    two events may carry pair-clash heat."""
    problem, pa = inst
    import itertools
    conflict = np.asarray(pa.conflict)
    # find an uncorrelated event pair
    pair = next((e1, e2) for e1, e2 in
                itertools.combinations(range(pa.n_events), 2)
                if conflict[e1, e2] == 0)
    e1, e2 = pair
    # spread all events over distinct slots (E=24 <= T=45), then collide
    # the chosen pair in slot 0, room 0
    slots = jnp.arange(pa.n_events, dtype=jnp.int32)[None, :] % pa.n_slots
    slots = slots.at[0, e1].set(0).at[0, e2].set(0)
    rooms = batch_assign_rooms(pa, slots)
    rooms = rooms.at[0, e1].set(0).at[0, e2].set(0)
    st = init_state(pa, slots, rooms)
    heat = sweep.event_heat(pa, st.slots[0], st.rooms[0], st.att[0],
                            st.occ[0], st.hcv[0])
    heat = np.asarray(heat)
    if int(st.hcv[0]) > 0:
        assert heat[e1] > 0 and heat[e2] > 0


def test_hot_sweep_state_consistent_and_monotone(inst):
    """The violation-guided sweep keeps exact maintained state and never
    worsens penalties (the selection changes WHICH events pivot, not the
    delta semantics)."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(12), 8)
    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms)
    st = init_state(pa, slots, rooms)
    st, improved = sweep.sweep_pass(pa, jax.random.key(13), st,
                                    swap_block=4, hot_k=6)
    assert bool(improved)
    pen, hcv, scv = fitness.batch_penalty(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.hcv), np.asarray(hcv))
    np.testing.assert_array_equal(np.asarray(st.scv), np.asarray(scv))
    np.testing.assert_array_equal(np.asarray(st.pen), np.asarray(pen))
    assert (np.asarray(pen) <= np.asarray(pen0)).all()
    st2 = init_state(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.att), np.asarray(st2.att))
    np.testing.assert_array_equal(np.asarray(st.occ), np.asarray(st2.occ))


def test_hot_sweep_reaches_feasibility(inst):
    """Converge-bounded hot-K sweeps must still repair a random
    population to feasibility on the easy module instance (the hot set
    re-scores every pass, so repairs chain across passes)."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(14), 8)
    s2, r2 = sweep.sweep_local_search(pa, jax.random.key(15), slots,
                                      rooms, n_sweeps=50, swap_block=4,
                                      converge=True, sideways=0.25,
                                      hot_k=6)
    _, hcv, _ = fitness.batch_penalty(pa, s2, r2)
    assert (np.asarray(hcv) == 0).any()


@pytest.mark.slow
def test_move3_sweep_state_consistent(inst):
    """p3 > 0 adds 3-cycle candidates; maintained state must stay exact
    after passes that can accept them (the _delta_one 3-relocation path
    with all three events active)."""
    problem, pa = inst
    slots, rooms = _rand_pop(pa, jax.random.key(16), 8)
    pen0, _, _ = fitness.batch_penalty(pa, slots, rooms)
    st = init_state(pa, slots, rooms)
    for i in range(3):
        st, _ = sweep.sweep_pass(pa, jax.random.key(17 + i), st,
                                 swap_block=4, p3=1.0)
    pen, hcv, scv = fitness.batch_penalty(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.hcv), np.asarray(hcv))
    np.testing.assert_array_equal(np.asarray(st.scv), np.asarray(scv))
    np.testing.assert_array_equal(np.asarray(st.pen), np.asarray(pen))
    assert (np.asarray(pen) <= np.asarray(pen0)).all()
    st2 = init_state(pa, st.slots, st.rooms)
    np.testing.assert_array_equal(np.asarray(st.att), np.asarray(st2.att))
    np.testing.assert_array_equal(np.asarray(st.occ), np.asarray(st2.occ))


@pytest.mark.slow
def test_move3_superset_neighborhood_property():
    """Property check on a dense instance: p3=1 adds 3-cycle candidates
    to every step (a strict superset of the p3=0 candidate set, same
    acceptance rule), so from the same start/key the p3 path's mean
    penalty must not be meaningfully worse. Exactness of the applied
    3-cycles (maintained state == recomputation) is what
    test_move3_sweep_state_consistent pins; this test only guards that
    the richer neighborhood participates without degrading search."""
    import jax
    from timetabling_ga_tpu.ops.rooms import batch_assign_rooms
    from timetabling_ga_tpu.problem import random_instance
    p = random_instance(23, n_events=20, n_rooms=3, n_features=2,
                        n_students=15, attend_prob=0.25)
    pa = p.device_arrays()
    slots = jax.random.randint(jax.random.key(20), (16, pa.n_events), 0,
                               pa.n_slots, dtype=jnp.int32)
    rooms = batch_assign_rooms(pa, slots)
    # identical keys, superset candidates: per step the p3 path picks
    # the lexicographic argmin over a superset, but trajectories diverge
    # after the first differing pick, so any single key is noise-bound
    # (final penalties are small integers; a one-key mean once flipped
    # from pass to fail on an unrelated tie-break change). Aggregate
    # over several keys and allow absolute slack of 1 scv point.
    means_a, means_b = [], []
    for k in (21, 22, 23):
        a = sweep.sweep_local_search(pa, jax.random.key(k), slots, rooms,
                                     n_sweeps=6, swap_block=4, p3=0.0)
        b = sweep.sweep_local_search(pa, jax.random.key(k), slots, rooms,
                                     n_sweeps=6, swap_block=4, p3=1.0)
        pen_a, _, _ = fitness.batch_penalty(pa, *a)
        pen_b, _, _ = fitness.batch_penalty(pa, *b)
        means_a.append(np.asarray(pen_a).mean())
        means_b.append(np.asarray(pen_b).mean())
    assert np.mean(means_b) <= np.mean(means_a) + 1.0, (means_a, means_b)


def test_sweep_hot_block_wider_than_hot_k(small_problem):
    """block_events > 2*hot_k: the pivot block is wider than two wraps
    of the hot-pivot list, so the wrap padding must tile (a single
    concat pad under-fills and the block slice fails at trace time).
    Both knobs are CLI-settable; this traced+ran fine with the old
    modular gather and must keep working with the sliced form."""
    from tests.conftest import random_assignment
    pa = small_problem.device_arrays()
    rng = np.random.default_rng(11)
    slots, rooms = random_assignment(rng, small_problem, 4)
    key = jax.random.key(0)
    s2, r2 = sweep.sweep_local_search(pa, key, jnp.asarray(slots),
                                      jnp.asarray(rooms), n_sweeps=1,
                                      block_events=8, hot_k=3)
    pen0 = fitness.batch_penalty(pa, slots, rooms)[0]
    pen1 = fitness.batch_penalty(pa, np.asarray(s2), np.asarray(r2))[0]
    assert (np.asarray(pen1) <= np.asarray(pen0)).all()
