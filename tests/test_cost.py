"""Cost observatory tests (timetabling_ga_tpu/obs/cost.py, tt-obs v3).

Five layers:

  unit        signature keying, CostProgram compile accounting +
              fallback, roofline helper, MemPoller gauges, the
              near-HBM /readyz reason, ProfileCapture lifecycle, the
              /profile endpoint + `tt profile` client, supervisor
              ladder step-back-UP
  engine A/B  warm second run pays ZERO compiles (the compile-hit
              contract), record stream identical with the observatory
              enabled vs disabled (TT_COST_OBS kill switch) and with
              costEntry emission on vs off — THE acceptance criterion
  serve A/B   bucket reuse => exactly one compile per lane program
              (compile.count.{lane_runner,lane_init} pin it), same
              stream-identity contract
  faults      `mem_poll` and `profile` hang/die never stall dispatch,
              serve, or writer drain
  CLI         costEntry records render in `tt trace` / `tt stats`
"""

import io
import json
import os
import threading
import time

import pytest

from timetabling_ga_tpu.obs import cost as obs_cost
from timetabling_ga_tpu.obs import http as obs_http
from timetabling_ga_tpu.obs import metrics as obs_metrics
from timetabling_ga_tpu.obs.metrics import MetricsRegistry
from timetabling_ga_tpu.runtime import faults, jsonl
from timetabling_ga_tpu.runtime.config import RunConfig, ServeConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM = os.path.join(REPO, "fixtures", "comp01s.tim")


def _wait(cond, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# ------------------------------------------------------------------ unit


def test_sig_distinguishes_shapes_dtypes_and_scalars():
    import numpy as np
    a = np.zeros((4, 3), np.int32)
    b = np.zeros((4, 4), np.int32)
    c = np.zeros((4, 3), np.float32)
    assert obs_cost._sig((a, 1)) == obs_cost._sig((a, 2))
    assert obs_cost._sig((a,)) != obs_cost._sig((b,))
    assert obs_cost._sig((a,)) != obs_cost._sig((c,))
    assert obs_cost._sig((a, 1)) != obs_cost._sig((a, 1.0))
    # nested pytrees (NamedTuple-ish) key by leaves
    assert obs_cost._sig(((a, a),)) != obs_cost._sig(((a, b),))
    # REGISTERED dataclass pytrees key by their leaves too: two serve
    # buckets' ProblemArrays must never collide onto one executable
    # (the soak leg caught exactly that before the tree_flatten path)
    from timetabling_ga_tpu.problem import random_instance
    pa1 = random_instance(1, n_events=40, n_rooms=4, n_features=4,
                          n_students=30).device_arrays()
    pa2 = random_instance(1, n_events=100, n_rooms=8, n_features=4,
                          n_students=60).device_arrays()
    assert obs_cost._sig((pa1,)) != obs_cost._sig((pa2,))
    tag = obs_cost.sig_tag(obs_cost._sig((a, 1)))
    assert tag == obs_cost.sig_tag(obs_cost._sig((a, 2)))
    assert len(tag) == 10


def test_cost_program_accounting_and_cost_entry_emission():
    import jax
    import numpy as np
    reg = MetricsRegistry()
    obs = obs_cost.Observatory(registry=reg)
    buf = io.StringIO()
    obs.bind(buf, now=lambda: 1.5)
    prog = obs_cost.CostProgram(jax.jit(lambda x: x * 2 + 1), "toy",
                                observatory=obs)
    x = np.arange(8, dtype=np.int32)
    y1 = prog(x)
    assert list(np.asarray(y1)[:3]) == [1, 3, 5]
    assert reg.counter("compile.count").value == 1
    assert reg.counter("compile.count.toy").value == 1
    assert reg.counter("compile.cache_hits").value == 0
    assert reg.histogram("compile.seconds").count == 1
    prog(x)                                   # warm: a cache hit
    assert reg.counter("compile.count").value == 1
    assert reg.counter("compile.cache_hits").value == 1
    prog(np.arange(16, dtype=np.int32))       # new shape: new compile
    assert reg.counter("compile.count").value == 2
    # the executable's cost analysis landed in last_cost + gauges
    assert prog.last_cost is None or "flops" not in prog.last_cost \
        or prog.last_cost["flops"] > 0
    # bound emitter: one costEntry per compile, stamped with now()
    recs = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert len(recs) == 2 and all("costEntry" in r for r in recs)
    ce = recs[0]["costEntry"]
    assert ce["program"] == "toy" and ce["ts"] == 1.5
    assert "compileSeconds" in ce and "sig" in ce
    # costEntry is a timing record: stream identity holds by strip
    assert jsonl.strip_timing(recs) == []
    # unbound: compiles keep counting, nothing more is emitted
    obs.unbind()
    prog(np.arange(32, dtype=np.int32))
    assert reg.counter("compile.count").value == 3
    assert len(buf.getvalue().splitlines()) == 2


def test_cost_program_fallback_on_unloweable_fn():
    reg = MetricsRegistry()
    obs = obs_cost.Observatory(registry=reg)
    prog = obs_cost.CostProgram(lambda x: x + 1, "plain",
                                observatory=obs)
    assert prog(41) == 42                 # no .lower: plain-call path
    assert prog(41) == 42
    assert reg.counter("compile.count").value == 1
    assert prog.last_cost is None


def test_roofline_and_hit_rate_helpers():
    out = obs_cost.roofline(27.6e6, 0.865e6, 400_000)
    assert out["arithmetic_intensity_flops_per_byte"] == pytest.approx(
        31.9, rel=0.01)
    assert out["bf16_peak_tflops"] == obs_cost.BF16_PEAK_TFLOPS
    assert out["hbm_peak_gbps"] == obs_cost.HBM_PEAK_GBPS
    assert out["achieved_tflops"] >= 0
    assert "min_fused_fraction_pct" in out
    reg = MetricsRegistry()
    assert obs_cost.compile_hit_rate(reg) == 0.0
    reg.counter("compile.count").inc(2)
    reg.counter("compile.cache_hits").inc(6)
    assert obs_cost.compile_hit_rate(reg) == pytest.approx(0.75)


def test_mem_poller_gauges_and_near_hbm_readiness():
    reg = MetricsRegistry()
    stats = {"bytes_in_use": 50, "bytes_limit": 100,
             "peak_bytes_in_use": 60}
    poller = obs_cost.MemPoller(lambda: stats, interval_s=60,
                                registry=reg)
    assert poller.poll_once()
    g = reg.snapshot()["gauges"]
    assert g["device.mem_bytes_in_use"] == 50
    assert g["device.mem_bytes_limit"] == 100
    assert g["device.mem_frac_used"] == 0.5
    assert g["device.mem_peak_bytes_in_use"] == 60
    ok, detail = obs_http.readiness(reg)
    assert ok and detail["mem_frac_used"] == 0.5
    # cross the near-HBM threshold: /readyz degrades with the reason
    stats["bytes_in_use"] = int(100 * obs_cost.NEAR_HBM_FRAC) + 1
    assert poller.poll_once()
    ok, detail = obs_http.readiness(reg)
    assert not ok and "near_hbm_limit" in detail["reasons"]
    # a None-stats backend (CPU) still counts polls, sets no gauges
    reg2 = MetricsRegistry()
    p2 = obs_cost.MemPoller(lambda: None, registry=reg2)
    assert p2.poll_once()
    assert reg2.counter("device.mem_polls").value == 1
    assert "device.mem_frac_used" not in reg2.snapshot().get(
        "gauges", {})


def test_mem_poller_die_and_hang_never_stall(monkeypatch):
    monkeypatch.setattr(faults, "HANG_S", 0.15)
    reg = MetricsRegistry()
    # die: the poller thread exits silently; close() returns at once
    faults.install("mem_poll:1:die")
    try:
        p = obs_cost.MemPoller(lambda: {"bytes_in_use": 1},
                               interval_s=0.01, registry=reg).start()
        assert _wait(lambda: not p.alive())
        t0 = time.monotonic()
        p.close()
        assert time.monotonic() - t0 < 1.0
        # hang: the poller parks inside its own thread; the caller's
        # close() is bounded and everything else runs on
        faults.install("mem_poll:1:hang")
        p2 = obs_cost.MemPoller(lambda: {"bytes_in_use": 1},
                                interval_s=0.01, registry=reg).start()
        time.sleep(0.05)            # poller is inside the hang now
        t0 = time.monotonic()
        p2.close()
        assert time.monotonic() - t0 < 3.0
    finally:
        faults.install(None)


def test_profile_capture_lifecycle():
    calls = []
    cap = obs_cost.ProfileCapture(lambda d: calls.append(("start", d)),
                                  lambda: calls.append(("stop",)),
                                  default_dir="outdir",
                                  registry=MetricsRegistry())
    try:
        ack = cap.trigger(2)
        assert ack == {"ok": True, "dispatches": 2, "dir": "outdir"}
        assert _wait(lambda: ("start", "outdir") in calls)
        busy = cap.trigger(1)
        assert not busy["ok"] and "active" in busy["reason"]
        cap.on_dispatch()
        assert ("stop",) not in calls
        cap.on_dispatch()
        assert _wait(lambda: ("stop",) in calls)
        assert _wait(lambda: not cap.active())
        # a finished capture frees the slot for the next trigger
        assert cap.trigger(1)["ok"]
        assert _wait(lambda: calls.count(("start", "outdir")) == 2)
        cap.on_dispatch()
        assert _wait(lambda: calls.count(("stop",)) == 2)
    finally:
        cap.close()


def test_profile_capture_hang_and_die_never_stall(monkeypatch):
    monkeypatch.setattr(faults, "HANG_S", 30.0)
    for action in ("hang", "die"):
        calls = []
        faults.install(f"profile:1:{action}")
        try:
            cap = obs_cost.ProfileCapture(
                lambda d: calls.append("start"),
                lambda: calls.append("stop"),
                registry=MetricsRegistry())
            assert cap.trigger(1)["ok"]
            time.sleep(0.05)
            # the capture worker is hung/dead; dispatch ticks must
            # return instantly
            t0 = time.monotonic()
            for _ in range(100):
                cap.on_dispatch()
            assert time.monotonic() - t0 < 0.5
            assert "start" not in calls
            t0 = time.monotonic()
            cap.close()
            assert time.monotonic() - t0 < 3.0
        finally:
            faults.install(None)


def test_profile_endpoint_and_cli_client(capsys):
    calls = []
    cap = obs_cost.ProfileCapture(lambda d: calls.append(d),
                                  lambda: None,
                                  registry=MetricsRegistry())
    srv = obs_http.ObsServer("127.0.0.1:0", registry=MetricsRegistry(),
                             profile=cap).start()
    try:
        assert obs_cost.main_profile([srv.url, "--for", "3"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out == {"ok": True, "dispatches": 3,
                       "dir": cap.default_dir}
        assert _wait(lambda: calls == [cap.default_dir])
        # busy: 409 surfaces as exit 1 with the reason
        assert obs_cost.main_profile([srv.url]) == 1
        assert "active" in json.loads(capsys.readouterr().out)["reason"]
    finally:
        srv.close()
        cap.close()
    # no capture wired: 404
    srv2 = obs_http.ObsServer("127.0.0.1:0",
                              registry=MetricsRegistry()).start()
    try:
        assert obs_cost.main_profile([srv2.url]) == 1
        assert "no profile capture" in json.loads(
            capsys.readouterr().out)["reason"]
    finally:
        srv2.close()


def test_supervisor_ladder_steps_back_up(monkeypatch):
    from timetabling_ga_tpu.runtime import engine as eng
    sup = eng._Supervisor.__new__(eng._Supervisor)
    sup.level = 3
    sup.failures = [100.0]
    sup._relaxed_at = None
    monkeypatch.setattr(eng._Supervisor, "WINDOW_S", 10.0)
    assert not sup.maybe_relax(105.0)      # clean stretch too short
    assert sup.maybe_relax(110.0) and sup.level == 2
    assert not sup.maybe_relax(115.0)      # one level per clean window
    assert sup.maybe_relax(120.0) and sup.level == 1
    assert sup.maybe_relax(130.0) and sup.level == 0
    assert not sup.maybe_relax(999.0)      # floor at 0


# ----------------------------------------------------------- engine A/Bs


def _engine_run(obs=False, faults_spec=None, **kw):
    from timetabling_ga_tpu.runtime import engine as eng
    buf = io.StringIO()
    base = dict(input=TIM, seed=3, pop_size=8, islands=2,
                generations=30, migration_period=10, max_steps=8,
                time_limit=300, backend="cpu", auto_tune=False,
                trace=True, obs=obs, metrics_every=1,
                faults=faults_spec)
    base.update(kw)
    best = eng.run(RunConfig(**base), out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


def _compile_counters():
    c = obs_metrics.REGISTRY.snapshot().get("counters", {})
    return {k: v for k, v in c.items() if k.startswith("compile.")}


def _clear_program_caches():
    from timetabling_ga_tpu.runtime import engine as eng
    eng._RUNNER_CACHE.clear()
    eng._INIT_CACHE.clear()


@pytest.mark.skipif(not obs_cost.ENABLED, reason="TT_COST_OBS=0")
def test_engine_stream_identity_and_compile_accounting(monkeypatch):
    """THE acceptance criterion, engine half, plus the compile-hit
    contract — structured to pay each XLA compile exactly once:

      leg OFF   observatory disabled (TT_COST_OBS kill switch => plain
                jit dispatch), cold caches
      leg ON    observatory enabled + emitting (--obs), cold caches —
                its costEntry records and cold compile.* deltas are
                the accounting assertions, and its warm programs are
                left in the caches for every later engine test
      leg WARM  a second enabled run: ZERO new compiles, cache_hits
                grow, roofline gauges move

    All three emit identical protocol records modulo timing
    (costEntry is a timing record). DISPATCH_CAP_S is pinned out of
    range so timing noise cannot re-size dispatches between legs (the
    test_obs A/B discipline)."""
    from timetabling_ga_tpu.runtime import engine as eng
    monkeypatch.setattr(eng, "DISPATCH_CAP_S", 1e9)
    monkeypatch.setattr(obs_cost, "ENABLED", False)
    _clear_program_caches()
    b_off, l_off = _engine_run(obs=False)
    assert not any("costEntry" in r for r in l_off)
    monkeypatch.setattr(obs_cost, "ENABLED", True)
    _clear_program_caches()               # leg ON compiles THROUGH the
    #                                       observatory
    before = _compile_counters()
    b_on, l_on = _engine_run(obs=True)
    after = _compile_counters()
    assert b_on == b_off
    assert jsonl.strip_timing(l_on) == jsonl.strip_timing(l_off)
    assert any("costEntry" in r for r in l_on)
    assert after.get("compile.count", 0) > before.get(
        "compile.count", 0)
    assert after.get("compile.count.runner", 0) - before.get(
        "compile.count.runner", 0) == 1
    # leg WARM: same records, zero compiles, hits + roofline move
    b2, l2 = _engine_run()
    final = _compile_counters()
    assert b2 == b_on
    assert jsonl.strip_timing(l2) == jsonl.strip_timing(l_off)
    assert final.get("compile.count", 0) == after.get(
        "compile.count", 0), (after, final)
    assert final.get("compile.cache_hits", 0) > after.get(
        "compile.cache_hits", 0)
    g = obs_metrics.REGISTRY.snapshot()["gauges"]
    assert g.get("cost.flops.runner", 0) > 0
    assert g.get("cost.achieved_tflops", 0) > 0
    assert g.get("cost.flop_utilization_pct", 0) > 0


@pytest.mark.skipif(not obs_cost.ENABLED, reason="TT_COST_OBS=0")
def test_engine_ladder_restore_path(monkeypatch,
                                    engine_stream_baseline):
    """The recovery ladder's step-back-UP surfaces live: with a
    deterministic one-failure escalate/relax policy (the real timing
    logic is unit-tested above), a degraded run emits the faultEntry
    `restore` record, clears the engine.degrade_level gauge, and still
    matches the uninjected stream modulo timing+fault records."""
    from timetabling_ga_tpu.runtime import engine as eng

    class FastRelax(eng._Supervisor):
        def escalate(self, now):
            self.failures.append(now)
            if self.level < 1:
                self.level = 1          # serial on the first failure
                return True
            return False

        def maybe_relax(self, now):
            if self.level > 0 and self.recoveries >= 1:
                self.level -= 1
                self._relaxed_at = now
                return True
            return False

    b0, l0 = engine_stream_baseline    # session-shared baseline run
    monkeypatch.setattr(eng, "_Supervisor", FastRelax)
    b, l = _engine_run(faults_spec="dispatch:2:unavailable")
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    fe = [r["faultEntry"] for r in l if "faultEntry" in r]
    actions = [f["action"] for f in fe]
    assert "degrade" in actions            # the ladder stepped down...
    restores = [f for f in fe if f["action"] == "restore"]
    assert restores                        # ...and back up, audited
    assert restores[-1]["site"] == "run"
    assert int(obs_metrics.REGISTRY.gauge(
        "engine.degrade_level").value) == 0


@pytest.mark.skipif(not obs_cost.ENABLED, reason="TT_COST_OBS=0")
def test_engine_profile_for_wiring(tmp_path, monkeypatch,
                                   engine_stream_baseline):
    """--profile-for N: the engine builds the capture, triggers it at
    launch, ticks it per retired dispatch, and the capture brackets
    exactly N dispatches — with the profiler entry points stubbed (the
    REAL jax.profiler.start_trace lazily imports tensorflow, ~a
    minute of import on the capture worker; the engine's lambdas look
    the attribute up at call time, so the stub is what runs). The
    record stream is identical with the capture on or off."""
    import jax
    prof_dir = str(tmp_path / "prof")
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    before = obs_metrics.REGISTRY.counter("profile.captures").value
    b0, l0 = engine_stream_baseline    # session-shared baseline run
    b, l = _engine_run(profile_for=2, profile_dir=prof_dir)
    assert b == b0
    assert jsonl.strip_timing(l) == jsonl.strip_timing(l0)
    assert _wait(lambda: ("stop",) in calls)
    assert calls == [("start", prof_dir), ("stop",)]
    assert obs_metrics.REGISTRY.counter(
        "profile.captures").value == before + 1


# ------------------------------------------------------------ serve A/Bs


def _serve_problems():
    from timetabling_ga_tpu.problem import random_instance
    # two DIFFERENT raw shapes landing in ONE bucket with the default
    # floors/ratio — the bucket-reuse compile contract's minimal case
    return [random_instance(4001, n_events=40, n_rooms=4,
                            n_features=4, n_students=30,
                            attend_prob=0.05),
            random_instance(4002, n_events=50, n_rooms=4,
                            n_features=4, n_students=25,
                            attend_prob=0.05)]


def _serve_run(problems, obs=False, **cfg_kw):
    from timetabling_ga_tpu.serve.service import SolveService
    buf = io.StringIO()
    cfg = ServeConfig(backend="cpu", lanes=2, quantum=10, pop_size=8,
                      generations=20, obs=obs, metrics_every=1,
                      **cfg_kw)
    svc = SolveService(cfg, out=buf)
    for i, p in enumerate(problems):
        svc.submit(p, job_id=f"j{i}", seed=i)
    svc.drive()
    svc.close()
    return [json.loads(x) for x in buf.getvalue().splitlines()]


@pytest.mark.skipif(not obs_cost.ENABLED, reason="TT_COST_OBS=0")
def test_serve_bucket_compiles_and_stream_identity(monkeypatch):
    """Serve half of the acceptance criterion. Compile accounting:
    from cold caches, a 2-job different-raw-shape one-bucket stream
    compiles each lane program EXACTLY once (bucket reuse =>
    per-signature cache hit), making the compile-hit rate a real
    number. Stream identity: the same stream with the observatory
    disabled is identical modulo timing records. Leg order pays each
    compile once and leaves WARM wrapped programs for the fault-
    isolation test below."""
    problems = _serve_problems()
    monkeypatch.setattr(obs_cost, "ENABLED", False)
    _clear_program_caches()
    l_off = _serve_run(problems, obs=False)
    assert not any("costEntry" in r for r in l_off)
    monkeypatch.setattr(obs_cost, "ENABLED", True)
    _clear_program_caches()
    before = _compile_counters()
    l_on = _serve_run(problems, obs=True)
    after = _compile_counters()
    assert jsonl.strip_timing(l_on) == jsonl.strip_timing(l_off)
    assert any("costEntry" in r for r in l_on)
    assert after.get("compile.count.lane_runner", 0) - before.get(
        "compile.count.lane_runner", 0) == 1     # one per bucket
    assert after.get("compile.count.lane_init", 0) - before.get(
        "compile.count.lane_init", 0) == 1
    # the co-tenant job's dispatches rode the same executables warm
    assert after.get("compile.cache_hits", 0) > before.get(
        "compile.cache_hits", 0)


@pytest.mark.skipif(not obs_cost.ENABLED, reason="TT_COST_OBS=0")
def test_serve_mem_poll_and_profile_faults_never_stall(monkeypatch):
    """A hung or dying poller/capture never stalls dispatch, serve, or
    writer drain: the stream completes, close() returns, and the
    records match a fault-free run modulo timing+fault records."""
    monkeypatch.setattr(faults, "HANG_S", 30.0)
    problems = _serve_problems()[:1]
    l0 = _serve_run(problems)
    for spec in ("mem_poll:1:hang,profile:1:hang",
                 "mem_poll:1:die,profile:1:die"):
        t0 = time.monotonic()
        l = _serve_run(problems, obs=True, mem_poll_every=0.01,
                       profile_for=1, faults=spec)
        # bounded: the hang variants park their own threads only (the
        # two close() joins are bounded at 2 s each)
        assert time.monotonic() - t0 < 25.0, spec
        assert jsonl.strip_timing(l) == jsonl.strip_timing(l0), spec
    assert faults.injected_total() >= 2


# ---------------------------------------------------------------- CLI


def test_cost_entry_renders_in_trace_and_stats():
    from timetabling_ga_tpu.obs.logstats import summarize
    from timetabling_ga_tpu.obs.trace_export import export_chrome_trace
    buf = io.StringIO()
    jsonl.cost_entry(buf, "lane_runner", sig="abc123", ts=2.0,
                     lowerSeconds=0.25, compileSeconds=0.75,
                     flops=1e9, intensity=30.0)
    jsonl.cost_entry(buf, "lane_runner", sig="def456", ts=5.0,
                     lowerSeconds=0.1, compileSeconds=0.4)
    recs = [json.loads(x) for x in buf.getvalue().splitlines()]
    doc = export_chrome_trace(recs)
    ev = [e for e in doc["traceEvents"] if e["cat"] == "compile"]
    assert len(ev) == 2
    assert ev[0]["name"] == "compile:lane_runner"
    assert ev[0]["ph"] == "X" and ev[0]["tid"] == 998
    # the slab ENDS at ts: start = (2.0 - 1.0) s in microseconds
    assert ev[0]["ts"] == pytest.approx(1.0e6)
    assert ev[0]["dur"] == pytest.approx(1.0e6)
    text = summarize(recs)
    assert "== compiles (2 costEntry records)" in text
    assert "lane_runner: 2x, 1.50s lower+compile" in text
    assert "AI 30.0" in text
