"""Test configuration: run everything on a virtual 8-device CPU mesh.

The env vars must be set before jax is first imported anywhere, which is
why they live at module top here (pytest imports conftest first). This is
the portable substitute for a real TPU pod slice (SURVEY.md section 4.4):
island/migration tests assert topology on the fake devices, and kernels are
dtype/shape-identical to the TPU path.
"""

import os

# Force CPU even if the environment pre-sets JAX_PLATFORMS (e.g. to the
# real TPU via axon) — the suite must run on the virtual 8-device mesh.
# The axon plugin overrides the env var, so the config.update below (after
# import, before first backend use) is the authoritative switch.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from timetabling_ga_tpu.problem import random_instance  # noqa: E402


@pytest.fixture(scope="session")
def tiny_problem():
    """4 events, 2 rooms — hand-checkable."""
    return random_instance(0, n_events=4, n_rooms=2, n_features=2,
                           n_students=5, attend_prob=0.5)


@pytest.fixture(scope="session")
def small_problem():
    """A small but non-trivial instance."""
    return random_instance(1, n_events=30, n_rooms=4, n_features=3,
                           n_students=20, attend_prob=0.15)


@pytest.fixture(scope="session")
def medium_problem():
    return random_instance(2, n_events=80, n_rooms=8, n_features=5,
                           n_students=60, attend_prob=0.08)


def random_assignment(rng, problem, n):
    """Uniformly random (slots, rooms) population, like
    RandomInitialSolution before room matching (Solution.cpp:48-55)."""
    slots = rng.integers(0, problem.n_slots,
                         size=(n, problem.n_events)).astype(np.int32)
    rooms = rng.integers(0, problem.n_rooms,
                         size=(n, problem.n_events)).astype(np.int32)
    return slots, rooms


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Free compiled XLA executables after each test module.

    A full-suite run accumulates every module's jitted programs in one
    process; at round-5 program counts the CPU client segfaulted inside
    a late scan dispatch (test_sweep, reproducibly at ~the same point,
    while the same test passes solo). Dropping the caches between
    modules bounds the live-executable population; cross-module cache
    reuse was nil anyway (different shapes/configs per module)."""
    yield
    jax.clear_caches()
