"""Test configuration: run everything on a virtual 8-device CPU mesh.

The env vars must be set before jax is first imported anywhere, which is
why they live at module top here (pytest imports conftest first). This is
the portable substitute for a real TPU pod slice (SURVEY.md section 4.4):
island/migration tests assert topology on the fake devices, and kernels are
dtype/shape-identical to the TPU path.
"""

import os

# Force CPU even if the environment pre-sets JAX_PLATFORMS (e.g. to the
# real TPU via axon) — the suite must run on the virtual 8-device mesh.
# The axon plugin overrides the env var, so the config.update below (after
# import, before first backend use) is the authoritative switch.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from timetabling_ga_tpu.problem import random_instance  # noqa: E402


@pytest.fixture(scope="session")
def tiny_problem():
    """4 events, 2 rooms — hand-checkable."""
    return random_instance(0, n_events=4, n_rooms=2, n_features=2,
                           n_students=5, attend_prob=0.5)


@pytest.fixture(scope="session")
def small_problem():
    """A small but non-trivial instance."""
    return random_instance(1, n_events=30, n_rooms=4, n_features=3,
                           n_students=20, attend_prob=0.15)


@pytest.fixture(scope="session")
def medium_problem():
    return random_instance(2, n_events=80, n_rooms=8, n_features=5,
                           n_students=60, attend_prob=0.08)


def random_assignment(rng, problem, n):
    """Uniformly random (slots, rooms) population, like
    RandomInitialSolution before room matching (Solution.cpp:48-55)."""
    slots = rng.integers(0, problem.n_slots,
                         size=(n, problem.n_events)).astype(np.int32)
    rooms = rng.integers(0, problem.n_rooms,
                         size=(n, problem.n_events)).astype(np.int32)
    return slots, rooms


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TIM_FIXTURE = os.path.join(_REPO, "fixtures", "comp01s.tim")


@pytest.fixture(scope="session")
def engine_stream_baseline():
    """SESSION-shared reference engine stream: comp01s, seed 3, pop 8,
    islands 2, 30 generations at migration period 10, full trace, obs
    off, pipelined — the exact baseline the obs/cost/quality stream-
    identity A/Bs diff against. Before this fixture each module (and
    several individual tests) re-ran the identical deterministic
    baseline, recompiling its programs from scratch every time
    (the between-module jax.clear_caches wipes executables); at 2-core-
    box speeds those duplicate runs were a measurable slice of the
    tier-1 budget overrun (ISSUE 9 satellite). The run is a pure
    function of (fixture, seed, config), so sharing the recorded
    stream across modules changes no assertion."""
    import io
    import json
    from timetabling_ga_tpu.runtime import engine as eng
    from timetabling_ga_tpu.runtime.config import RunConfig
    buf = io.StringIO()
    cfg = RunConfig(input=TIM_FIXTURE, seed=3, pop_size=8, islands=2,
                    generations=30, migration_period=10, max_steps=8,
                    time_limit=300, backend="cpu", auto_tune=False,
                    trace=True)
    best = eng.run(cfg, out=buf)
    return best, [json.loads(x) for x in buf.getvalue().splitlines()]


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Free compiled XLA executables after each test module.

    A full-suite run accumulates every module's jitted programs in one
    process; at round-5 program counts the CPU client segfaulted inside
    a late scan dispatch (test_sweep, reproducibly at ~the same point,
    while the same test passes solo). Dropping the caches between
    modules bounds the live-executable population; cross-module cache
    reuse was nil anyway (different shapes/configs per module).

    Best-effort: a replica deliberately `kill()`ed mid-quantum (the
    crashed-process simulation — its drive thread is NOT joined) can
    still be inside a compile when the module ends, and a thread
    registering jit caches while clear_caches() iterates the weakref
    registry raises "Set changed size during iteration". Retry briefly,
    then skip — clearing is a memory bound, not a correctness fence."""
    yield
    import time as _time
    for _ in range(5):
        try:
            jax.clear_caches()
            break
        except RuntimeError:
            _time.sleep(0.5)
