"""tt-accord — the out-of-band multi-host control side channel.

Why it exists: every cross-process decision the run loop makes today
(chunk sizes, stop/continue, resume yes/no) rode
`multihost_utils.broadcast_one_to_all` — a DEVICE collective, i.e. part
of the very program whose death the supervisor is trying to recover
from. After a fault the collective runtime is poisoned on at least one
process, so "agree on what to do about the fault" could not use the
program to agree — a multi-host fault was an unrecoverable hang at the
next collective rendezvous (ROADMAP item 2). This module is the
host-side channel that never touches the device path: schedule
agreement (`agree`), pre-collective rendezvous (`guard_collective`),
fault-recovery consensus (`agree_on_fault`) and a liveness heartbeat
that converts a dead peer's infinite collective hang into a classified
`PeerLost` within `--peer-timeout`.

Two backends, one protocol:

* `DistributedChannel` — the `jax.distributed` coordination-service
  key-value store (`key_value_set` / `blocking_key_value_get`), live
  whenever a coordinator is (`--coordinator` / `--distributed`).
  No coordination-service *barriers*: a timed-out barrier id is
  poisoned for every later arrival, so every wait here is a
  first-write-wins KV rendezvous that stays re-enterable.
* `LoopbackChannel` — an in-process dict + condition variable sharing
  the exact protocol code (everything above the `_set`/`_get`/beat
  primitives), so every agreement path — including heartbeat expiry
  and disagreeing-verdict merges — unit-tests on single-process CPU
  in tier-1 (`tests/test_accord.py`). `kill()` simulates a peer's
  process death by silencing its heartbeat.

Key discipline (what makes replay safe):

* every key is namespaced `e{epoch}/...`; `agree_on_fault` bumps the
  epoch on ALL processes at the same agreement and resets the fence
  counters, so control fences replayed after a recovery write FRESH
  keys instead of colliding with their first-attempt values;
* per-run namespaces (`DistributedChannel` is opened once per
  engine.run on every process, in lockstep) keep repeated runs against
  one long-lived coordinator from reusing keys;
* the fault flag (`e{epoch}/fault`) is the only multi-writer key and
  is written first-write-wins — both-see-fault races are benign.

This module is the accord-modules surface tt-analyze TT307 audits:
nothing here may launch a device collective or touch
`multihost_utils.*` — recovery must ride this channel precisely
because the collective program cannot be trusted after a fault.
Import-time stdlib-only; jax is reached lazily inside `open_channel`.
"""

from __future__ import annotations

import json
import threading
import time

# how often a waiting process re-checks the fault flag and peer
# liveness between KV polls. Short enough that fault hand-off latency
# is negligible next to a dispatch chunk; long enough that a waiting
# peer costs ~5 coordination-service RPCs a second.
POLL_S = 0.2


class PeerLost(RuntimeError):
    """A peer's heartbeat went silent past --peer-timeout while we
    waited for it at a control fence. NOT transient (the message
    carries no retry.TRANSIENT_MARKERS string): the peer's process is
    gone, no rehydrate brings it back, and the only correct move is
    the agreed clean abort with a final durable checkpoint — never a
    hang at the collective the peer will not join."""

    def __init__(self, proc: int, silence_s: float):
        super().__init__(
            f"lost contact with process {proc}: no heartbeat for "
            f"{silence_s:.1f}s (over --peer-timeout)")
        self.proc = proc
        self.silence_s = silence_s


class AccordPeerFault(RuntimeError):
    """Another process declared a fault on the side channel while this
    one waited at a control fence. The LOCAL program is healthy — the
    message carries the 'peer declared a fault' marker
    retry.TRANSIENT_MARKERS matches, so the supervisor classifies it
    transient and this process joins the recovery agreement instead of
    entering the collective its faulted peer will never reach."""

    tt_site = "accord"

    def __init__(self):
        super().__init__(
            "accord: a peer declared a fault on the control channel; "
            "joining the recovery agreement")


def merge_verdicts(verdicts: list) -> dict:
    """Deterministically merge per-process fault verdicts into THE
    agreed one — pure function of the verdict list, so every process
    computes the identical decision from the identical inputs with no
    second round trip. Rules: any `abort` wins (lowest-pid abort is
    the decider — a process out of recovery budget, or a lost peer's
    synthesized verdict, must never be outvoted into a retry its
    state cannot survive); otherwise the lowest-pid verdict naming a
    REAL fault site wins (a process that merely observed the fault
    flag carries site 'accord' and defers to the process that saw the
    actual error). The result gains `agreed`/`decider`/`procs`."""
    vs = sorted(verdicts, key=lambda v: int(v.get("proc", 0)))
    if not vs:
        raise ValueError("merge_verdicts: empty verdict list")
    aborts = [v for v in vs if v.get("action") == "abort"]
    if aborts:
        agreed = dict(aborts[0])
    else:
        real = [v for v in vs if v.get("site") not in (None, "accord")]
        agreed = dict(real[0] if real else vs[0])
    agreed["agreed"] = True
    agreed["decider"] = int(agreed.get("proc", 0))
    agreed["procs"] = [int(v.get("proc", 0)) for v in vs]
    return agreed


class ControlChannel:
    """Protocol base: agreement fences, collective guards, fault
    consensus and heartbeats over three backend primitives —
    `_set(key, value)` (first-write-wins), `_get(key, timeout_s)`
    (value or None) and `_beat_ages()` (seconds since each peer's last
    observed heartbeat). Single-process channels (`nproc == 1`) are
    complete no-ops on every path — the engine keeps one code path
    and the record stream stays bit-identical channel on or off."""

    def __init__(self, pid: int, nproc: int, peer_timeout: float = 60.0,
                 hb_interval: float | None = None):
        self.pid = int(pid)
        self.nproc = int(nproc)
        # 0 = wait forever (never classify a peer dead)
        self.peer_timeout = float(peer_timeout)
        if hb_interval is None:
            hb_interval = min(1.0, self.peer_timeout / 4) \
                if self.peer_timeout > 0 else 1.0
        self.hb_interval = max(0.02, float(hb_interval))
        self.epoch = 0
        self._fences: dict = {}        # tag -> fence count within epoch
        self._hb_stop = threading.Event()
        self._hb_thread = None
        if self.nproc > 1:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="tt-accord-hb", daemon=True)
            self._hb_thread.start()

    # ---- backend primitives ----------------------------------------
    def _set(self, key: str, value: str) -> None:
        raise NotImplementedError

    def _get(self, key: str, timeout_s: float):
        raise NotImplementedError

    def _post_beat(self, seq: int) -> None:
        raise NotImplementedError

    def _silence_s(self, proc: int) -> float:
        """Seconds since `proc`'s last observed heartbeat."""
        raise NotImplementedError

    # ---- heartbeat --------------------------------------------------
    def _hb_loop(self):
        seq = 0
        while not self._hb_stop.wait(self.hb_interval):
            seq += 1
            try:
                self._post_beat(seq)
            except Exception:
                return     # a dead backend ends the beat, silently:
                #            exactly what peers' liveness checks detect

    def close(self) -> None:
        """Stop the heartbeat. Idempotent; the channel must not be
        used afterwards (peers will classify this process lost)."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2 * self.hb_interval + 1.0)

    # ---- protocol helpers -------------------------------------------
    def _next(self, tag) -> int:
        n = self._fences.get(tag, 0) + 1
        self._fences[tag] = n
        return n

    def _fault_key(self) -> str:
        return f"e{self.epoch}/fault"

    def fault_flagged(self) -> bool:
        return self._get(self._fault_key(), 0.0) is not None

    def _await(self, key: str, peer: int, check_flag: bool = True) -> str:
        """Wait for `key` tolerant of everything but silence: returns
        its value; raises AccordPeerFault the moment any process posts
        the epoch's fault flag (unless already inside fault agreement),
        PeerLost when `peer`'s heartbeat has been quiet past
        --peer-timeout. Never waits on a barrier — re-enterable."""
        while True:
            v = self._get(key, POLL_S)
            if v is not None:
                return v
            if check_flag and self.fault_flagged():
                raise AccordPeerFault()
            if self.peer_timeout > 0:
                silence = self._silence_s(peer)
                if silence > self.peer_timeout:
                    raise PeerLost(peer, silence)

    # ---- the three agreement surfaces -------------------------------
    def agree(self, tag: str, payload):
        """Process-0-wins agreement at a named control fence: process 0
        posts its JSON-serializable `payload` and proceeds; every other
        process adopts it. The fence index is the per-tag call count
        within the epoch, so lockstep callers need no explicit ids.
        Single-process: identity."""
        if self.nproc == 1:
            return payload
        key = f"e{self.epoch}/a/{tag}/{self._next(('a', tag))}"
        if self.pid == 0:
            self._set(key, json.dumps(payload))
            return payload
        return json.loads(self._await(key, peer=0))

    def guard_collective(self) -> None:
        """Host-side rendezvous BEFORE entering a device collective:
        every process posts arrival and waits for all peers. A peer
        that faulted raises AccordPeerFault here (join its recovery
        agreement instead of hanging at its missing collective); a
        peer whose heartbeat died raises PeerLost within
        --peer-timeout. This is what converts 'infinite hang inside
        the collective' into a classified host-side fault.

        Instrumented (tt-prof satellite): the whole-rendezvous wait
        lands in the `accord.fence_wait_s` histogram and each peer's
        individual wait in `accord.peer_wait_s.<p>` gauges — fence
        waits ARE the straggler diagnostic (a persistently-slow peer
        shows up as a skewed gauge long before it misses a timeout).
        Host-side and registry-only: the record stream is untouched."""
        if self.nproc == 1:
            return
        base = f"e{self.epoch}/g/{self._next('g')}"
        t0 = time.monotonic()
        self._set(f"{base}/{self.pid}", "1")
        for p in range(self.nproc):
            if p != self.pid:
                self._await(f"{base}/{p}", peer=p)
                self._observe_peer_wait(p, time.monotonic() - t0)
        self._observe_fence_wait(time.monotonic() - t0)

    # fence-wait instrumentation: the process-global registry unless
    # the channel was handed a private one (serve embeds its own).
    # Failure-swallowing — the channel must keep agreeing even when
    # the registry is frozen mid-snapshot or the obs package is
    # stripped from a deployment.
    _registry = None

    def _observe_fence_wait(self, wait_s: float) -> None:
        try:
            reg = self._registry
            if reg is None:
                from timetabling_ga_tpu.obs import metrics as obs_metrics
                reg = obs_metrics.REGISTRY
            reg.histogram("accord.fence_wait_s").observe(wait_s)
        except Exception:
            pass

    def _observe_peer_wait(self, peer: int, wait_s: float) -> None:
        """Per-peer arrival gauge: wait from THIS process's fence entry
        until `peer`'s arrival was observed — the cross-peer spread of
        these gauges is the fence's straggler skew."""
        try:
            reg = self._registry
            if reg is None:
                from timetabling_ga_tpu.obs import metrics as obs_metrics
                reg = obs_metrics.REGISTRY
            reg.gauge(f"accord.peer_wait_s.{peer}").set(wait_s)
        except Exception:
            pass

    def agree_on_fault(self, local_verdict: dict) -> dict:
        """Fault-recovery consensus: post this process's verdict
        ({'site', 'action': 'recover'|'abort', 'gens', ...}), collect
        every peer's (a peer lost mid-agreement contributes a
        synthesized abort verdict instead of raising — its death IS a
        vote), and return `merge_verdicts` of the full set — identical
        on every process. Bumps the epoch and resets the fence
        counters: all processes resume (or abort) in a fresh key
        namespace, so replayed fences cannot collide with their
        pre-fault writes. Single-process: the local verdict, agreed."""
        verdict = dict(local_verdict)
        verdict["proc"] = self.pid
        if self.nproc == 1:
            return merge_verdicts([verdict])
        try:
            self._set(self._fault_key(), "1")
        except Exception:
            pass       # both-see-fault: a peer flagged first — fine
        self._set(f"e{self.epoch}/v/{self.pid}", json.dumps(verdict))
        verdicts = [verdict]
        for p in range(self.nproc):
            if p == self.pid:
                continue
            try:
                verdicts.append(json.loads(
                    self._await(f"e{self.epoch}/v/{p}", peer=p,
                                check_flag=False)))
            except PeerLost as e:
                verdicts.append({"proc": p, "site": "accord",
                                 "action": "abort", "gens": -1,
                                 "lost": True,
                                 "silence_s": round(e.silence_s, 3)})
        agreed = merge_verdicts(verdicts)
        self.epoch += 1
        self._fences.clear()
        return agreed


class _LoopbackStore:
    """The shared in-process backend: one dict + condition variable and
    per-process heartbeat timestamps."""

    def __init__(self):
        self.cond = threading.Condition()
        self.data: dict = {}
        self.beats: dict = {}


class LoopbackChannel(ControlChannel):
    """In-process backend: N channel views over one `_LoopbackStore`
    run the full protocol (including real heartbeat threads) on one
    CPU process — the tier-1 test double for the distributed backend,
    and the single-process fast path (`solo()`)."""

    def __init__(self, pid: int, nproc: int,
                 store: _LoopbackStore | None = None,
                 peer_timeout: float = 60.0,
                 hb_interval: float | None = None):
        self._store = store if store is not None else _LoopbackStore()
        with self._store.cond:
            self._store.beats[pid] = time.monotonic()
        super().__init__(pid, nproc, peer_timeout, hb_interval)

    @classmethod
    def group(cls, n: int, peer_timeout: float = 60.0,
              hb_interval: float | None = None) -> list:
        """N views over one shared store — 'n processes' in one."""
        store = _LoopbackStore()
        return [cls(p, n, store, peer_timeout, hb_interval)
                for p in range(n)]

    @classmethod
    def solo(cls) -> "LoopbackChannel":
        """The single-process channel: every protocol surface is a
        no-op/identity and no heartbeat thread runs."""
        return cls(0, 1)

    def kill(self) -> None:
        """Simulate this view's process dying: its heartbeat stops,
        so peers' liveness checks see growing silence. (A dead process
        also stops writing keys — tests simply stop calling.)"""
        self._hb_stop.set()

    def _set(self, key, value):
        with self._store.cond:
            self._store.data.setdefault(key, value)
            self._store.cond.notify_all()

    def _get(self, key, timeout_s):
        with self._store.cond:
            if timeout_s > 0:
                self._store.cond.wait_for(
                    lambda: key in self._store.data, timeout_s)
            return self._store.data.get(key)

    def _post_beat(self, seq):
        with self._store.cond:
            self._store.beats[self.pid] = time.monotonic()

    def _silence_s(self, proc):
        with self._store.cond:
            t = self._store.beats.get(proc)
        return 0.0 if t is None else max(0.0, time.monotonic() - t)


class DistributedChannel(ControlChannel):
    """The real multi-host backend over the jax.distributed
    coordination-service client's KV store. Heartbeats are
    sequence-numbered keys (`hb/{pid}/{seq}`) because the KV store is
    write-once: liveness is 'how long since the NEXT sequence number
    appeared', tracked per peer on the observing side."""

    def __init__(self, client, pid: int, nproc: int,
                 peer_timeout: float = 60.0,
                 hb_interval: float | None = None,
                 namespace: str = "tt-accord/0"):
        self._client = client
        self._ns = namespace
        # per-peer [last seen seq, monotonic time it was seen]
        self._hb_seen = {p: [0, time.monotonic()]
                         for p in range(nproc) if p != pid}
        super().__init__(pid, nproc, peer_timeout, hb_interval)

    def _set(self, key, value):
        self._client.key_value_set(f"{self._ns}/{key}", value)

    def _get(self, key, timeout_s):
        try:
            return self._client.blocking_key_value_get(
                f"{self._ns}/{key}", max(1, int(timeout_s * 1000)))
        except Exception:
            return None        # missing within timeout — the protocol
            #                    loops re-check flag + liveness

    def _post_beat(self, seq):
        self._client.key_value_set(f"{self._ns}/hb/{self.pid}/{seq}", "1")

    def _silence_s(self, proc):
        ent = self._hb_seen[proc]
        while True:            # drain beats that landed since last look
            if self._get(f"hb/{proc}/{ent[0] + 1}", 0.001) is None:
                break
            ent[0] += 1
            ent[1] = time.monotonic()
        return max(0.0, time.monotonic() - ent[1])


# ---- per-process channel registry -----------------------------------
# The active channel (None = accord off). dispatch_core.fetch guards
# its multi-host allgather through `active()`; engine.run installs at
# open and uninstalls in its finally. Per-run sequence numbers keep
# repeated runs in one process (against one long-lived coordinator)
# from colliding in the shared KV namespace — every process opens the
# channel once per run, in lockstep, so the counters agree.
_ACTIVE: ControlChannel | None = None
_RUN_SEQ = 0


def install(ch: ControlChannel | None):
    global _ACTIVE
    _ACTIVE = ch
    return ch


def active() -> ControlChannel | None:
    return _ACTIVE


def open_channel(accord: bool = True, peer_timeout: float = 60.0):
    """Build the channel for this process's topology: None when accord
    is disabled (--no-accord), a solo loopback single-process (all
    paths no-op), the coordination-service backend when
    jax.distributed is live. Multi-process WITHOUT a coordination
    client (should not happen — jax.distributed.initialize creates
    one) degrades to None rather than failing the run."""
    if not accord:
        return None
    import jax

    from timetabling_ga_tpu import compat
    nproc = jax.process_count()
    if nproc == 1:
        return LoopbackChannel.solo()
    client = compat.coordination_client()
    if client is None:
        return None
    global _RUN_SEQ
    _RUN_SEQ += 1
    return DistributedChannel(
        client, jax.process_index(), nproc, peer_timeout=peer_timeout,
        namespace=f"tt-accord/{_RUN_SEQ}")
