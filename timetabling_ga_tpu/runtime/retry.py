"""Retry policy for the tunneled device's sick windows.

The device tunnel intermittently kills heavy work with
'UNAVAILABLE: TPU device error — often a kernel fault' for minutes-long
stretches, then recovers; identical deterministic programs pass between
windows (BASELINE.md, round-4 diagnosis). A second transient class
surfaced in BENCH_r05: the remote-compile RPC dies mid-response
('remote_compile: read body: response body closed before all bytes were
read') and poisons a whole bench leg that would pass seconds later.
Harnesses that must survive a window (the quality race, the benchmark's
legs) retry through it with this one shared policy, so the
error-matching condition cannot drift between copies.

Distinct from the engine's DISPATCH_CAP_S defense: the cap prevents
SELF-INFLICTED kills (a single fused dispatch predicted to outrun the
device's long-kernel watchdog); this retry absorbs kills that arrive
anyway.
"""

from __future__ import annotations

import sys
import time

# substrings identifying a transient tunnel/device failure. Matched
# against str(exception) over the WHOLE cause chain; anything else
# re-raises immediately — a real bug must never be retried into
# flakiness.
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "response body closed",     # remote_compile RPC died mid-stream
    "remote_compile",           # any other remote-compile tunnel error
    "fetch watchdog",           # engine._fetch deadline timeout (a hung
    #                             control-fence read is assumed to be a
    #                             tunnel stall, not a program bug)
    # fleet-front connection failures (fleet/gateway.py submission +
    # status polls): a replica mid-restart refuses or resets its
    # socket for a bounded window, exactly the sick-window shape this
    # policy absorbs — the router retries through it with short waits
    # and only then fails the replica over. These strings cannot arise
    # from a compiled program, so the engine-side classification is
    # unchanged.
    "Connection refused",
    "Connection reset",
    "Remote end closed connection",
    "timed out",                # socket/urllib timeout: a slow or
    #                             overloaded peer, retryable by every
    #                             consumer of this policy (the engine's
    #                             own hung-fetch case is already the
    #                             'fetch watchdog' marker)
    # tt-accord (runtime/control_channel.py AccordPeerFault): a PEER
    # declared a fault on the control side channel while this process
    # waited at a fence — the local program is healthy and the
    # supervisor must join the recovery agreement, so the signal
    # classifies transient. control_channel.PeerLost deliberately
    # avoids this substring: a dead peer is NOT retryable (the agreed
    # clean abort handles it).
    "peer declared a fault",
)

# cause-chain walk bound: a pathological cycle (e1.__cause__ = e2,
# e2.__context__ = e1) must not spin the classifier forever
_CHAIN_LIMIT = 16


def is_transient(exc: BaseException) -> bool:
    """True when `exc` — or anything on its `__cause__`/`__context__`
    chain — carries a transient tunnel/device marker. jit dispatch wraps
    the XLA 'UNAVAILABLE' error in a RuntimeError, so matching only the
    top exception misclassified exactly the failures this policy exists
    to absorb."""
    seen: set[int] = set()
    e: BaseException | None = exc
    while e is not None and len(seen) < _CHAIN_LIMIT:
        if id(e) in seen:
            break
        seen.add(id(e))
        if any(m in str(e) for m in TRANSIENT_MARKERS):
            return True
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return False


def backoff_schedule(attempts: int, wait_s: float, backoff: float,
                     max_wait_s: float):
    """The waits retry_transient sleeps between attempts: exponential
    from `wait_s` by `backoff`, capped at `max_wait_s`. Exposed so tests
    pin the schedule without sleeping through it."""
    return [min(wait_s * backoff ** i, max_wait_s)
            for i in range(max(0, attempts - 1))]


def retry_transient(fn, *args, attempts: int = 3, wait_s: float = 120.0,
                    backoff: float = 2.0, max_wait_s: float = 600.0):
    """Call `fn(*args)`; retry on transient tunnel/device errors.

    Waits grow exponentially (`wait_s * backoff**(attempt-1)`, capped at
    `max_wait_s`): the sick windows run from seconds to minutes, and a
    fixed wait either burns budget on short blips or re-enters a long
    window still sick. Returns `(result, attempts_used)` so callers can
    record how many tries the measurement cost (bench legs persist it
    in their JSON). Non-transient errors and the final attempt
    re-raise, with `exc.tt_attempts` set to the attempts consumed.
    Timed results are unaffected: a run either completes its full
    budget or raises."""
    waits = backoff_schedule(attempts, wait_s, backoff, max_wait_s)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args), attempt
        except Exception as e:
            e.tt_attempts = attempt
            if not is_transient(e) or attempt == attempts:
                raise
            wait = waits[attempt - 1]
            print(f"# transient device error "
                  f"({getattr(fn, '__name__', 'fn')}, attempt "
                  f"{attempt}/{attempts}): {str(e)[:120]}; retrying in "
                  f"{wait:.0f}s", file=sys.stderr, flush=True)
            time.sleep(wait)


def retry_unavailable(fn, *args, attempts: int = 3, wait_s: float = 120.0):
    """Back-compat wrapper around `retry_transient` returning only the
    result (the quality race and matching-gap harnesses use this form)."""
    result, _ = retry_transient(fn, *args, attempts=attempts,
                                wait_s=wait_s)
    return result
