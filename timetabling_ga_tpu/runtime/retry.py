"""Retry policy for the tunneled device's sick windows.

The device tunnel intermittently kills heavy work with
'UNAVAILABLE: TPU device error — often a kernel fault' for minutes-long
stretches, then recovers; identical deterministic programs pass between
windows (BASELINE.md, round-4 diagnosis). Harnesses that must survive a
window (the quality race, the benchmark's headline measurement) retry
through it with this one shared policy, so the error-matching condition
cannot drift between copies.

Distinct from the engine's DISPATCH_CAP_S defense: the cap prevents
SELF-INFLICTED kills (a single fused dispatch predicted to outrun the
device's long-kernel watchdog); this retry absorbs kills that arrive
anyway.
"""

from __future__ import annotations

import sys
import time


def retry_unavailable(fn, *args, attempts: int = 3, wait_s: float = 120.0):
    """Call `fn(*args)`, retrying on device-UNAVAILABLE errors.

    Non-UNAVAILABLE errors and the final attempt re-raise. Timed results
    are unaffected: a run either completes its full budget or raises."""
    from jax.errors import JaxRuntimeError
    for attempt in range(attempts):
        try:
            return fn(*args)
        except JaxRuntimeError as e:
            if "UNAVAILABLE" not in str(e) or attempt == attempts - 1:
                raise
            print(f"# device UNAVAILABLE ({getattr(fn, '__name__', 'fn')},"
                  f" attempt {attempt + 1}/{attempts}); retrying in "
                  f"{wait_s:.0f}s", file=sys.stderr, flush=True)
            time.sleep(wait_s)
