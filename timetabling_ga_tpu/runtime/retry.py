"""Retry policy for the tunneled device's sick windows.

The device tunnel intermittently kills heavy work with
'UNAVAILABLE: TPU device error — often a kernel fault' for minutes-long
stretches, then recovers; identical deterministic programs pass between
windows (BASELINE.md, round-4 diagnosis). A second transient class
surfaced in BENCH_r05: the remote-compile RPC dies mid-response
('remote_compile: read body: response body closed before all bytes were
read') and poisons a whole bench leg that would pass seconds later.
Harnesses that must survive a window (the quality race, the benchmark's
legs) retry through it with this one shared policy, so the
error-matching condition cannot drift between copies.

Distinct from the engine's DISPATCH_CAP_S defense: the cap prevents
SELF-INFLICTED kills (a single fused dispatch predicted to outrun the
device's long-kernel watchdog); this retry absorbs kills that arrive
anyway.
"""

from __future__ import annotations

import sys
import time

# substrings identifying a transient tunnel/device failure. Matched
# against str(exception); anything else re-raises immediately — a real
# bug must never be retried into flakiness.
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "response body closed",     # remote_compile RPC died mid-stream
    "remote_compile",           # any other remote-compile tunnel error
)


def is_transient(exc: BaseException) -> bool:
    return any(m in str(exc) for m in TRANSIENT_MARKERS)


def retry_transient(fn, *args, attempts: int = 3, wait_s: float = 120.0):
    """Call `fn(*args)`; retry on transient tunnel/device errors.

    Returns `(result, attempts_used)` so callers can record how many
    tries the measurement cost (bench legs persist it in their JSON).
    Non-transient errors and the final attempt re-raise, with
    `exc.tt_attempts` set to the attempts consumed. Timed results are
    unaffected: a run either completes its full budget or raises."""
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args), attempt
        except Exception as e:
            e.tt_attempts = attempt
            if not is_transient(e) or attempt == attempts:
                raise
            print(f"# transient device error "
                  f"({getattr(fn, '__name__', 'fn')}, attempt "
                  f"{attempt}/{attempts}): {str(e)[:120]}; retrying in "
                  f"{wait_s:.0f}s", file=sys.stderr, flush=True)
            time.sleep(wait_s)


def retry_unavailable(fn, *args, attempts: int = 3, wait_s: float = 120.0):
    """Back-compat wrapper around `retry_transient` returning only the
    result (the quality race and matching-gap harnesses use this form)."""
    result, _ = retry_transient(fn, *args, attempts=attempts,
                                wait_s=wait_s)
    return result
