"""The single dispatch core shared by all three dispatch loops.

Before this module, `runtime/engine.py` (the run loop), `serve/
scheduler.py` (the packing/time-slicing scheduler) and `fleet/
replicas.py` (the replica drive loop) each re-implemented the
control-vs-telemetry fence rule that keeps the device busy — three
hand-copied variants of the same discipline (ROADMAP item 1). This
module owns the pieces they share:

  - the compiled-program caches (RUNNER_CACHE / INIT_CACHE) and the
    fault-recovery program purge bound to a mesh;
  - the fetch watchdog (`fetch`): every classified CONTROL-fence host
    read runs under a deadline so a hung fetch RPC becomes a
    recoverable FetchTimeout, with deterministic fault injection
    (runtime/faults.py `fetch` site) on the same path;
  - the sanctioned TELEMETRY read (`fetch_leaf`): a plain host
    materialization of an already-transferred telemetry leaf — never a
    control fence, never injected, never deadline-guarded;
  - the packed one-round-trip readbacks (`fetch_final`, `fetch_state`)
    and the resume-side rehydrate (`reshard_state`);
  - the snapshot/rehydrate fault-recovery policy (Snapshot /
    Supervisor) the engine's supervised region and the serve path's
    per-job recovery both apply;
  - the depth-2 dispatch pipeline discipline (Chunk /
    DispatchPipeline): at most one in-flight chunk, retired with the
    next chunk already enqueued;
  - the command fence (CommandFence) of the fleet drive loop: commands
    from other threads are consumed only at control-fence boundaries,
    never mid-dispatch;
  - the shared telemetry decode (`decode_telemetry`): quality-block
    split, event decode under the effective trace mode, and on-device
    event-capacity overflow surfacing — one implementation for the
    engine's retire path and the scheduler's park path.

The split matters beyond deduplication: tt-analyze's interprocedural
device-taint pass (TT303/TT304/TT305 — analysis/project.py) treats
this module as THE dispatch surface. `fetch`/`fetch_final`/
`fetch_state` are the sanctioned control fences that clear device
taint; `fetch_leaf` is the sanctioned telemetry read; any other
host-forcing sink on a device-tainted value inside a dispatch loop is
a finding.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import sys
import threading
import time

import jax
import numpy as np

from timetabling_ga_tpu.obs.spans import NULL_TRACER
from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.runtime import control_channel
from timetabling_ga_tpu.runtime import faults
from timetabling_ga_tpu.runtime import retry
from timetabling_ga_tpu.runtime.config import RunConfig

# Compiled-program caches, shared across engine.run calls AND the serve
# path's lane programs. A jitted island runner costs seconds to tens of
# seconds to compile at race scale; rebuilding it per run (as round 2
# did, with a run-local dict) made every timed run recompile inside its
# own wall-clock budget even after a warm-up run with identical shapes.
# Keyed on the mesh's device identity plus every static that changes
# the traced program. The engine's cached_* factories populate them;
# they live HERE so recovery's purge_programs covers every loop's
# programs with one rule.
RUNNER_CACHE: dict = {}
INIT_CACHE: dict = {}


def mesh_key(mesh):
    return tuple((d.platform, d.id) for d in mesh.devices.flat)


def purge_programs(mesh) -> None:
    """Drop every compiled program bound to `mesh`'s devices from the
    module caches. After a transient device failure the cached
    executables may reference poisoned device state (a killed kernel's
    buffers, a dead tunnel stream); recovery rebuilds them — the
    recompile costs seconds and is charged against the trial budget,
    which beats resuming through an executable in an unknown state.
    Shared by the run supervisor and the serve-path per-job recovery
    (serve/scheduler.py _recover_quantum): both apply the same rule."""
    mk = mesh_key(mesh)
    for cache in (RUNNER_CACHE, INIT_CACHE):
        for k in [k for k in cache if mk in k]:
            del cache[k]


def clone_state(state):
    """Fresh device copy of a state pytree, sharding preserved.

    precompile's warm-up calls run through the DONATING runners (timed
    runs reuse exactly these compiled programs, so the warmed programs
    must be the donating ones), and donation DELETES its input buffers
    at dispatch. Every state a warm-up consumes is therefore either a
    clone of a state that is needed again, or the previous warm-up
    call's output — never a buffer someone else still holds."""
    import jax.numpy as jnp
    return jax.tree.map(jnp.copy, state)


# one dispatched-but-not-yet-retired chunk of the pipelined run loop
# (see DispatchPipeline): `trace` is the chunk's DEVICE-side telemetry
# array, fenced only when the chunk is retired; `flow` is the chunk's
# causal flow id (obs/spans.py new_flow) connecting its dispatch /
# fetch / fetch-read / process spans across threads; `cost` is the
# dispatched program's compile-time cost dict (obs/cost.py
# CostProgram.last_cost — flops/bytes), joined with the chunk's
# measured wall time into the live roofline gauges at retire
Chunk = collections.namedtuple(
    "Chunk", "td0 n_ep gens_run dyn_gens trace warm do_prof flow cost")


class DispatchPipeline:
    """Depth-2 asynchronous dispatch pipeline discipline (the engine
    module docstring's control-vs-telemetry split, distilled): at most
    ONE chunk is in flight; submitting chunk N+1 retires chunk N with
    N+1 already enqueued on the device, so N's telemetry processing
    overlaps N+1's compute. `enabled` is mutable mid-run — the fault
    supervisor's degradation ladder serializes the loop at level >= 1
    and restores the configured pipelining when the ladder relaxes
    back to level 0 — and disabling only changes WHEN chunks retire,
    never what was dispatched, which is why serial and pipelined runs
    emit identical records modulo timing (jsonl.strip_timing)."""

    def __init__(self, process, enabled: bool):
        self.process = process       # process(chunk, inflight=None)
        self.enabled = enabled
        self.pending = None          # the one in-flight chunk

    def submit(self, chunk) -> None:
        """Dispatch-side handoff: pipelined, park the chunk and retire
        its predecessor (which `process` sees with this chunk already
        running, passed as `inflight`); serial, retire immediately —
        exactly the classic loop-body order."""
        if self.enabled:
            if self.pending is not None:
                self.process(self.pending, inflight=chunk)
            self.pending = chunk
        else:
            self.process(chunk)

    def drain(self) -> None:
        """Retire the in-flight chunk, if any — the loop-exit barrier,
        and the serial fallback when a control read needs every chunk
        retired before the next dispatch decision."""
        if self.pending is not None:
            self.process(self.pending)
            self.pending = None

    def abandon(self):
        """Recovery-side teardown: forget the in-flight chunk WITHOUT
        retiring it (its device buffers may be poisoned) and return it
        so the caller can delete its trace. The supervisor calls this
        before rehydrating from the snapshot."""
        chunk, self.pending = self.pending, None
        return chunk


class CommandFence:
    """Bounded command inbox drained at control fences — the fleet
    drive loop's concurrency discipline (fleet/replicas.py). The drive
    loop is the ONLY thread that touches the device; HTTP handlers,
    signal flags and test drivers communicate by enqueueing commands,
    which the loop consumes only BETWEEN dispatched quanta (every job
    is at a park fence there), never mid-dispatch. `poll` is the busy
    fence tick; `wait` is the idle tick, bounded so drain/kill flags
    are still observed promptly."""

    def __init__(self):
        import queue
        self._q = queue.Queue()
        self._empty = queue.Empty

    def put(self, cmd) -> None:
        self._q.put(cmd)

    def poll(self):
        """Non-blocking fence drain: the next queued command, or None
        when the inbox is empty (the loop proceeds to dispatch)."""
        try:
            return self._q.get_nowait()
        except self._empty:
            return None

    def wait(self, timeout: float):
        """Idle fence tick: block up to `timeout` for a command, or
        None — the loop re-checks its drain/kill flags either way."""
        try:
            return self._q.get(timeout=timeout)
        except self._empty:
            return None


@dataclasses.dataclass
class Snapshot:
    """Rolling in-memory host snapshot of the last control-fenced run
    state — what the supervisor rehydrates from. All-numpy: nothing
    here references device buffers, so a device kill cannot poison it.
    Captured at the points where the host state is already in hand
    (init/resume, every checkpoint fence), so steady-state snapshotting
    adds no extra device round trips."""
    state: ga.PopState          # host (numpy) population
    key: np.ndarray             # raw key_data at this point
    gens_done: int
    epochs_done: int
    epochs_at_ckpt: int
    best_seen: list             # control bests AT this point
    post: bool                  # post-feasibility phase active
    kick: tuple                 # (kick_stall, kick_best, kick_streak)
    # a pipelined checkpoint fence covers the in-flight chunk's STATE
    # but its logEntries are not yet emitted; the already-fetched trace
    # is kept so recovery can emit them before resuming (the JSONL
    # stream then matches an uninjected run's, modulo timing)
    inflight_trace: object = None
    # True only for the init-time snapshot of a run whose LAHC endgame
    # already ran before the generation loop (feasible at init): replay
    # must skip the loop, not re-breed
    lahc_done: bool = False


class Supervisor:
    """In-run fault recovery policy (README "Fault tolerance").

    Holds the rolling Snapshot, classifies failures via
    retry.is_transient (cause chain included), budgets recoveries
    (--max-recoveries), and drives the degradation ladder on repeated
    failures within a window:

        level 0  pipelined dispatch (as configured)
        level 1  strictly serial loop (--no-pipeline equivalent)
        level 2+ serial AND dispatch chunks halved per level (the
                 DISPATCH_CAP_S machinery's dynamic runner serves the
                 shrunk chunks — smaller dispatches both finish under a
                 sick device's watchdog and lose less work per kill)

    Multi-host (tt-accord): recovery decisions read local clocks and
    local errors, so before any process diverges from the collective
    program order ALL processes adopt one verdict over the control
    side channel (runtime/control_channel.py) — `agree_on_fault` posts
    this process's classification and returns the deterministic merge
    of every peer's (README "Multi-host recovery"). Only then do the
    processes purge/rehydrate/resume (or cleanly abort) in lockstep.
    Requires the channel (--no-accord restores the single-process-only
    gate); the recovery path itself must never launch a device
    collective — tt-analyze TT307 audits exactly that."""

    WINDOW_S = float(os.environ.get("TT_FAULT_WINDOW_S", "300"))
    MAX_LEVEL = 4

    def __init__(self, cfg: RunConfig):
        self.cfg = cfg
        self.enabled = (cfg.max_recoveries > 0
                        and (jax.process_count() == 1
                             or getattr(cfg, "accord", True)))
        self.snap: Snapshot | None = None
        self.recoveries = 0
        self.level = 0
        self.failures: list = []     # monotonic fail times (ladder window)
        self._relaxed_at: float | None = None   # last step-back-UP time

    def snapshot(self, **kw) -> None:
        if self.enabled:
            self.snap = Snapshot(**kw)

    def dispatch_scale(self) -> float:
        """Chunk-size multiplier for ladder levels >= 2."""
        return 0.5 ** max(0, self.level - 1)

    def classify(self, exc: BaseException):
        """The faultEntry site when `exc` is recoverable here, else
        None (caller re-raises). Recoverable = supervisor enabled, a
        snapshot exists to rehydrate from, and the error classifies
        transient over its whole cause chain."""
        if not self.enabled or self.snap is None:
            return None
        if not retry.is_transient(exc):
            return None
        return getattr(exc, "tt_site", "dispatch")

    def escalate(self, now: float) -> bool:
        """Record a failure; step the ladder when failures cluster
        inside WINDOW_S. Returns True when the level changed."""
        self.failures.append(now)
        recent = [t for t in self.failures if now - t <= self.WINDOW_S]
        new_level = min(len(recent) - 1, self.MAX_LEVEL)
        if new_level > self.level:
            self.level = new_level
            return True
        return False

    def agree_on_fault(self, channel, site: str, error=None) -> dict:
        """Multi-host recovery consensus: build this process's local
        verdict — `recover` at the snapshot's generation count, or
        `abort` when the recovery budget is spent — and return the
        channel's agreed merge (control_channel.merge_verdicts: abort
        wins, else the lowest-pid REAL fault site). Host-side only:
        this and the snapshot rehydrate are the Supervisor's
        TT307-audited recovery surface, and neither may touch the
        possibly-poisoned collective program. Single-process channels
        return the local verdict unchanged."""
        local = {
            "site": site,
            "action": ("abort"
                       if self.recoveries + 1 > self.cfg.max_recoveries
                       else "recover"),
            "gens": int(self.snap.gens_done) if self.snap else -1,
            "err": str(error)[:200] if error is not None else None,
        }
        return channel.agree_on_fault(local)

    def maybe_relax(self, now: float) -> bool:
        """Step the ladder back UP (one level per clean WINDOW_S):
        before this the ladder only ever worsened within a run, so one
        early sick window left the whole rest of a long run serialized
        and chunk-halved — and /readyz stuck on `degraded` — even
        after the device recovered (carried ROADMAP item). A stretch
        of WINDOW_S with no failure since the last failure OR the last
        relax earns one level back; the engine re-enables pipelining
        when level 0 is reached and the degrade_level gauge follows
        live, so the /readyz reason clears. Returns True when the
        level changed (the caller emits the faultEntry `restore`
        record)."""
        if self.level <= 0:
            return False
        anchor = self.failures[-1] if self.failures else None
        if self._relaxed_at is not None:
            anchor = (self._relaxed_at if anchor is None
                      else max(anchor, self._relaxed_at))
        if anchor is not None and now - anchor < self.WINDOW_S:
            return False
        self.level -= 1
        self._relaxed_at = now
        return True


def reshard_state(state: ga.PopState, mesh) -> ga.PopState:
    """Place a host (numpy) PopState onto the mesh as GLOBAL
    island-sharded arrays. Multi-host safe: every process holds the full
    host copy (the checkpoint stores the global population), and
    `make_array_from_callback` slices out each process's local shards —
    the resume-side counterpart of the checkpoint allgather.

    Single-process (every serve replica) takes the `device_put` fast
    path: one placement call per leaf instead of the per-shard callback
    slicing, which matters now that the serve scheduler re-places a
    whole stacked group at every non-resident resume fence."""
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, jax.sharding.PartitionSpec(islands.AXIS))
    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), sh), state)
    return jax.tree.map(
        lambda x: jax.make_array_from_callback(
            np.asarray(x).shape, sh, lambda idx, x=x: np.asarray(x)[idx]),
        state)


def state_nbytes(state) -> int:
    """Bytes a PopState moves across the device<->host boundary when
    parked (`fetch_state`) or re-placed (`reshard_state`) — the unit the
    serve scheduler's `serve.park_bytes` / `serve.resume_bytes` counters
    and the bench `extra.serve_mesh` leg account in. Works on host
    (numpy) and device pytrees alike; None-safe."""
    if state is None:
        return 0
    return int(sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree.leaves(state)))


# deadline (seconds) for the fetch watchdog below; set per run from
# RunConfig.fetch_timeout (0/None disables, via set_fetch_timeout).
# Module-level because fetch is called from every layer of every
# dispatch loop.
_FETCH_TIMEOUT: float | None = None


def set_fetch_timeout(timeout: float | None) -> None:
    """Install the control-fence fetch deadline for this process
    (engine.run / engine.precompile call this from
    RunConfig.fetch_timeout; 0/None disables the watchdog)."""
    global _FETCH_TIMEOUT
    _FETCH_TIMEOUT = timeout if timeout else None


class FetchTimeout(TimeoutError):
    """A classified control-fence host read exceeded the watchdog
    deadline. The message carries retry.TRANSIENT_MARKERS' 'fetch
    watchdog' so the supervisor classifies it transient: a hung fetch
    on the tunneled device (the BENCH_r05 mid-stream RPC death's worst
    case) is a sick window, not a program bug."""


def fetch(x, tracer=NULL_TRACER, flow=None) -> np.ndarray:
    """Device->host CONTROL fetch that also works for multi-host global
    arrays: single-process it is a plain np.asarray; multi-process the
    shards are allgathered so every process sees the global value (the
    reference ships full solutions between ranks the same way,
    ga.cpp:318-368).

    Single-process fetches run under a deadline watchdog (RunConfig.
    fetch_timeout): the read happens on a monitored thread, and when it
    outlives the deadline the MAIN loop abandons it and raises
    FetchTimeout — a hung fetch RPC becomes a classified, recoverable
    error instead of a silent stall. The abandoned daemon thread parks
    on the dead RPC; its eventual result is discarded. Multi-host
    fetches are collectives and must stay on the main thread (every
    process must enter them in program order), so the watchdog is
    single-process only. `faults.maybe_fail('fetch')` is the injection
    point for both the hang and the kill flavor."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        faults.maybe_fail("fetch")
        # tt-accord: host-side rendezvous BEFORE the allgather. A peer
        # that faulted (or died) can never reach this collective — the
        # guard raises AccordPeerFault/PeerLost on the side channel
        # within --peer-timeout instead of letting this process hang
        # forever at the collective rendezvous.
        ch = control_channel.active()
        if ch is not None:
            ch.guard_collective()
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    timeout = _FETCH_TIMEOUT
    if not timeout:
        faults.maybe_fail("fetch")
        return np.asarray(x)
    box: dict = {}

    def _read():
        tr0 = time.monotonic()
        try:
            faults.maybe_fail("fetch")
            box["value"] = np.asarray(x)
            if flow is not None:
                # the watchdog THREAD's half of the fetch: a span on its
                # own tid, tied to the dispatch's flow id so `tt trace`
                # draws the arrow across the thread boundary
                tracer.record("fetch-read", tr0,
                              time.monotonic() - tr0, cat="engine",
                              flow=flow)
        except BaseException as e:   # re-raised on the main thread
            box["error"] = e

    th = threading.Thread(target=_read, name="tt-fetch-watchdog",
                          daemon=True)
    th.start()
    th.join(timeout)
    if th.is_alive():
        err = FetchTimeout(
            f"fetch watchdog: control-fence host read exceeded "
            f"{timeout:.0f}s deadline")
        err.tt_site = "fetch"
        raise err
    if "error" in box:
        e = box["error"]
        e.tt_site = "fetch"
        raise e
    return box["value"]


def fetch_leaf(x) -> np.ndarray:
    """Sanctioned TELEMETRY read: materialize an already-dispatched
    telemetry leaf on the host. Deliberately NOT `fetch`: a telemetry
    read must never become a classified control fence — no fault
    injection (adding a `fetch` site here would shift every
    deterministic TT_FAULTS invocation index), no watchdog deadline,
    no allgather (telemetry is process-local by construction). The
    interprocedural taint pass (TT303/TT305) treats this as the
    telemetry-side sink that CLEARS device taint without fencing the
    dispatch stream."""
    return np.asarray(x)


def fetch_final(state, n_islands: int, pop: int):
    """endTry device->host readback as ONE round trip: concatenate
    slots/rooms/hcv/scv into a single (N*P, 2E+2) device array and fetch
    it once (each separate fetch is a multi-second round trip on
    tunneled devices — the same cost the polish loop's stacked stats
    fetch avoids). Returns (slots (N,P,E), rooms (N,P,E), best-row hcv
    (N,), best-row scv (N,)) as numpy."""
    import jax.numpy as jnp
    packed = fetch(jnp.concatenate(
        [state.slots, state.rooms,
         state.hcv[:, None], state.scv[:, None]], axis=1))
    E = (packed.shape[1] - 2) // 2
    slots = packed[:, :E].reshape(n_islands, pop, E)
    rooms = packed[:, E:2 * E].reshape(n_islands, pop, E)
    hcv = packed[:, 2 * E].reshape(n_islands, pop)[:, 0]
    scv = packed[:, 2 * E + 1].reshape(n_islands, pop)[:, 0]
    return slots, rooms, hcv, scv


def fetch_state(state) -> ga.PopState:
    """Host (numpy) snapshot of a PopState as ONE device round trip —
    the checkpoint-path sibling of `fetch_final` (each separate fetch
    is a multi-second round trip on tunneled devices, VERDICT round-3
    weak #3, and this fetch sits on the pipelined dispatch path):
    concatenate slots/rooms/penalty/hcv/scv into a single
    (N*P, 2E+3) int32 array, fetch once, slice apart. The returned
    all-numpy PopState is the same tuple checkpoint.save takes and
    reshard_state re-places."""
    import jax.numpy as jnp
    packed = fetch(jnp.concatenate(
        [state.slots, state.rooms, state.penalty[:, None],
         state.hcv[:, None], state.scv[:, None]], axis=1))
    E = (packed.shape[1] - 3) // 2
    return ga.PopState(
        slots=packed[:, :E], rooms=packed[:, E:2 * E],
        penalty=packed[:, 2 * E], hcv=packed[:, 2 * E + 1],
        scv=packed[:, 2 * E + 2])


def decode_telemetry(trace, quality: bool, trace_mode: str,
                     metrics=None, overflow_counter: str = "",
                     overflow_warned: bool = True,
                     warn_label: str = "", dyn_gens=None):
    """Shared telemetry decode for a retired chunk/quantum — the block
    the engine's `_process` and the scheduler's park path used to
    hand-copy. Splits the trailing quality rows off the fetched leaf
    (numpy slice; the fetch stayed one leaf), trims a dynamic
    dispatch's full-trace tail, decodes events under the EFFECTIVE
    trace mode (a full trace upgrades to deltas under quality —
    islands.effective_trace_mode; the record stream is unchanged), and
    surfaces on-device event-capacity overflow: the count says how
    many improvements happened, the event block holds at most
    TRACE_DELTAS_CAP — never under-report silently.

    Returns (events, ev_moments, qrows, overflow_warned). Pass
    `metrics`/`overflow_counter` to count dropped events (engine:
    engine.trace_delta_overflow, serve: serve.trace_delta_overflow);
    `warn_label` prefixes the one-shot stderr warning ("" for the
    engine, "serve " for the scheduler) so the messages stay exactly
    what each loop printed before the extraction."""
    trace, qrows = islands.split_quality(trace, quality)
    ev_mode = islands.effective_trace_mode(trace_mode, quality)
    if dyn_gens is not None and ev_mode == "full":
        # compressed leaves carry their own validity (sentinel event
        # rows); only the full trace needs the tail slice
        trace = trace[:, :, :dyn_gens]
    events, ev_counts, ev_moments = islands.trace_events(trace, ev_mode)
    if ev_counts is not None and metrics is not None:
        dropped = int(sum(max(0, int(c) - len(e))
                          for c, e in zip(ev_counts, events)))
        if dropped:
            metrics.counter(overflow_counter).inc(dropped)
            if not overflow_warned:
                overflow_warned = True
                print(f"warning: {warn_label}--trace-mode {trace_mode} "
                      f"dropped {dropped} improvement event(s) "
                      f"this dispatch (cap "
                      f"{islands.TRACE_DELTAS_CAP}; raise "
                      f"TT_TRACE_DELTAS_CAP)", file=sys.stderr)
    return events, ev_moments, qrows, overflow_warned
