"""Deterministic fault injection — the test double for the tunnel's
sick windows.

Every recovery path the run supervisor implements (runtime/engine.py)
exists because the REAL device occasionally kills dispatches with
'UNAVAILABLE', hangs a fetch RPC mid-stream, or lets a process die with
a half-written checkpoint (BASELINE.md round-4 diagnosis, BENCH_r05).
None of that is reproducible on demand, so this module provides the
faults on demand instead: named injection points threaded through the
engine's dispatch sites, the control-fence fetch path, the
jsonl.AsyncWriter worker, and checkpoint.save — each of which calls
`maybe_fail(site)` exactly once per logical operation. A fault plan
then makes the Nth invocation of a site fail in a chosen way, so every
recovery path runs deterministically on the CPU backend in tier-1 with
no real TPU sick window required.

Grammar (env var `TT_FAULTS`, or RunConfig.faults / `--faults`):

    TT_FAULTS=dispatch:3:unavailable,fetch:5:hang,writer:1:die,ckpt:2:truncate

Each entry is `site:nth:action` — on the `nth` (1-based) invocation of
`site`, perform `action`. Multi-process runs (tt-accord) scope a site
with `site@proc` — `dispatch@1:2:die` kills process 1's second
dispatch and is parsed AWAY on every other process, so their
invocation indices are exactly the single-process plan's (one shared
`TT_FAULTS` env value drives a deterministic cross-process matrix).
UNSCOPED entries apply to process 0 only when `set_process` reports
more than one process: without that rule a shared env value would
fire the same plan independently on every process, shifting every
index the moment any site's call count differs across processes.
Single-process runs (the default `set_process(0, 1)`) are untouched —
unscoped entries apply, `@0` is accepted and equivalent:

    unavailable  raise RuntimeError wrapping an inner exception whose
                 message carries 'UNAVAILABLE' (the jit-dispatch
                 wrapping shape — retry.is_transient must walk the
                 cause chain to classify it)
    hang         sleep for TT_FAULT_HANG_S seconds (default 3600) —
                 inside the fetch watchdog's monitored thread this
                 becomes a deadline timeout, the designed detection
    die          raise SystemExit — inside the AsyncWriter worker the
                 thread exits silently without draining its queue (the
                 worker-death scenario the death-aware enqueue guards)
    truncate     truncate the just-written file to half its size (the
                 torn-checkpoint scenario the path.prev rotation
                 recovers from); requires the site to pass `path=`
    error        raise FaultInjected directly (a NON-transient failure:
                 the supervisor must re-raise, not recover)

Sites currently wired: `dispatch` (engine generation/polish/LAHC/kick
dispatch sites), `fetch` (every classified control-fence host read,
inside the watchdog thread), `writer` (AsyncWriter worker, once per
dequeued item), `ckpt` (checkpoint.save, after the durable rename),
`init` (the engine's pre-snapshot init dispatch — the supervised-init
retry's window), `obs_listen` (the pull front's server thread at
startup), `scrape` (once per handled HTTP request, on the handler
thread — a hang/die there must never stall dispatch, serve, or writer
drain; tests/test_obs.py pins it), `mem_poll` (once per device-memory
sample on the cost observatory's poller thread), `profile` (on the
profiler-capture worker around each start/stop — same isolation
contract as the listener sites; tests/test_cost.py pins it),
`gateway` (the fleet gateway's HTTP accept loop at startup),
`route` (once per routing decision on the gateway dispatcher thread —
both fleet sites share the listener sites' isolation contract:
tests/test_fleet.py pins that a wedged gateway never stalls replica
dispatch or writer drain), `gw_writer` (the gateway's OWN telemetry
AsyncWriter worker, once per dequeued item — a dead gateway log
writer must never stall the dispatcher or job settlement; the gateway
disables its obs emission and routes on), `gw_scrape` (once per
replica /metrics scrape on the prober thread — a hung scrape parks
only the prober; routing continues on the last-probed gauges and job
settlement never waits on it; tests/test_fleet_obs.py pins both),
`quantum` (once per stacked serve lane dispatch — the serve-path
fault-recovery window: affected jobs requeue from their park
snapshots), `snapshot_ship` (once per `?snapshot=1` export on a
replica handler thread — a hung export parks one handler, never the
drive loop or writer), `resume` (once per warm-start snapshot
admission — any failure falls back to a fresh replay;
tests/test_resume.py pins the triad), `history` (once per registry
sample on the tt-flight history sampler thread — obs/history.py),
`flight_dump` (once per incident-dump attempt on the flight recorder
thread — obs/flight.py; both share the mem_poll isolation contract:
a hung or dead sampler/dumper never stalls dispatch, settlement, or
writer drain — tests/test_flight.py pins it) and `usage` (once per
drained event batch on the tt-meter usage ledger thread —
obs/usage.py; same contract: a hung or dead ledger leaves stale
meters, never a stalled dispatch — tests/test_usage.py pins it) and
`scaler` (once per policy-evaluation tick on the tt-scale autoscaler
thread — fleet/autoscaler.py; same isolation contract: a hung or dead
scaler freezes the fleet at its current replica count, never routing,
dispatch, settlement, or writer drain — tests/test_scale.py pins it).

The plan is installed per engine.run call (`install`), which resets the
per-site counters — invocation indices are deterministic within one
run. With no plan installed every `maybe_fail` is a no-op costing one
dict lookup. Stdlib-only: jsonl/checkpoint import this module, and
nothing here may import jax or the rest of the runtime.
"""

from __future__ import annotations

import os
import threading
import time

HANG_S = float(os.environ.get("TT_FAULT_HANG_S", "3600"))

ACTIONS = ("unavailable", "hang", "die", "truncate", "error")

# the wired injection points — a closed set, validated at parse time so
# a typo'd site fails loudly instead of becoming a silent no-op plan
# (the exact failure mode a deterministic harness exists to prevent).
# `init` fires at the engine's pre-snapshot init dispatch (the window
# the supervised-init retry covers — ROADMAP PR-3 follow-up); it is a
# separate site so injecting there does not shift the invocation
# indices of the `dispatch` plans existing tests pin.
# `obs_listen` fires on the pull front's server thread at startup and
# `scrape` once per handled HTTP request (obs/http.py) — both execute
# OFF the dispatch/serve/writer paths by design, and the tests pin
# that a hung or dead listener never stalls any of them.
# `mem_poll` fires once per device-memory sample on the MemPoller's
# own daemon thread and `profile` on the ProfileCapture worker around
# each profiler start/stop (obs/cost.py) — the cost observatory's two
# threads, with the same isolation contract: a hang parks only that
# thread, a die ends it, and dispatch/serve/writer drain never wait on
# either (tests/test_cost.py pins it).
# `gateway` fires on the fleet gateway's HTTP accept loop at startup
# (fleet/gateway.py — the obs_listen analogue for the solve front) and
# `route` once per routing decision on the gateway's dispatcher thread
# (fleet/router.py Router.route). Both run OFF every replica's
# dispatch/serve/writer path: a wedged gateway makes the FRONT
# unreachable, but every replica keeps dispatching and draining its
# writer untouched (tests/test_fleet.py pins it).
# `gw_writer` fires on the gateway's telemetry AsyncWriter worker
# (once per dequeued item — the `writer` site's gateway twin, separate
# so a gateway-log fault cannot shift a replica writer plan's indices)
# and `gw_scrape` once per replica /metrics scrape on the ReplicaSet
# prober thread (fleet/replicas.py). Isolation contract: a dead
# gateway writer disables obs emission and the dispatcher routes on; a
# hung scrape parks only the prober — job settlement never waits on
# either (tests/test_fleet_obs.py pins it).
# The resume triad (tests/test_resume.py pins all three):
# `quantum` fires once per stacked serve lane dispatch on the drive
# loop (serve/scheduler.py _advance) — the serve-path fault-recovery
# window: a transient there requeues ONLY the dispatch's jobs from
# their park snapshots (supervisor classify/rehydrate at job
# granularity) while co-tenant jobs and the writer run on untouched.
# `snapshot_ship` fires once per snapshot export (the `?snapshot=1`
# pack on a replica HTTP handler thread, fleet/replicas.py): a hang
# parks that one handler thread — the gateway's fetch times out and
# routing, the drive loop and writer drain never wait on it; a die is
# absorbed as a dropped connection like the `scrape` site's.
# `resume` fires once per warm-start snapshot admission on the drive
# loop (serve/scheduler.py _admit_resumed): any failure there —
# including an injected die — falls back to a fresh solve (replay)
# with a faultEntry, so a poisoned snapshot can reject, never stall,
# the service.
# The tt-flight pair (tests/test_flight.py pins both): `history` fires
# once per registry sample on the obs/history.py sampler thread (the
# mem_poll discipline — a hang parks the sampler, history goes stale,
# nothing else notices; a die ends it silently) and `flight_dump`
# once per incident-dump attempt on the obs/flight.py recorder thread
# (a hang parks the recorder — no bundle materializes; a die ends it —
# dispatch, settlement, and writer drain never wait on either).
# `usage` fires once per drained event batch on the tt-meter usage
# ledger thread (obs/usage.py UsageLedger) — the mem_poll/history
# discipline: a hang parks the ledger (tenant meters go stale, over-cap
# events drop into the honest `usage.dropped` counter), a die ends it
# silently; dispatch, job settlement, and writer drain never wait on it
# (tests/test_usage.py pins the isolation).
# `scaler` fires once per policy-evaluation tick on the tt-scale
# autoscaler thread (fleet/autoscaler.py) — the history/usage
# discipline: a hang parks the scaler (the fleet stops scaling but
# keeps serving at its current replica count), a die ends it silently;
# routing, dispatch, job settlement, and writer drain never wait on it
# (tests/test_scale.py pins the isolation).
SITES = ("dispatch", "fetch", "writer", "ckpt", "init", "obs_listen",
         "scrape", "mem_poll", "profile", "gateway", "route",
         "gw_writer", "gw_scrape", "quantum", "snapshot_ship",
         "resume", "history", "flight_dump", "usage", "scaler")


# this process's coordinates in a multi-launch run, injected by
# engine.run AFTER jax.distributed init (this module is stdlib-only
# and cannot ask jax itself). Defaults keep every single-process
# caller — serve replicas, the fleet, direct installs in tests —
# bit-identical to the pre-accord behavior.
_PROC = 0
_NPROC = 1


def set_process(proc: int, nproc: int) -> None:
    """Declare this process's (index, count) for plan scoping. Parse
    happens per install, so call this BEFORE `install` (engine.run
    orders it right after maybe_init_distributed)."""
    global _PROC, _NPROC
    _PROC = int(proc)
    _NPROC = max(1, int(nproc))


class FaultInjected(Exception):
    """An injected fault (also the inner 'device' error for the
    `unavailable` action, whose message carries the transient marker)."""


class FaultPlanError(ValueError):
    """Malformed TT_FAULTS specification."""


class FaultPlan:
    """Parsed `site:nth:action` entries plus per-site invocation
    counters. Thread-safe: the writer worker and the fetch watchdog
    threads hit `maybe_fail` concurrently with the main loop."""

    def __init__(self, entries: dict):
        # {site: {nth: action}}
        self._entries = entries
        self._counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.injected = 0          # actions actually triggered

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries: dict[str, dict[int, str]] = {}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            parts = item.split(":")
            if len(parts) != 3:
                raise FaultPlanError(
                    f"bad TT_FAULTS entry {item!r} (want site:nth:action)")
            site, nth_s, action = (p.strip() for p in parts)
            # process scope (`site@proc`, tt-accord): parse-time
            # filtering — a plan only ever holds THIS process's
            # entries, so counters and indices are per-process stable
            # under one shared TT_FAULTS env value
            proc = None
            if "@" in site:
                site, _, proc_s = site.partition("@")
                site = site.strip()
                try:
                    proc = int(proc_s)
                except ValueError:
                    raise FaultPlanError(
                        f"bad TT_FAULTS process scope {proc_s!r} in "
                        f"{item!r} (want site@proc)") from None
                if proc < 0:
                    raise FaultPlanError(
                        f"TT_FAULTS process scope must be >= 0 in "
                        f"{item!r}")
            try:
                nth = int(nth_s)
            except ValueError:
                raise FaultPlanError(
                    f"bad TT_FAULTS index {nth_s!r} in {item!r}") from None
            if nth < 1:
                raise FaultPlanError(
                    f"TT_FAULTS index must be >= 1 in {item!r}")
            if site not in SITES:
                raise FaultPlanError(
                    f"unknown TT_FAULTS site {site!r} in {item!r} "
                    f"(one of {', '.join(SITES)})")
            if action not in ACTIONS:
                raise FaultPlanError(
                    f"unknown TT_FAULTS action {action!r} in {item!r} "
                    f"(one of {', '.join(ACTIONS)})")
            if proc is None:
                # unscoped under a multi-process launch: process 0
                # only (module docstring — the indices rule)
                if _NPROC > 1 and _PROC != 0:
                    continue
            elif proc != _PROC:
                continue           # another process's entry
            entries.setdefault(site, {})[nth] = action
        return cls(entries)

    def pop_action(self, site: str):
        """Count one invocation of `site`; return the action due at this
        index, or None."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            act = self._entries.get(site, {}).pop(n, None)
            if act is not None:
                self.injected += 1
                # mirror into the obs metrics registry (tt-obs) so
                # `faults.injected` shows up in metricsEntry snapshots.
                # Lazy import on the injection path only: module LOAD
                # stays stdlib-only (the contract above), and a plan
                # only ever fires inside an engine/serve run where the
                # package is long imported.
                try:
                    from timetabling_ga_tpu.obs import metrics as _obs
                    _obs.REGISTRY.counter("faults.injected").inc()
                except Exception:
                    pass   # telemetry must never break injection
            return act


# the active plan (None = injection disabled) and a process-lifetime
# count of triggered faults (bench.py records per-leg deltas)
_PLAN: FaultPlan | None = None
_INJECTED_TOTAL = 0


def install(spec: str | None) -> FaultPlan | None:
    """Install the plan for `spec` (resetting all counters), or disable
    injection when `spec` is falsy. Called by engine.run with
    RunConfig.faults, falling back to the TT_FAULTS env var."""
    global _PLAN, _INJECTED_TOTAL
    if _PLAN is not None:
        _INJECTED_TOTAL += _PLAN.injected
    if not spec:
        _PLAN = None
    else:
        _PLAN = FaultPlan.parse(spec)
    return _PLAN


def active_spec(cfg_spec: str | None = None) -> str | None:
    """The spec to install: explicit config wins, else TT_FAULTS."""
    return cfg_spec if cfg_spec else os.environ.get("TT_FAULTS") or None


def injected_total() -> int:
    """Faults triggered over the process lifetime (all plans)."""
    return _INJECTED_TOTAL + (_PLAN.injected if _PLAN is not None else 0)


def maybe_fail(site: str, path: str | None = None) -> None:
    """One logical operation at `site`; trigger the plan's fault for
    this invocation index, if any. No-op without an installed plan."""
    plan = _PLAN
    if plan is None:
        return
    act = plan.pop_action(site)
    if act is None:
        return
    if act == "unavailable":
        inner = FaultInjected(
            f"UNAVAILABLE: TPU device error — injected fault "
            f"(site {site})")
        raise RuntimeError(
            f"injected transient failure at {site}") from inner
    if act == "hang":
        time.sleep(HANG_S)
        return
    if act == "die":
        raise SystemExit(f"injected thread death at {site}")
    if act == "truncate":
        if path is not None and os.path.exists(path):
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(max(1, size // 2))
        return
    if act == "error":
        raise FaultInjected(
            f"injected non-transient failure at {site}")
