"""JSONL reporting protocol — byte-compatible with the reference.

The reference emits one JSON object per line with JsonCpp's
StreamWriterBuilder and `indentation=""` (ga.cpp:169-171, 469-470). Three
record types (SURVEY C18); field names verified against ga.cpp:

  {"logEntry":{"procID":i,"threadID":t,"best":b,"time":s}}
      on every new local best (ga.cpp:502, setCurrentCost 203-228);
      `best` is scv when feasible, else hcv*1e6+scv
  {"solution":{"procID":i,"threadID":t,"totalTime":s,"totalBest":b,
               "feasible":f[,"timeslots":[...],"rooms":[...]]}}
      per process at the end (endTry, ga.cpp:169-197, 474); the timetable
      arrays are present only when feasible
  {"runEntry":{"totalBest":b,"feasible":f}}
      cluster-level best after the Allreduce (setGlobalCost, ga.cpp:
      234-257), then the same object re-emitted with procsNum/threadsNum/
      totalTime appended (ga.cpp:604-607) — both lines are reproduced.

This protocol is the reference's de-facto external API, so the schema is
kept verbatim (keys, nesting, and which records appear when).

threadID semantics on the TPU path: DEFINED AS 0. The reference's
threadID names the OpenMP thread that bred the improving child
(ga.cpp:203-228); on the TPU path the whole island's breeding is one
fused vmap with no thread identity, and the logEntry values come from
the island's penalty-sorted row 0, so there is no meaningful lane to
report. The field is kept (schema parity) with the constant value 0.
`tt_cpu --algo reference` emits real thread ids (its breeding IS
threaded); tests/test_runtime.py pins the TPU-path constant.
"""

from __future__ import annotations

import json
from typing import IO, List, Optional


def _write(stream: IO, obj: dict) -> None:
    stream.write(json.dumps(obj, separators=(",", ":")) + "\n")
    stream.flush()


def reported_best(hcv: int, scv: int) -> int:
    """The value the protocol reports: scv when feasible, else
    hcv*1e6+scv (ga.cpp:205-228)."""
    return int(scv) if int(hcv) == 0 else int(hcv) * 1_000_000 + int(scv)


def log_entry(stream: IO, proc_id: int, thread_id: int, best: int,
              time_s: float) -> None:
    _write(stream, {"logEntry": {
        "procID": proc_id,
        "threadID": thread_id,
        "best": int(best),
        "time": max(0.0, float(time_s)),
    }})


def solution_record(stream: IO, proc_id: int, thread_id: int,
                    total_time: float, total_best: int, feasible: bool,
                    timeslots: Optional[List[int]] = None,
                    rooms: Optional[List[int]] = None) -> None:
    rec = {
        "procID": proc_id,
        "threadID": thread_id,
        "totalTime": float(total_time),
        "totalBest": int(total_best),
        "feasible": bool(feasible),
    }
    if feasible:
        rec["timeslots"] = [int(x) for x in timeslots]
        rec["rooms"] = [int(x) for x in rooms]
    _write(stream, {"solution": rec})


def phase_record(stream: IO, name: str, trial: int, seconds: float,
                 **extra) -> None:
    """Observability EXTENSION record (not in the reference protocol;
    emitted only under --trace): per-phase host timing bracketed by
    block_until_ready — the TPU-native stand-in for the reference's
    Timer instrumentation (Timer.C:36-49) and the MPE trace hook it
    never enabled (Makefile:3)."""
    rec = {"name": name, "trial": int(trial),
           "seconds": float(seconds)}
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"phase": rec})


def run_entry(stream: IO, total_best: int, feasible: bool,
              procs_num: Optional[int] = None,
              threads_num: Optional[int] = None,
              total_time: Optional[float] = None) -> None:
    rec = {"totalBest": int(total_best), "feasible": bool(feasible)}
    if procs_num is not None:
        rec["procsNum"] = int(procs_num)
        rec["threadsNum"] = int(threads_num)
        rec["totalTime"] = float(total_time)
    _write(stream, {"runEntry": rec})
