"""JSONL reporting protocol — byte-compatible with the reference.

The reference emits one JSON object per line with JsonCpp's
StreamWriterBuilder and `indentation=""` (ga.cpp:169-171, 469-470). Three
record types (SURVEY C18); field names verified against ga.cpp:

  {"logEntry":{"procID":i,"threadID":t,"best":b,"time":s}}
      on every new local best (ga.cpp:502, setCurrentCost 203-228);
      `best` is scv when feasible, else hcv*1e6+scv
  {"solution":{"procID":i,"threadID":t,"totalTime":s,"totalBest":b,
               "feasible":f[,"timeslots":[...],"rooms":[...]]}}
      per process at the end (endTry, ga.cpp:169-197, 474); the timetable
      arrays are present only when feasible
  {"runEntry":{"totalBest":b,"feasible":f}}
      cluster-level best after the Allreduce (setGlobalCost, ga.cpp:
      234-257), then the same object re-emitted with procsNum/threadsNum/
      totalTime appended (ga.cpp:604-607) — both lines are reproduced.

This protocol is the reference's de-facto external API, so the schema is
kept verbatim (keys, nesting, and which records appear when).

threadID semantics on the TPU path: DEFINED AS 0. The reference's
threadID names the OpenMP thread that bred the improving child
(ga.cpp:203-228); on the TPU path the whole island's breeding is one
fused vmap with no thread identity, and the logEntry values come from
the island's penalty-sorted row 0, so there is no meaningful lane to
report. The field is kept (schema parity) with the constant value 0.
`tt_cpu --algo reference` emits real thread ids (its breeding IS
threaded); tests/test_runtime.py pins the TPU-path constant.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import IO, List, Optional

from timetabling_ga_tpu.runtime import faults


def _write(stream: IO, obj: dict) -> dict:
    stream.write(json.dumps(obj, separators=(",", ":")) + "\n")
    stream.flush()
    # the record is returned through every emitter below so a caller
    # that must MIRROR its own stream — the serve scheduler's per-job
    # ship units (serve/snapshot.py), which ride a snapshot across
    # processes together with the exact records emitted up to its
    # fence — can capture the dict it just wrote without rebuilding it
    return obj


class AsyncWriter:
    """Background writer thread behind a bounded queue: the telemetry
    half of the engine's dispatch pipeline.

    The engine's host loop used to pay every JSONL write (and every
    checkpoint np.savez) INLINE between device dispatches — host work
    the device idled through. This object is file-like (`write`/`flush`)
    so the record emitters above use it unchanged; each `write` call
    enqueues one COMPLETE line (the emitters always pass exactly one
    record per call, which is what keeps the output line-atomic — the
    worker hands the line to the underlying stream in a single write()
    and flushes, so a kill mid-run leaves whole records, never spliced
    ones). `submit` enqueues an arbitrary job (checkpoint
    serialization) on the SAME queue, preserving order relative to the
    records around it.

    Drain semantics: `close()` (and `drain()`) block until every queued
    item has been handed to the underlying stream, then (`close` only)
    stop the worker — the engine calls close() in a finally, so the
    stream is complete both on clean exit and on error. A worker-side
    exception (disk full, closed stream) is captured and re-raised on
    the MAIN thread at the next write/submit/drain/close — telemetry
    failures must fail the run, not vanish into a daemon thread. The
    bounded queue (default 1024 items) is backpressure: a stalled disk
    blocks the producer instead of growing memory without bound."""

    _STOP = object()

    def __init__(self, stream: IO, maxsize: int = 1024,
                 site: str = "writer"):
        self._stream = stream
        # which fault-injection site this writer's worker fires
        # (runtime/faults.py): "writer" for the engine/serve record
        # stream, "gw_writer" for the fleet gateway's telemetry log —
        # separate sites so a test killing the gateway's writer cannot
        # shift the invocation indices of an in-process replica's plan
        self._site = site
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._records = 0      # lines enqueued (obs: writer.records)
        self._error: BaseException | None = None
        self._failed = False   # worker latch, never cleared: once the
        #                        stream failed mid-record, writing more
        #                        would splice after the partial line
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="tt-jsonl-writer", daemon=True)
        self._thread.start()

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            # fault-injection point (runtime/faults.py `writer` site):
            # an injected death exits the thread WITHOUT task_done — the
            # worker-death scenario the death-aware enqueue/drain below
            # must turn into a raised error, not a deadlock
            try:
                faults.maybe_fail(self._site)
            except SystemExit:
                return
            try:
                if item is self._STOP:
                    return
                if callable(item):
                    # a failed JOB (checkpoint serialization) leaves no
                    # partial line, so records queued behind it are
                    # still safe to write — only the error propagates
                    if self._error is None:
                        item()
                elif not self._failed:
                    try:
                        self._stream.write(item)
                        self._stream.flush()
                    except BaseException:
                        # _error is cleared when re-raised to the
                        # producer; _failed is not — the worker must
                        # never write past a mid-record STREAM failure
                        # (a resumed stream would splice the next
                        # record onto the partial line)
                        self._failed = True
                        raise
            except BaseException as e:  # captured, re-raised on main
                self._error = e
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _check_open(self) -> None:
        if self._closed:
            # silently dropping records would violate the 'telemetry
            # failures must fail the run' contract
            raise RuntimeError("AsyncWriter is closed")

    def _put(self, item) -> None:
        """Death-aware enqueue: a plain `queue.put` on a full queue
        blocks FOREVER if the worker thread has died (nothing will ever
        drain it) — the producer then hangs instead of failing. Bounded
        waits re-check worker liveness between attempts and raise the
        pending worker error (or a thread-death error) instead."""
        while True:
            if not self._thread.is_alive():
                self._raise_pending()
                raise RuntimeError(
                    "AsyncWriter worker thread died; enqueue would "
                    "never drain")
            try:
                self._q.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    def _await_drained(self) -> None:
        """Death-aware queue join: `Queue.join` waits on task_done
        calls only the worker makes, so a dead worker turns it into a
        deadlock. Wait on the same condition with a liveness check."""
        q = self._q
        with q.all_tasks_done:
            while q.unfinished_tasks:
                if not self._thread.is_alive():
                    self._raise_pending()
                    raise RuntimeError(
                        "AsyncWriter worker thread died with items "
                        "still queued")
                q.all_tasks_done.wait(0.1)

    def write(self, s: str) -> None:
        if threading.current_thread() is self._thread:
            # called FROM the worker thread — a submitted job emitting a
            # record (a flow span bracketing checkpoint serialization,
            # obs/spans.py). Enqueueing here could deadlock: on a full
            # queue the producer-side _put would wait for a drain only
            # this very thread performs. The worker is the stream's sole
            # writer and it is exactly here, so a direct write stays
            # line-atomic and ordered (it lands right where the job sits
            # in the queue order).
            if not self._failed:
                self._records += 1
                try:
                    self._stream.write(s)
                    self._stream.flush()
                except BaseException:
                    # same latch as the worker's own write path: never
                    # splice another record after a partial line
                    self._failed = True
                    raise
            return
        self._check_open()
        self._raise_pending()
        self._records += 1
        self._put(s)

    def alive(self) -> bool:
        """Worker-thread liveness — the pull front's `/healthz` writer
        probe (obs/http.py): a dead worker means records are piling into
        a queue nothing drains."""
        return self._thread.is_alive()

    def qsize(self) -> int:
        """Current queue occupancy — the obs metrics registry samples
        this through a pull gauge (`writer.queue_depth`): a queue
        sitting near its bound means the disk, not the device, is the
        bottleneck."""
        return self._q.qsize()

    @property
    def records_written(self) -> int:
        """Lines enqueued over this writer's lifetime (obs:
        `writer.records` pull gauge)."""
        return self._records

    def flush(self) -> None:
        """No-op: the worker flushes after every record. (The emitters
        call stream.flush() per line; making this synchronous would
        serialize the pipeline the writer exists to unblock.)"""

    def submit(self, job) -> None:
        """Enqueue `job()` (e.g. a checkpoint np.savez) behind every
        record already queued."""
        self._check_open()
        self._raise_pending()
        self._put(job)

    def drain(self) -> None:
        """Block until the queue is empty and every item is written."""
        self._await_drained()
        self._raise_pending()

    def close(self, raise_error: bool = True) -> None:
        """Drain, then stop the worker; idempotent. Does NOT close the
        underlying stream (the engine owns that). `raise_error=False`
        swallows a pending worker error — for close() calls already on
        an exception path, where re-raising would MASK the run's real
        failure (retry/diagnosis match on the propagating exception).
        Both the STOP enqueue and the drain are death-aware, so closing
        after a worker death raises (or swallows) instead of hanging."""
        if not self._closed:
            self._closed = True
            try:
                self._put(self._STOP)
                self._await_drained()
            except BaseException:
                if raise_error:
                    self._thread.join(timeout=1.0)
                    raise
            self._thread.join(timeout=5.0)
        if raise_error:
            self._raise_pending()


def reported_best(hcv: int, scv: int) -> int:
    """The value the protocol reports: scv when feasible, else
    hcv*1e6+scv (ga.cpp:205-228)."""
    return int(scv) if int(hcv) == 0 else int(hcv) * 1_000_000 + int(scv)


def log_entry(stream: IO, proc_id: int, thread_id: int, best: int,
              time_s: float, job: Optional[str] = None) -> dict:
    rec = {
        "procID": proc_id,
        "threadID": thread_id,
        "best": int(best),
        "time": max(0.0, float(time_s)),
    }
    if job is not None:
        # multi-tenant serving (timetabling_ga_tpu/serve): every record
        # of a job's stream carries its id, so one shared output stream
        # demultiplexes per tenant. Absent on single-run streams — the
        # reference protocol's records stay byte-identical there.
        rec["job"] = str(job)
    return _write(stream, {"logEntry": rec})


def solution_record(stream: IO, proc_id: int, thread_id: int,
                    total_time: float, total_best: int, feasible: bool,
                    timeslots: Optional[List[int]] = None,
                    rooms: Optional[List[int]] = None,
                    job: Optional[str] = None) -> dict:
    rec = {
        "procID": proc_id,
        "threadID": thread_id,
        "totalTime": float(total_time),
        "totalBest": int(total_best),
        "feasible": bool(feasible),
    }
    if feasible:
        rec["timeslots"] = [int(x) for x in timeslots]
        rec["rooms"] = [int(x) for x in rooms]
    if job is not None:
        rec["job"] = str(job)
    return _write(stream, {"solution": rec})


def job_entry(stream: IO, job: str, event: str, **extra) -> dict:
    """Serving EXTENSION record (not in the reference protocol): one
    line per job lifecycle transition on the service stream —

      {"jobEntry":{"job":"j1","event":"admitted","bucket":[64,8,8,64,
                   5,9]}}

    `event` is one of admitted / rejected / started / parked / done /
    failed / cancelled; `extra` carries per-event context (bucket dims,
    generation counts, rejection reason). Deliberately no wall-clock
    field: lifecycle records must stay in the byte-identity domain of
    determinism tests (strip_timing keeps them)."""
    rec = {"job": str(job), "event": str(event)}
    for k, v in extra.items():
        rec[k] = v
    return _write(stream, {"jobEntry": rec})


def fault_entry(stream: IO, site: str, action: str, error, trial: int,
                recovery: int, level: int, time_s: float,
                **extra) -> dict:
    """Robustness EXTENSION record (not in the reference protocol;
    always emitted — a recovery changes the run's trust story, so it
    must be visible without --trace). One line per supervisor event:

      {"faultEntry":{"site":"dispatch","action":"recover",
                     "error":"...","trial":0,"recovery":1,"level":0,
                     "time":12.3, ...}}

    `site` is the failing operation class (dispatch/fetch/writer/ckpt/
    run), `action` one of recover (state rehydrated, loop resumed),
    degrade (the ladder stepped: level 1 = serial dispatch, level >= 2
    = halved dispatch chunks), or abort (--max-recoveries exhausted;
    the run raises after this record). `recovery` counts recoveries so
    far this run; `time` is seconds into the trial — the lost wall
    time stays charged against the trial budget.

    Multi-host (tt-accord) events additionally carry `proc` (the
    emitting process index), `agreed` (True when the action is the
    channel-merged verdict every process adopted, False for a
    unilateral PeerLost abort), `decider` (which process's verdict won
    the merge) and `lostProc` on PeerLost. All inside the TIMING
    discipline: faultEntry is a TIMING_RECORDS member, so strip_timing
    drops the whole record and the determinism contract (records
    identical modulo timing/fault records) is untouched by the new
    fields."""
    rec = {"site": str(site), "action": str(action),
           "error": str(error)[:200], "trial": int(trial),
           "recovery": int(recovery), "level": int(level),
           "time": max(0.0, float(time_s))}
    for k, v in extra.items():
        rec[k] = v
    return _write(stream, {"faultEntry": rec})


def span_entry(stream: IO, name: str, cat: str, ts: float, dur: float,
               depth: int = 0, tid: int = 0, **extra) -> None:
    """Observability EXTENSION record (tt-obs, README "Observability";
    emitted only when a run's span tracer is enabled): one host-side
    timing span —

      {"spanEntry":{"name":"dispatch","cat":"device","ts":1.234,
                    "dur":0.087,"depth":0,"tid":0, ...}}

    `ts` is seconds since the tracer epoch (time.monotonic domain),
    `dur` the span length, `depth` the nesting level on `tid`'s thread.
    `tt trace` exports these as Chrome trace-event JSON. Pure timing:
    strip_timing drops the whole record (like phase records), so span
    emission never enters the determinism A/Bs' byte-identity domain."""
    rec = {"name": str(name), "cat": str(cat),
           "ts": round(max(0.0, float(ts)), 6),
           "dur": round(max(0.0, float(dur)), 6),
           "depth": int(depth), "tid": int(tid)}
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"spanEntry": rec})


def metrics_entry(stream: IO, snapshot: dict, ts=None) -> None:
    """Observability EXTENSION record: one metrics-registry snapshot
    (obs/metrics.py MetricsRegistry.snapshot) —

      {"metricsEntry":{"ts":12.3,"counters":{...},"gauges":{...},
                       "histograms":{...}}}

    `ts` (tracer-epoch seconds) is optional — `tt trace` turns stamped
    snapshots into Perfetto counter tracks. Wall-clock-dependent
    throughout, so strip_timing drops the record."""
    rec = dict(snapshot)
    if ts is not None:
        rec["ts"] = round(max(0.0, float(ts)), 6)
    _write(stream, {"metricsEntry": rec})


def quality_entry(stream: IO, payload: dict, ts=None,
                  job: Optional[str] = None, **extra) -> None:
    """Observability EXTENSION record (tt-obs search-quality
    observatory, obs/quality.py; emitted only under --obs with
    --quality): one decoded quality block per retired dispatch —

      {"qualityEntry":{"quality.diversity.hamming":0.41,
                       "quality.ops.crossover_wins":3, ...,
                       "ts":5.2[,"job":"j42"]}}

    Engine entries carry the run-wide cross-island aggregate
    (obs_quality.entry_payload); serve entries carry one LANE's flat
    payload tagged with its job id (obs_quality.lane_payload). Search
    telemetry, not protocol output: strip_timing drops the whole record
    (like spanEntry), which is what keeps the quality observatory's
    on/off A/B in the byte-identity domain."""
    rec = dict(payload)
    if job is not None:
        rec["job"] = str(job)
    if ts is not None:
        rec["ts"] = round(max(0.0, float(ts)), 6)
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"qualityEntry": rec})


def cost_entry(stream: IO, program: str, **extra) -> None:
    """Observability EXTENSION record (tt-obs cost observatory,
    obs/cost.py; emitted only when a run's observatory has a bound
    emitter — i.e. under --obs): one per-program compile event —

      {"costEntry":{"program":"lane_runner","sig":"9f31c2ab44",
                    "lowerSeconds":0.12,"compileSeconds":2.31,
                    "flops":1.1e9,"bytes_accessed":3.4e7,
                    "intensity":32.4,"temp_bytes":1048576,"ts":5.2}}

    `sig` is the short input-signature tag (for serve programs: the
    shape bucket); `ts` is tracer-epoch seconds when available. Pure
    cost/timing telemetry: strip_timing drops the whole record, so the
    stream identity contract (observatory on vs off) holds by
    construction."""
    rec = {"program": str(program)}
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"costEntry": rec})


def prof_entry(stream: IO, payload: dict, ts=None, **extra) -> None:
    """Observability EXTENSION record (tt-prof phase profiler,
    obs/prof.py; emitted only when a run's capture hook has a bound
    emitter — i.e. under --obs): one attributed profiler capture —

      {"profEntry":{"dir":"tt-profile","totalSeconds":2.31,
                    "phases":{"sweep":{"s":1.1,"frac":0.47,
                                       "top_ops":[["fusion.3",0.8]]},
                              ...},
                    "unattributedSeconds":0.12,
                    "unattributedFrac":0.05,"ts":41.2}}

    Per-phase DEVICE self-time of one jax.profiler capture, bucketed
    by tt.* scope (obs/prof.py attribute); `tt hotspots LOG` and the
    `tt stats` "== phases" section read these so a log alone answers
    "where did the time go". Pure timing telemetry: strip_timing drops
    the whole record, so the stream identity contract (profiling on vs
    off) holds by construction."""
    rec = dict(payload)
    if ts is not None:
        rec["ts"] = round(max(0.0, float(ts)), 6)
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"profEntry": rec})


def route_entry(stream: IO, job: str, bucket, replica: str,
                outcome: str, **extra) -> None:
    """Observability EXTENSION record (tt-obs v5, the fleet
    observatory; emitted only when the gateway runs with `-o LOG`):
    one line per placement decision on the gateway's dispatcher —

      {"routeEntry":{"job":"j42","bucket":[64,8,8,64,5,9],
                     "replica":"r0","outcome":"hit","backlog":1.0,
                     "pins":2,"compile_hit_rate":0.93,"attempt":1}}

    `outcome` is the router's affinity classification (hit / warm /
    miss — fleet/router.py docstring); the extra fields carry the
    score inputs the decision read (backlog gauge, pin count, measured
    compile-hit rate). Gateway-side telemetry, not protocol output:
    strip_timing drops the whole record, so the job record streams'
    identity contract (routed vs unrouted, gateway obs on vs off)
    holds by construction."""
    rec = {"job": str(job),
           "bucket": list(bucket) if bucket is not None else None,
           "replica": str(replica), "outcome": str(outcome)}
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"routeEntry": rec})


def scale_entry(stream: IO, action: str, reason: str, ts=None,
                **extra) -> dict:
    """Observability EXTENSION record (tt-scale, fleet/autoscaler.py;
    emitted only when the gateway runs with `-o LOG` and the
    autoscaler is enabled): one line per autoscaler decision —

      {"scaleEntry":{"action":"up","reason":"queue_depth",
                     "replica":"s1","live":1,"target":2,
                     "dry_run":false,"evidence":{
                       "serve.queue_depth":{"op":">=","threshold":8.0,
                                            "for_s":30.0,"mean":12.4}},
                     "ts":41.2}}

    `action` is up / down / blocked_warmth / blocked_cooldown / hold;
    `evidence` carries the sustained-window queries that justified (or
    blocked) the decision — the numbers `tt scale` renders next to
    each action. Control-plane telemetry, not protocol output:
    strip_timing drops the whole record, so the job record streams are
    bit-identical with the scaler on or off by construction."""
    rec = {"action": str(action), "reason": str(reason)}
    for k, v in extra.items():
        rec[k] = v
    if ts is not None:
        rec["ts"] = round(max(0.0, float(ts)), 6)
    return _write(stream, {"scaleEntry": rec})


def usage_entry(stream: IO, payload: dict, ts=None) -> None:
    """Observability EXTENSION record (tt-meter, obs/usage.py; emitted
    by the usage ledger thread when an emitter is bound — i.e. under
    --obs with metering on): per-dispatch capacity attribution, or a
    settled job's cumulative meter —

      {"usageEntry":{"dispatch":7,"gens":10,"device_seconds":0.083,
                     "compile_seconds":0.0,"flops":1.1e9,
                     "lanes":[{"job":"j1","tenant":"acme","gens":5,
                               "device_seconds":0.041,...}, ...],
                     "ts":5.2}}
      {"usageEntry":{"event":"total","job":"j1","tenant":"acme",
                     "gens":200,"device_seconds":1.7,...,"ts":9.9}}

    The per-lane shares of a dispatch entry sum EXACTLY to its totals
    (obs/usage.split — the conservation invariant bench `extra.usage`
    asserts). Pure capacity/timing telemetry: strip_timing drops the
    whole record, so the stream identity contract (metering on vs off)
    holds by construction."""
    rec = dict(payload)
    if ts is not None:
        rec["ts"] = round(max(0.0, float(ts)), 6)
    _write(stream, {"usageEntry": rec})


def phase_record(stream: IO, name: str, trial: int, seconds: float,
                 **extra) -> None:
    """Observability EXTENSION record (not in the reference protocol;
    emitted only under --trace): per-phase host timing bracketed by
    block_until_ready — the TPU-native stand-in for the reference's
    Timer instrumentation (Timer.C:36-49) and the MPE trace hook it
    never enabled (Makefile:3)."""
    rec = {"name": name, "trial": int(trial),
           "seconds": float(seconds)}
    for k, v in extra.items():
        rec[k] = v
    _write(stream, {"phase": rec})


# which fields on each record type are TIMING (wall-clock-dependent):
# the dispatch pipeline reorders WHEN telemetry is processed, never WHAT
# is dispatched, so serial and pipelined runs must emit identical
# records once these fields are stripped. Owned here, next to the
# emitters, so the bench A/B and the determinism test cannot drift on
# what "modulo timing" means.
TIMING_FIELDS = {"logEntry": ("time",), "solution": ("totalTime",),
                 "runEntry": ("totalTime",)}

# record types that are timing through and through — the determinism
# A/Bs drop them entirely rather than field-stripping them. phase and
# the obs records (spanEntry/metricsEntry/costEntry/qualityEntry) are
# wall-clock measurements or observer-only telemetry; faultEntry is
# excluded by the fault-recovery contract (a recovered run matches an
# uninjected one MODULO fault records), and qualityEntry by the quality
# observatory's (streams identical with it on or off MODULO
# qualityEntry/timing records — tests/test_quality.py).
TIMING_RECORDS = ("phase", "faultEntry", "spanEntry", "metricsEntry",
                  "costEntry", "qualityEntry", "routeEntry",
                  "usageEntry", "scaleEntry", "profEntry")


def strip_timing(records: List[dict]) -> List[dict]:
    """Protocol records minus timing-only records (TIMING_RECORDS) and
    timing fields — the byte-identity domain of the pipeline A/B
    (bench.py extra.pipeline, tests/test_runtime.py pipeline
    determinism), of the fault-recovery determinism contract (a
    recovered run matches an uninjected one modulo timing and fault
    records — tests/test_faults.py), AND of the obs / trace-mode A/Bs
    (obs on vs off, full vs deltas vs stats — tests/test_obs.py)."""
    out = []
    for rec in records:
        if any(k in rec for k in TIMING_RECORDS):
            continue
        rec = json.loads(json.dumps(rec))   # deep copy, JSON domain
        for kind, fields in TIMING_FIELDS.items():
            if kind in rec:
                for f in fields:
                    rec[kind].pop(f, None)
        out.append(rec)
    return out


def run_entry(stream: IO, total_best: int, feasible: bool,
              procs_num: Optional[int] = None,
              threads_num: Optional[int] = None,
              total_time: Optional[float] = None,
              job: Optional[str] = None) -> dict:
    rec = {"totalBest": int(total_best), "feasible": bool(feasible)}
    if procs_num is not None:
        rec["procsNum"] = int(procs_num)
        rec["threadsNum"] = int(threads_num)
        rec["totalTime"] = float(total_time)
    if job is not None:
        rec["job"] = str(job)
    return _write(stream, {"runEntry": rec})
