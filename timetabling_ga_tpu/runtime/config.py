"""Run configuration and CLI flag parsing.

Flag surface parity with the reference's Control (Control.cpp:3-176),
which parses `-key value` pairs into a string map with typed getters and
warn-on-default behavior:

    -c <int>    threads (Control.cpp:22-28)    — recorded in runEntry
                threadsNum; on TPU the intra-island parallelism is the
                vmapped population, so this does not change execution
    -i <path>   input instance, required (Control.cpp:32-39)
    -o <path>   output stream (Control.cpp:43-48), default stdout
    -n <int>    tries, default 10 legacy / 1 here (Control.cpp:52-58; the
                MPI binary never used it, SURVEY C19)
    -t <secs>   time limit, default 90 (Control.cpp:62-68)
    -p <int>    problem type 1/2/3, default 1 (Control.cpp:72-78); sets
                the local-search budget 200/1000/2000 (ga.cpp:389-397)
    -m <int>    explicit LS maxSteps override (Control.cpp:83-89)
    -l <secs>   LS time limit (Control.cpp:93-99) — RETIRED with a
                warning: the fixed-shape batched LS is bounded by -m
                (candidate-evaluation count) deterministically, where the
                reference's bound was temporal (Solution.cpp:499)
    -p1/-p2/-p3 move-type probabilities, default 1.0/1.0/0.0
                (Control.cpp:103-125)
    -s <int>    seed, default time() (Control.cpp:129-136)

TPU-specific extensions (SURVEY section 7.6):
    --backend {tpu,cpu}   device backend (cpu = same kernels on host CPU)
    --pop-size <int>      population per island (reference fixed 10,
                          ga.cpp:64)
    --islands <int>       number of islands (reference: MPI world size).
                          May EXCEED the device count: each device then
                          carries islands/devices vmapped local islands
                          (the mpirun ranks-per-node analogue;
                          parallel/islands.py local_islands)
    --generations <int>   generation budget per island (reference 2001,
                          ga.cpp:510)
    --migration-period <int>  generations between migrations (reference:
                          every 100 local periods, ga.cpp:514)
    --ls-candidates <int> candidate moves per LS round (random mode)
    --ls-mode {random,sweep}  K-random candidates per round, or the
                          systematic all-slots Move1 + Move2-block sweep
                          (ops/sweep.py, Solution.cpp:508-561 analogue)
    --ls-sweeps <int>     full sweep passes per generation (sweep mode)
    --ls-swap-block <int> Move2 partners per event per pass (sweep mode)
    --checkpoint <path>   checkpoint file (npz); enables save/resume
    --checkpoint-every <int>  epochs between checkpoints
    --resume              resume from --checkpoint if it exists
    --epochs-per-dispatch <int>  epochs fused into one device dispatch
                          (amortizes dispatch latency; time-limit checks
                          happen between dispatches)
    --trace               emit {"phase": ...} timing records (extension;
                          the reference's 3 record types are unchanged)

Fault tolerance (README "Fault tolerance"; runtime/engine.py run
supervisor + runtime/faults.py):
    --max-recoveries <int>  in-run transient-failure recoveries before
                          the run aborts (default 3; 0 disables
                          recovery — every failure propagates)
    --fetch-timeout <secs>  deadline watchdog on every classified
                          control-fence host read; a hung fetch becomes
                          a recoverable timeout error instead of a
                          silent stall (default 600; 0 disables)
    --faults <spec>       deterministic fault injection plan
                          (site:nth:action, comma-separated — see
                          runtime/faults.py); defaults to $TT_FAULTS

Observability (README "Observability"; timetabling_ga_tpu/obs):
    --obs                 emit spanEntry timing spans and metricsEntry
                          registry snapshots on the JSONL stream
                          (`tt trace` / `tt stats` read them)
    --trace-mode <mode>   device-side telemetry reduction: full |
                          deltas (per-island improvement events only) |
                          stats (events + streamed on-device moments);
                          the emitted record stream is identical
    --metrics-every <n>   dispatches between metricsEntry snapshots
                          under --obs (0 = end-of-try only)
    --obs-listen <h:p>    opt-in localhost pull front (obs/http.py): a
                          stdlib HTTP listener on a daemon thread
                          serving /metrics (OpenMetrics with histogram
                          exemplars), /healthz (process + writer
                          liveness) and /readyz (registry-derived
                          readiness) — no sidecar needed; the JSONL
                          record stream is identical with it on or off
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Optional


@dataclasses.dataclass
class RunConfig:
    threads: int = 1
    input: Optional[str] = None
    output: Optional[str] = None
    tries: int = 1
    time_limit: float = 90.0
    problem_type: int = 1
    max_steps: Optional[int] = None
    ls_time_limit: float = 99999.0
    p1: float = 1.0
    p2: float = 1.0
    p3: float = 0.0
    seed: Optional[int] = None
    backend: str = "tpu"
    pop_size: int = 10
    islands: Optional[int] = None
    generations: int = 2001
    migration_period: int = 100
    ls_candidates: int = 8
    ls_mode: str = "random"   # "random" K-candidate | "sweep" systematic
    ls_sweeps: int = 1
    ls_swap_block: int = 8
    ls_block_events: int = 1  # events per sweep scan step (see GAConfig)
    ls_sideways: float = 0.0  # P(accept equal-penalty move): plateau walk
    ls_hot_k: int = 0         # violation-guided sweep: top-K hot events
    #                           per pass (0 = sweep all events); the
    #                           reference's skip rule, Solution.cpp:
    #                           501-505/628-633
    # ---- post-feasibility polish phase (the reference's phase 2 runs a
    # DIFFERENT sweep once feasible — scv polish to a local optimum with
    # all partners, Solution.cpp:619-768). When any post_* field is set,
    # the engine switches the breeding config to these values at the
    # first dispatch after the global best reaches feasibility:
    post_ls_sweeps: Optional[int] = None     # sweep passes per child
    post_swap_block: Optional[int] = None    # Move2 partners per pivot
    post_hot_k: Optional[int] = None         # pivot selection (0 = all)
    post_sideways: Optional[float] = None    # plateau-walk acceptance
    post_pop_size: Optional[int] = None      # endgame population: at the
    #                           phase switch each island truncates to its
    #                           elite top-k rows (islands.
    #                           make_shrink_runner) — fewer rows per
    #                           generation buys proportionally more
    #                           deep-polish generations per second, while
    #                           the REPAIR phase keeps the full
    #                           population's robustness (a pop this small
    #                           from generation 0 strands whole runs
    #                           infeasible — measured, BASELINE.md r5)
    post_lahc: int = 0        # > 0 replaces the post-feasibility GA
    #                           endgame with Late-Acceptance Hill
    #                           Climbing chains of this history length
    #                           (ops/lahc.py): each elite row (after the
    #                           post_pop_size shrink) becomes an
    #                           independent LAHC walker taking cheap
    #                           delta-evaluated random moves with the
    #                           late-acceptance rule — controlled uphill
    #                           acceptance where the sweep endgame only
    #                           descends/drifts. 0 = GA endgame (default)
    post_lahc_k: int = 16     # random candidates evaluated per walker
    #                           per LAHC step (lex-best of the block is
    #                           the proposal — "steepest-of-K"): vmap
    #                           width rides the latency-bound chain
    #                           nearly free, multiplying candidate
    #                           throughput
    ls_converge: bool = False  # sweep LS early-exits at the population-
    #                            wide local optimum (reference stopping
    #                            rule); ls_sweeps becomes the hard bound
    init_sweeps: int = 0      # sweep-to-convergence passes on the initial
    #                           population (ga.cpp:429-434 analogue)
    rooms_mode: str = "scan"  # "scan" E-deep | "parallel" O(1)-depth
    checkpoint: Optional[str] = None
    checkpoint_every: int = 1
    resume: bool = False
    nsga2: bool = False       # NSGA-II (hcv, scv) replacement stage
    kick_stall: int = 2       # post-phase stall kick: after this many
    #                           consecutive non-improving dispatches in
    #                           the post-feasibility phase, reseed the
    #                           worst half of each island's population
    #                           from mutated copies of its best (the
    #                           single-island analogue of migration's
    #                           diversity injection, ga.cpp:522-535;
    #                           VERDICT round-4 next #5). 0 = off
    ls_full_eval: bool = False  # disable delta evaluation (debugging)
    epochs_per_dispatch: int = 1  # epochs fused into one device dispatch
    trace: bool = False       # emit {"phase": ...} timing JSONL records
    # ---- observability (tt-obs; README "Observability"):
    obs: bool = False         # emit spanEntry (host-side timing spans)
    #                           and periodic metricsEntry (registry
    #                           snapshots) records on the JSONL stream;
    #                           `tt trace` exports them as Chrome
    #                           trace-event JSON, `tt stats` summarizes.
    #                           Counters/gauges update regardless of
    #                           this flag — it gates only record
    #                           emission
    trace_mode: str = "full"  # device-side telemetry reduction:
    #                           "full" ships the per-generation
    #                           (hcv, scv) best trace; "deltas" ships
    #                           only per-island improvement events
    #                           (gen, hcv, scv) + count; "stats" adds
    #                           streamed on-device moments and the
    #                           polish pass counts. The emitted record
    #                           stream is identical across modes
    #                           (tests/test_obs.py pins it)
    metrics_every: int = 10   # dispatches between metricsEntry
    #                           snapshots under --obs (0 = only the
    #                           end-of-try snapshot)
    obs_listen: Optional[str] = None  # HOST:PORT of the opt-in pull
    #                           front (obs/http.py ObsServer): /metrics
    #                           OpenMetrics + exemplars, /healthz,
    #                           /readyz — a daemon-thread listener that
    #                           shares nothing with the dispatch loop
    #                           but the registry lock (None = off)
    # ---- tt-flight (obs/history.py + obs/flight.py, README "Flight
    # recorder & history"): windowed metrics history and automatic
    # incident capture. The history sampler runs under
    # --obs/--obs-listen/--incident-dir; the recorder only under
    # --incident-dir. Both live on their own daemon threads (fault
    # sites `history`/`flight_dump`) and the record stream is
    # bit-identical with them on or off.
    history_every: float = 1.0  # seconds between registry samples on
    #                           the history ring (GET /metrics/history,
    #                           rate/mean_over/sustained window
    #                           queries; 0 disables the ring)
    incident_dir: Optional[str] = None  # directory the flight recorder
    #                           dumps incident bundles into (trigger +
    #                           reasons, config fingerprint, registry
    #                           snapshot, history window, span/record
    #                           rings); None = recorder off
    incident_min_interval: float = 30.0  # seconds between dumps: a
    #                           reason storm produces ONE bundle, not a
    #                           bundle storm (oldest-first retention
    #                           under TT_INCIDENT_KEEP)
    # ---- search-quality observatory (tt-obs v4; obs/quality.py +
    # parallel/islands.py quality runners, README "Search-quality
    # observatory"): on-device diversity/operator/migration telemetry
    # packed onto the telemetry leaf, decoded into the quality.*
    # metrics namespace (+ qualityEntry records under --obs). The
    # record stream is bit-identical with it on or off (modulo
    # qualityEntry/timing records — tests/test_quality.py pins it).
    quality: bool = False     # --quality enables the quality runners
    stall_window: int = 8     # stall detector: consecutive dispatches
    #                           with no new global best before the run
    #                           counts as plateaued (0 disables the
    #                           detector; active only under --quality)
    stall_hamming: float = 0.05  # diversity-collapse threshold: the
    #                           most-collapsed island's Hamming sample
    #                           must sit at/below this for a plateau to
    #                           count as a STALL (a diverse plateau may
    #                           still recombine its way off)
    auto_kick_on_stall: bool = False  # opt-in: a detected stall
    #                           triggers the existing kick path
    #                           (islands.make_kick_runner) — reseeds
    #                           the worst half of every island from
    #                           mutated elites, with the usual
    #                           escalation ladder; disables pipelining
    #                           (the kick is a control read)
    trace_profile: Optional[str] = None  # capture a jax.profiler trace of
    #                           one mid-run dispatch into this directory
    #                           (SURVEY section 5 tracing; view with
    #                           tensorboard / xprof)
    # ---- cost observatory (tt-obs v3; obs/cost.py, README "Cost
    # observatory"). Compile accounting and roofline gauges are always
    # on (like every other registry metric); these flags drive the two
    # observatory THREADS:
    profile_dir: Optional[str] = None  # jax.profiler output directory
    #                           for on-demand captures (`tt profile` /
    #                           GET /profile on --obs-listen /
    #                           --profile-for); default "tt-profile"
    profile_for: int = 0      # > 0: capture the run's first N
    #                           dispatches at launch (the on-demand
    #                           trigger without a listener round trip)
    mem_poll_every: float = 1.0  # seconds between device memory_stats()
    #                           samples on the poller thread (feeds
    #                           device.mem_* gauges + the /readyz
    #                           near_hbm_limit reason; runs only under
    #                           --obs/--obs-listen; 0 disables)
    precompile: bool = True   # CLI compiles every dispatchable program
    #                           before the timed run (ADVICE round 4:
    #                           --no-precompile skips the probe
    #                           dispatches; first dispatches then compile
    #                           inside -t)
    pipeline: bool = True     # depth-2 asynchronous dispatch pipeline:
    #                           enqueue dispatch N+1 before fencing
    #                           dispatch N's telemetry trace, so the
    #                           device never idles through host-side
    #                           logging (engine docstring, "Dispatch
    #                           pipeline"). Auto-disabled whenever a
    #                           control path must fence between
    #                           dispatches (post config, multi-host,
    #                           --trace-profile); --no-pipeline forces
    #                           the strictly serial loop (the A/B
    #                           baseline bench.py measures against)
    donate: bool = True       # donate population buffers to each
    #                           dispatch (jit donate_argnums): the
    #                           (pop x events) state tensors are aliased
    #                           between dispatches instead of copied.
    #                           --no-donate keeps the copying engine
    #                           (debugging aid: donated inputs read
    #                           after dispatch raise 'Array deleted')
    # ---- multi-host (the reference's MPI_Init role, ga.cpp:373-380):
    # jax.distributed.initialize is called before any device use when
    # --distributed or --coordinator is given; the island mesh then spans
    # every process's devices (ICI within a slice, DCN across hosts)
    # ---- in-run fault recovery (engine run supervisor; README "Fault
    # tolerance"): transient dispatch/fetch failures rehydrate device
    # state from the rolling host snapshot and resume, with the lost
    # wall time charged against the trial budget
    max_recoveries: int = 3   # recoveries before the run aborts with a
    #                           final durable checkpoint (0 = off)
    fetch_timeout: float = 600.0  # seconds before a control-fence host
    #                           read is abandoned as a timeout error —
    #                           the hung-RPC worst case becomes a
    #                           classified, recoverable failure
    #                           (0 = no watchdog)
    accord: bool = True       # tt-accord control side channel
    #                           (runtime/control_channel.py): multi-host
    #                           schedule agreement, pre-collective
    #                           rendezvous and fault-recovery consensus
    #                           ride the coordination-service KV store
    #                           instead of device collectives.
    #                           --no-accord restores the PR-1
    #                           broadcast_one_to_all behavior (and its
    #                           hang-on-fault failure mode);
    #                           single-process runs are bit-identical
    #                           either way
    peer_timeout: float = 60.0  # seconds of heartbeat silence before a
    #                           multi-host peer is classified lost
    #                           (control_channel.PeerLost -> agreed
    #                           clean abort instead of an infinite
    #                           collective hang; 0 = wait forever)
    faults: Optional[str] = None  # deterministic fault-injection plan
    #                           (runtime/faults.py grammar); None reads
    #                           $TT_FAULTS — the tier-1 recovery tests
    #                           drive every path above through this
    distributed: bool = False     # auto-detected initialize() (TPU pods)
    coordinator: Optional[str] = None  # host:port of process 0
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    auto_tune: bool = True    # apply size-tuned solver defaults to any
    #                           field the user left untouched (see
    #                           apply_tuned_defaults); --no-auto-tune
    #                           keeps the raw dataclass defaults
    # Fields the user EXPLICITLY set (parse_args records every flag it
    # consumes here). apply_tuned_defaults never touches these, so an
    # explicit `--ls-sweeps 1` or `--ls-mode random` wins even when the
    # chosen value coincides with the dataclass default (ADVICE round 3:
    # comparing against defaults alone cannot tell those apart).
    # Programmatic construction can pass explicit_fields too; absent
    # that, the value-differs-from-default rule still applies.
    explicit_fields: frozenset = frozenset()

    def resolved_seed(self) -> int:
        # reference default: time(NULL) (Control.cpp:129-136)
        return int(time.time()) if self.seed is None else self.seed

    def apply_tuned_defaults(self, n_events: int) -> "RunConfig":
        """Size-tuned solver parameters (VERDICT round-2 item 8: defaults
        decided by measured solver outcome, not kernel time).

        The reference scales its LS budget with problem type the same
        way (-p 1/2/3 -> maxSteps 200/1000/2000, ga.cpp:389-397); here
        the knob set is (pop, LS depth, post-feasibility polish depth),
        measured in the round-3/4 quality probes:
          - SMALL populations with very deep children dominate: the
            per-child sweep LS is so strong that generations of GA
            mixing beat multistart breadth at equal wall clock (pop 32
            small / pop 16 comp — approaching the reference's own
            pop 10, ga.cpp:64);
          - comp-scale instances (E > 200) repair fastest with
            violation-guided top-K pivots, then need a DIFFERENT
            endgame: deep full-pivot sweeps with a wide Move2 partner
            block once feasible (post_* fields).
        Returns self (mutated) for chaining; only fields the user left
        at their dataclass defaults are touched."""
        d = RunConfig()
        tuned = (dict(pop_size=32, ls_sweeps=6, init_sweeps=30,
                      ls_swap_block=8, migration_period=10,
                      post_ls_sweeps=12, post_swap_block=64,
                      post_hot_k=0,
                      # 3-cycles in the sweep (Move3 block) escape the
                      # small-instance scv plateaus Move1/2 cannot:
                      # round-4 probe part 9, seeds 42/43 went 16 -> 14
                      # and 20 -> 16 while every other lever (pop,
                      # dispatch fusion, hotter sideways, more sweeps)
                      # moved nothing
                      p3=0.15)
                 if n_events <= 200 else
                 # comp scale: violation-guided top-K sweeps while
                 # infeasible (repair is concentrated on few hot events
                 # — measured time-to-feasible 28.6 s -> 0.5-3 s on
                 # comp01s), then switch to deep full-pivot wide-partner
                 # sweeps for the scv polish endgame once feasible.
                 # Round-4 probe ladder on comp01s best-at-budget (60 s,
                 # seed 42): pop 256 no post = 135 -> pop 32 post 16x32
                 # = 82 -> pop 16 post 16x64 = 68; the same config took
                 # comp05s to 343 (< the round-3 CPU baseline 351).
                 # Small populations win: with children this deep, GA
                 # mixing generations beat multistart breadth
                 # epochs_per_dispatch 4: at migration_period 2 a
                 # dispatch per epoch is a host round trip every 2
                 # generations; fusing 4 epochs cut comp01s 68 -> 64
                 # and medium 239 -> 224 (probe part 7)
                 # post_pop_size 4: the endgame shrinks each island to
                 # its elite 4 rows at the phase switch — comp01s probe
                 # (round 5): pop-16 post 72/65/67 vs pop-4-throughout
                 # 61/49/52 at 60 s, while pop-4 REPAIR is unsafe (a
                 # pop-8 run stranded a seed infeasible); the shrink
                 # keeps full-pop repair and small-pop polish
                 dict(pop_size=16, ls_sweeps=2, init_sweeps=200,
                      ls_swap_block=8, migration_period=2,
                      ls_hot_k=48, post_hot_k=0, post_ls_sweeps=16,
                      post_swap_block=64, epochs_per_dispatch=4,
                      post_pop_size=4))
        # plateau-walking acceptance: measured to take comp05s from
        # never-feasible (hcv stuck at 3 — pure correlation clashes) to
        # feasible in ~24 s; see ops/sweep.py sweep_pass
        tuned.update(ls_mode="sweep", ls_converge=True, ls_sideways=0.25)
        if self.checkpoint:
            # the mid-run shape change cannot round-trip a
            # checkpoint/resume cycle (parse_args refuses the explicit
            # combination for the same reason)
            tuned.pop("post_pop_size", None)
        for field, value in tuned.items():
            if (field not in self.explicit_fields
                    and getattr(self, field) == getattr(d, field)):
                setattr(self, field, value)
        if (self.post_pop_size is not None
                and self.post_pop_size >= self.pop_size):
            if "post_pop_size" in self.explicit_fields:
                # the USER asked for this shrink; silently ignoring the
                # flag would be worse than stopping
                raise SystemExit(
                    f"--post-pop-size {self.post_pop_size} does not "
                    f"shrink the (tuned) population {self.pop_size}; "
                    f"pass --pop-size explicitly or drop the flag")
            # an explicit small --pop-size can undercut the TUNED
            # endgame shrink; a post population >= the repair one is
            # meaningless (and > would crash the shard reshape), so
            # drop the tuned default rather than error
            self.post_pop_size = None
        return self

    def resolved_max_steps(self) -> int:
        """LS budget by problem type (ga.cpp:389-397) unless -m given."""
        if self.max_steps is not None:
            return self.max_steps
        return {1: 200, 2: 1000}.get(self.problem_type, 2000)


_FLAG_MAP = {
    "-c": ("threads", int),
    "-i": ("input", str),
    "-o": ("output", str),
    "-n": ("tries", int),
    "-t": ("time_limit", float),
    "-p": ("problem_type", int),
    "-m": ("max_steps", int),
    "-l": ("ls_time_limit", float),
    "-p1": ("p1", float),
    "-p2": ("p2", float),
    "-p3": ("p3", float),
    "-s": ("seed", int),
    "--backend": ("backend", str),
    "--pop-size": ("pop_size", int),
    "--islands": ("islands", int),
    "--generations": ("generations", int),
    "--migration-period": ("migration_period", int),
    "--ls-candidates": ("ls_candidates", int),
    "--ls-mode": ("ls_mode", str),
    "--ls-sweeps": ("ls_sweeps", int),
    "--ls-swap-block": ("ls_swap_block", int),
    "--ls-block-events": ("ls_block_events", int),
    "--ls-sideways": ("ls_sideways", float),
    "--ls-hot-k": ("ls_hot_k", int),
    "--post-sweeps": ("post_ls_sweeps", int),
    "--post-swap-block": ("post_swap_block", int),
    "--post-hot-k": ("post_hot_k", int),
    "--post-sideways": ("post_sideways", float),
    "--post-pop-size": ("post_pop_size", int),
    "--post-lahc": ("post_lahc", int),
    "--post-lahc-k": ("post_lahc_k", int),
    "--init-sweeps": ("init_sweeps", int),
    "--rooms-mode": ("rooms_mode", str),
    "--checkpoint": ("checkpoint", str),
    "--checkpoint-every": ("checkpoint_every", int),
    "--epochs-per-dispatch": ("epochs_per_dispatch", int),
    "--kick-stall": ("kick_stall", int),
    "--trace-profile": ("trace_profile", str),
    "--profile-dir": ("profile_dir", str),
    "--profile-for": ("profile_for", int),
    "--mem-poll-every": ("mem_poll_every", float),
    "--trace-mode": ("trace_mode", str),
    "--metrics-every": ("metrics_every", int),
    "--obs-listen": ("obs_listen", str),
    "--history-every": ("history_every", float),
    "--incident-dir": ("incident_dir", str),
    "--incident-min-interval": ("incident_min_interval", float),
    "--stall-window": ("stall_window", int),
    "--stall-hamming": ("stall_hamming", float),
    "--max-recoveries": ("max_recoveries", int),
    "--fetch-timeout": ("fetch_timeout", float),
    "--peer-timeout": ("peer_timeout", float),
    "--faults": ("faults", str),
    "--coordinator": ("coordinator", str),
    "--num-processes": ("num_processes", int),
    "--process-id": ("process_id", int),
}

_BOOL_FLAGS = {"--resume": "resume", "--nsga2": "nsga2",
               "--ls-full-eval": "ls_full_eval", "--trace": "trace",
               "--ls-converge": "ls_converge", "--obs": "obs",
               "--quality": "quality",
               "--auto-kick-on-stall": "auto_kick_on_stall",
               "--distributed": "distributed"}

# device-side telemetry reduction modes (mirrors islands.TRACE_MODES —
# duplicated literally because this module must parse flags without
# importing jax)
TRACE_MODES = ("full", "deltas", "stats")
_NEG_BOOL_FLAGS = {"--no-auto-tune": "auto_tune",
                   "--no-precompile": "precompile",
                   "--no-pipeline": "pipeline",
                   "--no-donate": "donate",
                   "--no-accord": "accord"}


def _format_usage(header_lines, flag_map, bool_flag_maps=()) -> str:
    """Shared usage formatter for every `-key value` parser here."""
    lines = list(header_lines)
    for flag, (field, typ) in flag_map.items():
        lines.append(f"  {flag} <{typ.__name__}>".ljust(28) + field)
    for m in bool_flag_maps:
        for flag, field in m.items():
            lines.append(f"  {flag}".ljust(28) + field)
    lines.append("  -h, --help".ljust(28) + "show this message and exit")
    return "\n".join(lines)


def _parse_flag_stream(argv, cfg, flag_map, usage_fn,
                       bool_flags=None, neg_bool_flags=None) -> set:
    """Shared `-key value` parse loop (Control.cpp:14-16 model) behind
    parse_args AND parse_serve_args. -h/--help prints usage and exits 0
    (the smoke tier checks that path runs with no device access —
    API-drift canary); unknown flags and missing values are SystemExit.
    Returns the set of field names the argv explicitly set."""
    bool_flags = bool_flags or {}
    neg_bool_flags = neg_bool_flags or {}
    seen: set = set()
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(usage_fn())
            raise SystemExit(0)
        if a in bool_flags:
            setattr(cfg, bool_flags[a], True)
            seen.add(bool_flags[a])
            i += 1
            continue
        if a in neg_bool_flags:
            setattr(cfg, neg_bool_flags[a], False)
            seen.add(neg_bool_flags[a])
            i += 1
            continue
        if a not in flag_map:
            raise SystemExit(f"unknown flag: {a}")
        if i + 1 >= len(argv):
            raise SystemExit(f"flag {a} needs a value")
        field, typ = flag_map[a]
        setattr(cfg, field, typ(argv[i + 1]))
        seen.add(field)
        i += 2
    return seen


def _validate_obs_listen(spec) -> None:
    """Fail the parse, not the run, on a malformed --obs-listen (the
    pull front's own parse_listen is the single source of truth; local
    import keeps this module's import surface flag-parsing-only)."""
    if spec is None:
        return
    from timetabling_ga_tpu.obs.http import parse_listen
    try:
        parse_listen(spec)
    except ValueError as e:
        raise SystemExit(str(e)) from None


def _validate_flight(cfg) -> None:
    """Shared tt-flight flag validation (RunConfig / ServeConfig /
    FleetConfig all carry the trio)."""
    if cfg.history_every < 0:
        raise SystemExit("--history-every must be >= 0 seconds "
                         "(0 disables the metrics history ring)")
    if cfg.incident_min_interval < 0:
        raise SystemExit("--incident-min-interval must be >= 0 "
                         "seconds between incident dumps")


def _usage() -> str:
    return _format_usage(
        ["usage: python -m timetabling_ga_tpu -i <instance.tim> "
         "[flags]", "",
         "reference-style flags (Control.cpp parsing model):"],
        _FLAG_MAP, ({**_BOOL_FLAGS, **_NEG_BOOL_FLAGS},))


def parse_args(argv) -> RunConfig:
    """Parse `-key value` pairs (Control.cpp:14-16 parsing model).

    Unknown flags raise; a missing `-i` raises like the reference's
    exit-on-missing-input (Control.cpp:36-39)."""
    cfg = RunConfig()
    seen = _parse_flag_stream(argv, cfg, _FLAG_MAP, _usage,
                              _BOOL_FLAGS, _NEG_BOOL_FLAGS)
    cfg.explicit_fields = frozenset(seen)
    if cfg.input is None:
        raise SystemExit("No instance file specified, use -i <file>")
    if cfg.backend not in ("tpu", "cpu"):
        raise SystemExit(f"unknown backend: {cfg.backend}")
    if cfg.ls_mode not in ("random", "sweep"):
        raise SystemExit(f"unknown ls-mode: {cfg.ls_mode}")
    if cfg.rooms_mode not in ("scan", "parallel"):
        raise SystemExit(f"unknown rooms-mode: {cfg.rooms_mode}")
    if cfg.trace_mode not in TRACE_MODES:
        raise SystemExit(f"unknown trace-mode: {cfg.trace_mode} "
                         f"(one of {', '.join(TRACE_MODES)})")
    if cfg.metrics_every < 0:
        raise SystemExit("--metrics-every must be >= 0 dispatches "
                         "(0 = only the end-of-try snapshot)")
    _validate_obs_listen(cfg.obs_listen)
    _validate_flight(cfg)
    if cfg.profile_for < 0:
        raise SystemExit("--profile-for must be >= 0 dispatches "
                         "(0 = no launch-time capture)")
    if cfg.mem_poll_every < 0:
        raise SystemExit("--mem-poll-every must be >= 0 seconds "
                         "(0 disables the device memory poller)")
    if cfg.stall_window < 0:
        raise SystemExit("--stall-window must be >= 0 dispatches "
                         "(0 disables the stall detector)")
    if not 0.0 <= cfg.stall_hamming <= 1.0:
        raise SystemExit("--stall-hamming must be in [0, 1] (a Hamming "
                         "sample mean is a fraction of differing slots)")
    if cfg.auto_kick_on_stall and not cfg.quality:
        raise SystemExit("--auto-kick-on-stall needs --quality (the "
                         "stall detector reads the on-device diversity "
                         "telemetry)")
    if cfg.coordinator is not None and (cfg.num_processes is None
                                        or cfg.process_id is None):
        raise SystemExit("--coordinator requires --num-processes and "
                         "--process-id (the reference's mpirun provides "
                         "these; here they are explicit)")
    if cfg.post_pop_size is not None and cfg.checkpoint:
        raise SystemExit("--post-pop-size changes the population shape "
                         "mid-run, which a checkpoint/resume cycle "
                         "cannot represent; drop one of the two flags")
    if cfg.post_pop_size is not None and cfg.post_pop_size < 1:
        raise SystemExit("--post-pop-size must be >= 1")
    if cfg.max_recoveries < 0:
        raise SystemExit("--max-recoveries must be >= 0 (0 disables "
                         "in-run recovery)")
    if cfg.fetch_timeout < 0:
        raise SystemExit("--fetch-timeout must be >= 0 seconds "
                         "(0 disables the fetch watchdog)")
    if cfg.peer_timeout < 0:
        raise SystemExit("--peer-timeout must be >= 0 seconds "
                         "(0 waits forever for a silent peer)")
    if cfg.post_lahc < 0:
        raise SystemExit("--post-lahc must be >= 0 (history length; "
                         "0 disables the LAHC endgame)")
    if cfg.post_lahc > 1_000_000:
        # two (pop, hist_len) int32 ring buffers per walker ensemble —
        # beyond this the allocation fails as an opaque XLA OOM
        raise SystemExit("--post-lahc history length is implausibly "
                         "large (max 1000000)")
    if not 1 <= cfg.post_lahc_k <= 4096:
        raise SystemExit("--post-lahc-k must be in [1, 4096] "
                         "(candidates per walker per step)")
    if (cfg.post_pop_size is not None and "pop_size" in seen
            and cfg.post_pop_size > cfg.pop_size):
        # only checkable at parse time when the user pinned BOTH sides;
        # otherwise auto-tune may still change pop_size — engine._setup
        # re-validates the final pair
        raise SystemExit("--post-pop-size must not exceed --pop-size "
                         "(it truncates to the elite rows)")
    return cfg


# ---------------------------------------------------------------------------
# Solver-service configuration (`tt serve`, timetabling_ga_tpu/serve).
# Kept here with RunConfig so the whole flag surface lives in one module.


@dataclasses.dataclass
class ServeConfig:
    """Configuration of the multi-tenant solver service.

    The service accepts jobs over a line-JSON protocol (serve/service.py
    docstring has the grammar), pads each instance to its shape bucket
    (serve/bucket.py), and time-slices up to `lanes` same-bucket jobs
    per mesh dispatch in `quantum`-generation slices."""

    input: Optional[str] = None   # line-JSON request file; None = stdin
    output: Optional[str] = None  # record stream; None = stdout
    backend: str = "tpu"
    lanes: int = 4                # job lanes per dispatch (stacked along
    #                               the island axis). The scheduler pads
    #                               the dispatch width UP to the next
    #                               multiple of the mesh's device count
    #                               (islands.local_islands requires
    #                               `lanes % devices == 0`); padding
    #                               lanes carry no job and their
    #                               device-seconds are metered as
    #                               overhead, not billed to tenants
    mesh_devices: int = 0         # devices in the serving mesh
    #                               (0 = every device the replica owns —
    #                               jax.devices(); N = first N, the
    #                               pre-mesh single-device behaviour at
    #                               N=1). Part of the lane-runner
    #                               compile-cache key
    resident: bool = True         # device-resident job groups: while a
    #                               stacked group's lane assignment is
    #                               unchanged between consecutive
    #                               quanta, keep its population state on
    #                               device and fetch only the compressed
    #                               trace leaf; park to host (the
    #                               pre-residency per-quantum
    #                               fetch_state/reshard_state cycle) on
    #                               any repack, fault, finish, deadline,
    #                               preempt-drain or snapshot-shipping
    #                               request. --no-resident is the A/B's
    #                               other leg: record streams are
    #                               identical either way
    quantum: int = 25             # generations per time slice: small
    #                               enough that late arrivals wait at
    #                               most one dispatch, large enough to
    #                               amortize dispatch latency
    backlog: int = 64             # admission-control bound (active jobs)
    pop_size: int = 16            # per-job island population
    generations: int = 200        # default per-job budget (a submit may
    #                               override per job)
    seed: int = 0                 # default per-job seed
    bucket_events: int = 32       # geometric bucket floors + ratio
    bucket_rooms: int = 4         #   (serve/bucket.py BucketSpec)
    bucket_features: int = 4
    bucket_students: int = 32
    bucket_ratio: float = 2.0
    max_steps: int = 32           # LS budget per generation (see
    #                               RunConfig.resolved_max_steps)
    ls_candidates: int = 8
    # ---- observability (tt-obs, same semantics as RunConfig's):
    obs: bool = False             # spanEntry spans (admit/pack/quantum/
    #                               park/resume) + periodic metricsEntry
    #                               snapshots on the record stream
    trace_mode: str = "full"      # lane-runner telemetry reduction
    #                               (full | deltas | stats)
    quality: bool = False         # search-quality observatory on the
    #                               lane runners: per-job operator
    #                               efficacy + diversity telemetry
    #                               (quality.* metrics; per-job
    #                               qualityEntry records under --obs;
    #                               the record stream is identical with
    #                               it on or off)
    metrics_every: int = 10       # dispatches between metricsEntry
    #                               snapshots under --obs
    usage: bool = True            # tt-meter (obs/usage.py, README
    #                               "Usage metering"): per-job /
    #                               per-tenant capacity attribution at
    #                               every park fence — the live
    #                               usage.tenant.<t>.* metrics
    #                               namespace, the per-job meter
    #                               GET /v1/usage serves, the snapshot
    #                               wire's usage cursor, and (under
    #                               --obs) usageEntry records. ON by
    #                               default — host-side dict
    #                               arithmetic off the dispatch path;
    #                               --no-usage is the A/B's other leg
    #                               (record streams identical either
    #                               way — usageEntry is TIMING)
    obs_listen: Optional[str] = None  # HOST:PORT pull front (/metrics
    #                               with exemplars, /healthz, /readyz,
    #                               /profile) — same semantics as
    #                               RunConfig's
    # ---- tt-flight (same semantics as RunConfig's): metrics history
    # ring + incident flight recorder — the replica additionally
    # serves its newest bundle at GET /v1/incident
    history_every: float = 1.0
    incident_dir: Optional[str] = None
    incident_min_interval: float = 30.0
    # ---- cost observatory (obs/cost.py; same semantics as
    # RunConfig's): the device memory poller and the on-demand
    # profiler capture
    profile_dir: Optional[str] = None
    profile_for: int = 0          # capture the service's first N
    #                               dispatches at launch
    mem_poll_every: float = 1.0   # device memory_stats() cadence
    #                               (under --obs/--obs-listen; 0 = off)
    # ---- admission/backpressure (the scheduler reads its own metrics
    # registry at every control fence and sheds the lowest-priority
    # runnable work while a depth is at/over its high-water mark;
    # jobEntry event "shed" + counter serve.jobs_shed surface it):
    shed_queue_hwm: int = 0       # serve.queue_depth high-water mark
    #                               (0 = never shed on queue depth)
    shed_writer_hwm: int = 0      # writer.queue_depth high-water mark:
    #                               a record stream nobody drains is the
    #                               other way a service drowns
    #                               (0 = never shed on writer depth)
    faults: Optional[str] = None  # deterministic fault-injection plan
    #                               (runtime/faults.py grammar — the
    #                               serve-relevant sites are writer,
    #                               obs_listen, scrape, quantum,
    #                               snapshot_ship, resume); None reads
    #                               $TT_FAULTS, like the engine
    # ---- serve-path fault recovery + fleet resume (serve/snapshot.py,
    # README "Fleet resume"):
    max_job_recoveries: int = 2   # quantum-fault requeues PER JOB
    #                               before the job fails alone with a
    #                               terminal jobEntry (the engine's
    #                               --max-recoveries, at job
    #                               granularity; 0 = any quantum fault
    #                               fails its dispatch's jobs)
    preempt_grace: float = 10.0   # preempt-drain ship deadline: after
    #                               POST /v1/drain?mode=preempt (or
    #                               SIGTERM with --preempt-on-term)
    #                               the replica parks + publishes
    #                               every active job's snapshot and
    #                               stays up at most this many seconds
    #                               waiting for them to be fetched —
    #                               then exits regardless (a spot
    #                               preemption waits for nobody)
    preempt_on_term: bool = False  # map SIGTERM to the PREEMPT drain
    #                               (spot/preemptible workers: park +
    #                               ship, don't run the queue dry)
    # ---- fleet front (timetabling_ga_tpu/fleet; README "Fleet"):
    http: Optional[str] = None    # HOST:PORT of the HTTP solve front
    #                               (fleet/replicas.py serve_http): the
    #                               replica speaks the gateway's own
    #                               /v1 protocol — POST /v1/solve,
    #                               GET /v1/jobs/<id>, DELETE
    #                               /v1/jobs/<id>, POST /v1/drain —
    #                               plus /metrics, /healthz and
    #                               /readyz, all on ONE port, so the
    #                               router's scrape and the tenants'
    #                               submissions need no second
    #                               listener. None = the line-JSON
    #                               stdio protocol (the pre-fleet mode)


_SERVE_FLAG_MAP = {
    "-i": ("input", str),
    "-o": ("output", str),
    "--backend": ("backend", str),
    "--lanes": ("lanes", int),
    "--mesh-devices": ("mesh_devices", int),
    "--quantum": ("quantum", int),
    "--backlog": ("backlog", int),
    "--pop-size": ("pop_size", int),
    "--generations": ("generations", int),
    "-s": ("seed", int),
    "--bucket-events": ("bucket_events", int),
    "--bucket-rooms": ("bucket_rooms", int),
    "--bucket-features": ("bucket_features", int),
    "--bucket-students": ("bucket_students", int),
    "--bucket-ratio": ("bucket_ratio", float),
    "-m": ("max_steps", int),
    "--ls-candidates": ("ls_candidates", int),
    "--trace-mode": ("trace_mode", str),
    "--metrics-every": ("metrics_every", int),
    "--obs-listen": ("obs_listen", str),
    "--history-every": ("history_every", float),
    "--incident-dir": ("incident_dir", str),
    "--incident-min-interval": ("incident_min_interval", float),
    "--profile-dir": ("profile_dir", str),
    "--profile-for": ("profile_for", int),
    "--mem-poll-every": ("mem_poll_every", float),
    "--shed-queue-hwm": ("shed_queue_hwm", int),
    "--shed-writer-hwm": ("shed_writer_hwm", int),
    "--faults": ("faults", str),
    "--http": ("http", str),
    "--max-job-recoveries": ("max_job_recoveries", int),
    "--preempt-grace": ("preempt_grace", float),
}

_SERVE_BOOL_FLAGS = {"--obs": "obs", "--quality": "quality",
                     "--preempt-on-term": "preempt_on_term"}

_SERVE_NEG_BOOL_FLAGS = {"--no-usage": "usage",
                         "--no-resident": "resident"}


def _serve_usage() -> str:
    return _format_usage(
        ["usage: python -m timetabling_ga_tpu serve [flags]", "",
         "multi-tenant solver service (line-JSON jobs on -i/stdin, "
         "job-tagged JSONL records on -o/stdout):"],
        _SERVE_FLAG_MAP, (_SERVE_BOOL_FLAGS, _SERVE_NEG_BOOL_FLAGS))


def parse_serve_args(argv) -> ServeConfig:
    """Parse the `serve` subcommand's flags (same -key value model as
    parse_args — _parse_flag_stream is the shared loop)."""
    cfg = ServeConfig()
    _parse_flag_stream(argv, cfg, _SERVE_FLAG_MAP, _serve_usage,
                       _SERVE_BOOL_FLAGS, _SERVE_NEG_BOOL_FLAGS)
    if cfg.backend not in ("tpu", "cpu"):
        raise SystemExit(f"unknown backend: {cfg.backend}")
    if cfg.trace_mode not in TRACE_MODES:
        raise SystemExit(f"unknown trace-mode: {cfg.trace_mode} "
                         f"(one of {', '.join(TRACE_MODES)})")
    if cfg.metrics_every < 0:
        raise SystemExit("--metrics-every must be >= 0 dispatches")
    _validate_obs_listen(cfg.obs_listen)
    _validate_obs_listen(cfg.http)   # same HOST:PORT grammar
    _validate_flight(cfg)
    if cfg.profile_for < 0:
        raise SystemExit("--profile-for must be >= 0 dispatches")
    if cfg.mem_poll_every < 0:
        raise SystemExit("--mem-poll-every must be >= 0 seconds")
    if cfg.shed_queue_hwm < 0 or cfg.shed_writer_hwm < 0:
        raise SystemExit("--shed-queue-hwm / --shed-writer-hwm must be "
                         ">= 0 (0 disables that shed trigger)")
    if cfg.max_job_recoveries < 0:
        raise SystemExit("--max-job-recoveries must be >= 0 requeues "
                         "per job")
    if cfg.preempt_grace < 0:
        raise SystemExit("--preempt-grace must be >= 0 seconds")
    if cfg.lanes < 1:
        raise SystemExit("--lanes must be >= 1")
    if cfg.mesh_devices < 0:
        raise SystemExit("--mesh-devices must be >= 0 "
                         "(0 = every visible device)")
    if cfg.quantum < 1:
        raise SystemExit("--quantum must be >= 1 generation")
    if cfg.backlog < 1:
        raise SystemExit("--backlog must be >= 1")
    if cfg.bucket_ratio <= 1.0:
        raise SystemExit("--bucket-ratio must be > 1.0 (geometric "
                         "bucket growth)")
    return cfg


# ---------------------------------------------------------------------------
# Fleet-gateway configuration (`tt fleet`, timetabling_ga_tpu/fleet).


@dataclasses.dataclass
class FleetConfig:
    """Configuration of the fleet gateway (fleet/gateway.py).

    The gateway fronts N replicas with one HTTP solve API and routes
    each job to the replica where its shape bucket's lane programs are
    already compiled (fleet/router.py). Replicas come from a static
    `--replica URL` list, or `--spawn N` local worker processes
    (`tt serve --http`, one per replica — fleet/replicas.py). Flags
    after a literal `--` pass through verbatim to spawned workers (and
    the gateway parses them as serve flags, so its router's bucket
    spec can never drift from the workers')."""

    listen: str = "127.0.0.1:8070"   # gateway HTTP bind
    replicas: list = dataclasses.field(default_factory=list)
    spawn: int = 0                   # local worker processes to spawn
    backend: str = "tpu"             # backend for spawned workers
    probe_every: float = 0.5         # liveness + /readyz + /metrics
    #                                  scrape cadence (the router's
    #                                  inputs refresh at this rate)
    poll_every: float = 0.2          # job-status poll cadence on the
    #                                  dispatcher thread (handlers
    #                                  serve the cached copy — they
    #                                  never do outbound I/O)
    probe_timeout: float = 2.0       # per-probe HTTP timeout
    #                                  (control plane: /readyz,
    #                                  /metrics, bulk state polls)
    io_timeout: float = 30.0         # data-plane HTTP timeout:
    #                                  submissions (a problem-JSON
    #                                  payload can be tens of MB) and
    #                                  terminal record-tail fetches —
    #                                  a 2 s probe budget would fail
    #                                  every large job on a healthy
    #                                  but distant replica
    max_restarts: int = 3            # restart-on-death budget per
    #                                  spawned replica
    dead_after: int = 3              # consecutive failed probes before
    #                                  a replica is declared dead and
    #                                  its unfinished jobs fail over
    boot_grace: float = 120.0        # seconds a replica that has NEVER
    #                                  probed OK may stay unreachable
    #                                  before failures count — a
    #                                  spawned worker pays a long jax
    #                                  import before it binds its port,
    #                                  and declaring it dead mid-boot
    #                                  (then killing + respawning it)
    #                                  burns every restart before the
    #                                  first one ever comes up
    place_timeout: float = 120.0     # seconds a job may wait in
    #                                  requeue-and-retry placement
    #                                  (e.g. every replica still
    #                                  booting) before it fails —
    #                                  anchored per placement round,
    #                                  so failover restarts the clock
    retain_terminal: int = 4096      # settled jobs kept queryable in
    #                                  the gateway's table; beyond
    #                                  this the oldest are evicted
    #                                  (404) — a long-running gateway
    #                                  must not hold every record
    #                                  tail it ever served
    route_retries: int = 3           # bounded-backoff submission
    #                                  attempts per replica
    #                                  (runtime/retry.py schedule)
    retry_wait_s: float = 0.2        # base wait of that schedule
    backlog: int = 256               # gateway job-table admission bound
    snapshot_timeout: float = 5.0    # per-fetch HTTP budget for the
    #                                  ?snapshot=1 cache refreshes:
    #                                  they run on the ONE dispatcher
    #                                  thread and are an OPTIMIZATION
    #                                  (a failed fetch keeps the
    #                                  previous cache; failover just
    #                                  resumes further back), so they
    #                                  get a budget far under
    #                                  --io-timeout — one hung
    #                                  replica's export must not eat
    #                                  the fleet's routing/poll/
    #                                  failover tick or trip the
    #                                  dispatcher_stalled watchdog
    snapshot_hwm: int = 256 * 1024 * 1024
    #                                  byte budget for the dispatcher's
    #                                  per-job snapshot cache (README
    #                                  "Fleet resume"): at every park
    #                                  fence the owning replica
    #                                  publishes the job's latest wire
    #                                  snapshot (?snapshot=1) and the
    #                                  gateway caches the newest
    #                                  fingerprint-valid one; over the
    #                                  budget the OLDEST-PROGRESS
    #                                  snapshots are evicted first
    #                                  (losing them wastes the least
    #                                  re-run). Evicted or uncached
    #                                  jobs fail over by replay, as
    #                                  before. 0 disables caching —
    #                                  failover is pure replay
    faults: Optional[str] = None     # fault plan (gateway/route/
    #                                  gw_writer/gw_scrape sites)
    # ---- fleet observability (tt-obs v5, README "Fleet
    # observability"): the gateway's own telemetry stream + readiness
    output: Optional[str] = None     # -o LOG: the gateway's JSONL
    #                                  telemetry stream (spanEntry
    #                                  dispatcher-phase spans with
    #                                  cross-process flow ids,
    #                                  routeEntry per placement,
    #                                  periodic metricsEntry, faultEntry
    #                                  SLO events) through an
    #                                  AsyncWriter — `tt trace
    #                                  gateway.jsonl replica*.jsonl`
    #                                  stitches it with replica logs.
    #                                  None = no gateway records
    metrics_every: int = 50          # dispatcher ticks between
    #                                  metricsEntry snapshots on the
    #                                  gateway log (0 = only the final
    #                                  snapshot at close)
    slo_p99: float = 0.0             # --slo-p99 SECONDS: rolling-window
    #                                  p99 bound over e2e job latencies
    #                                  (submit→settled at the gateway);
    #                                  while the measured p99 exceeds
    #                                  it, /readyz reports `slo_burn`
    #                                  and a faultEntry records the
    #                                  burn's start. 0 = no SLO monitor
    slo_window: int = 100            # settled jobs in the rolling
    #                                  window the p99 is measured over
    stall_after: float = 60.0        # dispatcher watchdog: seconds
    #                                  since the last dispatcher tick
    #                                  before /readyz reports
    #                                  `dispatcher_stalled` (a dead or
    #                                  wedged dispatcher still accepts
    #                                  jobs it will never place — HA
    #                                  stacks must route around it).
    #                                  0 disables the watchdog
    # ---- tt-flight (same semantics as RunConfig's trio): the gateway
    # additionally triggers its recorder on failover/SLO burn, pulls
    # the involved replicas' GET /v1/incident bundles on the recorder
    # thread, and writes ONE stitched cross-process bundle (README
    # "Flight recorder & history")
    history_every: float = 1.0
    incident_dir: Optional[str] = None
    incident_min_interval: float = 30.0
    # ---- tt-scale (fleet/autoscaler.py, README "Autoscaling"): the
    # policy-driven actuator that spawns and retires `--spawn` workers
    # off SUSTAINED fleet signals (the obs/history.py window queries
    # over the gateway's own registry). Enabled iff --scale-max > 0;
    # actuation needs the --spawn worker pool (a static --replica
    # fleet has no pool to grow) unless --scale-dry-run, which
    # evaluates and logs decisions without acting. Every decision is a
    # scaleEntry record on the gateway log (TIMING domain — job
    # streams are bit-identical with the scaler on or off) plus the
    # fleet.scale.* metrics families, which the history rings sample
    # like everything else.
    scale_min: int = 1               # never retire below this many
    #                                  live replicas
    scale_max: int = 0               # never spawn above this many;
    #                                  0 = autoscaler off
    scale_up_queue: float = 8.0      # spawn trigger: gateway
    #                                  serve.queue_depth (active jobs)
    #                                  sustained >= this ...
    scale_up_for: float = 30.0       # ... for this many seconds
    #                                  (also the sustained window for
    #                                  the fleet.slo_burn spawn
    #                                  trigger)
    scale_down_queue: float = 1.0    # retire trigger: queue_depth
    #                                  sustained <= this ...
    scale_down_for: float = 120.0    # ... for this many seconds
    scale_idle_window: float = 300.0  # a retire VICTIM must also show
    #                                  mean_over(fleet.replica.<n>.
    #                                  backlog, this window) <= the
    #                                  scale-down threshold — per-
    #                                  replica idleness, not just
    #                                  fleet-wide calm
    scale_cooldown: float = 60.0     # hysteresis: seconds after any
    #                                  scale action before the next
    #                                  may fire (spawn OR retire —
    #                                  blocked attempts count
    #                                  fleet.scale.blocked_cooldown);
    #                                  the below-min floor heal
    #                                  bypasses it
    scale_every: float = 1.0         # policy evaluation cadence on
    #                                  the scaler thread
    scale_warm_recent: float = 120.0  # warmth guard: a bucket routed
    #                                  within this many seconds (or
    #                                  with in-flight jobs) is HOT —
    #                                  scale-down never retires its
    #                                  only warm replica
    #                                  (fleet.scale.blocked_warmth)
    scale_starve_rate: float = 0.0   # premium-tier starvation spawn:
    #                                  a tenant whose usage.tenant.<t>
    #                                  .queue_seconds grows at/above
    #                                  this rate (s/s) over the
    #                                  scale-up window triggers a
    #                                  spawn; 0 = off
    scale_dry_run: bool = False      # evaluate + log scaleEntry
    #                                  decisions, actuate nothing
    serve_args: list = dataclasses.field(default_factory=list)
    #                                  verbatim worker flags (after --)


_FLEET_FLAG_MAP = {
    "--listen": ("listen", str),
    "-o": ("output", str),
    "--metrics-every": ("metrics_every", int),
    "--slo-p99": ("slo_p99", float),
    "--slo-window": ("slo_window", int),
    "--stall-after": ("stall_after", float),
    "--history-every": ("history_every", float),
    "--incident-dir": ("incident_dir", str),
    "--incident-min-interval": ("incident_min_interval", float),
    "--spawn": ("spawn", int),
    "--backend": ("backend", str),
    "--probe-every": ("probe_every", float),
    "--poll-every": ("poll_every", float),
    "--probe-timeout": ("probe_timeout", float),
    "--io-timeout": ("io_timeout", float),
    "--max-restarts": ("max_restarts", int),
    "--dead-after": ("dead_after", int),
    "--boot-grace": ("boot_grace", float),
    "--place-timeout": ("place_timeout", float),
    "--retain-terminal": ("retain_terminal", int),
    "--route-retries": ("route_retries", int),
    "--retry-wait": ("retry_wait_s", float),
    "--backlog": ("backlog", int),
    "--snapshot-hwm": ("snapshot_hwm", int),
    "--snapshot-timeout": ("snapshot_timeout", float),
    "--scale-min": ("scale_min", int),
    "--scale-max": ("scale_max", int),
    "--scale-up-queue": ("scale_up_queue", float),
    "--scale-up-for": ("scale_up_for", float),
    "--scale-down-queue": ("scale_down_queue", float),
    "--scale-down-for": ("scale_down_for", float),
    "--scale-idle-window": ("scale_idle_window", float),
    "--scale-cooldown": ("scale_cooldown", float),
    "--scale-every": ("scale_every", float),
    "--scale-warm-recent": ("scale_warm_recent", float),
    "--scale-starve-rate": ("scale_starve_rate", float),
    "--faults": ("faults", str),
}

_FLEET_BOOL_FLAGS = {"--scale-dry-run": "scale_dry_run"}


def _fleet_usage() -> str:
    return _format_usage(
        ["usage: python -m timetabling_ga_tpu fleet --listen H:P "
         "(--replica URL ... | --spawn N) [flags] [-- serve flags]", "",
         "fleet gateway: HTTP solve front + bucket-affine router over "
         "N replicas (`--replica` may repeat; flags after `--` pass "
         "through to spawned `tt serve --http` workers):"],
        {"--replica": ("replicas (repeatable)", str),
         **_FLEET_FLAG_MAP},
        (_FLEET_BOOL_FLAGS,))


def parse_fleet_args(argv) -> FleetConfig:
    """Parse `tt fleet` flags. `--replica URL` repeats; everything
    after a literal `--` is kept verbatim for spawned workers (and
    parsed as serve flags by the gateway for its bucket spec)."""
    cfg = FleetConfig()
    argv = list(argv)
    if "--" in argv:
        split = argv.index("--")
        cfg.serve_args = argv[split + 1:]
        argv = argv[:split]
    rest = []
    i = 0
    while i < len(argv):
        if argv[i] == "--replica":
            if i + 1 >= len(argv):
                raise SystemExit("flag --replica needs a value")
            cfg.replicas.append(argv[i + 1])
            i += 2
        else:
            rest.append(argv[i])
            i += 1
    _parse_flag_stream(rest, cfg, _FLEET_FLAG_MAP, _fleet_usage,
                       _FLEET_BOOL_FLAGS)
    _validate_obs_listen(cfg.listen)
    if cfg.backend not in ("tpu", "cpu"):
        raise SystemExit(f"unknown backend: {cfg.backend}")
    if cfg.spawn < 0:
        raise SystemExit("--spawn must be >= 0 worker processes")
    if not cfg.replicas and cfg.spawn == 0:
        raise SystemExit("fleet needs replicas: pass --replica URL "
                         "(repeatable) or --spawn N")
    if cfg.replicas and cfg.spawn:
        raise SystemExit("--replica and --spawn are exclusive: either "
                         "the fleet manages its own local workers or "
                         "it fronts externally managed ones")
    if cfg.probe_every <= 0 or cfg.poll_every <= 0:
        raise SystemExit("--probe-every / --poll-every must be > 0 "
                         "seconds")
    if cfg.probe_timeout <= 0 or cfg.io_timeout <= 0:
        raise SystemExit("--probe-timeout / --io-timeout must be > 0 "
                         "seconds")
    if cfg.max_restarts < 0:
        raise SystemExit("--max-restarts must be >= 0")
    if cfg.dead_after < 1:
        raise SystemExit("--dead-after must be >= 1 failed probes")
    if cfg.boot_grace < 0 or cfg.place_timeout < 0:
        raise SystemExit("--boot-grace / --place-timeout must be "
                         ">= 0 seconds")
    if cfg.retain_terminal < 1:
        raise SystemExit("--retain-terminal must be >= 1 settled job")
    if cfg.route_retries < 1:
        raise SystemExit("--route-retries must be >= 1 attempts")
    if cfg.retry_wait_s <= 0:
        raise SystemExit("--retry-wait must be > 0 seconds")
    if cfg.backlog < 1:
        raise SystemExit("--backlog must be >= 1")
    if cfg.snapshot_hwm < 0:
        raise SystemExit("--snapshot-hwm must be >= 0 bytes (0 "
                         "disables the snapshot cache: failover "
                         "replays from generation 0)")
    if cfg.snapshot_timeout <= 0:
        raise SystemExit("--snapshot-timeout must be > 0 seconds")
    if cfg.metrics_every < 0:
        raise SystemExit("--metrics-every must be >= 0 dispatcher "
                         "ticks (0 = only the final snapshot)")
    if cfg.slo_p99 < 0:
        raise SystemExit("--slo-p99 must be >= 0 seconds (0 disables "
                         "the SLO monitor)")
    if cfg.slo_window < 1:
        raise SystemExit("--slo-window must be >= 1 settled jobs")
    if cfg.stall_after < 0:
        raise SystemExit("--stall-after must be >= 0 seconds (0 "
                         "disables the dispatcher watchdog)")
    _validate_flight(cfg)
    if cfg.scale_max < 0:
        raise SystemExit("--scale-max must be >= 0 replicas "
                         "(0 disables the autoscaler)")
    if cfg.scale_max > 0:
        # tt-scale (fleet/autoscaler.py): the actuator needs a worker
        # pool to grow/shrink and a history ring to evaluate against
        if cfg.scale_min < 1:
            raise SystemExit("--scale-min must be >= 1 replica (the "
                             "fleet must keep something to route to)")
        if cfg.scale_min > cfg.scale_max:
            raise SystemExit("--scale-min must not exceed --scale-max")
        if not cfg.spawn and not cfg.scale_dry_run:
            raise SystemExit(
                "--scale-max needs the --spawn worker pool (the "
                "actuator spawns/retires local workers; a static "
                "--replica fleet has no pool) — or --scale-dry-run "
                "to evaluate the policy without acting")
        if cfg.history_every <= 0:
            raise SystemExit("--scale-max needs --history-every > 0 "
                             "(the policy evaluates obs/history.py "
                             "sustained()/rate()/mean_over() windows)")
        if cfg.scale_every <= 0:
            raise SystemExit("--scale-every must be > 0 seconds")
        if cfg.scale_up_for <= 0 or cfg.scale_down_for <= 0:
            raise SystemExit("--scale-up-for / --scale-down-for must "
                             "be > 0 seconds (a sustained window)")
        if cfg.scale_up_queue <= cfg.scale_down_queue:
            raise SystemExit(
                "--scale-up-queue must exceed --scale-down-queue "
                "(overlapping trigger bands guarantee flapping)")
        if (cfg.scale_cooldown < 0 or cfg.scale_idle_window < 0
                or cfg.scale_warm_recent < 0
                or cfg.scale_starve_rate < 0):
            raise SystemExit("--scale-cooldown / --scale-idle-window "
                             "/ --scale-warm-recent / "
                             "--scale-starve-rate must be >= 0")
    # the worker flags must themselves parse (a typo would otherwise
    # only surface as N crashed spawns); the parsed copy also gives
    # the gateway its bucket spec, so router and workers agree
    if cfg.serve_args:
        parse_serve_args(cfg.serve_args)
    if cfg.spawn and "-o" in cfg.serve_args:
        # N worker processes appending one record file interleave
        # torn JSONL lines — each spawned worker gets its own
        # tt-fleet-<name>.jsonl instead (fleet/replicas.spawn_local)
        raise SystemExit("-o in the worker passthrough flags would "
                         "make every spawned replica write ONE shared "
                         "record file; drop it — workers write "
                         "./tt-fleet-<name>.jsonl each")
    return cfg
