"""Checkpoint / resume: population snapshots as npz.

The reference has no checkpointing (SURVEY section 5); its closest
artifact is the MPI wire format that serializes full populations
(ga.cpp:264-368), which doubles as the blueprint: a checkpoint is
{population tensors, penalties, RNG key, generation counter, config
fingerprint}. Host-level np.savez with atomic rename; resume restores the
exact device state, so an interrupted run continues deterministically.
"""

from __future__ import annotations

import os
import sys
import tempfile
import zipfile
import zlib

import jax
import numpy as np

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.runtime import faults

FORMAT_VERSION = 2


class FingerprintMismatch(ValueError):
    """Deliberate refusal: the checkpoint is intact but belongs to a
    different instance/config/island layout. ValueError for
    back-compat with callers that match the original refusal."""


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file exists but cannot be read (truncated npz,
    bad zip magic, missing arrays) and no previous-generation file
    could serve in its place. Names both paths so the operator knows
    exactly what was tried."""


def prev_path(path: str) -> str:
    """The rotation target `save` moves the previous checkpoint to."""
    return path + ".prev"


def config_fingerprint(problem, cfg, n_islands: int) -> str:
    """Cheap compatibility stamp: shapes + breeding params + island
    layout. A checkpoint from a different instance, GA config, or island
    count refuses to load — the saved PopState's global shape is
    n_islands * pop_size, so a mismatched --islands resume would
    otherwise mis-assign rows to islands deep inside jit.

    The SEED is deliberately not part of the fingerprint (the default
    seed is time()-derived, so it would make every default resume fail);
    it is stored as checkpoint metadata instead, and the engine refuses
    only an EXPLICIT conflicting -s."""
    return (f"v{FORMAT_VERSION}"
            f"|E{problem.n_events}R{problem.n_rooms}S{problem.n_students}"
            f"T{problem.n_days * problem.slots_per_day}"
            f"|P{cfg.pop_size}k{cfg.tournament_k}"
            f"x{cfg.p_crossover}m{cfg.p_mutation}"
            f"|ls{cfg.ls_steps}c{cfg.ls_candidates}o{cfg.ls_mode}"
            f"w{cfg.ls_sweeps}b{cfg.ls_swap_block}"
            f"e{cfg.ls_block_events}y{cfg.ls_sideways}"
            f"g{int(cfg.ls_converge)}i{cfg.init_sweeps}"
            f"r{cfg.rooms_mode}"
            f"|I{n_islands}")


def key_data(key) -> np.ndarray:
    """Host copy of a PRNG key's raw data. The engine snapshots this on
    the MAIN thread (it is a device fetch — a control-path fence) before
    handing serialization to the background writer; `save` accepts the
    resulting ndarray in place of the key so the writer thread never
    touches the device."""
    if isinstance(key, np.ndarray):
        return key
    return np.asarray(jax.random.key_data(key))


def save(path: str, state: ga.PopState, key, generation: int,
         fingerprint: str, best_seen=None, seed: int = None) -> None:
    """Atomic DURABLE snapshot: write temp, fsync, rename, fsync dir.

    The fsync pair is what makes 'the last checkpoint on disk' a
    guarantee rather than a hope: serialization now runs on the async
    writer thread while the engine keeps dispatching, so the process can
    be killed at any moment — a rename alone could leave the new name
    pointing at pages the kernel never flushed. `state` may be a device
    PopState or a host (numpy) snapshot; `key` a JAX key or its
    key_data ndarray (see `key_data`).

    `best_seen` is the per-island best reported value already emitted to
    the JSONL stream; persisting it keeps the logEntry stream monotone
    across a resume (a fresh INT_MAX would re-emit pre-crash bests).
    `seed` is metadata for the engine's explicit-mismatch check.

    Rotation: before the rename lands, the previous checkpoint is moved
    to `prev_path(path)` — durability (fsync) protects against a crash
    DURING the write, but not against the newest file being corrupted
    later on disk (torn filesystem, truncation by a full disk); `load`
    falls back to the rotated previous-good file in that case."""
    arrays = {
        "slots": np.asarray(state.slots),
        "rooms": np.asarray(state.rooms),
        "penalty": np.asarray(state.penalty),
        "hcv": np.asarray(state.hcv),
        "scv": np.asarray(state.scv),
        "key": key_data(key),
        "generation": np.asarray(generation),
        "fingerprint": np.asarray(fingerprint),
    }
    if best_seen is not None:
        arrays["best_seen"] = np.asarray(best_seen, dtype=np.int64)
    if seed is not None:
        arrays["seed"] = np.asarray(seed, dtype=np.int64)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, **arrays)
            fh.flush()
            os.fsync(fh.fileno())
        if os.path.exists(path):
            # keep one previous-good generation: if THIS file is later
            # found corrupted on disk, load() falls back to it
            os.replace(path, prev_path(path))
        os.replace(tmp, path)
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)    # both renames must be durable too
        finally:
            os.close(dirfd)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    # fault-injection point (runtime/faults.py `ckpt` site): `truncate`
    # tears the just-written file the way a torn disk would, so the
    # load-side fallback path runs deterministically in tier-1
    faults.maybe_fail("ckpt", path=path)


# np.load failure classes that mean 'the file on disk is damaged'
# (truncated zip, bad magic, member cut short, missing arrays) — as
# opposed to FileNotFoundError (no checkpoint) and FingerprintMismatch
# (intact but foreign), which are deliberate, distinct outcomes.
# Deliberately NOT a blanket OSError: a transient EIO/EACCES on an
# INTACT newest file must propagate, not silently roll the run back to
# the stale .prev generation.
_CORRUPT_ERRORS = (zipfile.BadZipFile, zlib.error, ValueError, EOFError,
                   KeyError)

# public alias: the per-JOB snapshot wire format (serve/snapshot.py —
# the job-granular analogue of this module) classifies a torn npz
# payload with the same error set, so what counts as "damaged on the
# wire" can never drift from what counts as "damaged on disk"
CORRUPT_ERRORS = _CORRUPT_ERRORS


def _load_one(path: str, fingerprint: str):
    with np.load(path, allow_pickle=False) as z:
        found = str(z["fingerprint"])
        if found != fingerprint:
            raise FingerprintMismatch(
                f"checkpoint fingerprint mismatch: {found!r} != "
                f"{fingerprint!r} — different instance, GA config, "
                f"island count, or seed")
        state = ga.PopState(
            slots=np.array(z["slots"]),
            rooms=np.array(z["rooms"]),
            penalty=np.array(z["penalty"]),
            hcv=np.array(z["hcv"]),
            scv=np.array(z["scv"]),
        )
        key = jax.random.wrap_key_data(np.array(z["key"]))
        generation = int(z["generation"])
        best_seen = (np.array(z["best_seen"]).tolist()
                     if "best_seen" in z else None)
        seed = int(z["seed"]) if "seed" in z else None
    return state, key, generation, best_seen, seed


def load(path: str, fingerprint: str):
    """Restore (state, key, generation, best_seen, seed); raises
    FingerprintMismatch (a ValueError) on a config mismatch. best_seen
    is None for pre-v2 checkpoints.

    A corrupt `path` (truncated npz, bad magic — _CORRUPT_ERRORS) falls
    back to the rotated previous-good file `prev_path(path)`; so does a
    missing `path` when the rotated file exists (a crash between save's
    two renames leaves exactly that state). When neither file is
    readable the error is a CheckpointCorrupt naming BOTH paths."""
    prev = prev_path(path)
    try:
        return _load_one(path, fingerprint)
    except FingerprintMismatch:
        raise
    except FileNotFoundError:
        if not os.path.exists(prev):
            raise
        first_err: BaseException = FileNotFoundError(path)
    except _CORRUPT_ERRORS as e:
        first_err = e
    if not os.path.exists(prev):
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is unreadable ({first_err!r}) and no "
            f"previous checkpoint {prev!r} exists") from first_err
    try:
        result = _load_one(prev, fingerprint)
    except (FingerprintMismatch, FileNotFoundError,
            *_CORRUPT_ERRORS) as e2:
        raise CheckpointCorrupt(
            f"checkpoint {path!r} is unreadable ({first_err!r}) and the "
            f"previous checkpoint {prev!r} failed too ({e2!r})"
        ) from first_err
    print(f"warning: checkpoint {path!r} is unreadable "
          f"({str(first_err)[:120]}); resuming from the previous "
          f"checkpoint {prev!r}", file=sys.stderr)
    return result
