"""Host runtime: CLI/config, JSONL protocol, engine loop, checkpointing.

The host-side layer of the framework (reference: Control.{h,cpp} CLI +
ga.cpp main() orchestration + the JSONL protocol, SURVEY C17-C19). The
device-side work is dispatched through `timetabling_ga_tpu.parallel`;
everything here runs on the host: flag parsing, problem loading, epoch
scheduling, incremental-best logging, checkpoint/resume, and final
reporting.
"""

from timetabling_ga_tpu.runtime.config import RunConfig, parse_args
from timetabling_ga_tpu.runtime.engine import run
