"""The run engine: host orchestration of the island GA.

The TPU-native re-design of ga.cpp main() (ga.cpp:370-613). Where the
reference interleaves MPI bootstrap, OpenMP breeding loops and ad-hoc
logging in one function, the engine is a host loop over *dispatches*: each
dispatch is one fully on-device jit call covering one or more epochs
(migration_period generations per island + ring migration each, see
parallel/islands.py). The runner returns a per-GENERATION (hcv, scv) best
trace per island, so the JSONL logEntry protocol sees every mid-epoch
improvement (ga.cpp:203-228 granularity) while the host reads back exactly
one array per dispatch — no per-epoch scalar fetches (they cost seconds on
tunneled devices; BASELINE.md methodology note).

Timing semantics (Control/Timer parity):
  - the wall-clock bound -t applies per try, reset at the top of each
    trial (beginTry/resetTime, ga.cpp:163-167; Control.cpp:62-68);
  - the generation budget is exact: the final dispatch is clamped to the
    remaining generations instead of overshooting to a multiple of
    migration_period;
  - logEntry times are interpolated linearly across a dispatch's wall
    time (generations inside one dispatch are not individually host-
    timestampable; the interpolation error is bounded by one dispatch).

Observability (--trace, SURVEY section 5): per-phase host timings
(init / dispatch / fetch / checkpoint) bracketed by block_until_ready are
emitted as {"phase": ...} JSONL records — an extension record type; the
reference protocol's three record types are unchanged and remain
byte-compatible.
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

from timetabling_ga_tpu.ops import ga
from timetabling_ga_tpu.parallel import islands
from timetabling_ga_tpu.problem import load_tim_file
from timetabling_ga_tpu.runtime import checkpoint as ckpt
from timetabling_ga_tpu.runtime import jsonl
from timetabling_ga_tpu.runtime.config import RunConfig

INT_MAX = 2 ** 31 - 1


def build_ga_config(cfg: RunConfig) -> ga.GAConfig:
    """Map run flags to breeding hyper-parameters.

    The reference's LS budget counts candidate evaluations
    (stepCount, Solution.cpp:471-769); one of our LS rounds evaluates
    `ls_candidates` candidates, so rounds = maxSteps / ls_candidates keeps
    the candidate budget comparable."""
    max_steps = cfg.resolved_max_steps()
    ls_rounds = max(1, max_steps // cfg.ls_candidates)
    return ga.GAConfig(
        pop_size=cfg.pop_size,
        p1=cfg.p1, p2=cfg.p2, p3=cfg.p3,
        ls_steps=ls_rounds, ls_candidates=cfg.ls_candidates,
        ls_delta=not cfg.ls_full_eval,
        ls_mode=cfg.ls_mode, ls_sweeps=cfg.ls_sweeps,
        ls_swap_block=cfg.ls_swap_block,
        rooms_mode=cfg.rooms_mode,
        multi_objective=cfg.nsga2,
    )


def run(cfg: RunConfig, out=None) -> int:
    """Execute the configured run; emit the JSONL protocol on `out`.

    Returns the global best reported evaluation (scv if feasible else
    hcv*1e6+scv), the quantity the reference's runEntry reports.
    """
    if cfg.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")
    if cfg.ls_time_limit != 99999.0:
        # -l is formally retired on this path: the fixed-shape batched LS
        # is bounded by candidate count (-m maxSteps), not wall clock —
        # a deterministic budget where the reference's was temporal
        # (Solution.cpp:499). Warn instead of silently ignoring.
        print("warning: -l (LS time limit) is retired on the TPU path; "
              "the local search is bounded by -m (maxSteps) candidate "
              "evaluations instead", file=sys.stderr)

    close_out = False
    if out is None:
        if cfg.output:
            out = open(cfg.output, "w")
            close_out = True
        else:
            out = sys.stdout

    try:
        return _run_tries(cfg, out)
    finally:
        if close_out:
            out.close()


def _phase(out, enabled: bool, name: str, trial: int, seconds: float,
           **extra) -> None:
    if enabled:
        jsonl.phase_record(out, name, trial, seconds, **extra)


def _run_tries(cfg: RunConfig, out) -> int:
    t0 = time.monotonic()
    problem = load_tim_file(cfg.input)
    pa = problem.device_arrays()

    devices = jax.devices()
    n_islands = cfg.islands if cfg.islands is not None else len(devices)
    if n_islands > len(devices):
        print(f"warning: {n_islands} islands requested but only "
              f"{len(devices)} devices; using {len(devices)}",
              file=sys.stderr)
        n_islands = len(devices)
    mesh = islands.make_mesh(n_islands)

    gacfg = build_ga_config(cfg)
    seed = cfg.resolved_seed()
    fingerprint = ckpt.config_fingerprint(problem, gacfg, n_islands)
    _phase(out, cfg.trace, "load", 0, time.monotonic() - t0)

    # Runners are cached per (n_epochs, gens) shape; the clamped final
    # dispatch compiles its own (1, remainder) program only when the
    # budget is not a multiple of migration_period.
    runners = {}

    def get_runner(n_epochs: int, gens: int):
        k = (n_epochs, gens)
        if k not in runners:
            runners[k] = islands.make_island_runner(
                mesh, gacfg, n_epochs=n_epochs, gens_per_epoch=gens)
        return runners[k]

    global_best = INT_MAX
    # The reference's try loop is legacy Control behavior (Control.cpp:
    # 188-246) unused by the MPI binary; we honor -n but default it to 1.
    for trial in range(cfg.tries):
        t_try = time.monotonic()   # per-try clock (beginTry, ga.cpp:163)
        key = jax.random.key(seed + trial)
        k_init, key = jax.random.split(key)

        gens_done = 0
        best_seen = None
        state = None
        if cfg.resume and cfg.checkpoint:
            try:
                state, key, gens_done, best_seen, saved_seed = ckpt.load(
                    cfg.checkpoint, fingerprint)
                if saved_seed is not None:
                    if cfg.seed is not None and cfg.seed != saved_seed:
                        raise ValueError(
                            f"checkpoint was written with seed "
                            f"{saved_seed}, but -s {cfg.seed} given — "
                            f"refusing to mix RNG streams")
                    seed = saved_seed   # default seed adopts the saved one
            except FileNotFoundError:
                state = None
        if state is None:
            t = time.monotonic()
            state = islands.init_island_population(
                pa, k_init, mesh, cfg.pop_size)
            jax.block_until_ready(state)
            _phase(out, cfg.trace, "init", trial, time.monotonic() - t)
        if best_seen is None:
            best_seen = [INT_MAX] * n_islands

        epochs_done = 0
        epochs_at_ckpt = 0
        while gens_done < cfg.generations:
            if time.monotonic() - t_try > cfg.time_limit:
                break
            remaining = cfg.generations - gens_done
            if remaining >= cfg.migration_period:
                n_ep = max(1, min(cfg.epochs_per_dispatch,
                                  remaining // cfg.migration_period))
                gens = cfg.migration_period
            else:
                n_ep, gens = 1, remaining      # clamped final dispatch
            runner = get_runner(n_ep, gens)

            key, k_epoch = jax.random.split(key)
            td0 = time.monotonic()
            state, trace, _gbest = runner(pa, k_epoch, state)
            trace = np.asarray(trace)          # blocks on the dispatch
            td1 = time.monotonic()
            _phase(out, cfg.trace, "dispatch", trial, td1 - td0,
                   epochs=n_ep, gens=n_ep * gens)
            gens_done += n_ep * gens
            epochs_done += n_ep

            # per-generation logEntry emission from the device-side trace
            flat = trace.reshape(n_islands, n_ep * gens, 2)
            total = n_ep * gens
            for i in range(n_islands):
                for g in range(total):
                    rep = jsonl.reported_best(flat[i, g, 0], flat[i, g, 1])
                    if rep < best_seen[i]:
                        best_seen[i] = rep
                        tg = (td0 - t_try) + (g + 1) / total * (td1 - td0)
                        jsonl.log_entry(out, i, 0, rep, tg)

            if (cfg.checkpoint
                    and epochs_done - epochs_at_ckpt >= cfg.checkpoint_every):
                t = time.monotonic()
                ckpt.save(cfg.checkpoint, state, key, gens_done,
                          fingerprint, best_seen, seed)
                epochs_at_ckpt = epochs_done
                _phase(out, cfg.trace, "checkpoint", trial,
                       time.monotonic() - t)

        # final per-island solution records (endTry, ga.cpp:169-197)
        t = time.monotonic()
        P = cfg.pop_size
        slots = np.asarray(state.slots).reshape(n_islands, P, -1)
        rooms = np.asarray(state.rooms).reshape(n_islands, P, -1)
        hcv = np.asarray(state.hcv).reshape(n_islands, P)[:, 0]
        scv = np.asarray(state.scv).reshape(n_islands, P)[:, 0]
        _phase(out, cfg.trace, "fetch", trial, time.monotonic() - t)
        total_time = time.monotonic() - t_try
        for i in range(n_islands):
            feas = hcv[i] == 0
            rep = jsonl.reported_best(hcv[i], scv[i])
            jsonl.solution_record(
                out, i, 0, total_time, rep, feas,
                timeslots=slots[i, 0].tolist() if feas else None,
                rooms=rooms[i, 0].tolist() if feas else None)

        # cluster-level best (setGlobalCost's Allreduce MIN, ga.cpp:
        # 234-257): first runEntry line
        trial_best = min(jsonl.reported_best(hcv[i], scv[i])
                         for i in range(n_islands))
        feasible = bool((hcv == 0).any())
        jsonl.run_entry(out, trial_best, feasible)
        # final runEntry with procsNum/threadsNum/totalTime appended
        # (ga.cpp:604-607)
        jsonl.run_entry(out, trial_best, feasible,
                        procs_num=n_islands, threads_num=cfg.threads,
                        total_time=total_time)
        global_best = min(global_best, trial_best)

    return global_best
